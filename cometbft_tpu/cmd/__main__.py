"""``python -m cometbft_tpu.cmd`` — the node CLI (reference:
cmd/cometbft/main.go:14-52 + commands/).

Commands: init, start, unsafe-reset-all, show-validator, show-node-id,
gen-validator, testnet, rollback, inspect, version.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import signal
import sys
import time


def _config(args, strict: bool = True):
    """Defaults <- config.toml (if present) <- CLI flags, then validated
    (commands/root.go + viper layering).

    Recovery commands pass strict=False: the tools an operator reaches
    for when a node is broken must not be blocked by the very config
    file that broke it — problems downgrade to a warning.
    """
    from ..config import default_config
    from ..config_file import load_toml, validate_basic

    cfg = default_config()
    cfg.base.home = args.home
    toml_path = cfg.base.resolve("config/config.toml")
    try:
        if os.path.exists(toml_path):
            home = cfg.base.home
            cfg = load_toml(toml_path, base=cfg)
            cfg.base.home = home  # the file must not relocate the tree
        if getattr(args, "proxy_app", None):
            cfg.base.proxy_app = args.proxy_app
        if getattr(args, "p2p_laddr", None):
            cfg.p2p.laddr = args.p2p_laddr
        if getattr(args, "persistent_peers", None):
            cfg.p2p.persistent_peers = args.persistent_peers
        if getattr(args, "rpc_laddr", None):
            cfg.rpc.laddr = args.rpc_laddr
        if getattr(args, "log_level", None):
            cfg.base.log_level = args.log_level
        validate_basic(cfg)
    except ValueError as e:
        if strict:
            raise SystemExit(f"config error: {e}")
        print(f"warning: ignoring config problem: {e}", file=sys.stderr)
    return cfg


def _open_db(cfg, relpath: str):
    """Offline tools must open the SAME backend the node wrote with:
    running the pure-Python log reader over a native-engine file (or
    vice versa) reads nothing — and compaction would then erase it."""
    from ..libs import db as dbm

    if cfg.base.db_backend == "native":
        from ..libs.db_native import NativeDB

        return NativeDB(cfg.base.resolve(relpath))
    return dbm.FileDB(cfg.base.resolve(relpath))


def cmd_version(args) -> int:
    from ..state.state import ABCI_SEMVER, BLOCK_PROTOCOL, SOFTWARE_VERSION

    print(
        json.dumps(
            {
                "version": SOFTWARE_VERSION,
                "block_protocol": BLOCK_PROTOCOL,
                "abci": ABCI_SEMVER,
            }
        )
    )
    return 0


def cmd_init(args) -> int:
    from ..node import init_files

    cfg = _config(args)
    out = init_files(cfg)
    print(f"initialized home at {os.path.expanduser(cfg.base.home)}")
    if out["created_genesis"]:
        print(f"generated genesis at {out['genesis_file']}")
    print(
        "validator address:",
        bytes(out["pv"].get_pub_key().address()).hex().upper(),
    )
    return 0


def cmd_show_validator(args) -> int:
    from ..privval import FilePV

    cfg = _config(args)
    pv = FilePV.load(
        cfg.base.resolve(cfg.base.priv_validator_key_file),
        cfg.base.resolve(cfg.base.priv_validator_state_file),
    )
    pub = pv.get_pub_key()
    print(json.dumps({"type": pub.type, "value": pub.bytes().hex()}))
    return 0


def cmd_show_node_id(args) -> int:
    from ..p2p import NodeKey

    cfg = _config(args)
    nk = NodeKey.load_or_generate(cfg.base.resolve(cfg.base.node_key_file))
    print(nk.node_id)
    return 0


def cmd_unsafe_reset_all(args) -> int:
    """commands/reset.go — wipe data, keep keys, reset sign state."""
    from ..privval import FilePV, LastSignState

    cfg = _config(args, strict=False)
    data_dir = cfg.base.resolve("data")
    if os.path.isdir(data_dir):
        shutil.rmtree(data_dir)
    os.makedirs(data_dir, exist_ok=True)
    key_file = cfg.base.resolve(cfg.base.priv_validator_key_file)
    state_file = cfg.base.resolve(cfg.base.priv_validator_state_file)
    if os.path.exists(key_file):
        LastSignState(file_path=state_file).save()
    print(f"reset data dir {data_dir}")
    return 0


def cmd_gen_validator(args) -> int:
    """commands/gen_validator.go: print a fresh validator key (no files)."""
    from ..crypto.keys import Ed25519PrivKey

    pv = Ed25519PrivKey.generate()
    print(
        json.dumps(
            {
                "address": bytes(pv.pub_key().address()).hex().upper(),
                "pub_key": {"type": pv.pub_key().type,
                            "value": pv.pub_key().bytes().hex()},
                "priv_key": {"type": pv.type, "value": pv.bytes().hex()},
            }
        )
    )
    return 0


def cmd_testnet(args) -> int:
    """commands/testnet.go: write N node home dirs sharing one genesis."""
    from ..config import default_config
    from ..config_file import save_toml
    from ..crypto.keys import Ed25519PrivKey
    from ..node import init_files
    from ..p2p import NodeKey
    from ..types import GenesisDoc, GenesisValidator

    n_vals = args.validators
    out_dir = os.path.expanduser(args.output_dir)
    pvs = [Ed25519PrivKey.generate() for _ in range(n_vals)]
    doc = GenesisDoc(
        chain_id=args.chain_id or f"testnet-{os.urandom(3).hex()}",
        validators=[
            GenesisValidator(pub_key=pv.pub_key(), power=10) for pv in pvs
        ],
    )
    doc.validate_and_complete()
    node_ids = []
    cfgs = []
    for i in range(n_vals):
        home = os.path.join(out_dir, f"node{i}")
        cfg = default_config()
        cfg.base.home = home
        cfg.base.moniker = f"node{i}"
        cfg.p2p.laddr = f"tcp://127.0.0.1:{args.starting_port + 2 * i}"
        cfg.rpc.laddr = f"tcp://127.0.0.1:{args.starting_port + 2 * i + 1}"
        init_files(cfg)
        # overwrite the generated single-validator genesis with the shared one
        with open(cfg.base.resolve(cfg.base.genesis_file), "w") as f:
            f.write(doc.to_json())
        from ..privval import FilePV

        pv_file = FilePV.generate_from_key(
            pvs[i],
            cfg.base.resolve(cfg.base.priv_validator_key_file),
            cfg.base.resolve(cfg.base.priv_validator_state_file),
        )
        pv_file.save()
        nk = NodeKey.load_or_generate(
            cfg.base.resolve(cfg.base.node_key_file)
        )
        node_ids.append(
            f"{nk.node_id}@127.0.0.1:{args.starting_port + 2 * i}"
        )
        cfgs.append(cfg)
    # wire everyone to everyone, then write each config ONCE
    for i, cfg in enumerate(cfgs):
        cfg.p2p.persistent_peers = ",".join(
            a for j, a in enumerate(node_ids) if j != i
        )
        save_toml(cfg, cfg.base.resolve("config/config.toml"))
    print(f"wrote {n_vals} node homes under {out_dir}")
    print("peers:", ",".join(node_ids))
    return 0


def cmd_rollback(args) -> int:
    """commands/rollback.go: remove the last block, roll state back one
    height (recovery from an app-hash fork after an app bug)."""
    from ..libs import db as dbm
    from ..state import Store as StateStore
    from ..state.rollback import rollback_state
    from ..store import BlockStore

    cfg = _config(args, strict=False)
    state_db = _open_db(cfg, "data/state.db")
    block_db = _open_db(cfg, "data/blockstore.db")
    try:
        state_store = StateStore(state_db)
        block_store = BlockStore(block_db)
        height, app_hash = rollback_state(
            state_store, block_store, remove_block=args.hard
        )
        print(
            f"rolled back state to height {height} "
            f"(app_hash {app_hash.hex().upper()})"
        )
        return 0
    finally:
        state_db.close()
        block_db.close()


def cmd_inspect(args) -> int:
    """inspect/inspect.go:32: read-only RPC over a STOPPED node's data
    dir — crash forensics without a consensus engine."""
    from ..libs import db as dbm
    from ..rpc import Environment, RPCServer
    from ..state import Store as StateStore
    from ..state.indexer import KVBlockIndexer, KVTxIndexer
    from ..store import BlockStore
    from ..types import GenesisDoc

    cfg = _config(args)
    with open(cfg.base.resolve(cfg.base.genesis_file)) as f:
        genesis = GenesisDoc.from_json(f.read())
    state_db = _open_db(cfg, "data/state.db")
    block_db = _open_db(cfg, "data/blockstore.db")
    idx_db = _open_db(cfg, "data/tx_index.db")
    env = Environment(
        block_store=BlockStore(block_db),
        state_store=StateStore(state_db),
        tx_indexer=KVTxIndexer(idx_db),
        block_indexer=KVBlockIndexer(idx_db),
        genesis=genesis,
        config=cfg,
    )
    server = RPCServer(env, args.rpc_laddr or cfg.rpc.laddr)
    server.start()
    print(f"inspect RPC serving {cfg.base.home} at {server.bound_addr}")
    print("read-only routes: status/block/commit/validators/tx_search/...")
    stop = {"flag": False}
    signal.signal(signal.SIGINT, lambda *_: stop.update(flag=True))
    signal.signal(signal.SIGTERM, lambda *_: stop.update(flag=True))
    while not stop["flag"]:
        time.sleep(0.2)
    server.stop()
    return 0


def cmd_reindex_events(args) -> int:
    """commands/reindex_event.go: rebuild tx/block indexes of a STOPPED
    node from stored blocks + FinalizeBlock responses."""
    from ..libs import db as dbm
    from ..state import Store as StateStore
    from ..state.indexer import KVBlockIndexer, KVTxIndexer, TxRecord
    from ..store import BlockStore

    cfg = _config(args, strict=False)  # offline repair tool
    block_store = BlockStore(_open_db(cfg, "data/blockstore.db"))
    state_store = StateStore(_open_db(cfg, "data/state.db"))
    idx_db = _open_db(cfg, "data/tx_index.db")
    tx_indexer = KVTxIndexer(idx_db)
    block_indexer = KVBlockIndexer(idx_db)

    base = max(args.start_height or block_store.base(), block_store.base())
    head = min(args.end_height or block_store.height(), block_store.height())
    if base <= 0 or head < base:
        print(f"nothing to reindex (range {base}..{head})")
        return 1
    n_txs = 0
    for h in range(base, head + 1):
        blk = block_store.load_block(h)
        resp = state_store.load_finalize_block_response(h)
        if blk is None or resp is None:
            print(f"height {h}: missing block or finalize response; skipped")
            continue
        if len(resp.tx_results) != len(blk.data.txs):
            print(
                f"height {h}: {len(blk.data.txs)} txs but "
                f"{len(resp.tx_results)} results (torn write?); skipped"
            )
            continue
        block_indexer.index(h, resp.events)
        for i, tx in enumerate(blk.data.txs):
            result = resp.tx_results[i]
            tx_indexer.index(
                TxRecord(height=h, index=i, tx=tx, result=result),
                getattr(result, "events", None),
            )
            n_txs += 1
    idx_db.close()
    print(f"reindexed heights {base}..{head}: {n_txs} txs")
    return 0


def cmd_compact_db(args) -> int:
    """commands/compact.go analog: rewrite every append-log DB of a
    STOPPED node down to its live records."""
    from ..libs import db as dbm

    cfg = _config(args, strict=False)  # offline repair tool
    data_dir = cfg.base.resolve("data")
    total_before = total_after = 0
    for name in sorted(os.listdir(data_dir)) if os.path.isdir(data_dir) else []:
        if not name.endswith(".db"):
            continue
        path = os.path.join(data_dir, name)
        before = os.path.getsize(path)
        db = _open_db(cfg, f"data/{name}")
        db.compact()
        db.close()
        after = os.path.getsize(path)
        total_before += before
        total_after += after
        print(f"{name}: {before} -> {after} bytes")
    print(f"total: {total_before} -> {total_after} bytes")
    return 0


def cmd_start(args) -> int:
    from ..node import default_new_node

    cfg = _config(args)
    node = default_new_node(cfg)
    node.start()
    print(
        f"node started: chain={node.genesis.chain_id} "
        f"height={node.state.last_block_height}",
        flush=True,
    )

    stop = {"flag": False}

    def _sig(signum, frame):
        stop["flag"] = True

    signal.signal(signal.SIGINT, _sig)
    signal.signal(signal.SIGTERM, _sig)
    last = -1
    while not stop["flag"]:
        h = node.block_store.height()
        if h != last:
            print(
                f"committed height={h} "
                f"app_hash={node.block_store.load_block_meta(h).header.app_hash.hex() if h > 1 else ''}",
                flush=True,
            )
            last = h
        time.sleep(0.25)
    node.stop()
    print("node stopped")
    return 0


def _debug_bundle(args, out_dir: str) -> list[str]:
    """Collect one crash-forensics bundle from a live node
    (cmd/cometbft/commands/debug/dump.go's artifact set)."""
    import json as _json
    import urllib.request

    captured = []
    os.makedirs(out_dir, exist_ok=True)

    def save(name: str, data: str) -> None:
        with open(os.path.join(out_dir, name), "w") as f:
            f.write(data)
        captured.append(name)

    from ..rpc.client import HTTPClient

    rpc = HTTPClient(args.rpc_laddr.replace("tcp://", "http://"))
    for name, method in (
        ("status.json", "status"),
        ("net_info.json", "net_info"),
        ("consensus_state.json", "dump_consensus_state"),
    ):
        try:
            save(name, _json.dumps(rpc.call(method), indent=1, default=str))
        except Exception as e:
            save(name + ".err", repr(e))

    if args.pprof_laddr:
        base = "http://" + args.pprof_laddr.replace("tcp://", "")
        for name, path in (
            ("goroutines.txt", "/debug/pprof/goroutine"),
            ("heap.txt", "/debug/pprof/heap"),
            # no ?seconds=: the recent-sample ring (the seconds BEFORE
            # the dump), so a post-incident dump needs no live window
            ("profile.json", "/debug/pprof/profile?format=json"),
            ("locks.json", "/debug/locks"),
            ("devstats.json", "/debug/devstats"),
            ("health.json", "/debug/health"),
            ("net.json", "/debug/net"),
            ("tx.json", "/debug/tx"),
            ("flight.json", "/debug/flight"),
            ("contention.json", "/debug/contention"),
            ("timeline.json", "/debug/timeline"),
            ("trace.json", "/debug/trace"),
        ):
            try:
                with urllib.request.urlopen(base + path, timeout=5) as r:
                    save(name, r.read().decode())
            except Exception as e:
                save(name + ".err", repr(e))
    return captured


def cmd_debug_dump(args) -> int:
    """debug dump: capture bundles from a live node, optionally repeating
    (debug/dump.go's --frequency)."""
    for i in range(args.count):
        if i:
            time.sleep(args.frequency)
        stamp = time.strftime("%Y%m%d-%H%M%S")
        out = os.path.join(args.output_dir, f"dump-{stamp}-{i}")
        captured = _debug_bundle(args, out)
        print(f"captured {len(captured)} artifacts in {out}")
    return 0


def cmd_debug_kill(args) -> int:
    """debug kill: capture a bundle, then SIGTERM the node process
    (debug/kill.go)."""
    out = os.path.join(
        args.output_dir, f"kill-{time.strftime('%Y%m%d-%H%M%S')}"
    )
    captured = _debug_bundle(args, out)
    print(f"captured {len(captured)} artifacts in {out}")
    try:
        os.kill(args.pid, signal.SIGTERM)
        print(f"sent SIGTERM to {args.pid}")
    except ProcessLookupError:
        print(f"no such process {args.pid}")
        return 1
    return 0


def cmd_light(args) -> int:
    """light proxy: a locally served RPC whose answers are light-verified
    (cmd/cometbft light — light/proxy/proxy.go)."""
    from ..libs import db as dbm
    from ..light import Client, TrustOptions
    from ..light.proxy import LightProxy
    from ..light.rpc_provider import RPCProvider
    from ..light.store import Store

    if not args.trusted_height or not args.trusted_hash:
        print(
            "a subjective root of trust is required: "
            "--trusted-height H --trusted-hash HEX"
        )
        return 1

    primary = RPCProvider(args.primary, args.chain_id)
    witnesses = [
        RPCProvider(w, args.chain_id)
        for w in (args.witnesses.split(",") if args.witnesses else [])
        if w
    ]
    store_db = (
        dbm.FileDB(os.path.join(os.path.expanduser(args.dir), "light.db"))
        if args.dir
        else dbm.MemDB()
    )
    client = Client(
        chain_id=args.chain_id,
        trust_options=TrustOptions(
            period_ns=int(args.trust_period_hours * 3600 * 1e9),
            height=args.trusted_height,
            hash=bytes.fromhex(args.trusted_hash),
        ),
        primary=primary,
        witnesses=witnesses,
        trusted_store=Store(store_db),
    )
    proxy = LightProxy(client, args.primary, args.laddr)
    proxy.start()
    print(f"light proxy serving on {proxy.bound_addr} "
          f"(primary {args.primary})", flush=True)

    stop = {"flag": False}
    signal.signal(signal.SIGINT, lambda *a: stop.update(flag=True))
    signal.signal(signal.SIGTERM, lambda *a: stop.update(flag=True))
    while not stop["flag"]:
        time.sleep(0.25)
    proxy.stop()
    return 0


def _abci_client(args):
    """socket | grpc | local client for the abci-* commands
    (abci/cmd/abci-cli.go's --abci flag)."""
    if args.transport == "grpc":
        from ..abci.grpc import GrpcClient

        client = GrpcClient(args.addr)
    elif args.transport == "local":
        from ..abci.client import LocalClient
        from ..abci.kvstore import KVStoreApplication

        client = LocalClient(KVStoreApplication())
    else:
        from ..abci.socket_client import SocketClient

        client = SocketClient(args.addr)
    client.start()
    return client


def cmd_abci_test(args) -> int:
    """abci-cli test: protocol conformance against a running app."""
    from ..abci.conformance import ConformanceError, run_conformance

    client = _abci_client(args)
    try:
        passed = run_conformance(client)
    except ConformanceError as e:
        print(f"FAIL {e}")
        return 1
    finally:
        client.stop()
    for name in passed:
        print(f"ok {name}")
    print(f"passed {len(passed)} checks")
    return 0


def cmd_abci_console(args) -> int:
    from ..abci.conformance import console

    client = _abci_client(args)
    try:
        console(client)
    finally:
        client.stop()
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="cometbft-tpu")
    p.add_argument(
        "--home",
        default=os.environ.get("CMTHOME", "~/.cometbft-tpu"),
        help="node home directory",
    )
    sub = p.add_subparsers(dest="command", required=True)

    sub.add_parser("version")
    sub.add_parser("init")
    sub.add_parser("show-validator")
    sub.add_parser("unsafe-reset-all")
    sp = sub.add_parser("start")
    sp.add_argument(
        "--proxy-app",
        dest="proxy_app",
        default=None,
        help="kvstore | noop | tcp://... | unix://...",
    )
    sp.add_argument("--p2p-laddr", dest="p2p_laddr", default=None)
    sp.add_argument(
        "--p2p-persistent-peers",
        dest="persistent_peers",
        default=None,
        help="comma-separated id@host:port",
    )
    sub.add_parser("show-node-id")
    sub.add_parser("gen-validator")
    tp = sub.add_parser("testnet")
    tp.add_argument("--v", dest="validators", type=int, default=4)
    tp.add_argument("--o", dest="output_dir", default="./mytestnet")
    tp.add_argument("--chain-id", dest="chain_id", default=None)
    tp.add_argument(
        "--starting-port", dest="starting_port", type=int, default=26656
    )
    rb = sub.add_parser("rollback")
    rb.add_argument(
        "--hard", action="store_true",
        help="also remove the block itself, not only the state",
    )
    ip = sub.add_parser("inspect")
    ip.add_argument("--rpc-laddr", dest="rpc_laddr", default=None)
    ri = sub.add_parser("reindex-events")
    ri.add_argument("--start-height", dest="start_height", type=int, default=0)
    ri.add_argument("--end-height", dest="end_height", type=int, default=0)
    sub.add_parser("compact-db")
    lt = sub.add_parser("light")
    lt.add_argument("chain_id")
    lt.add_argument("--primary", required=True, help="primary RPC addr")
    lt.add_argument("--witnesses", default="", help="comma-separated RPCs")
    lt.add_argument("--laddr", default="tcp://127.0.0.1:8888")
    lt.add_argument("--trusted-height", dest="trusted_height", type=int,
                    default=0)
    lt.add_argument("--trusted-hash", dest="trusted_hash", default="")
    lt.add_argument("--trust-period-hours", dest="trust_period_hours",
                    type=float, default=168.0)
    lt.add_argument("--dir", default="", help="trusted store directory")
    for name in ("debug-dump", "debug-kill"):
        dp = sub.add_parser(name)
        dp.add_argument("--rpc-laddr", dest="rpc_laddr",
                        default="tcp://127.0.0.1:26657")
        dp.add_argument("--pprof-laddr", dest="pprof_laddr", default="")
        dp.add_argument("--output-dir", dest="output_dir", default=".")
        if name == "debug-dump":
            dp.add_argument("--frequency", type=float, default=30.0)
            dp.add_argument("--count", type=int, default=1)
        else:
            dp.add_argument("pid", type=int)
    for name in ("abci-test", "abci-console"):
        ab = sub.add_parser(name)
        ab.add_argument("--addr", default="tcp://127.0.0.1:26658")
        ab.add_argument(
            "--transport",
            choices=["socket", "grpc", "local"],
            default="socket",
        )
    sp.add_argument("--rpc-laddr", dest="rpc_laddr", default=None)
    sp.add_argument("--log-level", dest="log_level", default=None)

    args = p.parse_args(argv)
    return {
        "version": cmd_version,
        "init": cmd_init,
        "show-validator": cmd_show_validator,
        "show-node-id": cmd_show_node_id,
        "gen-validator": cmd_gen_validator,
        "testnet": cmd_testnet,
        "rollback": cmd_rollback,
        "inspect": cmd_inspect,
        "unsafe-reset-all": cmd_unsafe_reset_all,
        "start": cmd_start,
        "abci-test": cmd_abci_test,
        "abci-console": cmd_abci_console,
        "debug-dump": cmd_debug_dump,
        "debug-kill": cmd_debug_kill,
        "light": cmd_light,
        "reindex-events": cmd_reindex_events,
        "compact-db": cmd_compact_db,
    }[args.command](args)


if __name__ == "__main__":
    sys.exit(main())
