"""``python -m cometbft_tpu.cmd`` — the node CLI (reference:
cmd/cometbft/main.go:14-52 + commands/).

Commands: init, start, unsafe-reset-all, show-validator, version.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import signal
import sys
import time


def _config(args):
    from ..config import default_config

    cfg = default_config()
    cfg.base.home = args.home
    if getattr(args, "proxy_app", None):
        cfg.base.proxy_app = args.proxy_app
    if getattr(args, "p2p_laddr", None):
        cfg.p2p.laddr = args.p2p_laddr
    if getattr(args, "persistent_peers", None):
        cfg.p2p.persistent_peers = args.persistent_peers
    return cfg


def cmd_version(args) -> int:
    from ..state.state import ABCI_SEMVER, BLOCK_PROTOCOL, SOFTWARE_VERSION

    print(
        json.dumps(
            {
                "version": SOFTWARE_VERSION,
                "block_protocol": BLOCK_PROTOCOL,
                "abci": ABCI_SEMVER,
            }
        )
    )
    return 0


def cmd_init(args) -> int:
    from ..node import init_files

    cfg = _config(args)
    out = init_files(cfg)
    print(f"initialized home at {os.path.expanduser(cfg.base.home)}")
    if out["created_genesis"]:
        print(f"generated genesis at {out['genesis_file']}")
    print(
        "validator address:",
        bytes(out["pv"].get_pub_key().address()).hex().upper(),
    )
    return 0


def cmd_show_validator(args) -> int:
    from ..privval import FilePV

    cfg = _config(args)
    pv = FilePV.load(
        cfg.base.resolve(cfg.base.priv_validator_key_file),
        cfg.base.resolve(cfg.base.priv_validator_state_file),
    )
    pub = pv.get_pub_key()
    print(json.dumps({"type": pub.type, "value": pub.bytes().hex()}))
    return 0


def cmd_show_node_id(args) -> int:
    from ..p2p import NodeKey

    cfg = _config(args)
    nk = NodeKey.load_or_generate(cfg.base.resolve(cfg.base.node_key_file))
    print(nk.node_id)
    return 0


def cmd_unsafe_reset_all(args) -> int:
    """commands/reset.go — wipe data, keep keys, reset sign state."""
    from ..privval import FilePV, LastSignState

    cfg = _config(args)
    data_dir = cfg.base.resolve("data")
    if os.path.isdir(data_dir):
        shutil.rmtree(data_dir)
    os.makedirs(data_dir, exist_ok=True)
    key_file = cfg.base.resolve(cfg.base.priv_validator_key_file)
    state_file = cfg.base.resolve(cfg.base.priv_validator_state_file)
    if os.path.exists(key_file):
        LastSignState(file_path=state_file).save()
    print(f"reset data dir {data_dir}")
    return 0


def cmd_start(args) -> int:
    from ..node import default_new_node

    cfg = _config(args)
    node = default_new_node(cfg)
    node.start()
    print(
        f"node started: chain={node.genesis.chain_id} "
        f"height={node.state.last_block_height}",
        flush=True,
    )

    stop = {"flag": False}

    def _sig(signum, frame):
        stop["flag"] = True

    signal.signal(signal.SIGINT, _sig)
    signal.signal(signal.SIGTERM, _sig)
    last = -1
    while not stop["flag"]:
        h = node.block_store.height()
        if h != last:
            print(
                f"committed height={h} "
                f"app_hash={node.block_store.load_block_meta(h).header.app_hash.hex() if h > 1 else ''}",
                flush=True,
            )
            last = h
        time.sleep(0.25)
    node.stop()
    print("node stopped")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="cometbft-tpu")
    p.add_argument(
        "--home",
        default=os.environ.get("CMTHOME", "~/.cometbft-tpu"),
        help="node home directory",
    )
    sub = p.add_subparsers(dest="command", required=True)

    sub.add_parser("version")
    sub.add_parser("init")
    sub.add_parser("show-validator")
    sub.add_parser("unsafe-reset-all")
    sp = sub.add_parser("start")
    sp.add_argument(
        "--proxy-app",
        dest="proxy_app",
        default=None,
        help="kvstore | noop | tcp://... | unix://...",
    )
    sp.add_argument("--p2p-laddr", dest="p2p_laddr", default=None)
    sp.add_argument(
        "--p2p-persistent-peers",
        dest="persistent_peers",
        default=None,
        help="comma-separated id@host:port",
    )
    sub.add_parser("show-node-id")

    args = p.parse_args(argv)
    return {
        "version": cmd_version,
        "init": cmd_init,
        "show-validator": cmd_show_validator,
        "show-node-id": cmd_show_node_id,
        "unsafe-reset-all": cmd_unsafe_reset_all,
        "start": cmd_start,
    }[args.command](args)


if __name__ == "__main__":
    sys.exit(main())
