"""CLI (reference: cmd/cometbft)."""
