"""Reactor contract (reference: p2p/base_reactor.go:15-44).

A reactor claims channel IDs on the switch and receives every inbound
message on those channels, plus peer lifecycle callbacks.
"""

from __future__ import annotations

from ..libs.service import BaseService
from .conn.connection import ChannelDescriptor  # re-export  # noqa: F401


class Reactor(BaseService):
    def __init__(self, name: str):
        super().__init__(name)
        self.switch = None

    def set_switch(self, switch) -> None:
        self.switch = switch

    def get_channels(self) -> list[ChannelDescriptor]:
        raise NotImplementedError

    def init_peer(self, peer) -> None:
        """Called before the peer starts (may attach per-peer state)."""

    def add_peer(self, peer) -> None:
        """Called once the peer is running (start gossip routines)."""

    def remove_peer(self, peer, reason) -> None:
        """Called when the peer is stopped/evicted."""

    def receive(self, ch_id: int, peer, msg_bytes: bytes) -> None:
        raise NotImplementedError
