"""Peer-health suspicion scorer: the gray-failure defense of the p2p plane.

The reference CometBFT evicts peers that are provably broken (bad
messages, dead sockets: ``stopPeerForError``), but a *gray* peer — one
that is connected and handshaking yet saturated, one-directionally
partitioned, or seconds behind on every message — passes every
liveness check while quietly degrading consensus.  The netstats layer
(PR 8) already *sees* these peers: sustained send-queue-full streaks,
stale last-receive stamps, one-hop propagation-lag outliers.  This
module *acts* on those signals.

Design: a :class:`SuspicionScorer` (BaseService, one per node, booted
by node/node.py behind ``COMETBFT_TPU_SUSPICION``) polls the switch's
live peers every ``interval_s`` and folds three per-peer signals into
a decaying suspicion score:

* **queue_full** — fresh ``MConnection.send`` drops on a consensus
  channel since the last check: the peer stopped draining its socket
  (+1.0 per check it persists);
* **stale** — no message received from the peer for ``stale_after_s``
  while at least one *other* peer delivered recently (the one-way
  partition shape: our sends "succeed", nothing comes back) (+1.0);
* **lag** — the peer's latest stamped one-hop lag is both a large
  multiple of the live peer-set's median and above an absolute floor
  (a slow-but-alive peer, not mutual clock noise) (+0.5).

Scores decay multiplicatively (``decay`` per check), so one bad tick is
forgiven and only *sustained* misbehavior accumulates — the hysteresis
that keeps a transient burst from evicting a healthy peer.  At
``evict_score`` the peer is evicted through the ordinary switch
machinery (``stop_and_remove_peer``); persistent peers then reconnect
with fresh sockets and fresh gossip state, which is exactly the
recovery a gray TCP connection needs.  A per-peer ``cooldown_s`` floor
between evictions stops a genuinely-broken link from flapping.

Every eviction raises ``p2p_suspicion_evictions_total{reason}`` and
records an ``EV_FAULT``/``peer_evict`` flight-ring row, so watchdog
bundles and the postmortem attributor (``peer_evicted`` detector) can
name the defense when it acts.

The check path takes no lock: peers come from the switch's snapshot
accessor and every signal is a lock-free read of preallocated netstats
columns.  All scorer state lives in plain per-peer dicts owned by the
scorer thread.
"""

from __future__ import annotations

import os
import threading
import time

from ..libs import health as libhealth
from ..libs import metrics as libmetrics
from ..libs import netstats as libnetstats
from ..libs.service import BaseService

_ENV_SUSPICION = "COMETBFT_TPU_SUSPICION"
_ENV_EVICT = "COMETBFT_TPU_SUSPICION_EVICT"
_ENV_COOLDOWN = "COMETBFT_TPU_SUSPICION_COOLDOWN_S"

_OFF_VALUES = ("0", "off", "false", "no")

DEFAULT_EVICT_SCORE = 3.0
DEFAULT_COOLDOWN_S = 30.0
DEFAULT_INTERVAL_S = 1.0
# per-check multiplicative decay: with +1.0/check from one sustained
# signal the score converges to 1/(1-decay) = 5.0, crossing the
# default evict threshold after ~5 consecutive bad checks — and a
# single transient burst decays back to zero in a few clean ones
DEFAULT_DECAY = 0.8
DEFAULT_STALE_AFTER_S = 10.0
# lag outlier: both relative (vs the peer set's median) and absolute
# floors must clear — a quiet LAN's microsecond medians must not make
# a 5 ms hop "suspicious"
LAG_OUTLIER_MULT = 8.0
LAG_OUTLIER_FLOOR_S = 0.25

# eviction reason codes (EV_FAULT/peer_evict detail + metrics label);
# the detail namespace is shared with the other peer-evicting defense —
# libs/health.PEER_EVICT_STATESYNC_ROTATE (5) marks a statesync
# chunk-fetch rotation, so codes here must stay below 5
REASON_QUEUE_FULL = 1
REASON_STALE = 2
REASON_LAG = 3
REASON_MIXED = 4
_REASON_NAMES = {
    REASON_QUEUE_FULL: "queue_full",
    REASON_STALE: "stale",
    REASON_LAG: "lag",
    REASON_MIXED: "mixed",
}


def enabled() -> bool:
    """Whether a booting node should start a scorer (the operator kill
    switch; default on — the scorer is pure defense and idles free)."""
    return os.environ.get(_ENV_SUSPICION, "").lower() not in _OFF_VALUES


_env_float = libhealth._env_float


class SuspicionScorer(BaseService):
    """Background peer-health watchdog over one node's switch."""

    def __init__(
        self,
        switch,
        metrics=None,
        interval_s: float = DEFAULT_INTERVAL_S,
        evict_score: float | None = None,
        cooldown_s: float | None = None,
        decay: float = DEFAULT_DECAY,
        stale_after_s: float = DEFAULT_STALE_AFTER_S,
        lag_outlier_mult: float = LAG_OUTLIER_MULT,
        lag_floor_s: float = LAG_OUTLIER_FLOOR_S,
        logger=None,
    ):
        super().__init__("SuspicionScorer", logger)
        self.switch = switch
        self.metrics = metrics
        self.interval_s = interval_s
        self.evict_score = (
            evict_score
            if evict_score is not None
            else _env_float(_ENV_EVICT, DEFAULT_EVICT_SCORE)
        )
        self.cooldown_s = (
            cooldown_s
            if cooldown_s is not None
            else _env_float(_ENV_COOLDOWN, DEFAULT_COOLDOWN_S)
        )
        self.decay = decay
        self.stale_after_s = stale_after_s
        self.lag_outlier_mult = lag_outlier_mult
        self.lag_floor_s = lag_floor_s
        # per-peer scorer state (scorer-thread-owned)
        self._score: dict[str, float] = {}
        self._qfull_seen: dict[str, int] = {}
        self._first_seen: dict[str, int] = {}
        self._last_evict: dict[str, float] = {}
        self.evictions = 0
        self._thread: threading.Thread | None = None

    # -- lifecycle -------------------------------------------------------

    def on_start(self) -> None:
        t = threading.Thread(
            target=self._run, name="p2p-suspicion", daemon=True
        )
        t.start()
        self._thread = t

    def on_stop(self) -> None:
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=2)

    def _run(self) -> None:
        quit_ev = self.quit_event()
        while not quit_ev.is_set():
            try:
                self.check_once()
            except Exception:
                # a scorer fault must never take the node down
                import traceback

                traceback.print_exc()
            quit_ev.wait(self.interval_s)

    # -- evaluation ------------------------------------------------------

    def _peer_rows(self):
        """(peer, stats) for live peers carrying a netstats block."""
        out = []
        for peer in self.switch.peers():
            mconn = getattr(peer, "mconn", None)
            stats = getattr(mconn, "stats", None)
            if stats is not None:
                out.append((peer, stats))
        return out

    def check_once(self, now_ns: int | None = None) -> list[dict]:
        """One scoring pass; returns the evictions performed (empty on
        a healthy net).  Pure over the switch + netstats state, so
        tests drive it directly without the thread."""
        if now_ns is None:
            now_ns = time.time_ns()
        rows = self._peer_rows()
        live_ids = set()
        # the staleness signal needs the net to be otherwise ACTIVE: a
        # fully-idle net (nobody sends) must not mark everyone stale
        freshest_ns = 0
        lags_s = []
        for _, stats in rows:
            last = stats.last_recv_ns()
            if last > freshest_ns:
                freshest_ns = last
            lag = stats.last_lag_ns()
            if lag > 0:
                lags_s.append(lag / 1e9)
        lags_s.sort()
        median_lag_s = lags_s[len(lags_s) // 2] if lags_s else 0.0
        evicted: list[dict] = []
        suspects = 0
        for peer, stats in rows:
            pid = peer.id
            live_ids.add(pid)
            score = self._score.get(pid, 0.0) * self.decay
            reasons = 0
            dominant = 0
            # -- consecutive send-queue-full streaks
            qfull = stats.queue_full_total(libnetstats.CONSENSUS_CHANNELS)
            if qfull > self._qfull_seen.get(pid, 0):
                score += 1.0
                reasons += 1
                dominant = REASON_QUEUE_FULL
            self._qfull_seen[pid] = qfull
            # -- stamp staleness while the rest of the net is live; a
            # peer that NEVER delivered a message (deaf from connect —
            # the sever pre-dates its first inbound) ages from the
            # moment the scorer first saw it instead of escaping the
            # signal on a zero stamp
            last = stats.last_recv_ns() or self._first_seen.setdefault(
                pid, now_ns
            )
            if (
                freshest_ns
                and (now_ns - last) / 1e9 > self.stale_after_s
                and (now_ns - freshest_ns) / 1e9 < self.stale_after_s
            ):
                score += 1.0
                reasons += 1
                dominant = dominant or REASON_STALE
            # -- propagation-lag outlier vs the live peer set
            lag_s = stats.last_lag_ns() / 1e9
            if (
                lag_s > self.lag_floor_s
                and median_lag_s > 0
                and lag_s > self.lag_outlier_mult * median_lag_s
            ):
                score += 0.5
                reasons += 1
                dominant = dominant or REASON_LAG
            if score < 1e-3:
                score = 0.0
            self._score[pid] = score
            if score > 0:
                suspects += 1
            if score >= self.evict_score:
                last_evict = self._last_evict.get(pid, 0.0)
                now_s = now_ns / 1e9
                if now_s - last_evict < self.cooldown_s:
                    continue
                reason = dominant if reasons == 1 else REASON_MIXED
                self._last_evict[pid] = now_s
                self._score[pid] = 0.0
                evicted.append(
                    self._evict(peer, reason, score)
                )
        # forget departed peers so churn can't grow the maps unbounded
        for d in (self._score, self._qfull_seen, self._first_seen):
            for pid in list(d):
                if pid not in live_ids:
                    del d[pid]
        # eviction stamps persist past departure (an evicted peer is
        # gone by the next check, and its cooldown must survive the
        # reconnect) — but an EXPIRED cooldown is meaningless, so churn
        # can't grow this map either
        now_s = now_ns / 1e9
        for pid in list(self._last_evict):
            if now_s - self._last_evict[pid] > self.cooldown_s:
                del self._last_evict[pid]
        m = self.metrics if self.metrics is not None else (
            libmetrics.node_metrics()
        )
        m.p2p_suspect_peers.set(suspects)
        return evicted

    def _evict(self, peer, reason: int, score: float) -> dict:
        name = _REASON_NAMES.get(reason, "mixed")
        m = self.metrics if self.metrics is not None else (
            libmetrics.node_metrics()
        )
        m.p2p_suspicion_evictions.labels(name).inc()
        self.evictions += 1
        # the defense acted: annotate the flight ring so bundles and
        # the postmortem peer_evicted detector can name it
        libhealth.record(
            libhealth.EV_FAULT, a=libhealth.FAULT_PEER_EVICT, b=reason
        )
        if self.logger is not None:
            self.logger.error(
                "evicting suspect peer",
                peer=peer.id[:10],
                reason=name,
                score=round(score, 2),
            )
        try:
            self.switch.stop_and_remove_peer(
                peer, f"suspicion: {name} (score {score:.2f})"
            )
        except Exception:
            pass
        return {"peer": peer.id, "reason": name, "score": score}

    def scores(self) -> dict:
        """Current per-peer suspicion (10-char prefixes; /debug path)."""
        return {
            pid[:10]: round(s, 3)
            for pid, s in self._score.items()
            if s > 0
        }

    def status(self) -> dict:
        return {
            "running": self.is_running(),
            "evict_score": self.evict_score,
            "cooldown_s": self.cooldown_s,
            "interval_s": self.interval_s,
            "evictions": self.evictions,
            "suspects": self.scores(),
        }
