"""Peer: one connected remote node (reference: p2p/peer.go).

Wraps the MConnection, carries the exchanged NodeInfo, and a small kv
store reactors use for per-peer state (p2p/peer.go Set/Get).
"""

from __future__ import annotations

from ..libs import sync as libsync

from ..libs.service import BaseService
from .conn.connection import MConnection
from .node_info import NodeInfo


class Peer(BaseService):
    def __init__(
        self,
        secret_conn,
        node_info: NodeInfo,
        channels,  # list[ChannelDescriptor]
        on_receive,  # f(ch_id, peer, msg_bytes)
        on_error,  # f(peer, err)
        outbound: bool,
        persistent: bool = False,
        socket_addr: str = "",
        mconn_config=None,
    ):
        super().__init__(f"peer-{node_info.node_id[:10]}")
        self.node_info = node_info
        self.outbound = outbound
        self.persistent = persistent
        self.socket_addr = socket_addr
        self._data: dict[str, object] = {}
        self._data_mtx = libsync.Mutex("p2p.peer._data_mtx")
        self.mconn = MConnection(
            secret_conn,
            channels,
            on_receive=lambda ch, msg: on_receive(ch, self, msg),
            on_error=lambda err: on_error(self, err),
            config=mconn_config,
        )

    @property
    def id(self) -> str:
        return self.node_info.node_id

    def on_start(self) -> None:
        self.mconn.start()

    def on_stop(self) -> None:
        if self.mconn.is_running():
            self.mconn.stop()

    def send(self, ch_id: int, msg: bytes) -> bool:
        return self.mconn.send(ch_id, msg)

    def try_send(self, ch_id: int, msg: bytes) -> bool:
        return self.mconn.try_send(ch_id, msg)

    # per-peer kv store used by reactors (peer.go Set/Get)
    def set(self, key: str, value) -> None:
        with self._data_mtx:
            self._data[key] = value

    def get(self, key: str):
        with self._data_mtx:
            return self._data.get(key)

    def __repr__(self) -> str:
        arrow = "out" if self.outbound else "in"
        return f"Peer<{arrow} {self.id[:10]}@{self.socket_addr}>"
