"""Peer: one connected remote node (reference: p2p/peer.go).

Wraps the MConnection, carries the exchanged NodeInfo, and a small kv
store reactors use for per-peer state (p2p/peer.go Set/Get).

Provenance stamping (libs/netstats): when BOTH ends advertised the
``netstamp`` capability in their NodeInfo, every message on the
:data:`~..libs.netstats.STAMPED_CHANNELS` enum is prefixed with a fixed
23-byte origin stamp on send and stripped on receive (the stamp parks in
a thread-local for the reactor dispatch, which attributes gossip lag per
consensus phase).  The capability is negotiated at handshake and pinned
for the connection's lifetime — an unstamped peer sees byte-identical
wire traffic, so wire compat never depends on payload sniffing.
"""

from __future__ import annotations

import itertools
import time

from ..libs import netstats as libnetstats
from ..libs import sync as libsync
from ..libs.service import BaseService
from .conn.connection import MConnection
from .node_info import NodeInfo


class Peer(BaseService):
    def __init__(
        self,
        secret_conn,
        node_info: NodeInfo,
        channels,  # list[ChannelDescriptor]
        on_receive,  # f(ch_id, peer, msg_bytes)
        on_error,  # f(peer, err)
        outbound: bool,
        persistent: bool = False,
        socket_addr: str = "",
        mconn_config=None,
        our_node_info: NodeInfo | None = None,
        origin_id: int = 0,  # libs/health flight-ring origin of OUR node
        logger=None,
    ):
        super().__init__(f"peer-{node_info.node_id[:10]}", logger)
        self.node_info = node_info
        self.outbound = outbound
        self.persistent = persistent
        self.socket_addr = socket_addr
        self._data: dict[str, object] = {}
        self._data_mtx = libsync.Mutex("p2p.peer._data_mtx")
        # Stamping is on exactly when both handshaken NodeInfos carried
        # the capability: the remote stamps toward us only when WE
        # advertised, so receive-side stripping under the same
        # condition is deterministic — no content sniffing.
        key = libnetstats.NODEINFO_STAMP_KEY
        self._stamp = bool(
            our_node_info is not None
            and our_node_info.other.get(key)
            and node_info.other.get(key)
        )
        self._origin8 = (
            libnetstats.origin_prefix(our_node_info.node_id)
            if our_node_info is not None
            else b"\0" * 8
        )
        self._stamp_seq = itertools.count(1)
        self.mconn = MConnection(
            secret_conn,
            channels,
            on_receive=lambda ch, msg: self._dispatch(ch, msg, on_receive),
            on_error=lambda err: on_error(self, err),
            config=mconn_config,
            peer_id=node_info.node_id,
            outbound=outbound,
            origin_id=origin_id,
            logger=logger,
        )

    @property
    def id(self) -> str:
        return self.node_info.node_id

    def stamping(self) -> bool:
        """Whether this connection negotiated provenance stamps."""
        return self._stamp

    def _dispatch(self, ch_id: int, msg: bytes, on_receive) -> None:
        """Strip the provenance stamp (negotiated connections only) and
        park it for the reactor running on this recv thread."""
        if self._stamp and ch_id in libnetstats.STAMPED_CHANNELS:
            stamp, msg = libnetstats.split_stamp(msg)
            if stamp is not None:
                libnetstats.set_current_stamp(stamp, self.mconn.stats)
                try:
                    on_receive(ch_id, self, msg)
                finally:
                    libnetstats.clear_current_stamp()
                return
        on_receive(ch_id, self, msg)

    def _maybe_stamp(self, ch_id: int, msg: bytes) -> bytes:
        if self._stamp and ch_id in libnetstats.STAMPED_CHANNELS:
            seq = next(self._stamp_seq)
            wall = time.time_ns()
            stats = self.mconn.stats
            stats.stamp_tx_seq[0] = seq
            # the skew estimator pairs this send with the next inbound
            # stamp from the peer (NTP-style round trip)
            stats.stamp_tx_wall[0] = wall
            return libnetstats.make_stamp(self._origin8, seq, wall) + msg
        return msg

    def on_start(self) -> None:
        self.mconn.start()

    def on_stop(self) -> None:
        if self.mconn.is_running():
            self.mconn.stop()

    def send(self, ch_id: int, msg: bytes) -> bool:
        return self.mconn.send(ch_id, self._maybe_stamp(ch_id, msg))

    def try_send(self, ch_id: int, msg: bytes) -> bool:
        return self.mconn.try_send(ch_id, self._maybe_stamp(ch_id, msg))

    # per-peer kv store used by reactors (peer.go Set/Get)
    def set(self, key: str, value) -> None:
        with self._data_mtx:
            self._data[key] = value

    def get(self, key: str):
        with self._data_mtx:
            return self._data.get(key)

    def __repr__(self) -> str:
        arrow = "out" if self.outbound else "in"
        return f"Peer<{arrow} {self.id[:10]}@{self.socket_addr}>"
