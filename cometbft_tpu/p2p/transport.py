"""TCP transport with encrypted upgrade (reference: p2p/transport.go:139).

Listens/dials raw TCP, then upgrades every connection: SecretConnection
handshake (authenticates the remote ed25519 key) → NodeInfo exchange →
validation (ID-matches-key, network/version compatibility). Returns the
material the Switch turns into a ``Peer``.
"""

from __future__ import annotations

import socket
import struct
import threading

from .conn.secret_connection import SecretConnection
from .key import NodeKey, node_id_from_pubkey
from .node_info import MAX_NODE_INFO_SIZE, NodeInfo


class TransportError(Exception):
    pass


def parse_addr(addr: str) -> tuple[str, int]:
    """'tcp://host:port' | 'host:port' | 'id@host:port' → (host, port)."""
    if addr.startswith("tcp://"):
        addr = addr[len("tcp://") :]
    if "@" in addr:
        addr = addr.split("@", 1)[1]
    host, _, port = addr.rpartition(":")
    return host or "127.0.0.1", int(port)


def addr_id(addr: str) -> str | None:
    """The id part of 'id@host:port', if present."""
    if addr.startswith("tcp://"):
        addr = addr[len("tcp://") :]
    if "@" in addr:
        return addr.split("@", 1)[0]
    return None


class UpgradedConn:
    def __init__(self, secret_conn, node_info, outbound, socket_addr):
        self.secret_conn = secret_conn
        self.node_info = node_info
        self.outbound = outbound
        self.socket_addr = socket_addr


class MultiplexTransport:
    def __init__(
        self,
        node_key: NodeKey,
        node_info: NodeInfo,
        handshake_timeout: float = 20.0,
        dial_timeout: float = 3.0,
    ):
        self.node_key = node_key
        self.node_info = node_info
        self.handshake_timeout = handshake_timeout
        self.dial_timeout = dial_timeout
        self._listener: socket.socket | None = None
        self._closed = threading.Event()

    # -- listening ---------------------------------------------------------

    def listen(self, addr: str) -> None:
        host, port = parse_addr(addr)
        s = socket.socket(socket.AF_INET)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((host, port))
        s.listen(32)
        self._listener = s

    @property
    def listen_addr(self) -> str:
        host, port = self._listener.getsockname()
        return f"tcp://{host}:{port}"

    def accept(self) -> UpgradedConn:
        """Blocks for the next inbound upgraded connection."""
        conn, addr = self._listener.accept()
        return self._upgrade(conn, outbound=False, socket_addr=f"{addr[0]}:{addr[1]}")

    # -- dialing -----------------------------------------------------------

    def dial(self, addr: str) -> UpgradedConn:
        host, port = parse_addr(addr)
        conn = socket.create_connection((host, port), timeout=self.dial_timeout)
        up = self._upgrade(conn, outbound=True, socket_addr=f"{host}:{port}")
        expect = addr_id(addr)
        if expect and up.node_info.node_id != expect:
            up.secret_conn.close()
            raise TransportError(
                f"dialed {expect} but got {up.node_info.node_id}"
            )
        return up

    # -- upgrade (transport.go upgrade) ------------------------------------

    def _upgrade(self, conn: socket.socket, outbound: bool, socket_addr: str):
        conn.settimeout(self.handshake_timeout)
        try:
            sc = SecretConnection(conn, self.node_key.priv_key)
            # NodeInfo exchange: u32 length + JSON, both directions.
            raw = self.node_info.encode()
            sc.write(struct.pack("<I", len(raw)) + raw)
            (length,) = struct.unpack("<I", sc.read_exact_msg(4))
            if length > MAX_NODE_INFO_SIZE:
                raise TransportError("oversized node info")
            peer_info = NodeInfo.decode(sc.read_exact_msg(length))
            peer_info.validate_basic()
            # The authenticated key must match the claimed ID.
            derived = node_id_from_pubkey(sc.remote_pub_key)
            if derived != peer_info.node_id:
                raise TransportError(
                    f"node id {peer_info.node_id} does not match "
                    f"authenticated key {derived}"
                )
            if peer_info.node_id == self.node_info.node_id:
                raise TransportError("rejecting self-connection")
            self.node_info.compatible_with(peer_info)
        except TransportError:
            try:
                conn.close()
            except OSError:
                pass
            raise
        except Exception as e:
            # Anything a hostile/broken peer can trigger mid-handshake
            # (bad JSON, bad hex, SecretConnectionError, EOF...) must not
            # escape as a non-TransportError — the accept loop would die.
            try:
                conn.close()
            except OSError:
                pass
            raise TransportError(f"{type(e).__name__}: {e}") from e
        conn.settimeout(None)
        return UpgradedConn(sc, peer_info, outbound, socket_addr)

    def close(self) -> None:
        self._closed.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
