"""Peer exchange: bucketed address book + discovery reactor (channel 0x00).

Reference: /root/reference/p2p/pex/.
"""

from .addrbook import AddrBook, KnownAddress
from .reactor import PEX_CHANNEL, PexAddrsMessage, PexReactor, PexRequestMessage

__all__ = [
    "AddrBook",
    "KnownAddress",
    "PEX_CHANNEL",
    "PexAddrsMessage",
    "PexReactor",
    "PexRequestMessage",
]
