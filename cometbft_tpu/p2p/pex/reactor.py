"""PEX reactor: peer discovery over channel 0x00.

Reference: p2p/pex/pex_reactor.go:764. Responsibilities:

* answer PexRequest with a random selection from the address book
  (throttled per peer);
* feed received PexAddrs into the book;
* ensure-peers loop: when outbound slots are free, dial addresses picked
  from the book (new/old biased by connectedness);
* seed mode: accept, serve addresses, then hang up (crawler-lite).
"""

from __future__ import annotations

import threading
from ...libs import sync as libsync
import time
from dataclasses import dataclass, field

from ...libs import log as _log
from ...types import serialization as ser
from ..base_reactor import ChannelDescriptor, Reactor
from .addrbook import AddrBook

PEX_CHANNEL = 0x00

_ENSURE_INTERVAL = 1.0  # pex_reactor.go ensurePeersPeriod (30s; test-scaled)
_REQUEST_INTERVAL = 2.0  # min seconds between requests per peer
_MAX_ADDRS_PER_MSG = 250


@dataclass(slots=True)
class PexRequestMessage:
    pass


@dataclass(slots=True)
class PexAddrsMessage:
    addrs: list[str] = field(default_factory=list)


ser.codec.register(PexRequestMessage, PexAddrsMessage)


class PexReactor(Reactor):
    def __init__(
        self,
        book: AddrBook,
        seed_mode: bool = False,
        ensure_interval: float = _ENSURE_INTERVAL,
        max_outbound: int = 10,
    ):
        super().__init__("pex-reactor")
        self.book = book
        self.seed_mode = seed_mode
        self.ensure_interval = ensure_interval
        self.max_outbound = max_outbound
        self._last_request: dict[str, float] = {}
        self._requested: set[str] = set()  # peers we asked (expect a reply)
        self._dialing: set[str] = set()
        self._mtx = libsync.Mutex("p2p.pex.reactor._mtx")
        self._stop = threading.Event()

    def get_channels(self):
        return [
            ChannelDescriptor(
                id=PEX_CHANNEL,
                priority=1,
                send_queue_capacity=10,
                recv_message_capacity=64 * 1024,
            )
        ]

    def on_start(self) -> None:
        threading.Thread(
            target=self._ensure_peers_routine, name="pex-ensure", daemon=True
        ).start()

    def on_stop(self) -> None:
        self._stop.set()
        self.book.save()

    # -- peer lifecycle ----------------------------------------------------

    def add_peer(self, peer) -> None:
        if peer.outbound:
            # outbound connect proved the address (pex_reactor.go AddPeer).
            # socket_addr is bare host:port; the book keys by node id.
            if peer.socket_addr:
                addr = peer.socket_addr
                if "@" not in addr:
                    addr = f"{peer.id}@{addr}"
                self.book.mark_good(addr)
            self._request_addrs(peer)
        elif self.seed_mode:
            # seeds serve a selection immediately, then disconnect
            peer.try_send(
                PEX_CHANNEL,
                ser.dumps(
                    PexAddrsMessage(
                        addrs=self.book.get_selection()[:_MAX_ADDRS_PER_MSG]
                    )
                ),
            )

    def remove_peer(self, peer, reason) -> None:
        with self._mtx:
            self._last_request.pop(peer.id, None)
            self._requested.discard(peer.id)

    # -- receive -----------------------------------------------------------

    def receive(self, ch_id: int, peer, msg_bytes: bytes) -> None:
        msg = ser.loads(msg_bytes)
        if isinstance(msg, PexRequestMessage):
            now = time.monotonic()
            with self._mtx:
                last = self._last_request.get(peer.id, 0.0)
                if now - last < _REQUEST_INTERVAL:
                    return  # throttle spammy askers (receiveRequest)
                self._last_request[peer.id] = now
            peer.try_send(
                PEX_CHANNEL,
                ser.dumps(
                    PexAddrsMessage(
                        addrs=self.book.get_selection()[:_MAX_ADDRS_PER_MSG]
                    )
                ),
            )
            if self.seed_mode:
                # seed: job done, free the slot (pex_reactor.go:174)
                threading.Timer(
                    0.5, self._disconnect_peer, args=(peer,)
                ).start()
        elif isinstance(msg, PexAddrsMessage):
            with self._mtx:
                solicited = peer.id in self._requested
                self._requested.discard(peer.id)
            if not solicited:
                return  # unsolicited addrs: ignore (ReceiveAddrs guard)
            for addr in msg.addrs[:_MAX_ADDRS_PER_MSG]:
                self.book.add_address(addr, src=peer.id)

    def _disconnect_peer(self, peer) -> None:
        if self.switch is not None:
            self.switch.stop_and_remove_peer(peer, "seed: served addrs")

    def _request_addrs(self, peer) -> None:
        with self._mtx:
            self._requested.add(peer.id)
        peer.try_send(PEX_CHANNEL, ser.dumps(PexRequestMessage()))

    # -- ensure-peers loop (pex_reactor.go:426 ensurePeers) ----------------

    def _ensure_peers_routine(self) -> None:
        while not self._stop.is_set():
            try:
                self._ensure_peers()
            except Exception as e:  # CLNT006: keep the loop alive, but a
                # failing ensure-peers pass starves the dial schedule
                _log.default_logger().with_module("pex").error(
                    "ensure-peers pass failed", err=repr(e)[:120]
                )
            self._stop.wait(self.ensure_interval)

    def _ensure_peers(self) -> None:
        if self.switch is None or self.seed_mode:
            return
        outbound, _inbound = self.switch.num_peers()
        need = self.max_outbound - outbound
        if need <= 0:
            return
        connected = {p.id for p in self.switch.peers()}
        for _ in range(need * 2):
            ka = self.book.pick_address()
            if ka is None:
                break
            with self._mtx:
                if ka.node_id in self._dialing:
                    continue
            if ka.node_id in connected:
                continue
            with self._mtx:
                self._dialing.add(ka.node_id)
            self.book.mark_attempt(ka.addr)
            threading.Thread(
                target=self._dial, args=(ka,), daemon=True
            ).start()
            need -= 1
            if need <= 0:
                break
        # still starving and nobody to dial: ask a connected peer for more
        if need > 0:
            peers = self.switch.peers()
            if peers:
                self._request_addrs(peers[int(time.time()) % len(peers)])

    def _dial(self, ka) -> None:
        try:
            # non-persistent dial: single attempt, no backoff loop
            self.switch._dial_with_backoff(ka.addr)
        except Exception as e:  # CLNT006: dial failures are routine
            # (mark_attempt already recorded it) — log at debug only
            _log.default_logger().with_module("pex").debug(
                "dial failed", addr=str(ka.addr), err=repr(e)[:120]
            )
        finally:
            with self._mtx:
                self._dialing.discard(ka.node_id)
