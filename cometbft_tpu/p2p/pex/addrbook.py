"""Bucketed peer address book (reference: p2p/pex/addrbook.go:947).

Two bucket classes, like the reference:

* NEW buckets — addresses heard about (from PEX or config) but never
  successfully connected to. Bucketed by hash(src_id, addr_group) so one
  gossiping peer can't own the whole table.
* OLD buckets — addresses we HAVE connected to (mark_good promotes).
  Bucketed by hash(addr_group).

Eviction drops the oldest address of a full bucket (the reference evicts
by lowest chance score; last_attempt ordering approximates it without the
clock arithmetic). The book persists to a JSON file on every mutation
batch and reloads on boot (addrbook.go saveToFile/loadFromFile).
"""

from __future__ import annotations

import hashlib
import json
import os
import random
from ...libs import sync as libsync
import time
from dataclasses import asdict, dataclass

NEW_BUCKET_COUNT = 256
OLD_BUCKET_COUNT = 64
BUCKET_SIZE = 64
# getSelection caps (pex_reactor / addrbook.go GetSelection)
SELECTION_PERCENT = 23
MAX_SELECTION = 250


@dataclass
class KnownAddress:
    """addrbook.go knownAddress."""

    addr: str  # "id@host:port"
    src: str  # peer id that told us
    attempts: int = 0
    last_attempt: float = 0.0
    last_success: float = 0.0
    bucket_type: str = "new"  # "new" | "old"

    @property
    def node_id(self) -> str:
        return self.addr.partition("@")[0]

    @property
    def host(self) -> str:
        return self.addr.partition("@")[2].rpartition(":")[0]

    def is_old(self) -> bool:
        return self.bucket_type == "old"

    def is_bad(self, now: float) -> bool:
        """addrbook.go isBad: too many failed attempts, never succeeded."""
        return self.attempts >= 3 and self.last_success == 0


def _group(addr: str) -> str:
    """Routability group: /16 for IPv4-ish hosts (addrbook.go groupKey)."""
    host = addr.partition("@")[2].rpartition(":")[0]
    parts = host.split(".")
    if len(parts) == 4:
        return ".".join(parts[:2])
    return host


class AddrBook:
    def __init__(self, file_path: str | None = None, key: bytes | None = None):
        self.file_path = file_path
        self._key = key if key is not None else os.urandom(8)
        self._mtx = libsync.Mutex("p2p.pex.addrbook._mtx")
        self._addrs: dict[str, KnownAddress] = {}  # node_id -> ka
        self._new: list[set[str]] = [set() for _ in range(NEW_BUCKET_COUNT)]
        self._old: list[set[str]] = [set() for _ in range(OLD_BUCKET_COUNT)]
        self._our_ids: set[str] = set()
        self._rng = random.Random()
        if file_path and os.path.exists(file_path):
            self._load()

    # -- identity ----------------------------------------------------------

    def add_our_address(self, node_id: str) -> None:
        with self._mtx:
            self._our_ids.add(node_id)
            self._remove_locked(node_id)

    # -- hashing -----------------------------------------------------------

    def _bucket_new(self, ka: KnownAddress) -> int:
        h = hashlib.sha256(
            self._key + ka.src.encode() + _group(ka.addr).encode()
        ).digest()
        return int.from_bytes(h[:4], "big") % NEW_BUCKET_COUNT

    def _bucket_old(self, ka: KnownAddress) -> int:
        h = hashlib.sha256(self._key + _group(ka.addr).encode()).digest()
        return int.from_bytes(h[:4], "big") % OLD_BUCKET_COUNT

    # -- mutations ---------------------------------------------------------

    def add_address(self, addr: str, src: str) -> bool:
        """addrbook.go AddAddress: new addresses land in a NEW bucket."""
        node_id = addr.partition("@")[0]
        if not node_id or "@" not in addr:
            return False
        with self._mtx:
            if node_id in self._our_ids:
                return False
            ka = self._addrs.get(node_id)
            if ka is not None:
                if ka.is_old():
                    return False  # already proven; keep the old entry
                # refresh source/address for a known-new entry
                ka.addr = addr
                return False
            ka = KnownAddress(addr=addr, src=src)
            self._addrs[node_id] = ka
            bucket = self._new[self._bucket_new(ka)]
            if len(bucket) >= BUCKET_SIZE:
                self._evict_locked(bucket)
            bucket.add(node_id)
            self._save_locked()
            return True

    def mark_attempt(self, addr: str) -> None:
        with self._mtx:
            ka = self._addrs.get(addr.partition("@")[0])
            if ka is not None:
                ka.attempts += 1
                ka.last_attempt = time.time()

    def mark_good(self, addr: str) -> None:
        """Successful handshake: promote to an OLD bucket
        (addrbook.go MarkGood/moveToOld)."""
        node_id = addr.partition("@")[0]
        with self._mtx:
            ka = self._addrs.get(node_id)
            if ka is None:
                ka = KnownAddress(addr=addr, src=node_id)
                self._addrs[node_id] = ka
            ka.attempts = 0
            ka.last_success = time.time()
            if not ka.is_old():
                self._new[self._bucket_new(ka)].discard(node_id)
                ka.bucket_type = "old"
                bucket = self._old[self._bucket_old(ka)]
                if len(bucket) >= BUCKET_SIZE:
                    self._evict_locked(bucket)
                bucket.add(node_id)
            self._save_locked()

    def mark_bad(self, addr: str) -> None:
        with self._mtx:
            self._remove_locked(addr.partition("@")[0])
            self._save_locked()

    def _remove_locked(self, node_id: str) -> None:
        ka = self._addrs.pop(node_id, None)
        if ka is None:
            return
        for bucket in self._new + self._old:
            bucket.discard(node_id)

    def _evict_locked(self, bucket: set[str]) -> None:
        """Drop the stalest entry of a full bucket."""
        victim = min(
            bucket,
            key=lambda nid: self._addrs[nid].last_attempt
            if nid in self._addrs
            else 0.0,
        )
        bucket.discard(victim)
        self._addrs.pop(victim, None)

    # -- queries -----------------------------------------------------------

    def size(self) -> int:
        with self._mtx:
            return len(self._addrs)

    def is_empty(self) -> bool:
        return self.size() == 0

    def has(self, node_id: str) -> bool:
        with self._mtx:
            return node_id in self._addrs

    def pick_address(self, new_bias_pct: int = 30) -> KnownAddress | None:
        """Random pick biased between new/old (addrbook.go PickAddress)."""
        with self._mtx:
            now = time.time()
            news = [
                ka
                for ka in self._addrs.values()
                if not ka.is_old() and not ka.is_bad(now)
            ]
            olds = [
                ka
                for ka in self._addrs.values()
                if ka.is_old() and not ka.is_bad(now)
            ]
            if not news and not olds:
                return None
            use_new = news and (
                not olds or self._rng.randrange(100) < new_bias_pct
            )
            pool = news if use_new else olds
            return self._rng.choice(pool)

    def get_selection(self) -> list[str]:
        """Random ~23% (max 250) of addresses for a PEX response
        (addrbook.go GetSelection)."""
        with self._mtx:
            addrs = [ka.addr for ka in self._addrs.values()]
        n = min(max(len(addrs) * SELECTION_PERCENT // 100, 1), MAX_SELECTION)
        self._rng.shuffle(addrs)
        return addrs[:n]

    # -- persistence -------------------------------------------------------

    def _save_locked(self) -> None:
        if not self.file_path:
            return
        payload = {
            "key": self._key.hex(),
            "addrs": [asdict(ka) for ka in self._addrs.values()],
        }
        tmp = self.file_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, self.file_path)

    def save(self) -> None:
        with self._mtx:
            self._save_locked()

    def _load(self) -> None:
        try:
            with open(self.file_path) as f:
                payload = json.load(f)
        except (OSError, json.JSONDecodeError):
            return
        self._key = bytes.fromhex(payload.get("key", self._key.hex()))
        for row in payload.get("addrs", []):
            ka = KnownAddress(**row)
            self._addrs[ka.node_id] = ka
            if ka.is_old():
                self._old[self._bucket_old(ka)].add(ka.node_id)
            else:
                self._new[self._bucket_new(ka)].add(ka.node_id)
