"""Authenticated encryption handshake + framing (reference:
p2p/conn/secret_connection.go:63-120).

Station-to-Station protocol:
1. exchange ephemeral X25519 pubkeys (unencrypted, 32B each);
2. ECDH → HKDF-SHA256 (secret_connection.go:335) expands 96 bytes: two
   ChaCha20-Poly1305 keys (low/high by ephemeral key order) + a 32-byte
   challenge;
3. each side signs the challenge with its persistent ed25519 key and
   sends (pubkey, sig) over the now-encrypted link (:389);
4. all traffic flows in sealed frames: 4-byte LE length + payload padded
   to 1024 bytes, 16-byte Poly1305 tag; 96-bit little-endian counter
   nonces (:453).
"""

from __future__ import annotations

import os
import struct
from ...libs import sync as libsync

try:  # the cryptography wheel (OpenSSL) is preferred; slim containers
    # fall back to the project's pure-Python X25519/HKDF/ChaCha20-
    # Poly1305 below — same RFCs, interoperable across the two paths.
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric.x25519 import (
        X25519PrivateKey,
        X25519PublicKey,
    )
    from cryptography.hazmat.primitives.kdf.hkdf import HKDF

    _HAVE_CRYPTOGRAPHY = True
except ImportError:  # pragma: no cover
    _HAVE_CRYPTOGRAPHY = False

from ...crypto import x25519 as x25519_fallback
from ...crypto.aead import new_chacha20poly1305
from ...crypto.keys import Ed25519PubKey

DATA_LEN_SIZE = 4
DATA_MAX_SIZE = 1024
TOTAL_FRAME_SIZE = DATA_MAX_SIZE + DATA_LEN_SIZE
TAG_SIZE = 16
SEALED_FRAME_SIZE = TOTAL_FRAME_SIZE + TAG_SIZE

HKDF_INFO = b"TENDERMINT_SECRET_CONNECTION_KEY_AND_CHALLENGE_GEN"
CHALLENGE_CONTEXT = b"TENDERMINT_SECRET_CONNECTION_KEY_CHALLENGE"


class SecretConnectionError(Exception):
    pass


def _x25519_keypair():
    """(opaque private handle, 32-byte public key)."""
    if _HAVE_CRYPTOGRAPHY:
        priv = X25519PrivateKey.generate()
        return priv, priv.public_key().public_bytes_raw()
    seed = os.urandom(32)
    return seed, x25519_fallback.x25519_base(seed)


def _x25519_exchange(priv, remote_pub: bytes) -> bytes:
    if _HAVE_CRYPTOGRAPHY:
        return priv.exchange(X25519PublicKey.from_public_bytes(remote_pub))
    shared = x25519_fallback.x25519(priv, remote_pub)
    if shared == bytes(32):
        # low-order remote point: the whole "shared" secret is attacker-
        # known. OpenSSL's exchange() raises here; match it exactly so
        # wheel-less nodes reject the same peers wheel-backed ones do.
        raise SecretConnectionError("x25519: low-order remote ephemeral key")
    return shared


def hkdf_sha256(
    ikm: bytes, info: bytes, length: int, salt: bytes = b"\x00" * 32
) -> bytes:
    """RFC 5869 HKDF-SHA256 (pure, stdlib hmac). Default salt is the
    RFC's not-provided case (HashLen zeros). Pinned against the RFC 5869
    A.1/A.3 vectors in tests/test_crypto_host.py."""
    import hashlib
    import hmac

    prk = hmac.new(salt, ikm, hashlib.sha256).digest()
    okm = b""
    t = b""
    i = 1
    while len(okm) < length:
        t = hmac.new(prk, t + info + bytes([i]), hashlib.sha256).digest()
        okm += t
        i += 1
    return okm[:length]


def _hkdf_sha256_96(shared: bytes) -> bytes:
    """HKDF-SHA256(salt=None, info=HKDF_INFO) -> 96 bytes."""
    if _HAVE_CRYPTOGRAPHY:
        return HKDF(
            algorithm=hashes.SHA256(),
            length=96,
            salt=None,
            info=HKDF_INFO,
        ).derive(shared)
    return hkdf_sha256(shared, HKDF_INFO, 96)


class _Nonce:
    """96-bit LE counter nonce (secret_connection.go:446-458)."""

    __slots__ = ("n",)

    def __init__(self) -> None:
        self.n = 0

    def next(self) -> bytes:
        out = b"\x00\x00\x00\x00" + struct.pack("<Q", self.n)
        self.n += 1
        if self.n >= 1 << 64:
            raise SecretConnectionError("nonce wrapped")
        return out


class SecretConnection:
    """Wraps a socket-like object (needs sendall/recv) post-handshake."""

    def __init__(self, sock, priv_key):
        """priv_key: our persistent ed25519 key (node key)."""
        self._sock = sock
        self._send_mtx = libsync.Mutex("p2p.conn.secret_connection._send_mtx")
        self._recv_mtx = libsync.Mutex("p2p.conn.secret_connection._recv_mtx")
        self._recv_buf = b""

        # 1. ephemeral key exchange
        eph_priv, eph_pub = _x25519_keypair()
        self._write_all(eph_pub)
        remote_eph = self._read_exact(32)

        # 2. shared secret → keys + challenge
        shared = _x25519_exchange(eph_priv, remote_eph)
        okm = _hkdf_sha256_96(shared)
        # Key order: the side with the smaller ephemeral pubkey uses okm[:32]
        # to receive (secret_connection.go:312-333).
        loc_is_least = eph_pub < remote_eph
        if loc_is_least:
            recv_key, send_key = okm[:32], okm[32:64]
        else:
            send_key, recv_key = okm[:32], okm[32:64]
        challenge = okm[64:96]
        self._send_aead = new_chacha20poly1305(send_key)
        self._recv_aead = new_chacha20poly1305(recv_key)
        self._send_nonce = _Nonce()
        self._recv_nonce = _Nonce()

        # 3. authenticate: sign challenge, swap (pubkey, sig) encrypted
        sig = priv_key.sign(CHALLENGE_CONTEXT + challenge)
        self.write(priv_key.pub_key().bytes() + sig)
        auth = self.read_exact_msg(32 + 64)
        remote_pub_bytes, remote_sig = auth[:32], auth[32:]
        self.remote_pub_key = Ed25519PubKey(remote_pub_bytes)
        if not self.remote_pub_key.verify_signature(
            CHALLENGE_CONTEXT + challenge, remote_sig
        ):
            raise SecretConnectionError("challenge signature invalid")

    # -- raw io ------------------------------------------------------------

    def _write_all(self, data: bytes) -> None:
        self._sock.sendall(data)

    def _read_exact(self, n: int) -> bytes:
        out = b""
        while len(out) < n:
            chunk = self._sock.recv(n - len(out))
            if not chunk:
                raise EOFError("secret connection closed")
            out += chunk
        return out

    # -- sealed framing ----------------------------------------------------

    def write(self, data: bytes) -> int:
        """Encrypt+send; fragments into 1024-byte frames."""
        n = 0
        with self._send_mtx:  # cometlint: disable=CLNT009 -- send mutex pairs the AEAD nonce sequence with socket order
            for i in range(0, max(len(data), 1), DATA_MAX_SIZE):
                chunk = data[i : i + DATA_MAX_SIZE]
                frame = struct.pack("<I", len(chunk)) + chunk
                frame += b"\x00" * (TOTAL_FRAME_SIZE - len(frame))
                sealed = self._send_aead.encrypt(
                    self._send_nonce.next(), frame, None
                )
                self._write_all(sealed)
                n += len(chunk)
        return n

    def _read_frame(self) -> bytes:
        sealed = self._read_exact(SEALED_FRAME_SIZE)
        try:
            frame = self._recv_aead.decrypt(
                self._recv_nonce.next(), sealed, None
            )
        except Exception as e:
            raise SecretConnectionError(f"frame decryption failed: {e}") from e
        (length,) = struct.unpack("<I", frame[:DATA_LEN_SIZE])
        if length > DATA_MAX_SIZE:
            raise SecretConnectionError("frame length corrupt")
        return frame[DATA_LEN_SIZE : DATA_LEN_SIZE + length]

    def read(self, n: int) -> bytes:
        """Read up to n plaintext bytes (at least 1)."""
        with self._recv_mtx:  # cometlint: disable=CLNT009 -- recv mutex pairs the AEAD nonce sequence with socket reads
            if not self._recv_buf:
                self._recv_buf = self._read_frame()
            out, self._recv_buf = self._recv_buf[:n], self._recv_buf[n:]
            return out

    def read_exact_msg(self, n: int) -> bytes:
        out = b""
        while len(out) < n:
            out += self.read(n - len(out))
        return out

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass
