"""Multiplexed connection (reference: p2p/conn/connection.go:80-921).

N logical byte-ID channels over one (secret) connection. Each channel has
a bounded send queue and a priority; the send routine repeatedly picks the
channel with the least recently-sent/priority ratio (connection.go:522)
and emits one packet (≤1024B payload). The recv routine reassembles
packets until EOF and hands complete messages to ``on_receive``.
Ping/pong keep-alive kills dead peers; flowrate throttles both directions.

Packet wire format (binary, little-endian):
``0x01`` ping | ``0x02`` pong | ``0x03 channel_id:u8 eof:u8 len:u16 data``.
"""

from __future__ import annotations

import struct

from ...libs import metrics as libmetrics
from ...libs import netstats as libnetstats
from ...libs import trace as libtrace
import threading
from ...libs import sync as libsync
import time
from dataclasses import dataclass

from ...libs.flowrate import Monitor
from ...libs.service import BaseService

_PKT_PING = 1
_PKT_PONG = 2
_PKT_MSG = 3

MAX_PACKET_PAYLOAD = 1024
DEFAULT_SEND_RATE = 5_120_000
DEFAULT_RECV_RATE = 5_120_000
DEFAULT_SEND_QUEUE_CAPACITY = 1
DEFAULT_RECV_MESSAGE_CAPACITY = 22020096  # block part ceiling
PING_INTERVAL = 60.0
PONG_TIMEOUT = 45.0
FLUSH_THROTTLE = 0.1


@dataclass(slots=True)
class MConnConfig:
    send_rate: int = DEFAULT_SEND_RATE
    recv_rate: int = DEFAULT_RECV_RATE
    max_packet_msg_payload_size: int = MAX_PACKET_PAYLOAD
    flush_throttle: float = FLUSH_THROTTLE
    ping_interval: float = PING_INTERVAL
    pong_timeout: float = PONG_TIMEOUT


@dataclass(slots=True)
class ChannelDescriptor:
    id: int
    priority: int = 1
    send_queue_capacity: int = DEFAULT_SEND_QUEUE_CAPACITY
    recv_message_capacity: int = DEFAULT_RECV_MESSAGE_CAPACITY


class _Channel:
    def __init__(self, desc: ChannelDescriptor):
        self.desc = desc
        self._mtx = libsync.Mutex("p2p.conn.connection._mtx")
        self._queue: list[bytes] = []
        self._not_full = libsync.Condition(self._mtx)
        self.sending: bytes | None = None
        self.sent_pos = 0
        self.recently_sent = 0  # exponentially decayed
        self.recving = b""

    def enqueue(self, msg: bytes, timeout: float) -> bool:
        with self._not_full:
            if not self._not_full.wait_for(
                lambda: len(self._queue) < self.desc.send_queue_capacity,
                timeout,
            ):
                return False
            self._queue.append(msg)
            return True

    def try_enqueue(self, msg: bytes) -> bool:
        with self._mtx:
            if len(self._queue) >= self.desc.send_queue_capacity:
                return False
            self._queue.append(msg)
            return True

    def has_data(self) -> bool:
        with self._mtx:
            return self.sending is not None or bool(self._queue)

    def next_packet(self, max_payload: int) -> tuple[bytes, bool] | None:
        with self._not_full:
            if self.sending is None:
                if not self._queue:
                    return None
                self.sending = self._queue.pop(0)
                self.sent_pos = 0
                self._not_full.notify()
            chunk = self.sending[self.sent_pos : self.sent_pos + max_payload]
            self.sent_pos += len(chunk)
            eof = self.sent_pos >= len(self.sending)
            if eof:
                self.sending = None
                self.sent_pos = 0
            self.recently_sent += len(chunk)
            return chunk, eof


class MConnection(BaseService):
    def __init__(
        self,
        conn,  # SecretConnection or socket-like with write/read_exact_msg
        channels: list[ChannelDescriptor],
        on_receive,  # f(channel_id, msg_bytes)
        on_error,  # f(exception)
        config: MConnConfig | None = None,
        peer_id: str = "",
        outbound: bool = False,
        origin_id: int = 0,
        logger=None,
    ):
        super().__init__("mconnection", logger)
        self.conn = conn
        # flight-ring origin of the node that OWNS this connection: the
        # recv routine dispatches reactors synchronously, so rows they
        # record (p2p.gossip) are attributed to this node (libs/health)
        self.origin_id = origin_id
        self.config = config or MConnConfig()
        self.channels = {d.id: _Channel(d) for d in channels}
        # Labeled-counter children resolved ONCE per channel: the wire
        # loops must not pay a registry lookup + label format per packet.
        # Bound at connection setup — connections are created after node
        # boot, when the node registry is installed.
        m = libmetrics.node_metrics()
        self._send_ctr = {
            d.id: m.p2p_send_bytes.labels(f"{d.id:#04x}") for d in channels
        }
        self._recv_ctr = {
            d.id: m.p2p_recv_bytes.labels(f"{d.id:#04x}") for d in channels
        }
        self._msg_send_ctr = {
            d.id: m.p2p_msgs_sent.labels(f"{d.id:#04x}") for d in channels
        }
        self._msg_recv_ctr = {
            d.id: m.p2p_msgs_recv.labels(f"{d.id:#04x}") for d in channels
        }
        self._drop_ctr = {
            d.id: m.p2p_send_queue_full.labels(f"{d.id:#04x}")
            for d in channels
        }
        self.on_receive = on_receive
        self.on_error = on_error
        self.send_monitor = Monitor()
        self.recv_monitor = Monitor()
        # Per-peer/per-channel stats block (libs/netstats): constructed
        # unconditionally (setup path, not hot), registered for the
        # connection's lifetime in on_start; the per-packet record
        # calls below are one enabled() flag check when the layer is
        # off.
        self.stats = libnetstats.ConnStats(
            peer_id, [d.id for d in channels], self, outbound=outbound
        )
        self._send_signal = threading.Event()
        self._pong_pending = threading.Event()
        self._last_pong = time.monotonic()
        self._write_mtx = libsync.Mutex("p2p.conn.connection._write_mtx")

    # -- API ---------------------------------------------------------------

    def send(self, ch_id: int, msg: bytes, timeout: float = 10.0) -> bool:
        """Queue a message; blocks up to ``timeout`` when the channel queue
        is full (connection.go Send).  A timeout is a DROP the caller
        must handle — it is logged, counted in
        ``p2p_send_queue_full_total{chID}`` and trace-attributed, never
        a silent False."""
        ch = self.channels.get(ch_id)
        if ch is None or not self.is_running():
            return False
        ok = ch.enqueue(msg, timeout)
        if ok:
            self._send_signal.set()
            if libnetstats.enabled():
                self.stats.note_depth(
                    self.stats.slots[ch_id], len(ch._queue)
                )
        else:
            self._note_drop(ch_id, len(msg), timeout)
        return ok

    def _note_drop(self, ch_id: int, nbytes: int, timeout: float) -> None:
        """Account one send() timeout on a full bounded queue."""
        ctr = self._drop_ctr.get(ch_id)
        if ctr is not None:
            ctr.inc()
        if libnetstats.enabled():
            self.stats.note_queue_full(self.stats.slots[ch_id])
        if libtrace.enabled():
            libtrace.event(
                "p2p.drop",
                ch=ch_id,
                bytes=nbytes,
                timeout_s=timeout,
                peer=self.stats.peer_id,
            )
        if self.logger is not None:
            self.logger.debug(
                "send queue full; message dropped",
                ch=f"{ch_id:#04x}",
                bytes=nbytes,
                peer=self.stats.peer_id,
                timeout_s=timeout,
            )

    def try_send(self, ch_id: int, msg: bytes) -> bool:
        ch = self.channels.get(ch_id)
        if ch is None or not self.is_running():
            return False
        ok = ch.try_enqueue(msg)
        if ok:
            self._send_signal.set()
            if libnetstats.enabled():
                self.stats.note_depth(
                    self.stats.slots[ch_id], len(ch._queue)
                )
        elif libnetstats.enabled():
            # an immediate-full miss is normal backpressure (broadcast
            # paths retry) — tallied per channel, surfaced in
            # /debug/net, not in the drop counter
            self.stats.note_try_full(self.stats.slots[ch_id])
        return ok

    # -- lifecycle ---------------------------------------------------------

    def on_start(self) -> None:
        self._last_pong = time.monotonic()
        libnetstats.register(self.stats)
        threading.Thread(
            target=self._routine_entry, args=(self._send_routine,),
            name="mconn-send", daemon=True,
        ).start()
        threading.Thread(
            target=self._routine_entry, args=(self._recv_routine,),
            name="mconn-recv", daemon=True,
        ).start()

    def _routine_entry(self, routine) -> None:
        if self.origin_id:
            from ...libs import health as libhealth

            libhealth.set_thread_origin(self.origin_id)
        routine()

    def on_stop(self) -> None:
        libnetstats.deregister(self.stats)
        self._send_signal.set()
        try:
            self.conn.close()
        except Exception:
            pass

    def _fail(self, err: Exception) -> None:
        if self.is_running():
            try:
                self.stop()
            except Exception:
                pass
            self.on_error(err)

    # -- send side (connection.go:424 sendRoutine) -------------------------

    def _send_routine(self) -> None:
        last_ping = time.monotonic()
        while not self.quit_event().is_set():
            self._send_signal.wait(timeout=0.05)
            self._send_signal.clear()
            try:
                now = time.monotonic()
                if now - last_ping >= self.config.ping_interval:
                    self._write_packet(struct.pack("<B", _PKT_PING))
                    last_ping = now
                if (
                    self._pong_pending.is_set()
                ):
                    self._write_packet(struct.pack("<B", _PKT_PONG))
                    self._pong_pending.clear()
                # Drain packets while any channel has data.
                while not self.quit_event().is_set():
                    if not self._send_some_packets():
                        break
                if (
                    now - self._last_pong
                    > self.config.ping_interval + self.config.pong_timeout
                ):
                    raise TimeoutError("pong timeout")
            except Exception as e:
                self._fail(e)
                return

    def _send_some_packets(self, batch: int = 10) -> bool:
        sent_any = False
        for _ in range(batch):
            if not self._send_one_packet():
                return sent_any
            sent_any = True
        return sent_any

    def _send_one_packet(self) -> bool:
        """Pick the channel with least recently_sent/priority
        (connection.go:522 sendPacketMsg)."""
        best, best_ratio = None, None
        for ch in self.channels.values():
            if not ch.has_data():
                continue
            ratio = ch.recently_sent / max(ch.desc.priority, 1)
            if best_ratio is None or ratio < best_ratio:
                best, best_ratio = ch, ratio
        if best is None:
            # decay all counters while idle
            for ch in self.channels.values():
                ch.recently_sent = int(ch.recently_sent * 0.8)
            return False
        # Ask for budget BEFORE cutting the packet so the allowance bounds
        # the payload (limit() also sleeps when over rate).
        allowed = self.send_monitor.limit(
            self.config.max_packet_msg_payload_size + 5, self.config.send_rate
        )
        max_payload = min(
            self.config.max_packet_msg_payload_size, max(1, allowed - 5)
        )
        pkt = best.next_packet(max_payload)
        if pkt is None:
            return False
        chunk, eof = pkt
        # frame: type u8 | channel u8 | eof u8 | len u16 | data
        self._write_packet(
            struct.pack(
                "<BBBH", _PKT_MSG, best.desc.id, 1 if eof else 0, len(chunk)
            )
            + chunk
        )
        self.send_monitor.update(len(chunk) + 5)
        self._send_ctr[best.desc.id].inc(len(chunk) + 5)
        if eof:
            self._msg_send_ctr[best.desc.id].inc()
        if libnetstats.enabled():
            self.stats.note_sent(
                self.stats.slots[best.desc.id], len(chunk) + 5, eof
            )
        if libtrace.enabled():
            libtrace.event(
                "p2p.send", ch=best.desc.id, bytes=len(chunk) + 5, eof=eof
            )
        return True

    def _write_packet(self, data: bytes) -> None:
        with self._write_mtx:  # cometlint: disable=CLNT009 -- the write mutex exists to serialize whole frames onto the socket
            self.conn.write(data)

    # -- recv side (connection.go:562 recvRoutine) -------------------------

    def _read_exact(self, n: int) -> bytes:
        if hasattr(self.conn, "read_exact_msg"):
            return self.conn.read_exact_msg(n)
        out = b""
        while len(out) < n:
            chunk = self.conn.read(n - len(out))
            if not chunk:
                raise EOFError("connection closed")
            out += chunk
        return out

    def _recv_routine(self) -> None:
        while not self.quit_event().is_set():
            try:
                (ptype,) = struct.unpack("<B", self._read_exact(1))
                if ptype == _PKT_PING:
                    self._pong_pending.set()
                    self._send_signal.set()
                    continue
                if ptype == _PKT_PONG:
                    self._last_pong = time.monotonic()
                    continue
                if ptype != _PKT_MSG:
                    raise ValueError(f"unknown packet type {ptype}")
                ch_id, eof, length = struct.unpack("<BBH", self._read_exact(4))
                data = self._read_exact(length) if length else b""
                ctr = self._recv_ctr.get(ch_id)
                if ctr is not None:
                    ctr.inc(length + 5)
                if libnetstats.enabled():
                    slot = self.stats.slots.get(ch_id)
                    if slot is not None:
                        self.stats.note_recv_bytes(slot, length + 5)
                self.recv_monitor.limit(length + 5, self.config.recv_rate)
                self.recv_monitor.update(length + 5)
                ch = self.channels.get(ch_id)
                if ch is None:
                    raise ValueError(f"unknown channel {ch_id:#x}")
                ch.recving += data
                if len(ch.recving) > ch.desc.recv_message_capacity:
                    raise ValueError(
                        f"recv msg exceeds capacity on channel {ch_id:#x}"
                    )
                if eof:
                    msg, ch.recving = ch.recving, b""
                    self._msg_recv_ctr[ch_id].inc()
                    if libnetstats.enabled():
                        self.stats.note_recv_msg(self.stats.slots[ch_id])
                    if libtrace.enabled():
                        libtrace.event(
                            "p2p.recv", ch=ch_id, bytes=len(msg)
                        )
                    self.on_receive(ch_id, msg)
            except Exception as e:
                if not self.quit_event().is_set():
                    self._fail(e)
                return
