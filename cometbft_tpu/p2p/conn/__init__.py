"""Authenticated, multiplexed connections (reference: p2p/conn/)."""

from .secret_connection import SecretConnection  # noqa: F401
from .connection import ChannelDescriptor, MConnection  # noqa: F401
