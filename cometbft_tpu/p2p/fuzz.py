"""FuzzedConnection: config-driven fault injection on a connection.

Reference: p2p/fuzz.go:14-86. Wraps any read/write/close connection (the
SecretConnection in practice) and probabilistically delays or drops
writes and reads — the lossy-link tier of the test strategy (SURVEY §4):
reactors must survive arbitrary message loss because consensus timeouts,
blocksync re-requests and mempool rebroadcast all assume it.

Modes (fuzz.go FuzzModeDrop/FuzzModeDelay):
  * drop  — with probability ``prob_drop_rw`` a write is swallowed whole
            (the peer never sees it) or a read returns empty;
  * delay — with probability ``prob_sleep`` the op sleeps ``sleep_s``.
"""

from __future__ import annotations

import random
import time


class FuzzedConnection:
    def __init__(
        self,
        conn,
        prob_drop_rw: float = 0.0,
        prob_sleep: float = 0.0,
        sleep_s: float = 0.05,
        seed: int | None = None,
    ):
        self._conn = conn
        self.prob_drop_rw = prob_drop_rw
        self.prob_sleep = prob_sleep
        self.sleep_s = sleep_s
        self._rng = random.Random(seed)
        self.dropped_writes = 0
        self.dropped_reads = 0

    def _fuzz(self) -> bool:
        """True -> drop this op."""
        if self.prob_sleep and self._rng.random() < self.prob_sleep:
            time.sleep(self.sleep_s)
        return bool(
            self.prob_drop_rw and self._rng.random() < self.prob_drop_rw
        )

    def write(self, data: bytes) -> int:
        if self._fuzz():
            self.dropped_writes += 1
            return len(data)  # swallowed: caller believes it was sent
        return self._conn.write(data)

    def read(self, n: int) -> bytes:
        data = self._conn.read(n)
        if self._fuzz():
            self.dropped_reads += 1
            return b""
        return data

    def __getattr__(self, name):
        return getattr(self._conn, name)
