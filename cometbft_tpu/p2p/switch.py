"""Switch: peer/reactor hub (reference: p2p/switch.go:109-861).

Owns the transport, the reactor set, and the peer table. Routes every
inbound message to the reactor that claimed its channel; fans out
broadcasts; redials persistent peers with exponential backoff.
"""

from __future__ import annotations

import random
import threading

from ..libs import sync as libsync

from ..libs.service import BaseService
from .base_reactor import Reactor
from .conn.connection import ChannelDescriptor, MConnConfig
from .peer import Peer
from .transport import MultiplexTransport, TransportError, addr_id, parse_addr

MAX_RECONNECT_ATTEMPTS = 20
RECONNECT_BASE_DELAY = 0.5


class SwitchError(Exception):
    pass


class Switch(BaseService):
    def __init__(
        self,
        transport: MultiplexTransport,
        mconn_config: MConnConfig | None = None,
        max_inbound: int = 40,
        max_outbound: int = 10,
    ):
        super().__init__("p2p-switch")
        self.transport = transport
        self.mconn_config = mconn_config
        self.max_inbound = max_inbound
        self.max_outbound = max_outbound
        self.reactors: dict[str, Reactor] = {}
        self._channel_to_reactor: dict[int, Reactor] = {}
        self._descriptors: list[ChannelDescriptor] = []
        self._peers: dict[str, Peer] = {}
        self._peers_mtx = libsync.RLock("p2p.switch.peers")
        self._persistent_addrs: list[str] = []
        self._dialing: set[str] = set()
        self._health_origin = 0  # interned on first peer admit

    # -- wiring ------------------------------------------------------------

    def add_reactor(self, name: str, reactor: Reactor) -> Reactor:
        for desc in reactor.get_channels():
            if desc.id in self._channel_to_reactor:
                raise SwitchError(f"channel {desc.id:#x} already claimed")
            self._channel_to_reactor[desc.id] = reactor
            self._descriptors.append(desc)
        self.reactors[name] = reactor
        reactor.set_switch(self)
        return reactor

    def channel_ids(self) -> bytes:
        return bytes(sorted(d.id for d in self._descriptors))

    @staticmethod
    def _normalize_addr(addr: str) -> str:
        """Canonical 'id@host:port' (or 'host:port') so persistence checks
        survive formatting differences like a tcp:// scheme."""
        host, port = parse_addr(addr)
        target_id = addr_id(addr)
        base = f"{host}:{port}"
        return f"{target_id}@{base}" if target_id else base

    def set_persistent_peers(self, addrs: list[str]) -> None:
        # lockfree: wiring-phase setter — the list is frozen before on_start spawns the dial/accept routines that read it
        self._persistent_addrs = [self._normalize_addr(a) for a in addrs]

    # -- lifecycle ---------------------------------------------------------

    def on_start(self) -> None:
        for reactor in self.reactors.values():
            reactor.start()
        threading.Thread(
            target=self._accept_routine, name="switch-accept", daemon=True
        ).start()

    def on_stop(self) -> None:
        self.transport.close()
        with self._peers_mtx:
            peers = list(self._peers.values())
        for peer in peers:
            self.stop_and_remove_peer(peer, "switch stopping")
        for reactor in self.reactors.values():
            if reactor.is_running():
                reactor.stop()

    # -- peers -------------------------------------------------------------

    def peers(self) -> list[Peer]:
        with self._peers_mtx:
            return list(self._peers.values())

    def num_peers(self) -> tuple[int, int]:
        with self._peers_mtx:
            out = sum(1 for p in self._peers.values() if p.outbound)
            return out, len(self._peers) - out

    def get_peer(self, peer_id: str) -> Peer | None:
        with self._peers_mtx:
            return self._peers.get(peer_id)

    def _accept_routine(self) -> None:
        while not self.quit_event().is_set():
            try:
                up = self.transport.accept()
            except OSError:
                return
            except TransportError:
                continue
            _, inbound = self.num_peers()
            if inbound >= self.max_inbound:
                up.secret_conn.close()
                continue
            try:
                self._add_peer(up, persistent=False)
            except SwitchError:
                up.secret_conn.close()

    def dial_peers_async(self, addrs: list[str]) -> None:
        for addr in addrs:
            threading.Thread(
                target=self._dial_with_backoff,
                args=(addr,),
                daemon=True,
            ).start()

    def _dial_with_backoff(self, addr: str) -> None:
        addr = self._normalize_addr(addr)
        persistent = addr in self._persistent_addrs
        target_id = addr_id(addr)
        with self._peers_mtx:
            if addr in self._dialing:
                return
            self._dialing.add(addr)
        try:
            for attempt in range(MAX_RECONNECT_ATTEMPTS):
                if self.quit_event().is_set():
                    return
                if target_id and self.get_peer(target_id) is not None:
                    return
                up = None
                try:
                    up = self.transport.dial(addr)
                    self._add_peer(up, persistent=persistent, addr=addr)
                    return
                except Exception:
                    if up is not None:
                        try:
                            up.secret_conn.close()
                        except Exception:
                            pass
                    if not persistent:
                        return
                    delay = min(
                        RECONNECT_BASE_DELAY * (2**attempt), 30.0
                    ) * (0.5 + random.random())
                    if self.quit_event().wait(delay):
                        return
        finally:
            with self._peers_mtx:
                self._dialing.discard(addr)

    def _add_peer(self, up, persistent: bool, addr: str = "") -> Peer:
        # flight-ring origin for this node's recv threads: rows they
        # record (gossip-lag events) decode with our node-id prefix, so
        # in-process multi-node rings split into per-node timelines
        # (register_origin dedupes — one interning per switch lifetime)
        if not self._health_origin:
            from ..libs import health as libhealth

            # lockfree: lazy interning — register_origin dedupes, so two racing admits store the same id and a double write is idempotent
            self._health_origin = libhealth.register_origin(
                self.transport.node_info.node_id[:10]
            )
        peer = Peer(
            up.secret_conn,
            up.node_info,
            self._descriptors,
            on_receive=self._on_peer_receive,
            on_error=self._on_peer_error,
            outbound=up.outbound,
            persistent=persistent,
            socket_addr=up.socket_addr,
            mconn_config=self.mconn_config,
            # our side of the provenance-stamp negotiation + the origin
            # id stamped onto outbound messages (libs/netstats)
            our_node_info=self.transport.node_info,
            origin_id=self._health_origin,
            logger=self.logger,
        )
        with self._peers_mtx:
            # A handshake that completed as (or after) on_stop snapshotted
            # the peer table would admit a peer nobody ever stops — its
            # connection (and netstats block) would outlive the switch.
            # stop() flips is_running() BEFORE on_stop runs, so peers in
            # the table at snapshot time are exactly the peers stopped.
            if not self.is_running():
                raise SwitchError("switch is stopping")
            if peer.id in self._peers:
                raise SwitchError(f"duplicate peer {peer.id[:10]}")
            self._peers[peer.id] = peer
            libsync.lockset_note("Switch._peers")
        try:
            for reactor in self.reactors.values():
                reactor.init_peer(peer)
            peer.start()
            for reactor in self.reactors.values():
                reactor.add_peer(peer)
        except BaseException:
            with self._peers_mtx:
                self._peers.pop(peer.id, None)
            raise
        if self.logger is not None:
            self.logger.info(
                "peer connected",
                peer=peer.id[:10],
                outbound=peer.outbound,
                addr=peer.socket_addr,
            )
        return peer

    def stop_and_remove_peer(self, peer: Peer, reason) -> None:
        with self._peers_mtx:
            if self._peers.pop(peer.id, None) is None:
                return
        try:
            if peer.is_running():
                peer.stop()
        except Exception:
            pass
        if self.logger is not None:
            self.logger.info(
                "peer disconnected", peer=peer.id[:10], reason=str(reason)
            )
        for reactor in self.reactors.values():
            try:
                reactor.remove_peer(peer, reason)
            except Exception:
                pass
        # Reconnect to persistent peers (switch.go:396).
        if peer.persistent and peer.socket_addr and not self.quit_event().is_set():
            addr = f"{peer.id}@{peer.socket_addr}"
            if peer.outbound:
                self.dial_peers_async([addr])

    def _on_peer_receive(self, ch_id: int, peer: Peer, msg: bytes) -> None:
        reactor = self._channel_to_reactor.get(ch_id)
        if reactor is None:
            self.stop_and_remove_peer(
                peer, f"message on unclaimed channel {ch_id:#x}"
            )
            return
        try:
            reactor.receive(ch_id, peer, msg)
        except Exception as e:
            self.stop_and_remove_peer(peer, e)

    def _on_peer_error(self, peer: Peer, err: Exception) -> None:
        self.stop_and_remove_peer(peer, err)

    # -- broadcast (switch.go:272) -----------------------------------------

    def broadcast(self, ch_id: int, msg: bytes) -> None:
        for peer in self.peers():
            threading.Thread(
                target=peer.send, args=(ch_id, msg), daemon=True
            ).start()

    def try_broadcast(self, ch_id: int, msg: bytes) -> None:
        for peer in self.peers():
            peer.try_send(ch_id, msg)
