"""Node identity/compatibility exchange (reference: p2p/node_info.go).

Exchanged right after the SecretConnection upgrade; peers are rejected on
network mismatch, protocol incompatibility, or no common channels.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

P2P_PROTOCOL_VERSION = 8
BLOCK_PROTOCOL_VERSION = 11
MAX_NODE_INFO_SIZE = 10240


class NodeInfoError(Exception):
    pass


@dataclass(slots=True)
class NodeInfo:
    node_id: str
    listen_addr: str
    network: str  # chain id
    version: str = "cometbft-tpu/0.1.0"
    channels: bytes = b""
    moniker: str = "anonymous"
    p2p_version: int = P2P_PROTOCOL_VERSION
    block_version: int = BLOCK_PROTOCOL_VERSION
    other: dict = field(default_factory=dict)

    def validate_basic(self) -> None:
        if not self.node_id:
            raise NodeInfoError("empty node id")
        if len(self.channels) > 16:
            raise NodeInfoError("too many channels")

    def compatible_with(self, other: "NodeInfo") -> None:
        """node_info.go CompatibleWith."""
        if self.block_version != other.block_version:
            raise NodeInfoError(
                f"block version mismatch: {self.block_version} vs "
                f"{other.block_version}"
            )
        if self.network != other.network:
            raise NodeInfoError(
                f"network mismatch: {self.network!r} vs {other.network!r}"
            )
        if self.channels and other.channels:
            if not set(self.channels) & set(other.channels):
                raise NodeInfoError("no common channels")

    def encode(self) -> bytes:
        return json.dumps(
            {
                "node_id": self.node_id,
                "listen_addr": self.listen_addr,
                "network": self.network,
                "version": self.version,
                "channels": self.channels.hex(),
                "moniker": self.moniker,
                "p2p_version": self.p2p_version,
                "block_version": self.block_version,
                "other": self.other,
            },
            separators=(",", ":"),
        ).encode()

    @classmethod
    def decode(cls, raw: bytes) -> "NodeInfo":
        if len(raw) > MAX_NODE_INFO_SIZE:
            raise NodeInfoError("node info too large")
        d = json.loads(raw)
        return cls(
            node_id=d["node_id"],
            listen_addr=d["listen_addr"],
            network=d["network"],
            version=d.get("version", ""),
            channels=bytes.fromhex(d.get("channels", "")),
            moniker=d.get("moniker", ""),
            p2p_version=int(d.get("p2p_version", 0)),
            block_version=int(d.get("block_version", 0)),
            other=d.get("other", {}),
        )
