"""L5 distributed communication backend (reference: p2p/)."""

from .key import NodeKey, node_id_from_pubkey  # noqa: F401
from .node_info import NodeInfo  # noqa: F401
from .base_reactor import Reactor, ChannelDescriptor  # noqa: F401
from .peer import Peer  # noqa: F401
from .switch import Switch  # noqa: F401
from .transport import MultiplexTransport  # noqa: F401
