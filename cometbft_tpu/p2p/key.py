"""Node identity (reference: p2p/key.go).

A node's ID is the hex of its ed25519 pubkey address (20 bytes); the key
persists in ``node_key.json``.
"""

from __future__ import annotations

import json
import os

from ..crypto.keys import Ed25519PrivKey


def node_id_from_pubkey(pub_key) -> str:
    return bytes(pub_key.address()).hex()


class NodeKey:
    def __init__(self, priv_key: Ed25519PrivKey):
        self.priv_key = priv_key

    @property
    def node_id(self) -> str:
        return node_id_from_pubkey(self.priv_key.pub_key())

    def pub_key(self):
        return self.priv_key.pub_key()

    @classmethod
    def load_or_generate(cls, path: str) -> "NodeKey":
        if os.path.exists(path):
            with open(path) as f:
                d = json.load(f)
            return cls(Ed25519PrivKey.from_seed(bytes.fromhex(d["priv_key"])))
        nk = cls(Ed25519PrivKey.generate())
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
        with os.fdopen(fd, "w") as f:
            json.dump({"priv_key": nk.priv_key.seed.hex()}, f)
        return nk
