"""Pallas TPU kernel for batched ed25519 verification.

Why a hand kernel when ops/curve.py already runs under jit: the XLA
lowering materializes every field-mul intermediate — a (20, 20, N) outer
product plus carry chains per multiply, ~3.6k multiplies per signature —
so the verify is HBM-bandwidth-bound at a few percent VPU utilization.
This kernel keeps the accumulator point, the per-lane 16-entry table and
every temporary in VMEM for the whole 64-window ladder; HBM traffic is
one read of the packed inputs and one write of the validity bitmap.

Layout: a field element is (20, B) int32 limbs of 13 bits, limb axis on
sublanes, the B-lane signature axis minor (vector lanes) — same
representation and lazy-carry discipline as ops/field.py (limbs <= 10015,
single-pass carries; see the interval proof in tests/test_field.py). The
math is the same complete a=-1 Edwards formulas and ZIP-215 acceptance as
ops/curve.py (reference semantics: crypto/ed25519/ed25519.go:26-29 and
curve25519-voi's cofactored batch equation in the Go engine); results are
asserted bit-identical to the XLA kernel in tests/test_curve.py.

Differences from the XLA path, all for Mosaic friendliness:
* mul accumulates the 39 product columns with 20 static slice-adds
  instead of the pad/flatten/reshape "shear" (leading-axis reshapes force
  relayouts in Mosaic).
* table selects are explicit 16-step one-hot multiply-accumulates.
* A and R decompress together as one (20, 2B) batch so the ~254-squaring
  sqrt chain runs at double vector width.
"""

from __future__ import annotations

import threading
from functools import lru_cache

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import curve, field
from ..libs.accel import ACCELERATOR_BACKENDS

BITS = field.BITS
NLIMB = field.NLIMB
MASK = field.MASK
FOLD = field.FOLD
TSIZE = curve.TSIZE
WINDOWS = curve.WINDOWS
WBITS = curve.WBITS

_P_LIMBS = tuple(int(v) for v in field._P_LIMBS)

# Array-shaped constants can't be captured by a Pallas kernel body, and
# (20, 1) values trip Mosaic's both-axes broadcast limitation. Instead
# every constant is rebuilt at kernel entry from Python ints as a stack
# of scalar splat rows — 52 concats of 20 (1, B) splats, executed once
# per block and dwarfed by the ~3.6k field muls that follow.


def _rows(limbs, batch) -> jnp.ndarray:
    """Static limb list -> (20, B) via scalar splats (Mosaic-friendly)."""
    return jnp.concatenate(
        [jnp.full((1, batch), int(v), jnp.int32) for v in limbs], axis=0
    )


class _TraceConsts:
    """Trace-time constants, built lazily per (name, lane width).

    The cache is THREAD-LOCAL and reset at each kernel trace entry so
    tracers never leak between traces — two threads tracing concurrently
    (e.g. blocksync and consensus both compiling on first use) must not
    share or wipe each other's tracer-backed constants. Widths: B for
    the ladder, 2B for the fused A+R decompression.
    """

    _tls = threading.local()

    @classmethod
    def reset(cls):
        cls._tls.cache = {}

    @classmethod
    def _get(cls, key, limbs, batch):
        cache = getattr(cls._tls, "cache", None)
        if cache is None:
            cache = cls._tls.cache = {}
        k = (key, batch)
        if k not in cache:
            cache[k] = _rows(limbs, batch)
        return cache[k]

    @classmethod
    def sub_bias(cls, batch):
        return cls._get("bias", field._SUB_BIAS, batch)

    @classmethod
    def d(cls, batch):
        return cls._get("d", field.to_limbs(curve.D_INT), batch)

    @classmethod
    def d2(cls, batch):
        return cls._get("d2", field.to_limbs(curve.D2_INT), batch)

    @classmethod
    def sqrt_m1(cls, batch):
        return cls._get("sqrt_m1", field.to_limbs(curve.SQRT_M1_INT), batch)

    @classmethod
    def base_entry(cls, k, batch):
        return tuple(
            cls._get(("bt", k, c), curve._BASE_TABLE[k, c], batch)
            for c in range(3)
        )


_TC = _TraceConsts


# ---------------------------------------------------------------- field ops
# Same semantics as ops/field.py, restricted to Mosaic-friendly shapes:
# every value is (..., 20, B) int32 with static leading axes.


def _carry(x, passes):
    for _ in range(passes):
        lo = x & MASK
        hi = x >> BITS
        rolled = jnp.concatenate([hi[..., -1:, :] * FOLD, hi[..., :-1, :]], axis=-2)
        x = lo + rolled
    return x


def _add(a, b):
    return _carry(a + b, 1)


def _sub(a, b):
    return _carry(a + _TC.sub_bias(max(a.shape[-1], b.shape[-1])) - b, 1)


def _neg(a):
    return _carry(_TC.sub_bias(a.shape[-1]) - a, 1)


def _dbl2(a):
    return _carry(a + a, 1)


def _mul(a, b):
    """(20, B) x (20, B) -> (20, B): schoolbook columns via slice-adds.

    Either operand may be a (20, 1) broadcast constant."""
    batch = max(a.shape[-1], b.shape[-1])
    # Pre-broadcast (20, 1) constants along lanes only: a row slice of a
    # (20, 1) operand would otherwise need a (1,1)->(20,B) splat, which
    # Mosaic refuses (both sublanes and lanes at once).
    if a.shape[-1] != batch:
        a = jnp.broadcast_to(a, (a.shape[0], batch))
    if b.shape[-1] != batch:
        b = jnp.broadcast_to(b, (b.shape[0], batch))
    rows = 2 * NLIMB - 1
    cols = None
    for i in range(NLIMB):
        t = a[i : i + 1] * b  # (20, B), lands at rows [i, i+20)
        parts = []
        if i:
            parts.append(jnp.zeros((i, batch), jnp.int32))
        parts.append(t)
        if rows - NLIMB - i:
            parts.append(jnp.zeros((rows - NLIMB - i, batch), jnp.int32))
        term = jnp.concatenate(parts, axis=0) if len(parts) > 1 else parts[0]
        cols = term if cols is None else cols + term
    return _fold_cols(cols)


def _fold_cols(cols):
    lo_cols = cols[:NLIMB]
    hi_cols = cols[NLIMB:]  # 19 columns at weight 2^(260 + 13i)
    hi_lo = (hi_cols & MASK) * FOLD
    hi_hi = (hi_cols >> BITS) * FOLD
    batch = cols.shape[-1]
    zero = jnp.zeros((1, batch), jnp.int32)
    r = (
        lo_cols
        + jnp.concatenate([hi_lo, zero], axis=0)
        + jnp.concatenate([zero, hi_hi], axis=0)
    )
    return _carry(r, 3)


def _sq(a):
    """Squaring at ~half the multiplies of _mul (210 vs 400).

    cols[c] = 2 * sum_{i<j, i+j=c} a_i*a_j + (c even ? a_{c/2}^2 : 0).
    Overflow check under the lazy bound (limbs <= 10015, products
    <= 1.0030e8): worst cross column has 10 pairs -> doubled sum
    <= 2.006e9; worst mixed column 9 pairs + diagonal
    <= 2 * 9 * 1.0030e8 + 1.0030e8 = 1.906e9 — both < 2^31 - 1.
    """
    batch = a.shape[-1]
    rows = 2 * NLIMB - 1
    cross = None
    for i in range(NLIMB - 1):
        t = a[i : i + 1] * a[i + 1 :]  # a_i * a_j, j > i: (19-i, B)
        top = 2 * i + 1  # lands at rows [2i+1, i+20)
        bottom = rows - top - (NLIMB - 1 - i)
        parts = [jnp.zeros((top, batch), jnp.int32), t]
        if bottom:
            parts.append(jnp.zeros((bottom, batch), jnp.int32))
        term = jnp.concatenate(parts, axis=0)
        cross = term if cross is None else cross + term
    d = a * a  # diagonals: a_i^2 at row 2i
    zero1 = jnp.zeros((1, batch), jnp.int32)
    diag_parts = []
    for i in range(NLIMB):
        diag_parts.append(d[i : i + 1])
        if i != NLIMB - 1:
            diag_parts.append(zero1)
    diag = jnp.concatenate(diag_parts, axis=0)  # (39, B)
    return _fold_cols(cross + cross + diag)


def _canonical(x):
    """Unique representative in [0, p); mirrors field.canonical."""
    batch = x.shape[-1]
    for _ in range(2):
        limbs = []
        c = jnp.zeros((1, batch), jnp.int32)
        for i in range(NLIMB - 1):
            v = x[i : i + 1] + c
            limbs.append(v & MASK)
            c = v >> BITS
        v = x[NLIMB - 1 :] + c
        limbs.append(v & 0xFF)
        top = v >> 8
        limbs[0] = limbs[0] + top * 19
        x = jnp.concatenate(limbs, axis=0)
    borrow = jnp.zeros((1, batch), jnp.int32)
    diff = []
    for i in range(NLIMB):
        v = x[i : i + 1] - _P_LIMBS[i] + borrow
        diff.append(v & (MASK if i < NLIMB - 1 else 0xFF))
        borrow = v >> (BITS if i < NLIMB - 1 else 8)
    ge_p = borrow == 0
    y = jnp.concatenate(diff, axis=0)
    return jnp.where(ge_p, y, x)


def _is_zero(x):
    return jnp.all(_canonical(x) == 0, axis=-2, keepdims=True)


def _eq(a, b):
    return jnp.all(_canonical(a) == _canonical(b), axis=-2, keepdims=True)


def _sq_n(x, n):
    return jax.lax.fori_loop(0, n, lambda i, v: _sq(v), x)


def _pow_2_252_m3(z):
    """z ** (2^252 - 3): the curve25519 addition chain (field.pow_2_252_m3)."""
    z2 = _sq(z)
    z8 = _sq_n(z2, 2)
    z9 = _mul(z, z8)
    z11 = _mul(z2, z9)
    z22 = _sq(z11)
    z_5_0 = _mul(z9, z22)
    z_10_0 = _mul(_sq_n(z_5_0, 5), z_5_0)
    z_20_0 = _mul(_sq_n(z_10_0, 10), z_10_0)
    z_40_0 = _mul(_sq_n(z_20_0, 20), z_20_0)
    z_50_0 = _mul(_sq_n(z_40_0, 10), z_10_0)
    z_100_0 = _mul(_sq_n(z_50_0, 50), z_50_0)
    z_200_0 = _mul(_sq_n(z_100_0, 100), z_100_0)
    z_250_0 = _mul(_sq_n(z_200_0, 50), z_50_0)
    return _mul(_sq_n(z_250_0, 2), z)


# ---------------------------------------------------------------- point ops
# Points are 4-tuples (x, y, z, t) of (20, B) arrays — kept as Python
# tuples (not stacked) so Mosaic never sees >3-d values.


def _point_double(p):
    x1, y1, z1, _ = p
    a = _sq(x1)
    b = _sq(y1)
    c = _dbl2(_sq(z1))
    h = _add(a, b)
    e = _sub(h, _sq(_add(x1, y1)))
    g = _sub(a, b)
    f = _add(c, g)
    return (_mul(e, f), _mul(g, h), _mul(f, g), _mul(e, h))


def _niels_add(p, n):
    """p + Q, Q in projective-Niels (Y+X, Y-X, 2Z, 2dT): 8 muls."""
    x1, y1, z1, t1 = p
    u2, v2, w2, t2d = n
    a = _mul(_sub(y1, x1), v2)
    b = _mul(_add(y1, x1), u2)
    c = _mul(t1, t2d)
    d = _mul(z1, w2)
    e = _sub(b, a)
    f = _sub(d, c)
    g = _add(d, c)
    h = _add(b, a)
    return (_mul(e, f), _mul(g, h), _mul(f, g), _mul(e, h))


def _affine_niels_add(p, n3):
    """p + Q, Q affine-Niels (y+x, y-x, 2dxy): 7 muls."""
    x1, y1, z1, t1 = p
    u2, v2, t2d = n3
    a = _mul(_sub(y1, x1), v2)
    b = _mul(_add(y1, x1), u2)
    c = _mul(t1, t2d)
    d = _dbl2(z1)
    e = _sub(b, a)
    f = _sub(d, c)
    g = _add(d, c)
    h = _add(b, a)
    return (_mul(e, f), _mul(g, h), _mul(f, g), _mul(e, h))


def _decompress(y, sign):
    """(20, B) y-limbs + (1, B) sign -> ((x,y,z,t) point, (1, B) ok)."""
    batch = y.shape[-1]
    one = jnp.concatenate(
        [jnp.ones((1, batch), jnp.int32), jnp.zeros((NLIMB - 1, batch), jnp.int32)],
        axis=0,
    )
    yy = _sq(y)
    u = _sub(yy, one)
    v = _add(_mul(_TC.d(yy.shape[-1]), yy), one)
    v3 = _mul(_sq(v), v)
    v7 = _mul(_sq(v3), v)
    x = _mul(_mul(u, v3), _pow_2_252_m3(_mul(u, v7)))
    vxx = _mul(v, _sq(x))
    root_ok = _eq(vxx, u)
    flip_ok = _eq(vxx, _neg(u))
    x = jnp.where(flip_ok, _mul(x, _TC.sqrt_m1(x.shape[-1])), x)
    ok = root_ok | flip_ok
    xc = _canonical(x)
    parity = xc[0:1] & 1
    x = jnp.where(parity != sign, _neg(xc), xc)
    return (x, y, one, _mul(x, y)), ok


def _onehot(idx, batch):
    """(1, B) window value -> (16, B) one-hot int32."""
    iota = jax.lax.broadcasted_iota(jnp.int32, (TSIZE, batch), 0)
    return (iota == idx).astype(jnp.int32)


# ------------------------------------------------------------------ kernel


def _verify_block_kernel(
    y_a_ref, sign_a_ref, y_r_ref, sign_r_ref, s_ref, kneg_ref, out_ref
):
    _TC.reset()
    batch = y_a_ref.shape[-1]

    # Decompress A and R as one double-width batch: the sqrt addition
    # chain (~254 squarings) dominates decompression and vectorizes
    # across both points.
    y2 = jnp.concatenate([y_a_ref[:], y_r_ref[:]], axis=-1)
    s2 = jnp.concatenate([sign_a_ref[:], sign_r_ref[:]], axis=-1)
    pt2, ok2 = _decompress(y2, s2)
    a_pt = tuple(c[:, :batch] for c in pt2)
    r_pt = tuple(c[:, batch:] for c in pt2)
    ok = ok2[:, :batch] & ok2[:, batch:]

    # Per-lane table [O, A, .., 15A] in projective-Niels form, stored as
    # 4 coordinate stacks of shape (16*20, B) so selects stay 2-d.
    entries = [a_pt, _point_double(a_pt)]
    a_niels3 = (
        _add(a_pt[1], a_pt[0]),
        _sub(a_pt[1], a_pt[0]),
        _mul(a_pt[3], _TC.d2(batch)),
    )
    for _ in range(2, TSIZE - 1):
        entries.append(_affine_niels_add(entries[-1], a_niels3))
    ident_niels = (  # O in Niels form: (1, 1, 2, 0)
        jnp.concatenate(
            [jnp.ones((1, batch), jnp.int32), jnp.zeros((NLIMB - 1, batch), jnp.int32)],
            axis=0,
        ),
    )
    one_l = ident_niels[0]
    two_l = jnp.concatenate(
        [jnp.full((1, batch), 2, jnp.int32), jnp.zeros((NLIMB - 1, batch), jnp.int32)],
        axis=0,
    )
    zero_l = jnp.zeros((NLIMB, batch), jnp.int32)
    niels_entries = [(one_l, one_l, two_l, zero_l)]
    for e in entries:
        x, yv, z, t = e
        niels_entries.append(
            (_add(yv, x), _sub(yv, x), _dbl2(z), _mul(t, _TC.d2(batch)))
        )
    # (16*20, B) per coordinate.
    tab = [
        jnp.concatenate([niels_entries[k][c] for k in range(TSIZE)], axis=0)
        for c in range(4)
    ]

    def select_a(oh):
        """One-hot (16, B) -> projective-Niels 4-tuple of (20, B)."""
        out = []
        for c in range(4):
            acc = tab[c][0:NLIMB] * oh[0:1]
            for k in range(1, TSIZE):
                acc = acc + tab[c][k * NLIMB : (k + 1) * NLIMB] * oh[k : k + 1]
            out.append(acc)
        return tuple(out)

    def select_b(oh):
        """One-hot (16, B) -> affine-Niels 3-tuple from the constant table."""
        out = []
        for c in range(3):
            acc = _TC.base_entry(0, batch)[c] * oh[0:1]
            for k in range(1, TSIZE):
                acc = acc + _TC.base_entry(k, batch)[c] * oh[k : k + 1]
            out.append(acc)
        return tuple(out)

    ident = (zero_l, one_l, one_l, zero_l)

    def body(j, acc):
        for _ in range(WBITS):
            acc = _point_double(acc)
        kn = kneg_ref[pl.ds(j, 1), :]
        sn = s_ref[pl.ds(j, 1), :]
        acc = _niels_add(acc, select_a(_onehot(kn, batch)))
        acc = _affine_niels_add(acc, select_b(_onehot(sn, batch)))
        return acc

    acc = jax.lax.fori_loop(0, WINDOWS, body, ident)

    # Subtract R (affine, Z == 1): add (-x, y, -t) in affine-Niels form.
    rx, ry, _, rt = r_pt
    nrx = _neg(rx)
    r_niels = (_add(ry, nrx), _sub(ry, nrx), _mul(_neg(rt), _TC.d2(batch)))
    acc = _affine_niels_add(acc, r_niels)
    for _ in range(3):
        acc = _point_double(acc)

    is_id = _is_zero(acc[0]) & _eq(acc[1], acc[2])
    out_ref[:] = (is_id & ok).astype(jnp.int32)


def _verify_block_kernel_cached(
    tab0_ref, tab1_ref, tab2_ref, tab3_ref, ok_a_ref,
    y_r_ref, sign_r_ref, s_ref, kneg_ref, out_ref,
):
    """Ladder with a PRE-GATHERED pubkey table (expanded-pubkey cache).

    ``tabN_ref``: (16*20, B) Niels coordinate stacks gathered from the
    HBM arena by the surrounding jit (ops/verify.PubkeyTableCache);
    ``ok_a_ref``: (1, B) cached decompress-ok bits. Only R decompresses
    here — the sqrt chain and per-launch table build of
    :func:`_verify_block_kernel` are gone (~11% fewer muls, and the
    decompression batch is half as wide).
    """
    _TC.reset()
    batch = y_r_ref.shape[-1]

    r_pt, ok = _decompress(y_r_ref[:], sign_r_ref[:])
    ok = ok & (ok_a_ref[:] != 0)

    tab = [tab0_ref[:], tab1_ref[:], tab2_ref[:], tab3_ref[:]]

    def select_a(oh):
        out = []
        for c in range(4):
            acc = tab[c][0:NLIMB] * oh[0:1]
            for k in range(1, TSIZE):
                acc = acc + tab[c][k * NLIMB : (k + 1) * NLIMB] * oh[k : k + 1]
            out.append(acc)
        return tuple(out)

    def select_b(oh):
        out = []
        for c in range(3):
            acc = _TC.base_entry(0, batch)[c] * oh[0:1]
            for k in range(1, TSIZE):
                acc = acc + _TC.base_entry(k, batch)[c] * oh[k : k + 1]
            out.append(acc)
        return tuple(out)

    one_l = jnp.concatenate(
        [jnp.ones((1, batch), jnp.int32),
         jnp.zeros((NLIMB - 1, batch), jnp.int32)],
        axis=0,
    )
    zero_l = jnp.zeros((NLIMB, batch), jnp.int32)
    ident = (zero_l, one_l, one_l, zero_l)

    def body(j, acc):
        for _ in range(WBITS):
            acc = _point_double(acc)
        kn = kneg_ref[pl.ds(j, 1), :]
        sn = s_ref[pl.ds(j, 1), :]
        acc = _niels_add(acc, select_a(_onehot(kn, batch)))
        acc = _affine_niels_add(acc, select_b(_onehot(sn, batch)))
        return acc

    acc = jax.lax.fori_loop(0, WINDOWS, body, ident)

    rx, ry, _, rt = r_pt
    nrx = _neg(rx)
    r_niels = (_add(ry, nrx), _sub(ry, nrx), _mul(_neg(rt), _TC.d2(batch)))
    acc = _affine_niels_add(acc, r_niels)
    for _ in range(3):
        acc = _point_double(acc)

    is_id = _is_zero(acc[0]) & _eq(acc[1], acc[2])
    out_ref[:] = (is_id & ok).astype(jnp.int32)


def _point_add_full(p, q, batch):
    """Complete extended + extended addition (9 muls) — used once per
    block to join the A-ladder result with the fixed-base B sum."""
    x1, y1, z1, t1 = p
    x2, y2, z2, t2 = q
    a = _mul(_sub(y1, x1), _sub(y2, x2))
    b = _mul(_add(y1, x1), _add(y2, x2))
    c = _mul(_mul(t1, _TC.d2(batch)), t2)
    d = _dbl2(_mul(z1, z2))
    e = _sub(b, a)
    f = _sub(d, c)
    g = _add(d, c)
    h = _add(b, a)
    return (_mul(e, f), _mul(g, h), _mul(f, g), _mul(e, h))


def _fixed_base_sum8_pl(tab8_ref, s_ref, batch):
    """[S]B from 8-bit windows: 32 MXU one-hot dots + 32 affine adds.

    ``tab8_ref``: (32*64, 256) f32 — per-window constant affine-Niels
    tables T_j[v] = [v*2^(8j)]B, coordinate rows j*64 + c*20 + limb
    (rows 60-63 of each window zero-padded: Mosaic requires the dynamic
    window offset to be provably 8-aligned, and 60 is not), entry axis
    on lanes so each window's select is one (64, 256) @ (256, B) matmul
    (exact in f32: limbs < 2^13, one-hot has a single nonzero per
    column). ``s_ref``: (32, B) S bytes, little-endian — byte j IS the
    window of weight 2^(8j).

    vs the joint ladder's per-window select_b: the 64 affine B-adds
    drop to 32 and the select work leaves the VPU entirely
    (curve.fixed_base_sum8 is the XLA twin; docs/tpu-kernel.md).
    """
    one_l = jnp.concatenate(
        [jnp.ones((1, batch), jnp.int32),
         jnp.zeros((NLIMB - 1, batch), jnp.int32)],
        axis=0,
    )
    zero_l = jnp.zeros((NLIMB, batch), jnp.int32)
    ident = (zero_l, one_l, one_l, zero_l)
    iota = jax.lax.broadcasted_iota(jnp.int32, (256, batch), 0)

    def body(j, acc):
        sj = s_ref[pl.ds(j, 1), :]  # (1, B)
        oh = (iota == sj).astype(jnp.float32)  # (256, B)
        tj = tab8_ref[pl.ds(j * 64, 64), :]  # (64, 256), 8-aligned start
        sel = jax.lax.dot_general(
            tj,
            oh,
            (((1,), (0,)), ((), ())),
            precision=jax.lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32,
        ).astype(jnp.int32)  # (64, B); rows 60+ are the zero padding
        n3 = (
            sel[0:NLIMB],
            sel[NLIMB : 2 * NLIMB],
            sel[2 * NLIMB : 3 * NLIMB],
        )
        return _affine_niels_add(acc, n3)

    return jax.lax.fori_loop(0, 32, body, ident)


def _verify_block_kernel8(
    tab8_ref, y_a_ref, sign_a_ref, y_r_ref, sign_r_ref, s_ref, kneg_ref,
    out_ref,
):
    """verify kernel with the [S]B part on 8-bit fixed-base windows."""
    _TC.reset()
    batch = y_a_ref.shape[-1]

    y2 = jnp.concatenate([y_a_ref[:], y_r_ref[:]], axis=-1)
    s2 = jnp.concatenate([sign_a_ref[:], sign_r_ref[:]], axis=-1)
    pt2, ok2 = _decompress(y2, s2)
    a_pt = tuple(c[:, :batch] for c in pt2)
    r_pt = tuple(c[:, batch:] for c in pt2)
    ok = ok2[:, :batch] & ok2[:, batch:]

    entries = [a_pt, _point_double(a_pt)]
    a_niels3 = (
        _add(a_pt[1], a_pt[0]),
        _sub(a_pt[1], a_pt[0]),
        _mul(a_pt[3], _TC.d2(batch)),
    )
    for _ in range(2, TSIZE - 1):
        entries.append(_affine_niels_add(entries[-1], a_niels3))
    one_l = jnp.concatenate(
        [jnp.ones((1, batch), jnp.int32),
         jnp.zeros((NLIMB - 1, batch), jnp.int32)],
        axis=0,
    )
    two_l = jnp.concatenate(
        [jnp.full((1, batch), 2, jnp.int32),
         jnp.zeros((NLIMB - 1, batch), jnp.int32)],
        axis=0,
    )
    zero_l = jnp.zeros((NLIMB, batch), jnp.int32)
    niels_entries = [(one_l, one_l, two_l, zero_l)]
    for e in entries:
        x, yv, z, t = e
        niels_entries.append(
            (_add(yv, x), _sub(yv, x), _dbl2(z), _mul(t, _TC.d2(batch)))
        )
    tab = [
        jnp.concatenate([niels_entries[k][c] for k in range(TSIZE)], axis=0)
        for c in range(4)
    ]

    def select_a(oh):
        out = []
        for c in range(4):
            acc = tab[c][0:NLIMB] * oh[0:1]
            for k in range(1, TSIZE):
                acc = acc + tab[c][k * NLIMB : (k + 1) * NLIMB] * oh[k : k + 1]
            out.append(acc)
        return tuple(out)

    ident = (zero_l, one_l, one_l, zero_l)

    def body(j, acc):
        for _ in range(WBITS):
            acc = _point_double(acc)
        kn = kneg_ref[pl.ds(j, 1), :]
        return _niels_add(acc, select_a(_onehot(kn, batch)))

    acc = jax.lax.fori_loop(0, WINDOWS, body, ident)
    acc = _point_add_full(
        acc, _fixed_base_sum8_pl(tab8_ref, s_ref, batch), batch
    )

    rx, ry, _, rt = r_pt
    nrx = _neg(rx)
    r_niels = (_add(ry, nrx), _sub(ry, nrx), _mul(_neg(rt), _TC.d2(batch)))
    acc = _affine_niels_add(acc, r_niels)
    for _ in range(3):
        acc = _point_double(acc)

    is_id = _is_zero(acc[0]) & _eq(acc[1], acc[2])
    out_ref[:] = (is_id & ok).astype(jnp.int32)


def _verify_block_kernel8_cached(
    tab8_ref, tab0_ref, tab1_ref, tab2_ref, tab3_ref, ok_a_ref,
    y_r_ref, sign_r_ref, s_ref, kneg_ref, out_ref,
):
    _TC.reset()
    batch = y_r_ref.shape[-1]

    r_pt, ok = _decompress(y_r_ref[:], sign_r_ref[:])
    ok = ok & (ok_a_ref[:] != 0)

    tab = [tab0_ref[:], tab1_ref[:], tab2_ref[:], tab3_ref[:]]

    def select_a(oh):
        out = []
        for c in range(4):
            acc = tab[c][0:NLIMB] * oh[0:1]
            for k in range(1, TSIZE):
                acc = acc + tab[c][k * NLIMB : (k + 1) * NLIMB] * oh[k : k + 1]
            out.append(acc)
        return tuple(out)

    one_l = jnp.concatenate(
        [jnp.ones((1, batch), jnp.int32),
         jnp.zeros((NLIMB - 1, batch), jnp.int32)],
        axis=0,
    )
    zero_l = jnp.zeros((NLIMB, batch), jnp.int32)
    ident = (zero_l, one_l, one_l, zero_l)

    def body(j, acc):
        for _ in range(WBITS):
            acc = _point_double(acc)
        kn = kneg_ref[pl.ds(j, 1), :]
        return _niels_add(acc, select_a(_onehot(kn, batch)))

    acc = jax.lax.fori_loop(0, WINDOWS, body, ident)
    acc = _point_add_full(
        acc, _fixed_base_sum8_pl(tab8_ref, s_ref, batch), batch
    )

    rx, ry, _, rt = r_pt
    nrx = _neg(rx)
    r_niels = (_add(ry, nrx), _sub(ry, nrx), _mul(_neg(rt), _TC.d2(batch)))
    acc = _affine_niels_add(acc, r_niels)
    for _ in range(3):
        acc = _point_double(acc)

    is_id = _is_zero(acc[0]) & _eq(acc[1], acc[2])
    out_ref[:] = (is_id & ok).astype(jnp.int32)


_TAB8_PL_CACHE: list = []


def _tab8_pl() -> np.ndarray:
    """(32*64, 256) f32 layout of curve's per-window base tables.

    Each window's 60 coordinate rows are padded to a 64-row block so
    the kernel's dynamic window offset (j*64) is provably 8-aligned
    (Mosaic rejects j*60)."""
    if not _TAB8_PL_CACHE:
        t8 = curve._base_table8_host()  # (32, 256, 3, 20)
        rows = t8.transpose(0, 2, 3, 1).reshape(32, 60, 256)
        padded = np.zeros((32, 64, 256), np.float32)
        padded[:, :60] = rows
        _TAB8_PL_CACHE.append(
            np.ascontiguousarray(padded.reshape(32 * 64, 256))
        )
    return _TAB8_PL_CACHE[0]


@lru_cache(maxsize=None)
def _compiled8(n: int, block: int, interpret: bool):
    grid = n // block
    spec2 = lambda rows: pl.BlockSpec(  # noqa: E731
        (rows, block), lambda i: (0, i), memory_space=pltpu.VMEM
    )
    tab_spec = pl.BlockSpec(
        (32 * 64, 256), lambda i: (0, 0), memory_space=pltpu.VMEM
    )
    call = pl.pallas_call(
        _verify_block_kernel8,
        grid=(grid,),
        in_specs=[
            tab_spec,
            spec2(NLIMB),
            spec2(1),
            spec2(NLIMB),
            spec2(1),
            spec2(32),
            spec2(WINDOWS),
        ],
        out_specs=spec2(1),
        out_shape=jax.ShapeDtypeStruct((1, n), jnp.int32),
        interpret=interpret,
    )

    def fn(y_a, sign_a, y_r, sign_r, s_bytes, kneg_nibs):
        return call(
            jnp.asarray(_tab8_pl()),
            y_a,
            sign_a.reshape(1, n),
            y_r,
            sign_r.reshape(1, n),
            s_bytes,
            kneg_nibs,
        )[0].astype(bool)

    return fn


def verify_kernel8(y_a, sign_a, y_r, sign_r, s_bytes, kneg_nibs, *,
                   interpret=None):
    """8-bit fixed-base-window Pallas lowering
    (COMETBFT_TPU_KERNEL=pallas8); same contract as
    curve.verify_kernel8."""
    if interpret is None:
        interpret = jax.default_backend() not in ACCELERATOR_BACKENDS
    n = y_a.shape[-1]
    block = _block_for(n)
    if n % block:
        raise ValueError(f"batch {n} not a multiple of block {block}")
    return _compiled8(n, block, interpret)(
        y_a, sign_a, y_r, sign_r, s_bytes, kneg_nibs
    )


@lru_cache(maxsize=None)
def _compiled8_cached(n: int, block: int, interpret: bool):
    grid = n // block
    spec2 = lambda rows: pl.BlockSpec(  # noqa: E731
        (rows, block), lambda i: (0, i), memory_space=pltpu.VMEM
    )
    tab_spec = pl.BlockSpec(
        (32 * 64, 256), lambda i: (0, 0), memory_space=pltpu.VMEM
    )
    call = pl.pallas_call(
        _verify_block_kernel8_cached,
        grid=(grid,),
        in_specs=[
            tab_spec,
            spec2(TSIZE * NLIMB),
            spec2(TSIZE * NLIMB),
            spec2(TSIZE * NLIMB),
            spec2(TSIZE * NLIMB),
            spec2(1),
            spec2(NLIMB),
            spec2(1),
            spec2(32),
            spec2(WINDOWS),
        ],
        out_specs=spec2(1),
        out_shape=jax.ShapeDtypeStruct((1, n), jnp.int32),
        interpret=interpret,
    )

    def fn(table, ok_a, y_r, sign_r, s_bytes, kneg_nibs):
        planes = [
            table[:, c].reshape(TSIZE * NLIMB, n) for c in range(4)
        ]
        return call(
            jnp.asarray(_tab8_pl()),
            *planes,
            ok_a.astype(jnp.int32).reshape(1, n),
            y_r,
            sign_r.reshape(1, n),
            s_bytes,
            kneg_nibs,
        )[0].astype(bool)

    return fn


def verify_kernel8_cached(table, ok_a, y_r, sign_r, s_bytes, kneg_nibs, *,
                          interpret=None):
    """Cached-table 8-bit-window Pallas lowering."""
    if interpret is None:
        interpret = jax.default_backend() not in ACCELERATOR_BACKENDS
    n = y_r.shape[-1]
    block = _block_for(n)
    if n % block:
        raise ValueError(f"batch {n} not a multiple of block {block}")
    return _compiled8_cached(n, block, interpret)(
        table, ok_a, y_r, sign_r, s_bytes, kneg_nibs
    )


@lru_cache(maxsize=None)
def _compiled_cached(n: int, block: int, interpret: bool):
    grid = n // block
    spec2 = lambda rows: pl.BlockSpec(  # noqa: E731
        (rows, block), lambda i: (0, i), memory_space=pltpu.VMEM
    )
    call = pl.pallas_call(
        _verify_block_kernel_cached,
        grid=(grid,),
        in_specs=[
            spec2(TSIZE * NLIMB),
            spec2(TSIZE * NLIMB),
            spec2(TSIZE * NLIMB),
            spec2(TSIZE * NLIMB),
            spec2(1),
            spec2(NLIMB),
            spec2(1),
            spec2(WINDOWS),
            spec2(WINDOWS),
        ],
        out_specs=spec2(1),
        out_shape=jax.ShapeDtypeStruct((1, n), jnp.int32),
        interpret=interpret,
    )

    def fn(table, ok_a, y_r, sign_r, s_nibs, kneg_nibs):
        # table: (16, 4, 20, n) gathered from the arena by the caller's
        # jit -> 4 coordinate stacks (16*20, n) for 2-d VMEM blocks.
        planes = [
            table[:, c].reshape(TSIZE * NLIMB, n) for c in range(4)
        ]
        return call(
            *planes,
            ok_a.astype(jnp.int32).reshape(1, n),
            y_r,
            sign_r.reshape(1, n),
            s_nibs,
            kneg_nibs,
        )[0].astype(bool)

    return fn


def verify_kernel_cached(table, ok_a, y_r, sign_r, s_nibs, kneg_nibs, *,
                         interpret=None):
    """Cached-table drop-in for ops.curve.verify_kernel_cached (+ ok AND)."""
    if interpret is None:
        interpret = jax.default_backend() not in ACCELERATOR_BACKENDS
    n = y_r.shape[-1]
    block = _block_for(n)
    if n % block:
        raise ValueError(f"batch {n} not a multiple of block {block}")
    return _compiled_cached(n, block, interpret)(
        table, ok_a, y_r, sign_r, s_nibs, kneg_nibs
    )


_BLOCK = 512


def _block_for(n: int) -> int:
    return min(n, _BLOCK)


@lru_cache(maxsize=None)
def _compiled(n: int, block: int, interpret: bool):
    grid = n // block
    spec2 = lambda rows: pl.BlockSpec(  # noqa: E731
        (rows, block), lambda i: (0, i), memory_space=pltpu.VMEM
    )
    call = pl.pallas_call(
        _verify_block_kernel,
        grid=(grid,),
        in_specs=[
            spec2(NLIMB),
            spec2(1),
            spec2(NLIMB),
            spec2(1),
            spec2(WINDOWS),
            spec2(WINDOWS),
        ],
        out_specs=spec2(1),
        out_shape=jax.ShapeDtypeStruct((1, n), jnp.int32),
        interpret=interpret,
    )

    def fn(y_a, sign_a, y_r, sign_r, s_nibs, kneg_nibs):
        return call(
            y_a,
            sign_a.reshape(1, n),
            y_r,
            sign_r.reshape(1, n),
            s_nibs,
            kneg_nibs,
        )[0].astype(bool)

    return fn


def verify_kernel(y_a, sign_a, y_r, sign_r, s_nibs, kneg_nibs, *, interpret=None):
    """Drop-in for ops.curve.verify_kernel with the same array contract.

    ``interpret`` defaults to True off-TPU (Pallas Mosaic only targets
    TPU; interpret mode keeps CPU tests and the virtual-device mesh path
    working) and False on TPU.
    """
    if interpret is None:
        interpret = jax.default_backend() not in ACCELERATOR_BACKENDS
    n = y_a.shape[-1]
    block = _block_for(n)
    if n % block:
        raise ValueError(f"batch {n} not a multiple of block {block}")
    return _compiled(n, block, interpret)(
        y_a, sign_a, y_r, sign_r, s_nibs, kneg_nibs
    )
