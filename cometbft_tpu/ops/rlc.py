"""Device-side RLC batch verification: ONE multiscalar multiplication.

This is the voi batch equation — the algorithm behind the reference's
batch verifier (behavioral surface: crypto/ed25519/ed25519.go:200-228,
types/validation.go:243-250) — run ON the device, replacing 4096
independent double-scalar ladders with one shared-window Pippenger-style
multiscalar multiplication across the batch axis:

    [8]( [sum z_i s_i]B  -  sum [z_i k_i]A_i  -  sum [z_i]R_i ) == O

with per-lane 128-bit random z_i drawn on host. Why this wins: the
per-lane ladder (ops/curve.verify_kernel) costs ~3.4k field muls per
signature no matter the batch size; the MSM's bucket accumulation
amortizes across lanes, so per-signature work FALLS as the batch grows
(~1.5k muls/sig at 4096 distinct keys, ~640 when lanes share a validator
set — see :func:`op_ledger` for the exact static count).

TPU-first design (none of this resembles the reference's serial Go):

* Scatter-free bucket accumulation. Classic Pippenger scatters each
  point into bucket[digit] — a data-dependent scatter with a
  non-commutative-hardware "add" (point addition), inexpressible as a
  TPU primitive. Instead: HOST argsorts each window's digits (numpy,
  microseconds), the device gathers points into sorted order, takes ONE
  batched inclusive prefix-scan of points along the lane axis
  (``jax.lax.associative_scan`` — point addition is associative, the
  lazy-limb invariant of ops/field makes any association order exact),
  and reads each bucket sum as a difference of two prefix gathers at
  host-precomputed segment boundaries. All windows process in parallel
  (windows x lanes is the batch shape); the scan's ~2N point adds per
  window are the dominant cost and vectorize perfectly.
* Signed digits halve the buckets. Digits are recoded to
  [-2^(c-1), 2^(c-1)]; negative digits negate the point at gather time
  (an X/T sign flip — free), so only 2^(c-1) buckets need aggregating.
* Bucket aggregation without the serial running-sum. The textbook
  sum_v v*B_v loop is 2*2^c SEQUENTIAL adds; here it is a reverse
  associative_scan over the bucket axis (suffix sums S_v = sum_{u>=v}
  B_u) plus a log-depth tree reduce of the S_v — batched across all
  windows at once.
* Per-lane scalars never touch the device. The host ships permutations,
  segment boundaries, and sign masks (int32); the device ships back one
  bool. The 128-bit z_i stay host-side, exactly like the reference keeps
  its entropy in the verifier process.
* Distinct-key folding. sum [z_i k_i]A_i groups by pubkey on host
  (consensus lanes share the validator set): one MSM point per DISTINCT
  key with coefficient sum(z_i k_i) mod L — a 150-validator commit has a
  150-point A-side MSM regardless of lane count. Folding is sound
  because scalar arithmetic happens mod L and the final [8] kills the
  torsion components mod-L reduction can expose (ZIP-215 points may
  have order 8L).

Failure contract (reference parity, types/validation.go:243-250): the
RLC check is all-or-nothing; on False the caller re-attributes with the
exact per-lane kernel. A lane whose A or R fails ZIP-215 decoding is
masked to the identity inside the sums AND fails the launch's all-decoded
bit, forcing the attribution pass — same observable behavior as the
reference's batch-then-singles fallback.
"""

from __future__ import annotations

import secrets
from functools import lru_cache, partial

import numpy as np

import jax
import jax.numpy as jnp

from . import curve

L = curve.L

# Lane counts are bucketed to powers of two (compile-once shapes); the
# window width c is then a pure function of the bucket, so each (bucket,
# scalar-width) pair compiles exactly one XLA program.
_MIN_BUCKET = 8


def _bucket(n: int) -> int:
    b = _MIN_BUCKET
    while b < n:
        b *= 2
    return b


def window_bits(n_points: int, nbits: int) -> int:
    """Pick the Pippenger window width minimizing the static add count.

    Cost model per window: 2N (prefix scan) + 5 * 2^(c-1) (bucket
    extraction + suffix scan + tree reduce); windows = ceil(nbits/c) + 1
    (signed-recode carry window). Exact argmin over c in [4, 12] — the
    same balance voi strikes dynamically, solved statically per bucket.
    """
    best_c, best_cost = 4, None
    for c in range(4, 13):
        w = -(-nbits // c) + 1
        cost = w * (2 * n_points + 5 * (1 << (c - 1)))
        if best_cost is None or cost < best_cost:
            best_c, best_cost = c, cost
    return best_c


def signed_digits(scalars: np.ndarray, c: int, nbits: int) -> np.ndarray:
    """(N, 32) LE scalar bytes -> (W, N) signed base-2^c digits.

    Digits lie in [-2^(c-1), 2^(c-1)]; scalar == sum d_j * 2^(c*j).
    Vectorized: bit unpack + window reduce, then one carry sweep across
    the W windows (W ~ 13..65 numpy passes over the lane axis).
    """
    n = scalars.shape[0]
    w = -(-nbits // c) + 1
    bits = np.unpackbits(scalars, axis=1, bitorder="little")
    padded = np.zeros((n, w * c), np.int32)
    width = min(bits.shape[1], w * c)
    padded[:, :width] = bits[:, :width]
    weights = 1 << np.arange(c, dtype=np.int32)
    digits = (padded.reshape(n, w, c) * weights).sum(axis=2, dtype=np.int32)
    half = 1 << (c - 1)
    carry = np.zeros(n, np.int32)
    out = np.empty((w, n), np.int32)
    for j in range(w):
        t = digits[:, j] + carry
        hi = t >= half
        out[j] = np.where(hi, t - (1 << c), t)
        carry = hi.astype(np.int32)
    assert not carry.any(), "signed recode overflow: widen W"
    return out


def plan_msm(scalars: np.ndarray, c: int, nbits: int):
    """Host-side MSM plan: everything data-dependent, none of it device.

    Returns dict of int32 arrays: perm (W, N) sorted-order lane indices,
    sign (W, N) 0/1 negate-the-point mask in SORTED order, starts/ends
    (W, m) prefix-scan segment boundaries per bucket value 1..m.
    """
    digits = signed_digits(scalars, c, nbits)
    w, n = digits.shape
    m = 1 << (c - 1)
    absd = np.abs(digits)
    perm = np.argsort(absd, axis=1).astype(np.int32)
    sorted_abs = np.take_along_axis(absd, perm, axis=1)
    sign = np.take_along_axis((digits < 0).astype(np.int32), perm, axis=1)
    vals = np.arange(1, m + 1, dtype=np.int32)
    starts = np.empty((w, m), np.int32)
    ends = np.empty((w, m), np.int32)
    for j in range(w):
        starts[j] = np.searchsorted(sorted_abs[j], vals, side="left")
        ends[j] = np.searchsorted(sorted_abs[j], vals, side="right")
    return {"perm": perm, "sign": sign, "starts": starts, "ends": ends}


# ------------------------------------------------------------- device


def _msm_window_sums(points, perm, sign, starts, ends):
    """Per-window bucket-weighted sums: (4, 20, N) points -> (4, 20, W).

    points: extended coordinates, batch-minor. perm/sign (W, N),
    starts/ends (W, m). See module docstring for the scan construction.
    """
    gathered = jnp.take(points, perm, axis=2)  # (4, 20, W, N)
    negated = curve.point_neg(gathered)
    pts = jnp.where(sign[None, None] == 1, negated, gathered)
    prefix = jax.lax.associative_scan(curve.point_add, pts, axis=3)
    ident = curve.broadcast_point(
        curve.const_point(curve.IDENTITY_INT), perm.shape
    )[:, :, :, :1]
    prefix0 = jnp.concatenate([ident, prefix], axis=3)  # (4,20,W,N+1)
    s_end = jnp.take_along_axis(prefix0, ends[None, None], axis=3)
    s_start = jnp.take_along_axis(prefix0, starts[None, None], axis=3)
    buckets = curve.point_add(s_end, curve.point_neg(s_start))  # (4,20,W,m)
    # sum_v v * B_v == sum_v (sum_{u >= v} B_u): suffix scan + tree sum.
    suffix = jax.lax.associative_scan(
        curve.point_add, buckets, axis=3, reverse=True
    )
    m = suffix.shape[3]
    while m > 1:
        m //= 2
        suffix = curve.point_add(suffix[:, :, :, :m], suffix[:, :, :, m:])
    return suffix[:, :, :, 0]  # (4, 20, W)


def _horner(wsums, c: int):
    """Combine window sums msb-first: acc = [2^c]acc + W_j. (4,20,W)->(4,20)."""
    w = wsums.shape[2]

    def body(i, acc):
        acc = curve.point_double_n(acc, c)
        return curve.point_add(
            acc, jax.lax.dynamic_index_in_dim(wsums, w - 2 - i, 2, False)
        )

    return jax.lax.fori_loop(0, w - 1, body, wsums[:, :, w - 1])


def _masked_decompress(y, sign):
    """Decompress with undecodable lanes masked to the identity.

    Masked lanes contribute nothing to the MSM sums; the returned
    all-ok bit still fails the launch so the caller attributes per-lane
    (an undecodable point IS an invalid signature)."""
    pts, ok = curve.decompress(y, sign)
    ident = curve.broadcast_point(curve.const_point(curve.IDENTITY_INT),
                                  y.shape[1:])
    return jnp.where(ok[None, None], pts, ident), ok


def _rlc_kernel(y_a, sign_a, plan_a, y_r, sign_r, plan_r, b_bytes,
                *, c_a: int, c_r: int):
    """The full batch equation on device; returns ONE bool.

    True == every decodable lane satisfies the linear combination AND
    every lane decoded. b_bytes: (32, 1) LE bytes of sum(z_i s_i) mod L.
    """
    a_pts, ok_a = _masked_decompress(y_a, sign_a)
    r_pts, ok_r = _masked_decompress(y_r, sign_r)
    sum_a = _horner(_msm_window_sums(a_pts, *plan_a), c_a)
    sum_r = _horner(_msm_window_sums(r_pts, *plan_r), c_r)
    sb = curve.fixed_base_sum8(b_bytes)[:, :, 0]
    total = curve.point_add(curve.point_add(sb, sum_a), sum_r)
    for _ in range(3):  # cofactor: [8] kills torsion exactly (ZIP-215)
        total = curve.point_double(total)
    return curve.is_identity(total) & jnp.all(ok_a) & jnp.all(ok_r)


@lru_cache(maxsize=None)
def _jitted(c_a: int, c_r: int):
    from . import verify as _v

    _v._enable_compilation_cache()
    return jax.jit(partial(_rlc_kernel, c_a=c_a, c_r=c_r))


# --------------------------------------------------------------- host


def _enc_arrays(encs: list[bytes], n_pad: int):
    """32-byte point encodings -> (y_limbs (20, n_pad), sign (n_pad,)).

    Pad lanes hold the identity encoding: they decode OK (so they never
    fail the launch) and carry all-zero digits (bucket 0, never summed).
    """
    from . import verify as _v

    rows = np.zeros((n_pad, 32), np.uint8)
    rows[:, 0] = 1  # identity encoding for every pad lane
    for i, e in enumerate(encs):
        rows[i] = np.frombuffer(e, np.uint8)
    bits = _v._le_bits(rows)
    return _v._y_limbs(bits), bits[:, 255].astype(np.int32)


def _scalar_rows(scalars: list[int], n_pad: int) -> np.ndarray:
    out = np.zeros((n_pad, 32), np.uint8)
    for i, s in enumerate(scalars):
        out[i] = np.frombuffer(s.to_bytes(32, "little"), np.uint8)
    return out


def check_equation(a_encs, a_scalars, r_encs, r_scalars, b_scalar) -> bool:
    """Run [8]([b]B + sum [a_s]A + sum [r_s]R) == O on device.

    All scalars are taken mod L by the caller; encodings are 32-byte
    compressed points (callers pre-negate R by flipping the sign bit —
    exact under ZIP-215 including the x == 0 fixed point).
    """
    na, nr = _bucket(max(1, len(a_encs))), _bucket(max(1, len(r_encs)))
    c_a = window_bits(na, 253)
    c_r = window_bits(nr, 128)
    y_a, sign_a = _enc_arrays(a_encs, na)
    y_r, sign_r = _enc_arrays(r_encs, nr)
    plan_a = plan_msm(_scalar_rows(a_scalars, na), c_a, 253)
    plan_r = plan_msm(_scalar_rows(r_scalars, nr), c_r, 128)
    b_bytes = np.frombuffer(
        b_scalar.to_bytes(32, "little"), np.uint8
    ).astype(np.int32)[:, None]
    out = _jitted(c_a, c_r)(
        y_a, sign_a,
        (plan_a["perm"], plan_a["sign"], plan_a["starts"], plan_a["ends"]),
        y_r, sign_r,
        (plan_r["perm"], plan_r["sign"], plan_r["starts"], plan_r["ends"]),
        b_bytes,
    )
    return bool(out)


def verify_batch_rlc(pubkeys, msgs, sigs):
    """Batch-verify via the device RLC equation; per-lane fallback on fail.

    Same (all_valid, bitmap) contract as ops.verify.verify_batch. The
    happy path costs one kernel launch; any invalid/undecodable lane
    fails the single equation and the exact per-lane kernel attributes
    (reference discipline: types/validation.go:243-250). The fallback
    re-packs the batch — paying the challenge hashing twice is confined
    to the attack/corruption path, like the reference's re-verify pass.
    """
    from . import verify as _v

    n = len(pubkeys)
    if n == 0:
        return True, np.zeros(0, bool)
    buf, host_ok = _v.pack_bytes(pubkeys, msgs, sigs)
    well = np.nonzero(host_ok)[0]
    if len(well) == 0:
        return False, host_ok
    # Per-lane 128-bit randomness: fresh each call, never revealed, so a
    # forged lane passes with p ~ 2^-128 (crypto/host_batch.py soundness
    # note; same draw discipline).
    zs = np.frombuffer(secrets.token_bytes(16 * len(well)), np.uint8)
    zints = [
        max(1, int.from_bytes(zs[16 * j: 16 * j + 16].tobytes(), "little"))
        for j in range(len(well))
    ]
    a_fold: dict[bytes, int] = {}
    r_encs, r_scalars = [], []
    b_acc = 0
    for j, i in enumerate(well):
        z = zints[j]
        a = buf[0:32, i].tobytes()
        r = buf[32:64, i].tobytes()
        s = int.from_bytes(buf[64:96, i].tobytes(), "little")
        kneg = int.from_bytes(buf[96:128, i].tobytes(), "little")
        # -sum [z k]A == +sum [z kneg]A; -R folds into the encoding.
        a_fold[a] = (a_fold.get(a, 0) + z * kneg) % L
        r_encs.append(r[:31] + bytes([r[31] ^ 0x80]))
        r_scalars.append(z)
        b_acc = (b_acc + z * s) % L
    ok = check_equation(
        list(a_fold.keys()), list(a_fold.values()), r_encs, r_scalars, b_acc
    )
    if ok:
        return bool(host_ok.all()), host_ok
    return _v.verify_batch(pubkeys, msgs, sigs)


# -------------------------------------------------------------- ledger


def op_ledger(n_lanes: int, n_keys: int | None = None) -> dict:
    """Static field-mul count for one RLC launch (no measurement).

    The analytic ledger the round-4 verdict prescribed: every add is 9
    muls (complete extended add), every doubling 7-8, decompression 265
    (the 2^252-3 chain). ``n_keys`` defaults to all-distinct.
    """
    n_keys = n_lanes if n_keys is None else n_keys
    na, nr = _bucket(max(1, n_keys)), _bucket(max(1, n_lanes))
    total_adds = 0.0
    total_dbls = 0.0
    for n_pts, nbits in ((na, 253), (nr, 128)):
        c = window_bits(n_pts, nbits)
        w = -(-nbits // c) + 1
        m = 1 << (c - 1)
        total_adds += w * (2 * n_pts + 1 + 5 * m)  # scan+extract+aggregate
        total_adds += w - 1  # horner adds
        total_dbls += (w - 1) * c  # horner doublings
    total_adds += 32 + 2 + 1  # fixed-base [b]B + final combine
    total_dbls += 3  # cofactor
    decompress = 265 * (na + nr)
    muls = total_adds * 9 + total_dbls * 8 + decompress
    return {
        "adds": int(total_adds),
        "doublings": int(total_dbls),
        "decompress_muls": int(decompress),
        "field_muls_total": int(muls),
        "field_muls_per_sig": round(muls / max(1, n_lanes), 1),
        "msm_muls_per_sig": round(total_adds * 9 / max(1, n_lanes), 1),
    }
