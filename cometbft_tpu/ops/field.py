"""Batched GF(2^255 - 19) field arithmetic for TPU (JAX, int32 limbs).

Design (TPU-first, not a port):

* A field element is ``(..., 20)`` int32 limbs, 13 bits each, little-endian
  (value = sum(limb[i] << (13*i))). 13-bit limbs are chosen so that a full
  schoolbook product column -- up to 20 partial products of 26 bits each --
  fits a 32-bit signed accumulator (20 * 2^26 < 2^31). This keeps everything
  in native int32 on the TPU VPU; no int64 emulation, no floats.
* Representation is *lazy*: limbs are normally <= 8191 but may exceed 13 bits
  slightly (bounded <= ~8400 after :func:`carry`); values are only canonical
  (< p) after :func:`canonical`. All ops tolerate lazy inputs.
* Multiplication is one batched outer product ``(..., 20, 20)`` plus a
  "shear" pad/reshape that turns anti-diagonal summation into a plain
  reduce -- a handful of fused XLA HLOs, no gathers, no data-dependent
  control flow.
* Reduction folds limb weight 2^260 -> 19 * 2^5 = 608 (since
  2^255 = 19 mod p) and uses a few *parallel* carry passes instead of a
  sequential ripple; bounds are re-established without branches.

This is the arithmetic core under the batched ed25519 verifier
(reference behavior: crypto/ed25519/ed25519.go + curve25519-voi batch
verification in the Go engine; here re-designed for SIMD-across-signatures
execution on the TPU VPU).
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

BITS = 13
NLIMB = 20
MASK = (1 << BITS) - 1
P = 2**255 - 19

# 2^(13*20) = 2^260 == 19 * 2^5 (mod p): fold factor for limb index 20.
FOLD = 19 << 5  # 608

# Subtraction bias: == 0 mod p, every limb >= 8191 so (bias + a - b) has
# non-negative limbs for any lazily-reduced a, b. Built from 2*(2^260 - 1)
# (all limbs 16382) with the residue 1214 = 2*(608 - 1) removed from limb 0.
_SUB_BIAS = (16382 - 1214,) + (16382,) * (NLIMB - 1)
assert (sum(l << (BITS * i) for i, l in enumerate(_SUB_BIAS)) % P) == 0

# p in canonical 13-bit limbs: [8173, 8191 x 18, 255].
_P_LIMBS = tuple((P >> (BITS * i)) & MASK for i in range(NLIMB))


def to_limbs(x: int) -> np.ndarray:
    """Python int -> limb vector (host helper)."""
    return np.array([(x >> (BITS * i)) & MASK for i in range(NLIMB)], np.int32)


def from_limbs(limbs) -> int:
    """Limb vector -> Python int (host helper; accepts lazy limbs)."""
    limbs = np.asarray(limbs)
    return sum(int(l) << (BITS * i) for i, l in enumerate(limbs))


def const(x: int) -> jnp.ndarray:
    """Constant field element as a (20,) device array."""
    return jnp.array([(x >> (BITS * i)) & MASK for i in range(NLIMB)], jnp.int32)


def carry(x: jnp.ndarray, passes: int = 3) -> jnp.ndarray:
    """Parallel carry propagation with mod-p folding.

    Accepts limbs up to ~2^27 and returns limbs <= 8191 + epsilon (< 8400),
    value unchanged mod p. Each pass: split every limb into lo 13 bits plus
    carry, shift carries up one limb, and fold the carry out of limb 19
    (weight 2^260) back into limb 0 with factor 608.
    """
    for _ in range(passes):
        lo = x & MASK
        hi = x >> BITS
        rolled = jnp.roll(hi, 1, axis=-1)
        fold0 = rolled[..., :1] * FOLD
        x = lo + jnp.concatenate([fold0, rolled[..., 1:]], axis=-1)
    return x


def add(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return carry(a + b, passes=2)


def sub(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    bias = jnp.array(_SUB_BIAS, jnp.int32)
    return carry(a + bias - b, passes=2)


def neg(a: jnp.ndarray) -> jnp.ndarray:
    bias = jnp.array(_SUB_BIAS, jnp.int32)
    return carry(bias - a, passes=2)


def _fold_cols(cols: jnp.ndarray) -> jnp.ndarray:
    """Reduce 39 product columns (each < 2^31) to 20 lazy limbs.

    High columns are split into lo13/hi parts *before* multiplying by the
    fold factor so every intermediate stays inside int32.
    """
    lo_cols = cols[..., :NLIMB]
    hi_cols = cols[..., NLIMB:]  # 19 columns, weight 2^(260 + 13*i)
    hi_lo = hi_cols & MASK
    hi_hi = hi_cols >> BITS
    r = lo_cols
    r = r + jnp.pad(hi_lo * FOLD, [(0, 0)] * (r.ndim - 1) + [(0, 1)])
    r = r + jnp.pad(hi_hi * FOLD, [(0, 0)] * (r.ndim - 1) + [(1, 0)])
    return carry(r, passes=4)


def mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Batched field multiplication.

    Schoolbook outer product, then the shear trick: pad each row i of the
    (20, 20) product to width 40, flatten, drop the tail, and reshape to
    (20, 39) -- element (i, j) lands in column i + j, so an axis sum yields
    the 39 anti-diagonal columns with no gathers.
    """
    prod = a[..., :, None] * b[..., None, :]  # (..., 20, 20), < 2^26 each
    padded = jnp.pad(prod, [(0, 0)] * (prod.ndim - 2) + [(0, 0), (0, NLIMB)])
    flat = padded.reshape(*prod.shape[:-2], NLIMB * 2 * NLIMB)
    sheared = flat[..., : NLIMB * (2 * NLIMB - 1)].reshape(
        *prod.shape[:-2], NLIMB, 2 * NLIMB - 1
    )
    cols = jnp.sum(sheared, axis=-2)  # (..., 39), each < 20 * 2^26 < 2^31
    return _fold_cols(cols)


def sq(a: jnp.ndarray) -> jnp.ndarray:
    return mul(a, a)


def canonical(x: jnp.ndarray) -> jnp.ndarray:
    """Fully reduce to the unique representative in [0, p).

    Sequential carries (exact), 2^255 -> 19 folding, then one conditional
    subtract of p (branchless select). Input limbs may be lazy (<= ~2^27).
    """
    for _ in range(3):
        limbs = []
        c = jnp.zeros_like(x[..., 0])
        for i in range(NLIMB - 1):
            v = x[..., i] + c
            limbs.append(v & MASK)
            c = v >> BITS
        v = x[..., NLIMB - 1] + c
        limbs.append(v & 0xFF)
        top = v >> 8  # weight 2^255 == 19
        limbs[0] = limbs[0] + top * 19
        x = jnp.stack(limbs, axis=-1)
    # x now in [0, 2^255); subtract p once if x >= p.
    p_limbs = jnp.array(_P_LIMBS, jnp.int32)
    borrow = jnp.zeros_like(x[..., 0])
    diff = []
    for i in range(NLIMB):
        v = x[..., i] - p_limbs[i] + borrow
        diff.append(v & (MASK if i < NLIMB - 1 else 0xFF))
        v_shift = BITS if i < NLIMB - 1 else 8
        borrow = v >> v_shift  # arithmetic shift: 0 or -1
    ge_p = borrow == 0
    y = jnp.stack(diff, axis=-1)
    return jnp.where(ge_p[..., None], y, x)


def is_zero(x: jnp.ndarray) -> jnp.ndarray:
    """True where x == 0 mod p. Shape (...,)."""
    return jnp.all(canonical(x) == 0, axis=-1)


def eq(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return jnp.all(canonical(a) == canonical(b), axis=-1)


def pow_const(base: jnp.ndarray, exponent: int) -> jnp.ndarray:
    """base ** exponent for a fixed public exponent.

    MSB-first square-and-multiply with a branchless select; the exponent is
    compile-time constant so XLA sees a fixed-trip loop.
    """
    import jax

    nbits = exponent.bit_length()
    bits = jnp.array(
        [(exponent >> (nbits - 1 - i)) & 1 for i in range(nbits)], jnp.int32
    )

    def body(i, acc):
        acc = sq(acc)
        return jnp.where(bits[i][..., None] == 1, mul(acc, base), acc)

    one = jnp.broadcast_to(const(1), base.shape)
    return jax.lax.fori_loop(0, nbits, body, one)
