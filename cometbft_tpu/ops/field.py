"""Batched GF(2^255 - 19) field arithmetic for TPU (JAX, int32 limbs).

Design (TPU-first, not a port):

* A field element is ``(20, *batch)`` int32 limbs, 13 bits each,
  little-endian along axis 0 (value = sum(limb[i] << (13*i))). The batch
  dimensions TRAIL so the (large) signature axis is minor-most and fills
  the TPU's 128-wide vector lanes; the 20-limb axis lives in sublanes.
  (The previous limbs-minor layout padded 20 -> 128 lanes and wasted ~84%
  of every vector register — measured ~2x end-to-end on a v5e.)
* 13-bit limbs are chosen so a full schoolbook product column — up to 20
  partial products of <= 2^27 each — plus the reduction fold stays inside
  a 32-bit signed accumulator. Everything runs in native int32 on the TPU
  VPU; no int64 emulation, no floats.
* Representation is *lazy* with a single closed invariant, chosen so
  every add/sub/neg/dbl2 needs only ONE carry pass and mul's column fold
  needs THREE (interval-arithmetic proof in tests/test_field.py):
  every op accepts operands with limbs <= 10015 and returns limbs
  <= 10015, with all int32 intermediates in range (worst fold column
  20 * 10015^2 + fold terms < 2^31). Values are only canonical (< p)
  after :func:`canonical`.
* Multiplication is one batched outer product ``(20, 20, *batch)`` plus a
  "shear" pad/reshape over the two leading axes that turns anti-diagonal
  summation into a plain axis-0 reduce — a handful of fused XLA HLOs, no
  gathers, no data-dependent control flow.

This is the arithmetic core under the batched ed25519 verifier
(reference behavior: crypto/ed25519/ed25519.go + curve25519-voi batch
verification in the Go engine; here re-designed for SIMD-across-signatures
execution on the TPU VPU).
"""

from __future__ import annotations

from functools import lru_cache

import numpy as np

import jax.numpy as jnp

BITS = 13
NLIMB = 20
MASK = (1 << BITS) - 1
P = 2**255 - 19

# 2^(13*20) = 2^260 == 19 * 2^5 (mod p): fold factor for limb index 20.
FOLD = 19 << 5  # 608

# Subtraction bias: == 0 mod p, every limb >= 8191 so (bias + a - b) has
# non-negative limbs for any lazily-reduced a, b. Built from 2*(2^260 - 1)
# (all limbs 16382) with the residue 1214 = 2*(608 - 1) removed from limb 0.
_SUB_BIAS = (16382 - 1214,) + (16382,) * (NLIMB - 1)
assert (sum(l << (BITS * i) for i, l in enumerate(_SUB_BIAS)) % P) == 0

# p in canonical 13-bit limbs: [8173, 8191 x 18, 255].
_P_LIMBS = tuple((P >> (BITS * i)) & MASK for i in range(NLIMB))


def to_limbs(x: int) -> np.ndarray:
    """Python int -> (20,) limb vector (host helper)."""
    return np.array([(x >> (BITS * i)) & MASK for i in range(NLIMB)], np.int32)


def from_limbs(limbs) -> int:
    """(20,) limb vector -> Python int (host helper; accepts lazy limbs)."""
    limbs = np.asarray(limbs)
    return sum(int(l) << (BITS * i) for i, l in enumerate(limbs))


@lru_cache(maxsize=None)
def _const_cached(x: int, batch_ndim: int) -> np.ndarray:
    # numpy (not a device array): safe to reuse across jit traces. Frozen:
    # the cache hands out the same object forever.
    arr = np.array(
        [(x >> (BITS * i)) & MASK for i in range(NLIMB)], np.int32
    ).reshape((NLIMB,) + (1,) * batch_ndim)
    arr.setflags(write=False)
    return arr


def const(x: int, batch_ndim: int = 0) -> np.ndarray:
    """Constant field element shaped (20, 1 x batch_ndim) for broadcasting."""
    return _const_cached(x, batch_ndim)


def bconst(x: int, ref: jnp.ndarray) -> np.ndarray:
    """Constant shaped to broadcast against field element ``ref``."""
    return _const_cached(x, ref.ndim - 1)


def carry(x: jnp.ndarray, passes: int) -> jnp.ndarray:
    """Parallel carry propagation with mod-p folding (axis 0 = limbs).

    Each pass: split every limb into lo 13 bits plus carry, shift carries up
    one limb, and fold the carry out of limb 19 (weight 2^260) back into
    limb 0 with factor 608. Pass counts are fixed per call site from the
    interval analysis in the module docstring.
    """
    for _ in range(passes):
        lo = x & MASK
        hi = x >> BITS
        rolled = jnp.roll(hi, 1, axis=0)
        fold0 = rolled[:1] * FOLD
        x = lo + jnp.concatenate([fold0, rolled[1:]], axis=0)
    return x


def add(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Sum. One pass: inputs <= 10015 -> raw <= 20030, carries <= 2,
    limb0 <= 8191 + 2*608 = 9407 <= 10015."""
    return carry(a + b, passes=1)


def sub(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Difference. One pass: raw <= 10015 + 16382 = 26397, carries <= 3,
    limb0 <= 8191 + 3*608 = 10015."""
    bias = jnp.asarray(
        np.array(_SUB_BIAS, np.int32).reshape((NLIMB,) + (1,) * (a.ndim - 1))
    )
    return carry(a + bias - b, passes=1)


def neg(a: jnp.ndarray) -> jnp.ndarray:
    bias = jnp.asarray(
        np.array(_SUB_BIAS, np.int32).reshape((NLIMB,) + (1,) * (a.ndim - 1))
    )
    return carry(bias - a, passes=1)


def dbl2(a: jnp.ndarray) -> jnp.ndarray:
    """2*a, one carry pass (inputs <= 10015, output <= 9407)."""
    return carry(a + a, passes=1)


def _fold_cols(cols: jnp.ndarray) -> jnp.ndarray:
    """Reduce 39 product columns (each < ~2.02e9) to 20 lazy limbs.

    High columns are split into lo13/hi parts *before* multiplying by the
    fold factor so every intermediate stays inside int32. Three carry
    passes restore the <= 10015 invariant (bound proof in
    tests/test_field.py::test_lazy_bound_discipline).
    """
    lo_cols = cols[:NLIMB]
    hi_cols = cols[NLIMB:]  # 19 columns, weight 2^(260 + 13*i)
    hi_lo = hi_cols & MASK
    hi_hi = hi_cols >> BITS
    pad_tail = [(0, 1)] + [(0, 0)] * (cols.ndim - 1)
    pad_head = [(1, 0)] + [(0, 0)] * (cols.ndim - 1)
    r = lo_cols + jnp.pad(hi_lo * FOLD, pad_tail) + jnp.pad(hi_hi * FOLD, pad_head)
    return carry(r, passes=3)


def mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Batched field multiplication.

    Schoolbook outer product over the two leading limb axes, then the shear
    trick: pad rows to width 40, flatten the leading two axes, drop the
    tail, reshape to (20, 39, *batch) — element (i, j) lands in column
    i + j, so an axis-0 sum yields the 39 anti-diagonal columns with no
    gathers. Inputs may be any lazy values (limbs <= 10015).
    """
    prod = a[:, None] * b[None, :]  # (20, 20, *batch), each <= ~1.07e8
    batch = prod.shape[2:]
    padded = jnp.pad(prod, [(0, 0), (0, NLIMB)] + [(0, 0)] * len(batch))
    flat = padded.reshape((NLIMB * 2 * NLIMB,) + batch)
    sheared = flat[: NLIMB * (2 * NLIMB - 1)].reshape(
        (NLIMB, 2 * NLIMB - 1) + batch
    )
    cols = jnp.sum(sheared, axis=0)  # (39, *batch)
    return _fold_cols(cols)


def sq(a: jnp.ndarray) -> jnp.ndarray:
    return mul(a, a)


def canonical(x: jnp.ndarray) -> jnp.ndarray:
    """Fully reduce to the unique representative in [0, p).

    Sequential carries (exact), 2^255 -> 19 folding, then one conditional
    subtract of p (branchless select). Inputs must satisfy the lazy bound
    (limbs <= 10015), for which two ripple rounds reach a fixpoint.
    """
    for _ in range(2):
        limbs = []
        c = jnp.zeros_like(x[0])
        for i in range(NLIMB - 1):
            v = x[i] + c
            limbs.append(v & MASK)
            c = v >> BITS
        v = x[NLIMB - 1] + c
        limbs.append(v & 0xFF)
        top = v >> 8  # weight 2^255 == 19
        limbs[0] = limbs[0] + top * 19
        x = jnp.stack(limbs, axis=0)
    # x now in [0, 2^255); subtract p once if x >= p.
    p_limbs = _P_LIMBS
    borrow = jnp.zeros_like(x[0])
    diff = []
    for i in range(NLIMB):
        v = x[i] - p_limbs[i] + borrow
        diff.append(v & (MASK if i < NLIMB - 1 else 0xFF))
        v_shift = BITS if i < NLIMB - 1 else 8
        borrow = v >> v_shift  # arithmetic shift: 0 or -1
    ge_p = borrow == 0
    y = jnp.stack(diff, axis=0)
    return jnp.where(ge_p[None], y, x)


def is_zero(x: jnp.ndarray) -> jnp.ndarray:
    """True where x == 0 mod p. Shape (*batch,)."""
    return jnp.all(canonical(x) == 0, axis=0)


def eq(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return jnp.all(canonical(a) == canonical(b), axis=0)


def pow_const(base: jnp.ndarray, exponent: int) -> jnp.ndarray:
    """base ** exponent for a fixed public exponent.

    MSB-first square-and-multiply with a branchless select; the exponent is
    compile-time constant so XLA sees a fixed-trip loop. Prefer
    :func:`pow_2_252_m3` for the decompression exponent — the addition
    chain does ~265 muls where this does ~2 per bit.
    """
    import jax

    nbits = exponent.bit_length()
    bits = jnp.array(
        [(exponent >> (nbits - 1 - i)) & 1 for i in range(nbits)], jnp.int32
    )

    def body(i, acc):
        acc = sq(acc)
        sel = bits[i].reshape((1,) * acc.ndim)
        return jnp.where(sel == 1, mul(acc, base), acc)

    one = jnp.broadcast_to(const(1, base.ndim - 1), base.shape)
    return jax.lax.fori_loop(0, nbits, body, one)


def _sq_n(x: jnp.ndarray, n: int) -> jnp.ndarray:
    import jax

    if n <= 4:
        for _ in range(n):
            x = sq(x)
        return x
    return jax.lax.fori_loop(0, n, lambda i, v: sq(v), x)


def pow_2_252_m3(z: jnp.ndarray) -> jnp.ndarray:
    """z ** (2^252 - 3) — the ed25519 decompression square-root exponent.

    Classic curve25519 addition chain (~254 squarings + 11 multiplies),
    ~2x cheaper than generic square-and-multiply over the same exponent.
    """
    z2 = sq(z)  # 2
    z8 = _sq_n(z2, 2)  # 8
    z9 = mul(z, z8)  # 9
    z11 = mul(z2, z9)  # 11
    z22 = sq(z11)  # 22
    z_5_0 = mul(z9, z22)  # 2^5 - 2^0
    z_10_0 = mul(_sq_n(z_5_0, 5), z_5_0)  # 2^10 - 2^0
    z_20_0 = mul(_sq_n(z_10_0, 10), z_10_0)  # 2^20 - 2^0
    z_40_0 = mul(_sq_n(z_20_0, 20), z_20_0)  # 2^40 - 2^0
    z_50_0 = mul(_sq_n(z_40_0, 10), z_10_0)  # 2^50 - 2^0
    z_100_0 = mul(_sq_n(z_50_0, 50), z_50_0)  # 2^100 - 2^0
    z_200_0 = mul(_sq_n(z_100_0, 100), z_100_0)  # 2^200 - 2^0
    z_250_0 = mul(_sq_n(z_200_0, 50), z_50_0)  # 2^250 - 2^0
    return mul(_sq_n(z_250_0, 2), z)  # 2^252 - 3
