"""Batched SHA-256 on device: the hash half of the accelerator plane.

Every SHA-256 in the node — mempool tx keys, PartSet leaf/proof
construction, merkle app-hash/header roots — used to be serial host
``hashlib`` work sitting next to an idle accelerator. Hashing, not just
signatures, dominates blockchain data paths (arXiv:2407.03511), and
MSM + hashing are the two primitives hardware proof pipelines share
(arXiv:2504.06211) — so this kernel is both the data-path win and the
on-ramp to proof generation.

Split of labor (same TPU-first discipline as ops/verify.py):

* Host: SHA-256 padding (append 0x80, zero fill, 64-bit bit length) and
  big-endian word extraction into fixed-shape buckets — the pack step,
  analogous to the ed25519 ``pack_bytes`` path. Per-lane cost is one
  ``np.frombuffer`` view; no per-byte Python.
* Device (jax): the message schedule + 64-round compression function,
  vectorized across lanes. Lanes are independent, so the whole window
  is one embarrassingly-parallel VPU program; multi-block messages run
  the compression sequentially over the block axis via ``lax.scan``
  with per-lane active masks (shorter lanes stop updating state).

Shapes are bucketed on BOTH axes so each (block-bucket, lane-bucket)
pair compiles once and stays cached: the block bucket is the smallest
power of two holding the longest message's padded block count, the lane
bucket the smallest power of two >= the lane count (min 8). Ragged
windows in the consensus hot loop must never retrigger XLA compilation
— the no-recompile guard covers these kernels too.

Array layout: batch axis LAST everywhere (blocks ``(B, 16, L)`` uint32,
state ``(8, L)``) — see ops/field.py for why batch-minor wins on TPU.
All arithmetic is uint32 with natural mod-2^32 wraparound; digests are
bit-identical to ``hashlib.sha256`` (fuzz-pinned across every padding
boundary by tests/test_hashplane.py).
"""

from __future__ import annotations

import hashlib
from functools import lru_cache

import numpy as np

import jax

from ..libs import devstats as libdevstats

_MIN_LANES = 8
# Lanes per launch cap, like ops/verify._CHUNK: one dispatch stays a
# bounded compile shape; the hash plane's windows are capped well below
# this anyway (COMETBFT_TPU_HASH_MAX_LANES).
MAX_LANES = 8192

# Round constants / initial state (FIPS 180-4).
_K = np.array([
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5,
    0x3956c25b, 0x59f111f1, 0x923f82a4, 0xab1c5ed5,
    0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174,
    0xe49b69c1, 0xefbe4786, 0x0fc19dc6, 0x240ca1cc,
    0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7,
    0xc6e00bf3, 0xd5a79147, 0x06ca6351, 0x14292967,
    0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85,
    0xa2bfe8a1, 0xa81a664b, 0xc24b8b70, 0xc76c51a3,
    0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5,
    0x391c0cb3, 0x4ed8aa4a, 0x5b9cca4f, 0x682e6ff3,
    0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2,
], dtype=np.uint32)

_H0 = np.array([
    0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
    0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19,
], dtype=np.uint32)


# -- host-side pack ---------------------------------------------------------


def n_blocks(msg_len: int) -> int:
    """Padded 64-byte block count of an ``msg_len``-byte message."""
    return (msg_len + 8) // 64 + 1


def block_bucket(blocks: int) -> int:
    """Smallest power-of-two compile bucket holding ``blocks`` (>= 1)."""
    b = 1
    while b < blocks:
        b *= 2
    return b


def lane_bucket(n: int) -> int:
    """Smallest power-of-two lane bucket holding n (8 <= bucket)."""
    b = _MIN_LANES
    while b < n:
        b *= 2
    return b


def _pad(msg: bytes) -> bytes:
    """FIPS 180-4 padding: 0x80, zeros, 64-bit big-endian bit length."""
    ln = len(msg)
    rem = (ln + 1 + 8) % 64
    zeros = (64 - rem) % 64
    return msg + b"\x80" + b"\x00" * zeros + (8 * ln).to_bytes(8, "big")


def pack_messages(msgs, blocks_cap: int | None = None):
    """Pack a message list into one bucketed device wire buffer.

    Returns ``(blocks (B, 16, L) uint32, nblocks (L,) int32)`` where B
    is the block bucket of the LONGEST message and L the lane bucket of
    ``len(msgs)``. Callers group messages by block bucket first (the
    hash plane's window split) so a window of 55-byte tx keys never
    pads to a 64 KiB part's block count. ``blocks_cap`` asserts the
    caller's bucketing (None recomputes it here).
    """
    n = len(msgs)
    nb = [n_blocks(len(m)) for m in msgs]
    bb = blocks_cap if blocks_cap is not None else block_bucket(max(nb, default=1))
    lb = lane_bucket(n)
    blocks = np.zeros((bb, 16, lb), np.uint32)
    # per-lane block counts ship h2d every launch: the narrowest dtype
    # that can hold the bucket's block count (uint16 up to 4 MiB
    # messages) halves-to-quarters the mask-lane wire cost vs int32
    nblocks = np.zeros(lb, np.uint16 if bb <= 0xFFFF else np.int32)
    for i, m in enumerate(msgs):
        padded = _pad(bytes(m))
        k = nb[i]
        if k > bb:
            raise ValueError(f"message of {k} blocks exceeds bucket {bb}")
        blocks[:k, :, i] = np.frombuffer(padded, ">u4").reshape(k, 16)
        nblocks[i] = k
    return blocks, nblocks


# -- the device kernel ------------------------------------------------------


def _rotr(x, r: int):
    return (x >> r) | (x << (32 - r))


def _compress(state, words):
    """One SHA-256 compression: state (8, L) + block words (16, L).

    The 48 schedule extensions and 64 rounds are unrolled in Python —
    a few hundred fused VPU ops per block, compiled once per shape
    bucket; uint32 adds wrap mod 2^32 natively.
    """
    import jax.numpy as jnp

    k = jnp.asarray(_K)  # constant-folded per compile
    w = [words[t] for t in range(16)]
    for t in range(16, 64):
        s0 = _rotr(w[t - 15], 7) ^ _rotr(w[t - 15], 18) ^ (w[t - 15] >> 3)
        s1 = _rotr(w[t - 2], 17) ^ _rotr(w[t - 2], 19) ^ (w[t - 2] >> 10)
        w.append(w[t - 16] + s0 + w[t - 7] + s1)
    a, b, c, d, e, f, g, h = (state[i] for i in range(8))
    for t in range(64):
        s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + s1 + ch + k[t] + w[t]
        s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        t2 = s0 + maj
        h, g, f, e, d, c, b, a = g, f, e, d + t1, c, b, a, t1 + t2
    return jnp.stack([
        state[0] + a, state[1] + b, state[2] + c, state[3] + d,
        state[4] + e, state[5] + f, state[6] + g, state[7] + h,
    ])


def _sha256_kernel(blocks, nblocks):
    """(B, 16, L) uint32 blocks + per-lane block counts -> (8, L) state.

    The scan walks the block axis; a lane whose message ended keeps its
    state (masked where), so one launch serves every length inside the
    bucket bit-identically.
    """
    import jax.numpy as jnp
    from jax import lax

    lanes = blocks.shape[2]
    state = jnp.tile(jnp.asarray(_H0)[:, None], (1, lanes))

    def step(st, inp):
        words, idx = inp
        new = _compress(st, words)
        active = (idx < nblocks)[None, :]
        return jnp.where(active, new, st), None

    idxs = jnp.arange(blocks.shape[0], dtype=jnp.int32)
    state, _ = lax.scan(step, state, (blocks, idxs))
    return state


def _donatable(argnums):
    from ..libs.accel import ACCELERATOR_BACKENDS

    try:
        return argnums if jax.default_backend() in ACCELERATOR_BACKENDS else ()
    except Exception:
        return ()


@lru_cache(maxsize=None)
def _jitted_kernel(blocks_bucket: int):
    """The tracked jit for ONE block bucket, built lazily (importing
    this module must not touch jax.jit). The kernel compiles per
    (block-bucket, lane-bucket) shape pair, but devstats keys its
    recompile detector on (kernel-name, lane-bucket) — so each block
    bucket gets its OWN jit + kernel name (``sha256.xla.b<B>``), or a
    fresh block bucket at an already-seen lane bucket would read as a
    phantom steady-state recompile and feed the recompile-storm
    watchdog. Compiles land in
    ``xla_compile_total{kernel="sha256.xla.b<B>",bucket=<lanes>}`` and
    the tier-1 no-recompile guard covers the hash plane too."""
    from .verify import _enable_compilation_cache

    _enable_compilation_cache()
    return libdevstats.track(
        f"sha256.xla.b{blocks_bucket}",
        jax.jit(_sha256_kernel, donate_argnums=_donatable((0,))),
        axis=0,
    )


def _digests_from_state(arr: np.ndarray, n: int) -> list[bytes]:
    """(8, L) uint32 host state -> n 32-byte big-endian digests."""
    raw = np.ascontiguousarray(arr.T[:n]).astype(">u4").tobytes()
    return [raw[32 * i : 32 * i + 32] for i in range(n)]


def sha256_many_async(msgs, blocks_cap: int | None = None):
    """Dispatch one bucketed batch; returns a zero-arg materializer.

    Same async contract as ops/verify.verify_bytes_async: the closure
    blocks on the device once and returns the per-lane 32-byte digests
    (bit-identical to ``hashlib.sha256``). Callers keep lanes within
    one block bucket (``blocks_cap``) and under :data:`MAX_LANES` — the
    hash plane's window split guarantees both.
    """
    n = len(msgs)
    if n == 0:
        return lambda: []
    if n > MAX_LANES:
        raise ValueError(f"{n} lanes exceed the {MAX_LANES}-lane launch cap")
    blocks, nblocks = pack_messages(msgs, blocks_cap)
    out = _jitted_kernel(blocks.shape[0])(blocks, nblocks)
    libdevstats.record_h2d(blocks.nbytes + nblocks.nbytes)

    def materialize() -> list[bytes]:
        # cometlint: disable=CLNT002 -- THE sanctioned readback of a hash
        # launch: every async dispatch materializes exactly once, here
        arr = np.asarray(out)
        libdevstats.record_d2h(arr.nbytes)
        return _digests_from_state(arr, n)

    return materialize


def sha256_many_host(msgs) -> list[bytes]:
    """The host oracle: one ``hashlib`` digest per message."""
    return [hashlib.sha256(m).digest() for m in msgs]
