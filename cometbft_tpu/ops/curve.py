"""Batched edwards25519 point arithmetic + the ed25519 verify kernel (JAX).

TPU-first design notes:

* Points are extended twisted-Edwards coordinates stacked as ``(..., 4, 20)``
  int32 arrays ([X, Y, Z, T] of 20-limb field elements, see ops.field).
* All formulas are the *complete* a=-1 addition laws -- branchless, valid for
  every input including identity and small-order points. Completeness is a
  correctness requirement under ZIP-215 (reference semantics:
  crypto/ed25519/ed25519.go:26-29 in the Go engine), not just a convenience:
  mixed-order points are admissible and the cofactored equation
  [8]([S]B - [k]A - R) == O must be evaluated exactly.
* Point decompression (including the sqrt candidate x = u*v^3*(u*v^7)^((p-5)/8))
  runs on device, batched; non-points surface as a False lane in the validity
  mask instead of an exception.
* The double-scalar multiplication [S]B + [k']A (k' = -k mod L, legal under
  the cofactored check because [L]A is small-order) is a joint Straus ladder:
  one shared doubling per bit plus one table-select add from
  {O, B, A, A+B}. 256 fixed iterations under lax.fori_loop -- no
  data-dependent control flow, fully batched across signatures.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from . import field
from .field import add, canonical, carry, const, eq, is_zero, mul, neg, sq, sub

P = field.P
L = 2**252 + 27742317777372353535851937790883648493
D_INT = (-121665 * pow(121666, P - 2, P)) % P
D2_INT = (2 * D_INT) % P
SQRT_M1_INT = pow(2, (P - 1) // 4, P)
_BY = (4 * pow(5, P - 2, P)) % P


def _recover_x_int(y: int, sign: int) -> int:
    u = (y * y - 1) % P
    v = (D_INT * y * y + 1) % P
    x = (u * pow(v, 3, P) * pow(u * pow(v, 7, P) % P, (P - 5) // 8, P)) % P
    if (v * x * x - u) % P != 0:
        x = x * SQRT_M1_INT % P
    assert (v * x * x - u) % P == 0
    if x & 1 != sign:
        x = (P - x) % P
    return x


_BX = _recover_x_int(_BY, 0)

# Constant points as Python limb tuples; materialized inside jit as constants.
IDENTITY_INT = (0, 1, 1, 0)
BASE_INT = (_BX, _BY, 1, _BX * _BY % P)


def const_point(coords) -> jnp.ndarray:
    """(x, y, z, t) Python ints -> (4, 20) device constant."""
    return jnp.stack([const(c) for c in coords])


def broadcast_point(point: jnp.ndarray, batch_shape) -> jnp.ndarray:
    return jnp.broadcast_to(point, tuple(batch_shape) + (4, 20))


def point_add(p: jnp.ndarray, q: jnp.ndarray) -> jnp.ndarray:
    """Complete addition, a=-1 extended coordinates (9 field muls)."""
    x1, y1, z1, t1 = p[..., 0, :], p[..., 1, :], p[..., 2, :], p[..., 3, :]
    x2, y2, z2, t2 = q[..., 0, :], q[..., 1, :], q[..., 2, :], q[..., 3, :]
    a = mul(sub(y1, x1), sub(y2, x2))
    b = mul(add(y1, x1), add(y2, x2))
    c = mul(mul(t1, const(D2_INT)), t2)
    d = carry(2 * mul(z1, z2), passes=2)
    e = sub(b, a)
    f = sub(d, c)
    g = add(d, c)
    h = add(b, a)
    return jnp.stack(
        [mul(e, f), mul(g, h), mul(f, g), mul(e, h)], axis=-2
    )


def point_double(p: jnp.ndarray) -> jnp.ndarray:
    """Complete doubling (4 squarings + 4 muls)."""
    x1, y1, z1 = p[..., 0, :], p[..., 1, :], p[..., 2, :]
    a = sq(x1)
    b = sq(y1)
    c = carry(2 * sq(z1), passes=2)
    h = add(a, b)
    e = sub(h, sq(add(x1, y1)))
    g = sub(a, b)
    f = add(c, g)
    return jnp.stack(
        [mul(e, f), mul(g, h), mul(f, g), mul(e, h)], axis=-2
    )


def point_neg(p: jnp.ndarray) -> jnp.ndarray:
    x, y, z, t = p[..., 0, :], p[..., 1, :], p[..., 2, :], p[..., 3, :]
    return jnp.stack([neg(x), y, z, neg(t)], axis=-2)


def is_identity(p: jnp.ndarray) -> jnp.ndarray:
    """True where p == O, i.e. X == 0 and Y == Z (projective). Shape (...,)."""
    x, y, z = p[..., 0, :], p[..., 1, :], p[..., 2, :]
    return is_zero(x) & is_zero(sub(y, z))


def decompress(y_limbs: jnp.ndarray, sign: jnp.ndarray):
    """Batched ZIP-215 point decompression on device.

    ``y_limbs``: (..., 20) limbs of the 255-bit y encoding -- may be
    non-canonical (y >= p), which ZIP-215 *accepts*; lazy reduction makes
    that free here. ``sign``: (...,) 0/1 x-parity bit.

    Returns (point (..., 4, 20), ok (...,) bool). "Negative zero"
    (x == 0, sign == 1) is accepted per ZIP-215 (the parity flip on x = 0 is
    a no-op, exactly the voi semantics the Go engine relies on).
    """
    one = jnp.broadcast_to(const(1), y_limbs.shape)
    yy = sq(y_limbs)
    u = sub(yy, one)
    v = add(mul(const(D_INT), yy), one)
    v3 = mul(sq(v), v)
    v7 = mul(sq(v3), v)
    x = mul(mul(u, v3), field.pow_const(mul(u, v7), (P - 5) // 8))
    vxx = mul(v, sq(x))
    root_ok = eq(vxx, u)
    flip_ok = eq(vxx, neg(u))
    x = jnp.where(flip_ok[..., None], mul(x, const(SQRT_M1_INT)), x)
    ok = root_ok | flip_ok
    xc = canonical(x)
    parity = xc[..., 0] & 1
    x = jnp.where((parity != sign)[..., None], neg(xc), xc)
    point = jnp.stack([x, y_limbs, one, mul(x, y_limbs)], axis=-2)
    return point, ok


def verify_kernel(
    y_a: jnp.ndarray,
    sign_a: jnp.ndarray,
    y_r: jnp.ndarray,
    sign_r: jnp.ndarray,
    s_bits: jnp.ndarray,
    kneg_bits: jnp.ndarray,
) -> jnp.ndarray:
    """Batched cofactored ed25519 verification.

    Inputs (N = batch):
      y_a, y_r:        (N, 20) y-limbs of pubkey A and signature point R
      sign_a, sign_r:  (N,)    x-parity bits
      s_bits:          (N, 256) bits of S, MSB first (host checks S < L)
      kneg_bits:       (N, 256) bits of (-k mod L), k = SHA512(R||A||M) mod L

    Returns (N,) bool: [8]([S]B + [-k]A - R) == O and both points decoded.
    The SHA-512 challenge is computed on host: hashing is byte-serial work
    with no TPU affinity, while the ~5k field muls per signature here are
    the >99.9% compute share and batch perfectly.
    """
    a_pt, ok_a = decompress(y_a, sign_a)
    r_pt, ok_r = decompress(y_r, sign_r)
    batch = y_a.shape[:-1]

    base = broadcast_point(const_point(BASE_INT), batch)
    ident = broadcast_point(const_point(IDENTITY_INT), batch)
    a_plus_b = point_add(a_pt, base)
    # Straus table indexed by (k_bit, s_bit): O, B, A, A+B -> (N, 4, 4, 20)
    table = jnp.stack([ident, base, a_pt, a_plus_b], axis=-3)

    def body(i, acc):
        acc = point_double(acc)
        idx = 2 * kneg_bits[..., i] + s_bits[..., i]  # (N,)
        onehot = (idx[..., None] == jnp.arange(4, dtype=jnp.int32)).astype(
            jnp.int32
        )  # (N, 4)
        sel = jnp.sum(onehot[..., :, None, None] * table, axis=-3)  # (N, 4, 20)
        return point_add(acc, sel)

    acc = jax.lax.fori_loop(0, 256, body, ident)
    acc = point_add(acc, point_neg(r_pt))
    acc = point_double(point_double(point_double(acc)))
    return is_identity(acc) & ok_a & ok_r
