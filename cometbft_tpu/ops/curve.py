"""Batched edwards25519 point arithmetic + the ed25519 verify kernel (JAX).

TPU-first design notes:

* Points are extended twisted-Edwards coordinates stacked as ``(4, 20, *B)``
  int32 arrays ([X, Y, Z, T] of 20-limb field elements, see ops.field).
  Batch dims TRAIL (minor-most = signature axis) so vector lanes are full.
* All formulas are the *complete* a=-1 addition laws — branchless, valid for
  every input including identity and small-order points. Completeness is a
  correctness requirement under ZIP-215 (reference semantics:
  crypto/ed25519/ed25519.go:26-29 in the Go engine), not just a convenience:
  mixed-order points are admissible and the cofactored equation
  [8]([S]B - [k]A - R) == O must be evaluated exactly.
* Point decompression (sqrt candidate x = u*v^3*(u*v^7)^((p-5)/8)) runs on
  device, batched, with the ~265-mul addition-chain power; non-points
  surface as a False lane in the validity mask instead of an exception.
* The double-scalar multiplication [S]B + [k']A (k' = -k mod L, legal under
  the cofactored check because [8][L]A = O) is a 4-bit windowed joint
  ladder: 64 windows of (4 shared doublings + one add from a per-lane
  16-entry table of A-multiples + one add from a constant 16-entry table of
  B-multiples). Table entries are kept in precomputed "Niels" form
  (Y+X, Y-X, 2Z, 2dT) so a table add costs 8 field muls (7 when the entry
  is affine, Z == 1) versus 9 for the generic complete add. Selection is a
  branchless one-hot multiply-reduce — no gathers, no data-dependent
  control flow; 64 fixed trips under lax.fori_loop.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from . import field
from .field import add, canonical, dbl2, eq, is_zero, mul, neg, sq, sub

P = field.P
L = 2**252 + 27742317777372353535851937790883648493
D_INT = (-121665 * pow(121666, P - 2, P)) % P
D2_INT = (2 * D_INT) % P
SQRT_M1_INT = pow(2, (P - 1) // 4, P)
_BY = (4 * pow(5, P - 2, P)) % P

WINDOWS = 64  # 4-bit windows over 256-bit scalars
WBITS = 4
TSIZE = 1 << WBITS


def _recover_x_int(y: int, sign: int) -> int:
    u = (y * y - 1) % P
    v = (D_INT * y * y + 1) % P
    x = (u * pow(v, 3, P) * pow(u * pow(v, 7, P) % P, (P - 5) // 8, P)) % P
    if (v * x * x - u) % P != 0:
        x = x * SQRT_M1_INT % P
    assert (v * x * x - u) % P == 0
    if x & 1 != sign:
        x = (P - x) % P
    return x


_BX = _recover_x_int(_BY, 0)

# Constant points as Python int tuples; materialized inside jit as constants.
IDENTITY_INT = (0, 1, 1, 0)
BASE_INT = (_BX, _BY, 1, _BX * _BY % P)


def _base_table_host() -> np.ndarray:
    """(16, 3, 20) int32: v*B for v in [0,16) in affine-Niels form
    (y+x, y-x, 2d*x*y), computed exactly on host with Python ints."""

    def ext_add(p, q):
        x1, y1, z1, t1 = p
        x2, y2, z2, t2 = q
        a = (y1 - x1) * (y2 - x2) % P
        b = (y1 + x1) * (y2 + x2) % P
        c = t1 * D2_INT % P * t2 % P
        d = 2 * z1 * z2 % P
        e, f, g, h = b - a, d - c, d + c, b + a
        return (e * f % P, g * h % P, f * g % P, e * h % P)

    rows = []
    pt = IDENTITY_INT
    for v in range(TSIZE):
        x, y, z, _ = pt
        zinv = pow(z, P - 2, P)
        xa, ya = x * zinv % P, y * zinv % P
        rows.append(
            [
                field.to_limbs((ya + xa) % P),
                field.to_limbs((ya - xa) % P),
                field.to_limbs(2 * D_INT * xa % P * ya % P),
            ]
        )
        pt = ext_add(pt, BASE_INT)
    return np.stack([np.stack(r) for r in rows])


_BASE_TABLE = _base_table_host()


def const_point(coords, batch_ndim: int = 0) -> jnp.ndarray:
    """(x, y, z, t) Python ints -> (4, 20, 1 x batch_ndim) device constant."""
    return jnp.stack([field.const(c, batch_ndim) for c in coords])


def broadcast_point(point: jnp.ndarray, batch_shape) -> jnp.ndarray:
    return jnp.broadcast_to(
        point.reshape(point.shape[:2] + (1,) * len(batch_shape)),
        point.shape[:2] + tuple(batch_shape),
    )


def point_add(p: jnp.ndarray, q: jnp.ndarray) -> jnp.ndarray:
    """Complete addition, a=-1 extended coordinates (9 field muls)."""
    x1, y1, z1, t1 = p[0], p[1], p[2], p[3]
    x2, y2, z2, t2 = q[0], q[1], q[2], q[3]
    a = mul(sub(y1, x1), sub(y2, x2))
    b = mul(add(y1, x1), add(y2, x2))
    c = mul(mul(t1, field.bconst(D2_INT, t1)), t2)
    d = dbl2(mul(z1, z2))
    e = sub(b, a)
    f = sub(d, c)
    g = add(d, c)
    h = add(b, a)
    return jnp.stack([mul(e, f), mul(g, h), mul(f, g), mul(e, h)])


def point_double(p: jnp.ndarray) -> jnp.ndarray:
    """Complete doubling (4 squarings + 4 muls)."""
    x1, y1, z1 = p[0], p[1], p[2]
    a = sq(x1)
    b = sq(y1)
    c = dbl2(sq(z1))
    h = add(a, b)
    e = sub(h, sq(add(x1, y1)))
    g = sub(a, b)
    f = add(c, g)
    return jnp.stack([mul(e, f), mul(g, h), mul(f, g), mul(e, h)])


def point_double_n(p: jnp.ndarray, n: int) -> jnp.ndarray:
    """n consecutive doublings, skipping T on all but the last.

    Doubling reads only (X, Y, Z); T (the E*H product) is needed only by
    the *add* that follows a doubling chain. Dropping it from the first
    n-1 doublings saves one field mul each — doubling chains are ~2/3 of
    the ladder's muls, so this is a free ~5% (64 windows x 3 muls)."""
    x1, y1, z1 = p[0], p[1], p[2]
    for i in range(n):
        a = sq(x1)
        b = sq(y1)
        c = dbl2(sq(z1))
        h = add(a, b)
        e = sub(h, sq(add(x1, y1)))
        g = sub(a, b)
        f = add(c, g)
        x1, y1, z1 = mul(e, f), mul(g, h), mul(f, g)
    return jnp.stack([x1, y1, z1, mul(e, h)])


def point_neg(p: jnp.ndarray) -> jnp.ndarray:
    return jnp.stack([neg(p[0]), p[1], p[2], neg(p[3])])


def to_niels(p: jnp.ndarray) -> jnp.ndarray:
    """Extended point -> projective-Niels (Y+X, Y-X, 2Z, 2dT): one mul."""
    x, y, z, t = p[0], p[1], p[2], p[3]
    return jnp.stack(
        [add(y, x), sub(y, x), dbl2(z), mul(t, field.bconst(D2_INT, t))]
    )


def to_affine_niels(p: jnp.ndarray) -> jnp.ndarray:
    """Affine (Z==1) extended point -> (Y+X, Y-X, 2dT): one mul."""
    x, y, t = p[0], p[1], p[3]
    return jnp.stack(
        [add(y, x), sub(y, x), mul(t, field.bconst(D2_INT, t))]
    )


def niels_add(p: jnp.ndarray, n: jnp.ndarray) -> jnp.ndarray:
    """p + Q where Q is in projective-Niels form (8 field muls)."""
    x1, y1, z1, t1 = p[0], p[1], p[2], p[3]
    u2, v2, w2, t2d = n[0], n[1], n[2], n[3]
    a = mul(sub(y1, x1), v2)
    b = mul(add(y1, x1), u2)
    c = mul(t1, t2d)
    d = mul(z1, w2)
    e = sub(b, a)
    f = sub(d, c)
    g = add(d, c)
    h = add(b, a)
    return jnp.stack([mul(e, f), mul(g, h), mul(f, g), mul(e, h)])


def affine_niels_add(p: jnp.ndarray, n3: jnp.ndarray) -> jnp.ndarray:
    """p + Q where Q is affine-Niels (y+x, y-x, 2dxy), Z == 1: 7 muls."""
    x1, y1, z1, t1 = p[0], p[1], p[2], p[3]
    u2, v2, t2d = n3[0], n3[1], n3[2]
    a = mul(sub(y1, x1), v2)
    b = mul(add(y1, x1), u2)
    c = mul(t1, t2d)
    d = dbl2(z1)
    e = sub(b, a)
    f = sub(d, c)
    g = add(d, c)
    h = add(b, a)
    return jnp.stack([mul(e, f), mul(g, h), mul(f, g), mul(e, h)])


def is_identity(p: jnp.ndarray) -> jnp.ndarray:
    """True where p == O, i.e. X == 0 and Y == Z (projective). Shape (*B,)."""
    return is_zero(p[0]) & is_zero(sub(p[1], p[2]))


def decompress(y_limbs: jnp.ndarray, sign: jnp.ndarray):
    """Batched ZIP-215 point decompression on device.

    ``y_limbs``: (20, *B) limbs of the 255-bit y encoding — may be
    non-canonical (y >= p), which ZIP-215 *accepts*; lazy reduction makes
    that free here. ``sign``: (*B,) 0/1 x-parity bit.

    Returns (point (4, 20, *B), ok (*B,) bool). "Negative zero"
    (x == 0, sign == 1) is accepted per ZIP-215 (the parity flip on x = 0 is
    a no-op, exactly the voi semantics the Go engine relies on).
    """
    one = jnp.broadcast_to(field.const(1, y_limbs.ndim - 1), y_limbs.shape)
    yy = sq(y_limbs)
    u = sub(yy, one)
    v = add(mul(field.bconst(D_INT, yy), yy), one)
    v3 = mul(sq(v), v)
    v7 = mul(sq(v3), v)
    x = mul(mul(u, v3), field.pow_2_252_m3(mul(u, v7)))
    vxx = mul(v, sq(x))
    root_ok = eq(vxx, u)
    flip_ok = eq(vxx, neg(u))
    x = jnp.where(flip_ok[None], mul(x, field.bconst(SQRT_M1_INT, x)), x)
    ok = root_ok | flip_ok
    xc = canonical(x)
    parity = xc[0] & 1
    x = jnp.where((parity != sign)[None], neg(xc), xc)
    point = jnp.stack([x, y_limbs, one, mul(x, y_limbs)])
    return point, ok


def _build_a_table(a_pt: jnp.ndarray) -> jnp.ndarray:
    """Per-lane table [O, A, 2A, ..., 15A] in projective-Niels form.

    a_pt: (4, 20, *B) decompressed pubkey (affine, Z=1). Returns
    (16, 4, 20, *B). One double + 13 Niels adds + one batched conversion.
    """
    batch = a_pt.shape[2:]
    a_niels3 = to_affine_niels(a_pt)
    entries = [a_pt, point_double(a_pt)]
    for _ in range(2, TSIZE - 1):
        entries.append(affine_niels_add(entries[-1], a_niels3))
    # (15, 4, 20, *B) -> (4, 20, 15, *B): limbs back on axis 0 per coord so
    # the Niels conversion runs as ONE batched field op over all 15 entries.
    stacked = jnp.moveaxis(jnp.stack(entries), 0, 2)
    niels = jnp.moveaxis(to_niels(stacked), 2, 0)  # (15, 4, 20, *B)
    ident = jnp.broadcast_to(
        const_point((1, 1, 2, 0), len(batch))[None],
        (1,) + niels.shape[1:],
    )
    return jnp.concatenate([ident, niels], axis=0)


def _select(table: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    """Branchless one-hot row select: table (16, *rest, *B), idx (*B,)."""
    iota = jnp.arange(TSIZE, dtype=jnp.int32).reshape(
        (TSIZE,) + (1,) * idx.ndim
    )
    onehot = (idx[None] == iota).astype(jnp.int32)
    oh = onehot.reshape(
        (TSIZE,) + (1,) * (table.ndim - 1 - idx.ndim) + idx.shape
    )
    return jnp.sum(oh * table, axis=0)


def build_pubkey_tables(y_a: jnp.ndarray, sign_a: jnp.ndarray):
    """Decompress pubkeys and expand their 16-entry Niels tables.

    The device-side half of the expanded-pubkey cache (the reference keeps
    a 4096-entry LRU of expanded keys, crypto/ed25519/ed25519.go:31,56;
    SURVEY §7(c) calls for HBM-resident tables keyed by validator set).
    Validators recur every round — paying the ~254-squaring sqrt chain and
    the 14-point-op table build once per KEY instead of once per LAUNCH
    removes ~11% of the per-signature muls in steady state.

    Returns (table (16, 4, 20, *B) int32, ok (*B,) bool).
    """
    a_pt, ok = decompress(y_a, sign_a)
    return _build_a_table(a_pt), ok


def verify_kernel_cached(
    table_a: jnp.ndarray,
    y_r: jnp.ndarray,
    sign_r: jnp.ndarray,
    s_nibs: jnp.ndarray,
    kneg_nibs: jnp.ndarray,
) -> jnp.ndarray:
    """Cofactored verification with a PRE-EXPANDED pubkey table.

    Same math as :func:`verify_kernel` minus A's decompression and table
    build — callers gather per-lane tables from the HBM-resident cache
    (ops/verify.PubkeyTableCache) and pass them in. Only R decompresses
    here. Returns (*B,) bool; the caller must AND in the cached per-key
    decompress-ok bits.
    """
    batch = y_r.shape[1:]
    r_pt, ok_r = decompress(y_r, sign_r)
    table_b = jnp.asarray(
        _BASE_TABLE.reshape((TSIZE, 3, field.NLIMB) + (1,) * len(batch))
    )
    ident = broadcast_point(const_point(IDENTITY_INT), batch)

    def body(j, acc):
        acc = point_double_n(acc, WBITS)
        acc = niels_add(acc, _select(table_a, kneg_nibs[j]))
        acc = affine_niels_add(acc, _select(table_b, s_nibs[j]))
        return acc

    acc = jax.lax.fori_loop(0, WINDOWS, body, ident)
    acc = affine_niels_add(acc, to_affine_niels(point_neg(r_pt)))
    acc = point_double(point_double(point_double(acc)))
    return is_identity(acc) & ok_r


# ---------------------------------------------------------------- 8-bit
# fixed-base windows for [S]B (gated prototype: COMETBFT_TPU_KERNEL=xla8).
#
# S is the one scalar whose base point is CONSTANT across every lane and
# every launch, so its window tables can be precomputed per WINDOW rather
# than per lane: with T_j[v] = [v * 2^(8j)]B in affine-Niels form,
# [S]B = sum_j T_j[S_j] needs 32 table adds and ZERO doublings — the
# ladder's doublings remain driven by the per-lane A part alone. vs the
# joint 4-bit ladder this removes 32 of 64 B-adds (~215 field muls/sig,
# ~11% of the cached total, docs/tpu-kernel.md ledger).
#
# The 256-entry selects are expressed as ONE batched one-hot matmul
# (32, 60, 256) @ (32, 256, N) so the MXU (systolic array) does the
# gather work instead of the VPU: a 16-entry select was affordable as a
# one-hot multiply-reduce, a 256-entry one is not. f32 accumulation is
# EXACT here: limbs are < 2^13, the one-hot has a single nonzero per
# column, and Precision.HIGHEST keeps full f32 fidelity through the
# bf16 decomposition on TPU.


def _base_table8_host() -> np.ndarray:
    """(32, 256, 3, 20) int32: [v * 2^(8j)]B affine-Niels entries.

    One Montgomery batch inversion turns 8192 per-point affine
    conversions into one modexp; table build is ~0.3 s once per process
    (and only when the 8-bit path is actually used).
    """

    def ext_add(p, q):
        x1, y1, z1, t1 = p
        x2, y2, z2, t2 = q
        a = (y1 - x1) * (y2 - x2) % P
        b = (y1 + x1) * (y2 + x2) % P
        c = t1 * D2_INT % P * t2 % P
        d = 2 * z1 * z2 % P
        e, f, g, h = b - a, d - c, d + c, b + a
        return (e * f % P, g * h % P, f * g % P, e * h % P)

    pts = []
    g = BASE_INT
    for _j in range(32):
        row = IDENTITY_INT
        for _v in range(256):
            pts.append(row)
            row = ext_add(row, g)
        for _ in range(8):  # g <- [2^8] g for the next window
            g = ext_add(g, g)

    # Montgomery batch inversion of all Z coordinates.
    prefix = [1]
    for p in pts:
        prefix.append(prefix[-1] * p[2] % P)
    inv_acc = pow(prefix[-1], P - 2, P)
    zinvs = [0] * len(pts)
    for i in range(len(pts) - 1, -1, -1):
        zinvs[i] = prefix[i] * inv_acc % P
        inv_acc = inv_acc * pts[i][2] % P

    out = np.empty((32 * 256, 3, NLIMB), np.int32)
    for i, (p, zi) in enumerate(zip(pts, zinvs)):
        xa, ya = p[0] * zi % P, p[1] * zi % P
        out[i, 0] = field.to_limbs((ya + xa) % P)
        out[i, 1] = field.to_limbs((ya - xa) % P)
        out[i, 2] = field.to_limbs(2 * D_INT * xa % P * ya % P)
    return out.reshape(32, 256, 3, NLIMB)


NLIMB = field.NLIMB
_TABLE8_CACHE: list = []


def _base_table8_f32() -> np.ndarray:
    """(32, 60, 256) float32, transposed for the select matmul."""
    if not _TABLE8_CACHE:
        t8 = _base_table8_host().reshape(32, 256, 3 * NLIMB)
        _TABLE8_CACHE.append(
            np.ascontiguousarray(t8.transpose(0, 2, 1)).astype(np.float32)
        )
    return _TABLE8_CACHE[0]


def fixed_base_sum8(s_bytes: jnp.ndarray) -> jnp.ndarray:
    """[S]B from little-endian S bytes via per-window constant tables.

    s_bytes: (32, *B) int32 in [0, 256). Returns an extended point
    (4, 20, *B). 32 affine-Niels adds, no doublings; selection rides the
    MXU as a batched one-hot matmul.
    """
    batch = s_bytes.shape[1:]
    nb = 1
    for d in batch:
        nb *= d
    flat = s_bytes.reshape(32, 1, nb)
    iota = jnp.arange(256, dtype=jnp.int32).reshape(1, 256, 1)
    onehot = (flat == iota).astype(jnp.float32)  # (32, 256, NB)
    tabs = jnp.asarray(_base_table8_f32())  # (32, 60, 256)
    sel = jax.lax.dot_general(
        tabs,
        onehot,
        dimension_numbers=(((2,), (1,)), ((0,), (0,))),
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32,
    )  # (32, 60, NB)
    sel = sel.astype(jnp.int32).reshape((32, 3, NLIMB) + batch)
    acc = broadcast_point(const_point(IDENTITY_INT), batch)

    def body(j, acc):
        return affine_niels_add(acc, sel[j])

    return jax.lax.fori_loop(0, 32, body, acc)


def _ladder_a_only(table_a, kneg_nibs, batch):
    """The joint ladder minus the B part: [(-k mod L)]A."""
    ident = broadcast_point(const_point(IDENTITY_INT), batch)

    def body(j, acc):
        acc = point_double_n(acc, WBITS)
        return niels_add(acc, _select(table_a, kneg_nibs[j]))

    return jax.lax.fori_loop(0, WINDOWS, body, ident)


def verify_kernel8(
    y_a: jnp.ndarray,
    sign_a: jnp.ndarray,
    y_r: jnp.ndarray,
    sign_r: jnp.ndarray,
    s_bytes: jnp.ndarray,
    kneg_nibs: jnp.ndarray,
) -> jnp.ndarray:
    """verify_kernel with the [S]B part on 8-bit fixed-base windows."""
    y2 = jnp.stack([y_a, y_r], axis=1)
    s2 = jnp.stack([sign_a, sign_r], axis=0)
    pts, oks = decompress(y2, s2)
    a_pt, r_pt = pts[:, :, 0], pts[:, :, 1]
    batch = y_a.shape[1:]
    table_a = _build_a_table(a_pt)
    acc = point_add(
        _ladder_a_only(table_a, kneg_nibs, batch), fixed_base_sum8(s_bytes)
    )
    acc = affine_niels_add(acc, to_affine_niels(point_neg(r_pt)))
    acc = point_double(point_double(point_double(acc)))
    return is_identity(acc) & oks[0] & oks[1]


def verify_kernel8_cached(
    table_a: jnp.ndarray,
    y_r: jnp.ndarray,
    sign_r: jnp.ndarray,
    s_bytes: jnp.ndarray,
    kneg_nibs: jnp.ndarray,
) -> jnp.ndarray:
    """verify_kernel_cached with 8-bit fixed-base [S]B windows."""
    batch = y_r.shape[1:]
    r_pt, ok_r = decompress(y_r, sign_r)
    acc = point_add(
        _ladder_a_only(table_a, kneg_nibs, batch), fixed_base_sum8(s_bytes)
    )
    acc = affine_niels_add(acc, to_affine_niels(point_neg(r_pt)))
    acc = point_double(point_double(point_double(acc)))
    return is_identity(acc) & ok_r


def verify_kernel(
    y_a: jnp.ndarray,
    sign_a: jnp.ndarray,
    y_r: jnp.ndarray,
    sign_r: jnp.ndarray,
    s_nibs: jnp.ndarray,
    kneg_nibs: jnp.ndarray,
) -> jnp.ndarray:
    """Batched cofactored ed25519 verification.

    Inputs (B = batch shape, limb/window axes lead):
      y_a, y_r:        (20, *B) y-limbs of pubkey A and signature point R
      sign_a, sign_r:  (*B,)    x-parity bits
      s_nibs:          (64, *B) 4-bit windows of S, MSB first (host checks S < L)
      kneg_nibs:       (64, *B) 4-bit windows of (-k mod L), k = SHA512(R||A||M) mod L

    Returns (*B,) bool: [8]([S]B + [-k]A - R) == O and both points decoded.
    The SHA-512 challenge is computed on host: hashing is byte-serial work
    with no TPU affinity, while the ~3k field muls per signature here are
    the >99.9% compute share and batch perfectly.
    """
    batch = y_a.shape[1:]

    # Decompress A and R in one stacked launch: (20, 2, *B).
    y2 = jnp.stack([y_a, y_r], axis=1)
    s2 = jnp.stack([sign_a, sign_r], axis=0)
    pts, oks = decompress(y2, s2)
    a_pt = pts[:, :, 0]
    r_pt = pts[:, :, 1]
    ok_a = oks[0]
    ok_r = oks[1]

    table_a = _build_a_table(a_pt)  # (16, 4, 20, *B)
    table_b = jnp.asarray(
        _BASE_TABLE.reshape((TSIZE, 3, field.NLIMB) + (1,) * len(batch))
    )

    ident = broadcast_point(const_point(IDENTITY_INT), batch)

    def body(j, acc):
        acc = point_double_n(acc, WBITS)
        acc = niels_add(acc, _select(table_a, kneg_nibs[j]))
        acc = affine_niels_add(acc, _select(table_b, s_nibs[j]))
        return acc

    acc = jax.lax.fori_loop(0, WINDOWS, body, ident)

    # Subtract R: add affine-Niels of -R = (-x, y, -t).
    acc = affine_niels_add(acc, to_affine_niels(point_neg(r_pt)))
    acc = point_double(point_double(point_double(acc)))
    return is_identity(acc) & ok_a & ok_r
