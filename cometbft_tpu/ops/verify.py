"""Host <-> device glue for batched ed25519 verification.

Split of labor (TPU-first):

* Host (numpy, vectorized): byte unpacking, limb packing, the SHA-512
  challenge k = SHA512(R || A || M) mod L (byte-serial, C-speed, irrelevant
  cost next to the curve math), canonicality check S < L, batch padding.
* Device (jax, ops.curve.verify_kernel): point decompression, the
  ~5k-field-mul double-scalar ladder per signature, validity bitmap.

Batches are padded to shape buckets (powers of two) so each bucket compiles
once and stays cached -- ragged per-round batch sizes (validator sets churn)
must not retrigger XLA compilation in the consensus hot loop (reference
behavior this replaces: per-round crypto/batch.BatchVerifier construction in
types/validation.go:153-257).
"""

from __future__ import annotations

from functools import lru_cache

import numpy as np

import jax
import jax.numpy as jnp

from ..crypto import ed25519_ref
from . import curve, field

L = curve.L
_MIN_BUCKET = 8
_MAX_BUCKET = 1 << 14

# (255, 20) bit->limb packing matrix: bit 13*i + j contributes 2^j to limb i.
_BIT_TO_LIMB = np.zeros((255, field.NLIMB), np.int32)
for _bit in range(255):
    _BIT_TO_LIMB[_bit, _bit // field.BITS] = 1 << (_bit % field.BITS)


def bucket_size(n: int) -> int:
    """Smallest compile-shape bucket holding n (pow2, then 16k multiples)."""
    if n > _MAX_BUCKET:
        return (n + _MAX_BUCKET - 1) // _MAX_BUCKET * _MAX_BUCKET
    b = _MIN_BUCKET
    while b < n:
        b *= 2
    return b


def _unpack_le_bits(arr: np.ndarray) -> np.ndarray:
    """(N, 32) uint8 -> (N, 256) bits, little-endian bit order."""
    return np.unpackbits(arr, axis=1, bitorder="little")


def pack_inputs(pubkeys, msgs, sigs):
    """Vectorized host-side packing of (pubkey, msg, sig) triples.

    Returns (arrays dict for verify_kernel, host_ok mask). Malformed inputs
    (wrong lengths, non-canonical S >= L) get host_ok=False and dummy lanes.
    """
    n = len(pubkeys)
    host_ok = np.ones(n, bool)
    pk = np.zeros((n, 32), np.uint8)
    rr = np.zeros((n, 32), np.uint8)
    ss = np.zeros((n, 32), np.uint8)
    kneg = np.zeros((n, 32), np.uint8)
    for i in range(n):
        p_i, m_i, s_i = pubkeys[i], msgs[i], sigs[i]
        if len(p_i) != 32 or len(s_i) != 64:
            host_ok[i] = False
            continue
        s_int = int.from_bytes(s_i[32:], "little")
        if s_int >= L:  # S must be canonical even under ZIP-215
            host_ok[i] = False
            continue
        k = ed25519_ref.challenge_scalar(s_i[:32], p_i, m_i)
        pk[i] = np.frombuffer(p_i, np.uint8)
        rr[i] = np.frombuffer(s_i[:32], np.uint8)
        ss[i] = np.frombuffer(s_i[32:], np.uint8)
        kneg[i] = np.frombuffer(((L - k) % L).to_bytes(32, "little"), np.uint8)

    pk_bits = _unpack_le_bits(pk)
    rr_bits = _unpack_le_bits(rr)
    arrays = {
        "y_a": pk_bits[:, :255].astype(np.int32) @ _BIT_TO_LIMB,
        "sign_a": pk_bits[:, 255].astype(np.int32),
        "y_r": rr_bits[:, :255].astype(np.int32) @ _BIT_TO_LIMB,
        "sign_r": rr_bits[:, 255].astype(np.int32),
        # kernel wants MSB-first bit order
        "s_bits": np.ascontiguousarray(_unpack_le_bits(ss)[:, ::-1]).astype(
            np.int32
        ),
        "kneg_bits": np.ascontiguousarray(
            _unpack_le_bits(kneg)[:, ::-1]
        ).astype(np.int32),
    }
    return arrays, host_ok


def pad_arrays(arrays: dict, size: int) -> dict:
    n = arrays["y_a"].shape[0]
    if n == size:
        return arrays
    out = {}
    for k, v in arrays.items():
        pad = [(0, size - n)] + [(0, 0)] * (v.ndim - 1)
        out[k] = np.pad(v, pad)
    return out


@lru_cache(maxsize=None)
def _jitted_kernel():
    return jax.jit(
        lambda y_a, sign_a, y_r, sign_r, s_bits, kneg_bits: curve.verify_kernel(
            y_a, sign_a, y_r, sign_r, s_bits, kneg_bits
        )
    )


def verify_batch(pubkeys, msgs, sigs) -> tuple[bool, np.ndarray]:
    """Verify a batch of ed25519 signatures on device.

    Returns (all_valid, per_signature_validity) -- the contract of the Go
    engine's crypto.BatchVerifier.Verify (crypto/crypto.go:45-54), including
    per-lane results so callers can attribute failures without a second pass
    (types/validation.go:243-250's find-first-invalid fallback).
    """
    n = len(pubkeys)
    if n == 0:
        return True, np.zeros(0, bool)
    arrays, host_ok = pack_inputs(pubkeys, msgs, sigs)
    size = bucket_size(n)
    padded = pad_arrays(arrays, size)
    device_ok = np.asarray(_jitted_kernel()(**padded))[:n]
    valid = device_ok & host_ok
    return bool(valid.all()), valid
