"""Host <-> device glue for batched ed25519 verification.

Split of labor (TPU-first):

* Host (numpy, vectorized): byte unpacking, limb packing, the SHA-512
  challenge k = SHA512(R || A || M) mod L (byte-serial, C-speed, irrelevant
  cost next to the curve math), canonicality check S < L, batch padding.
  The only per-lane Python work is the hash + two bigint ops; all byte ->
  bit -> limb/nibble conversion is bulk numpy.
* Device (jax, ops.curve.verify_kernel): point decompression, the
  ~3k-field-mul windowed double-scalar ladder per signature, validity bitmap.

Batches are padded to shape buckets (powers of two) so each bucket compiles
once and stays cached — ragged per-round batch sizes (validator sets churn)
must not retrigger XLA compilation in the consensus hot loop (reference
behavior this replaces: per-round crypto/batch.BatchVerifier construction in
types/validation.go:153-257).

Array layout: batch axis LAST everywhere (y limbs (20, N), scalars (64, N)
nibbles) — see ops/field.py for why batch-minor wins on TPU.
"""

from __future__ import annotations

import time

from ..libs import devstats as libdevstats
from ..libs.accel import ACCELERATOR_BACKENDS
from ..libs import metrics as libmetrics
from ..libs import sync as libsync
from collections import OrderedDict, deque
from functools import lru_cache

import numpy as np

import jax

from ..crypto import ed25519_ref
from . import curve, field

L = curve.L
_MIN_BUCKET = 8

_LIMB_WEIGHTS = (1 << np.arange(field.BITS, dtype=np.int32))  # (13,)
_NIB_WEIGHTS = np.array([1, 2, 4, 8], np.int32)


def bucket_size(n: int) -> int:
    """Smallest compile-shape bucket holding n (8 <= bucket <= _CHUNK):
    powers of two plus the 3*2^k midpoints that are multiples of the
    512-lane Pallas block (1536, 3072, 6144, 12288).

    Mid buckets cut worst-case padding from 2x toward 1.33x where the
    kernel time is lane-proportional — a 10k-lane light-client commit
    pads to 12288, not 16384 (measured 77 ms vs 120 ms on a v5e).
    Smaller midpoints are skipped: they are not block-multiples (the
    Pallas wrappers require n % 512 == 0 at or above one block), and
    sub-1024 batches route host anyway. Batches past _CHUNK never reach
    here — verify_bytes_async splits them into pipelined _CHUNK-lane
    launches first.
    """
    assert n <= _CHUNK, n
    b = _MIN_BUCKET
    while b < n:
        mid = b + b // 2
        if mid >= n and mid % 512 == 0:
            return mid
        b *= 2
    return b


def _le_bits(arr: np.ndarray) -> np.ndarray:
    """(N, 32) uint8 -> (N, 256) bits, little-endian bit order."""
    return np.unpackbits(arr, axis=1, bitorder="little")


def _msb_nibbles(arr: np.ndarray) -> np.ndarray:
    """(N, 32) uint8 little-endian scalars -> (64, N) 4-bit windows MSB-first."""
    bits = _le_bits(arr).reshape(arr.shape[0], 64, 4)
    nibs = (bits.astype(np.int32) * _NIB_WEIGHTS).sum(axis=2)  # LSB-first
    return np.ascontiguousarray(nibs[:, ::-1].T)


def _y_limbs(bits: np.ndarray) -> np.ndarray:
    """(N, 256) little-endian bits -> (20, N) 13-bit y limbs.

    Reshape + tiny reduce instead of a (255, 20) matmul: numpy integer
    matmul has no BLAS path and was the dominant packing cost.
    """
    n = bits.shape[0]
    padded = np.zeros((n, field.NLIMB * field.BITS), np.int32)
    padded[:, :255] = bits[:, :255]
    limbs = (padded.reshape(n, field.NLIMB, field.BITS) * _LIMB_WEIGHTS).sum(
        axis=2, dtype=np.int32
    )
    return np.ascontiguousarray(limbs.T)


def pack_part_row(a_enc, r_enc, s_int: int, k_int: int) -> bytes:
    """One 128-byte wire row A | R | S | (-k mod L), little-endian.

    The layout's home for quad-shaped inputs: :func:`pack_parts` and
    the sr25519 lanes of the mixed verifier's fused packer build
    through it. The mixed verifier's ed25519 lanes assemble the SAME
    layout from raw wire bytes + the native packer's kneg (no int
    round-trip); byte equality of the two assemblies is pinned by
    tests/test_sr25519_secp.py::
    test_mixed_row_assembly_matches_pack_part_row.
    """
    return (
        bytes(a_enc)
        + bytes(r_enc)
        + s_int.to_bytes(32, "little")
        + ((L - k_int) % L).to_bytes(32, "little")
    )


def pack_parts(parts) -> tuple[np.ndarray, np.ndarray]:
    """Pack pre-decomposed verification quadruples into the wire format.

    ``parts[i]`` is (a_edwards32, r_edwards32, s_int, k_int) or None for a
    host-rejected lane. Used by signature schemes whose challenge is NOT
    SHA512(R||A||M) — sr25519 computes k from a merlin transcript on host
    and rides the same cofactored kernel (crypto/sr25519.py).
    """
    n = len(parts)
    host_ok = np.ones(n, bool)
    buf = np.zeros((128, n), np.uint8)
    for i, part in enumerate(parts):
        if part is None:
            host_ok[i] = False
            continue
        buf[:, i] = np.frombuffer(pack_part_row(*part), np.uint8)
    return buf, host_ok


def pack_bytes(pubkeys, msgs, sigs) -> tuple[np.ndarray, np.ndarray]:
    """Host-side packing to the compact device wire format.

    Returns (buf (128, n) uint8, host_ok (n,) bool). Rows 0-31 pubkey,
    32-63 R, 64-95 S, 96-127 (-k mod L), all little-endian bytes; the
    device unpacks bits/limbs/nibbles itself (:func:`unpack_on_device`).
    Shipping 128 B/sig instead of ~680 B of pre-unpacked int32 limbs cuts
    the host->HBM transfer ~5x — the transfer is a material share of small-
    batch latency through the device relay. Malformed inputs (wrong
    lengths, non-canonical S >= L) get host_ok=False and dummy lanes.
    """
    n = len(pubkeys)
    native = _pack_bytes_native(pubkeys, msgs, sigs, n)
    if native is not None:
        return native
    host_ok = np.ones(n, bool)
    pk_buf = bytearray(32 * n)
    rr_buf = bytearray(32 * n)
    ss_buf = bytearray(32 * n)
    kneg_buf = bytearray(32 * n)
    # One tight Python loop for the parts numpy can't do: variable-length
    # guards, the SHA-512 challenge, and 256-bit canonicality/modular ops.
    challenge = ed25519_ref.challenge_scalar
    for i in range(n):
        p_i, s_i = pubkeys[i], sigs[i]
        if len(p_i) != 32 or len(s_i) != 64:
            host_ok[i] = False
            continue
        s_int = int.from_bytes(s_i[32:], "little")
        if s_int >= L:  # S must be canonical even under ZIP-215
            host_ok[i] = False
            continue
        k = challenge(s_i[:32], p_i, msgs[i])
        o = 32 * i
        pk_buf[o : o + 32] = p_i
        rr_buf[o : o + 32] = s_i[:32]
        ss_buf[o : o + 32] = s_i[32:]
        kneg_buf[o : o + 32] = ((L - k) % L).to_bytes(32, "little")

    rows = [
        np.frombuffer(bytes(b), np.uint8).reshape(n, 32).T
        for b in (pk_buf, rr_buf, ss_buf, kneg_buf)
    ]
    return np.ascontiguousarray(np.concatenate(rows, axis=0)), host_ok


_Z32 = bytes(32)
_Z96 = bytes(96)


def _pack_bytes_native(pubkeys, msgs, sigs, n: int):
    """pack_bytes via the native challenge engine; None to fall back.

    The Python loop above costs ~9 us/lane (SHA-512 + bigint mod +
    per-lane buffer writes); the C path (native/edbatch.cpp
    edb_pack_challenges) does the per-lane work in ~1.5 us, leaving
    only bulk joins here. Malformed lanes keep the same semantics:
    host_ok False, zeroed rows.
    """
    from ..crypto import host_batch

    if not host_batch.available():
        return None
    host_ok = np.ones(n, bool)
    recs = []
    msg_parts = []
    lens = np.zeros(n, np.uint64)  # host-staging: message byte lengths
    # for the C packer's offset table; never shipped to the device
    for i in range(n):
        p_i, s_i = pubkeys[i], sigs[i]
        if len(p_i) != 32 or len(s_i) != 64:
            host_ok[i] = False
            recs.append(_Z96)
            msg_parts.append(b"")
            continue
        recs.append(bytes(p_i) + bytes(s_i))
        m = bytes(msgs[i])
        msg_parts.append(m)
        lens[i] = len(m)
    recs_blob = b"".join(recs)
    msgs_blob = b"".join(msg_parts)
    offs = np.zeros(n + 1, np.uint64)  # host-staging: byte offsets into
    # msgs_blob for native/edbatch.cpp (size_t ABI); never device-bound
    np.cumsum(lens, out=offs[1:])
    out = host_batch.pack_challenges(recs_blob, msgs_blob, offs, n)
    if out is None:
        return None
    kneg_blob, s_ok = out
    rec_arr = np.frombuffer(recs_blob, np.uint8).reshape(n, 96)
    kneg_arr = np.frombuffer(kneg_blob, np.uint8).reshape(n, 32)
    buf = np.ascontiguousarray(
        np.concatenate([rec_arr, kneg_arr], axis=1).T
    )
    host_ok &= s_ok
    # zero the rows of malformed/non-canonical lanes (legacy semantics:
    # the kernel sees dummy data there; host_ok masks the verdict)
    bad = ~host_ok
    if bad.any():
        buf[:, bad] = 0
    return buf, host_ok


def pack_inputs(pubkeys, msgs, sigs):
    """Host-side packing of (pubkey, msg, sig) triples, batch axis last.

    Returns (arrays dict for verify_kernel, host_ok mask). Used by callers
    that need the unpacked limb arrays on host (e.g. the sharded multi-chip
    path); the single-chip fast path ships :func:`pack_bytes` instead.
    """
    buf, host_ok = pack_bytes(pubkeys, msgs, sigs)
    n = buf.shape[1]
    pk_bits = _le_bits(np.ascontiguousarray(buf[0:32].T))
    rr_bits = _le_bits(np.ascontiguousarray(buf[32:64].T))
    arrays = {
        "y_a": _y_limbs(pk_bits),
        "sign_a": pk_bits[:, 255].astype(np.int32),
        "y_r": _y_limbs(rr_bits),
        "sign_r": rr_bits[:, 255].astype(np.int32),
        "s_nibs": _msb_nibbles(np.ascontiguousarray(buf[64:96].T)),
        "kneg_nibs": _msb_nibbles(np.ascontiguousarray(buf[96:128].T)),
    }
    return arrays, host_ok


# -- device-side byte unpacking helpers (shared by the uncached, cached
# and builder unpackers; a fork here would silently diverge the paths) --


def _dev_le_bits(rows):  # (32, N) int32 -> (256, N)
    import jax.numpy as jnp

    shifts = jnp.arange(8, dtype=jnp.int32).reshape(1, 8, 1)
    bits = (rows[:, None, :] >> shifts) & 1
    return bits.reshape(256, rows.shape[-1])


def _dev_y_limbs(bits):  # (256, N) -> (20, N)
    import jax.numpy as jnp

    n = bits.shape[-1]
    padded = jnp.concatenate(
        [bits[:255], jnp.zeros((5, n), jnp.int32)], axis=0
    )
    w = (1 << jnp.arange(field.BITS, dtype=jnp.int32)).reshape(1, -1, 1)
    return jnp.sum(padded.reshape(field.NLIMB, field.BITS, n) * w, axis=1)


def _dev_msb_nibbles(rows):  # (32, N) -> (64, N), MSB-first windows
    import jax.numpy as jnp

    lo = rows & 15
    hi = rows >> 4
    nibs = jnp.stack([lo, hi], axis=1).reshape(64, rows.shape[-1])
    return nibs[::-1]


def unpack_on_device(buf):
    """(128, N) uint8 wire buffer -> verify_kernel arrays, on device.

    Bit/limb/nibble unpacking is a handful of shifts and tiny reduces —
    negligible VPU work that saves ~5x on the host->HBM transfer.
    """
    import jax.numpy as jnp

    b = buf.astype(jnp.int32)
    pk_bits = _dev_le_bits(b[0:32])
    rr_bits = _dev_le_bits(b[32:64])
    return {
        "y_a": _dev_y_limbs(pk_bits),
        "sign_a": pk_bits[255],
        "y_r": _dev_y_limbs(rr_bits),
        "sign_r": rr_bits[255],
        "s_nibs": _dev_msb_nibbles(b[64:96]),
        "kneg_nibs": _dev_msb_nibbles(b[96:128]),
    }


# -- verdict bit-packing ---------------------------------------------------
# The ok-mask is the ONLY payload the host consumes from a verify
# launch, and it used to ride back as one bool byte per lane. Packing
# it into uint8 mask words ON DEVICE (a reshape + tiny weighted reduce,
# fused into the kernel's jit program) shrinks the d2h readback 8x —
# the readback edge is latency-bound through the relay, and
# device_transfer_bytes_total{d2h} now reconciles at bucket/8 bytes per
# launch (tests/test_observability.py::TestNoRecompileGuard). Every
# lane count here is a shape bucket, so N % 8 == 0 always holds.

_OK_BIT_WEIGHTS = np.array([1, 2, 4, 8, 16, 32, 64, 128], np.int32)


def _pack_ok_bits(ok):
    """(N,) device bool -> (N//8,) uint8, little-endian bit order."""
    import jax.numpy as jnp

    bits = ok.astype(jnp.int32).reshape(-1, 8)
    w = jnp.asarray(_OK_BIT_WEIGHTS)
    return jnp.sum(bits * w, axis=1).astype(jnp.uint8)


def unpack_ok_bits(packed: np.ndarray, n: int) -> np.ndarray:
    """Host inverse of :func:`_pack_ok_bits`: (n,) bool validity."""
    return np.unpackbits(
        np.ascontiguousarray(packed, np.uint8), bitorder="little"
    )[:n].astype(bool)


def _kernel_from_bytes(buf):
    return _pack_ok_bits(curve.verify_kernel(**unpack_on_device(buf)))


def _kernel_from_bytes8(buf):
    """8-bit fixed-base-window lowering (COMETBFT_TPU_KERNEL=xla8).

    S rides as raw little-endian bytes: byte j IS the 8-bit window of
    weight 2^(8j), so the wire format needs no new rows."""
    import jax.numpy as jnp

    b = buf.astype(jnp.int32)
    pk_bits = _dev_le_bits(b[0:32])
    rr_bits = _dev_le_bits(b[32:64])
    return _pack_ok_bits(curve.verify_kernel8(
        y_a=_dev_y_limbs(pk_bits),
        sign_a=pk_bits[255],
        y_r=_dev_y_limbs(rr_bits),
        sign_r=rr_bits[255],
        s_bytes=b[64:96],
        kneg_nibs=_dev_msb_nibbles(b[96:128]),
    ))


# ------------------------------------------------------------------ cache
# HBM-resident expanded-pubkey cache. The reference keeps a 4096-entry
# LRU of expanded pubkeys because validators recur every round
# (crypto/ed25519/ed25519.go:31,56); the TPU analog caches each key's
# DECOMPRESSED point + 16-entry Niels table in a device arena, so a
# steady-state commit verify ships only (R, S, -k) plus uint16 slot
# indices and skips the ~254-squaring sqrt chain and the 14-point-op
# table build entirely (~11% of per-signature muls, SURVEY §7(c)).


def _unpack_rsk_on_device(buf):
    """(96, N) uint8 rows R|S|kneg -> cached-kernel arrays, on device."""
    import jax.numpy as jnp

    b = buf.astype(jnp.int32)
    rr_bits = _dev_le_bits(b[0:32])
    return {
        "y_r": _dev_y_limbs(rr_bits),
        "sign_r": rr_bits[255],
        "s_nibs": _dev_msb_nibbles(b[32:64]),
        "kneg_nibs": _dev_msb_nibbles(b[64:96]),
    }


def _cached_kernel(arena, arena_ok, idxs, buf):
    arrays = _unpack_rsk_on_device(buf)
    table = arena[:, :, :, idxs]
    ok = curve.verify_kernel_cached(table, **arrays)
    return _pack_ok_bits(ok & arena_ok[idxs])


def _cached_kernel8(arena, arena_ok, idxs, buf):
    import jax.numpy as jnp

    b = buf.astype(jnp.int32)
    rr_bits = _dev_le_bits(b[0:32])
    table = arena[:, :, :, idxs]
    ok = curve.verify_kernel8_cached(
        table,
        y_r=_dev_y_limbs(rr_bits),
        sign_r=rr_bits[255],
        s_bytes=b[32:64],
        kneg_nibs=_dev_msb_nibbles(b[64:96]),
    )
    return _pack_ok_bits(ok & arena_ok[idxs])


def _cached_kernel_pallas(arena, arena_ok, idxs, buf):
    from . import pallas_verify

    arrays = _unpack_rsk_on_device(buf)
    table = arena[:, :, :, idxs]
    return _pack_ok_bits(pallas_verify.verify_kernel_cached(
        table, arena_ok[idxs], **arrays
    ))


def _cached_kernel_pallas8(arena, arena_ok, idxs, buf):
    import jax.numpy as jnp

    from . import pallas_verify

    b = buf.astype(jnp.int32)
    rr_bits = _dev_le_bits(b[0:32])
    table = arena[:, :, :, idxs]
    return _pack_ok_bits(pallas_verify.verify_kernel8_cached(
        table,
        arena_ok[idxs],
        y_r=_dev_y_limbs(rr_bits),
        sign_r=rr_bits[255],
        s_bytes=b[32:64],
        kneg_nibs=_dev_msb_nibbles(b[64:96]),
    ))


def _builder_kernel(buf):
    """(32, M) uint8 pubkey bytes -> (table, ok) for the arena."""
    import jax.numpy as jnp

    bits = _dev_le_bits(buf.astype(jnp.int32))
    return curve.build_pubkey_tables(_dev_y_limbs(bits), bits[255])


def _scatter_kernel(arena, arena_ok, slots, tables, oks):
    arena = arena.at[:, :, :, slots].set(tables)
    arena_ok = arena_ok.at[slots].set(oks)
    return arena, arena_ok


def _donatable(argnums: tuple[int, ...]) -> tuple[int, ...]:
    """Donate per-launch input buffers on accelerator backends only.

    Donation lets XLA reuse the wire buffer's HBM for ladder temporaries
    (the buffer is dead after unpacking); on the CPU test backend
    donation is unsupported and every call would warn, so gate it.
    """
    try:
        return argnums if jax.default_backend() in ACCELERATOR_BACKENDS else ()
    except Exception:
        return ()


# ------------------------------------------------- persistent lane arenas
# The wire rows of every launch used to arrive as fresh host numpy
# arrays: each dispatch paid an implicit host->device transfer INTO A
# FRESH DEVICE ALLOCATION, and the buffer died after unpacking. The
# LaneArena keeps one persistent, donated device staging buffer per
# (kind, shape) — a window writes its rows into the arena through a
# jitted ``lax.dynamic_update_slice`` whose FIRST argument (the previous
# arena) is donated, so steady-state launches reuse the same device
# allocation instead of minting one per window and never call
# ``jax.device_put`` (the one device_put below runs once per (kind,
# bucket), at arena creation). Two slots ping-pong per key so staging
# window N+1 never writes into a buffer window N's launch still reads.
#
# COMETBFT_TPU_LANE_ARENA: "auto" (default) stages only on accelerator
# backends — on the CPU test backend donation is unsupported, so the
# arena would only add a copy; "1" forces (tests exercise the full
# staging path on XLA-CPU), "0" disables.

_LANE_ARENA_MODE = None


def _lane_arena_enabled() -> bool:
    global _LANE_ARENA_MODE
    if _LANE_ARENA_MODE is None:
        import os

        _LANE_ARENA_MODE = os.environ.get("COMETBFT_TPU_LANE_ARENA", "auto")
    mode = _LANE_ARENA_MODE
    if mode == "0":
        return False
    if mode == "1":
        return True
    try:
        return jax.default_backend() in ACCELERATOR_BACKENDS
    except Exception:
        return False


def _stage_write(arena, rows):
    """Write one window's rows into the staging arena, in place when the
    arena is donated (full-shape dynamic_update_slice: XLA lowers it to
    a copy into the donated buffer — no fresh allocation)."""
    from jax import lax

    return lax.dynamic_update_slice(
        arena, rows, tuple(0 for _ in rows.shape)
    )


@lru_cache(maxsize=None)
def _staging_jit(kind: str):
    _enable_compilation_cache()
    return libdevstats.track(
        "stage." + kind,
        jax.jit(_stage_write, donate_argnums=_donatable((0,))),
        axis=0,
    )


class LaneArena:
    """Persistent device staging buffers for per-launch wire rows.

    ``stage(kind, buf)`` returns a device-resident copy of ``buf``
    whose allocation is recycled window-over-window (donation of the
    previous arena slot). Kernels consuming a staged buffer must NOT
    donate it — the arena owns the allocation across launches; the
    dispatchers below select non-donating jit variants when staging is
    on. Thread-safe: verify paths stage from the coalescer executor,
    consensus, blocksync and RPC threads concurrently; the mutex guards
    only the slot bookkeeping, never a device wait (the staging jit
    dispatch is asynchronous).
    """

    # slots per key: window N+1 stages into the OTHER slot while window
    # N's launch may still read its staged rows (the readback drain
    # overlaps execute of N+1 with d2h of N)
    PING_PONG = 2

    def __init__(self) -> None:
        self._lock = libsync.Mutex("ops.verify._lane_mtx")
        self._bufs: dict[tuple, deque] = {}
        self.stages = 0  # total staging operations
        self.reuses = 0  # stages that recycled a donated arena slot
        self.allocs = 0  # one-time arena-slot allocations

    def stage(self, kind: str, buf):
        key = (kind, buf.shape, buf.dtype.str)
        with self._lock:
            self.stages += 1
            slots = self._bufs.setdefault(key, deque())
            if len(slots) < self.PING_PONG:
                self.allocs += 1
                staged = jax.device_put(buf)  # once per (kind, bucket) slot
            else:
                self.reuses += 1
                staged = _staging_jit(kind)(slots.popleft(), buf)
            slots.append(staged)
            return staged

    def buffers(self) -> int:
        # snapshot under the lock: a concurrent stage() inserting a new
        # (kind, shape) key must not resize the dict under this walk
        # (the devstats scrape path calls these from other threads)
        with self._lock:
            return sum(len(v) for v in self._bufs.values())

    def resident_bytes(self) -> int:
        with self._lock:
            arrs = [arr for slots in self._bufs.values() for arr in slots]
        return sum(int(getattr(arr, "nbytes", 0) or 0) for arr in arrs)

    def clear(self) -> None:
        """Drop every staged slot (tests; a backend teardown)."""
        with self._lock:
            self._bufs.clear()


_LANE_ARENA = LaneArena()


def _stage_wire(kind: str, buf):
    """Stage ``buf`` into the lane arena when enabled; None = launch
    from host memory (arena off, or staging faulted — staging is an
    optimization and must never kill a launch)."""
    if not _lane_arena_enabled():
        return None
    try:
        return _LANE_ARENA.stage(kind, buf)
    except Exception:
        return None


# Buckets at or below this get a DEDICATED jit per (flavor, bucket):
# their own executable cache, their own devstats kernel identity
# (``verify.xla.g64``), and a compile traced with exactly that grid —
# so a 64-lane coalescer window never shares (or walks) the big-bucket
# kernel's signature cache, and the per-window fixed cost of small
# grids is attributable per bucket in the 9_device_floor breakdown.
_SMALL_GRID_MAX = 256


def _small_grid(bucket: int):
    return bucket if bucket <= _SMALL_GRID_MAX else None


@lru_cache(maxsize=None)
def _cached_jits():
    _enable_compilation_cache()
    # NOTE: the scatter deliberately does NOT donate the arena — a verify
    # thread may hold the previous arena reference (handed out by lookup)
    # and dispatch against it after the update; donation would invalidate
    # that buffer under it. Updates are rare (new validator keys), the
    # ~21 MB copy is cheap. (The verify-side jits live in
    # _jitted_cached_kernel, keyed by lowering.)
    # devstats.track wraps each jit for compile accounting (axis = the
    # positional arg whose last dim is the lane bucket): every XLA
    # compile lands in xla_compile_total{kernel,bucket} and the
    # no-recompile tier-1 guard.
    return (
        libdevstats.track("arena.build", jax.jit(_builder_kernel), axis=0),
        libdevstats.track(
            "arena.scatter", jax.jit(_scatter_kernel), axis=3
        ),
    )


@lru_cache(maxsize=None)
def _jitted_cached_kernel(which: str, donate: bool = True, grid=None):
    """The cached-table jit for one (flavor, donation, grid) triple.

    ``donate=False`` variants serve lane-arena-staged launches (the
    staged rows must survive the launch — the arena owns them);
    ``grid`` pins a dedicated small-bucket jit (see _SMALL_GRID_MAX):
    its own executable cache and its own devstats kernel name, so
    small-window compiles and launches are attributable per bucket.
    """
    _enable_compilation_cache()
    flavors = {
        "pallas": _cached_kernel_pallas,
        "pallas8": _cached_kernel_pallas8,
        "xla8": _cached_kernel8,
    }
    fn = flavors.get(which, _cached_kernel)
    label = which if which in flavors else "xla"
    if grid is not None:
        label = f"{label}.g{grid}"
    # donate the per-launch R|S|kneg wire rows (arg 3) — NEVER the arena
    return libdevstats.track(
        "verify_cached." + label,
        jax.jit(fn, donate_argnums=_donatable((3,)) if donate else ()),
        axis=3,
    )


def _run_cached_kernel(arena, arena_ok, idxs, buf):
    """Cached-table launch with the same Pallas/XLA selection and Mosaic
    fallback discipline as :func:`_run_kernel`. Wire rows and slot
    indices go through the persistent lane arena when enabled; small
    buckets launch their dedicated small-grid jits."""
    staged_buf = _stage_wire("rsk", buf)
    staged_idx = _stage_wire("idx", idxs) if staged_buf is not None else None
    if staged_buf is not None and staged_idx is None:
        staged_buf = None  # stage both or neither
    donate = staged_buf is None
    buf_in = buf if donate else staged_buf
    idx_in = idxs if staged_idx is None else staged_idx
    grid = _small_grid(buf.shape[1])
    if buf.shape[1] >= _PALLAS_MIN_LANES and _pallas_wanted():
        for which in _pallas_candidates():
            try:
                out = _jitted_cached_kernel(which, donate, grid)(
                    arena, arena_ok, idx_in, buf_in
                )
            except Exception as e:
                _note_pallas_broken(which, e)
            else:
                # the arena stays HBM-resident; only the wire rows and
                # the slot indices cross the PCIe/tunnel edge
                libdevstats.record_h2d(buf.nbytes + idxs.nbytes)
                return out, which
    out = _jitted_cached_kernel(_xla_which(), donate, grid)(
        arena, arena_ok, idx_in, buf_in
    )
    libdevstats.record_h2d(buf.nbytes + idxs.nbytes)
    return out, None


class PubkeyTableCache:
    """LRU arena of expanded pubkey tables resident on device.

    ``lookup`` maps pubkey byte strings to slot indices, building missing
    entries in one bucketed launch and scattering them into the arena.
    Thread-safe: verify paths run from consensus, blocksync and RPC
    threads concurrently; a scatter produces a NEW arena value (no
    donation), so a verify dispatched against the previous arena keeps a
    live buffer and gathers never race an eviction.
    """

    # One full _CHUNK of distinct signers stays cacheable (a 10k-lane
    # light-client batch must not bail to the uncached path just because
    # it exceeds the arena). 4x the reference's 4096-entry LRU
    # (crypto/ed25519/ed25519.go:31) — theirs sizes a CPU heap, this
    # sizes HBM: ~84 MB of a v5e's 16 GB.
    CAPACITY = 16384

    def __init__(self, capacity: int = CAPACITY):
        self.capacity = capacity
        # Slot indices ship host->device on EVERY cached-path window;
        # use the narrowest dtype that can address capacity+1 slots
        # (the +1 scratch slot included): uint16 halves the per-lane
        # index wire cost vs int32 for every arena up to 65535 slots.
        # The no-recompile transfer reconciliation pins the reduction.
        self.idx_dtype = (
            np.uint16 if capacity + 1 <= 1 << 16 else np.int32
        )
        self._lock = libsync.Mutex("ops.verify._lock")
        self._slots: OrderedDict[bytes, int] = OrderedDict()
        self._arena = None
        self._arena_ok = None
        self.hits = 0
        self.misses = 0
        self.builds = 0  # builder launches (device round trips)
        self.evictions = 0  # LRU slot reclaims (devstats exports these)

    def _ensure_arena(self):
        import jax.numpy as jnp

        if self._arena is None:
            # +1 scratch slot: bucket-padding lanes of a build scatter
            # there (duplicate scatter indices have an unspecified
            # winner, so pads must never alias a real slot)
            self._arena = jnp.zeros(
                (curve.TSIZE, 4, field.NLIMB, self.capacity + 1), jnp.int32
            )
            self._arena_ok = jnp.zeros((self.capacity + 1,), bool)

    def lookup(self, pubkeys):
        """Per-pubkey slot indices into the arena, building misses.

        Returns (idxs (N,) int32, arena, arena_ok), or None when the
        call's UNIQUE keys exceed the arena (every lane of one gather
        needs a live slot — callers fall back to the uncached kernel).
        Keys used by the current call are pinned: eviction never frees a
        slot this call's gather will read.

        Locking: the builder launch (a full device round trip for new
        keys) runs OUTSIDE the lock, so a cache miss on one path
        (a new validator key seen by RPC) never stalls concurrent
        hit-only lookups from consensus/blocksync. Slot assignment,
        the scatter, and the final (idxs, arena, arena_ok) capture all
        happen under one lock hold, so a concurrent update can't tear
        the pairing; tables are a pure function of the key, so two
        threads racing to build the same key scatter identical values.
        """
        builder, scatter = _cached_jits()
        keys = [bytes(pk) for pk in pubkeys]
        in_use = set(keys)
        if len(in_use) > self.capacity:
            return None
        built: list[tuple[list[bytes], object, object]] = []
        built_keys: set[bytes] = set()
        # Bounded retries: under sustained eviction churn (concurrent
        # callers with disjoint key sets larger than capacity) a thread
        # could otherwise rebuild evicted keys forever. Three builder
        # launches is already pathological; give up to the uncached
        # kernel path rather than spin.
        for _attempt in range(4):
            with self._lock:
                self._ensure_arena()
                to_build = [
                    pk
                    for pk in dict.fromkeys(keys)
                    if pk not in self._slots and pk not in built_keys
                ]
                if not to_build:
                    for batch_keys, tables, oks in built:
                        size = int(tables.shape[-1])
                        slots = np.full(
                            size, self.capacity, self.idx_dtype
                        )  # pads -> scratch slot
                        for j, pk in enumerate(batch_keys):
                            slot = self._slots.get(pk)
                            if slot is None:
                                if len(self._slots) >= self.capacity:
                                    # evict the oldest key NOT referenced
                                    # by this call (an in-use eviction
                                    # would redirect an already-assigned
                                    # idx to a foreign table)
                                    slot = None
                                    for old in self._slots:
                                        if old not in in_use:
                                            slot = self._slots.pop(old)
                                            self.evictions += 1
                                            break
                                    # unreachable: len(in_use) <=
                                    # capacity guarantees an evictable
                                    # slot exists
                                    assert slot is not None
                                else:
                                    slot = len(self._slots)
                                self._slots[pk] = slot
                            slots[j] = slot
                        self._arena, self._arena_ok = scatter(
                            self._arena, self._arena_ok, slots, tables, oks
                        )
                    idxs = np.empty(len(keys), self.idx_dtype)
                    for i, pk in enumerate(keys):
                        idxs[i] = self._slots[pk]
                        self._slots.move_to_end(pk)
                        if pk in built_keys:
                            self.misses += 1
                        else:
                            self.hits += 1
                    return idxs, self._arena, self._arena_ok
            if _attempt == 3:
                break  # 3 builds done and keys STILL missing: stop
            # Outside the lock: one bucketed builder launch for the keys
            # still missing. A key evicted between iterations (another
            # thread filling the arena mid-build) sends us around again;
            # with in_use pinned per call that is vanishingly rare.
            m = len(to_build)
            size = _MIN_BUCKET
            while size < m:
                size *= 2
            buf = np.zeros((32, size), np.uint8)
            for j, pk in enumerate(to_build):
                if len(pk) == 32:
                    buf[:, j] = np.frombuffer(pk, np.uint8)
            self.builds += 1
            tables, oks = builder(buf)
            libdevstats.record_h2d(buf.nbytes)
            import jax.numpy as jnp

            host_wellformed = np.array(
                [len(pk) == 32 for pk in to_build] + [True] * (size - m),
                bool,
            )
            oks = jnp.logical_and(oks, jnp.asarray(host_wellformed))
            built.append((to_build, tables, oks))
            built_keys.update(to_build)
        return None  # churn won the race 3x: uncached kernel fallback


_PUBKEY_CACHE = PubkeyTableCache()


def prestage_pubkeys(pubkeys) -> int:
    """Warm the expanded-pubkey arena ahead of verification.

    Called from the consensus FSM at enter-new-round (round-3 verdict
    task 3): with the validator set's tables already HBM-resident, a
    commit verify ships only R|S|k per lane and the steady-state path
    performs ZERO builder launches. Returns the number of builder
    launches this warm-up performed (0 = already staged).

    COMETBFT_TPU_PRESTAGE: "auto" (default) warms only on accelerator
    backends — on the CPU test mesh the production sub-threshold path is
    the host verifier and an eager device build would only slow tests;
    "1" forces (tests), "0" disables.
    """
    import os

    mode = os.environ.get("COMETBFT_TPU_PRESTAGE", "auto")
    if mode == "0" or not _cache_enabled():
        return 0
    if mode != "1":
        try:
            if jax.default_backend() not in ACCELERATOR_BACKENDS:
                return 0
        except Exception:
            return 0
    keys = [bytes(pk) for pk in pubkeys][: _PUBKEY_CACHE.capacity]
    if not keys:
        return 0
    before = _PUBKEY_CACHE.builds
    try:
        _PUBKEY_CACHE.lookup(keys)
    except Exception:
        return 0  # warm-up must never take down the FSM
    return _PUBKEY_CACHE.builds - before


def _kernel_from_bytes_pallas(buf):
    from . import pallas_verify

    return _pack_ok_bits(pallas_verify.verify_kernel(**unpack_on_device(buf)))


def _kernel_from_bytes_pallas8(buf):
    import jax.numpy as jnp

    from . import pallas_verify

    b = buf.astype(jnp.int32)
    pk_bits = _dev_le_bits(b[0:32])
    rr_bits = _dev_le_bits(b[32:64])
    return _pack_ok_bits(pallas_verify.verify_kernel8(
        y_a=_dev_y_limbs(pk_bits),
        sign_a=pk_bits[255],
        y_r=_dev_y_limbs(rr_bits),
        sign_r=rr_bits[255],
        s_bytes=b[64:96],
        kneg_nibs=_dev_msb_nibbles(b[96:128]),
    ))


@lru_cache(maxsize=None)
def _enable_compilation_cache() -> None:
    """Persistent XLA compilation cache: the verify kernel compiles once
    per (backend, bucket) across ALL processes — node restarts, tests,
    CLI runs — instead of paying the 30-150 s XLA compile each boot."""
    import os

    cache_dir = os.environ.get(
        "COMETBFT_TPU_XLA_CACHE",
        os.path.join(
            os.path.expanduser("~"), ".cache", "cometbft_tpu_xla"
        ),
    )
    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1)
    except Exception:
        pass  # older jax or read-only fs: compiles stay in-process


@lru_cache(maxsize=None)
def _jitted_kernel(which: str = "xla", donate: bool = True, grid=None):
    """The uncached-path jit for one (flavor, donation, grid) triple —
    same contract as :func:`_jitted_cached_kernel`: ``donate=False``
    variants serve lane-arena-staged launches, ``grid`` pins a
    dedicated small-bucket jit with its own devstats identity."""
    _enable_compilation_cache()
    flavors = {
        "pallas": _kernel_from_bytes_pallas,
        "pallas8": _kernel_from_bytes_pallas8,
        "xla8": _kernel_from_bytes8,
    }
    fn = flavors.get(which, _kernel_from_bytes)
    label = which if which in flavors else "xla"
    if grid is not None:
        label = f"{label}.g{grid}"
    return libdevstats.track(
        "verify." + label,
        jax.jit(fn, donate_argnums=_donatable((0,)) if donate else ()),
        axis=0,
    )


# Kernel selection: "auto" routes single-chip batches through the Pallas
# kernel on TPU backends (VMEM-resident ladder, ~2x the XLA lowering) and
# the XLA kernel elsewhere (CPU tests, virtual-device meshes — Pallas
# interpret mode is far slower than the XLA program there). Overridable
# for benchmarking via COMETBFT_TPU_KERNEL=pallas|xla|xla8 ("xla8" is
# the 8-bit fixed-base-window prototype: MXU one-hot selects, -11%
# field muls — see curve.fixed_base_sum8).
_KERNEL_MODE = None
_PALLAS_BROKEN: set = set()  # flavors that faulted in this process


def _kernel_mode() -> str:
    global _KERNEL_MODE
    if _KERNEL_MODE is None:
        import os

        _KERNEL_MODE = os.environ.get("COMETBFT_TPU_KERNEL", "auto")
    return _KERNEL_MODE


def _xla_which() -> str:
    """The non-Pallas lowering to use: the gated 8-bit prototype or the
    default joint 4-bit ladder. pallas8 falls back to xla8 (same window
    scheme) when Mosaic balks."""
    return "xla8" if _kernel_mode() in ("xla8", "pallas8") else "xla"


_UNSET = object()
_MEASURED_FLAVOR = _UNSET


def _measured_pallas_flavor():
    """The pallas flavor that won the last accelerator-measured kernel
    A/B (BENCH_CHIP_TABLE.json, config 10_kernel_ab; best of its
    cached/uncached numbers per flavor), or None without chip data.
    Same measured-knob discipline as crypto/batch._derive_host_threshold
    — the default kernel follows what the chip actually ran fastest,
    not a guess."""
    global _MEASURED_FLAVOR
    if _MEASURED_FLAVOR is not _UNSET:
        return _MEASURED_FLAVOR
    from ..libs import chip_table

    flavor = None
    row = chip_table.find_row(chip_table.load_chip_table(), "10_kernel_ab")
    if row is not None:
        best = {}
        for fl in ("pallas", "pallas8"):
            vals = [
                v
                for k, v in row.items()
                if k.startswith(fl + "_")
                and k.endswith("_sigs_per_sec")
                and isinstance(v, (int, float))
            ]
            if vals:
                best[fl] = max(vals)
        if best:
            flavor = max(best, key=best.get)
    _MEASURED_FLAVOR = flavor
    return flavor


def _pallas_candidates() -> list[str]:
    """Pallas flavors to try, best first, faulted flavors excluded.

    Explicit COMETBFT_TPU_KERNEL=pallas|pallas8 pins a single flavor
    (benchmarking wants THAT kernel, its XLA twin is the only
    fallback); auto tries the chip-measured winner first, then the
    sibling."""
    mode = _kernel_mode()
    if mode in ("pallas", "pallas8"):
        order = [mode]
    else:
        m = _measured_pallas_flavor()
        if m is None:
            order = ["pallas", "pallas8"]
        else:
            order = [m, "pallas8" if m == "pallas" else "pallas"]
    return [f for f in order if f not in _PALLAS_BROKEN]


def _pallas_wanted() -> bool:
    mode = _kernel_mode()
    if mode in ("pallas", "pallas8"):
        return True
    if mode in ("xla", "xla8"):
        return False
    try:
        return jax.default_backend() in ACCELERATOR_BACKENDS
    except Exception:
        return False


# Buckets below this stay on the XLA kernel even when Pallas is wanted:
# small-lane Mosaic layouts compile pathologically slowly and the launch
# is latency-bound there anyway (the host path owns batches < 768).
_PALLAS_MIN_LANES = 512


def _note_pallas_broken(which: str, e: Exception) -> None:
    _PALLAS_BROKEN.add(which)
    from ..libs import log as _log

    _log.default_logger().with_module("ops.verify").error(
        "pallas verify kernel failed; falling back",
        flavor=which,
        err=repr(e)[:200],
    )


def _run_kernel(buf):
    """Dispatch one bucket launch, falling back through the remaining
    pallas flavor and then XLA if Mosaic balks.

    Returns (device_array, flavor-or-None). jit dispatch is
    asynchronous, so a Mosaic *runtime* fault only surfaces when the
    result materializes — callers resolve through :func:`_materialize`,
    which marks the flavor broken and re-dispatches.
    """
    staged = _stage_wire("wire", buf)
    donate = staged is None
    buf_in = buf if donate else staged
    grid = _small_grid(buf.shape[1])
    if buf.shape[1] >= _PALLAS_MIN_LANES and _pallas_wanted():
        for which in _pallas_candidates():
            try:
                out = _jitted_kernel(which, donate, grid)(buf_in)
            except Exception as e:  # synchronous trace/compile failure
                _note_pallas_broken(which, e)
            else:
                libdevstats.record_h2d(buf.nbytes)
                return out, which
    out = _jitted_kernel(_xla_which(), donate, grid)(buf_in)
    libdevstats.record_h2d(buf.nbytes)
    return out, None


def _materialize(out, used_pallas, buf):
    """np.asarray(out) with device-side pallas faults rerouted: the
    faulting flavor is retired and the launch retried through
    :func:`_run_kernel` (sibling flavor, then XLA). Bounded — each
    retry removes a flavor; the XLA launch (used_pallas None) raises.

    The wire value is the bit-packed ok mask (:func:`_pack_ok_bits` —
    bucket/8 uint8 words, what record_d2h counts); the return value is
    the unpacked (bucket,) bool bitmap callers slice."""
    try:
        # cometlint: disable=CLNT002 -- THE sanctioned per-launch readback:
        # every async dispatch materializes exactly once, here
        arr = np.asarray(out)
    except Exception as e:
        if used_pallas is None:
            raise
        _note_pallas_broken(used_pallas, e)
        out2, which2 = _run_kernel(buf)
        return _materialize(out2, which2, buf)
    libdevstats.record_d2h(arr.nbytes)
    return unpack_ok_bits(arr, 8 * arr.shape[0])


# Measured on a v5e (round 5, Pallas kernel): the launch has a ~40-50 ms
# floor nearly independent of lane count up to 4096, then scales gently —
# 4096 lanes 40 ms, 8192 66 ms, 16384 120 ms (137k sigs/s). Chunking at
# 2048 therefore DOUBLED 4096-lane cost (two floor payments); one big
# launch wins everywhere measured. Batches past _CHUNK still split so a
# single dispatch stays bounded (compile shape, VMEM head-room).
_CHUNK = 16384

# verify_batch pipelines pack->dispatch at this granularity. Device time
# dominates host packing ~10:1, so the pipeline grain equals _CHUNK:
# splitting finer pays the launch floor again without hiding anything.
_PIPE_CHUNK = 16384


def verify_bytes_async(buf: np.ndarray, n: int):
    """Dispatch a packed wire buffer to the device without blocking.

    Returns a zero-arg closure that materializes the (n,) validity bitmap;
    callers can overlap host work (packing the next batch, consensus
    bookkeeping) with device execution and pay the readback sync once.
    Batches beyond the per-launch sweet spot are auto-chunked and
    pipelined.
    """
    if n > _CHUNK:
        outs = []
        for lo in range(0, n, _CHUNK):
            hi = min(lo + _CHUNK, n)
            piece = buf[:, lo:hi]
            # The tail chunk pads to its own pow-2 bucket, not a full
            # _CHUNK: a 64-lane remainder costs the ~40 ms launch floor
            # instead of a full 16384-lane launch (~120 ms).
            size = bucket_size(hi - lo)
            if hi - lo < size:
                piece = np.pad(piece, [(0, 0), (0, size - (hi - lo))])
            out, used_pallas = _run_kernel(piece)
            outs.append((out, used_pallas, piece, hi - lo))
        return lambda: np.concatenate(
            [_materialize(o, up, p)[:m] for o, up, p, m in outs]
        )
    size = bucket_size(n)
    if size != n:
        buf = np.pad(buf, [(0, 0), (0, size - n)])
    out, used_pallas = _run_kernel(buf)
    return lambda: _materialize(out, used_pallas, buf)[:n]


def _cache_enabled() -> bool:
    import os

    return os.environ.get("COMETBFT_TPU_PUBKEY_CACHE", "1") != "0"


def _shard_devices():
    """Devices to shard verify_batch over, or None for single-device.

    COMETBFT_TPU_SHARD: "1" forces sharding whenever >1 device exists
    (the CPU virtual-device tier), "0" disables, default "auto" shards
    only on real accelerator backends — the 8-device virtual CPU mesh
    used by the test suite must not silently reroute every unit test
    through pjit. SURVEY §2.9: production batches shard over the
    signature axis when the host has multiple chips.
    """
    import os

    mode = os.environ.get("COMETBFT_TPU_SHARD", "auto")
    if mode == "0":
        return None
    try:
        devs = jax.devices()
    except Exception:
        return None
    if len(devs) < 2:
        return None
    if mode != "1" and jax.default_backend() not in ACCELERATOR_BACKENDS:
        return None
    return devs


def _verify_batch_sharded(pubkeys, msgs, sigs, n_dev: int):
    """Shard one flat batch over the signature axis of the device mesh.

    Lanes are padded to n_dev x pow2 so each (device-count, bucket)
    shape compiles once; the one cross-device collective is the 1-byte
    per-commit verdict all-reduce (parallel/mesh.py).
    """
    from ..parallel import mesh as pmesh

    n = len(pubkeys)
    t0 = time.perf_counter()
    arrays, host_ok = pack_inputs(pubkeys, msgs, sigs)
    per_dev = _MIN_BUCKET
    while per_dev * n_dev < n:
        per_dev *= 2
    nb = per_dev * n_dev
    if nb != n:
        arrays = {
            k: np.pad(v, [(0, 0)] * (v.ndim - 1) + [(0, nb - n)])
            for k, v in arrays.items()
        }
        host_ok = np.pad(host_ok, (0, nb - n))
    t1 = time.perf_counter()
    libmetrics.observe_verify_phase(
        "pack", "ed25519-tpu", t1 - t0, n, arena="sharded"
    )
    if libdevstats.enabled():
        # the sharded path ships pre-unpacked limb arrays (pack_inputs),
        # not the compact 128 B/lane wire rows — record what actually
        # crosses the edge
        libdevstats.record_h2d(
            sum(v.nbytes for v in arrays.values()) + host_ok.nbytes
        )
    ok = pmesh.verify_sharded(
        arrays, host_ok, pmesh.default_mesh(), 1, nb
    )[0][:n]
    libdevstats.record_d2h(ok.nbytes)
    # pjit materializes inside verify_sharded — dispatch and readback
    # are one phase on the multi-chip path
    libmetrics.observe_verify_phase(
        "dispatch", "ed25519-tpu", time.perf_counter() - t1, n,
        arena="sharded",
    )
    return bool(ok.all()), ok


def verify_rsk_async(buf: np.ndarray, idxs: np.ndarray, arena, arena_ok,
                     n: int):
    """Dispatch a cached-table launch: (96, n) R|S|kneg rows + arena slots.

    Same async contract as :func:`verify_bytes_async`. ``n`` must be
    <= _CHUNK (callers chunk above that)."""
    size = bucket_size(n)
    if size != n:
        buf = np.pad(buf, [(0, 0), (0, size - n)])
        idxs = np.pad(idxs, (0, size - n))  # slot 0 gather: harmless
    out, used_pallas = _run_cached_kernel(arena, arena_ok, idxs, buf)

    def materialize():
        o, which = out, used_pallas
        while True:
            try:
                # cometlint: disable=CLNT002 -- sanctioned readback of the
                # cached-table launch (the _materialize analog)
                arr = np.asarray(o)
            except Exception as e:
                if which is None:
                    raise
                # retire the faulting flavor; _run_cached_kernel then
                # tries the sibling, bottoming out at XLA (which=None)
                _note_pallas_broken(which, e)
                o, which = _run_cached_kernel(arena, arena_ok, idxs, buf)
            else:
                # arr is the bit-packed ok mask — bucket/8 uint8 words
                # on the wire, unpacked to per-lane bools here
                libdevstats.record_d2h(arr.nbytes)
                return unpack_ok_bits(arr, 8 * arr.shape[0])[:n]

    return materialize


def verify_prepacked(buf: np.ndarray, keys, n: int):
    """Async verify of a pre-packed (128, n) wire buffer with cache routing.

    ``keys``: per-lane 32-byte edwards A encodings (b"" / short for
    host-rejected lanes — they verify False via the arena ok bit). Used
    by schemes that pack their own challenge (sr25519: merlin transcript
    k, crypto/sr25519.py) but share the cofactored kernel — and the
    expanded-point cache, since the arena is keyed by the edwards
    encoding itself.
    """
    if not _cache_enabled():
        return verify_bytes_async(buf, n)
    finals = []
    for lo in range(0, n, _CHUNK):
        hi = min(lo + _CHUNK, n)
        hit = _PUBKEY_CACHE.lookup(keys[lo:hi])
        if hit is not None:
            idxs, arena, arena_ok = hit
            finals.append(
                verify_rsk_async(
                    buf[32:, lo:hi], idxs, arena, arena_ok, hi - lo
                )
            )
        else:
            finals.append(verify_bytes_async(buf[:, lo:hi], hi - lo))
    if len(finals) == 1:
        return finals[0]
    return lambda: np.concatenate([f() for f in finals])


def verify_batch(pubkeys, msgs, sigs) -> tuple[bool, np.ndarray]:
    """Verify a batch of ed25519 signatures on device.

    Returns (all_valid, per_signature_validity) — the contract of the Go
    engine's crypto.BatchVerifier.Verify (crypto/crypto.go:45-54), including
    per-lane results so callers can attribute failures without a second pass
    (types/validation.go:243-250's find-first-invalid fallback).

    Steady state routes through the expanded-pubkey cache: per lane the
    device receives 96 bytes (R, S, -k) plus a 2-byte arena slot, and the
    kernel skips pubkey decompression + table build entirely.
    """
    n = len(pubkeys)
    if n == 0:
        return True, np.zeros(0, bool)
    devs = _shard_devices()
    if devs is not None:
        return _verify_batch_sharded(pubkeys, msgs, sigs, len(devs))
    use_cache = _cache_enabled()
    finals, host_oks = [], []
    # Phase attribution (crypto_verify_phase_seconds + verify.* trace
    # events): pack = host staging incl. the arena lookup (a miss's
    # builder launch is part of staging cost), dispatch = the async jit
    # launches, readback = the one sanctioned materialization. Summed
    # across pipelined chunks so the three phases tile the end-to-end
    # crypto_verify_batch_seconds interval.
    pack_s = disp_s = 0.0
    arena_state = "hit" if use_cache else "off"
    builds_before = _PUBKEY_CACHE.builds
    step = min(_PIPE_CHUNK, _CHUNK)
    for lo in range(0, n, step):
        hi = min(lo + step, n)
        # Pipeline host packing with device execution: each chunk is
        # dispatched as soon as it is packed, so the per-lane SHA-512 /
        # packing cost of chunk i+1 overlaps chunk i's kernel time.
        tp = time.perf_counter()
        buf, hok = pack_bytes(pubkeys[lo:hi], msgs[lo:hi], sigs[lo:hi])
        hit = _PUBKEY_CACHE.lookup(pubkeys[lo:hi]) if use_cache else None
        td = time.perf_counter()
        pack_s += td - tp
        if hit is not None:
            idxs, arena, arena_ok = hit
            finals.append(
                verify_rsk_async(buf[32:], idxs, arena, arena_ok, hi - lo)
            )
        else:
            if use_cache:
                arena_state = "bypass"  # churn exhausted the arena
            finals.append(verify_bytes_async(buf, hi - lo))
        disp_s += time.perf_counter() - td
        host_oks.append(hok)
    if use_cache and arena_state == "hit" and (
        _PUBKEY_CACHE.builds > builds_before
    ):
        arena_state = "miss"  # lookup succeeded but had to build tables
    tr = time.perf_counter()
    if len(finals) == 1:
        device_ok, host_ok = finals[0](), host_oks[0]
    else:
        device_ok = np.concatenate([f() for f in finals])
        host_ok = np.concatenate(host_oks)
    read_s = time.perf_counter() - tr
    libmetrics.observe_verify_phase(
        "pack", "ed25519-tpu", pack_s, n, arena=arena_state
    )
    libmetrics.observe_verify_phase(
        "dispatch", "ed25519-tpu", disp_s, n, arena=arena_state
    )
    libmetrics.observe_verify_phase(
        "readback", "ed25519-tpu", read_s, n, arena=arena_state
    )
    valid = device_ok & host_ok
    return bool(valid.all()), valid
