"""Tx + block event indexers over the KV store.

Reference: state/txindex/kv/kv.go (tx indexer),
state/indexer/block/kv/kv.go (block indexer), and
state/txindex/indexer_service.go (the EventBus consumer that feeds both).

Key scheme (height zero-padded so lexicographic = numeric order):

  tx/h/<tx_hash>                                  -> serialized TxRecord
  tx/e/<composite_key>/<value>/<height>/<index>   -> tx_hash
  blk/e/<composite_key>/<value>/<height>          -> b""

Searches use the SAME query language as the pubsub layer
(libs/pubsub.Query) — ``tx.height = 5 AND transfer.amount > 100`` — by
scanning the event keyspace per condition and intersecting result sets.
Scan-based matching trades raw speed for zero bespoke query machinery;
the hot path of this framework is signature verification, not index
lookups, and range conditions still prune by key prefix when the
condition is an equality.
"""

from __future__ import annotations

import threading
from ..libs import sync as libsync
from dataclasses import dataclass

from ..crypto import tmhash
from ..libs import db as dbm
from ..libs.db import prefix_end
from ..libs.pubsub import Query
from ..types import serialization as ser
from ..types.event_bus import (
    BLOCK_HEIGHT_KEY,
    TX_HASH_KEY,
    TX_HEIGHT_KEY,
    flatten_abci_events,
)

_TX_HASH_PREFIX = b"tx/h/"
_TX_EVENT_PREFIX = b"tx/e/"
_BLK_EVENT_PREFIX = b"blk/e/"


@dataclass
class TxRecord:
    """Indexed transaction result (abci.TxResult analog)."""

    height: int
    index: int
    tx: bytes
    result: object  # ExecTxResult
    tx_hash: bytes = b""


ser.codec.register(TxRecord)


def _ek(prefix: bytes, key: str, value: str, height: int, index: int = -1) -> bytes:
    out = prefix + key.encode() + b"/" + value.encode() + b"/%020d" % height
    if index >= 0:
        out += b"/%010d" % index
    return out


class KVTxIndexer:
    """Event-key tx index (state/txindex/kv/kv.go:721)."""

    def __init__(self, db: dbm.DB | None = None):
        self.db = db if db is not None else dbm.MemDB()
        self._mtx = libsync.Mutex("state.indexer._mtx")

    def index(self, rec: TxRecord, events) -> None:
        """Index one tx: by hash plus every (event key, value) pair."""
        rec.tx_hash = rec.tx_hash or tmhash.sum(rec.tx)
        with self._mtx:  # cometlint: disable=CLNT009 -- one tx's index batch is atomic under the indexer mutex; indexing runs on the event-sink thread, not the FSM
            batch = self.db.new_batch()
            batch.set(_TX_HASH_PREFIX + rec.tx_hash, ser.dumps(rec))
            flat = flatten_abci_events(
                events,
                {
                    TX_HEIGHT_KEY: [str(rec.height)],
                    TX_HASH_KEY: [rec.tx_hash.hex().upper()],
                },
            )
            for key, values in flat.items():
                if "/" in key:  # app-controlled key would corrupt the layout
                    continue
                for value in values:
                    if "/" in value:
                        continue
                    batch.set(
                        _ek(_TX_EVENT_PREFIX, key, value, rec.height, rec.index),
                        rec.tx_hash,
                    )
            batch.write()

    def get(self, tx_hash: bytes) -> TxRecord | None:
        raw = self.db.get(_TX_HASH_PREFIX + bytes(tx_hash))
        return ser.loads(raw) if raw else None

    def search(self, query: str | Query) -> list[TxRecord]:
        """All indexed txs matching every condition, height/index order."""
        q = Query.parse(query) if isinstance(query, str) else query
        hashes = _match_conditions(
            self.db, q, _TX_EVENT_PREFIX, want_value=True
        )
        if hashes is None:  # unconstrained query: full scan by hash space
            hashes = []
            scanned = set()
            for _, v in self.db.iterator(
                _TX_EVENT_PREFIX, prefix_end(_TX_EVENT_PREFIX)
            ):
                if v not in scanned:
                    scanned.add(v)
                    hashes.append(v)
        out = []
        seen = set()
        for h in hashes:
            if h in seen:
                continue
            seen.add(h)
            rec = self.get(h)
            if rec is not None:
                out.append(rec)
        out.sort(key=lambda r: (r.height, r.index))
        return out


class KVBlockIndexer:
    """Block event index (state/indexer/block/kv/kv.go:609)."""

    def __init__(self, db: dbm.DB | None = None):
        self.db = db if db is not None else dbm.MemDB()
        self._mtx = libsync.Mutex("state.indexer.KVBlockIndexer._mtx")

    def index(self, height: int, events) -> None:
        with self._mtx:  # cometlint: disable=CLNT009 -- one block's index batch is atomic under the indexer mutex; off the consensus hot path
            batch = self.db.new_batch()
            flat = flatten_abci_events(
                events, {BLOCK_HEIGHT_KEY: [str(height)]}
            )
            for key, values in flat.items():
                if "/" in key:
                    continue
                for value in values:
                    if "/" in value:
                        continue
                    batch.set(
                        _ek(_BLK_EVENT_PREFIX, key, value, height), b""
                    )
            batch.write()

    def search(self, query: str | Query) -> list[int]:
        """Heights whose block events match every condition, ascending."""
        q = Query.parse(query) if isinstance(query, str) else query
        heights = _match_conditions(
            self.db, q, _BLK_EVENT_PREFIX, want_value=False
        )
        if heights is None:
            heights = []
            for k, _ in self.db.iterator(
                _BLK_EVENT_PREFIX, prefix_end(_BLK_EVENT_PREFIX)
            ):
                h = int(k.rsplit(b"/", 1)[-1])
                if h not in heights:
                    heights.append(h)
        return sorted(set(heights))


def _match_conditions(db, q: Query, prefix: bytes, want_value: bool):
    """Intersect per-condition matches. Returns None when the query has no
    usable conditions (caller falls back to a full scan)."""
    result = None
    for cond in q.conditions:
        matches = _match_one(db, cond, prefix, want_value)
        if result is None:
            result = matches
        else:
            keep = set(matches)
            result = [m for m in result if m in keep]
        if not result:
            return []
    return result


def _match_one(db, cond, prefix: bytes, want_value: bool):
    """One condition scan. Equality prunes by exact key prefix; range ops
    scan the composite key's whole value space and compare."""
    base = prefix + cond.key.encode() + b"/"
    out = []
    if cond.op == "=":
        # Prefix-prune on the canonical rendering. The indexer writes
        # integers as str(int) (heights, indexes), so "tx.height = 5"
        # resolves with one exact-prefix scan instead of walking every tx
        # ever indexed. Non-canonical numeric renderings ("5.0", "05")
        # fall back to the full comparator scan below.
        value = cond.value
        if cond.is_number and float(value) == int(float(value)):
            value = int(float(value))
        scan_from = base + str(value).encode() + b"/"
        for k, v in db.iterator(scan_from, prefix_end(scan_from)):
            out.append(v if want_value else int(_height_of(k, want_value)))
        if out or not cond.is_number:
            return out
        out = []
    # range / CONTAINS / EXISTS: scan all values under the key
    for k, v in db.iterator(base, prefix_end(base)):
        rest = k[len(base):]
        value = rest.rsplit(b"/", 2 if want_value else 1)[0].decode()
        if cond.matches_values([value]):
            out.append(v if want_value else int(_height_of(k, want_value)))
    return out


def _height_of(key: bytes, has_index: bool) -> bytes:
    parts = key.rsplit(b"/", 2 if has_index else 1)
    return parts[1] if has_index else parts[-1]


class IndexerService:
    """EventBus consumer feeding both indexers
    (state/txindex/indexer_service.go)."""

    def __init__(self, tx_indexer, block_indexer, event_bus):
        self.tx_indexer = tx_indexer
        self.block_indexer = block_indexer
        self.event_bus = event_bus
        self._threads: list[threading.Thread] = []
        self._stop = threading.Event()
        self._warned_types: set[str] = set()

    def start(self) -> None:
        from ..libs import pubsub
        from ..types.event_bus import (
            EVENT_NEW_BLOCK_EVENTS,
            EVENT_TX,
            EVENT_TYPE_KEY,
        )

        tx_q = pubsub.Query.parse(f"{EVENT_TYPE_KEY} = '{EVENT_TX}'")
        blk_q = pubsub.Query.parse(
            f"{EVENT_TYPE_KEY} = '{EVENT_NEW_BLOCK_EVENTS}'"
        )
        # Unbounded (capacity=0 -> Queue(0)): a bounded queue would trip the
        # pubsub slow-subscriber policy on a publish burst (a >N-tx block)
        # and silently cancel indexing forever — the reference uses
        # SubscribeUnbuffered for exactly this consumer.
        tx_sub = self.event_bus.subscribe("indexer-tx", tx_q, capacity=0)
        blk_sub = self.event_bus.subscribe("indexer-blk", blk_q, capacity=0)
        for sub, fn in ((tx_sub, self._on_tx), (blk_sub, self._on_block)):
            t = threading.Thread(
                target=self._consume, args=(sub, fn), daemon=True
            )
            t.start()
            self._threads.append(t)

    def _consume(self, sub, fn) -> None:
        import queue as _q

        while not self._stop.is_set() and not sub.canceled.is_set():
            try:
                msg = sub.out.get(timeout=0.2)
            except _q.Empty:
                continue
            try:
                fn(msg.data)
            except Exception as e:
                # indexing must never kill the node, but silent data loss
                # is undiagnosable: surface once per failure type
                if type(e).__name__ not in self._warned_types:
                    self._warned_types.add(type(e).__name__)
                    import traceback

                    traceback.print_exc()

    def _on_tx(self, data) -> None:  # EventDataTx
        self.tx_indexer.index(
            TxRecord(
                height=data.height,
                index=data.index,
                tx=data.tx,
                result=data.result,
            ),
            getattr(data.result, "events", None),
        )

    def _on_block(self, data) -> None:  # EventDataNewBlockEvents
        self.block_indexer.index(data.height, data.events)

    def stop(self) -> None:
        self._stop.set()
        for sub_name in ("indexer-tx", "indexer-blk"):
            try:
                self.event_bus.unsubscribe_all(sub_name)
            except Exception:
                pass
        for t in self._threads:
            t.join(timeout=1)
