"""L7 state & block execution (reference: state/)."""

from .state import State, make_genesis_state  # noqa: F401
from .store import Store  # noqa: F401
from .validation import validate_block  # noqa: F401
from .execution import BlockExecutor  # noqa: F401
