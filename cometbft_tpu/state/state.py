"""The replicated state snapshot (reference: state/state.go:355).

``State`` is the deterministic summary a node carries between blocks:
the validator-set window (last/current/next), consensus params, and the
app hash + results hash of the latest block. It is treated as immutable —
``BlockExecutor.apply_block`` derives the next State rather than mutating.
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field, replace

from ..crypto import merkle
from ..types import (
    Block,
    BlockID,
    Commit,
    ConsensusParams,
    GenesisDoc,
    Header,
    NIL_BLOCK_ID,
    Version,
    make_block,
)
from ..types.validator_set import ValidatorSet

# Version of the state-machine replication protocol this framework speaks
# (reference: version/version.go TMCoreSemVer + ABCI semver).
SOFTWARE_VERSION = "cometbft-tpu/0.1.0"
BLOCK_PROTOCOL = 11
ABCI_SEMVER = "2.0.0"


@dataclass(slots=True)
class State:
    chain_id: str
    initial_height: int

    last_block_height: int = 0
    last_block_id: BlockID = dc_field(default_factory=BlockID)
    last_block_time_ns: int = 0

    # Validator window: validators(H+1), validators(H), validators(H-1)
    next_validators: ValidatorSet | None = None
    validators: ValidatorSet | None = None
    last_validators: ValidatorSet | None = None
    last_height_validators_changed: int = 0

    consensus_params: ConsensusParams = dc_field(
        default_factory=ConsensusParams
    )
    last_height_consensus_params_changed: int = 0

    last_results_hash: bytes = b""
    app_hash: bytes = b""
    app_version: int = 0

    def is_empty(self) -> bool:
        return self.validators is None

    def copy(self) -> "State":
        return replace(self)

    # -- block construction ------------------------------------------------

    def make_block(
        self,
        height: int,
        txs: list[bytes],
        last_commit: Commit | None,
        evidence: list,
        proposer_address: bytes,
        time_ns: int,
    ) -> Block:
        """Header fields derived from this state (state/state.go MakeBlock)."""
        return make_block(
            height=height,
            txs=txs,
            last_commit=last_commit,
            evidence=evidence,
            header_fields=dict(
                version=Version(block=BLOCK_PROTOCOL, app=self.app_version),
                chain_id=self.chain_id,
                time_ns=time_ns,
                last_block_id=self.last_block_id,
                validators_hash=self.validators.hash(),
                next_validators_hash=self.next_validators.hash(),
                consensus_hash=self.consensus_params.hash(),
                app_hash=self.app_hash,
                last_results_hash=self.last_results_hash,
                proposer_address=proposer_address,
            ),
        )


def make_genesis_state(genesis: GenesisDoc) -> State:
    """state/state.go MakeGenesisState."""
    genesis.validate_and_complete()
    if genesis.validators:
        validators = genesis.validator_set()
        next_validators = validators.copy_increment_proposer_priority(1)
    else:
        # Validators arrive from ABCI InitChain.
        validators = ValidatorSet([])
        next_validators = ValidatorSet([])
    return State(
        chain_id=genesis.chain_id,
        initial_height=genesis.initial_height,
        last_block_height=0,
        last_block_id=NIL_BLOCK_ID,
        last_block_time_ns=genesis.genesis_time_ns,
        next_validators=next_validators,
        validators=validators,
        last_validators=ValidatorSet([]),
        last_height_validators_changed=genesis.initial_height,
        consensus_params=genesis.consensus_params,
        last_height_consensus_params_changed=genesis.initial_height,
        app_hash=genesis.app_hash,
        app_version=genesis.consensus_params.version.app,
    )


def results_hash(tx_results: list) -> bytes:
    """Merkle root over deterministic ExecTxResult encodings
    (reference: types/results.go ABCIResults.Hash — only code/data feed
    the hash via the deterministic proto subset)."""
    from ..types import proto

    leaves = []
    for r in tx_results:
        body = b""
        if r.code:
            body += proto.field_varint(1, r.code)
        body += proto.field_bytes(2, r.data)
        if r.gas_wanted:
            body += proto.field_varint(5, r.gas_wanted)
        if r.gas_used:
            body += proto.field_varint(6, r.gas_used)
        leaves.append(body)
    return merkle.hash_from_byte_slices(leaves)
