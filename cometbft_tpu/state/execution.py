"""Block execution — the consensus→application bridge (reference:
state/execution.go:25-737).

``BlockExecutor`` turns consensus decisions into application state:
``create_proposal_block`` (reap mempool → ABCI PrepareProposal),
``process_proposal``, ``apply_block`` (validate → FinalizeBlock → derive
next State → Commit with the mempool locked → prune → fire events), and
the vote-extension hooks.
"""

from __future__ import annotations

import time

from ..abci import types as abci
from ..types import BlockID, ExtendedCommit
from ..types.block import Block
from ..types.event_bus import (
    EventDataNewBlock,
    EventDataNewBlockEvents,
    EventDataNewBlockHeader,
    EventDataTx,
    EventDataValidatorSetUpdates,
    NopEventBus,
)
from ..types.validator_set import (
    Validator,
    ValidatorSet,
    pubkey_proto_encode,
)
from ..crypto import keys as crypto_keys
from .state import State, results_hash
from .validation import validate_block


class NopMempool:
    """Placeholder until the mempool service lands (mempool/)."""

    def reap_max_bytes_max_gas(self, max_bytes: int, max_gas: int) -> list[bytes]:
        return []

    def lock(self) -> None:
        pass

    def unlock(self) -> None:
        pass

    def update(self, height, txs, tx_results, *a, **k) -> None:
        pass


class NopEvidencePool:
    def pending_evidence(self, max_bytes: int) -> list:
        return []

    def update(self, state, evidence_list) -> None:
        pass

    def check_evidence(self, evidence_list) -> None:
        pass


def _commit_info(block: Block, last_validators: ValidatorSet) -> abci.CommitInfo:
    """ABCI view of the block's LastCommit against a given validator set."""
    votes = []
    if block.last_commit is not None and block.last_commit.size() > 0:
        for i, cs in enumerate(block.last_commit.signatures):
            val = last_validators.get_by_index(i)
            votes.append(
                abci.VoteInfo(
                    validator=abci.Validator(
                        address=val.address, power=val.voting_power
                    ),
                    block_id_flag=cs.block_id_flag,
                )
            )
    return abci.CommitInfo(
        round=block.last_commit.round if block.last_commit else 0, votes=votes
    )


def build_last_commit_info(
    block: Block, state_store, state: "State"
) -> abci.CommitInfo:
    """execution.go:405 buildLastCommitInfo — the voter powers the app sees
    for block H must come from the validator set AT height H-1.

    Live path (H == state.last_block_height + 1): state.last_validators IS
    that set, no store I/O. Replay path (handshake replaying an older
    window): load it from the state store — the boot-time in-memory set
    diverges across validator-set changes. A missing store record fails
    loudly rather than handing the app guessed voter powers (the reference
    panics on a failed LoadValidators)."""
    if block.header.height == state.initial_height:
        return abci.CommitInfo(round=0, votes=[])
    if (
        block.header.height == state.last_block_height + 1
        and state.last_validators is not None
    ):
        vals = state.last_validators
    else:
        vals = (
            state_store.load_validators(block.header.height - 1)
            if state_store is not None
            else None
        )
        if vals is None:
            raise RuntimeError(
                f"no validator set stored for height "
                f"{block.header.height - 1}"
            )
    commit_size = block.last_commit.size() if block.last_commit else 0
    if commit_size != len(vals.validators):
        raise RuntimeError(
            f"commit size ({commit_size}) != validator set length "
            f"({len(vals.validators)}) at height {block.header.height}"
        )
    return _commit_info(block, vals)


def extended_commit_info(ec: ExtendedCommit, validators: ValidatorSet):
    votes = []
    for i, es in enumerate(ec.extended_signatures):
        val = validators.get_by_index(i)
        votes.append(
            abci.ExtendedVoteInfo(
                validator=abci.Validator(
                    address=val.address, power=val.voting_power
                ),
                vote_extension=es.extension,
                extension_signature=es.extension_signature,
                block_id_flag=es.commit_sig.block_id_flag,
            )
        )
    return abci.ExtendedCommitInfo(round=ec.round, votes=votes)


def _abci_misbehavior(evidence_list, state: State) -> list[abci.Misbehavior]:
    """types/evidence.go ABCI() — evidence → ABCI Misbehavior records."""
    from ..types.evidence import (
        DuplicateVoteEvidence,
        LightClientAttackEvidence,
    )

    out = []
    for ev in evidence_list or ():
        if isinstance(ev, DuplicateVoteEvidence):
            out.append(
                abci.Misbehavior(
                    type=abci.MisbehaviorType.DUPLICATE_VOTE,
                    validator=abci.Validator(
                        address=ev.vote_a.validator_address,
                        power=ev.validator_power,
                    ),
                    height=ev.height(),
                    time_ns=ev.time_ns(),
                    total_voting_power=ev.total_voting_power,
                )
            )
        elif isinstance(ev, LightClientAttackEvidence):
            for val in ev.byzantine_validators:
                out.append(
                    abci.Misbehavior(
                        type=abci.MisbehaviorType.LIGHT_CLIENT_ATTACK,
                        validator=abci.Validator(
                            address=val.address, power=val.voting_power
                        ),
                        height=ev.height(),
                        time_ns=ev.time_ns(),
                        total_voting_power=ev.total_voting_power,
                    )
                )
    return out


def validate_validator_updates(
    updates: list[abci.ValidatorUpdate], validator_params
) -> None:
    """Reject app validator updates the consensus layer can't carry
    (state/execution.go:515-535 validateValidatorUpdates): negative
    power, key types outside ConsensusParams.validator.pub_key_types,
    and — beyond the params check — types the tendermint.crypto
    .PublicKey oneof cannot wire-encode at all (the valset hash would
    otherwise crash the FSM at the next header; same gate as genesis,
    types/genesis.py)."""
    allowed = tuple(validator_params.pub_key_types)
    for vu in updates:
        if vu.power < 0:
            raise ValueError(f"voting power can't be negative: {vu!r}")
        # Decode the key for removals too (the reference's converter
        # does, PB2TM.ValidatorUpdates): a malformed removal must fail
        # HERE with a validation error, not deep inside apply_block.
        try:
            pk = crypto_keys.pubkey_from_type_and_bytes(
                vu.pub_key_type, vu.pub_key_bytes
            )
        except ValueError as e:
            raise ValueError(f"invalid validator update key: {e}") from e
        if vu.power == 0:
            continue  # removal: decoded, but no type admission needed
        if vu.pub_key_type not in allowed:
            raise ValueError(
                f"validator update uses pubkey type {vu.pub_key_type!r},"
                f" which is unsupported for consensus (allowed:"
                f" {allowed})"
            )
        try:
            pubkey_proto_encode(pk)
        except ValueError as e:
            raise ValueError(
                f"validator update key not wire-encodable: {e}"
            ) from e


def validator_updates_to_validators(updates: list[abci.ValidatorUpdate]):
    """ABCI ValidatorUpdate list → Validator list (power 0 = removal).

    Rejects key types the tendermint.crypto.PublicKey oneof cannot
    carry: the reference's converter fails identically inside
    PubKeyFromProto (crypto/encoding/codec.go:41-63), which also guards
    its InitChain/replay path — without this, a non-wire key admitted
    here would crash the FSM at the next validator-set hash."""
    out = []
    for vu in updates:
        pk = crypto_keys.pubkey_from_type_and_bytes(
            vu.pub_key_type, vu.pub_key_bytes
        )
        if vu.power != 0:
            pubkey_proto_encode(pk)  # ValueError for non-wire types
        out.append(Validator(pub_key=pk, voting_power=vu.power))
    return out


class BlockExecutor:
    def __init__(
        self,
        state_store,
        proxy_app,  # consensus-connection ABCI client
        mempool=None,
        evidence_pool=None,
        block_store=None,
        event_bus=None,
        metrics=None,
    ):
        self.state_store = state_store
        self.proxy_app = proxy_app
        self.mempool = mempool if mempool is not None else NopMempool()
        self.evidence_pool = (
            evidence_pool if evidence_pool is not None else NopEvidencePool()
        )
        self.block_store = block_store
        self.event_bus = event_bus if event_bus is not None else NopEventBus()
        self.metrics = metrics
        # Pipelined commits (consensus/pipeline.py) set this to the
        # durability barrier: pruning must never outrun the fsynced
        # suffix, or a crash could lose a block the WAL marker claims.
        self.prune_gate = None  # lockfree: set once at pipeline wiring, before the worker starts; read-only afterwards

    # -- proposal ----------------------------------------------------------

    def create_proposal_block(
        self,
        height: int,
        state: State,
        last_ext_commit: ExtendedCommit | None,
        proposer_address: bytes,
        time_ns: int | None = None,
    ) -> Block:
        """execution.go:101 CreateProposalBlock."""
        max_bytes = state.consensus_params.block.max_bytes
        max_gas = state.consensus_params.block.max_gas
        evidence = self.evidence_pool.pending_evidence(
            state.consensus_params.evidence.max_bytes
        )
        # Data budget: block max minus header/commit/evidence overhead
        # (types.MaxDataBytes — approximated; parts cap enforces the rest).
        max_data_bytes = (
            max_bytes - 2048 if max_bytes > 0 else 104857600
        )
        txs = self.mempool.reap_max_bytes_max_gas(max_data_bytes, max_gas)
        last_commit = (
            last_ext_commit.to_commit()
            if last_ext_commit is not None
            else None
        )
        if time_ns is None:
            time_ns = time.time_ns()
        rpp = self.proxy_app.prepare_proposal(
            abci.RequestPrepareProposal(
                max_tx_bytes=max_data_bytes,
                txs=list(txs),
                local_last_commit=(
                    extended_commit_info(last_ext_commit, state.last_validators)
                    if last_ext_commit is not None and last_ext_commit.size()
                    else abci.ExtendedCommitInfo(round=0)
                ),
                misbehavior=_abci_misbehavior(evidence, state),
                height=height,
                time_ns=time_ns,
                next_validators_hash=state.next_validators.hash(),
                proposer_address=proposer_address,
            )
        )
        return state.make_block(
            height, list(rpp.txs), last_commit, evidence, proposer_address,
            time_ns,
        )

    def process_proposal(self, block: Block, state: State) -> bool:
        """execution.go:162 ProcessProposal."""
        resp = self.proxy_app.process_proposal(
            abci.RequestProcessProposal(
                txs=list(block.data.txs),
                proposed_last_commit=build_last_commit_info(
                    block, self.state_store, state
                ),
                misbehavior=_abci_misbehavior(block.evidence, state),
                hash=block.hash(),
                height=block.header.height,
                time_ns=block.header.time_ns,
                next_validators_hash=block.header.next_validators_hash,
                proposer_address=block.header.proposer_address,
            )
        )
        if resp.status == abci.ProcessProposalStatus.UNKNOWN:
            raise RuntimeError("ProcessProposal returned UNKNOWN status")
        return resp.is_accepted

    # -- validation --------------------------------------------------------

    def validate_block(self, state: State, block: Block) -> None:
        validate_block(state, block)
        self.evidence_pool.check_evidence(block.evidence)

    # -- apply -------------------------------------------------------------

    def finalize_request(
        self, state: State, block: Block
    ) -> abci.RequestFinalizeBlock:
        """The RequestFinalizeBlock apply_block sends — shared with the
        speculative path so both execute bit-identical requests."""
        return abci.RequestFinalizeBlock(
            txs=list(block.data.txs),
            decided_last_commit=build_last_commit_info(
                block, self.state_store, state
            ),
            misbehavior=_abci_misbehavior(block.evidence, state),
            hash=block.hash(),
            height=block.header.height,
            time_ns=block.header.time_ns,
            next_validators_hash=block.header.next_validators_hash,
            proposer_address=block.header.proposer_address,
        )

    def speculate_block(self, state: State, block: Block):
        """Run FinalizeBlock speculatively (consensus/pipeline.py's
        cs-spec-exec worker): the app comes out unchanged; returns
        ``(resp, post_token)`` for a later winning ``complete_apply``.
        Raises abci.client.SpeculationUnsupported on remote transports or
        apps without the snapshot/restore extension. The caller validated
        this exact block before prevoting it — speculation never runs an
        unvalidated block."""
        resp, post = self.proxy_app.speculate_finalize(
            self.finalize_request(state, block)
        )
        if len(resp.tx_results) != len(block.data.txs):
            raise RuntimeError(
                "speculative FinalizeBlock returned wrong number of "
                "tx results"
            )
        return resp, post

    def apply_block(
        self, state: State, block_id: BlockID, block: Block
    ) -> State:
        """execution.go:204 ApplyBlock: validate → FinalizeBlock → update
        state → Commit → prune → events. Returns the next State."""
        t0 = time.perf_counter()
        self.validate_block(state, block)
        new_state, resp = self.begin_apply(state, block_id, block)
        self.complete_apply(new_state, block_id, block, resp, t0=t0)
        return new_state

    def begin_apply(
        self, state: State, block_id: BlockID, block: Block, spec_resp=None
    ):
        """The FSM-side half of ApplyBlock: FinalizeBlock (or the
        memoized speculative response), response persistence, and the
        pure State(H+1) derivation. Returns ``(new_state, resp)``; no
        durable app/consensus state advances — ``complete_apply`` owns
        that, so a pipelined caller may run it on the commit-writer
        worker AFTER the block itself is durable (the handshake refuses
        an app ahead of the block store, consensus/replay.py)."""
        if spec_resp is not None:
            resp = spec_resp
        else:
            resp = self.proxy_app.finalize_block(
                self.finalize_request(state, block)
            )
        if len(resp.tx_results) != len(block.data.txs):
            raise RuntimeError(
                "FinalizeBlock returned wrong number of tx results"
            )
        from ..libs.fail import fail_point

        fail_point("exec-after-finalize")

        self.state_store.save_finalize_block_response(
            block.header.height, resp
        )
        fail_point("exec-after-save-responses")

        new_state = self._update_state(state, block_id, block, resp)
        new_state.app_hash = resp.app_hash
        return new_state, resp

    def complete_apply(
        self,
        new_state: State,
        block_id: BlockID,
        block: Block,
        resp,
        spec_token=None,
        t0: float | None = None,
    ) -> None:
        """The durable half of ApplyBlock: app Commit (mempool locked),
        state persistence, evidence update, pruning, events. A winning
        speculation passes ``spec_token`` — the memoized post-finalize
        app state is restored in place of re-execution, then Commit
        persists it."""
        if spec_token is not None:
            self.proxy_app.apply_speculation(spec_token)
        # Commit: lock mempool so no CheckTx races the app's state commit
        # (execution.go:360).
        app_hash = self._commit(new_state, block, resp)
        assert app_hash is not None

        self.state_store.save(new_state)

        self.evidence_pool.update(new_state, block.evidence)
        self._prune(new_state)
        self._fire_events(block, block_id, resp)
        if self.metrics is not None and t0 is not None:
            self.metrics.block_processing_time.observe(
                time.perf_counter() - t0
            )

    def _commit(self, state: State, block: Block, resp) -> bytes:
        self.mempool.lock()
        try:
            cres = self.proxy_app.commit()
            self.mempool.update(
                block.header.height,
                list(block.data.txs),
                list(resp.tx_results),
            )
            self._retain_height = cres.retain_height
            return resp.app_hash
        finally:
            self.mempool.unlock()

    def _prune(self, state: State) -> None:
        retain = getattr(self, "_retain_height", 0)
        if retain > 0 and self.prune_gate is not None:
            # never prune past the durability barrier: the pruned block
            # must not be the one a crash replay would need to re-serve
            retain = min(retain, self.prune_gate())
        if retain > 0 and self.block_store is not None:
            base = self.block_store.base()
            if retain > base:
                pruned = self.block_store.prune_blocks(retain)
                if pruned > 0:
                    self.state_store.prune_states(retain)

    def _update_state(
        self, state: State, block_id: BlockID, block: Block, resp
    ) -> State:
        """execution.go:541 updateState — derive State(H+1)."""
        height = block.header.height
        next_vals = state.next_validators.copy()
        last_height_vals_changed = state.last_height_validators_changed
        if resp.validator_updates:
            # validated against the params IN FORCE for this height
            # (the reference passes state.ConsensusParams.Validator)
            validate_validator_updates(
                resp.validator_updates, state.consensus_params.validator
            )
            changes = validator_updates_to_validators(resp.validator_updates)
            next_vals.update_with_change_set(changes)
            last_height_vals_changed = height + 1 + 1

        params = state.consensus_params
        last_height_params_changed = state.last_height_consensus_params_changed
        if resp.consensus_param_updates is not None:
            params = params.update(resp.consensus_param_updates)
            params.validate_basic()
            last_height_params_changed = height + 1

        # validators(H+1) = previous next_validators (unchanged); updates
        # land in next_validators(H+2) with rotated priorities
        # (execution.go updateState: nValSet).
        next_vals.increment_proposer_priority(1)
        return State(
            chain_id=state.chain_id,
            initial_height=state.initial_height,
            last_block_height=height,
            last_block_id=block_id,
            last_block_time_ns=block.header.time_ns,
            next_validators=next_vals,
            validators=state.next_validators.copy(),
            last_validators=state.validators.copy(),
            last_height_validators_changed=last_height_vals_changed,
            consensus_params=params,
            last_height_consensus_params_changed=last_height_params_changed,
            last_results_hash=results_hash(resp.tx_results),
            app_hash=b"",  # filled after Commit
            app_version=params.version.app,
        )

    def _fire_events(self, block: Block, block_id: BlockID, resp) -> None:
        """execution.go:614 fireEvents."""
        self.event_bus.publish_new_block(
            EventDataNewBlock(
                block=block, block_id=block_id, result_finalize_block=resp
            )
        )
        self.event_bus.publish_new_block_header(
            EventDataNewBlockHeader(header=block.header)
        )
        # Unconditional (execution.go fireEvents): block.height must be
        # searchable even when the app emitted no block-level events.
        self.event_bus.publish_new_block_events(
            EventDataNewBlockEvents(
                height=block.header.height,
                events=list(resp.events or []),
                num_txs=len(block.data.txs),
            )
        )
        for i, tx in enumerate(block.data.txs):
            self.event_bus.publish_tx(
                EventDataTx(
                    height=block.header.height,
                    index=i,
                    tx=tx,
                    result=resp.tx_results[i],
                )
            )
        if resp.validator_updates:
            self.event_bus.publish_validator_set_updates(
                EventDataValidatorSetUpdates(
                    validator_updates=list(resp.validator_updates)
                )
            )

    # -- vote extensions ---------------------------------------------------

    def extend_vote(self, vote, state: State) -> bytes:
        resp = self.proxy_app.extend_vote(
            abci.RequestExtendVote(
                hash=vote.block_id.hash,
                height=vote.height,
            )
        )
        return resp.vote_extension

    def verify_vote_extension(self, vote, state: State) -> bool:
        resp = self.proxy_app.verify_vote_extension(
            abci.RequestVerifyVoteExtension(
                hash=vote.block_id.hash,
                validator_address=vote.validator_address,
                height=vote.height,
                vote_extension=vote.extension,
            )
        )
        if resp.status == abci.VerifyVoteExtensionStatus.UNKNOWN:
            raise RuntimeError("VerifyVoteExtension returned UNKNOWN")
        return resp.is_accepted
