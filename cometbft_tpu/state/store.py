"""State persistence (reference: state/store.go:51-708).

Saves the ``State`` snapshot, per-height validator sets (full set when it
changed, else a pointer to the height it last changed — the reference's
checkpoint scheme, store.go:342), per-height consensus params, and
FinalizeBlock responses (for replay/handshake and the RPC
``block_results`` endpoint). All records go through the shared tagged-JSON
codec.
"""

from __future__ import annotations

import json

from ..libs import db as dbm
from ..libs import fail as libfail
from ..types import serialization as ser
from ..types.validator_set import ValidatorSet
from .state import State


def _h(prefix: bytes, height: int) -> bytes:
    return prefix + b"%020d" % height


_STATE_KEY = b"stateKey"


class Store:
    def __init__(self, db: dbm.DB):
        self.db = db

    # -- state snapshot ----------------------------------------------------

    def save(self, state: State) -> None:
        """Persist state + the validator/params records for the heights the
        snapshot implies (store.go:182 save)."""
        libfail.delay_point("store-write")  # slow-disk injection seam
        batch = self.db.new_batch()
        next_height = state.last_block_height + 1
        if next_height == state.initial_height:
            # Genesis: validators(H) and validators(H+1) both known.
            self._save_validators(
                batch, next_height, state.validators,
                state.last_height_validators_changed,
            )
        self._save_validators(
            batch, next_height + 1, state.next_validators,
            state.last_height_validators_changed,
        )
        self._save_params(
            batch, next_height, state.consensus_params,
            state.last_height_consensus_params_changed,
        )
        batch.set(_STATE_KEY, self._encode_state(state))
        batch.write_sync()

    def load(self) -> State | None:
        raw = self.db.get(_STATE_KEY)
        return self._decode_state(raw) if raw else None

    def bootstrap(self, state: State) -> None:
        """Seed the store from an out-of-band state (statesync)."""
        batch = self.db.new_batch()
        height = state.last_block_height + 1
        if state.last_validators is not None and height > state.initial_height:
            self._save_validators(
                batch, height - 1, state.last_validators,
                state.last_height_validators_changed,
            )
        self._save_validators(
            batch, height, state.validators,
            state.last_height_validators_changed,
        )
        self._save_validators(
            batch, height + 1, state.next_validators,
            state.last_height_validators_changed,
        )
        self._save_params(
            batch, height, state.consensus_params,
            state.last_height_consensus_params_changed,
        )
        batch.set(_STATE_KEY, self._encode_state(state))
        batch.write_sync()

    @staticmethod
    def _encode_state(state: State) -> bytes:
        fields = {
            "chain_id": state.chain_id,
            "initial_height": state.initial_height,
            "last_block_height": state.last_block_height,
            "last_block_id": ser.codec.encode(state.last_block_id),
            "last_block_time_ns": state.last_block_time_ns,
            "next_validators": ser.codec.encode(state.next_validators),
            "validators": ser.codec.encode(state.validators),
            "last_validators": ser.codec.encode(state.last_validators),
            "last_height_validators_changed": state.last_height_validators_changed,
            "consensus_params": ser.codec.encode(state.consensus_params),
            "last_height_consensus_params_changed": state.last_height_consensus_params_changed,
            "last_results_hash": state.last_results_hash.hex(),
            "app_hash": state.app_hash.hex(),
            "app_version": state.app_version,
        }
        return json.dumps(fields, separators=(",", ":")).encode()

    @staticmethod
    def _decode_state(raw: bytes) -> State:
        d = json.loads(raw)
        return State(
            chain_id=d["chain_id"],
            initial_height=d["initial_height"],
            last_block_height=d["last_block_height"],
            last_block_id=ser.codec.decode(d["last_block_id"]),
            last_block_time_ns=d["last_block_time_ns"],
            next_validators=ser.codec.decode(d["next_validators"]),
            validators=ser.codec.decode(d["validators"]),
            last_validators=ser.codec.decode(d["last_validators"]),
            last_height_validators_changed=d["last_height_validators_changed"],
            consensus_params=ser.codec.decode(d["consensus_params"]),
            last_height_consensus_params_changed=d[
                "last_height_consensus_params_changed"
            ],
            last_results_hash=bytes.fromhex(d["last_results_hash"]),
            app_hash=bytes.fromhex(d["app_hash"]),
            app_version=d["app_version"],
        )

    # -- validator sets ----------------------------------------------------

    def _save_validators(
        self, batch, height: int, vals: ValidatorSet, last_changed: int
    ) -> None:
        if vals is None:
            return
        if last_changed < height and self.db.get(_h(b"vals:", last_changed)):
            record = {"ref": last_changed}
        else:
            record = {"set": ser.codec.encode(vals)}
        batch.set(_h(b"vals:", height), json.dumps(record).encode())

    def save_validator_set(
        self, height: int, vals: ValidatorSet, last_changed: int
    ) -> None:
        batch = self.db.new_batch()
        self._save_validators(batch, height, vals, last_changed)
        batch.write()

    def load_validators(self, height: int) -> ValidatorSet | None:
        raw = self.db.get(_h(b"vals:", height))
        if raw is None:
            return None
        record = json.loads(raw)
        if "ref" in record:
            raw = self.db.get(_h(b"vals:", record["ref"]))
            if raw is None:
                return None
            record = json.loads(raw)
            if "set" not in record:
                return None
        return ser.codec.decode(record["set"])

    # -- consensus params --------------------------------------------------

    def _save_params(self, batch, height, params, last_changed) -> None:
        if last_changed < height and self.db.get(_h(b"params:", last_changed)):
            record = {"ref": last_changed}
        else:
            record = {"params": ser.codec.encode(params)}
        batch.set(_h(b"params:", height), json.dumps(record).encode())

    def load_consensus_params(self, height: int):
        raw = self.db.get(_h(b"params:", height))
        if raw is None:
            return None
        record = json.loads(raw)
        if "ref" in record:
            raw = self.db.get(_h(b"params:", record["ref"]))
            if raw is None:
                return None
            record = json.loads(raw)
        return ser.codec.decode(record["params"])

    # -- ABCI responses ----------------------------------------------------

    def save_finalize_block_response(self, height: int, response) -> None:
        from ..abci import codec as abci_codec

        self.db.set(
            _h(b"abciResp:", height),
            json.dumps(abci_codec._to_jsonable(response)).encode(),
        )

    def load_finalize_block_response(self, height: int):
        from ..abci import codec as abci_codec

        raw = self.db.get(_h(b"abciResp:", height))
        if raw is None:
            return None
        return abci_codec._from_jsonable(json.loads(raw))

    def load_last_finalize_block_response(self, height: int):
        """Response for the LAST height, used by handshake replay."""
        return self.load_finalize_block_response(height)

    # -- pruning -----------------------------------------------------------

    def prune_states(self, retain_height: int) -> None:
        """Drop validator/params/response records below retain_height,
        keeping anything still referenced by pointer records."""
        for prefix in (b"vals:", b"params:", b"abciResp:"):
            keep_refs = set()
            if prefix in (b"vals:", b"params:"):
                raw = self.db.get(_h(prefix, retain_height))
                if raw is not None:
                    record = json.loads(raw)
                    if "ref" in record:
                        keep_refs.add(record["ref"])
            batch = self.db.new_batch()
            for key, _ in self.db.iterator(
                _h(prefix, 0), _h(prefix, retain_height)
            ):
                height = int(key[len(prefix):])
                if height not in keep_refs:
                    batch.delete(key)
            batch.write()
