"""State rollback (reference: state/rollback.go + commands/rollback.go).

Removes the effects of the LAST block from the state store — the recovery
tool for an app-hash divergence after an app upgrade bug: roll the state
back one height, optionally delete the offending block, fix the app,
restart, and the node re-applies it.
"""

from __future__ import annotations


class RollbackError(Exception):
    pass


def rollback_state(state_store, block_store, remove_block: bool = False):
    """Roll the state back one height; returns (new_height, app_hash).

    reference rollback.go Rollback: the rolled-back state's fields come
    from the PREVIOUS block's header plus the stored validator sets.
    """
    from dataclasses import replace

    state = state_store.load()
    if state is None:
        raise RollbackError("no state found to roll back")
    height = state.last_block_height
    if height <= state.initial_height:
        raise RollbackError(
            f"state at initial height {height}, nothing to roll back"
        )
    rollback_height = height - 1
    prev_meta = block_store.load_block_meta(rollback_height)
    removed_meta = block_store.load_block_meta(height)
    if prev_meta is None or removed_meta is None:
        raise RollbackError(
            f"blocks at heights {rollback_height},{height} not found, "
            f"cannot roll back"
        )
    # Validator window: state.validators is the set validating block
    # last_block_height+1 (the store keys them that way), so the
    # rolled-back state wants sets for height, height+1 and
    # rollback_height respectively (rollback.go).
    validators = state_store.load_validators(height)
    next_validators = state_store.load_validators(height + 1)
    last_validators = state_store.load_validators(rollback_height)
    if validators is None or next_validators is None:
        raise RollbackError("validator sets for rollback height missing")
    if last_validators is None:
        last_validators = validators
    new_state = replace(
        state,
        last_block_height=rollback_height,
        last_block_id=prev_meta.block_id,
        last_block_time_ns=prev_meta.header.time_ns,
        validators=validators,
        next_validators=next_validators,
        last_validators=last_validators,
        # app hash and last-results hash are only agreed upon in the
        # FOLLOWING block, i.e. the removed block's header (rollback.go)
        app_hash=removed_meta.header.app_hash,
        last_results_hash=removed_meta.header.last_results_hash,
    )
    state_store.save(new_state)
    if remove_block:
        block_store.delete_block(height)
    return rollback_height, new_state.app_hash
