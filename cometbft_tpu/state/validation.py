"""Full block validation against state (reference: state/validation.go:150).

Everything a node checks before applying a block: header fields derived
from state must match, and the embedded LastCommit must carry +2/3 of the
previous validator set — the batch-verified hot path (validation.go:92 →
types/validation.go:26 → the TPU kernel via crypto/batch).
"""

from __future__ import annotations

from ..types import validation as tv
from ..types.block import Block
from .state import State


class BlockValidationError(Exception):
    pass


def validate_block(state: State, block: Block) -> None:
    block.validate_basic()

    hdr = block.header
    if hdr.chain_id != state.chain_id:
        raise BlockValidationError(
            f"wrong chain id {hdr.chain_id!r}, want {state.chain_id!r}"
        )
    expected_height = (
        state.last_block_height + 1
        if state.last_block_height > 0
        else state.initial_height
    )
    if hdr.height != expected_height:
        raise BlockValidationError(
            f"wrong height {hdr.height}, want {expected_height}"
        )
    if hdr.last_block_id != state.last_block_id:
        raise BlockValidationError("wrong last_block_id")
    if hdr.app_hash != state.app_hash:
        raise BlockValidationError(
            f"wrong app_hash {hdr.app_hash.hex()}, want {state.app_hash.hex()}"
        )
    if hdr.last_results_hash != state.last_results_hash:
        raise BlockValidationError("wrong last_results_hash")
    if hdr.validators_hash != state.validators.hash():
        raise BlockValidationError("wrong validators_hash")
    if hdr.next_validators_hash != state.next_validators.hash():
        raise BlockValidationError("wrong next_validators_hash")
    if hdr.consensus_hash != state.consensus_params.hash():
        raise BlockValidationError("wrong consensus_hash")

    # LastCommit: height-1 carries +2/3 of the PREVIOUS validator set.
    if hdr.height == state.initial_height:
        if block.last_commit is not None and block.last_commit.size() != 0:
            raise BlockValidationError(
                "initial block cannot carry a last commit"
            )
    else:
        if block.last_commit is None:
            raise BlockValidationError("missing last commit")
        if block.last_commit.size() != len(state.last_validators):
            raise BlockValidationError(
                f"last commit has {block.last_commit.size()} sigs, "
                f"want {len(state.last_validators)}"
            )
        try:
            tv.verify_commit(
                state.chain_id,
                state.last_validators,
                state.last_block_id,
                hdr.height - 1,
                block.last_commit,
            )  # ◄◄ HOT BATCH: types/validation.go:26 → TPU batch verifier
        except tv.VerificationError as e:
            raise BlockValidationError(f"invalid last commit: {e}") from e

    # Proposer must belong to the current validator set.
    if not state.validators.has_address(hdr.proposer_address):
        raise BlockValidationError("proposer not in validator set")

    # Block time sanity: must advance past the previous block
    # (median-time checks live with the consensus FSM's proposal rules).
    if hdr.height > state.initial_height and (
        hdr.time_ns <= state.last_block_time_ns
    ):
        raise BlockValidationError("block time must be monotonically increasing")
