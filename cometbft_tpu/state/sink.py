"""External-DB event sink: relational indexing of block/tx events.

Reference analog: state/indexer/sink/psql/psql.go:250 — CometBFT's psql
sink writes blocks, tx_results, events and attributes into PostgreSQL so
operators can query chain history with SQL instead of the kv indexer's
keyspace scans. This framework's out-of-process backend is SQLite (baked
into CPython; same relational shape, zero service dependency) — select
with ``tx_index.indexer = "sqlite"``.

Schema (mirrors the psql sink's):

  blocks(height PRIMARY KEY, created_at)
  tx_results(id, height, tx_index, tx_hash UNIQUE(height,tx_index), data)
  attributes(id, height, tx_id NULL, event_type, composite_key, key,
             value, value_num NULL)

Unlike the reference's psql sink (write-only from the node's side), this
sink also implements the SAME search API as the kv indexers —
``search_txs``/``search_blocks`` accept the pubsub query language
(``tx.height = 5 AND transfer.amount > 100``) and translate each
condition into SQL over ``attributes`` — so it is a drop-in indexer
backend and its results are asserted equal to the kv indexer's over a
generated chain (tests/test_sink.py).
"""

from __future__ import annotations

import sqlite3
from ..libs import sync as libsync

from ..crypto import tmhash
from ..libs.pubsub import Query
from ..types import serialization as ser
from ..types.event_bus import (
    BLOCK_HEIGHT_KEY,
    TX_HASH_KEY,
    TX_HEIGHT_KEY,
    flatten_abci_events,
)
from .indexer import TxRecord

_SCHEMA = """
CREATE TABLE IF NOT EXISTS blocks (
    height INTEGER PRIMARY KEY,
    created_at TEXT DEFAULT CURRENT_TIMESTAMP
);
CREATE TABLE IF NOT EXISTS tx_results (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    height INTEGER NOT NULL,
    tx_index INTEGER NOT NULL,
    tx_hash TEXT NOT NULL,
    data BLOB NOT NULL,
    UNIQUE(height, tx_index)
);
CREATE TABLE IF NOT EXISTS attributes (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    height INTEGER NOT NULL,
    tx_id INTEGER,
    event_type TEXT,
    composite_key TEXT NOT NULL,
    key TEXT NOT NULL,
    value TEXT NOT NULL,
    value_num REAL
);
CREATE INDEX IF NOT EXISTS attr_ck ON attributes(composite_key, value);
CREATE INDEX IF NOT EXISTS attr_h ON attributes(height);
CREATE INDEX IF NOT EXISTS tx_hash_idx ON tx_results(tx_hash);
"""


def _num(value: str):
    try:
        return float(value)
    except (TypeError, ValueError):
        return None


class SQLiteEventSink:
    """Relational event sink + drop-in tx/block indexer backend."""

    def __init__(self, path: str = ":memory:"):
        # one connection, serialized by a lock: the indexer service feeds
        # from two consumer threads, searches come from RPC threads
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._mtx = libsync.Mutex("state.sink._mtx")
        with self._mtx:
            self._conn.executescript(_SCHEMA)
            self._conn.commit()

    # -- write side (IndexerService-compatible) -------------------------

    def index_block(self, height: int, events) -> None:
        """KVBlockIndexer.index signature."""
        flat = flatten_abci_events(events, {BLOCK_HEIGHT_KEY: [str(height)]})
        with self._mtx:
            cur = self._conn.cursor()
            cur.execute(
                "INSERT OR IGNORE INTO blocks(height) VALUES (?)", (height,)
            )
            self._insert_attrs(cur, height, None, flat)
            self._conn.commit()

    # alias so the sink can stand in where a KVBlockIndexer is expected
    index = index_block

    def index_tx(self, rec: TxRecord, events) -> None:
        """KVTxIndexer.index signature."""
        rec.tx_hash = rec.tx_hash or tmhash.sum(rec.tx)
        flat = flatten_abci_events(
            events,
            {
                TX_HEIGHT_KEY: [str(rec.height)],
                TX_HASH_KEY: [rec.tx_hash.hex().upper()],
            },
        )
        with self._mtx:
            cur = self._conn.cursor()
            # Re-indexing (crash-replay re-executes recent blocks) must
            # not orphan the old row's attributes: REPLACE assigns a new
            # autoincrement id, so the dead tx_id's rows would accumulate
            # forever and leak into every scan.
            cur.execute(
                "DELETE FROM attributes WHERE tx_id IN "
                "(SELECT id FROM tx_results WHERE height=? AND tx_index=?)",
                (rec.height, rec.index),
            )
            cur.execute(
                "INSERT OR REPLACE INTO tx_results"
                "(height, tx_index, tx_hash, data) VALUES (?,?,?,?)",
                (
                    rec.height,
                    rec.index,
                    rec.tx_hash.hex().upper(),
                    ser.dumps(rec),
                ),
            )
            tx_id = cur.lastrowid
            self._insert_attrs(cur, rec.height, tx_id, flat)
            self._conn.commit()

    def _insert_attrs(self, cur, height, tx_id, flat) -> None:
        for ck, values in flat.items():
            etype, _, key = ck.rpartition(".")
            for value in values:
                cur.execute(
                    "INSERT INTO attributes"
                    "(height, tx_id, event_type, composite_key, key,"
                    " value, value_num) VALUES (?,?,?,?,?,?,?)",
                    (height, tx_id, etype, ck, key, value, _num(value)),
                )

    # -- read side ------------------------------------------------------

    def get_tx(self, tx_hash: bytes) -> TxRecord | None:
        with self._mtx:
            row = self._conn.execute(
                "SELECT data FROM tx_results WHERE tx_hash = ?",
                (bytes(tx_hash).hex().upper(),),
            ).fetchone()
        return ser.loads(row[0]) if row else None

    get = get_tx  # KVTxIndexer.get signature

    def _cond_sql(self, cond, id_col: str):
        """One query condition -> (SQL, params) yielding matching ids.

        Block searches (id_col == "height") see only BLOCK events
        (tx_id IS NULL): tx-event attributes share the table but belong
        to tx_search, exactly like the kv indexers' separate keyspaces.
        """
        scope = (
            "tx_id IS NULL" if id_col == "height" else f"{id_col} IS NOT NULL"
        )
        base = (
            f"SELECT DISTINCT {id_col} FROM attributes "
            f"WHERE {scope} AND composite_key = ?"
        )
        p = [cond.key]
        op = cond.op
        if op == "=":
            # numeric equality must match however the value was rendered
            # ("5" == 5.0), mirroring Query.matches_values
            if cond.is_number:
                base += " AND (value_num = ? OR value = ?)"
                p += [float(cond.value), str(cond.value)]
            else:
                base += " AND value = ?"
                p.append(str(cond.value))
        elif op in (">", ">=", "<", "<="):
            base += f" AND value_num {op} ?"
            p.append(float(cond.value))
        elif op == "CONTAINS":
            base += " AND instr(value, ?) > 0"
            p.append(str(cond.value))
        elif op == "EXISTS":
            pass  # key presence alone
        else:  # pragma: no cover - parser rejects unknown ops
            raise ValueError(f"unsupported op {op!r}")
        return base, p

    def _search_ids(self, query, id_col: str) -> list:
        q = Query.parse(query) if isinstance(query, str) else query
        result = None
        with self._mtx:
            for cond in q.conditions:
                sql, params = self._cond_sql(cond, id_col)
                ids = {r[0] for r in self._conn.execute(sql, params)}
                result = ids if result is None else (result & ids)
                if not result:
                    return []
            if result is None:  # unconstrained: everything indexed
                scope = (
                    "tx_id IS NULL"
                    if id_col == "height"
                    else f"{id_col} IS NOT NULL"
                )
                sql = (
                    f"SELECT DISTINCT {id_col} FROM attributes "
                    f"WHERE {scope}"
                )
                result = {r[0] for r in self._conn.execute(sql)}
        return sorted(result)

    def search_txs(self, query) -> list[TxRecord]:
        ids = self._search_ids(query, "tx_id")
        if not ids:
            return []
        with self._mtx:
            rows = self._conn.execute(
                "SELECT data FROM tx_results WHERE id IN (%s) "
                "ORDER BY height, tx_index"
                % ",".join("?" * len(ids)),
                ids,
            ).fetchall()
        return [ser.loads(r[0]) for r in rows]

    def search_blocks(self, query) -> list[int]:
        return self._search_ids(query, "height")

    # KVTxIndexer/KVBlockIndexer .search signatures (duck-typed by the
    # RPC routes: tx_search wants TxRecords, block_search wants heights)
    search = search_txs

    def close(self) -> None:
        with self._mtx:
            self._conn.close()


class SQLiteTxIndexer:
    """KVTxIndexer-shaped view over a shared sink."""

    def __init__(self, sink: SQLiteEventSink):
        self.sink = sink

    def index(self, rec, events) -> None:
        self.sink.index_tx(rec, events)

    def get(self, tx_hash):
        return self.sink.get_tx(tx_hash)

    def search(self, query):
        return self.sink.search_txs(query)


class SQLiteBlockIndexer:
    """KVBlockIndexer-shaped view over a shared sink."""

    def __init__(self, sink: SQLiteEventSink):
        self.sink = sink

    def index(self, height, events) -> None:
        self.sink.index_block(height, events)

    def search(self, query):
        return self.sink.search_blocks(query)
