"""cometbft_tpu — a TPU-native BFT state-machine-replication framework.

A ground-up re-design of CometBFT's capabilities (Tendermint consensus, ABCI
application boundary, mempool / block / state sync, light client, evidence,
RPC, operational tooling) built idiomatically around JAX/XLA/Pallas.

The defining feature is a TPU-resident cryptography backend: validator-set
wide ed25519 signature batches (vote ingest, commit verification, light-client
replay, blocksync catch-up) are streamed to HBM and verified in a single
batched kernel launch behind the engine's ``BatchVerifier`` interface.

Layer map (mirrors reference SURVEY.md §1):
  ops/        field/curve/hash kernels (JAX, device)     — the compute path
  parallel/   device mesh + sharding for multi-chip batches
  crypto/     keys, batch verifier, merkle, hashing       — L1
  types/      Block/Vote/Commit/ValidatorSet/...          — L2
  store/      block store, KV abstraction                 — L3
  state/      BlockExecutor, state store, indexers        — L3/L7
  abci/       application boundary                        — L4
  p2p/        transport, secret connection, switch        — L5
  consensus/, mempool/, blocksync/, statesync/, evidence/ — L6
  node/       assembly                                    — L8
  rpc/        JSON-RPC surface                            — L9
  light/, privval/, inspect, cmd/                         — L10
"""

__version__ = "0.1.0"

# ABCI protocol compatibility version (reference: version/version.go:6-9).
ABCI_VERSION = "2.0.0"
BLOCK_PROTOCOL = 11
P2P_PROTOCOL = 9
