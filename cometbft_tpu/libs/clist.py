"""Concurrent doubly-linked list (reference: libs/clist/clist.go:407).

The mempool/evidence gossip structure: elements are never moved, only
appended and removed; readers hold an element and call ``next_wait`` to
block until a successor exists (how per-peer broadcast routines tail the
pool without polling).
"""

from __future__ import annotations

from . import sync as libsync
from typing import Any

MAX_LENGTH = 1 << 30


class CElement:
    __slots__ = ("value", "_prev", "_next", "_removed", "_cv", "_list")

    def __init__(self, value: Any, list_: "CList"):
        self.value = value
        self._prev: CElement | None = None
        self._next: CElement | None = None
        self._removed = False
        self._list = list_
        self._cv = libsync.Condition()

    def next(self) -> "CElement | None":
        with self._cv:
            return self._next

    def prev(self) -> "CElement | None":
        with self._cv:
            return self._prev

    @property
    def removed(self) -> bool:
        with self._cv:
            return self._removed

    def next_wait(self, timeout: float | None = None) -> "CElement | None":
        """Block until this element has a successor or is removed."""
        with self._cv:
            if not self._cv.wait_for(
                lambda: self._next is not None or self._removed, timeout
            ):
                return None
            return self._next

    def _set_next(self, nxt: "CElement | None") -> None:
        with self._cv:
            self._next = nxt
            self._cv.notify_all()

    def _set_prev(self, prv: "CElement | None") -> None:
        with self._cv:
            self._prev = prv

    def _mark_removed(self) -> None:
        with self._cv:
            self._removed = True
            self._cv.notify_all()


class CList:
    def __init__(self, max_length: int = MAX_LENGTH):
        self._mtx = libsync.RLock("libs.clist._mtx")
        self._head: CElement | None = None
        self._tail: CElement | None = None
        self._len = 0
        self._max_length = max_length
        self._wait_cv = libsync.Condition(self._mtx)

    def __len__(self) -> int:
        with self._mtx:
            return self._len

    def front(self) -> CElement | None:
        with self._mtx:
            return self._head

    def back(self) -> CElement | None:
        with self._mtx:
            return self._tail

    def front_wait(self, timeout: float | None = None) -> CElement | None:
        """Block until the list is non-empty."""
        with self._mtx:
            if not self._wait_cv.wait_for(
                lambda: self._head is not None, timeout
            ):
                return None
            return self._head

    def push_back(self, value: Any) -> CElement:
        with self._mtx:
            if self._len >= self._max_length:
                raise OverflowError("clist at max length")
            el = CElement(value, self)
            if self._tail is None:
                self._head = self._tail = el
            else:
                el._set_prev(self._tail)
                self._tail._set_next(el)
                self._tail = el
            self._len += 1
            self._wait_cv.notify_all()
            return el

    def remove(self, el: CElement) -> Any:
        with self._mtx:
            if el.removed:
                return el.value
            prv, nxt = el.prev(), el.next()
            if self._head is el:
                self._head = nxt
            if self._tail is el:
                self._tail = prv
            if prv is not None:
                prv._set_next(nxt)
            if nxt is not None:
                nxt._set_prev(prv)
            self._len -= 1
            el._mark_removed()
            return el.value

    def __iter__(self):
        el = self.front()
        while el is not None:
            if not el.removed:
                yield el
            el = el.next()
