"""Shared compile-and-load machinery for the C++ engines.

Both native tiers — the storage engine (libs/db_native.py over
native/nkv.cpp) and the host batch verifier (crypto/host_batch.py over
native/edbatch.cpp) — build a shared object on first use with the
baked-in g++ and load it via ctypes (no pybind11 in the image). One
implementation of the staleness check / atomic replace / failure
handling keeps the two paths from drifting.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from . import sync as libsync

_lock = libsync.Mutex("libs.native_build._lock")


class NativeBuildError(RuntimeError):
    pass


def build_and_load(
    src: str,
    so: str,
    extra_flags: tuple[str, ...] = (),
    timeout: float = 120.0,
) -> ctypes.CDLL:
    """Compile ``src`` -> ``so`` (when missing or stale) and dlopen it.

    Raises NativeBuildError when the toolchain is unavailable or the
    compile fails; callers decide their own fallback policy.
    """
    with _lock:
        if not os.path.exists(so) or os.path.getmtime(so) < os.path.getmtime(
            src
        ):
            _compile(src, so, extra_flags, timeout)
        try:
            return ctypes.CDLL(so)
        except OSError:
            # A pre-existing .so that won't dlopen (truncated artifact,
            # wrong architecture) must not take down callers that have a
            # pure-Python fallback: rebuild once from source, and map any
            # remaining failure to NativeBuildError so the callers'
            # fallback policy applies.
            try:
                os.remove(so)
            except OSError:
                pass
            _compile(src, so, extra_flags, timeout)
            try:
                return ctypes.CDLL(so)
            except OSError as e:
                raise NativeBuildError(
                    f"{os.path.basename(so)} rebuilt but won't load: {e!r}"
                )


def _compile(
    src: str, so: str, extra_flags: tuple[str, ...], timeout: float
) -> None:
    cmd = [
        "g++", "-O3", "-funroll-loops", "-shared", "-fPIC",
        "-std=c++17", *extra_flags, src, "-o", so + ".tmp",
    ]
    try:
        # cometlint: disable=CLNT009 -- one-time lazy toolchain build; the
        # resulting .so is cached on disk and re-dlopened for free after
        r = subprocess.run(
            cmd, capture_output=True, text=True, timeout=timeout
        )
    except (OSError, subprocess.TimeoutExpired) as e:
        raise NativeBuildError(f"g++ unavailable: {e!r}")
    if r.returncode != 0:
        raise NativeBuildError(
            f"{os.path.basename(src)} compile failed:\n"
            f"{r.stderr[:800]}"
        )
    os.replace(so + ".tmp", so)
