"""L0 runtime primitives: bit arrays, events, service lifecycle, pubsub."""
