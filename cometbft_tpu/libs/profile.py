"""Continuous sampling profiler plane: on-CPU/off-CPU flame data with
subsystem attribution (reference: the net/http/pprof CPU profile the Go
node ships as a first-class operator tool — node/node.go:651-664 — here
rebuilt for a GIL-bound Python engine where *which subsystem holds the
interpreter* and *which lock a thread is parked on* are the questions).

A sampler thread (``prof-sampler``) walks ``sys._current_frames()`` at
``COMETBFT_TPU_PROF_HZ`` (default ~67 Hz, off the round numbers so the
sampler never phase-locks with 10 ms/100 ms engine timers) and folds
every thread's stack into an interned frame table.  Each sample carries:

* a **subsystem** — resolved from the engine's stable thread names
  (``cs-receive`` → consensus, ``mconn-send`` → p2p, ``verify-coalescer``
  → coalescer, ...) with a frame-module fallback for unnamed threads,
  the same resolver ``/debug/pprof/goroutine`` uses for its dump rows;
* a **state** — ``on_cpu`` vs ``blocked``, where blocked is classified
  by (a) libs/sync's per-thread blocked-on registry (a contended
  ``Mutex.acquire`` names the registered lock → ``lock:<name>``), then
  (b) a leaf-frame wait-site registry: ``threading.Condition/Event``
  waits resolve through their caller (coalescer ticket waits, hash-plane
  tickets, executor condition loops), ``selectors``/socket receives,
  ``queue.get``, and the WAL fsync — so off-CPU samples name *which
  lock or queue* a thread was parked on, not just "blocked".

Surfaces (the house plane pattern throughout):

* ``/debug/pprof/profile?seconds=N`` — flamegraph-compatible collapsed
  stacks (``subsystem;state[;wait];root;...;leaf N``) or ``&format=json``;
  without ``seconds`` it serves the bounded recent-sample ring, which is
  how watchdog black-box bundles and ``cometbft-tpu debug dump`` capture
  ``profile.json`` covering the seconds *before* a trip.
* ``profile_samples_total{subsystem,state}`` counters, bridged at scrape
  from lock-free columns by :func:`sample` (libs/health.sample calls it
  next to the txtrace/devledger/lockprof bridges).
* EV_PROF flight-ring rows (~1/s per active subsystem) feeding
  ``health.critical_path()`` — a commit window gated by GIL-bound Python
  says ``cpu:<subsystem>`` — and the ``cpu_saturated`` postmortem
  detector (cometbft_tpu/postmortem/attribute.py).
* :func:`module_shares` — the simnet ``--profile`` report splitting a
  scenario run's wall time into scheduler vs verify vs engine, the
  measurement the parallel-DES ROADMAP item needs.

Like every plane: ``COMETBFT_TPU_PROF`` kill switch (0 pins off, 1 pins
on, default auto — on while an acquirer holds it), devstats-style
``acquire()``/``release()`` refcount with leak-safe node-boot unwind,
an allocation-free *disabled* path (no sampler thread exists, the
record-free module touches nothing — pinned by the tracemalloc guard in
tests/test_observability.py; the *enabled* sampler may allocate while
interning, and attributes that cost to its own ``sampler`` subsystem),
and one mutex (``libs.profile._mtx``) that serializes only setup paths
(enable/disable/refcount), never a sample, registered in lockorder.json
and asserted edge-free in tests/test_lint_graph.py.

Known limitation (documented in docs/observability.md): a thread inside
a C call that leaves no Python frame (``time.sleep``, a builtin socket
recv whose caller is not in the wait-site registry) samples as on-CPU at
its caller's leaf frame — the registry names the engine's known wait
sites, not every stdlib sleep.
"""

from __future__ import annotations

import itertools
import os
import sys
import threading
import time
from array import array

from . import sync as libsync

# NOTE: this module imports NOTHING from the health layer at module
# level — libs/health imports it for EV_PROF decode and the scrape
# bridge, so the one upward call (EV_PROF ring emission) lazily imports
# health on the once-per-second flush path only (the lockprof posture).

_ENV = "COMETBFT_TPU_PROF"
_ENV_HZ = "COMETBFT_TPU_PROF_HZ"
_ENV_RING = "COMETBFT_TPU_PROF_RING"

_ON_VALUES = ("1", "on", "true", "yes")
_OFF_VALUES = ("0", "off", "false", "no")

# ~67 Hz: high enough that a 100 ms commit window holds ~7 samples,
# low enough that the walk (~tens of µs across ~20 threads) stays well
# under the <1% overhead headline; deliberately off 50/60/100 Hz so the
# sampler never aliases against engine timers ticking at round rates.
DEFAULT_HZ = 67.0
# recent-sample ring capacity (samples, all threads pooled): 32768
# samples at ~67 Hz x ~15 threads is ~30 s of history — the "seconds
# before the trip" a watchdog bundle wants
DEFAULT_RING = 1 << 15
_MAX_DEPTH = 64  # frames walked per stack
_LEAF_PROBE = 6  # leaf frames examined by the wait-site classifier
_MAX_FRAMES = 16384  # interned frame-label cap (overflow -> slot 0)
_MAX_STACKS = 32768  # interned stack cap
_MAX_WAITS = 512  # interned wait-site cap
_FLUSH_NS = 1_000_000_000  # EV_PROF window flush cadence

# -- subsystem vocabulary (indexes are the EV_PROF round-column payload
# and the metric label set; bounded, never caller input) ---------------
SUBSYSTEMS = (
    "unknown",  # 0: no name rule and no engine frame matched
    "consensus",  # FSM + gossip routines + timeout ticker
    "p2p",  # mconn send/recv, switch, pex, suspicion
    "mempool",
    "coalescer",  # verify-coalescer executor + readback
    "hashplane",
    "light",
    "blocksync",
    "rpc",
    "statesync",
    "abci",
    "privval",
    "health",  # health monitor, postmortem peer fetch
    "trace",  # trace file sink
    "load",  # load-generator threads (bench/simnet drivers)
    "simnet",
    "main",  # MainThread (CLI, tests, bench drivers)
    "sampler",  # the profiler's own thread: its overhead is visible
    "other",  # a live thread the engine doesn't own
)
_SUB_IDS = {name: i for i, name in enumerate(SUBSYSTEMS)}
_SUB_SAMPLER = _SUB_IDS["sampler"]

STATES = ("on_cpu", "blocked")

# thread-name prefix -> subsystem (first match wins; the engine's
# thread names are stable service names, the same seam the lock
# registry and the goroutine dump lean on)
_NAME_PREFIXES = (
    ("prof-sampler", "sampler"),
    ("cs-", "consensus"),
    ("timeout-ticker", "consensus"),
    ("prestage-", "consensus"),
    ("gossip-", "consensus"),
    ("mconn-", "p2p"),
    ("switch-", "p2p"),
    ("pex-", "p2p"),
    ("p2p-", "p2p"),
    ("peer-", "p2p"),
    ("relay-", "p2p"),
    ("mempool", "mempool"),
    ("verify-", "coalescer"),
    ("hash-", "hashplane"),
    ("light-", "light"),
    ("blocksync-", "blocksync"),
    ("rpc-", "rpc"),
    ("statesync", "statesync"),
    ("abci-", "abci"),
    ("privval-", "privval"),
    ("health-", "health"),
    ("pm-fetch-", "health"),
    ("trace-sink", "trace"),
    ("load-", "load"),
    ("sim-", "simnet"),
    ("MainThread", "main"),
)
_NAME_SUFFIXES = (("-http", "rpc"),)  # "{node}-http" RPC listeners

# frame-path fragment -> subsystem, leaf-first fallback for threads the
# name rules don't know (pytest workers, bare threading.Thread targets)
_FRAME_SUBSYSTEMS = (
    ("cometbft_tpu/crypto/coalesce", "coalescer"),
    ("cometbft_tpu/crypto/hashplane", "hashplane"),
    ("cometbft_tpu/consensus/", "consensus"),
    ("cometbft_tpu/p2p/", "p2p"),
    ("cometbft_tpu/mempool", "mempool"),
    ("cometbft_tpu/light/", "light"),
    ("cometbft_tpu/blocksync/", "blocksync"),
    ("cometbft_tpu/rpc/", "rpc"),
    ("cometbft_tpu/statesync/", "statesync"),
    ("cometbft_tpu/abci/", "abci"),
    ("cometbft_tpu/privval/", "privval"),
    ("cometbft_tpu/simnet/", "simnet"),
    ("cometbft_tpu/libs/health", "health"),
)

# (caller-file suffix, caller func or None=any) -> wait-site name, for
# blocked samples whose leaf is a stdlib Condition/Event wait: the
# CALLER names the queue.  Order matters (specific before catch-all).
_WAIT_CALLERS = (
    ("crypto/coalesce.py", "result", "coalesce.ticket"),
    ("crypto/coalesce.py", None, "coalesce.executor"),
    ("crypto/hashplane.py", "result", "hash.ticket"),
    ("crypto/hashplane.py", None, "hash.executor"),
    ("libs/clist.py", None, "clist.wait"),
    ("libs/service.py", None, "service.wait"),
)


def _env_mode() -> str:
    v = os.environ.get(_ENV, "").lower()
    if v in _ON_VALUES:
        return "on"
    if v in _OFF_VALUES:
        return "off"
    return "auto"


def _hz_from_env() -> float:
    try:
        hz = float(os.environ.get(_ENV_HZ, ""))
    except ValueError:
        return DEFAULT_HZ
    return min(1000.0, max(1.0, hz))


def _ring_from_env() -> int:
    try:
        n = int(os.environ.get(_ENV_RING, ""))
    except ValueError:
        return DEFAULT_RING
    return max(256, n)


# ------------------------------------------------------- intern tables
#
# Written ONLY by the sampler thread; readers index append-only lists,
# so a GIL-consistent racy read sees a prefix, never a torn entry.

_frames: list[str] = ["?"]  # idx -> "module.path:func" (0 = overflow)
# keyed by id(code), NOT the code object: code hashing re-hashes the
# bytecode on every lookup (~160ns); an id key is a pointer hash.  The
# id stays valid because _frame_objs pins every interned code object.
_frame_ids: dict = {}  # id(code object) -> idx
_frame_objs: list = [None]  # idx -> code object (strong ref, pins ids)
_frame_meta: list[tuple] = [("", "")]  # idx -> (co_filename, co_name)
_stacks: list[tuple] = [()]  # idx -> frame-idx tuple, LEAF first
_stack_ids: dict = {(): 0}
_waits: list[str] = [""]  # idx -> wait-site name (0 = none / on-CPU)
_wait_ids: dict = {"": 0}
# sid -> (wait site | None, file-fallback subsystem name): both are pure
# functions of the interned stack, so the sampler classifies each
# distinct stack once and the warm tick is a single dict hit per thread
_stack_info: dict = {}
# thread name -> subsystem name | None (the rule scan, memoized)
_name_subs: dict = {}


def _frame_label(code) -> str:
    fn = code.co_filename.replace("\\", "/")
    i = fn.rfind("cometbft_tpu/")
    if i >= 0:
        mod = fn[i:]
    else:
        mod = fn.rsplit("/", 1)[-1]
    if mod.endswith(".py"):
        mod = mod[:-3]
    return f"{mod.replace('/', '.')}:{code.co_name}"


def _intern_frame(code) -> int:
    idx = _frame_ids.get(id(code))
    if idx is None:
        if len(_frames) >= _MAX_FRAMES:
            return 0
        idx = len(_frames)
        _frames.append(_frame_label(code))
        _frame_objs.append(code)
        _frame_meta.append((code.co_filename, code.co_name))
        _frame_ids[id(code)] = idx
    return idx


def _intern_stack(t: tuple) -> int:
    idx = _stack_ids.get(t)
    if idx is None:
        if len(_stacks) >= _MAX_STACKS:
            return 0
        idx = len(_stacks)
        _stacks.append(t)
        _stack_ids[t] = idx
    return idx


def _intern_wait(name: str) -> int:
    idx = _wait_ids.get(name)
    if idx is None:
        if len(_waits) >= _MAX_WAITS:
            return 0
        idx = len(_waits)
        _waits.append(name)
        _wait_ids[name] = idx
    return idx


# ------------------------------------------------- subsystem resolution


def _subsystem_from_name(name: str) -> str | None:
    for prefix, sub in _NAME_PREFIXES:
        if name.startswith(prefix):
            return sub
    for suffix, sub in _NAME_SUFFIXES:
        if name.endswith(suffix):
            return sub
    return None


def _subsystem_from_files(files) -> str | None:
    """Leaf-first scan of frame file paths for an engine module."""
    for fn in files:
        fn = fn.replace("\\", "/")
        for frag, sub in _FRAME_SUBSYSTEMS:
            if frag in fn:
                return sub
        if "cometbft_tpu/" in fn:
            # engine code outside the named packages (libs, types, ...)
            # inherits nothing from the path — keep scanning callers
            continue
    return None


def subsystem_for(tid: int, name: str, frame=None) -> str:
    """The shared thread->subsystem resolver: thread-name rules first,
    then the frame-module fallback when ``frame`` (the thread's current
    frame) is supplied.  ``/debug/pprof/goroutine`` rows and profiler
    samples attribute threads through this one function."""
    sub = _subsystem_from_name(name)
    if sub is not None:
        return sub
    if frame is not None:
        files = []
        f, depth = frame, 0
        while f is not None and depth < _MAX_DEPTH:
            files.append(f.f_code.co_filename)
            f = f.f_back
            depth += 1
        sub = _subsystem_from_files(files)
        if sub is not None:
            return sub
        if files:
            return "other"
    return "unknown"


def subsystem_name(idx: int) -> str:
    """Decode an EV_PROF round-column subsystem index (libs/health)."""
    return SUBSYSTEMS[idx] if 0 <= idx < len(SUBSYSTEMS) else "?"


def wait_name(idx: int) -> str:
    waits = _waits
    return waits[idx] if 0 <= idx < len(waits) else "?"


# --------------------------------------------------- wait-site registry


def _classify_wait(leaf) -> str | None:
    """Name the wait site from the leaf ``(filename, funcname)`` pairs
    of a blocked-looking stack, or None for on-CPU.  The libs/sync
    blocked-on registry is consulted FIRST by the sampler (it names the
    registered lock exactly); this covers the non-Mutex parks."""
    for i, (fn, func) in enumerate(leaf):
        fn = fn.replace("\\", "/")
        if fn.endswith("threading.py") and func == "wait":
            # a Condition/Event park: the nearest non-threading caller
            # names the queue
            for fn2, func2 in leaf[i + 1:]:
                fn2 = fn2.replace("\\", "/")
                if fn2.endswith("threading.py"):
                    continue
                for suffix, fname, site in _WAIT_CALLERS:
                    if fn2.endswith(suffix) and (
                        fname is None or fname == func2
                    ):
                        return site
                mod = fn2.rsplit("/", 1)[-1]
                return f"cond:{mod[:-3] if mod.endswith('.py') else mod}"
            return "cond:?"
        if fn.endswith("selectors.py") and func == "select":
            return "socket.select"
        if fn.endswith("socketserver.py"):
            return "socket.accept"
        if fn.endswith("queue.py") and func == "get":
            return "queue.get"
        if fn.endswith("consensus/wal.py") and func == "sync":
            return "wal.fsync"
        if "/p2p/" in fn and (
            "recv" in func or "read" in func or func == "accept"
        ):
            return "socket.recv"
    return None


# --------------------------------------------------------- sample store


class _Tables:
    """Preallocated sample columns: the bounded recent-sample ring plus
    the per-(subsystem, state) counter vector the scrape bridge reads.
    Lock-free single-writer (the sampler); readers tolerate one torn
    in-flight row via the publish-last stack column (-1 = in progress),
    the flight-recorder discipline."""

    __slots__ = (
        "gen", "capacity", "ts", "tid", "stack", "sub", "state",
        "wait", "seq", "written", "counts",
    )

    _GEN = itertools.count(1)

    def __init__(self, capacity: int):
        self.gen = next(self._GEN)
        self.capacity = max(256, int(capacity))
        zeros = [0] * self.capacity
        self.ts = array("q", zeros)
        self.tid = array("q", zeros)
        self.stack = array("q", [-1] * self.capacity)
        self.sub = array("q", zeros)
        self.state = array("q", zeros)
        self.wait = array("q", zeros)
        self.seq = itertools.count()
        self.written = array("q", [0])
        self.counts = array("q", [0] * (len(SUBSYSTEMS) * 2))

    def write(self, ts, tid, sid, sub, state, wid) -> None:
        seq = next(self.seq)
        i = seq % self.capacity
        self.stack[i] = -1  # mark in-progress: readers skip torn rows
        self.ts[i] = ts
        self.tid[i] = tid
        self.sub[i] = sub
        self.state[i] = state
        self.wait[i] = wid
        self.stack[i] = sid  # publish last
        if seq >= self.written[0]:
            self.written[0] = seq + 1
        self.counts[sub * 2 + state] += 1

    def rows(self, since_ns: int = 0):
        """(ts, tid, stack_id, sub, state, wait_id) oldest-first over
        the filled window, skipping torn rows."""
        w = self.written[0]
        n = min(w, self.capacity)
        for k in range(w - n, w):
            i = k % self.capacity
            sid = self.stack[i]
            if sid < 0 or self.ts[i] < since_ns:
                continue
            yield (
                self.ts[i], self.tid[i], sid,
                self.sub[i], self.state[i], self.wait[i],
            )

    def status(self) -> dict:
        return {"capacity": self.capacity, "recorded": self.written[0]}


_T = _Tables(_ring_from_env())

# cumulative (stack_id, sub, state, wait_id) -> samples; sampler-thread
# writes, snapshot readers copy under the GIL (dict(d) is one C-level
# copy, safe against a concurrent writer)
_agg: dict = {}

_mode = _env_mode()
_acquirers = 0
_hz = _hz_from_env()
_sampler = None  # the running _SamplerThread, None while disabled

# setup paths only (enable/disable/refcount + sampler lifecycle); the
# sample path and every snapshot reader are lock-free — asserted
# edge-free in tests/test_lint_graph.py like the other plane mutexes
_mtx = libsync.Mutex("libs.profile._mtx")


# ------------------------------------------------------------- sampler


class _SamplerThread(threading.Thread):
    def __init__(self, hz: float):
        super().__init__(name="prof-sampler", daemon=True)
        self.period_ns = int(1e9 / hz)
        self._stop_ev = threading.Event()
        # EV_PROF window accumulator: per-(sub, state) samples since
        # the last once-per-second ring flush
        self._win = [0] * (len(SUBSYSTEMS) * 2)
        self._last_flush = time.monotonic_ns()
        # tid -> thread name, refreshed lazily: on a tid we have not
        # seen (new thread) and at every 1 s flush (drops dead tids)
        self._names: dict = {}

    def stop(self) -> None:
        self._stop_ev.set()

    def run(self) -> None:
        interval = self.period_ns / 1e9
        while not self._stop_ev.wait(interval):
            try:
                self._tick()
            except Exception:
                # a sampler crash must never take the node with it
                pass
        # flush the tail window so short profiled runs still emit rows
        try:
            self._flush(time.monotonic_ns())
        except Exception:
            pass

    def _tick(self) -> None:
        t = _T
        me = threading.get_ident()
        now = time.time_ns()
        names = self._names
        blocked = libsync._all_blocked
        win = self._win
        frame_ids = _frame_ids
        stack_ids = _stack_ids
        stack_info = _stack_info
        name_subs = _name_subs
        wait_ids = _wait_ids
        agg = _agg
        meta = _frame_meta
        for tid, frame in sys._current_frames().items():
            fids = []
            append = fids.append
            f, depth = frame, 0
            while f is not None and depth < _MAX_DEPTH:
                code = f.f_code
                idx = frame_ids.get(id(code))
                if idx is None:
                    idx = _intern_frame(code)
                append(idx)
                f = f.f_back
                depth += 1
            key = tuple(fids)
            sid = stack_ids.get(key)
            if sid is None:
                sid = _intern_stack(key)
            info = stack_info.get(sid) if sid else None
            if info is None:
                # first sight of this stack: classify the wait site and
                # the frame-module fallback once, from the interned
                # frame metadata (never re-walk live frame objects)
                leaf = [meta[i] for i in fids[:_LEAF_PROBE]]
                files = [meta[i][0] for i in fids]
                info = (
                    _classify_wait(leaf),
                    _subsystem_from_files(files)
                    or ("other" if files else "unknown"),
                )
                if sid:
                    stack_info[sid] = info
            wait_site, files_sub = info
            if tid == me:
                sub = _SUB_SAMPLER
            else:
                nm = names.get(tid)
                if nm is None:
                    names = self._names = {
                        th.ident: th.name for th in threading.enumerate()
                    }
                    nm = names.get(tid, "")
                try:
                    subname = name_subs[nm]
                except KeyError:
                    subname = _subsystem_from_name(nm)
                    if len(name_subs) < 4096:
                        name_subs[nm] = subname
                if subname is None:
                    subname = files_sub
                sub = _SUB_IDS[subname]
            cell = blocked.get(tid)
            if cell is not None and cell[0] is not None:
                wait = "lock:" + cell[0]
            else:
                wait = wait_site
            if wait is not None:
                state = 1
                wid = wait_ids.get(wait)
                if wid is None:
                    wid = _intern_wait(wait)
            else:
                state, wid = 0, 0
            t.write(now, tid, sid, sub, state, wid)
            akey = (sid, sub, state, wid)
            agg[akey] = agg.get(akey, 0) + 1
            win[sub * 2 + state] += 1
        mono = time.monotonic_ns()
        if mono - self._last_flush >= _FLUSH_NS:
            self._flush(mono)

    def _flush(self, mono: int) -> None:
        """Emit one EV_PROF flight-ring row per subsystem that sampled
        in the window: r = subsystem index, a = estimated on-CPU ns
        (on-CPU samples x the sampling period), b = total samples."""
        self._names = {th.ident: th.name for th in threading.enumerate()}
        win = self._win
        if not any(win):
            self._last_flush = mono
            return
        from . import health  # lazy: health imports this module at top

        if health.enabled():
            for sub in range(len(SUBSYSTEMS)):
                on, bl = win[sub * 2], win[sub * 2 + 1]
                if on or bl:
                    health.record(
                        health.EV_PROF, 0, sub,
                        on * self.period_ns, on + bl,
                    )
        for i in range(len(win)):
            win[i] = 0
        self._last_flush = mono


# ------------------------------------------------------ plane lifecycle


def enabled() -> bool:
    """Whether the sampler thread is live."""
    s = _sampler
    return s is not None and s.is_alive()


def _start_locked() -> None:
    global _sampler
    if _sampler is None or not _sampler.is_alive():
        _sampler = _SamplerThread(_hz)
        _sampler.start()


def _stop_locked() -> None:
    global _sampler
    s, _sampler = _sampler, None
    if s is not None:
        s.stop()
        s.join(timeout=2.0)


def enable(hz: float | None = None) -> None:
    """Force the sampler on (tests, bench, the endpoint's live window).
    ``hz`` overrides the sampling rate for the new sampler."""
    global _hz
    if _env_mode() == "off":
        return
    with _mtx:
        if hz is not None and hz != _hz:
            _hz = min(1000.0, max(1.0, float(hz)))
            _stop_locked()
        _start_locked()


def disable() -> None:
    with _mtx:
        _stop_locked()


def acquire() -> None:
    """Reference-counted enable for node lifecycles (the devstats
    pattern): every booting node acquires, so the sampler runs exactly
    while a node does — unless ``COMETBFT_TPU_PROF=0`` pins it off."""
    global _acquirers
    if _env_mode() == "off":
        return
    with _mtx:
        _acquirers += 1
        _start_locked()


def release() -> None:
    global _acquirers
    with _mtx:
        _acquirers = max(0, _acquirers - 1)
        if _acquirers == 0 and _env_mode() != "on":
            _stop_locked()


def reset(capacity: int | None = None) -> None:
    """Drop buffered samples and aggregates (tests, bench windows)."""
    global _T
    with _mtx:
        _T = _Tables(capacity if capacity is not None else _T.capacity)
        _agg.clear()


def status() -> dict:
    return {
        "enabled": enabled(),
        "mode": _env_mode(),
        "hz": _hz,
        "acquirers": _acquirers,
        "ring": _T.status(),
        "frames": len(_frames),
        "stacks": len(_stacks),
        "wait_sites": len(_waits),
    }


# ---------------------------------------------------------- aggregates


def snapshot_agg() -> dict:
    """A point-in-time copy of the cumulative aggregate: (stack_id,
    sub, state, wait_id) -> samples.  Two snapshots subtract into a
    window (the ``?seconds=N`` endpoint's delta)."""
    return dict(_agg)


def delta_agg(before: dict, after: dict) -> dict:
    out = {}
    for k, v in after.items():
        d = v - before.get(k, 0)
        if d > 0:
            out[k] = d
    return out


def collapsed(agg: dict | None = None) -> str:
    """Flamegraph-compatible collapsed stacks, one line per distinct
    (subsystem, state, wait, stack): ``sub;state[;wait];root;..;leaf N``
    — pipe into flamegraph.pl or paste into speedscope as-is."""
    if agg is None:
        agg = snapshot_agg()
    frames, stacks, waits = _frames, _stacks, _waits
    lines = []
    for (sid, sub, state, wid), n in sorted(agg.items()):
        parts = [subsystem_name(sub), STATES[state & 1]]
        if wid:
            parts.append(waits[wid] if wid < len(waits) else "?")
        st = stacks[sid] if sid < len(stacks) else ()
        parts.extend(frames[f] if f < len(frames) else "?" for f in reversed(st))
        lines.append(";".join(parts) + f" {n}")
    return "\n".join(lines) + ("\n" if lines else "")


def profile_dict(agg: dict | None = None) -> dict:
    """The JSON shape of a profile window (the ``&format=json`` body
    and the bundle's ``profile.json`` core): per-(subsystem, state)
    totals plus every distinct stack with its attribution."""
    if agg is None:
        agg = snapshot_agg()
    frames, stacks, waits = _frames, _stacks, _waits
    subs: dict = {}
    out_stacks = []
    for (sid, sub, state, wid), n in sorted(agg.items()):
        sname = subsystem_name(sub)
        st = subs.setdefault(sname, {"on_cpu": 0, "blocked": 0})
        st[STATES[state & 1]] += n
        stk = stacks[sid] if sid < len(stacks) else ()
        out_stacks.append({
            "subsystem": sname,
            "state": STATES[state & 1],
            "wait": (waits[wid] if wid < len(waits) else "?") if wid else None,
            "samples": n,
            "stack": [
                frames[f] if f < len(frames) else "?"
                for f in reversed(stk)
            ],
        })
    return {
        "schema": 1,
        "hz": _hz,
        "samples": sum(agg.values()),
        "subsystems": dict(sorted(subs.items())),
        "stacks": out_stacks,
    }


def recent(last_s: float = 30.0) -> dict:
    """Aggregate the recent-sample ring's last ``last_s`` seconds — the
    pre-trip view watchdog bundles and ``debug dump`` capture."""
    since = time.time_ns() - int(last_s * 1e9)
    agg: dict = {}
    for ts, _tid, sid, sub, state, wid in _T.rows(since):
        key = (sid, sub, state, wid)
        agg[key] = agg.get(key, 0) + 1
    out = profile_dict(agg)
    out["window_s"] = last_s
    return out


def bundle_snapshot(last_s: float = 30.0) -> dict:
    """The ``profile.json`` black-box artifact: plane status + the
    ring's pre-trip window in both JSON and collapsed form."""
    since = time.time_ns() - int(last_s * 1e9)
    agg: dict = {}
    for ts, _tid, sid, sub, state, wid in _T.rows(since):
        key = (sid, sub, state, wid)
        agg[key] = agg.get(key, 0) + 1
    out = profile_dict(agg)
    out["window_s"] = last_s
    return {
        "status": status(),
        "recent": out,
        "collapsed": collapsed(agg),
    }


def profile_window(seconds: float, fmt: str = "collapsed") -> str:
    """The ``/debug/pprof/profile`` body.  ``seconds > 0`` holds an
    acquire (so the sampler runs even on a node with the plane idle),
    sleeps, and returns the window's delta; ``seconds <= 0`` serves the
    recent-sample ring without waiting — the pre-trip path bundles and
    ``debug dump`` use."""
    import json as _json

    if seconds > 0:
        if _env_mode() == "off":
            return f"profiler pinned off ({_ENV}=0)\n"
        seconds = min(60.0, seconds)
        acquire()
        try:
            before = snapshot_agg()
            time.sleep(seconds)
            agg = delta_agg(before, snapshot_agg())
        finally:
            release()
        if fmt == "json":
            out = profile_dict(agg)
            out["window_s"] = seconds
            return _json.dumps(out, default=str)
        return collapsed(agg)
    if fmt == "json":
        return _json.dumps(recent(), default=str)
    return collapsed()


# ------------------------------------------------------- scrape bridge


def sample(metrics=None) -> None:
    """Bridge the per-(subsystem, state) sample counters into
    ``profile_samples_total`` from a per-registry watermark — pull-time
    work on the scrape path, zero cost on the sample path (the
    txtrace/lockprof bridge pattern; libs/health.sample calls this)."""
    if metrics is not None:
        m = metrics
    else:
        from . import metrics as libmetrics

        m = libmetrics.node_metrics()
    fam = getattr(m, "profile_samples", None)
    if fam is None:
        return
    t = _T
    wm = getattr(m, "_profile_wm", None)
    if wm is None or wm["gen"] != t.gen:
        wm = m._profile_wm = {
            "gen": t.gen, "counts": [0] * len(t.counts),
        }
    counts = wm["counts"]
    for i in range(len(t.counts)):
        v = t.counts[i]
        d = v - counts[i]
        if d > 0:
            fam.labels(SUBSYSTEMS[i // 2], STATES[i % 2]).inc(d)
        counts[i] = v


# ------------------------------------------------- simnet module shares


def module_shares(agg: dict) -> dict:
    """Split a window's samples into scheduler vs verify vs engine wall
    shares by frame module — the simnet ``--profile`` report.  A simnet
    run executes on ONE scheduler thread, so thread attribution is
    useless there; the leaf-most classifiable frame says whose code the
    interpreter was actually in."""
    frames, stacks = _frames, _stacks
    totals = {"scheduler": 0, "verify": 0, "engine": 0, "other": 0}
    for (sid, _sub, _state, _wid), n in agg.items():
        bucket = "other"
        st = stacks[sid] if sid < len(stacks) else ()
        for f in st:  # leaf first
            label = frames[f] if f < len(frames) else "?"
            if label.startswith((
                "cometbft_tpu.crypto.", "cometbft_tpu.ops.",
            )):
                bucket = "verify"
                break
            if label.startswith("cometbft_tpu.simnet"):
                bucket = "scheduler"
                break
            if label.startswith("cometbft_tpu."):
                bucket = "engine"
                break
        totals[bucket] += n
    total = sum(totals.values())
    return {
        "samples": total,
        "shares": {
            k: round(v / total, 4) if total else 0.0
            for k, v in totals.items()
        },
    }
