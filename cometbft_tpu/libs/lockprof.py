"""Lock-contention profiler: per-lock wait/hold accounting by registry slot.

PR 16's sanitizers prove the engine's locking is *correct* (acyclic
order, guards held); nothing measured what the locks *cost*.  This
module is that accounting plane: every named mutex/RLock built through
the ``libs/sync`` factories records, per lockorder.json registry name,
how often an acquire had to wait, for how long, and how long the lock
was then held — the ground truth the pipelined-heights refactor needs
to know which serialized resource actually gates each commit.

* **Slots** — the value space is the shipped lockorder.json registry
  (``devtools/lint/graph``): its lock names, sorted, plus one trailing
  ``other`` slot for unregistered ad-hoc names.  Bounded by
  construction, so the ``lock`` metric label can be audited against the
  same artifact the sanitizers validate.

* **Columns** — acquires, contended acquires, wait-ns, hold-ns and a
  per-slot wait histogram accumulate into preallocated lock-free
  ``array('q')`` columns (the netstats/devledger posture:
  single-scalar GIL-atomic stores; a lost increment under a rare
  cross-thread race costs one tally, never a corrupt structure).  The
  enabled record path retains ZERO allocations and takes no lock —
  pinned by the tracemalloc guard in tests/test_observability.py.

* **Slow path** — a wait or hold past the ``COMETBFT_TPU_LOCKPROF_SLOW_MS``
  threshold emits an EV_LOCK flight-ring row (libs/health) carrying the
  lock slot, the duration and the holder's interned acquire site, so a
  black-box bundle names the blocker, not just the victim.  Site
  interning allocates — slow-path only, never per acquire.

Scrape surface: :func:`sample` bridges the monotone columns into each
scraped registry's ``lock_wait_seconds_total{lock}`` /
``lock_hold_seconds_total{lock}`` / ``lock_contended_acquires_total{lock}``
counters from per-registry watermarks (the devledger replay pattern);
:func:`snapshot` is the ``/debug/contention`` and ``contention.json``
body; :func:`worst_windowed_p99` is the ``lock_contended`` watchdog's
delta-histogram signal.

Knobs (registered in config.ENV_KNOBS, enforced by cometlint CLNT007):
``COMETBFT_TPU_LOCKPROF`` (auto: on while a node runs, refcounted like
netstats/devledger; 1 force; 0 off — the kill switch makes the sync
factories hand out raw ``threading`` primitives again) and
``COMETBFT_TPU_LOCKPROF_SLOW_MS`` (slow wait/hold threshold for both
EV_LOCK emission and the watchdog's p99 trip line).

This module imports NOTHING from the sync/health layers at module
level (sync imports it to wire the profiled lock tier; health imports
it to decode EV_LOCK rows) — the one upward call, EV_LOCK emission,
lazily imports health on the slow path only.  The one lock here
(``_sites_mtx``, a raw ``threading.Lock``) serializes only slow-path
site interning, never the record path.
"""

from __future__ import annotations

import json
import os
import threading
from array import array

_ENV_LOCKPROF = "COMETBFT_TPU_LOCKPROF"
_ENV_SLOW_MS = "COMETBFT_TPU_LOCKPROF_SLOW_MS"

_ON_VALUES = ("1", "on", "true", "yes")
_OFF_VALUES = ("0", "off", "false", "no")

# EV_LOCK kind codes (the low bit of the ring row's b column)
KIND_WAIT = 0
KIND_HOLD = 1
KIND_NAMES = {KIND_WAIT: "wait", KIND_HOLD: "hold"}


def _env_mode() -> str:
    v = os.environ.get(_ENV_LOCKPROF, "").lower()
    if v in _ON_VALUES:
        return "on"
    if v in _OFF_VALUES:
        return "off"
    return "auto"


def slow_threshold_s() -> float:
    """Wait/hold duration (seconds) above which an acquire/release
    emits an EV_LOCK ring row, and the windowed p99 above which the
    lock_contended watchdog trips (default 50 ms)."""
    try:
        return float(os.environ.get(_ENV_SLOW_MS, "")) / 1e3
    except ValueError:
        return 0.050


# -- registry slots ------------------------------------------------------
#
# The FIXED value space of the ``lock`` label: the shipped lockorder.json
# registry names, sorted, plus one trailing "other" slot for
# unregistered ad-hoc names (kept out of the metrics bridge so the
# exported label stays bounded by the artifact).


def _registry_path() -> str:
    return os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "devtools", "lint", "graph", "lockorder.json",
    )


def _load_registry_names() -> tuple[str, ...]:
    try:
        with open(_registry_path(), encoding="utf-8") as f:
            data = json.load(f)
        return tuple(sorted(lk["name"] for lk in data.get("locks", [])))
    except Exception:
        return ()


_REGISTRY = _load_registry_names()
N_SLOTS = len(_REGISTRY)  # registered slots; OTHER_SLOT sits past them
OTHER_SLOT = N_SLOTS
NAMES = _REGISTRY + ("other",)
_SLOT_OF = {name: i for i, name in enumerate(_REGISTRY)}


def slot_for(name: str) -> int:
    """Registry slot of a lock name ("other" for unregistered names) —
    resolved once at lock construction, never on the record path."""
    return _SLOT_OF.get(name, OTHER_SLOT)


def slot_name(slot: int) -> str:
    return NAMES[slot] if 0 <= slot < len(NAMES) else "other"


# -- enable gating (the devstats/devledger refcount pattern) -------------

_enabled: bool = _env_mode() == "on"
_acquirers = 0
_slow_ns = max(0, int(slow_threshold_s() * 1e9))


def enabled() -> bool:
    return _enabled


def enable() -> None:
    global _enabled, _slow_ns
    _slow_ns = max(0, int(slow_threshold_s() * 1e9))
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def set_slow_ms(ms: float) -> None:
    """Programmatic analog of ``COMETBFT_TPU_LOCKPROF_SLOW_MS``
    (tests, bench storms) — takes effect immediately."""
    global _slow_ns
    _slow_ns = max(0, int(ms * 1e6))


def acquire() -> None:
    """Reference-counted enable for node lifecycles: the profiler is on
    exactly while a node runs unless ``COMETBFT_TPU_LOCKPROF=0``."""
    global _acquirers, _enabled, _slow_ns
    if _env_mode() == "off":
        return
    _acquirers += 1
    _slow_ns = max(0, int(slow_threshold_s() * 1e9))
    _enabled = True


def release() -> None:
    global _acquirers, _enabled
    _acquirers = max(0, _acquirers - 1)
    if _acquirers == 0 and _env_mode() != "on":
        _enabled = False


# -- storage -------------------------------------------------------------
#
# Flat preallocated columns indexed by registry slot.  The wait
# histogram gives the watchdog a real windowed p99 (delta buckets, the
# device_queue_wait pattern) instead of a mean that a single outlier
# hides in; bounds are ns, chosen to straddle the 50 ms default
# threshold.

BUCKET_NS = (
    1_000_000,  # 1 ms
    5_000_000,
    10_000_000,
    25_000_000,
    50_000_000,
    100_000_000,
    250_000_000,
    500_000_000,
    1_000_000_000,  # 1 s
)
N_BUCKETS = len(BUCKET_NS) + 1  # + overflow

_N_CELLS = N_SLOTS + 1  # + the "other" slot

_acquires = array("q", [0] * _N_CELLS)
_contended = array("q", [0] * _N_CELLS)
_wait_ns = array("q", [0] * _N_CELLS)
_hold_ns = array("q", [0] * _N_CELLS)
_hist = array("q", [0] * (_N_CELLS * N_BUCKETS))

# slow-path holder-site intern table (EV_LOCK's b column carries
# ``site_idx * 2 + kind``); index 0 is the unknown site
_SITES: list[str] = ["?"]
_SITE_IDX: dict[str, int] = {"?": 0}
# cometlint: disable=CLNT001 -- the profiler's own meta-lock must NOT
# route through the sync factories it instruments (recursion), and it
# serializes slow-path site interning only, never the record path
_sites_mtx = threading.Lock()  # cometlint: disable=CLNT001 -- see above


def reset() -> None:
    """Zero every column (tests, bench windows).  The site table is
    append-only interning and survives — indices in already-recorded
    ring rows must keep decoding."""
    for col in (_acquires, _contended, _wait_ns, _hold_ns, _hist):
        for i in range(len(col)):
            col[i] = 0


# -- record helpers (called from the libs/sync profiled tier) ------------


def note_contended(slot: int, wait_ns: int) -> None:
    """One acquire that had to block for ``wait_ns``.  Already the slow
    half of an acquire (the caller blocked), but still allocation- and
    lock-free: plain column stores plus a bounded bucket scan."""
    _contended[slot] += 1
    if wait_ns > 0:
        _wait_ns[slot] += wait_ns
    base = slot * N_BUCKETS
    k = 0
    for bound in BUCKET_NS:
        if wait_ns <= bound:
            break
        k += 1
    _hist[base + k] += 1


def intern_site(site: str) -> int:
    """Slow-path only: intern a "file:line" holder site -> index."""
    idx = _SITE_IDX.get(site)
    if idx is None:
        with _sites_mtx:
            idx = _SITE_IDX.get(site)
            if idx is None:
                idx = len(_SITES)
                _SITES.append(site)
                _SITE_IDX[site] = idx
    return idx


def site_name(idx: int) -> str:
    sites = _SITES
    return sites[idx] if 0 <= idx < len(sites) else "?"


def note_slow(slot: int, kind: int, dur_ns: int, site: str) -> None:
    """A wait or hold crossed the slow threshold: emit the EV_LOCK
    flight-ring row naming the lock, the duration and the holder's
    acquire site.  Slow-path: may allocate and intern.  Swallows every
    failure — this runs inside lock acquire/release, and a telemetry
    fault propagating there would leave the caller's lock state
    corrupt."""
    try:
        from . import health  # lazy: health imports this module at top

        health.record(
            health.EV_LOCK, 0, slot, dur_ns, intern_site(site) * 2 + kind
        )
    except Exception:
        pass


def slow_ns() -> int:
    """The live slow threshold in ns (the sync tier reads the module
    global directly on its record path; this is the test surface)."""
    return _slow_ns


# -- read paths (scrape / watchdog / debug) ------------------------------


def counts(slot: int) -> dict:
    return {
        "acquires": _acquires[slot],
        "contended": _contended[slot],
        "wait_ns": _wait_ns[slot],
        "hold_ns": _hold_ns[slot],
    }


def _hist_p99(counts_row: list, total: int) -> float:
    """Upper-bound p99 (seconds) of one slot's bucket counts."""
    target = total - total // 100  # ceil-ish rank of the 99th pct
    seen = 0
    for k in range(N_BUCKETS):
        seen += counts_row[k]
        if seen >= target:
            if k < len(BUCKET_NS):
                return BUCKET_NS[k] / 1e9
            return 2 * BUCKET_NS[-1] / 1e9
    return 0.0


def wait_p99_s(slot: int) -> float | None:
    """Cumulative (not windowed) p99 wait of one slot, for snapshots."""
    base = slot * N_BUCKETS
    row = [0] * N_BUCKETS
    total = 0
    for k in range(N_BUCKETS):
        row[k] = _hist[base + k]
        total += row[k]
    if total == 0:
        return None
    return _hist_p99(row, total)


def worst_windowed_p99(prev: array) -> tuple[int, float]:
    """The lock_contended watchdog's signal: per REGISTERED slot, the
    p99 wait of the contended acquires observed since the last call
    (bucket deltas against ``prev``, a caller-preallocated
    ``array('q')`` of ``N_SLOTS * N_BUCKETS`` watermarks, updated in
    place).  Returns ``(slot, p99_s)`` of the worst lock this window,
    or ``(-1, 0.0)`` when no registered lock saw a contended acquire.
    Plain loops and int temporaries only — the no-trip check path must
    retain nothing (the _qfull posture in libs/health)."""
    worst_slot = -1
    worst_p99 = 0.0
    row = [0] * N_BUCKETS  # transient scratch, reused per slot
    for slot in range(N_SLOTS):  # "other" is not an engine lock
        base = slot * N_BUCKETS
        total = 0
        for k in range(N_BUCKETS):
            cur = _hist[base + k]
            row[k] = cur - prev[base + k]
            prev[base + k] = cur
            total += row[k]
        if total <= 0:
            continue
        p99 = _hist_p99(row, total)
        if p99 > worst_p99:
            worst_p99 = p99
            worst_slot = slot
    return (worst_slot, worst_p99)


def snapshot() -> dict:
    """The per-lock contention body of ``/debug/contention`` and
    ``contention.json``: every slot that saw an acquire, with derived
    seconds and the cumulative p99 wait; ``hottest`` names the lock
    with the largest total wait."""
    locks: dict[str, dict] = {}
    hottest = None
    hottest_wait = 0
    total_wait = 0
    total_hold = 0
    for slot in range(_N_CELLS):
        acq = _acquires[slot]
        cont = _contended[slot]
        if acq == 0 and cont == 0:
            continue
        w = _wait_ns[slot]
        h = _hold_ns[slot]
        total_wait += w
        total_hold += h
        if w > hottest_wait:
            hottest_wait = w
            hottest = NAMES[slot]
        locks[NAMES[slot]] = {
            "acquires": acq,
            "contended": cont,
            "wait_s": round(w / 1e9, 6),
            "hold_s": round(h / 1e9, 6),
            "wait_p99_s": wait_p99_s(slot),
        }
    return {
        "enabled": _enabled,
        "slow_threshold_s": round(_slow_ns / 1e9, 6),
        "registered_locks": N_SLOTS,
        "locks": locks,
        "hottest": hottest,
        "total_wait_s": round(total_wait / 1e9, 6),
        "total_hold_s": round(total_hold / 1e9, 6),
    }


def sample(metrics=None) -> None:
    """Bridge the monotone columns into ``metrics``' counter families
    from per-registry watermarks (the devledger replay pattern).  The
    "other" slot is deliberately NOT exported: the ``lock`` label stays
    bounded by the lockorder.json registry."""
    from . import metrics as libmetrics

    m = metrics if metrics is not None else libmetrics.node_metrics()
    wm = getattr(m, "_lockprof_wm", None)
    if wm is None:
        wm = m._lockprof_wm = {}
    for slot in range(N_SLOTS):
        w = _wait_ns[slot]
        h = _hold_ns[slot]
        c = _contended[slot]
        if w == 0 and h == 0 and c == 0 and slot not in wm:
            continue  # never-contended slot: keep the scrape sparse
        seen_w, seen_h, seen_c = wm.get(slot, (0, 0, 0))
        name = NAMES[slot]
        if w > seen_w:
            m.lock_wait.labels(name).inc((w - seen_w) / 1e9)
        if h > seen_h:
            m.lock_hold.labels(name).inc((h - seen_h) / 1e9)
        if c > seen_c:
            m.lock_contended.labels(name).inc(c - seen_c)
        wm[slot] = (w, h, c)
