"""Tagged-JSON codec for persisting/transporting framework types.

The reference serializes everything with generated protobuf
(proto/tendermint/*, 34k LoC). This framework keeps consensus-critical
byte strings hand-encoded (types/proto.py — those must be byte-exact) and
uses this self-describing JSON codec for storage records and non-canonical
wire payloads, where only round-trip fidelity matters.

Encoding rules: dataclasses carry a ``__t`` class tag; bytes are hex under
``__b``; IntEnums are ints (re-coerced from the declared field type on
decode); adapters cover non-dataclass types (key objects, ValidatorSet).
"""

from __future__ import annotations

import dataclasses
import json
import typing
from enum import IntEnum
from typing import Any, Callable


class Codec:
    def __init__(self) -> None:
        self._types: dict[str, type] = {}
        self._hints: dict[type, dict[str, Any]] = {}
        # cls -> (tag, enc, dec); tag -> (cls, enc, dec)
        self._adapters_by_cls: dict[type, tuple[str, Callable, Callable]] = {}
        self._adapters_by_tag: dict[str, tuple[type, Callable, Callable]] = {}

    def register(self, *classes: type) -> None:
        for cls in classes:
            if not dataclasses.is_dataclass(cls):
                raise TypeError(f"{cls.__name__} is not a dataclass")
            self._types[cls.__name__] = cls

    def register_adapter(
        self,
        cls: type,
        tag: str,
        enc: Callable[[Any], Any],
        dec: Callable[[Any], Any],
    ) -> None:
        """enc(obj) -> jsonable payload; dec(payload) -> obj."""
        self._adapters_by_cls[cls] = (tag, enc, dec)
        self._adapters_by_tag[tag] = (cls, enc, dec)

    # -- encode ------------------------------------------------------------

    def encode(self, v: Any) -> Any:
        adapter = self._adapters_by_cls.get(type(v))
        if adapter is not None:
            tag, enc, _ = adapter
            return {"__a": tag, "v": self.encode(enc(v))}
        if dataclasses.is_dataclass(v) and not isinstance(v, type):
            name = type(v).__name__
            if name not in self._types:
                raise TypeError(f"unregistered dataclass {name}")
            d: dict[str, Any] = {"__t": name}
            for f in dataclasses.fields(v):
                # Underscore fields are in-memory caches (e.g. Commit._hash)
                # — serializing them breaks canonical byte equality.
                if f.name.startswith("_"):
                    continue
                d[f.name] = self.encode(getattr(v, f.name))
            return d
        if isinstance(v, bytes):
            return {"__b": v.hex()}
        if isinstance(v, bool) or v is None:
            return v
        if isinstance(v, IntEnum):
            return int(v)
        if isinstance(v, (int, float, str)):
            return v
        if isinstance(v, (list, tuple)):
            return [self.encode(x) for x in v]
        if isinstance(v, dict):
            return {"__d": [[self.encode(k), self.encode(x)] for k, x in v.items()]}
        raise TypeError(f"cannot encode {type(v).__name__}")

    # -- decode ------------------------------------------------------------

    def _field_hints(self, cls: type) -> dict[str, Any]:
        if cls not in self._hints:
            try:
                self._hints[cls] = typing.get_type_hints(cls)
            except Exception:
                self._hints[cls] = {}
        return self._hints[cls]

    def decode(self, v: Any, hint: Any = None) -> Any:
        if isinstance(v, dict):
            if "__a" in v:
                _, _, dec = self._adapters_by_tag[v["__a"]]
                return dec(self.decode(v["v"]))
            if "__b" in v:
                return bytes.fromhex(v["__b"])
            if "__d" in v:
                return {
                    self.decode(k): self.decode(x) for k, x in v["__d"]
                }
            if "__t" in v:
                cls = self._types[v["__t"]]
                hints = self._field_hints(cls)
                kwargs = {
                    k: self.decode(x, hints.get(k))
                    for k, x in v.items()
                    if k != "__t"
                }
                return cls(**kwargs)
            raise ValueError(f"unknown tagged object: {list(v)}")
        if isinstance(v, list):
            out = [self.decode(x) for x in v]
            if typing.get_origin(hint) is tuple:
                return tuple(out)
            return out
        if (
            isinstance(v, int)
            and not isinstance(v, bool)
            and isinstance(hint, type)
            and issubclass(hint, IntEnum)
        ):
            return hint(v)
        return v

    # -- bytes round-trip --------------------------------------------------

    def dumps(self, obj: Any) -> bytes:
        return json.dumps(self.encode(obj), separators=(",", ":")).encode()

    def loads(self, data: bytes) -> Any:
        return self.decode(json.loads(data))
