"""Profiling/introspection HTTP server (reference: the net/http/pprof
server gated by RPC config ``pprof_laddr`` — node/node.go:651-664 — plus
the JAX-profiler hooks that replace Go's CPU profiles on a TPU node).

Endpoints (all GET, plain text or JSON):

  /debug/pprof/            index
  /debug/pprof/goroutine   every thread's stack (goroutine dump analog)
  /debug/pprof/heap        tracemalloc top allocations (heap profile)
  /debug/pprof/profile     sampling profiler (libs/profile): ?seconds=N
                           captures a live window; without it the
                           recent-sample ring is served (collapsed
                           stacks, or &format=json)
  /debug/jax/start_trace?dir=PATH   start a JAX profiler trace (TensorBoard
                                    format) capturing kernel launches
  /debug/jax/stop_trace             stop it
  /debug/locks             deadlock-tier status (libs/sync)
  /debug/contention        per-lock wait/hold profile + critical path
  /debug/devstats          device/XLA telemetry snapshot (libs/devstats)
  /debug/trace             libs/trace ring-buffer dump (JSON)
  /debug/trace/start?file=PATH   enable the span tracer (+ optional
                                 JSONL file sink at PATH on the node host)
  /debug/trace/stop        disable the tracer and close the file sink

The debug CLI (``cometbft-tpu debug dump|kill``) scrapes these into a
crash bundle the way cmd/cometbft/commands/debug does with pprof URLs.
"""

from __future__ import annotations

import io
import json
import sys
import threading
import traceback

from .service import HTTPService


def thread_dump() -> str:
    """All live threads' stacks — the goroutine-dump analog.

    Each header also names the lock the thread is currently blocked on
    (and for how long), from libs/sync's blocked-on registry, plus the
    thread's subsystem attribution — resolved by the SAME resolver the
    sampling profiler uses (libs/profile.subsystem_for), so stack dumps
    and profiles attribute threads identically — so a bundle's
    threads.txt answers "who is waiting on whom" without
    cross-referencing /debug/contention."""
    import time

    names = {t.ident: t.name for t in threading.enumerate()}
    try:
        from . import sync as libsync

        held = libsync.held_locks_snapshot()
    except Exception:
        held = {}
    try:
        from . import profile as libprofile

        resolve = libprofile.subsystem_for
    except Exception:
        def resolve(tid, name, frame=None):
            return "?"
    now = time.monotonic_ns()
    out = io.StringIO()
    for tid, frame in sys._current_frames().items():
        name = names.get(tid, "?")
        sub = resolve(tid, name if name != "?" else "", frame)
        out.write(f"--- thread {tid} ({name}) [{sub}] ---\n")
        info = held.get(tid)
        if info:
            if info.get("held"):
                locks = ", ".join(
                    name for name, _site in info["held"]
                )
                out.write(f"    holding: {locks}\n")
            blocked = info.get("blocked_on")
            if blocked is not None:
                since = info.get("blocked_since_ns")
                if since:
                    wait_s = max(0.0, (now - since) / 1e9)
                    out.write(
                        f"    blocked on: {blocked} "
                        f"(for {wait_s:.3f}s)\n"
                    )
                else:
                    out.write(f"    blocked on: {blocked}\n")
        traceback.print_stack(frame, file=out)
        out.write("\n")
    return out.getvalue()


def heap_start() -> str:
    """Explicitly enable tracemalloc (interpreter-wide allocation
    tracking has real overhead — never switched on by a mere scrape)."""
    import tracemalloc

    if tracemalloc.is_tracing():
        return "tracemalloc already tracing\n"
    tracemalloc.start()
    return "tracemalloc started\n"


def heap_stop() -> str:
    import tracemalloc

    if not tracemalloc.is_tracing():
        return "tracemalloc not tracing\n"
    tracemalloc.stop()
    return "tracemalloc stopped\n"


def heap_dump(top: int = 40) -> str:
    """tracemalloc top allocation sites. Read-only: reports process RSS
    plus, when tracing was explicitly enabled via /debug/heap/start, the
    top allocation sites — so a one-shot debug-dump bundle always gets a
    useful artifact without permanently instrumenting the node."""
    import resource
    import tracemalloc

    rss_kb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    head = f"max rss: {rss_kb / 1024:.1f} MB\n"
    if not tracemalloc.is_tracing():
        return head + (
            "tracemalloc off (enable with /debug/heap/start for "
            "per-site allocation stats)\n"
        )
    snap = tracemalloc.take_snapshot()
    lines = [str(s) for s in snap.statistics("lineno")[:top]]
    total = sum(s.size for s in snap.statistics("filename"))
    return (
        head
        + f"total traced: {total / 1e6:.1f} MB\n"
        + "\n".join(lines)
        + "\n"
    )


class _TraceState:
    active_dir: str | None = None


def start_jax_trace(trace_dir: str) -> str:
    import jax

    if _TraceState.active_dir is not None:
        return f"trace already active at {_TraceState.active_dir}"
    jax.profiler.start_trace(trace_dir)
    _TraceState.active_dir = trace_dir
    return f"tracing to {trace_dir}"


def stop_jax_trace() -> str:
    import jax

    if _TraceState.active_dir is None:
        return "no active trace"
    jax.profiler.stop_trace()
    d, _TraceState.active_dir = _TraceState.active_dir, None
    return f"trace written to {d}"


# One-line operator docs per route, rendered into the index page.  The
# index is GENERATED from the live route map (every registered route
# appears, with its doc line when one exists), and the tier-1
# completeness gate in tests/test_observability.py pins exactly that —
# a new debug plane cannot silently ship an unlisted route.
ROUTE_DOCS: dict[str, str] = {
    "/debug/pprof/goroutine": "thread stacks",
    "/debug/pprof/heap": "rss + tracemalloc snapshot",
    "/debug/pprof/profile": (
        "?seconds=N  sampling profile window (collapsed stacks; "
        "&format=json; no seconds serves the recent-sample ring)"
    ),
    "/debug/heap/start": "enable tracemalloc",
    "/debug/heap/stop": "disable tracemalloc",
    "/debug/jax/start_trace": "?dir=PATH  start a JAX profiler trace",
    "/debug/jax/stop_trace": "stop the JAX profiler trace",
    "/debug/locks": "deadlock-tier status",
    "/debug/devstats": "device/XLA telemetry (JSON)",
    "/debug/health": "flight-recorder SLIs + watchdogs (JSON)",
    "/debug/budget": (
        "device-time ledger + per-height latency budgets (JSON)"
    ),
    "/debug/net": "per-peer/per-channel p2p telemetry (JSON)",
    "/debug/tx": (
        "sampled tx-lifecycle plane; ?key=<hex-prefix> looks one "
        "transaction up (JSON)"
    ),
    "/debug/flight": (
        "raw flight-ring export (JSON; the cross-node merge input "
        "peers pull)"
    ),
    "/debug/timeline": (
        "merged height timelines + root-cause verdicts (JSON; "
        "?peer=URL fans in)"
    ),
    "/debug/contention": (
        "per-lock wait/hold profile + per-height critical path (JSON)"
    ),
    "/debug/trace": "span-tracer ring dump",
    "/debug/trace/start": "?file=PATH  enable the span tracer",
    "/debug/trace/stop": "disable the tracer, close the sink",
}


class PprofServer(HTTPService):
    """Tiny threaded HTTP server bound to ``pprof_laddr`` (scaffolding
    shared with the Prometheus exporter via ``libs/service.HTTPService``)."""

    def __init__(self, addr: str, logger=None):
        super().__init__("pprof", addr, logger)
        self._route_map = self._routes()

    def handle_get(self, path: str, query: dict) -> tuple[str, str]:
        fn = self._route_map.get(path)
        if fn is None:
            raise KeyError(path)
        return "text/plain; charset=utf-8", fn(query)

    def index_text(self) -> str:
        """The index body, generated from the registered routes so a
        new route can never be omitted from the listing."""
        lines = ["cometbft-tpu pprof"]
        for path in sorted(self._route_map):
            if path in ("/debug/pprof", "/debug/pprof/"):
                continue  # the index's own aliases
            doc = ROUTE_DOCS.get(path, "")
            lines.append(f"{path:<24} {doc}".rstrip())
        return "\n".join(lines) + "\n"

    def _routes(self):
        def index(q):
            return self.index_text()

        def goroutine(q):
            return thread_dump()

        def heap(q):
            return heap_dump(int(q.get("top", ["40"])[0]))

        def profile(q):
            from . import profile as libprofile

            secs = q.get("seconds")
            fmt = q.get("format", ["collapsed"])[0]
            return libprofile.profile_window(
                float(secs[0]) if secs else 0.0, fmt
            )

        def heap_on(q):
            return heap_start()

        def heap_off(q):
            return heap_stop()

        def jax_start(q):
            dirs = q.get("dir")
            if not dirs:
                raise ValueError("missing ?dir=")
            return start_jax_trace(dirs[0])

        def jax_stop(q):
            return stop_jax_trace()

        def locks(q):
            from . import sync as libsync

            return json.dumps(
                {
                    "deadlock_detection": libsync.enabled(),
                    "timeout_s": libsync.DEADLOCK_TIMEOUT,
                }
            )

        def devstats_dump(q):
            from . import devstats as libdevstats

            return libdevstats.debug_devstats_json()

        def health_dump(q):
            from . import health as libhealth

            return libhealth.debug_health_json(
                tail=int(q.get("tail", ["100"])[0])
            )

        def net_dump(q):
            from . import netstats as libnetstats

            return libnetstats.debug_net_json()

        def tx_dump(q):
            # "where is my transaction": ?key=<hex prefix> (up to the
            # retained 16 chars; a full 64-char tx-key hex works and
            # is truncated) — no key returns the plane snapshot
            from . import txtrace as libtxtrace

            keys = q.get("key")
            return libtxtrace.debug_tx_json(keys[0] if keys else None)

        def budget_dump(q):
            from . import health as libhealth

            return libhealth.debug_budget_json()

        def flight_dump(q):
            from . import health as libhealth

            return json.dumps(libhealth.export_ring(), default=str)

        def timeline_dump(q):
            # the local node's per-height timelines + attribution;
            # ?peer=URL (repeatable) merges reachable peers' rings in
            from .. import postmortem

            return json.dumps(
                postmortem.debug_timeline(peers=q.get("peer", [])),
                default=str,
            )

        def contention_dump(q):
            from . import health as libhealth

            return libhealth.debug_contention_json()

        def trace_dump(q):
            from . import trace as libtrace

            out = libtrace.status()
            out["events"] = libtrace.ring_dump()
            return json.dumps(out, default=str)

        def trace_start(q):
            from . import trace as libtrace

            # sink FIRST: if the path can't be opened the request 500s
            # with tracing still off, instead of silently enabling a
            # ring-only tracer the operator thinks failed
            files = q.get("file")
            if files:
                started = libtrace.start_file_sink(files[0])
                libtrace.enable()
                sink = (
                    f"sink started at {files[0]}"
                    if started
                    else "sink already active"
                )
                return f"tracing on; {sink}\n"
            libtrace.enable()
            return "tracing on (ring only)\n"

        def trace_stop(q):
            from . import trace as libtrace

            libtrace.disable()
            closed = libtrace.stop_file_sink()
            return "tracing off" + ("; sink closed\n" if closed else "\n")

        return {
            "/debug/pprof/": index,
            "/debug/pprof": index,
            "/debug/pprof/goroutine": goroutine,
            "/debug/pprof/heap": heap,
            "/debug/pprof/profile": profile,
            "/debug/heap/start": heap_on,
            "/debug/heap/stop": heap_off,
            "/debug/jax/start_trace": jax_start,
            "/debug/jax/stop_trace": jax_stop,
            "/debug/locks": locks,
            "/debug/devstats": devstats_dump,
            "/debug/health": health_dump,
            "/debug/budget": budget_dump,
            "/debug/net": net_dump,
            "/debug/tx": tx_dump,
            "/debug/flight": flight_dump,
            "/debug/timeline": timeline_dump,
            "/debug/contention": contention_dump,
            "/debug/trace": trace_dump,
            "/debug/trace/start": trace_start,
            "/debug/trace/stop": trace_stop,
        }
