"""Device-time ledger: who used the shared device planes, and for how long.

The verify coalescer (crypto/coalesce.py) and the hash plane
(crypto/hashplane.py) are genuinely multi-tenant — consensus vote and
commit verification, the light proof service, mempool/PartSet hashing,
and blocksync all coalesce lanes into the same device windows — yet
until this layer nothing attributed device time, lane share, or queue
delay to a caller.  This module is that accounting plane:

* **Caller classes** — every routed submit carries a caller class
  declared by the OUTERMOST tenant via the :func:`caller_class`
  thread-local (the ``request_deadline`` pattern): consensus-vote,
  commit-verify, proposal, light, mempool, blocksync, evidence,
  merkle, or "other" when nobody declared.  Outermost wins: the light
  service's "light" is not overwritten by the commit-verify walk it
  delegates to — attribution names the tenant, not the mechanism.

* **The ledger** — per-(plane, caller) lanes, tickets, queue-wait and
  pro-rata window execute/host-fallback time accumulate into
  preallocated lock-free ``array('q')`` columns (the netstats pattern:
  single-writer-per-plane record paths, GIL-atomic scalar stores, a
  lost increment under a rare cross-thread race costs one tally, never
  a corrupt structure).  The enabled record path retains ZERO
  allocations — pinned by the tracemalloc guard in
  tests/test_observability.py alongside the flight recorder's.

* **Occupancy** — per-plane executor-busy, readback and measured
  readback/execute overlap columns, derived at scrape time into busy
  fraction and drain overlap efficiency (how much of the d2h readback
  actually hid under the next window's pack+dispatch).

Scrape surface: :func:`sample` bridges the monotone columns into each
scraped registry's ``device_time_seconds_total{plane,caller}`` /
``device_lanes_total{plane,caller}`` counters from per-registry
watermarks (the devstats replay pattern — multi-node scrapes each see
the full series); :func:`snapshot` is the ``/debug/budget`` and
``budget.json`` ledger body; :func:`reconcile` is the tier-1 oracle
that caller-attributed time sums to total window time within 1%.

Knobs (registered in config.ENV_KNOBS, enforced by cometlint CLNT007):
``COMETBFT_TPU_LEDGER`` (auto: on while a node runs, refcounted like
devstats/health; 1 force; 0 off) and
``COMETBFT_TPU_LEDGER_STARVE_MS`` (consensus queue-wait p99 threshold
of the consensus-starvation watchdog in libs/health).

No locks: registration-free by construction — the one shared mutable
state is the preallocated column set, and thread-locals carry the
caller declaration.
"""

from __future__ import annotations

import os
import threading
import time
from array import array

_ENV_LEDGER = "COMETBFT_TPU_LEDGER"
_ENV_STARVE_MS = "COMETBFT_TPU_LEDGER_STARVE_MS"

_ON_VALUES = ("1", "on", "true", "yes")
_OFF_VALUES = ("0", "off", "false", "no")

# -- caller classes ------------------------------------------------------
#
# A FIXED enum: the ``caller`` label of every exported family, so the
# cardinality audit can pin its value space.  Index 0 is the
# unattributed default; appending is fine, reordering is not (the
# columns are indexed by these codes).
CALLERS = (
    "other",
    "consensus-vote",
    "commit-verify",
    "proposal",
    "light",
    "mempool",
    "blocksync",
    "evidence",
    "merkle",
)
CALLER_CODES = {name: i for i, name in enumerate(CALLERS)}
N_CALLERS = len(CALLERS)

# -- planes --------------------------------------------------------------
PLANES = ("verify", "hash")
PLANE_VERIFY = 0
PLANE_HASH = 1
N_PLANES = len(PLANES)

# Caller classes whose verify/hash plane time blocks the consensus FSM —
# the share the per-height latency budget (libs/health.budget) charges
# to its verify/hash stages, and the consensus side of the starvation
# watchdog's lane-share test.  Vote admission, the proposal signature
# check and commit verification all run on (or block) the FSM thread;
# merkle (PartSet/header roots) and the mempool's commit-path key batch
# are the hash plane's FSM-adjacent callers (CheckTx key hashing rides
# the same class from RPC/p2p threads — documented approximation).
BUDGET_VERIFY_CALLERS = frozenset(
    CALLER_CODES[c] for c in ("consensus-vote", "commit-verify", "proposal")
)
BUDGET_HASH_CALLERS = frozenset(
    CALLER_CODES[c] for c in ("merkle", "mempool")
)

_TLS = threading.local()


class caller_class:
    """Declare the caller class for routed submits on this thread.

    OUTERMOST wins: a nested declaration (the light service delegating
    into the commit-verify walk, a mempool update batching through the
    merkle-tagged hash helpers) is a no-op, so attribution always names
    the tenant that entered the engine, not the innermost mechanism.
    Unknown names map to "other" rather than raising — a bad tag must
    never break a verify path.

    A plain ``__slots__`` context manager, not a generator-based
    ``@contextmanager``: tag sites sit on per-item hot paths (every
    vote verify, every TxKey, every merkle leaf), and the generator
    frame + wrapper object would roughly double the cost of a small
    host hash just to set one thread-local int.
    """

    __slots__ = ("_cid", "_prev")

    def __init__(self, name: str):
        self._cid = CALLER_CODES.get(name, 0)

    def __enter__(self):
        prev = getattr(_TLS, "cid", 0)
        self._prev = prev
        if prev == 0 and self._cid:
            _TLS.cid = self._cid
        return self

    def __exit__(self, exc_type, exc, tb):
        _TLS.cid = self._prev
        return False


def current_caller() -> int:
    """The caller-class code routed submits on this thread carry."""
    return getattr(_TLS, "cid", 0)


def caller_name(cid: int) -> str:
    return CALLERS[cid] if 0 <= cid < N_CALLERS else "other"


# -- enable gating (the devstats/health refcount pattern) ----------------


def _env_mode() -> str:
    v = os.environ.get(_ENV_LEDGER, "").lower()
    if v in _ON_VALUES:
        return "on"
    if v in _OFF_VALUES:
        return "off"
    return "auto"


def starve_threshold_s() -> float:
    """Consensus queue-wait p99 (seconds) above which the starvation
    watchdog considers consensus starved (default 50 ms)."""
    try:
        return float(os.environ.get(_ENV_STARVE_MS, "")) / 1e3
    except ValueError:
        return 0.050


_enabled: bool = _env_mode() == "on"
_acquirers = 0


def enabled() -> bool:
    return _enabled


def enable() -> None:
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def acquire() -> None:
    """Reference-counted enable for node lifecycles: the ledger is on
    exactly while a node runs unless ``COMETBFT_TPU_LEDGER=0``."""
    global _acquirers, _enabled
    if _env_mode() == "off":
        return
    _acquirers += 1
    _enabled = True


def release() -> None:
    global _acquirers, _enabled
    _acquirers = max(0, _acquirers - 1)
    if _acquirers == 0 and _env_mode() != "on":
        _enabled = False


# -- storage -------------------------------------------------------------
#
# Per-(plane, caller) cells, flat-indexed plane * N_CALLERS + caller.
# All columns preallocated; the record path performs only C-level
# scalar loads/stores and small-int arithmetic.

_N_CELLS = N_PLANES * N_CALLERS

_lanes = array("q", [0] * _N_CELLS)
_tickets = array("q", [0] * _N_CELLS)
_wait_ns = array("q", [0] * _N_CELLS)
_exec_ns = array("q", [0] * _N_CELLS)  # pro-rata device window execute
_host_ns = array("q", [0] * _N_CELLS)  # pro-rata host-fallback time

# per-plane columns
_p_windows = array("q", [0] * N_PLANES)
_p_dev_windows = array("q", [0] * N_PLANES)
_p_window_lanes = array("q", [0] * N_PLANES)
_p_window_ns = array("q", [0] * N_PLANES)  # total window execute time
_p_exec_busy_ns = array("q", [0] * N_PLANES)  # executor pack+dispatch/host
_p_exec_since = array("q", [0] * N_PLANES)  # 0 = executor idle
_p_readback_ns = array("q", [0] * N_PLANES)  # drain materialization time
_p_overlap_ns = array("q", [0] * N_PLANES)  # readback under executor busy
_p_first_ns = array("q", [0] * N_PLANES)  # activity watermarks (monotonic)
_p_last_ns = array("q", [0] * N_PLANES)


def reset() -> None:
    """Zero every column (tests, bench windows)."""
    for col in (
        _lanes, _tickets, _wait_ns, _exec_ns, _host_ns,
        _p_windows, _p_dev_windows, _p_window_lanes, _p_window_ns,
        _p_exec_busy_ns, _p_exec_since, _p_readback_ns, _p_overlap_ns,
        _p_first_ns, _p_last_ns,
    ):
        for i in range(len(col)):
            col[i] = 0


# -- record paths --------------------------------------------------------


def note_resolve(
    plane: int, caller: int, lanes: int, wait_ns: int,
    exec_share_ns: int, host_share_ns: int,
) -> None:
    """One resolved ticket: ``lanes`` verified/hashed for ``caller``
    after ``wait_ns`` in the pending queue, charged pro-rata shares of
    the window's device execute and host-fallback time SEPARATELY —
    a mixed hash window (one bucket launched, one hashed inline) splits
    honestly instead of mislabeling host work as device time.  Called
    by the planes' resolve paths — executor or drain thread, never a
    caller thread."""
    if not _enabled:
        return
    i = plane * N_CALLERS + caller
    _lanes[i] += lanes
    _tickets[i] += 1
    if wait_ns > 0:
        _wait_ns[i] += wait_ns
    if exec_share_ns > 0:
        _exec_ns[i] += exec_share_ns
    if host_share_ns > 0:
        _host_ns[i] += host_share_ns


def note_window(plane: int, lanes: int, device: bool) -> None:
    """One flushed window entering launch (plane-grain counters)."""
    if not _enabled:
        return
    _p_windows[plane] += 1
    _p_window_lanes[plane] += lanes
    if device:
        _p_dev_windows[plane] += 1
    now = time.monotonic_ns()
    if _p_first_ns[plane] == 0:
        _p_first_ns[plane] = now
    _p_last_ns[plane] = now


def note_window_time(plane: int, exec_ns: int) -> None:
    """The window's total execute/fallback duration — the denominator
    the per-caller pro-rata shares must reconcile against."""
    if not _enabled:
        return
    if exec_ns > 0:
        _p_window_ns[plane] += exec_ns
    _p_last_ns[plane] = time.monotonic_ns()


def exec_begin(plane: int) -> None:
    """Executor entered its busy section (pack+dispatch, or the host
    window resolve) — the overlap estimator's busy marker."""
    if not _enabled:
        return
    _p_exec_since[plane] = time.monotonic_ns()


def exec_end(plane: int) -> None:
    """Executor left its busy section; banks the busy duration."""
    if not _enabled:
        return
    since = _p_exec_since[plane]
    if since:
        _p_exec_busy_ns[plane] += time.monotonic_ns() - since
        _p_exec_since[plane] = 0


def exec_busy_ns(plane: int) -> int:
    """Cumulative executor-busy ns (the drain snapshots this around a
    readback to measure overlap)."""
    return _p_exec_busy_ns[plane]


def note_readback(plane: int, t0_ns: int, busy0_ns: int) -> None:
    """One drain-side readback finished: ``t0_ns`` was its
    ``monotonic_ns`` start, ``busy0_ns`` the :func:`exec_busy_ns`
    snapshot taken then.  The overlap credit is the executor-busy time
    that elapsed DURING the readback (banked sections plus a live
    in-progress one), clamped to the readback duration — an estimate,
    exact when the executor's busy sections nest cleanly inside or
    around the readback window, and documented as such."""
    if not _enabled:
        return
    now = time.monotonic_ns()
    dur = now - t0_ns
    if dur <= 0:
        return
    overlap = _p_exec_busy_ns[plane] - busy0_ns
    since = _p_exec_since[plane]
    if since:
        live = now - (since if since > t0_ns else t0_ns)
        if live > 0:
            overlap += live
    if overlap < 0:
        overlap = 0
    elif overlap > dur:
        overlap = dur
    _p_readback_ns[plane] += dur
    _p_overlap_ns[plane] += overlap


# -- read paths (scrape / watchdog / tests) ------------------------------


def cell(plane: int, caller: int) -> dict:
    i = plane * N_CALLERS + caller
    return {
        "lanes": _lanes[i],
        "tickets": _tickets[i],
        "wait_ns": _wait_ns[i],
        "exec_ns": _exec_ns[i],
        "host_ns": _host_ns[i],
    }


def verify_lanes_split() -> tuple[int, int]:
    """(consensus-caller lanes, total lanes) on the verify plane — the
    starvation watchdog's lane-share signal.  Plain loops, no
    comprehension frames (the no-trip check path posture)."""
    cons = 0
    total = 0
    base = PLANE_VERIFY * N_CALLERS
    for c in range(N_CALLERS):
        n = _lanes[base + c]
        total += n
        if c in BUDGET_VERIFY_CALLERS:
            cons += n
    return cons, total


def reconcile() -> dict:
    """Caller-attributed time vs total window time, per plane.

    ``ratio`` is attributed/total (1.0 = perfect); integer pro-rata
    floor division loses at most one nanosecond per ticket, so the
    tier-1 gate pins ``|1 - ratio| <= 0.01`` for any real burst."""
    out = {}
    for p, plane in enumerate(PLANES):
        attributed = 0
        lanes = 0
        base = p * N_CALLERS
        for c in range(N_CALLERS):
            attributed += _exec_ns[base + c] + _host_ns[base + c]
            lanes += _lanes[base + c]
        total = _p_window_ns[p]
        out[plane] = {
            "attributed_ns": attributed,
            "window_ns": total,
            "caller_lanes": lanes,
            "window_lanes": _p_window_lanes[p],
            "ratio": (attributed / total) if total else None,
        }
    return out


def occupancy() -> dict:
    """The device occupancy view, derived from the plane columns:
    busy fraction (executor-busy plus non-overlapped readback over the
    plane's active wall span) and the readback drain's overlap
    efficiency (fraction of d2h time hidden under the next window's
    pack+dispatch)."""
    out = {}
    for p, plane in enumerate(PLANES):
        first, last = _p_first_ns[p], _p_last_ns[p]
        span = last - first
        busy = _p_exec_busy_ns[p] + _p_readback_ns[p] - _p_overlap_ns[p]
        rb = _p_readback_ns[p]
        out[plane] = {
            "windows": _p_windows[p],
            "device_windows": _p_dev_windows[p],
            "window_lanes": _p_window_lanes[p],
            "window_exec_s": round(_p_window_ns[p] / 1e9, 6),
            "executor_busy_s": round(_p_exec_busy_ns[p] / 1e9, 6),
            "readback_s": round(rb / 1e9, 6),
            "overlap_s": round(_p_overlap_ns[p] / 1e9, 6),
            "busy_fraction": (
                round(min(1.0, busy / span), 4) if span > 0 else None
            ),
            "overlap_efficiency": (
                round(_p_overlap_ns[p] / rb, 4) if rb > 0 else None
            ),
            "active_span_s": round(span / 1e9, 6) if span > 0 else 0.0,
        }
    return out


def snapshot() -> dict:
    """The ledger body of ``/debug/budget`` and ``budget.json``."""
    callers: dict[str, dict] = {}
    for p, plane in enumerate(PLANES):
        rows = {}
        for c, name in enumerate(CALLERS):
            i = p * N_CALLERS + c
            if _tickets[i] == 0 and _lanes[i] == 0:
                continue
            rows[name] = {
                "lanes": _lanes[i],
                "tickets": _tickets[i],
                "queue_wait_s": round(_wait_ns[i] / 1e9, 6),
                "execute_s": round(_exec_ns[i] / 1e9, 6),
                "host_s": round(_host_ns[i] / 1e9, 6),
            }
        callers[plane] = rows
    return {
        "enabled": _enabled,
        "callers": callers,
        "occupancy": occupancy(),
        "reconciliation": reconcile(),
    }


def sample(metrics=None) -> None:
    """Bridge the monotone ledger columns into ``metrics``' counter
    families from per-registry watermarks (the devstats replay
    pattern), so every scraped registry sees the full series regardless
    of how many nodes share the process."""
    from . import metrics as libmetrics

    m = metrics if metrics is not None else libmetrics.node_metrics()
    wm = getattr(m, "_devledger_wm", None)
    if wm is None:
        wm = m._devledger_wm = {}
    for p, plane in enumerate(PLANES):
        for c, name in enumerate(CALLERS):
            i = p * N_CALLERS + c
            time_ns = _exec_ns[i] + _host_ns[i]
            lanes = _lanes[i]
            if time_ns == 0 and lanes == 0 and (plane, name) not in wm:
                continue  # never-used cell: keep the scrape sparse
            seen_t, seen_l = wm.get((plane, name), (0, 0))
            if time_ns > seen_t:
                m.device_time.labels(plane, name).inc(
                    (time_ns - seen_t) / 1e9
                )
            if lanes > seen_l:
                m.device_lanes.labels(plane, name).inc(lanes - seen_l)
            wm[(plane, name)] = (time_ns, lanes)
