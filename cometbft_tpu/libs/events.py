"""Internal event switch (reference: libs/events/events.go:247).

The consensus reactor uses this lighter-weight bus (distinct from the
pubsub EventBus) to observe the consensus state's round transitions —
string event keys, no query language, synchronous fan-out in listener
registration order.
"""

from __future__ import annotations

from . import sync as libsync
from typing import Any, Callable

EventCallback = Callable[[Any], None]


class EventSwitch:
    def __init__(self) -> None:
        self._mtx = libsync.RLock("libs.events._mtx")
        # event -> {listener_id: callback}
        self._cells: dict[str, dict[str, EventCallback]] = {}

    def add_listener_for_event(
        self, listener_id: str, event: str, cb: EventCallback
    ) -> None:
        with self._mtx:
            self._cells.setdefault(event, {})[listener_id] = cb

    def remove_listener_for_event(self, event: str, listener_id: str) -> None:
        with self._mtx:
            cell = self._cells.get(event)
            if cell:
                cell.pop(listener_id, None)
                if not cell:
                    del self._cells[event]

    def remove_listener(self, listener_id: str) -> None:
        with self._mtx:
            for event in list(self._cells):
                self.remove_listener_for_event(event, listener_id)

    def fire_event(self, event: str, data: Any = None) -> None:
        with self._mtx:
            cbs = list(self._cells.get(event, {}).values())
        for cb in cbs:
            cb(data)
