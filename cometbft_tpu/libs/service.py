"""Service lifecycle primitives (reference: libs/service/service.go:24,97).

The reference's ``BaseService`` gives every long-lived component a uniform
start/stop/reset contract with idempotency guarantees (started twice →
``ErrAlreadyStarted``; stopped before started → error) and a ``Quit`` channel.
Here the same contract is a small thread-safe state machine; the quit channel
becomes a ``threading.Event`` that Python code can ``wait()`` on.
"""

from __future__ import annotations

import threading
from . import sync as libsync


class ServiceError(Exception):
    pass


class AlreadyStartedError(ServiceError):
    pass


class AlreadyStoppedError(ServiceError):
    pass


class NotStartedError(ServiceError):
    pass


class BaseService:
    """Uniform lifecycle: ``start() -> on_start()``, ``stop() -> on_stop()``.

    Subclasses override ``on_start``/``on_stop``/``on_reset``. Mirrors
    libs/service/service.go:97 (BaseService) without the logger plumbing —
    logging is injected via the ``logger`` attribute.
    """

    def __init__(self, name: str | None = None, logger=None):
        self._name = name or type(self).__name__
        self._mtx = libsync.Mutex("libs.service._mtx")
        self._started = False
        self._stopped = False
        self._quit = threading.Event()
        self.logger = logger

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        with self._mtx:
            if self._stopped:
                raise AlreadyStoppedError(
                    f"{self._name}: stopped services cannot be restarted; "
                    "use reset()"
                )
            if self._started:
                raise AlreadyStartedError(self._name)
            self._started = True
        try:
            self.on_start()
        except BaseException:
            with self._mtx:
                self._started = False
            raise

    def stop(self) -> None:
        with self._mtx:
            if self._stopped:
                raise AlreadyStoppedError(self._name)
            if not self._started:
                raise NotStartedError(self._name)
            self._stopped = True
        self._quit.set()
        self.on_stop()

    def reset(self) -> None:
        with self._mtx:
            if not self._stopped:
                raise ServiceError(f"{self._name}: cannot reset a running service")
            self._started = False
            self._stopped = False
            self._quit = threading.Event()
        self.on_reset()

    # -- queries -----------------------------------------------------------

    def is_running(self) -> bool:
        with self._mtx:
            return self._started and not self._stopped

    def quit_event(self) -> threading.Event:
        """The analog of the reference's ``Quit()`` channel."""
        return self._quit

    def wait(self, timeout: float | None = None) -> bool:
        """Block until the service stops (Quit closes)."""
        return self._quit.wait(timeout)

    @property
    def name(self) -> str:
        return self._name

    def __str__(self) -> str:
        return self._name

    # -- overridables ------------------------------------------------------

    def on_start(self) -> None:  # pragma: no cover - trivial default
        pass

    def on_stop(self) -> None:  # pragma: no cover - trivial default
        pass

    def on_reset(self) -> None:  # pragma: no cover - trivial default
        pass


class HTTPService(BaseService):
    """A threaded HTTP listener with the BaseService lifecycle.

    The shared scaffolding of the introspection servers (the pprof
    server in ``libs/pprof``, the Prometheus exporter in
    ``libs/devstats``): ``tcp://host:port`` / ``:port`` address
    parsing, a quiet handler, the daemon accept loop, ``bound_port``
    capture, shutdown. Subclasses implement
    ``handle_get(path, query) -> (content_type, body)`` and raise
    ``KeyError`` for unknown routes (rendered as 404; any other
    exception renders as 500).
    """

    DEFAULT_HOST = "127.0.0.1"  # debug servers stay loopback by default

    def __init__(self, name: str, addr: str, logger=None):
        super().__init__(name, logger)
        if addr.startswith("tcp://"):
            addr = addr[len("tcp://") :]
        host, _, port = addr.rpartition(":")
        self.host = host or self.DEFAULT_HOST
        self.port = int(port)
        self._httpd = None

    def handle_get(self, path: str, query: dict) -> tuple[str, str]:
        raise KeyError(path)  # pragma: no cover - subclass contract

    def on_start(self) -> None:
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
        from urllib.parse import parse_qs, urlparse

        svc = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def do_GET(self):
                parsed = urlparse(self.path)
                try:
                    ctype, text = svc.handle_get(
                        parsed.path, parse_qs(parsed.query)
                    )
                except KeyError:
                    self.send_error(404)
                    return
                except Exception as e:
                    self.send_error(500, repr(e))
                    return
                body = text.encode()
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self.bound_port = self._httpd.server_address[1]
        threading.Thread(
            target=self._httpd.serve_forever,
            name=f"{self._name}-http",
            daemon=True,
        ).start()

    def on_stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
