"""Service lifecycle primitives (reference: libs/service/service.go:24,97).

The reference's ``BaseService`` gives every long-lived component a uniform
start/stop/reset contract with idempotency guarantees (started twice →
``ErrAlreadyStarted``; stopped before started → error) and a ``Quit`` channel.
Here the same contract is a small thread-safe state machine; the quit channel
becomes a ``threading.Event`` that Python code can ``wait()`` on.
"""

from __future__ import annotations

import threading
from . import sync as libsync


class ServiceError(Exception):
    pass


class AlreadyStartedError(ServiceError):
    pass


class AlreadyStoppedError(ServiceError):
    pass


class NotStartedError(ServiceError):
    pass


class BaseService:
    """Uniform lifecycle: ``start() -> on_start()``, ``stop() -> on_stop()``.

    Subclasses override ``on_start``/``on_stop``/``on_reset``. Mirrors
    libs/service/service.go:97 (BaseService) without the logger plumbing —
    logging is injected via the ``logger`` attribute.
    """

    def __init__(self, name: str | None = None, logger=None):
        self._name = name or type(self).__name__
        self._mtx = libsync.Mutex("libs.service._mtx")
        self._started = False
        self._stopped = False
        self._quit = threading.Event()
        self.logger = logger

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        with self._mtx:
            if self._stopped:
                raise AlreadyStoppedError(
                    f"{self._name}: stopped services cannot be restarted; "
                    "use reset()"
                )
            if self._started:
                raise AlreadyStartedError(self._name)
            self._started = True
        try:
            self.on_start()
        except BaseException:
            with self._mtx:
                self._started = False
            raise

    def stop(self) -> None:
        with self._mtx:
            if self._stopped:
                raise AlreadyStoppedError(self._name)
            if not self._started:
                raise NotStartedError(self._name)
            self._stopped = True
        self._quit.set()
        self.on_stop()

    def reset(self) -> None:
        with self._mtx:
            if not self._stopped:
                raise ServiceError(f"{self._name}: cannot reset a running service")
            self._started = False
            self._stopped = False
            self._quit = threading.Event()
        self.on_reset()

    # -- queries -----------------------------------------------------------

    def is_running(self) -> bool:
        with self._mtx:
            return self._started and not self._stopped

    def quit_event(self) -> threading.Event:
        """The analog of the reference's ``Quit()`` channel."""
        return self._quit

    def wait(self, timeout: float | None = None) -> bool:
        """Block until the service stops (Quit closes)."""
        return self._quit.wait(timeout)

    @property
    def name(self) -> str:
        return self._name

    def __str__(self) -> str:
        return self._name

    # -- overridables ------------------------------------------------------

    def on_start(self) -> None:  # pragma: no cover - trivial default
        pass

    def on_stop(self) -> None:  # pragma: no cover - trivial default
        pass

    def on_reset(self) -> None:  # pragma: no cover - trivial default
        pass
