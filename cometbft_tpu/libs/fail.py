"""Env-gated crash points for fault-injection testing.

Reference: libs/fail/fail.go:28 + the FAIL_TEST_INDEX callsites at
state/execution.go:247-297 and consensus/state.go:1753-1820. Crash tests
spawn a real node process with ``COMETBFT_TPU_FAIL=<point-name>``; when
execution reaches that named point the process dies HARD (os._exit — no
cleanup, no flushes beyond what the code already fsynced), and the test
restarts the node asserting WAL/handshake recovery.

Points are free when the env var is unset: one dict lookup.
"""

from __future__ import annotations

import os
import sys

ENV_VAR = "COMETBFT_TPU_FAIL"

_target = os.environ.get(ENV_VAR, "")
_handler = None


def fail_point(name: str) -> None:
    """Die hard if this named point is the injection target."""
    if _target and name == _target:
        if _handler is not None:
            # In-process crash simulation (the simnet scenario engine):
            # the handler either raises — "this node just died" without
            # taking down the whole multi-node process — or returns to
            # skip (e.g. the armed point belongs to a different sim
            # node). Subprocess tests keep the os._exit semantics.
            _handler(name)
            return
        sys.stderr.write(f"FAIL POINT HIT: {name} — crashing\n")
        sys.stderr.flush()
        os._exit(99)


def set_target(name: str) -> None:
    """Test helper: arm a point in-process (subprocess tests use the env)."""
    global _target
    _target = name


def set_handler(fn) -> None:
    """Install (or clear, with None) the in-process crash handler used
    by simnet scenarios; see :func:`fail_point`."""
    global _handler
    _handler = fn


# -- delay points (gray-failure injection) ------------------------------
#
# Crash points model fail-stop; DELAY points model slow-but-alive — the
# gray failures (a disk whose fsync takes 200 ms, a store write stuck
# behind a saturated volume) that kill production clusters without ever
# tripping a liveness check.  A delay point is free when no handler is
# installed: one global read.  The simnet scenario engine installs a
# handler that charges VIRTUAL latency to the current sim node (on the
# sim clock, deterministic); live fault-injection tests may install one
# that really sleeps.

_delay_handler = None


def delay_point(name: str) -> None:
    """Charge the injected latency for this named point, if armed."""
    if _delay_handler is not None:
        _delay_handler(name)


def set_delay_handler(fn) -> None:
    """Install (or clear, with None) the slow-path handler used by the
    simnet slow-disk injection; see :func:`delay_point`."""
    global _delay_handler
    _delay_handler = fn
