"""Accelerator-measured bench table loading — the one home for every
measured knob's data source.

bench.py persists each chip-measured capture to the repo-root
``BENCH_CHIP_TABLE.json``; the knobs that steer production off it
(crypto/batch.HOST_BATCH_THRESHOLD's crossover tier,
ops/verify's auto pallas-flavor selection) load it through here so the
resolution rules, the accelerator-trust gate, and the malformed-file
robustness cannot drift between consumers.
"""

from __future__ import annotations

import json
import os

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def load_chip_table():
    """The last ACCELERATOR-measured bench table as a dict, or None.

    Resolution: the ``COMETBFT_TPU_CHIP_TABLE`` env override, else the
    repo-root ``BENCH_CHIP_TABLE.json`` (anchored — a CWD-relative open
    would silently miss the table for any process not started in the
    repo root, and trust an unrelated same-named file that is).
    Host-fallback tables (``measured_on_accelerator`` false) return
    None: they must never steer a measured knob. Malformed files (parse
    errors, non-dict shapes) also return None rather than raise — the
    knobs they feed sit on every verify dispatch path.
    """
    path = os.environ.get("COMETBFT_TPU_CHIP_TABLE") or os.path.join(
        _REPO_ROOT, "BENCH_CHIP_TABLE.json"
    )
    try:
        with open(path) as f:
            table = json.load(f)
    except (OSError, ValueError):
        return None
    if isinstance(table, dict) and table.get("measured_on_accelerator"):
        return table
    return None


def find_row(table, config: str):
    """The named config row of a loaded table, or None."""
    if not isinstance(table, dict):
        return None
    rows = table.get("table")
    if not isinstance(rows, list):
        return None
    for row in rows:
        if isinstance(row, dict) and row.get("config") == config:
            return row
    return None
