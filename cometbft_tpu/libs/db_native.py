"""NativeDB: the C++ storage engine behind the DB interface.

The reference's production nodes run cgo storage backends (cleveldb /
rocksdb via cometbft-db, config/config.go:256); this is that tier for
the framework — cometbft_tpu/native/nkv.cpp compiled on first use with
the baked-in g++ and driven through ctypes (pybind11 is not in the
image). Same on-disk guarantees as libs/db.FileDB: CRC-framed append
log, atomic batches (one framed record), torn-tail tolerance,
live-set compaction.

Select with ``db_backend = "native"``; construction raises if the
toolchain or compile is unavailable, and node assembly falls back to
the pure-Python FileDB with a logged warning.
"""

from __future__ import annotations

import ctypes
import os
import struct
from . import sync as libsync

from .db import DB, prefix_end  # noqa: F401  (prefix_end re-export parity)
from .native_build import NativeBuildError, build_and_load  # noqa: F401

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "native")
_SRC = os.path.abspath(os.path.join(_NATIVE_DIR, "nkv.cpp"))
_SO = os.path.abspath(os.path.join(_NATIVE_DIR, "_nkv.so"))

_load_lock = libsync.Mutex("libs.db_native._load_lock")
_lib = None


def _load():
    global _lib
    if _lib is not None:
        return _lib
    with _load_lock:
        if _lib is not None:
            return _lib
        lib = build_and_load(_SRC, _SO)
    c_ubyte_p = ctypes.POINTER(ctypes.c_ubyte)
    lib.nkv_open.restype = ctypes.c_void_p
    lib.nkv_open.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.nkv_get.restype = ctypes.c_int
    lib.nkv_get.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t,
        ctypes.POINTER(c_ubyte_p), ctypes.POINTER(ctypes.c_size_t),
    ]
    lib.nkv_set.restype = ctypes.c_int
    lib.nkv_set.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t,
        ctypes.c_char_p, ctypes.c_size_t, ctypes.c_int,
    ]
    lib.nkv_delete.restype = ctypes.c_int
    lib.nkv_delete.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t, ctypes.c_int
    ]
    lib.nkv_batch.restype = ctypes.c_int
    lib.nkv_batch.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t, ctypes.c_int
    ]
    lib.nkv_range.restype = ctypes.c_int
    lib.nkv_range.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t,
        ctypes.c_char_p, ctypes.c_size_t, ctypes.c_int,
        ctypes.POINTER(c_ubyte_p), ctypes.POINTER(ctypes.c_size_t),
    ]
    lib.nkv_free.argtypes = [c_ubyte_p]
    lib.nkv_compact.restype = ctypes.c_int
    lib.nkv_compact.argtypes = [ctypes.c_void_p]
    lib.nkv_count.restype = ctypes.c_size_t
    lib.nkv_count.argtypes = [ctypes.c_void_p]
    lib.nkv_sync.restype = ctypes.c_int
    lib.nkv_sync.argtypes = [ctypes.c_void_p]
    lib.nkv_close.argtypes = [ctypes.c_void_p]
    _lib = lib
    return lib


class NativeDB(DB):
    """C++-backed durable KV store (DB-interface conformant)."""

    def __init__(self, path: str, compact_factor: int = 4):
        self._lib = _load()
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self._h = self._lib.nkv_open(path.encode(), compact_factor)
        if not self._h:
            raise NativeBuildError(
                f"nkv_open failed for {path!r} (unreadable, or a "
                f"foreign-format file — FileDB files start with b'FKV1\\n', "
                f"native files with b'NKV1\\n'; was db_backend changed?)"
            )
        self._mtx = libsync.RLock("libs.db_native._mtx")
        self._closed = False

    def _live(self):
        """The handle, or a clean error after close() — every native call
        must come through here: nkv_close frees the C++ object, so a
        dangling call would be a use-after-free, not an exception."""
        if self._closed:
            raise OSError("native db is closed")
        return self._h

    # -- point ops ----------------------------------------------------------

    def get(self, key: bytes) -> bytes | None:
        key = bytes(key)
        out = ctypes.POINTER(ctypes.c_ubyte)()
        n = ctypes.c_size_t()
        with self._mtx:
            rc = self._lib.nkv_get(
                self._live(), key, len(key), ctypes.byref(out), ctypes.byref(n)
            )
            if rc != 0:
                return None
            try:
                return ctypes.string_at(out, n.value)
            finally:
                self._lib.nkv_free(out)

    def set(self, key: bytes, value: bytes) -> None:
        self._set(key, value, sync=0)

    def set_sync(self, key: bytes, value: bytes) -> None:
        self._set(key, value, sync=1)

    def _set(self, key: bytes, value: bytes, sync: int) -> None:
        key, value = bytes(key), bytes(value)
        with self._mtx:
            if self._lib.nkv_set(
                self._live(), key, len(key), value, len(value), sync
            ):
                raise OSError("native set failed")

    def delete(self, key: bytes) -> None:
        self._delete(key, 0)

    def delete_sync(self, key: bytes) -> None:
        self._delete(key, 1)

    def _delete(self, key: bytes, sync: int) -> None:
        key = bytes(key)
        with self._mtx:
            if self._lib.nkv_delete(self._live(), key, len(key), sync):
                raise OSError("native delete failed")

    # -- batches ------------------------------------------------------------

    def apply_batch(self, ops) -> None:
        blob = bytearray()
        for is_set, k, v in ops:
            k, v = bytes(k), bytes(v)
            blob.append(1 if is_set else 2)
            blob += struct.pack("<II", len(k), len(v) if is_set else 0)
            blob += k
            if is_set:
                blob += v
        blob = bytes(blob)
        with self._mtx:
            if self._lib.nkv_batch(self._live(), blob, len(blob), 1):
                raise OSError("native batch failed")

    # -- iteration ----------------------------------------------------------

    def _range(self, start, end, rev: int):
        s = bytes(start) if start is not None else None
        e = bytes(end) if end is not None else None
        out = ctypes.POINTER(ctypes.c_ubyte)()
        n = ctypes.c_size_t()
        with self._mtx:
            rc = self._lib.nkv_range(
                self._live(),
                s, len(s) if s is not None else 0,
                e, len(e) if e is not None else 0,
                rev, ctypes.byref(out), ctypes.byref(n),
            )
            if rc != 0:
                raise OSError("native range failed")
            try:
                buf = ctypes.string_at(out, n.value)
            finally:
                self._lib.nkv_free(out)
        pos = 0
        items = []
        while pos < len(buf):
            (klen,) = struct.unpack_from("<I", buf, pos)
            k = buf[pos + 4 : pos + 4 + klen]
            pos += 4 + klen
            (vlen,) = struct.unpack_from("<I", buf, pos)
            v = buf[pos + 4 : pos + 4 + vlen]
            pos += 4 + vlen
            items.append((k, v))
        return items

    def iterator(self, start=None, end=None):
        yield from self._range(start, end, 0)

    def reverse_iterator(self, start=None, end=None):
        yield from self._range(start, end, 1)

    # -- maintenance ---------------------------------------------------------

    def compact(self) -> None:
        with self._mtx:
            if self._lib.nkv_compact(self._live()):
                raise OSError("native compact failed")

    def __len__(self) -> int:
        with self._mtx:
            return int(self._lib.nkv_count(self._live()))

    def close(self) -> None:
        with self._mtx:
            if not self._closed:
                self._closed = True
                self._lib.nkv_close(self._h)
