"""Device/compilation telemetry: the XLA side of observability.

PR 3 made the HOST side of the verify pipeline legible (spans, phase
histograms); this layer makes the DEVICE side legible. Three concerns:

* **Compile accounting.** Every jit entry point in ``ops/verify.py``
  is wrapped in :func:`track`, so each XLA compilation is counted and
  timed per kernel x shape bucket (``xla_compile_total{kernel,bucket}``,
  ``xla_compile_seconds{kernel}``), persistent-compilation-cache hits
  are distinguished from real compiles (``xla_cache_hit_total{outcome}``
  via ``jax.monitoring``), and a process-wide recompile counter
  (``xla_recompile_total``) flags a compile for an ALREADY-compiled
  kernel x bucket — the signature of a shape-bucket leak or a dtype
  drift past CLNT003 that would silently destroy steady-state
  throughput. Compiles also emit ``xla.compile`` trace events so the
  one-time cost shows up in ``/debug/trace`` next to pack/dispatch/
  readback (the BENCH_r05 lesson: 9-10 s of "dispatch" was compile).

* **Device gauges on the metrics path.** :func:`sample` is a pull-time
  collector (called from the node's refresh hook and the Prometheus
  listener): ``device.memory_stats()`` byte gauges per device
  (``device_memory_bytes{device,kind}``), expanded-pubkey arena
  occupancy/lookup/eviction counters (``pubkey_arena_*``), and the
  host<->device transfer byte/op counters recorded at the pack and
  readback edges (``device_transfer_bytes_total{direction}``).

* **A scrape endpoint.** :class:`PrometheusServer` (a
  ``libs/service.BaseService``, like ``libs/pprof.PprofServer``) serves
  the node registry's exposition at ``COMETBFT_TPU_PROM_ADDR`` — the
  analog of the reference's dedicated Instrumentation listener
  (config/config.go ``prometheus_listen_addr``, ``:26660``).

Design constraints (same priority order as ``libs/trace``):

* **Zero cost when off.** ``COMETBFT_TPU_DEVSTATS`` unset means every
  entry point is one module-flag check and an immediate return — no
  allocation retained, no lock touched, no clock read (pinned by the
  tracemalloc guard in tests/test_observability.py). The node flips it
  on automatically when it starts a Prometheus listener.
* **Never block an engine thread.** The launch-path entry points (the
  tracked-jit wrapper's compile detection, which can run with
  ``ops.verify._lock`` held — the arena scatter launches under it)
  touch NO lock at all: a detected compile appends one record to a
  lock-free deque (plus a lock-free trace event); the ledger folding
  (:func:`_drain_compiles`) and the per-registry metric replay
  (:func:`_publish_compiles`) happen on the READ paths only (scrape,
  snapshot, tests). The one lock here
  (``libs.devstats._mtx``) serializes the ledger ints on those read
  paths and is never held across a metrics/trace/jax call — it is a
  LEAF of the lock-order graph like ``libs.trace._mtx`` (asserted in
  tests/test_lint_graph.py). :func:`sample` never *initializes* a jax
  backend: a scrape must not be the thing that first touches (and, on
  a dead tunnel, hangs in) PJRT init.

Knobs (registered in config.ENV_KNOBS, enforced by cometlint CLNT007):
``COMETBFT_TPU_DEVSTATS`` (1/on enables accounting + sampling),
``COMETBFT_TPU_PROM_ADDR`` (scrape listener address).
"""

from __future__ import annotations

import json
import os
import time
from collections import deque

from . import health as libhealth
from . import metrics as libmetrics
from . import sync as libsync
from . import trace as libtrace
from .service import HTTPService

_ENV_DEVSTATS = "COMETBFT_TPU_DEVSTATS"
_ENV_PROM_ADDR = "COMETBFT_TPU_PROM_ADDR"

_ON_VALUES = ("1", "on", "true", "yes")


def _env_on() -> bool:
    return os.environ.get(_ENV_DEVSTATS, "").lower() in _ON_VALUES


_enabled: bool = _env_on()
# reference count of node-lifecycle holders (Prometheus-serving nodes
# acquire on start, release on stop) — telemetry turns itself off when
# the last holder stops, unless the env knob keeps it on
_acquirers = 0

_mtx = libsync.Mutex("libs.devstats._mtx")  # read-path ledger folding only

# Launch-path staging: detected compiles land here LOCK-FREE (deque
# append is GIL-atomic) because the launch may hold an engine lock
# (the arena scatter jits under ops.verify._lock). Unbounded by design:
# growth is bounded by the total compile count, which the whole layer
# exists to keep near-zero. _drain_compiles folds it into the ledger
# from read paths only.
_pending_compiles: deque = deque()

# (kernel, bucket) -> in-process compile count. A count > 1 means the
# same kernel x bucket compiled AGAIN — a steady-state recompile.
_compiled: dict[tuple[str, int], int] = {}
# Every COUNTED compile, in drain order. Publishing to a registry
# replays this log from the registry's own high-water index (stored on
# the NodeMetrics instance), so every scraped node sees the full
# compile series no matter how many nodes scrape, and a registry's
# watermark dies with it. Bounded by the total compile count, which
# this layer exists to keep near-zero.
_compile_log: list = []
# Launch-path detection memory for runtimes WITHOUT _cache_size (the
# ledger's _compiled only updates at drain, so detection can't use it):
# GIL-atomic set adds keep warm launches between two drains from
# re-staging the same pair N times.
_seen_pairs: set = set()
# last drained executable-cache size per kernel: dedupes the race where
# two threads dispatch the same cold kernel concurrently and BOTH see
# the jit cache grow — only real growth past the drained watermark
# counts, so a healthy concurrent cold boot can never fire the
# recompile alarm.
_jit_sizes: dict[str, int] = {}
_c = {
    "compiles": 0,
    "recompiles": 0,
    "compile_seconds": 0.0,
    "pcache_hits": 0,
    "pcache_misses": 0,
    "h2d_ops": 0,
    "h2d_bytes": 0,
    "d2h_ops": 0,
    "d2h_bytes": 0,
}
# (The arena counter bridge and the compile-log replay both keep their
# per-registry watermarks ON the target NodeMetrics instance — see
# _bridge_delta / _publish_compiles — so nothing global grows per
# registry and a recycled object id can never inherit a watermark.)

# jax.monitoring persistent-compilation-cache tallies. The listener is
# registered once per process and always counts (two int increments per
# COMPILE, not per dispatch — negligible); classification into the
# metrics happens in the tracked-jit wrapper only when enabled.
_mon_hits = 0
_mon_requests = 0
_mon_registered = False


def _on_jax_event(event: str, **kwargs) -> None:
    global _mon_hits, _mon_requests
    if event == "/jax/compilation_cache/cache_hits":
        _mon_hits += 1
    elif event == "/jax/compilation_cache/compile_requests_use_cache":
        _mon_requests += 1


def _register_monitoring() -> None:
    global _mon_registered
    if _mon_registered:
        return
    _mon_registered = True
    try:
        import jax.monitoring

        jax.monitoring.register_event_listener(_on_jax_event)
    except Exception:
        pass  # older jax: persistent-cache outcomes stay unknown


def enabled() -> bool:
    """The one check hot paths make before any telemetry work."""
    return _enabled


def enable() -> None:
    """Turn device telemetry on (node boot with a Prometheus listener,
    tests, bench captures)."""
    global _enabled
    _register_monitoring()
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def acquire() -> None:
    """Reference-counted enable for node lifecycles: a Prometheus-
    serving node acquires on start and releases on stop, so telemetry
    stays on exactly while someone can scrape it — an in-process
    multi-node net doesn't keep paying per-launch accounting after the
    instrumented node is gone."""
    global _acquirers
    _acquirers += 1
    enable()


def release() -> None:
    global _acquirers
    _acquirers = max(0, _acquirers - 1)
    if _acquirers == 0 and not _env_on():
        disable()


# --------------------------------------------------------- compile ledger


def _jit_cache_size(fn):
    """The jitted callable's executable-cache size, or None when the
    runtime doesn't expose it (then first-seen-bucket approximates)."""
    try:
        return fn._cache_size()
    except Exception:
        return None


class _TrackedJit:
    """Per-launch compile detector around one jitted callable.

    Each call compares the jit executable-cache size before/after: a
    growth IS a compilation (trace + lower + compile happened inside
    this call), regardless of which shape/dtype signature triggered it
    — so a dtype drift recompiling an already-seen bucket is caught,
    not just new buckets. The wrapped callable stays drop-in (bench.py
    and tests call these directly).
    """

    __slots__ = ("fn", "kernel", "axis")

    def __init__(self, fn, kernel: str, axis: int):
        self.fn = fn
        self.kernel = kernel
        self.axis = axis

    def _cache_size(self):
        return self.fn._cache_size()

    def __call__(self, *args):
        fn = self.fn
        if not _enabled:
            return fn(*args)
        # read the bucket BEFORE dispatch: with buffer donation the
        # launch may consume args[axis]
        bucket = int(args[self.axis].shape[-1])
        before = _jit_cache_size(fn)
        hits0, reqs0 = _mon_hits, _mon_requests
        t0 = time.perf_counter()
        out = fn(*args)
        dt = time.perf_counter() - t0
        after = _jit_cache_size(fn)
        if after is None:
            # no executable-cache visibility: approximate with
            # first-seen (kernel, bucket); the staged set keeps warm
            # launches between drains from re-staging the pair
            key = (self.kernel, bucket)
            compiled = key not in _seen_pairs
            if compiled:
                _seen_pairs.add(key)
        else:
            compiled = after > before
        if compiled:
            # LOCK-FREE staging: this call may run under an engine
            # mutex (the arena scatter launches under ops.verify._lock)
            # — no ledger/metrics lock may be touched here. Folding
            # happens in _drain_compiles on the read paths.
            _pending_compiles.append(
                (
                    self.kernel,
                    bucket,
                    dt,
                    before,
                    after,
                    _mon_hits > hits0,
                    _mon_requests > reqs0,
                )
            )
            if libtrace.enabled():
                # trace emission is lock-free by design (libs/trace);
                # the recompile flag is best-effort from drained state
                cache = "off"
                if _mon_hits > hits0:
                    cache = "hit"
                elif _mon_requests > reqs0:
                    cache = "miss"
                libtrace.event(
                    "xla.compile",
                    kernel=self.kernel,
                    bucket=bucket,
                    cache=cache,
                    recompile=(self.kernel, bucket) in _compiled,
                    dur_ns=int(dt * 1e9),
                )
        return out


def track(kernel: str, fn, axis: int = 0) -> _TrackedJit:
    """Wrap a jitted callable for compile accounting. ``axis`` is the
    positional arg whose LAST dimension is the lane bucket.

    The recompile detector keys on ``(kernel, lane-bucket)`` — a
    kernel whose compile shape varies on a SECOND axis must encode
    that axis into the kernel name (one tracked jit per value, like
    ops/sha256's ``sha256.xla.b<block-bucket>``), or a fresh sibling
    shape at an already-seen lane bucket reads as a phantom
    steady-state recompile and feeds the recompile-storm watchdog."""
    return _TrackedJit(fn, kernel, axis)


def _drain_compiles() -> None:
    """Fold staged compile records into the process-wide ledger.

    Runs ONLY from read paths (scrape refresh, snapshot, counters,
    bench/tests) — never from the launch path — so the ledger mutex
    stays off the engine lock hierarchy. Touches NO metrics: registries
    catch up via :func:`_publish_compiles`. Dedupe: a record only
    counts if the kernel's executable cache actually grew past the
    drained watermark, so two threads racing the same cold compile
    produce ONE count (and never a phantom recompile)."""
    records = []
    while True:
        try:
            records.append(_pending_compiles.popleft())
        except IndexError:
            break
    if not records:
        return
    with _mtx:
        for kernel, bucket, seconds, before, after, p_hit, cons in records:
            if after is None:
                # fallback mode can't see real recompiles; a pair that
                # somehow staged twice (detection race) counts once
                if (kernel, bucket) in _compiled:
                    continue
            else:
                prev = _jit_sizes.get(kernel)
                base = before if prev is None else prev
                if after > base:
                    _jit_sizes[kernel] = after
                elif (kernel, bucket) in _compiled:
                    # no growth past the watermark AND this bucket is
                    # already on the ledger: a duplicate record of an
                    # already-counted compile (two threads racing the
                    # same cold pair). An UNSEEN bucket with no visible
                    # growth still counts — a concurrent compile of a
                    # sibling bucket consumed the growth, and dropping
                    # it would desync the recompile detector for this
                    # bucket forever.
                    continue
            n_prior = _compiled.get((kernel, bucket), 0)
            _compiled[(kernel, bucket)] = n_prior + 1
            _c["compiles"] += 1
            _c["compile_seconds"] += seconds
            if n_prior:
                _c["recompiles"] += 1
                # health hook: a steady-state recompile lands in the
                # flight recorder so the black-box bundle and the
                # recompile-storm watchdog see it (libhealth.record is
                # lock-free — _mtx stays a leaf)
                libhealth.record(libhealth.EV_RECOMPILE, a=bucket)
            if p_hit:
                _c["pcache_hits"] += 1
            elif cons:
                _c["pcache_misses"] += 1
            _compile_log.append(
                (kernel, bucket, seconds, n_prior, p_hit, cons)
            )


def _publish_compiles(m) -> None:
    """Replay ledger compiles into ``m``'s counter families from m's
    own high-water index (an attribute on the NodeMetrics — its
    lifetime is the registry's, so nothing global grows or aliases a
    recycled object id). Metric updates happen OUTSIDE the ledger lock:
    _mtx stays a leaf."""
    with _mtx:
        start = m.__dict__.get("_devstats_compile_idx", 0)
        fresh = _compile_log[start:]
        m._devstats_compile_idx = start + len(fresh)
    for kernel, bucket, seconds, n_prior, p_hit, cons in fresh:
        m.xla_compiles.labels(kernel, str(bucket)).inc()
        m.xla_compile_seconds.labels(kernel).observe(seconds)
        if n_prior:
            m.xla_recompiles.inc()
        if p_hit:
            m.xla_cache.labels("hit").inc()
        elif cons:
            m.xla_cache.labels("miss").inc()


def compile_count() -> int:
    """Total in-process XLA compiles (the no-recompile guard's number)."""
    _drain_compiles()
    with _mtx:
        return _c["compiles"]


def compile_seconds_total() -> float:
    _drain_compiles()
    with _mtx:
        return _c["compile_seconds"]


# ------------------------------------------------------ transfer counters


def record_h2d(nbytes: int) -> None:
    """One host->device shipment at the pack edge (wire buffer, arena
    slot indices, builder pubkey rows). Ledger only — registries catch
    up per-scrape via the :func:`sample` bridge, so the launch path
    never touches a metrics mutex and every scraped node sees the full
    series."""
    if not _enabled:
        return
    with _mtx:
        _c["h2d_ops"] += 1
        _c["h2d_bytes"] += nbytes


def record_d2h(nbytes: int) -> None:
    """One device->host materialization at the readback edge (ledger
    only, like :func:`record_h2d`)."""
    if not _enabled:
        return
    with _mtx:
        _c["d2h_ops"] += 1
        _c["d2h_bytes"] += nbytes


def counters() -> dict:
    """Copy of the raw process-wide tallies (tests, /debug/devstats)."""
    _drain_compiles()
    with _mtx:
        return dict(_c)


# -------------------------------------------------------- pull-time gauges


def _devices_if_initialized():
    """Live jax devices, WITHOUT forcing backend init: a metrics scrape
    must never be the first thing to touch PJRT (a dead accelerator
    tunnel hangs init, and the scrape path would hang with it)."""
    try:
        from jax._src import xla_bridge

        if not xla_bridge.backends_are_initialized():
            return []
        import jax

        return jax.devices()
    except Exception:
        return []


def _sample_device_memory(m) -> list[dict]:
    out = []
    for d in _devices_if_initialized():
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue  # CPU backend: memory_stats() is None
        dev = str(getattr(d, "id", "?"))
        row = {"device": dev, "kind": getattr(d, "device_kind", "?")}
        for k, v in stats.items():
            if not isinstance(v, (int, float)):
                continue
            row[k] = v
            if "bytes" in k or "size" in k:
                m.device_memory.labels(dev, k).set(v)
        out.append(row)
    return out


def _bridge_delta(store: dict, key: str, current: int) -> int:
    """Advance the last-seen snapshot for a monotone plain int and
    return the delta to feed its Prometheus counter. ``store`` is the
    target NodeMetrics' own watermark dict, so two scraped nodes in one
    process each see the full series and a registry's watermarks die
    with it. Caller holds ``_mtx``; the counter inc itself happens
    OUTSIDE the lock — _mtx stays a leaf."""
    last = store.get(key, 0)
    store[key] = max(last, current)
    return current - last if current > last else 0


def _sample_arena(m) -> dict:
    try:
        from ..ops.verify import _PUBKEY_CACHE as arena
    except Exception:
        return {}
    # unlocked reads: GIL-consistent snapshots of ints/len are fine for
    # gauges, and the scrape path must not contend with verify lookups
    used = len(arena._slots)
    out = {
        "slots_used": used,
        "capacity": arena.capacity,
        "hits": arena.hits,
        "misses": arena.misses,
        "builds": arena.builds,
        "evictions": arena.evictions,
    }
    m.arena_slots.labels("used").set(used)
    m.arena_slots.labels("capacity").set(arena.capacity)
    with _mtx:
        store = m.__dict__.setdefault("_devstats_bridge", {})
        hit_d = _bridge_delta(store, "hits", arena.hits)
        miss_d = _bridge_delta(store, "misses", arena.misses)
        build_d = _bridge_delta(store, "builds", arena.builds)
        evict_d = _bridge_delta(store, "evictions", arena.evictions)
    if hit_d:
        m.arena_lookups.labels("hit").inc(hit_d)
    if miss_d:
        m.arena_lookups.labels("miss").inc(miss_d)
    if build_d:
        m.arena_builds.inc(build_d)
    if evict_d:
        m.arena_evictions.inc(evict_d)
    return out


def _sample_lane_arena(m) -> dict:
    """Lane staging arena gauges + stage-outcome counters (the
    persistent donated wire-row buffers of ops/verify.LaneArena)."""
    try:
        from ..ops.verify import _LANE_ARENA as arena
    except Exception:
        return {}
    # unlocked reads, like the pubkey-arena sample: GIL-consistent int
    # snapshots are fine for gauges
    out = {
        "buffers": arena.buffers(),
        "resident_bytes": arena.resident_bytes(),
        "stages": arena.stages,
        "reuses": arena.reuses,
        "allocs": arena.allocs,
    }
    m.lane_arena_staging.labels("buffers").set(out["buffers"])
    m.lane_arena_staging.labels("resident_bytes").set(
        out["resident_bytes"]
    )
    with _mtx:
        store = m.__dict__.setdefault("_devstats_bridge", {})
        reuse_d = _bridge_delta(store, "lane_reuses", arena.reuses)
        alloc_d = _bridge_delta(store, "lane_allocs", arena.allocs)
    if reuse_d:
        m.lane_arena_stages.labels("reuse").inc(reuse_d)
    if alloc_d:
        m.lane_arena_stages.labels("alloc").inc(alloc_d)
    return out


def _bridge_transfers(m) -> None:
    """Per-registry catch-up of the transfer ledger (same watermark
    store as the arena bridge): the launch-path recorders only touch
    the ledger, so every scraped node gets the full series here."""
    with _mtx:
        store = m.__dict__.setdefault("_devstats_bridge", {})
        deltas = {
            k: _bridge_delta(store, k, _c[k])
            for k in ("h2d_ops", "h2d_bytes", "d2h_ops", "d2h_bytes")
        }
    for direction in ("h2d", "d2h"):
        if deltas[direction + "_bytes"]:
            m.transfer_bytes.labels(direction).inc(
                deltas[direction + "_bytes"]
            )
        if deltas[direction + "_ops"]:
            m.transfer_ops.labels(direction).inc(deltas[direction + "_ops"])


def sample(metrics=None) -> dict:
    """Pull-time collector: device memory + arena gauges into
    ``metrics`` (a NodeMetrics — the node being scraped passes its own,
    so a multi-node process never writes one node's gauges into
    another's registry) or, by default, the process-wide node_metrics()
    top. Called at scrape (Prometheus listener, RPC /metrics refresh)
    and by :func:`snapshot`. No-op when disabled."""
    if not _enabled:
        return {}
    _drain_compiles()  # scrape shows compiles staged since the last read
    m = metrics if metrics is not None else libmetrics.node_metrics()
    _publish_compiles(m)
    _bridge_transfers(m)
    return {
        "device_memory": _sample_device_memory(m),
        "pubkey_arena": _sample_arena(m),
        "lane_arena": _sample_lane_arena(m),
    }


def snapshot() -> dict:
    """The /debug/devstats JSON: ledger + live sample, one dict."""
    _drain_compiles()
    with _mtx:
        per = {
            f"{kernel}:{bucket}": n
            for (kernel, bucket), n in sorted(_compiled.items())
        }
        c = dict(_c)
    return {
        "enabled": _enabled,
        "xla": {
            "compiles": c["compiles"],
            "recompiles": c["recompiles"],
            "compile_seconds": round(c["compile_seconds"], 3),
            "per_kernel_bucket": per,
            "persistent_cache": {
                "hits": c["pcache_hits"],
                "misses": c["pcache_misses"],
            },
        },
        "transfers": {
            "h2d_ops": c["h2d_ops"],
            "h2d_bytes": c["h2d_bytes"],
            "d2h_ops": c["d2h_ops"],
            "d2h_bytes": c["d2h_bytes"],
        },
        # who used the device: the per-(plane, caller) time/lane ledger
        # and its occupancy view (libs/devledger; full budget plane at
        # /debug/budget)
        "device_ledger": _ledger_block(),
        **sample(),
    }


def _ledger_block() -> dict:
    try:
        from . import devledger as libdevledger

        return libdevledger.snapshot()
    except Exception as e:  # a ledger fault must not sink a bundle
        return {"error": repr(e)}


# --------------------------------------------------------- scrape server


def prometheus_addr(config=None) -> str:
    """The scrape listener address: COMETBFT_TPU_PROM_ADDR wins, then
    the config Instrumentation section, else "" (no listener)."""
    addr = os.environ.get(_ENV_PROM_ADDR, "")
    if addr:
        return addr
    if config is not None and config.instrumentation.prometheus:
        return config.instrumentation.prometheus_listen_addr
    return ""


class PrometheusServer(HTTPService):
    """Dedicated /metrics listener (the reference's Instrumentation
    server, node/node.go:630): serves ``registry.render()`` with the
    exposition content type; ``refresh`` (the node's pull-time gauge
    hook, which includes :func:`sample`) runs before each render."""

    CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"
    # the reference Instrumentation listener binds ALL interfaces on its
    # ":26660" default (a scrape target, not a loopback debug server)
    DEFAULT_HOST = "0.0.0.0"

    def __init__(self, addr: str, registry, refresh=None, logger=None):
        super().__init__("prometheus", addr, logger)
        self.registry = registry
        self._refresh = refresh
        # scrape self-metric (one shared family definition so the
        # NodeMetrics registration and this one dedupe to ONE instance)
        self._scrape_hist = libmetrics.scrape_duration_histogram(registry)

    def handle_get(self, path: str, query: dict) -> tuple[str, str]:
        if path == "/":
            return (
                "text/plain; charset=utf-8",
                "cometbft-tpu prometheus exporter\n"
                "/metrics  registry exposition\n",
            )
        if path != "/metrics":
            raise KeyError(path)
        t0 = time.perf_counter()
        if self._refresh is not None:
            try:
                self._refresh()
            except Exception as e:
                # pull-time gauges are best-effort; the counters and
                # histograms must still scrape
                if self.logger is not None:
                    self.logger.error(
                        "metrics refresh failed", err=repr(e)[:200]
                    )
        body = self.registry.render()
        # observed BEFORE the final render would be invisible to THIS
        # scrape; the one-scrape lag on the self-metric is the standard
        # exporter trade (prometheus client libs do the same)
        self._scrape_hist.labels("prometheus").observe(
            time.perf_counter() - t0
        )
        return self.CONTENT_TYPE, body


def debug_devstats_json() -> str:
    """Body of the pprof server's /debug/devstats route."""
    t0 = time.perf_counter()
    body = json.dumps(snapshot(), default=str)
    libmetrics.node_metrics().health_scrape_seconds.labels(
        "devstats"
    ).observe(time.perf_counter() - t0)
    return body


# Env-enabled processes (COMETBFT_TPU_DEVSTATS=1 with no node/listener
# ever calling enable()) still need the jax.monitoring listener, or the
# persistent-cache hit/miss classification would silently read 0.
if _enabled:
    _register_monitoring()
