"""Event pub/sub with query language (reference: libs/pubsub/pubsub.go:90,
libs/pubsub/query/query.go:357).

Subscriptions are matched by *queries* over event attributes — conjunctions
of conditions like ``tm.event = 'NewBlock' AND tx.height > 5``. Supported
operators (the reference's full set, query.go): ``=  <  <=  >  >=  CONTAINS
EXISTS``, joined by ``AND``. Values are single-quoted strings or numbers;
``TIME``/``DATE`` literals are compared as RFC3339 strings (which sort
chronologically, so ordinary string comparison is correct).

Messages are published with an attribute map ``{composite_key: [values]}``;
a condition matches if ANY value under the key satisfies it (reference
semantics, query.go ``Matches``).

Delivery is synchronous-in-order per subscriber via per-subscription
unbounded queues drained by the subscriber (``Subscription.out``); the
server itself runs no goroutine loop — publish fans out under a read lock,
which preserves the reference's guarantee that events are observed in
publish order.
"""

from __future__ import annotations

import queue
import re
import threading
from . import sync as libsync
from dataclasses import dataclass, field
from typing import Any


class PubSubError(Exception):
    pass


class AlreadySubscribedError(PubSubError):
    pass


class NotSubscribedError(PubSubError):
    pass


class QuerySyntaxError(PubSubError):
    pass


# --------------------------------------------------------------------------
# Query language
# --------------------------------------------------------------------------

_TOKEN_RE = re.compile(
    r"""\s*(?:
        (?P<and>AND\b)
      | (?P<exists>EXISTS\b)
      | (?P<contains>CONTAINS\b)
      | (?P<timeword>TIME\b|DATE\b)
      | (?P<op><=|>=|=|<|>)
      | (?P<string>'[^']*')
      | (?P<rfc3339>\d{4}-\d{2}-\d{2}
           (?:T\d{2}:\d{2}:\d{2}(?:\.\d+)?(?:Z|[+-]\d{2}:\d{2})?)?)
      | (?P<number>-?\d+(?:\.\d+)?)
      | (?P<key>[A-Za-z_][A-Za-z0-9_.-]*)
    )""",
    re.VERBOSE,
)


@dataclass(frozen=True)
class Condition:
    key: str
    op: str  # '=', '<', '<=', '>', '>=', 'CONTAINS', 'EXISTS'
    value: Any = None  # str for =/CONTAINS on strings, float for numeric cmp
    is_number: bool = False

    def matches_values(self, values: list[str]) -> bool:
        if self.op == "EXISTS":
            return True  # key presence already checked by caller
        for v in values:
            if self.op == "CONTAINS":
                if str(self.value) in v:
                    return True
            elif self.is_number:
                try:
                    x = float(v)
                except ValueError:
                    continue
                if _cmp(x, self.op, float(self.value)):
                    return True
            else:
                if _cmp(v, self.op, str(self.value)):
                    return True
        return False


def _cmp(a, op: str, b) -> bool:
    if op == "=":
        return a == b
    if op == "<":
        return a < b
    if op == "<=":
        return a <= b
    if op == ">":
        return a > b
    if op == ">=":
        return a >= b
    raise QuerySyntaxError(f"unknown operator {op!r}")


class Query:
    """Compiled conjunction of conditions. ``Query.parse("tm.event='Tx'")``."""

    def __init__(self, conditions: list[Condition], source: str = ""):
        self.conditions = conditions
        self._source = source or " AND ".join(
            f"{c.key} {c.op} {c.value!r}" for c in conditions
        )

    @classmethod
    def parse(cls, s: str) -> "Query":
        tokens = cls._tokenize(s)
        conds: list[Condition] = []
        i = 0
        while i < len(tokens):
            kind, val = tokens[i]
            if kind != "key":
                raise QuerySyntaxError(f"expected key at token {i} in {s!r}")
            key = val
            i += 1
            if i >= len(tokens):
                raise QuerySyntaxError(f"dangling key {key!r} in {s!r}")
            kind, val = tokens[i]
            if kind == "exists":
                conds.append(Condition(key, "EXISTS"))
                i += 1
            elif kind == "contains":
                i += 1
                if i >= len(tokens) or tokens[i][0] != "string":
                    raise QuerySyntaxError("CONTAINS needs a string operand")
                conds.append(Condition(key, "CONTAINS", tokens[i][1]))
                i += 1
            elif kind == "op":
                op = val
                i += 1
                if i < len(tokens) and tokens[i][0] == "timeword":
                    # TIME/DATE prefix: RFC3339 literal, compared as a
                    # string (RFC3339 sorts chronologically).
                    i += 1
                    if i >= len(tokens) or tokens[i][0] != "rfc3339":
                        raise QuerySyntaxError("TIME/DATE needs an RFC3339 literal")
                    conds.append(Condition(key, op, tokens[i][1], is_number=False))
                    i += 1
                elif i < len(tokens) and tokens[i][0] == "string":
                    conds.append(Condition(key, op, tokens[i][1]))
                    i += 1
                elif i < len(tokens) and tokens[i][0] == "number":
                    conds.append(
                        Condition(key, op, float(tokens[i][1]), is_number=True)
                    )
                    i += 1
                else:
                    raise QuerySyntaxError(f"missing operand after {op!r}")
            else:
                raise QuerySyntaxError(f"unexpected token {val!r} in {s!r}")
            if i < len(tokens):
                kind, val = tokens[i]
                if kind != "and":
                    raise QuerySyntaxError(f"expected AND, got {val!r}")
                i += 1
                if i >= len(tokens):
                    raise QuerySyntaxError("dangling AND")
        return cls(conds, s)

    @staticmethod
    def _tokenize(s: str) -> list[tuple[str, str]]:
        tokens = []
        pos = 0
        while pos < len(s):
            m = _TOKEN_RE.match(s, pos)
            if not m or m.end() == pos:
                if s[pos:].strip() == "":
                    break
                raise QuerySyntaxError(f"bad token at {s[pos:]!r}")
            pos = m.end()
            kind = m.lastgroup
            val = m.group(kind)
            if kind == "string":
                val = val[1:-1]
            tokens.append((kind, val))
        return tokens

    def matches(self, events: dict[str, list[str]]) -> bool:
        for c in self.conditions:
            values = events.get(c.key)
            if values is None:
                # TIME/DATE-prefixed height-style keys may carry dotted values
                return False
            if not c.matches_values(values):
                return False
        return True

    def __str__(self) -> str:
        return self._source

    def __eq__(self, other) -> bool:
        return isinstance(other, Query) and str(self) == str(other)

    def __hash__(self) -> int:
        return hash(str(self))


class Empty:
    """Matches everything (reference: libs/pubsub/query.Empty)."""

    def matches(self, events) -> bool:
        return True

    def __str__(self) -> str:
        return "empty"

    def __eq__(self, other) -> bool:
        return isinstance(other, Empty)

    def __hash__(self) -> int:
        return hash("__empty_query__")


# --------------------------------------------------------------------------
# Server
# --------------------------------------------------------------------------


@dataclass
class Message:
    data: Any
    events: dict[str, list[str]] = field(default_factory=dict)


class Subscription:
    """Per-subscriber queue. ``out`` yields Messages; ``canceled`` is set
    with a reason when the server drops the subscription (unsubscribe/stop).
    """

    def __init__(self, capacity: int | None):
        self.out: queue.Queue[Message] = queue.Queue(capacity or 0)
        self.canceled = threading.Event()
        self.cancel_reason: str | None = None

    def _cancel(self, reason: str) -> None:
        self.cancel_reason = reason
        self.canceled.set()


class Server:
    """Pubsub hub keyed by (subscriber_id, query) like the reference
    (pubsub.go:90). ``capacity`` bounds each subscription queue; a full
    queue on publish cancels that subscriber (the reference's slow-client
    policy for non-buffered subscriptions).
    """

    def __init__(self, capacity: int | None = None):
        self._mtx = libsync.RLock("libs.pubsub._mtx")
        self._subs: dict[str, dict[Any, Subscription]] = {}
        self._capacity = capacity

    def subscribe(
        self, subscriber: str, query, capacity: int | None = None
    ) -> Subscription:
        with self._mtx:
            by_query = self._subs.setdefault(subscriber, {})
            if query in by_query:
                raise AlreadySubscribedError(f"{subscriber}/{query}")
            sub = Subscription(capacity if capacity is not None else self._capacity)
            by_query[query] = sub
            return sub

    def unsubscribe(self, subscriber: str, query) -> None:
        with self._mtx:
            by_query = self._subs.get(subscriber)
            if not by_query or query not in by_query:
                raise NotSubscribedError(f"{subscriber}/{query}")
            by_query.pop(query)._cancel("unsubscribed")
            if not by_query:
                del self._subs[subscriber]

    def unsubscribe_all(self, subscriber: str) -> None:
        with self._mtx:
            by_query = self._subs.pop(subscriber, None)
            if not by_query:
                raise NotSubscribedError(subscriber)
            for sub in by_query.values():
                sub._cancel("unsubscribed")

    def num_clients(self) -> int:
        with self._mtx:
            return len(self._subs)

    def num_client_subscriptions(self, subscriber: str) -> int:
        with self._mtx:
            return len(self._subs.get(subscriber, {}))

    def publish(self, data: Any, events: dict[str, list[str]] | None = None) -> None:
        msg = Message(data, events or {})
        with self._mtx:
            dead: list[tuple[str, Any]] = []
            for subscriber, by_query in self._subs.items():
                for q, sub in by_query.items():
                    if not q.matches(msg.events):
                        continue
                    try:
                        sub.out.put_nowait(msg)
                    except queue.Full:
                        sub._cancel("slow subscriber")
                        dead.append((subscriber, q))
            for subscriber, q in dead:
                self._subs[subscriber].pop(q, None)
                if not self._subs[subscriber]:
                    del self._subs[subscriber]

    def stop(self) -> None:
        with self._mtx:
            for by_query in self._subs.values():
                for sub in by_query.values():
                    sub._cancel("server stopped")
            self._subs.clear()
