"""Flow-rate monitoring and throttling (reference: libs/flowrate/flowrate.go).

``Monitor`` tracks transfer rate with an EMA; ``limit`` returns how many
bytes may be sent now to honor a bytes/sec cap, sleeping like the
reference's blocking mode when the budget is exhausted.
"""

from __future__ import annotations

from . import sync as libsync
import time


class Monitor:
    def __init__(self, sample_period: float = 0.1, window: float = 1.0):
        self._mtx = libsync.Mutex("libs.flowrate._mtx")
        self._start = time.monotonic()
        self._total = 0
        self._rate_ema = 0.0
        self._window = window
        self._last_sample = self._start
        self._sample_bytes = 0

    def update(self, n: int) -> None:
        with self._mtx:
            now = time.monotonic()
            self._total += n
            self._sample_bytes += n
            dt = now - self._last_sample
            if dt >= 0.1:
                rate = self._sample_bytes / dt
                alpha = min(1.0, dt / self._window)
                self._rate_ema += alpha * (rate - self._rate_ema)
                self._sample_bytes = 0
                self._last_sample = now

    def rate(self) -> float:
        with self._mtx:
            return self._rate_ema

    def total(self) -> int:
        with self._mtx:
            return self._total

    def limit(self, want: int, rate_limit: int) -> int:
        """Bytes allowed now under ``rate_limit`` B/s; sleeps briefly when
        over budget (flowrate.go Limit in blocking mode)."""
        if rate_limit <= 0:
            return want
        while True:
            with self._mtx:
                now = time.monotonic()
                elapsed = max(now - self._start, 1e-9)
                budget = rate_limit * elapsed - self._total
            if budget > 0:
                return max(1, min(want, int(budget)))
            time.sleep(min(0.05, -budget / rate_limit))
