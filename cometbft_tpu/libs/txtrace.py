"""Transaction lifecycle plane: sampled end-to-end tx tracing.

Every prior observability plane instrumented a LAYER — the engine
(libs/trace), the device (libs/devstats), the network (libs/netstats),
liveness (libs/health), device tenancy (libs/devledger) — but nothing
follows a TRANSACTION through them, and submit→commit is the one
latency a user of the chain actually feels.  This module is that
plane: a sampled, lock-free lifecycle ledger keyed on the mempool's
``TxKey`` (the SHA-256 computed once per CheckTx since the hash-plane
PR), recording fixed-width stage stamps per sampled tx:

* **admit** — the CheckTx response admitted the tx into the mempool
  (plus the mempool depth it saw at admission),
* **gossip_send** — the first time this node's mempool reactor sent
  the tx to a peer (channel 0x30),
* **gossip_recv** — the first time the tx arrived FROM a peer, with
  the one-hop lag from the PR 8 netstamp thread-local when the link
  negotiated provenance stamps,
* **proposal** — the accepted proposal for the height that later
  committed the tx (per-height stamp, backfilled at commit — the
  proposal message does not name its txs, and re-hashing a block's
  txs on the FSM thread to find out would cost more than the plane
  is allowed to),
* **commit** — the tx landed in a committed block
  (``CListMempool.update``), closing the submit→commit latency.

**Deterministic hash-based sampling.**  A tx is sampled iff
``key[0] % COMETBFT_TPU_TX_SAMPLE == 0`` — a pure function of the tx
key's first byte (uniform for SHA-256 keys), so every node samples
the SAME txs and cross-node joins (timeline tx rows, multi-node
benches) work with no coordination, and the not-sampled path — what
EVERY tx pays at each stage — is one flag check, one byte index and
one modulo.  Default 1/64; rates above 256 degrade to 1/256 (the
predicate reads one byte — documented, not silent: ``status()``
reports the effective rate).

**Flight-recorder storage posture** (the libs/health contract — this
plane is on for every running node):

* the disabled path is ONE module-flag check;
* the enabled record path retains ZERO allocations — all state lives
  in preallocated ``array('q')`` columns (pinned by the tracemalloc
  guard in tests/test_observability.py alongside the flight-recorder
  and devledger guards);
* the record path takes NO lock: the in-flight table is direct-mapped
  by key fingerprint (a colliding key evicts the older row — sampled
  flight-recorder semantics, losing an old row is the design), the
  completion ring reserves slots through one GIL-atomic
  ``itertools.count``.  The one lock here (``libs.txtrace._mtx``)
  serializes only the mempool-probe registry and is asserted
  edge-free in tests/test_lint_graph.py like ``libs.trace._mtx``.

Exposure (every surface the other planes use):

* ``EV_TX`` flight-ring rows per sampled stage (decoded ``tx.stage``;
  the timeline merge groups them into per-height sampled-tx rows);
* ``tx_commit_latency_seconds`` / ``tx_stage_seconds{stage}`` /
  ``tx_sampled_total{stage}`` and the ``mempool_oldest_age_seconds``
  gauge, bridged at scrape by :func:`sample` (called from
  libs/health.sample — the devledger watermark pattern, so the record
  path touches no metrics object);
* ``/debug/tx?key=<hex-prefix>`` on the pprof server ("where is my
  transaction") and ``tx.json`` in watchdog black-box bundles;
* the ``tx_starved`` watchdog (libs/health): an admitted tx older
  than N commit intervals while heights keep committing pages with
  the oldest keys named.

Knobs (registered in config.ENV_KNOBS, enforced by cometlint CLNT007):
``COMETBFT_TPU_TX`` (auto: on while a node runs, refcounted like
devstats/netstats; 1 force; 0 kill switch), ``COMETBFT_TPU_TX_SAMPLE``
(sampling denominator; 1 = every tx, <= 0 disables),
``COMETBFT_TPU_TX_RING`` (in-flight table + completion ring capacity),
``COMETBFT_TPU_TX_STARVE_COMMITS`` (the tx_starved watchdog's window
in commit intervals).
"""

from __future__ import annotations

import itertools
import os
from array import array

from . import health as libhealth
from . import sync as libsync

_ENV_TX = "COMETBFT_TPU_TX"
_ENV_SAMPLE = "COMETBFT_TPU_TX_SAMPLE"
_ENV_RING = "COMETBFT_TPU_TX_RING"
_ENV_STARVE = "COMETBFT_TPU_TX_STARVE_COMMITS"

_ON_VALUES = ("1", "on", "true", "yes")
_OFF_VALUES = ("0", "off", "false", "no")

DEFAULT_SAMPLE = 64
DEFAULT_RING = 4096
DEFAULT_STARVE_COMMITS = 16.0

# -- stage codes (the EV_TX ``round`` column; the decode names live
# with the rest of the ring vocabulary in libs/health.TX_STAGES —
# aliased here so the record and decode sides cannot diverge) -----------
ST_ADMIT = 1
ST_SEND = 2
ST_RECV = 3
ST_PROPOSAL = 4
ST_COMMIT = 5
STAGE_NAMES = libhealth.TX_STAGES
# per-stage residencies of the completed-tx view (the ``stage`` label
# of tx_stage_seconds): admit->first gossip send, the stamped one-hop
# receive lag, admit->proposal (mempool residency), proposal->commit
RESIDENCIES = (
    "admit_to_send", "hop", "admit_to_proposal", "proposal_to_commit",
)

_U64 = 1 << 64
_S63 = 1 << 63


def _env_mode() -> str:
    v = os.environ.get(_ENV_TX, "").lower()
    if v in _ON_VALUES:
        return "on"
    if v in _OFF_VALUES:
        return "off"
    return "auto"


def sample_rate() -> int:
    """The sampling denominator (1/N of keys; <= 0 disables)."""
    try:
        return int(os.environ.get(_ENV_SAMPLE, ""))
    except ValueError:
        return DEFAULT_SAMPLE


def starve_commits() -> float:
    """tx_starved window in commit intervals (<= 0 disables) —
    through the shared lenient parser every health knob uses."""
    return libhealth._env_float(_ENV_STARVE, DEFAULT_STARVE_COMMITS)


def _ring_size_from_env() -> int:
    try:
        n = int(os.environ.get(_ENV_RING, ""))
    except ValueError:
        n = DEFAULT_RING
    return max(64, n)


def key_fp(key: bytes) -> int:
    """Unsigned 64-bit fingerprint: the key's first 8 bytes.  A pure
    function of the tx key, so sampling and slot assignment agree on
    every node; displayed as the 16-hex-char key prefix."""
    return int.from_bytes(key[:8], "big")


def _signed(fp: int) -> int:
    """Two's-complement store form for the array('q') columns."""
    return fp - _U64 if fp >= _S63 else fp


def _unsigned(fp_s: int) -> int:
    return fp_s % _U64


def fp_hex(fp: int) -> str:
    """The bounded short key prefix (16 hex chars = 8 key bytes) —
    the ONLY key form this plane ever exports (never a raw 32-byte
    key, and never as a metric label)."""
    return format(_unsigned(fp), "016x")


# -- enable gating (the devstats/netstats refcount pattern) --------------

_mode = _env_mode()
_enabled: bool = _mode == "on"
_acquirers = 0
_rate: int = sample_rate()

# mempool-probe registry only (node boot/stop — never the record path)
_mtx = libsync.Mutex("libs.txtrace._mtx")
_MEMPOOLS: list = []


def enabled() -> bool:
    """The one check hot paths make before recording."""
    return _enabled


def enable(rate: int | None = None) -> None:
    """Force the plane on (tests, bench); ``rate`` overrides the
    sampling denominator for the process."""
    global _enabled, _rate
    if rate is not None:
        _rate = int(rate)
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def acquire() -> None:
    """Reference-counted enable for node lifecycles: the plane is on
    exactly while a node runs unless ``COMETBFT_TPU_TX=0``."""
    global _acquirers, _enabled, _rate
    if _env_mode() == "off":
        return
    _acquirers += 1
    _rate = sample_rate()
    _enabled = True


def release() -> None:
    global _acquirers, _enabled
    _acquirers = max(0, _acquirers - 1)
    if _acquirers == 0 and _env_mode() != "on":
        _enabled = False


def register_mempool(mp) -> None:
    """Register a mempool for the oldest-age probe (node boot).  The
    object answers ``oldest_age_s()`` and ``oldest_entries(n)``."""
    with _mtx:
        _MEMPOOLS.append(mp)


def deregister_mempool(mp) -> None:
    with _mtx:
        for i in range(len(_MEMPOOLS) - 1, -1, -1):
            if _MEMPOOLS[i] is mp:
                del _MEMPOOLS[i]
                return


def mempools() -> tuple:
    """Lock-free snapshot (the netstats.connections posture)."""
    return tuple(_MEMPOOLS)


def oldest_admitted_age_s() -> float:
    """Age of the oldest admitted-uncommitted tx across registered
    mempools (0.0 = every mempool empty) — the tx_starved watchdog's
    signal.  Plain loop over a tuple snapshot: the no-trip check path
    stays allocation-free."""
    worst = 0.0
    for mp in mempools():
        try:
            age = mp.oldest_age_s()
        except Exception:
            continue
        if age > worst:
            worst = age
    return worst


# -- storage -------------------------------------------------------------
#
# In-flight table: direct-mapped by fingerprint (slot = fp % capacity).
# A row is created by the admit/recv stages; send matches by fp;
# commit closes the row into the completion ring and frees the slot.
# fp 0 doubles as the empty sentinel (a real all-zero 8-byte key
# prefix has probability 2^-64 — that tx simply goes untracked).


class _Tables:
    __slots__ = (
        "capacity", "fp", "t_admit", "depth", "t_send", "t_recv",
        "recv_lag",
        "d_cap", "d_fp", "d_h", "d_r", "d_admit", "d_total", "d_send",
        "d_recv_lag", "d_prop", "d_wait", "d_depth", "d_seq",
        "d_written",
        "ph", "pr", "pts",
        "counts",
    )

    _PH_CAP = 64  # per-height proposal-stamp slots (height % 64)

    def __init__(self, capacity: int):
        self.capacity = max(64, int(capacity))
        zeros = [0] * self.capacity
        # in-flight columns
        self.fp = array("q", zeros)
        self.t_admit = array("q", zeros)
        self.depth = array("q", zeros)
        self.t_send = array("q", zeros)
        self.t_recv = array("q", zeros)
        self.recv_lag = array("q", zeros)
        # completion ring
        self.d_cap = self.capacity
        dz = [0] * self.d_cap
        self.d_fp = array("q", dz)
        self.d_h = array("q", dz)
        self.d_r = array("q", dz)
        self.d_admit = array("q", dz)
        self.d_total = array("q", dz)
        self.d_send = array("q", dz)
        self.d_recv_lag = array("q", dz)
        self.d_prop = array("q", dz)
        self.d_wait = array("q", dz)
        self.d_depth = array("q", dz)
        self.d_seq = itertools.count()
        self.d_written = array("q", [0])
        # per-height proposal stamps (backfilled into commits)
        self.ph = array("q", [0] * self._PH_CAP)
        self.pr = array("q", [0] * self._PH_CAP)
        self.pts = array("q", [0] * self._PH_CAP)
        # per-stage record tallies (index = stage code)
        self.counts = array("q", [0] * 8)


_T = _Tables(_ring_size_from_env())


def reset(capacity: int | None = None) -> None:
    """Drop all rows (tests, bench windows); ``capacity`` rebuilds."""
    global _T
    _T = _Tables(capacity if capacity is not None else _T.capacity)


# -- record paths (lock-free, allocation-free) ---------------------------


def _sampled(fp: int) -> bool:
    """The sampling predicate on a fingerprint: the key's FIRST BYTE
    (fp's top byte — big-endian) mod the rate.  The record paths
    inline the equivalent ``key[0] % rate`` so the not-sampled path
    never builds the 8-byte fingerprint int at all.  fp 0 is the
    empty-slot sentinel AND the fingerprint of a keyless
    (hand-constructed test) entry — never tracked."""
    r = _rate
    return fp != 0 and r > 0 and (fp >> 56) % r == 0


def note_admit(key: bytes, depth: int) -> None:
    """CheckTx response admitted the tx into the mempool; ``depth`` is
    the mempool size the tx saw at admission (txs queued ahead)."""
    if not _enabled:
        return
    r = _rate
    if r <= 0 or not key or key[0] % r:
        return  # the not-sampled path: flag, byte, modulo — nothing else
    fp = key_fp(key)
    if fp == 0:
        return
    t = _T
    i = fp % t.capacity
    fps = _signed(fp)
    now = libhealth.now_ns()
    if t.fp[i] != fps:
        # claim (or evict a colliding/stale row — sampled
        # flight-recorder semantics): clear the per-stage columns a
        # previous occupant left behind
        t.fp[i] = fps
        t.t_admit[i] = 0
        t.t_send[i] = 0
        t.t_recv[i] = 0
        t.recv_lag[i] = 0
    if t.t_admit[i] == 0:
        # SET-ONCE: in-process multi-node nets share one table, and a
        # peer re-admitting a gossiped tx must not overwrite the
        # origin node's admission stamp (the submit time the
        # submit->commit latency anchors on); each node's admit still
        # counts and rings below
        t.t_admit[i] = now
        t.depth[i] = depth
    t.counts[ST_ADMIT] += 1
    libhealth.record(libhealth.EV_TX, 0, ST_ADMIT, fps, depth)


def note_gossip_send(key: bytes) -> None:
    """First gossip send of the tx toward any peer (set-once)."""
    if not _enabled:
        return
    r = _rate
    if r <= 0 or not key or key[0] % r:
        return
    fp = key_fp(key)
    if fp == 0:
        return
    t = _T
    i = fp % t.capacity
    if t.fp[i] != _signed(fp) or t.t_send[i] != 0:
        return
    now = libhealth.now_ns()
    t.t_send[i] = now
    t.counts[ST_SEND] += 1
    admit = t.t_admit[i]
    libhealth.record(
        libhealth.EV_TX, 0, ST_SEND, _signed(fp),
        now - admit if admit else 0,
    )


def note_gossip_recv(key: bytes, wall_hint_ns: int = 0) -> None:
    """First receipt of the tx FROM a peer (set-once; creates the row
    when the tx reaches this node by gossip before local admission).
    ``wall_hint_ns`` is the sender-side stamp wall from the netstamp
    thread-local when the mempool channel negotiated provenance — the
    one-hop ``hop`` residency; 0 = unstamped link."""
    if not _enabled:
        return
    r = _rate
    if r <= 0 or not key or key[0] % r:
        return
    fp = key_fp(key)
    if fp == 0:
        return
    t = _T
    i = fp % t.capacity
    fps = _signed(fp)
    now = libhealth.now_ns()
    if t.fp[i] != fps:
        t.fp[i] = fps
        t.t_admit[i] = 0
        t.depth[i] = 0
        t.t_send[i] = 0
    elif t.t_recv[i] != 0:
        return  # later duplicate gossip of a tracked tx
    t.t_recv[i] = now
    lag = now - wall_hint_ns if wall_hint_ns else 0
    t.recv_lag[i] = lag if lag > 0 else 0
    t.counts[ST_RECV] += 1
    libhealth.record(
        libhealth.EV_TX, 0, ST_RECV, fps, t.recv_lag[i]
    )


def note_proposal(height: int, round_: int) -> None:
    """An accepted proposal for ``height`` (consensus/state hook; one
    call per proposal, NOT per tx).  The stamp is backfilled into each
    sampled tx the height later commits — the proposal message does
    not name its txs, so the per-tx join happens at commit where the
    keys are already derived."""
    if not _enabled:
        return
    t = _T
    i = height % t._PH_CAP
    t.ph[i] = height
    t.pr[i] = round_
    t.pts[i] = libhealth.now_ns()


def note_commit(key: bytes, height: int) -> None:
    """The tx landed in the committed block at ``height``
    (CListMempool.update) — closes the row into the completion ring.
    Recorded for every sampled committed tx even when this node never
    admitted it (blocksync replay, table eviction): the commit tally
    must reconcile against EV_COMMIT tx counts."""
    if not _enabled:
        return
    r = _rate
    if r <= 0 or not key or key[0] % r:
        return
    fp = key_fp(key)
    if fp == 0:
        return
    t = _T
    i = fp % t.capacity
    fps = _signed(fp)
    now = libhealth.now_ns()
    if t.fp[i] == fps:
        admit, depth = t.t_admit[i], t.depth[i]
        send, recv, lag = t.t_send[i], t.t_recv[i], t.recv_lag[i]
        t.fp[i] = 0  # free the slot
    else:
        admit = depth = send = recv = lag = 0
    # proposal backfill: the accepted proposal stamp for this height
    pi = height % t._PH_CAP
    prop_ts = t.pts[pi] if t.ph[pi] == height else 0
    prop_r = t.pr[pi] if t.ph[pi] == height else -1
    # completion-ring slot (GIL-atomic reservation, libs/health style)
    seq = next(t.d_seq)
    k = seq % t.d_cap
    t.d_fp[k] = 0  # mark in-progress: readers skip torn rows
    t.d_h[k] = height
    t.d_r[k] = prop_r
    t.d_admit[k] = admit
    t.d_total[k] = now - admit if admit else 0
    t.d_send[k] = send - admit if (admit and send) else -1
    t.d_recv_lag[k] = lag if recv else -1
    if admit and prop_ts:
        p = prop_ts - admit
        t.d_prop[k] = p if p > 0 else 0
    else:
        t.d_prop[k] = -1
    if prop_ts:
        w = now - prop_ts
        t.d_wait[k] = w if w > 0 else 0
    else:
        t.d_wait[k] = -1
    t.d_depth[k] = depth if admit else -1
    t.d_fp[k] = fps  # publish last
    if seq >= t.d_written[0]:
        t.d_written[0] = seq + 1
    t.counts[ST_COMMIT] += 1
    if prop_ts:
        t.counts[ST_PROPOSAL] += 1
    libhealth.record(
        libhealth.EV_TX, height, ST_COMMIT, fps,
        t.d_total[k],
    )


def note_commit_many(keys, height: int) -> None:
    """Batched commit stamping: ONE call per committed block
    (CListMempool.update already derives every committed key as a
    batch).  The not-sampled per-key cost is a byte index and a modulo
    inside one loop — no per-tx function call, which measurably
    matters: the call overhead alone was the largest share of the
    plane's per-tx cost (bench 20_tx_lifecycle's record_ns columns)."""
    if not _enabled:
        return
    r = _rate
    if r <= 0:
        return
    for key in keys:
        if not key or key[0] % r:
            continue
        note_commit(key, height)


# -- read paths (scrape / debug / bench — may allocate) ------------------


def _iter_done():
    t = _T
    w = t.d_written[0]
    n = min(w, t.d_cap)
    for seq in range(w - n, w):
        yield seq, seq % t.d_cap


def completed_rows(limit: int | None = None) -> list[dict]:
    """Decoded completion-ring rows, oldest first (lock-free snapshot;
    torn rows are skipped)."""
    t = _T
    out = []
    for _seq, k in _iter_done():
        fps = t.d_fp[k]
        if fps == 0:
            continue
        row = {
            "key": fp_hex(fps),
            "height": t.d_h[k],
            "round": t.d_r[k] if t.d_r[k] >= 0 else None,
            "latency_s": (
                round(t.d_total[k] / 1e9, 6) if t.d_total[k] else None
            ),
            "admit_to_send_s": (
                round(t.d_send[k] / 1e9, 6) if t.d_send[k] >= 0 else None
            ),
            "hop_s": (
                round(t.d_recv_lag[k] / 1e9, 6)
                if t.d_recv_lag[k] >= 0
                else None
            ),
            "admit_to_proposal_s": (
                round(t.d_prop[k] / 1e9, 6) if t.d_prop[k] >= 0 else None
            ),
            "proposal_to_commit_s": (
                round(t.d_wait[k] / 1e9, 6) if t.d_wait[k] >= 0 else None
            ),
            "depth_at_admit": (
                t.d_depth[k] if t.d_depth[k] >= 0 else None
            ),
        }
        out.append(row)
    return out[-limit:] if limit else out


def in_flight_rows(now_ns: int | None = None) -> list[dict]:
    """Sampled txs admitted/received but not yet committed."""
    t = _T
    if now_ns is None:
        now_ns = libhealth.now_ns()
    out = []
    for i in range(t.capacity):
        fps = t.fp[i]
        if fps == 0:
            continue
        admit = t.t_admit[i]
        first = admit or t.t_recv[i]
        out.append({
            "key": fp_hex(fps),
            "age_s": (
                round((now_ns - first) / 1e9, 6) if first else None
            ),
            "admitted": bool(admit),
            "depth_at_admit": t.depth[i] if admit else None,
            "gossip_sent": bool(t.t_send[i]),
            "gossip_received": bool(t.t_recv[i]),
        })
    out.sort(key=lambda r: -(r["age_s"] or 0.0))
    return out


def commit_latencies_s() -> list[float]:
    """Submit→commit latencies of completed rows with a known admit
    (seconds) — the bench p50/p99 source."""
    t = _T
    out = []
    for _seq, k in _iter_done():
        if t.d_fp[k] != 0 and t.d_total[k] > 0:
            out.append(t.d_total[k] / 1e9)
    return out


def stage_counts() -> dict[str, int]:
    return {
        name: _T.counts[code] for code, name in STAGE_NAMES.items()
    }


def effective_rate() -> float:
    """The rate the one-byte predicate ACTUALLY samples at: exact for
    divisors of 256 (incl. the default 64), 256/ceil(256/r) otherwise,
    and 256 for anything above — the number a consumer must scale
    sampled counts by (0.0 = sampling off)."""
    r = _rate
    if r <= 0:
        return 0.0
    matching = sum(1 for b in range(256) if b % r == 0)
    return 256.0 / matching


def status() -> dict:
    return {
        "enabled": _enabled,
        "sample_rate": _rate,
        "sample_rate_effective": round(effective_rate(), 2),
        "capacity": _T.capacity,
        "completed": _T.d_written[0],
        "counts": stage_counts(),
    }


def mempool_table(n: int = 8) -> list[dict]:
    """Oldest admitted-uncommitted txs per registered mempool (the
    starved keys a tx_starved bundle names; key prefixes only)."""
    out = []
    for mp in mempools():
        try:
            entries = mp.oldest_entries(n)
        except Exception:
            continue
        out.append({
            "size": mp.size(),
            "oldest": [
                {
                    "key": fp_hex(_signed(key_fp(key))),
                    "age_s": round(age, 6),
                    "sampled": _sampled(key_fp(key)),
                }
                for key, age in entries
            ],
        })
    return out


def snapshot() -> dict:
    """The ``tx.json`` bundle body and the ``/debug/tx`` index view."""
    return {
        **status(),
        "oldest_admitted_age_s": round(oldest_admitted_age_s(), 6),
        "mempools": mempool_table(),
        "in_flight": in_flight_rows()[:64],
        "recent_completed": completed_rows(limit=64),
    }


def lookup(prefix: str) -> dict:
    """'Where is my transaction': rows whose 16-hex-char key prefix
    starts with ``prefix`` (a full 64-char tx-key hex is accepted and
    truncated — only the first 8 key bytes are retained)."""
    prefix = prefix.strip().lower()[:16]
    t = _T
    in_flight = [
        r for r in in_flight_rows() if r["key"].startswith(prefix)
    ]
    completed = [
        r for r in completed_rows() if r["key"].startswith(prefix)
    ]
    fp = None
    sampled = None
    if prefix and all(c in "0123456789abcdef" for c in prefix):
        if len(prefix) == 16:
            fp = int(prefix, 16)
            sampled = _sampled(fp)
    return {
        "prefix": prefix,
        "sampled": sampled,
        "sample_rate": _rate,
        "in_flight": in_flight,
        "completed": completed,
    }


def debug_tx_json(prefix: str | None = None) -> str:
    """Body of the pprof server's ``/debug/tx`` route."""
    import json

    if prefix:
        return json.dumps(lookup(prefix), default=str)
    return json.dumps(snapshot(), default=str)


def sample(metrics=None) -> None:
    """Scrape-time bridge (called from libs/health.sample): completed
    rows since the per-registry watermark observe into the tx
    histograms, stage tallies bridge into ``tx_sampled_total``, and
    ``mempool_oldest_age_seconds`` is set from the live mempools —
    the devledger watermark pattern, so multi-node scrapes each see
    the full series and the record path touches no metrics object."""
    from . import metrics as libmetrics

    m = metrics if metrics is not None else libmetrics.node_metrics()
    t = _T
    wm = getattr(m, "_txtrace_wm", None)
    if wm is None:
        wm = m._txtrace_wm = {"seq": 0, "counts": [0] * 8}
    w = t.d_written[0]
    start = max(wm["seq"], w - t.d_cap)
    for seq in range(start, w):
        k = seq % t.d_cap
        if t.d_fp[k] == 0:
            continue
        if t.d_total[k] > 0:
            m.tx_commit_latency.observe(t.d_total[k] / 1e9)
        if t.d_send[k] >= 0:
            m.tx_stage_seconds.labels("admit_to_send").observe(
                t.d_send[k] / 1e9
            )
        if t.d_recv_lag[k] >= 0:
            m.tx_stage_seconds.labels("hop").observe(
                t.d_recv_lag[k] / 1e9
            )
        if t.d_prop[k] >= 0:
            m.tx_stage_seconds.labels("admit_to_proposal").observe(
                t.d_prop[k] / 1e9
            )
        if t.d_wait[k] >= 0:
            m.tx_stage_seconds.labels("proposal_to_commit").observe(
                t.d_wait[k] / 1e9
            )
    wm["seq"] = w
    seen = wm["counts"]
    for code, name in STAGE_NAMES.items():
        cur = t.counts[code]
        if cur > seen[code]:
            m.tx_sampled.labels(name).inc(cur - seen[code])
            seen[code] = cur
    m.mempool_oldest_age.set(round(oldest_admitted_age_s(), 6))
