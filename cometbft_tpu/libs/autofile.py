"""Rotating file groups (reference: libs/autofile/group.go:540).

``Group`` appends to ``<path>`` (the "head") and rotates it to
``<path>.000``, ``<path>.001``, … when it exceeds ``head_size_limit``;
oldest files are dropped once the group exceeds ``group_size_limit``.
The consensus WAL sits on top of this. ``GroupReader`` reads the whole
group in order (rotated files first, head last), which WAL replay and
``SearchForEndHeight`` use.
"""

from __future__ import annotations

import os
import re
from . import sync as libsync

DEFAULT_HEAD_SIZE_LIMIT = 10 * 1024 * 1024  # 10MB (group.go:27)
DEFAULT_GROUP_SIZE_LIMIT = 1024 * 1024 * 1024  # 1GB (group.go:28)

_INDEX_RE = re.compile(r"\.(\d{3,})$")


class Group:
    def __init__(
        self,
        head_path: str,
        head_size_limit: int = DEFAULT_HEAD_SIZE_LIMIT,
        group_size_limit: int = DEFAULT_GROUP_SIZE_LIMIT,
    ):
        self.head_path = head_path
        self.head_size_limit = head_size_limit
        self.group_size_limit = group_size_limit
        self._mtx = libsync.Mutex("libs.autofile._mtx")
        os.makedirs(os.path.dirname(head_path) or ".", exist_ok=True)
        self._head = open(head_path, "ab")

    # -- writing -----------------------------------------------------------

    def write(self, data: bytes) -> None:
        with self._mtx:
            self._head.write(data)

    def flush(self) -> None:
        with self._mtx:
            self._head.flush()

    def flush_and_sync(self) -> None:
        with self._mtx:  # cometlint: disable=CLNT009 -- the group mutex serializes write+fsync: the WAL durability point
            self._head.flush()
            os.fsync(self._head.fileno())

    def check_head_size_limit(self) -> None:
        """Rotate the head if over limit (called periodically by the WAL)."""
        with self._mtx:
            self._head.flush()
            if self._head.tell() >= self.head_size_limit:
                self._rotate()
            self._check_total_size()

    def _rotate(self) -> None:
        self._head.close()
        idx = self.max_index() + 1
        os.replace(self.head_path, f"{self.head_path}.{idx:03d}")
        self._head = open(self.head_path, "ab")

    def _check_total_size(self) -> None:
        while True:
            paths = self._rotated_paths()
            total = sum(os.path.getsize(p) for p in paths) + self._head.tell()
            if total <= self.group_size_limit or not paths:
                return
            os.remove(paths[0])  # drop the oldest

    # -- indexes -----------------------------------------------------------

    def _rotated_paths(self) -> list[str]:
        d = os.path.dirname(self.head_path) or "."
        base = os.path.basename(self.head_path)
        out = []
        for name in os.listdir(d):
            if not name.startswith(base + "."):
                continue
            m = _INDEX_RE.search(name)
            if m:
                out.append((int(m.group(1)), os.path.join(d, name)))
        return [p for _, p in sorted(out)]

    def min_index(self) -> int:
        paths = self._rotated_paths()
        if not paths:
            return 0
        return int(_INDEX_RE.search(paths[0]).group(1))

    def max_index(self) -> int:
        paths = self._rotated_paths()
        if not paths:
            return -1
        return int(_INDEX_RE.search(paths[-1]).group(1))

    def all_paths(self) -> list[str]:
        """Rotated files in order, then the head."""
        return self._rotated_paths() + [self.head_path]

    def close(self) -> None:
        with self._mtx:
            self._head.flush()
            self._head.close()


class GroupReader:
    """Sequential reader over a whole group (rotated files, then head)."""

    def __init__(self, group: Group):
        group.flush()
        self._paths = group.all_paths()
        self._i = 0
        self._f = None
        self._advance()

    def _advance(self) -> None:
        if self._f:
            self._f.close()
            self._f = None
        while self._i < len(self._paths):
            p = self._paths[self._i]
            self._i += 1
            if os.path.exists(p):
                self._f = open(p, "rb")
                return

    def read(self, n: int) -> bytes:
        out = b""
        while n > 0 and self._f is not None:
            chunk = self._f.read(n)
            if chunk:
                out += chunk
                n -= len(chunk)
            else:
                self._advance()
        return out

    def close(self) -> None:
        if self._f:
            self._f.close()
            self._f = None
