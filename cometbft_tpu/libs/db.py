"""Key-value store abstraction (reference: cometbft-db dependency).

The reference stores blocks/state/indexes on a pluggable KV interface
(goleveldb default — config/config.go:256). Here the same interface is a
small ABC with two backends:

* ``MemDB`` — sorted in-memory store (tests, ephemeral nodes).
* ``FileDB`` — persistent append-only log with in-memory index and
  compaction, durable across restarts. Plays goleveldb's role without a
  native dependency; the interface leaves room for a C++ backend later.

Iteration is ordered by raw bytes, half-open ``[start, end)``, matching the
reference semantics that the indexers and stores rely on.
"""

from __future__ import annotations

import os
import struct
from . import sync as libsync
from bisect import bisect_left, insort
from typing import Iterator


class DB:
    """The cometbft-db interface subset the framework uses."""

    def get(self, key: bytes) -> bytes | None:
        raise NotImplementedError

    def has(self, key: bytes) -> bool:
        return self.get(key) is not None

    def set(self, key: bytes, value: bytes) -> None:
        raise NotImplementedError

    def set_sync(self, key: bytes, value: bytes) -> None:
        self.set(key, value)

    def delete(self, key: bytes) -> None:
        raise NotImplementedError

    def delete_sync(self, key: bytes) -> None:
        self.delete(key)

    def iterator(
        self, start: bytes | None = None, end: bytes | None = None
    ) -> Iterator[tuple[bytes, bytes]]:
        raise NotImplementedError

    def reverse_iterator(
        self, start: bytes | None = None, end: bytes | None = None
    ) -> Iterator[tuple[bytes, bytes]]:
        raise NotImplementedError

    def new_batch(self) -> "Batch":
        return Batch(self)

    def close(self) -> None:
        pass


class Batch:
    """Write batch: buffered mutations applied atomically on ``write()``."""

    def __init__(self, db: DB):
        self._db = db
        self._ops: list[tuple[bool, bytes, bytes]] = []

    def set(self, key: bytes, value: bytes) -> None:
        self._ops.append((True, bytes(key), bytes(value)))

    def delete(self, key: bytes) -> None:
        self._ops.append((False, bytes(key), b""))

    def write(self) -> None:
        self._db.apply_batch(self._ops)
        self._ops = []

    def write_sync(self) -> None:
        self.write()


class MemDB(DB):
    def __init__(self) -> None:
        self._mtx = libsync.RLock("libs.db._mtx")
        self._data: dict[bytes, bytes] = {}
        self._keys: list[bytes] = []  # sorted view for iteration

    def get(self, key: bytes) -> bytes | None:
        with self._mtx:
            return self._data.get(bytes(key))

    def set(self, key: bytes, value: bytes) -> None:
        key, value = bytes(key), bytes(value)
        with self._mtx:
            if key not in self._data:
                insort(self._keys, key)
            self._data[key] = value

    def delete(self, key: bytes) -> None:
        key = bytes(key)
        with self._mtx:
            if key in self._data:
                del self._data[key]
                i = bisect_left(self._keys, key)
                del self._keys[i]

    def apply_batch(self, ops: list[tuple[bool, bytes, bytes]]) -> None:
        with self._mtx:  # cometlint: disable=CLNT009 -- MemDB batch is memory-only; FileDB's fsync sites carry their own justification
            for is_set, k, v in ops:
                if is_set:
                    self.set(k, v)
                else:
                    self.delete(k)

    def _range_keys(self, start: bytes | None, end: bytes | None) -> list[bytes]:
        lo = 0 if start is None else bisect_left(self._keys, bytes(start))
        hi = len(self._keys) if end is None else bisect_left(self._keys, bytes(end))
        return self._keys[lo:hi]

    def iterator(self, start=None, end=None):
        with self._mtx:
            keys = self._range_keys(start, end)
            snap = [(k, self._data[k]) for k in keys]
        yield from snap

    def reverse_iterator(self, start=None, end=None):
        with self._mtx:
            keys = self._range_keys(start, end)
            snap = [(k, self._data[k]) for k in reversed(keys)]
        yield from snap


def prefix_end(prefix: bytes) -> bytes | None:
    """Smallest key greater than every key with the given prefix, or None
    if no such key exists (prefix is all 0xff). For prefix iteration:
    ``db.iterator(p, prefix_end(p))`` covers exactly the keys under ``p``.
    """
    p = bytearray(prefix)
    while p and p[-1] == 0xFF:
        p.pop()
    if not p:
        return None
    p[-1] += 1
    return bytes(p)


# FileDB file framing: 5-byte magic, then records of
# u8 op | u32 klen | u32 vlen | key | value.
# The magic distinguishes this format from the native engine's
# CRC-framed "NKV1\n" files: opening a foreign-format file raises
# instead of parsing zero records and truncating the database to zero
# (a flipped db_backend in config must not silently erase data).
# Deliberately NO legacy (pre-magic) acceptance path: the formats are
# pre-release with no deployed data dirs, and sniffing legacy records
# is exactly the ambiguity that allowed cross-format erasure (both
# framings begin with a plausible op byte).
_HDR = struct.Struct("<BII")
_OP_SET, _OP_DEL, _OP_BATCH = 1, 2, 3
_MAGIC = b"FKV1\n"


class FileDB(MemDB):
    """Durable log-structured store: MemDB index + append-only on-disk log.

    Every mutation appends a framed record; ``compact()`` (run automatically
    when the log grows past ``compact_factor`` × live size) rewrites the log
    to just the live records. A torn final record (crash mid-append) is
    truncated on open — the same recover-to-last-good-record posture the
    reference's WAL takes (consensus/wal.go). Batches are one BATCH record
    (sub-records nested in its value), so a batch is atomic under crash:
    either the whole record replays or the torn tail is dropped.
    """

    def __init__(self, path: str, compact_factor: int = 4):
        super().__init__()
        self._path = path
        self._compact_factor = compact_factor
        self._live_bytes = 0
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._replay()
        self._f = open(path, "ab")
        if self._f.tell() == 0:
            self._f.write(_MAGIC)
            self._f.flush()

    def _replay(self) -> None:
        if not os.path.exists(self._path):
            return
        if os.path.getsize(self._path) == 0:
            return
        good = len(_MAGIC)
        with open(self._path, "rb") as f:
            head = f.read(len(_MAGIC))
            if len(head) < len(_MAGIC) and head == _MAGIC[: len(head)]:
                # crash between file creation and the magic becoming
                # durable: a strict prefix of the magic is a torn tail of
                # an EMPTY database, not a foreign format — reset to
                # empty (the constructor rewrites the magic)
                with open(self._path, "r+b") as t:
                    t.truncate(0)
                return
            if head != _MAGIC:
                raise ValueError(
                    f"{self._path}: not a FileDB file (bad magic "
                    f"{head!r}; native-engine files start with b'NKV1\\n' "
                    f"— was db_backend changed?)"
                )
            while True:
                hdr = f.read(_HDR.size)
                if len(hdr) < _HDR.size:
                    break
                op, klen, vlen = _HDR.unpack(hdr)
                body = f.read(klen + vlen)
                if len(body) < klen + vlen or op not in (
                    _OP_SET,
                    _OP_DEL,
                    _OP_BATCH,
                ):
                    break
                key, value = body[:klen], body[klen:]
                if op == _OP_SET:
                    super().set(key, value)
                elif op == _OP_DEL:
                    super().delete(key)
                else:
                    try:
                        sub = self._decode_batch(value)
                    except ValueError:
                        break
                    for is_set, k, v in sub:
                        if is_set:
                            super().set(k, v)
                        else:
                            super().delete(k)
                good = f.tell()
        size = os.path.getsize(self._path)
        if size > good:
            with open(self._path, "r+b") as f:
                f.truncate(good)
        self._recount()

    def _recount(self) -> None:
        self._live_bytes = sum(
            _HDR.size + len(k) + len(v) for k, v in self._data.items()
        )

    def _append(self, op: int, key: bytes, value: bytes, sync: bool) -> None:
        self._f.write(_HDR.pack(op, len(key), len(value)) + key + value)
        self._f.flush()
        if sync:
            os.fsync(self._f.fileno())

    def _account(self, key: bytes, new_value: bytes | None) -> None:
        """Update the live-size estimate across an overwrite or delete.
        Must run BEFORE the in-memory update (needs the old value)."""
        old = self._data.get(key)
        if old is not None:
            self._live_bytes -= _HDR.size + len(key) + len(old)
        if new_value is not None:
            self._live_bytes += _HDR.size + len(key) + len(new_value)

    def _set_locked(self, key: bytes, value: bytes, sync: bool) -> None:
        self._account(key, value)
        super().set(key, value)
        self._append(_OP_SET, key, value, sync=sync)
        self._maybe_compact()

    def set(self, key: bytes, value: bytes) -> None:
        with self._mtx:  # cometlint: disable=CLNT009 -- FileDB's mutex is the atomicity boundary for the append-log record
            self._set_locked(bytes(key), bytes(value), sync=False)

    def set_sync(self, key: bytes, value: bytes) -> None:
        with self._mtx:  # cometlint: disable=CLNT009 -- set_sync exists to fsync under the DB mutex: the durability contract
            self._set_locked(bytes(key), bytes(value), sync=True)

    def delete(self, key: bytes) -> None:
        key = bytes(key)
        with self._mtx:  # cometlint: disable=CLNT009 -- delete record must pair with the in-memory delete atomically
            self._account(key, None)
            super().delete(key)
            self._append(_OP_DEL, key, b"", sync=False)

    @staticmethod
    def _decode_batch(blob: bytes) -> list[tuple[bool, bytes, bytes]]:
        ops, pos = [], 0
        while pos < len(blob):
            if pos + _HDR.size > len(blob):
                raise ValueError("truncated batch sub-record")
            op, klen, vlen = _HDR.unpack_from(blob, pos)
            pos += _HDR.size
            if pos + klen + vlen > len(blob) or op not in (_OP_SET, _OP_DEL):
                raise ValueError("corrupt batch sub-record")
            ops.append(
                (op == _OP_SET, blob[pos : pos + klen], blob[pos + klen : pos + klen + vlen])
            )
            pos += klen + vlen
        return ops

    def apply_batch(self, ops: list[tuple[bool, bytes, bytes]]) -> None:
        blob = b"".join(
            _HDR.pack(_OP_SET if is_set else _OP_DEL, len(k), len(v)) + k + v
            for is_set, k, v in ops
        )
        with self._mtx:  # cometlint: disable=CLNT009 -- a batch is one atomic fsynced log record
            for is_set, k, v in ops:
                self._account(k, v if is_set else None)
                if is_set:
                    MemDB.set(self, k, v)
                else:
                    MemDB.delete(self, k)
            self._append(_OP_BATCH, b"", blob, sync=True)
            self._maybe_compact()

    def _maybe_compact(self) -> None:
        log_size = self._f.tell()
        if log_size > max(1 << 16, self._compact_factor * self._live_bytes):
            self.compact()

    def compact(self) -> None:
        with self._mtx:  # cometlint: disable=CLNT009 -- compaction rewrites the log; the mutex holds off writers
            tmp = self._path + ".compact"
            with open(tmp, "wb") as out:
                out.write(_MAGIC)
                for k in self._keys:
                    v = self._data[k]
                    out.write(_HDR.pack(_OP_SET, len(k), len(v)) + k + v)
                out.flush()
                os.fsync(out.fileno())
            self._f.close()
            os.replace(tmp, self._path)
            self._f = open(self._path, "ab")
            self._recount()

    def close(self) -> None:
        with self._mtx:
            self._f.close()
