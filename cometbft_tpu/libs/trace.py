"""Low-overhead span/event tracer for the consensus + TPU hot paths.

The CometBFT reference grew ``libs/trace`` (a JSONL event tracer wired
into consensus and p2p) because aggregate metrics cannot answer "where
did THIS slow round spend its time". This is the TPU-native analog: the
batch-verify pipeline's phases (pack / dispatch / readback / fallback),
consensus height/round/step transitions, vote admission, mempool
CheckTx, p2p channel traffic, blocksync applies and WAL fsyncs all emit
timestamped records into a bounded in-memory ring, optionally teed to a
rotating JSONL file (``libs/autofile.Group``).

Design constraints (in priority order):

* **Zero cost when off.** ``COMETBFT_TPU_TRACE`` unset means every
  entry point is one module-flag check and an immediate return: no
  allocation retained, no lock touched, no clock read.  Hot-path call
  sites additionally guard with :func:`enabled` before building their
  field dicts so the disabled path does not even allocate kwargs
  (pinned by tests/test_observability.py's allocation guard).
* **Never block an engine thread.** Record emission appends to a
  ``collections.deque`` (GIL-atomic, lock-free) — the file sink has a
  dedicated writer thread draining a second deque, so no engine mutex
  ever reaches file I/O through the tracer (cometlint CLNT009).  The
  single lock here (``libs.trace._mtx``) only serializes sink
  start/stop and is never held across blocking calls.

Record schema (one JSON object per line in the file sink, same dicts
from :func:`ring_dump`)::

    {"ts": <wall-clock ns>, "kind": "event"|"span", "name": str,
     "thread": str, ...}
    span records add:   "span": id, "parent": id, "dur_ns": int
    event records add:  "span": id of the enclosing with-span (if any)
                        plus free-form fields ("dur_ns", "backend",
                        "lanes", "height", ...)

Knobs (registered in config.ENV_KNOBS, enforced by cometlint CLNT007):
``COMETBFT_TPU_TRACE`` (on|1 enables), ``COMETBFT_TPU_TRACE_FILE``
(JSONL sink path), ``COMETBFT_TPU_TRACE_RING`` (ring capacity).
"""

from __future__ import annotations

import itertools
import json
import os
import sys
import threading
import time
from collections import deque

from . import autofile
from . import sync as libsync

_ENV_TRACE = "COMETBFT_TPU_TRACE"
_ENV_TRACE_FILE = "COMETBFT_TPU_TRACE_FILE"
_ENV_TRACE_RING = "COMETBFT_TPU_TRACE_RING"

DEFAULT_RING_SIZE = 8192

_ON_VALUES = ("1", "on", "true", "yes")


def _ring_size_from_env() -> int:
    raw = os.environ.get(_ENV_TRACE_RING, "")
    try:
        n = int(raw) if raw else DEFAULT_RING_SIZE
    except ValueError:
        n = DEFAULT_RING_SIZE
    return max(16, n)


_enabled: bool = os.environ.get(_ENV_TRACE, "").lower() in _ON_VALUES
_ring: deque = deque(maxlen=_ring_size_from_env())
_ids = itertools.count(1)  # span ids; count.__next__ is GIL-atomic
_tls = threading.local()  # .spans: stack of with-entered Span objects
_mtx = libsync.Mutex("libs.trace._mtx")  # sink start/stop only
_sink: "_FileSink | None" = None


def enabled() -> bool:
    """The one check hot paths make before building trace fields."""
    return _enabled


def enable(ring: int | None = None) -> None:
    """Turn tracing on (tests, /debug/trace/start). ``ring`` resizes the
    buffer, preserving the newest records."""
    global _enabled, _ring
    if ring is not None and ring != _ring.maxlen:
        _ring = deque(_ring, maxlen=max(16, ring))
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def reset() -> None:
    """Drop all buffered records (tests, bench bursts)."""
    _ring.clear()


def ring_dump() -> list[dict]:
    """Snapshot of the ring buffer, oldest first.

    Emitters append concurrently (lock-free by design); a full ring
    mutates on every append, so iteration can observe a mutation and
    raise — retry until a consistent snapshot lands rather than 500ing
    the /debug/trace scrape exactly when the node is busy.
    """
    while True:
        try:
            return list(_ring)
        except RuntimeError:  # deque mutated during iteration
            continue


def status() -> dict:
    s = _sink
    return {
        "enabled": _enabled,
        "ring_capacity": _ring.maxlen,
        "ring_len": len(_ring),
        "sink": s.path if s is not None else None,
    }


# ------------------------------------------------------------- emission


def _emit(
    kind: str,
    name: str,
    fields: dict | None,
    span_id: int = 0,
    parent_id: int = 0,
    dur_ns: int | None = None,
) -> None:
    rec: dict = {
        "ts": time.time_ns(),
        "kind": kind,
        "name": name,
        "thread": threading.current_thread().name,
    }
    if span_id:
        rec["span"] = span_id
    if parent_id:
        rec["parent"] = parent_id
    if dur_ns is not None:
        rec["dur_ns"] = dur_ns
    if fields:
        rec.update(fields)
    _ring.append(rec)
    s = _sink
    if s is not None:
        s.put(rec)


def _span_stack() -> list:
    stack = getattr(_tls, "spans", None)
    if stack is None:
        stack = _tls.spans = []
    return stack


def event(name: str, **fields) -> None:
    """Record one point event. Attributed to the innermost with-entered
    span on this thread, if any."""
    if not _enabled:
        return
    stack = getattr(_tls, "spans", None)
    _emit("event", name, fields, span_id=stack[-1].id if stack else 0)


class Span:
    """A timed interval.  Two usage modes:

    * ``with span("name", k=v): ...`` — nests on the per-thread stack,
      so events inside attribute to it automatically;
    * ``sp = begin("name", parent=outer); ...; sp.end()`` — manual
      lifetime for state-machine phases (consensus height/round/step)
      that do not nest lexically.  Manual spans never touch the thread
      stack, so they are safe to end from a different callback.

    One record is emitted at ``end()`` carrying the measured
    ``dur_ns``; a span never ends twice.
    """

    __slots__ = ("name", "id", "parent", "fields", "_t0", "_ended")

    def __init__(self, name: str, parent_id: int, fields: dict | None):
        self.name = name
        self.id = next(_ids)
        self.parent = parent_id
        self.fields = fields
        self._t0 = time.monotonic_ns()
        self._ended = False

    def event(self, name: str, **fields) -> None:
        if not _enabled:
            return
        _emit("event", name, fields, span_id=self.id)

    def end(self, **fields) -> None:
        if self._ended:
            return
        self._ended = True
        if not _enabled:
            # tracing was turned off mid-span: drop the record — once
            # disabled, nothing reaches the ring or sink
            return
        merged = self.fields
        if fields:
            merged = dict(merged or ())
            merged.update(fields)
        _emit(
            "span",
            self.name,
            merged,
            span_id=self.id,
            parent_id=self.parent,
            dur_ns=time.monotonic_ns() - self._t0,
        )

    def __enter__(self) -> "Span":
        _span_stack().append(self)
        return self

    def __exit__(self, *exc) -> None:
        stack = _span_stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] is self:
                del stack[i]
                break
        self.end()


class _NopSpan:
    """Shared do-nothing span: the disabled path allocates nothing."""

    __slots__ = ()
    id = 0

    def event(self, name: str, **fields) -> None:
        pass

    def end(self, **fields) -> None:
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NOP_SPAN = _NopSpan()


def span(name: str, **fields):
    """A span for ``with`` use; parent = innermost entered span."""
    if not _enabled:
        return NOP_SPAN
    stack = getattr(_tls, "spans", None)
    return Span(name, stack[-1].id if stack else 0, fields or None)


def begin(name: str, parent: "Span | None" = None, **fields):
    """Start a manually-ended span (see :class:`Span`)."""
    if not _enabled:
        return NOP_SPAN
    parent_id = parent.id if parent is not None else 0
    return Span(name, parent_id, fields or None)


# ------------------------------------------------------------ file sink


class _FileSink:
    """JSONL writer on a rotating autofile Group.

    Emitters append records to a bounded deque (lossy under extreme
    backlog — tracing must shed load, never apply backpressure); the
    dedicated writer thread drains it and owns all file I/O, so no
    engine lock is ever held across a write or rotation.
    """

    BUFFER = 1 << 16

    def __init__(self, path: str):
        self.path = path
        self.group = autofile.Group(path)
        self._buf: deque = deque(maxlen=self.BUFFER)
        self._wake = threading.Event()
        self._stop = False
        self._thread = threading.Thread(
            target=self._run, name="trace-sink", daemon=True
        )
        self._thread.start()

    def put(self, rec: dict) -> None:
        self._buf.append(rec)
        self._wake.set()

    def _drain(self) -> None:
        lines = []
        while True:
            try:
                lines.append(self._buf.popleft())
            except IndexError:
                break
        if lines:
            data = "".join(
                json.dumps(rec, default=str) + "\n" for rec in lines
            ).encode()
            self.group.write(data)
            self.group.flush()
            self.group.check_head_size_limit()

    def _run(self) -> None:
        while True:
            self._wake.wait(0.1)
            self._wake.clear()
            try:
                self._drain()
            except Exception as e:
                # a failing sink must never take down tracing or the
                # engine: drop to ring-only AND deregister, so status()
                # stops claiming an active sink and a fresh
                # start_file_sink isn't blocked by the corpse
                sys.stderr.write(f"trace sink failed, stopping: {e!r}\n")
                _deregister_sink(self)
                return
            if self._stop and not self._buf:
                return

    def close(self) -> None:
        self._stop = True
        self._wake.set()
        self._thread.join(timeout=2)
        if self._thread.is_alive():
            # writer wedged inside a write (hung disk): it still owns
            # the group — racing it with a caller-thread drain/close
            # would interleave records and write on a closed file.
            # Leak the handle; the daemon thread dies with the process.
            sys.stderr.write(
                f"trace sink writer stuck; abandoning {self.path}\n"
            )
            return
        try:
            self._drain()  # writer exited: final drain on this thread
            self.group.close()
        except Exception:
            sys.stderr.write(f"trace sink close failed: {self.path}\n")


def _deregister_sink(sink: "_FileSink") -> None:
    """Clear ``sink`` from the module slot if it still owns it (writer
    self-removal on a fatal I/O error)."""
    global _sink
    with _mtx:
        if _sink is sink:
            _sink = None


def start_file_sink(path: str) -> bool:
    """Tee records to a rotating JSONL file. False if a sink is already
    active (stop it first)."""
    global _sink
    new = None
    with _mtx:
        if _sink is not None:
            return False
        new = _sink = _FileSink(path)
    return new is not None


def stop_file_sink() -> bool:
    """Stop and flush the file sink. False when none was active."""
    global _sink
    with _mtx:
        s, _sink = _sink, None
    if s is None:
        return False
    s.close()  # outside the lock: close joins the writer thread
    return True


def _autostart_sink_from_env() -> None:
    path = os.environ.get(_ENV_TRACE_FILE, "")
    if _enabled and path:
        try:
            start_file_sink(path)
        except Exception as e:
            sys.stderr.write(
                f"trace: cannot open {_ENV_TRACE_FILE}={path!r}: {e!r}\n"
            )


_autostart_sink_from_env()
