"""Network-plane telemetry: per-peer/per-channel stats + message provenance.

The reference CometBFT ships a rich p2p metrics surface
(p2p/metrics.go: per-channel ``message_{send,receive}_bytes_total``,
``peer_pending_send_bytes``) and the tpu-bft committee-consensus
measurements (PAPERS.md, arxiv 2302.00418) show vote dissemination +
verification dominating latency at scale — yet until this layer the p2p
plane here was nearly blind: one ``p2p_peers`` gauge and two byte
counters.  Three pillars close that gap:

* **Per-peer/per-channel stats** (:class:`ConnStats`): every live
  ``MConnection`` registers one stats block — per-channel message/byte
  counters, send-queue depth + high-watermark, queue-full drop tallies,
  last-send/recv timestamps — stored in preallocated ``array('q')``
  columns indexed by a channel→slot map built at connection setup.  The
  record path is **lock-free by design**: the send columns are written
  only by the connection's single send routine, the recv columns only
  by its recv routine, so no mutex ever joins the wire path (cometlint
  CLNT009 / lockorder discipline — the one lock here,
  ``libs.netstats._mtx``, serializes only connection (de)registration
  and is asserted edge-free in tests/test_lint_graph.py).

* **Cross-node message provenance**: peers that advertise the
  ``netstamp`` capability in their NodeInfo prepend a fixed 23-byte
  stamp (magic + version + origin node-id prefix + per-peer monotonic
  seq + wall-clock hint) to every message on the :data:`STAMPED_CHANNELS`
  enum.  Stamping is **negotiated**, never sniffed blind: a sender
  stamps only toward peers that advertised the capability, so an
  unstamped (older) peer sees byte-identical wire traffic and an
  advertising peer's messages are always stamped — no payload can be
  confused with a stamp.  On receive the stamp is stripped, parked in a
  thread-local for the reactor dispatch (the recv routine calls the
  reactor synchronously), and the wall hint yields one-hop gossip lag:
  the consensus reactor attributes it per phase into
  ``p2p_propagation_seconds{phase}`` histograms and ``EV_GOSSIP``
  flight-recorder events.  The wall hint crosses node clocks — exact
  for in-process multi-node nets and benches (one clock), a skew-bound
  estimate between real hosts (documented in docs/observability.md).

* **Scrape-time aggregation** (:func:`sample`, :func:`snapshot`):
  per-channel queue depth/high-watermark gauges, queue-full counters,
  flowrate send/recv rates per peer (a capped **top-K by traffic plus
  an ``other`` bucket** keeps the ``peer`` label cardinality bounded —
  peer label values are 10-char node-id prefixes, never the full
  unbounded string), and the ``/debug/net`` JSON table served by the
  pprof server.

Design constraints (same tier as libs/health — this layer is on for
every running node):

* **Allocation-free when disabled.**  Every hot entry point is one
  module-flag check and an immediate return — pinned by the
  tracemalloc guard in tests/test_observability.py.
* **Allocation-light when enabled.**  Enabled recording performs only
  C-level array stores and small-int arithmetic; nothing is retained
  per packet.

Knobs (registered in config.ENV_KNOBS, enforced by cometlint CLNT007):
``COMETBFT_TPU_NET`` (auto: on while a node runs; 1 force; 0 off),
``COMETBFT_TPU_NET_STAMP`` (provenance stamping; default on — still
negotiated per peer), ``COMETBFT_TPU_NET_TOPK`` (peers exported with
their own ``peer`` label value before aggregating into ``other``).
"""

from __future__ import annotations

import itertools
import json
import os
import struct
import threading
import time
from array import array

from . import metrics as libmetrics
from . import sync as libsync
from . import trace as libtrace

_ENV_NET = "COMETBFT_TPU_NET"
_ENV_STAMP = "COMETBFT_TPU_NET_STAMP"
_ENV_TOPK = "COMETBFT_TPU_NET_TOPK"

_ON_VALUES = ("1", "on", "true", "yes")
_OFF_VALUES = ("0", "off", "false", "no")

DEFAULT_TOPK = 8
# recent one-hop gossip-lag window (ring of wall-hint deltas, seconds)
_LAG_RING = 512

# Channels that carry provenance stamps when both ends negotiated the
# capability. A fixed enum — never derived from peer input — so the
# ``chID`` label space stays bounded. The mempool channel is included:
# negotiation (never content sniffing) makes raw-tx payloads safe.
CONSENSUS_CHANNELS = frozenset({0x20, 0x21, 0x22, 0x23})
STAMPED_CHANNELS = frozenset({0x20, 0x21, 0x22, 0x23, 0x30, 0x40})

# -- provenance stamp wire format ---------------------------------------
# magic(2) | version u8 | origin node-id prefix (8 raw bytes = 16 hex
# chars) | per-peer monotonic seq u32 | wall-clock hint u64 (ns).
# The magic pair can never open a ser.dumps JSON payload, and stamping
# is negotiated anyway — the prefix check is a consistency assertion,
# not a discriminator.
STAMP_MAGIC = b"\xc5\x9d"
STAMP_VERSION = 1
_STAMP_FMT = "<2sB8sIQ"
STAMP_LEN = struct.calcsize(_STAMP_FMT)  # 23 bytes

NODEINFO_STAMP_KEY = "netstamp"

# propagation phase codes (EV_GOSSIP ``a`` column; names are the
# ``phase`` label of p2p_propagation_seconds).  The tail three are
# channel-grain phases the simnet delivery plane records (one EV_GOSSIP
# per delivered message, attributed by channel: 0x20/0x23 state,
# 0x22 vote, 0x38 evidence) — appended so existing codes never move.
PHASES = (
    "proposal", "block_part", "prevote", "precommit", "commit",
    "block", "tx", "state", "vote", "evidence",
)
PHASE_CODES = {name: i + 1 for i, name in enumerate(PHASES)}
PHASE_NAMES = {i + 1: name for i, name in enumerate(PHASES)}


def _env_mode() -> str:
    v = os.environ.get(_ENV_NET, "").lower()
    if v in _ON_VALUES:
        return "on"
    if v in _OFF_VALUES:
        return "off"
    return "auto"


def stamping_wanted() -> bool:
    """Whether this process advertises + applies provenance stamps
    (still negotiated per peer).  ``COMETBFT_TPU_NET_STAMP=0`` opts out
    of stamping alone; ``COMETBFT_TPU_NET=0`` kills it with the rest of
    the layer — a dark node must not pay the per-message stamp copy
    for telemetry nobody consumes."""
    if _env_mode() == "off":
        return False
    return (
        os.environ.get(_ENV_STAMP, "").lower() not in _OFF_VALUES
    )


def top_k() -> int:
    try:
        return max(1, int(os.environ.get(_ENV_TOPK, "")))
    except ValueError:
        return DEFAULT_TOPK


_mode = _env_mode()
_enabled: bool = _mode == "on"
_acquirers = 0

_mtx = libsync.Mutex("libs.netstats._mtx")  # connection registry only
_CONNS: list["ConnStats"] = []

# thread-local parking spot for the stamp of the message currently
# being dispatched to a reactor (the recv routine calls the reactor
# synchronously, so the slot is scoped to one dispatch)
_tls = threading.local()

# recent gossip-lag ring (seconds, cross-conn): preallocated, slot
# reservation via one GIL-atomic count — same posture as libs/health
_lag = array("d", [0.0] * _LAG_RING)
_lag_seq = itertools.count()
_lag_n = array("q", [0])


def enabled() -> bool:
    """The one check hot paths make before recording."""
    return _enabled


def enable() -> None:
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def acquire() -> None:
    """Reference-counted enable for node lifecycles (the devstats /
    health pattern): on for every running node unless
    ``COMETBFT_TPU_NET=0`` pins it off."""
    global _acquirers, _enabled
    if _env_mode() == "off":
        return
    _acquirers += 1
    _enabled = True


def release() -> None:
    global _acquirers, _enabled
    _acquirers = max(0, _acquirers - 1)
    if _acquirers == 0 and _env_mode() != "on":
        _enabled = False
        # drop the gossip-lag window with the last holder: a stopped
        # node's p99 must not leak into the next node's SLI (or a later
        # process-wide gossip_lag_s() read)
        reset()


def reset() -> None:
    """Drop the gossip-lag window (tests, bench bursts). Registered
    connections are untouched — they deregister with their owners."""
    global _lag_seq
    for i in range(len(_lag)):
        _lag[i] = 0.0
    _lag_seq = itertools.count()
    _lag_n[0] = 0


# ------------------------------------------------------ per-conn stats

# ConnStats column indices (per channel slot)
_C_MSGS_SENT = 0
_C_BYTES_SENT = 1
_C_MSGS_RECV = 2
_C_BYTES_RECV = 3
_C_QUEUE_FULL = 4  # MConnection.send timeout drops
_C_TRY_FULL = 5  # try_send immediate-full misses (normal backpressure)
_C_QUEUE_HWM = 6  # send-queue depth high-watermark
_C_LAST_SEND = 7  # time_ns of the last packet sent
_C_LAST_RECV = 8  # time_ns of the last message received
_N_COLS = 9


class ConnStats:
    """One connection's per-channel telemetry block.

    Columns are parallel ``array('q')`` vectors indexed by a
    channel→slot map frozen at construction.  Send columns are written
    only by the connection's send routine, recv columns only by its
    recv routine — single-writer, so the record path takes no lock.
    ``queue_full``/``try_full`` are written by arbitrary caller
    threads; a lost increment under that rare race costs one tally,
    never a corrupt structure (the libs/health notice posture).
    """

    __slots__ = (
        "peer_id", "outbound", "created_mono", "slots", "ch_ids",
        "_cols", "stamp_tx_seq", "stamp_rx_seq", "stamp_rx_lag_ns",
        "stamp_tx_wall", "skew",
        "_channels", "_send_monitor", "_recv_monitor",
    )

    def __init__(self, peer_id: str, ch_ids, mconn=None, outbound=False):
        self.peer_id = (peer_id or "")[:10]  # short id: bounded label
        self.outbound = outbound
        self.created_mono = time.monotonic()
        self.ch_ids = tuple(sorted(ch_ids))
        self.slots = {ch: i for i, ch in enumerate(self.ch_ids)}
        self._cols = [
            array("q", [0] * len(self.ch_ids)) for _ in range(_N_COLS)
        ]
        # provenance bookkeeping (send routine / recv routine writers)
        self.stamp_tx_seq = array("q", [0])
        self.stamp_rx_seq = array("q", [0])
        self.stamp_rx_lag_ns = array("q", [0])
        # clock-skew estimator state: wall ns of our last stamped send
        # (written by whichever thread stamps; one slot, GIL-atomic)
        # and the best NTP-style round-trip pair so far — see
        # _note_skew_pair for the estimate's semantics.
        # skew slots: [off_ns, bound_ns, rt_ns, pairs, lb_ns, lb_set]
        # where lb_ns is the always-sound lower bound on the offset
        # (max over inbound stamps of t2 - t3: a message cannot arrive
        # before it was sent, whatever the clocks say)
        self.stamp_tx_wall = array("q", [0])
        self.skew = array("q", [0, 0, 0, 0, 0, 0])
        self._channels = mconn.channels if mconn is not None else {}
        self._send_monitor = mconn.send_monitor if mconn is not None else None
        self._recv_monitor = mconn.recv_monitor if mconn is not None else None

    # -- record paths (single-writer per direction, lock-free) ----------

    def note_sent(self, slot: int, nbytes: int, eof: bool) -> None:
        cols = self._cols
        cols[_C_BYTES_SENT][slot] += nbytes
        cols[_C_LAST_SEND][slot] = time.time_ns()
        if eof:
            cols[_C_MSGS_SENT][slot] += 1

    def note_recv_msg(self, slot: int) -> None:
        self._cols[_C_MSGS_RECV][slot] += 1

    def note_recv_bytes(self, slot: int, nbytes: int) -> None:
        cols = self._cols
        cols[_C_BYTES_RECV][slot] += nbytes
        cols[_C_LAST_RECV][slot] = time.time_ns()

    def note_queue_full(self, slot: int) -> None:
        self._cols[_C_QUEUE_FULL][slot] += 1

    def note_try_full(self, slot: int) -> None:
        self._cols[_C_TRY_FULL][slot] += 1

    def note_depth(self, slot: int, depth: int) -> None:
        hwm = self._cols[_C_QUEUE_HWM]
        if depth > hwm[slot]:
            hwm[slot] = depth

    # -- read paths (scrape only) ---------------------------------------

    def queue_depth(self, ch_id: int) -> int:
        ch = self._channels.get(ch_id)
        if ch is None:
            return 0
        # racy len() read of a list: scrape-time best effort, no lock
        return len(ch._queue) + (1 if ch.sending is not None else 0)

    def total_bytes(self) -> int:
        return sum(self._cols[_C_BYTES_SENT]) + sum(
            self._cols[_C_BYTES_RECV]
        )

    def last_recv_ns(self) -> int:
        """time_ns of the most recent received message across channels
        (0 = nothing yet) — the suspicion scorer's staleness signal."""
        col = self._cols[_C_LAST_RECV]
        latest = 0
        for i in range(len(col)):
            if col[i] > latest:
                latest = col[i]
        return latest

    def last_lag_ns(self) -> int:
        """One-hop lag of the most recent stamped inbound message."""
        return self.stamp_rx_lag_ns[0]

    def queue_full_total(self, channels=None) -> int:
        col = self._cols[_C_QUEUE_FULL]
        if channels is None:
            return sum(col)
        # plain loop, no genexpr: the saturation watchdog calls this
        # from HealthMonitor._check, whose no-trip path is pinned
        # allocation-free — a generator frame caught in a GC cycle
        # would read as a retained allocation there
        total = 0
        for ch, i in self.slots.items():
            if ch in channels:
                total += col[i]
        return total

    def _note_skew_pair(self, peer_wall_ns: int, now_ns: int) -> None:
        """Fold one (our last stamped send t1, peer stamp t2, our
        receive t3) triple into the NTP-style skew estimate.

        offset = t2 - (t1 + t3)/2 with a ±rt/2 bound (rt = t3 - t1) —
        valid when the paired inbound was emitted AFTER our send, the
        NTP causality assumption.  Under continuous bidirectional
        gossip a CROSSED message (emitted before our send, arriving
        just after it) can produce an artificially tiny rt and an
        offset understated by up to a one-way delay, and a naive
        minimum-rt rule would lock exactly those pairs in.  Two
        defenses: (1) every inbound stamp yields the always-sound
        lower bound ``offset >= t2 - t3`` (a message cannot arrive
        before it was sent — no causality assumption at all), tracked
        as the running max; (2) a candidate pair whose offset+bound
        falls BELOW that sound bound is provably crossed and is
        rejected, and a stored pair a later sound bound invalidates is
        evicted so the next consistent pair replaces it.  Among the
        consistent pairs, minimum rt gives the tightest bound.  Runs
        on the recv routine; ``stamp_tx_wall`` is written by the
        sender side (one-slot cross-thread read, GIL-atomic, the
        ConnStats lost-increment posture)."""
        sk = self.skew
        # sound lower bound from EVERY inbound stamp (t2 - t3)
        lb = peer_wall_ns - now_ns
        if not sk[5] or lb > sk[4]:
            sk[4] = lb
            sk[5] = 1
            # a tighter sound bound can expose the stored pair as
            # crossed after the fact: evict it
            if sk[2] and sk[0] + sk[1] < sk[4]:
                sk[0] = sk[1] = sk[2] = 0
        t1 = self.stamp_tx_wall[0]
        if t1 == 0:
            return
        rt = now_ns - t1
        if rt < 0:
            return  # racing writer moved t1 past our read; skip
        sk[3] += 1
        off = peer_wall_ns - (t1 + now_ns) // 2
        bound = max(1, rt // 2)
        if off + bound < sk[4]:
            return  # provably crossed pairing: offset range excludes
            # the sound lower bound
        if sk[2] == 0 or rt < sk[2]:
            sk[2] = rt
            sk[0] = off
            sk[1] = bound

    def skew_row(self) -> dict | None:
        """The peer's clock-skew estimate, or None before any
        round-trip pair completed."""
        sk = self.skew
        if sk[3] == 0 or sk[2] == 0:
            return None
        return {
            "offset_s": round(sk[0] / 1e9, 9),
            "bound_s": round(sk[1] / 1e9, 9),
            "rt_s": round(sk[2] / 1e9, 9),
            "pairs": sk[3],
            # the causality-free floor: offset >= this, whatever the
            # message interleaving was
            "floor_s": round(sk[4] / 1e9, 9) if sk[5] else None,
        }

    def rates(self) -> tuple[float, float]:
        sm, rm = self._send_monitor, self._recv_monitor
        return (
            sm.rate() if sm is not None else 0.0,
            rm.rate() if rm is not None else 0.0,
        )

    def channel_row(self, ch_id: int) -> dict:
        i = self.slots[ch_id]
        cols = self._cols
        now = time.time_ns()

        def age(ns: int):
            return round((now - ns) / 1e9, 3) if ns else None

        ch = self._channels.get(ch_id)
        return {
            "chID": f"{ch_id:#04x}",
            "msgs_sent": cols[_C_MSGS_SENT][i],
            "bytes_sent": cols[_C_BYTES_SENT][i],
            "msgs_recv": cols[_C_MSGS_RECV][i],
            "bytes_recv": cols[_C_BYTES_RECV][i],
            "queue_depth": self.queue_depth(ch_id),
            "queue_capacity": (
                ch.desc.send_queue_capacity if ch is not None else None
            ),
            "queue_highwater": cols[_C_QUEUE_HWM][i],
            "send_queue_full": cols[_C_QUEUE_FULL][i],
            "try_send_full": cols[_C_TRY_FULL][i],
            "last_send_age_s": age(cols[_C_LAST_SEND][i]),
            "last_recv_age_s": age(cols[_C_LAST_RECV][i]),
        }

    def row(self) -> dict:
        send_rate, recv_rate = self.rates()
        return {
            "peer": self.peer_id or "?",
            "outbound": self.outbound,
            "age_s": round(time.monotonic() - self.created_mono, 3),
            "send_rate_bps": round(send_rate, 1),
            "recv_rate_bps": round(recv_rate, 1),
            "stamp": {
                "tx_seq": self.stamp_tx_seq[0],
                "rx_seq": self.stamp_rx_seq[0],
                "rx_lag_last_s": round(self.stamp_rx_lag_ns[0] / 1e9, 6),
                "clock_skew": self.skew_row(),
            },
            "channels": [
                self.channel_row(ch) for ch in self.ch_ids
            ],
        }


def register(stats: ConnStats) -> None:
    """Add a connection's stats block (connection start — not hot)."""
    with _mtx:
        _CONNS.append(stats)


def deregister(stats: ConnStats) -> None:
    with _mtx:
        for i in range(len(_CONNS) - 1, -1, -1):
            if _CONNS[i] is stats:
                del _CONNS[i]
                return


def connections() -> tuple:
    """Lock-free snapshot of the registered connections (scrape paths
    must never touch ``_mtx`` — same posture as health.active_monitor)."""
    return tuple(_CONNS)


def skew_table() -> dict:
    """Per-peer clock-skew estimates (tightest-bound connection wins
    when a peer has several) — exported with the flight ring so the
    cross-node timeline merge can tag live cross-node edges with a
    measured bound instead of a warning."""
    out: dict[str, dict] = {}
    for c in connections():
        row = c.skew_row()
        if row is None or not c.peer_id:
            continue
        prev = out.get(c.peer_id)
        if prev is None or row["bound_s"] < prev["bound_s"]:
            out[c.peer_id] = row
    return out


def consensus_queue_full_total() -> int:
    """Total MConnection.send timeout drops on the consensus channels —
    the saturated-send-queue watchdog's signal (libs/health)."""
    total = 0
    for c in connections():
        total += c.queue_full_total(CONSENSUS_CHANNELS)
    return total


# -------------------------------------------------- provenance stamps


def make_stamp(origin8: bytes, seq: int, wall_ns: int | None = None) -> bytes:
    """Encode one provenance stamp (origin prefix must be 8 bytes)."""
    return struct.pack(
        _STAMP_FMT,
        STAMP_MAGIC,
        STAMP_VERSION,
        origin8,
        seq & 0xFFFFFFFF,
        wall_ns if wall_ns is not None else time.time_ns(),
    )


def split_stamp(msg: bytes) -> tuple[tuple | None, bytes]:
    """``(stamp, payload)`` — stamp is ``(origin_hex, seq, wall_ns)``
    or None when the message carries no stamp (wire-compat path)."""
    if len(msg) < STAMP_LEN or not msg.startswith(STAMP_MAGIC):
        return None, msg
    magic, ver, origin, seq, wall = struct.unpack_from(_STAMP_FMT, msg)
    if ver != STAMP_VERSION:
        # a future stamp version we cannot decode: drop the stamp,
        # keep the payload (forward compat)
        return None, msg[STAMP_LEN:]
    return (origin.hex(), seq, wall), msg[STAMP_LEN:]


def origin_prefix(node_id: str) -> bytes:
    """8-byte origin prefix from a (hex) node id; tolerant of exotic
    ids so a misconfigured moniker can't crash the wire path."""
    try:
        raw = bytes.fromhex(node_id[:16])
    except ValueError:
        raw = node_id.encode()[:8]
    return raw.ljust(8, b"\0")


def set_current_stamp(stamp, stats: ConnStats | None = None) -> None:
    """Park ``stamp`` for the reactor dispatch running on this thread
    (the recv routine calls reactors synchronously)."""
    _tls.stamp = stamp
    if stamp is not None and stats is not None:
        stats.stamp_rx_seq[0] = stamp[1]
        now = time.time_ns()
        lag = now - stamp[2]
        stats.stamp_rx_lag_ns[0] = lag if lag > 0 else 0
        # every inbound stamp that follows one of our stamped sends is
        # a round-trip pair for the per-peer clock-skew estimator
        stats._note_skew_pair(stamp[2], now)


def current_stamp():
    """The provenance stamp of the message being dispatched on this
    thread, or None (unstamped peer / non-p2p path)."""
    return getattr(_tls, "stamp", None)


def clear_current_stamp() -> None:
    # store only when something is parked: a no-op clear must not even
    # materialize the thread-local mapping (the disabled wire path
    # calls this and is pinned allocation-free by the tracemalloc guard)
    if getattr(_tls, "stamp", None) is not None:
        _tls.stamp = None


def observe_propagation(phase: str, height: int = 0) -> None:
    """Attribute the current message's one-hop propagation lag to a
    consensus ``phase``: Prometheus histogram + EV_GOSSIP flight event
    + the gossip-lag window the health SLI reads.  One flag check and
    out when the layer is off or the message carried no stamp."""
    if not _enabled:
        return
    stamp = getattr(_tls, "stamp", None)
    if stamp is None:
        return
    lag_ns = time.time_ns() - stamp[2]
    if lag_ns < 0:
        lag_ns = 0  # cross-host clock skew: clamp, don't go negative
    lag_s = lag_ns / 1e9
    libmetrics.node_metrics().p2p_propagation.labels(phase).observe(lag_s)
    i = next(_lag_seq) % _LAG_RING
    _lag[i] = lag_s
    if _lag_n[0] < _LAG_RING:
        _lag_n[0] = min(_LAG_RING, _lag_n[0] + 1)
    from . import health as libhealth

    libhealth.record(
        libhealth.EV_GOSSIP,
        height,
        a=PHASE_CODES.get(phase, 0),
        b=lag_ns,
    )
    if libtrace.enabled():
        libtrace.event(
            "p2p.gossip",
            phase=phase,
            height=height,
            origin=stamp[0],
            seq=stamp[1],
            lag_ns=lag_ns,
        )


def propagation_p99(metrics=None) -> dict:
    """Per-phase p99 of the one-hop propagation histogram, through the
    shared promql-style estimator (libmetrics.quantile_from_buckets —
    the same math health.sample and the budget plane use).  Scrape-time
    only; phases with no observations yet are omitted."""
    m = metrics if metrics is not None else libmetrics.node_metrics()
    fam = m.p2p_propagation
    with fam._mtx:
        children = list(fam._children.items())
    out: dict[str, float] = {}
    for key, child in children:
        counts = list(child._counts)
        if not any(counts):
            continue
        out[key[0]] = round(
            libmetrics.quantile_from_buckets(child.buckets, counts, 0.99),
            6,
        )
    return out


def gossip_lag_s(q: float = 0.99) -> float:
    """Quantile of the recent one-hop gossip-lag window (seconds);
    0.0 when nothing stamped arrived yet.  Scrape-time only."""
    n = min(_lag_n[0], _LAG_RING)
    if n <= 0:
        return 0.0
    vals = sorted(_lag[i] for i in range(n))
    return vals[min(n - 1, int(q * n))]


# ------------------------------------------------ scrape-time sampling


def sample(metrics=None) -> dict:
    """Pull-time collector: aggregate the registered connections into
    the per-channel queue gauges and the capped top-K ``peer`` rate
    gauges of ``metrics`` (or the process-wide top registry).  Stale
    peer series are removed so the ``peer`` label stays bounded by
    K + 1 (``other``) regardless of churn."""
    m = metrics if metrics is not None else libmetrics.node_metrics()
    conns = connections()
    depth: dict[int, int] = {}
    hwm: dict[int, int] = {}
    for c in conns:
        for ch, i in c.slots.items():
            depth[ch] = depth.get(ch, 0) + c.queue_depth(ch)
            hwm[ch] = max(hwm.get(ch, 0), c._cols[_C_QUEUE_HWM][i])
    live_ch = {f"{ch:#04x}" for ch in depth}
    for ch in depth:
        lbl = f"{ch:#04x}"
        m.p2p_send_queue_depth.labels(lbl).set(depth[ch])
        m.p2p_send_queue_hwm.labels(lbl).set(hwm[ch])
    # channels no live connection carries: drop the series, or a
    # backlog alert built on the depth gauge never clears after the
    # saturated peer disconnects
    for gauge in (m.p2p_send_queue_depth, m.p2p_send_queue_hwm):
        for key in list(gauge._children):
            if key[0] not in live_ch:
                gauge.remove(*key)
    # top-K peers by total traffic; the rest fold into "other"
    k = top_k()
    ranked = sorted(conns, key=lambda c: c.total_bytes(), reverse=True)
    live: set[str] = set()
    other_send = other_recv = 0.0
    for idx, c in enumerate(ranked):
        send_rate, recv_rate = c.rates()
        if idx < k and c.peer_id:
            live.add(c.peer_id)
            m.p2p_peer_rate.labels(c.peer_id, "send").set(send_rate)
            m.p2p_peer_rate.labels(c.peer_id, "recv").set(recv_rate)
            # measured clock-skew bound to the stamped top-K peers
            # (netstamp round-trip pairs; no pair yet = no series)
            srow = c.skew_row()
            if srow is not None:
                m.p2p_peer_clock_skew.labels(c.peer_id).set(
                    srow["offset_s"]
                )
                m.p2p_peer_clock_skew_bound.labels(c.peer_id).set(
                    srow["bound_s"]
                )
        else:
            other_send += send_rate
            other_recv += recv_rate
    m.p2p_peer_rate.labels("other", "send").set(other_send)
    m.p2p_peer_rate.labels("other", "recv").set(other_recv)
    # drop series for departed / demoted peers: bounded cardinality
    for key in list(m.p2p_peer_rate._children):
        if key[0] != "other" and key[0] not in live:
            m.p2p_peer_rate.remove(*key)
    for gauge in (m.p2p_peer_clock_skew, m.p2p_peer_clock_skew_bound):
        for key in list(gauge._children):
            if key[0] not in live:
                gauge.remove(*key)
    # (health_gossip_lag_seconds is set by libhealth.sample — the SLI
    # engine owns it; setting it here too would sort the lag window
    # twice per scrape)
    return {
        "connections": len(conns),
        "queue_depth": {f"{ch:#04x}": d for ch, d in depth.items()},
        "queue_highwater": {f"{ch:#04x}": h for ch, h in hwm.items()},
    }


def snapshot() -> dict:
    """The ``/debug/net`` body and the ``net.json`` bundle artifact:
    per-peer table (channels, queue depths, rates, last-msg ages,
    stamp state) + the process-wide gossip-lag window."""
    conns = connections()
    return {
        "enabled": _enabled,
        "stamping": stamping_wanted(),
        "top_k": top_k(),
        "connections": len(conns),
        "gossip_lag_p50_s": round(gossip_lag_s(0.50), 6),
        "gossip_lag_p99_s": round(gossip_lag_s(0.99), 6),
        "propagation_p99_s": propagation_p99(),
        "consensus_send_queue_full": consensus_queue_full_total(),
        "clock_skew": skew_table(),
        "peers": [c.row() for c in conns],
    }


def debug_net_json() -> str:
    return json.dumps(snapshot(), default=str)
