"""Always-on consensus flight recorder, SLO/health engine, watchdogs.

The reference CometBFT treats liveness as *observable state* — consensus
metrics per height/round/step — but PR 3's tracer and PR 4's devstats
are opt-in and passive: when a node stalls, wedges its verify executor,
or enters a recompile storm, nothing notices until a human scrapes
``/debug/trace``.  This layer closes that loop with three pieces:

* **Flight recorder** (:class:`FlightRecorder`): a bounded ring of
  structured events — height/round/step transitions, proposal/vote
  admission, per-height commit latency, coalescer breaker trips, XLA
  recompiles, WAL fsyncs, watchdog trips — recorded even when
  ``COMETBFT_TPU_TRACE`` is off.  The black box: when something goes
  wrong, the last few thousand consensus events are already captured.

* **SLO/health engine** (:func:`sample`, :func:`slis`): derives SLIs
  from the ring and the existing metrics families (per-height commit
  latency p50/p99, rounds-per-height, verify-window wait p99, breaker
  state, WAL fsync lag, step-progress age) into ``health_*`` Prometheus
  gauges plus one composite ``health_score`` in [0, 1].

* **Watchdogs** (:class:`HealthMonitor`): a consensus **stall**
  detector (no step progress within a multiple of the commit timeout),
  a **wedged-coalescer** detector (hooked to crypto/coalesce's
  half-open breaker via :func:`note_breaker_trip`), and a
  **recompile-storm** alarm (hooked to the ``xla_recompile_total``
  ledger in libs/devstats).  Any trip raises
  ``health_watchdog_trips_total{watchdog}`` and emits a rate-limited
  **black-box bundle** (flight-recorder ring + devstats snapshot +
  lock-order held stacks + thread dump + trace tail) into the
  debug-dump directory, so forensic state is captured at the moment of
  failure, not minutes later.

Design constraints (stricter than libs/trace — this layer is ON by
default for every node):

* **Allocation-free steady state.**  The record path writes scalars
  into preallocated ``array.array`` columns; slot reservation is one
  GIL-atomic ``itertools.count`` step.  Nothing is retained per record
  — pinned by the tracemalloc guard in tests/test_observability.py,
  which also covers the watchdog's no-trip check.  (Temporaries are
  fine; *retained* allocations are not.)

* **Lock-free record and scrape paths.**  ``record()`` touches no lock
  (concurrent writers reserve distinct slots; a reader may observe a
  torn in-progress row, which the decoder skips — same posture as PR
  4's lock-free compile-record deque).  The one lock here
  (``libs.health._mtx``) serializes only the bundle rate limit and the
  monitor registry, is never held across file I/O or another lock, and
  is asserted edge-free in tests/test_lint_graph.py like
  ``libs.trace._mtx`` / ``libs.devstats._mtx``.

Knobs (registered in config.ENV_KNOBS, enforced by cometlint CLNT007):
``COMETBFT_TPU_HEALTH`` (auto: on while a node runs; 1 force; 0 off),
``COMETBFT_TPU_HEALTH_RING`` (ring capacity),
``COMETBFT_TPU_HEALTH_STALL_MULT`` (stall window as a multiple of the
commit+propose timeout), ``COMETBFT_TPU_HEALTH_BUNDLE_DIR`` (black-box
dump directory override), ``COMETBFT_TPU_HEALTH_BUNDLE_RL_S`` (minimum
seconds between bundles).
"""

from __future__ import annotations

import itertools
import json
import os
import shutil
import threading
import time
from array import array

from . import devledger as libdevledger
from . import lockprof as liblockprof
from . import metrics as libmetrics
from . import netstats as libnetstats
from . import profile as libprofile
from . import sync as libsync
from . import trace as libtrace
from .service import BaseService

_ENV_HEALTH = "COMETBFT_TPU_HEALTH"
_ENV_RING = "COMETBFT_TPU_HEALTH_RING"
_ENV_STALL_MULT = "COMETBFT_TPU_HEALTH_STALL_MULT"
_ENV_BUNDLE_DIR = "COMETBFT_TPU_HEALTH_BUNDLE_DIR"
_ENV_BUNDLE_RL = "COMETBFT_TPU_HEALTH_BUNDLE_RL_S"
_ENV_POSTMORTEM = "COMETBFT_TPU_POSTMORTEM"

DEFAULT_RING_SIZE = 4096
# Stall window = multiplier x (timeout_commit + timeout_propose(0)):
# one full empty-block cycle is the longest a healthy node legitimately
# goes between step transitions, so 25 cycles of silence is a wedge,
# not a slow round (production defaults: ~100 s).
DEFAULT_STALL_MULT = 25.0
DEFAULT_BUNDLE_RL_S = 60.0
# Retention cap: newest bundle directories kept per bundle dir. The
# rate limit floors the write INTERVAL; this bounds the TOTAL — a node
# stalled over a weekend must not fill its data volume with thousands
# of ring dumps.
DEFAULT_BUNDLE_KEEP = 16
# Recompile storm: this many steady-state recompiles inside one rolling
# window is a shape-bucket leak / dtype drift actively destroying
# throughput (each recompile costs seconds of XLA time on the hot path).
STORM_RECOMPILES = 3
STORM_WINDOW_S = 60.0

# -- ring event codes (decoded by _CODE_NAMES / dump()) -----------------
EV_STEP = 1  # height, round, a=RoundStep int
EV_PROPOSAL = 2  # height, round, a=1 accepted / 0 rejected
EV_VOTE = 3  # height, round, a=vote type, b=validator index
EV_COMMIT = 4  # height, round=commit round, a=height latency ns
EV_BREAKER = 5  # a=1 trip / 0 re-arm (crypto/coalesce half-open breaker)
EV_RECOMPILE = 6  # a=shape bucket (libs/devstats steady-state recompile)
EV_FSYNC = 7  # a=WAL fsync ns
EV_WATCHDOG = 8  # a=watchdog bit (see _WATCHDOGS)
EV_GOSSIP = 9  # a=propagation phase code (netstats.PHASE_NAMES), b=lag ns
EV_FAULT = 10  # simnet fault plane: h=src node, r=dst node, a=kind, b=detail
EV_HASH = 11  # hash-plane window flush: a=lanes, b=1 device / 0 host
# plane.budget: FSM-blocking device-plane time per window resolution —
# r=plane (libs/devledger: 0 verify / 1 hash), a=consensus-caller
# queue-wait ns, b=consensus-caller pro-rata execute ns. The per-height
# latency budget (budget_from_events) window-assigns these rows to the
# height they delayed, exactly like EV_FSYNC.
EV_BUDGET = 12
# tx.stage: one sampled transaction crossing a lifecycle stage
# (libs/txtrace): r=stage code (TX_STAGES), a=signed 64-bit key
# fingerprint (first 8 key bytes; decoded as the 16-hex-char ``key``
# prefix), b=stage payload — mempool depth at admit, one-hop lag ns at
# gossip_recv, ns-since-admit at gossip_send/commit. Stamped from the
# ring clock, so virtual-domain (simnet) rows stay merge-consistent.
EV_TX = 13
# sync.lock: a lock wait or hold crossed the lockprof slow threshold
# (libs/lockprof, COMETBFT_TPU_LOCKPROF_SLOW_MS) — r=lockorder.json
# registry slot (decoded to the ``lock`` name), a=duration ns,
# b=site_idx*2+kind (kind 0 wait / 1 hold; site_idx indexes lockprof's
# interned holder-acquire-site table, decoded as ``site``). Bundles
# name the blocker, not just the victim.
EV_LOCK = 14
# prof.window: one sampling-profiler flush window for one subsystem
# (libs/profile, ~1/s per subsystem with samples) — r=subsystem index
# (libs/profile.SUBSYSTEMS, decoded as ``subsystem``), a=estimated
# on-CPU ns (on-CPU samples x the sampling period), b=total samples
# (on-CPU + blocked). critical_path_from_events window-assigns these to
# name commits gated by GIL-bound Python (``cpu:<subsystem>``), and the
# cpu_saturated postmortem detector scores them.
EV_PROF = 15
# spec.exec: one speculative block execution resolved by the commit
# pipeline (consensus/pipeline) — a=outcome code (_SPEC_OUTCOMES:
# 1 hit / 2 miss / 3 abort), b=speculative FinalizeBlock execute ns
# (0 for miss/abort rows — there is nothing to credit). Recorded at
# consumption/discard time on the FSM thread, so the row sits inside
# the commit window budget_from_events assigns it to.
EV_SPEC = 16

_N_CODES = 17  # size of the per-code last-seen vector

# EV_SPEC outcome vocabulary (recorded by consensus/pipeline)
SPEC_HIT = 1  # precommitted block matched the memoized speculation
SPEC_MISS = 2  # nothing memoized for the committed block — serial path
SPEC_ABORT = 3  # speculation discarded (superseded / failed) unconsumed

_SPEC_OUTCOMES = {SPEC_HIT: "hit", SPEC_MISS: "miss", SPEC_ABORT: "abort"}

# EV_TX stage vocabulary (the decode side of libs/txtrace's stage
# codes — the decoder lives here with the rest of the ring vocabulary,
# txtrace aliases this map so the two cannot diverge)
TX_STAGES = {
    1: "admit",
    2: "gossip_send",
    3: "gossip_recv",
    4: "proposal",
    5: "commit",
}

# EV_FAULT kinds (recorded by cometbft_tpu/simnet): the black-box ring
# explains WHICH fault was live when a scenario failed — a partition
# forming, a link dropping a message class, a node crashing mid-height.
FAULT_PARTITION = 1  # partition formed (detail = group count)
FAULT_HEAL = 2  # partition healed
FAULT_KILL = 3  # node killed (churn)
FAULT_RESTART = 4  # node restarted (churn)
FAULT_DROP = 5  # one message eaten by link faults (detail = channel)
FAULT_LINK = 6  # link fault parameters changed
FAULT_CRASH = 7  # armed COMETBFT_TPU_FAIL crash point fired in-process
# gray-failure vocabulary (PR 13): slow-but-alive and asymmetric faults
FAULT_ONEWAY = 8  # one DIRECTION severed (h=src, r=dst; detail 1=sever 0=restore)
FAULT_SLOW_DISK = 9  # node's disk slowed (h=node; detail = latency ms, 0=cleared)
FAULT_STORM = 10  # sustained mempool storm (detail = tx/s rate, 0=stopped)
FAULT_PEER_EVICT = 11  # a node-side DEFENSE evicted a peer (suspicion /
# statesync chunk-peer rotation); h=node where known, detail=reason code
# FAULT_PEER_EVICT detail namespace (WHICH defense acted): 1-4 are the
# p2p/suspicion reason enum (queue_full/stale/lag/mixed); 5 is a
# statesync chunk-fetch rotation abandoning a timing-out chunk peer
PEER_EVICT_STATESYNC_ROTATE = 5

_FAULT_NAMES = {
    FAULT_PARTITION: "partition",
    FAULT_HEAL: "heal",
    FAULT_KILL: "kill",
    FAULT_RESTART: "restart",
    FAULT_DROP: "drop",
    FAULT_LINK: "link_change",
    FAULT_CRASH: "crash_point",
    FAULT_ONEWAY: "oneway_sever",
    FAULT_SLOW_DISK: "slow_disk",
    FAULT_STORM: "mempool_storm",
    FAULT_PEER_EVICT: "peer_evict",
}


def fault_kind_codes() -> dict[str, int]:
    """Every ``FAULT_*`` kind this module defines, by constant name —
    the registry the EV_FAULT decode-completeness tier-1 test walks, so
    a new fault kind cannot ship without a ``fault_name`` decode entry
    and a docs row."""
    return {
        name: value
        for name, value in globals().items()
        if name.startswith("FAULT_") and isinstance(value, int)
    }

_CODE_NAMES = {
    EV_STEP: "consensus.step",
    EV_PROPOSAL: "consensus.proposal",
    EV_VOTE: "consensus.vote",
    EV_COMMIT: "consensus.commit",
    EV_BREAKER: "coalesce.breaker",
    EV_RECOMPILE: "xla.recompile",
    EV_FSYNC: "wal.fsync",
    EV_WATCHDOG: "health.watchdog",
    EV_GOSSIP: "p2p.gossip",
    EV_FAULT: "simnet.fault",
    EV_HASH: "hash.flush",
    EV_BUDGET: "plane.budget",
    EV_TX: "tx.stage",
    EV_LOCK: "sync.lock",
    EV_PROF: "prof.window",
    EV_SPEC: "spec.exec",
}
# decode the free-form a/b columns per code
_CODE_FIELDS = {
    EV_STEP: ("step", None),
    EV_PROPOSAL: ("accepted", None),
    EV_VOTE: ("type", "index"),
    EV_COMMIT: ("dur_ns", "txs"),
    EV_BREAKER: ("open", None),
    EV_RECOMPILE: ("bucket", None),
    # overlapped=1 marks an fsync that ran OFF the FSM critical section
    # (the pipelined commit-writer, consensus/pipeline): the budget
    # plane excludes it from the serial wal_fsync stage and reports it
    # in the per-height ``overlapped`` credit instead
    EV_FSYNC: ("dur_ns", "overlapped"),
    EV_WATCHDOG: ("watchdog", None),
    EV_GOSSIP: ("phase", "lag_ns"),
    EV_FAULT: ("kind", "detail"),
    EV_HASH: ("lanes", "device"),
    EV_BUDGET: ("wait_ns", "exec_ns"),
    EV_TX: ("key_fp", "val"),
    EV_LOCK: ("dur_ns", "ref"),
    EV_PROF: ("oncpu_ns", "samples"),
    EV_SPEC: ("outcome", "dur_ns"),
}

# codes whose payload is a wall-clock-measured duration: meaningless in
# a virtual-time (simnet) ring, so the cross-node timeline merge drops
# them from virtual-domain sources (cometbft_tpu/postmortem) — EV_PROF
# rides along because its on-CPU estimate is sampled in wall time
WALL_DURATION_CODES = frozenset(
    {EV_FSYNC, EV_BUDGET, EV_LOCK, EV_PROF, EV_SPEC}
)


def ring_event_codes() -> dict[str, int]:
    """Every ``EV_*`` code this module defines, by constant name — the
    registry the decoder-completeness tier-1 test walks, so a new event
    code cannot ship without a decode path and a docs entry."""
    return {
        name: value
        for name, value in globals().items()
        if name.startswith("EV_") and isinstance(value, int)
    }

_STEP_NAMES = {
    1: "NewHeight", 2: "NewRound", 3: "Propose", 4: "Prevote",
    5: "PrevoteWait", 6: "Precommit", 7: "PrecommitWait", 8: "Commit",
}

# watchdog name -> trip bitmask returned by HealthMonitor._check
_WATCHDOGS = (
    ("consensus_stall", 1),
    ("verify_breaker", 2),
    ("recompile_storm", 4),
    ("send_queue_saturated", 8),
    ("slow_disk", 16),
    ("consensus_starved", 32),
    ("tx_starved", 64),
    ("lock_contended", 128),
)
# tx_starved: an ADMITTED tx is older than COMETBFT_TPU_TX_STARVE_COMMITS
# commit intervals WHILE heights keep committing — inclusion is broken
# though the chain is live (a dead chain is the stall watchdog's case,
# and an idle mempool can never starve: the age signal is the oldest
# admitted-uncommitted tx across libs/txtrace's registered mempools).
# consensus_starved: consensus-caller verify queue-wait p99 (windowed,
# from the device_queue_wait_seconds buckets) above the threshold WHILE
# other callers dominate the window's lane share — a light-service /
# mempool storm taxing consensus through the shared device planes. The
# lane-share test keeps an overloaded-but-fairly-shared plane from
# paging as starvation.
STARVE_LANE_SHARE = 0.5  # others' share that counts as "dominating"
STARVE_MIN_LANES = 64  # ledger lanes per check window before judging
# send_queue_saturated: this many CONSECUTIVE checks each observing
# fresh MConnection.send drops on a consensus channel = sustained
# backpressure (a one-off burst drop re-baselines without a trip)
SATURATION_STREAK = 3
# lock_contended: an ENGINE mutex's windowed p99 wait (libs/lockprof
# delta-histogram) at or above the slow threshold in this many
# CONSECUTIVE checks = a serialized resource actively gating the
# engine, not one unlucky acquire
LOCK_CONTENDED_STREAK = 2
_WATCHDOG_NAMES = {bit: name for name, bit in _WATCHDOGS}

_ON_VALUES = ("1", "on", "true", "yes")
_OFF_VALUES = ("0", "off", "false", "no")


def _env_mode() -> str:
    v = os.environ.get(_ENV_HEALTH, "").lower()
    if v in _ON_VALUES:
        return "on"
    if v in _OFF_VALUES:
        return "off"
    return "auto"


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, ""))
    except ValueError:
        return default


def _ring_size_from_env() -> int:
    try:
        n = int(os.environ.get(_ENV_RING, ""))
    except ValueError:
        n = DEFAULT_RING_SIZE
    return max(64, n)


# ----------------------------------------------- ring clock + origins

# Injectable ring timestamp source: the simnet plane swaps in its
# virtual clock (SimClock.time_ns) for the run's lifetime, so every
# ring row of an N-node simulation carries EXACT shared virtual time —
# the property that makes the cross-node timeline merge lossless there.
# Live nodes keep the wall clock and the merge tags cross-node edges
# with a netstamp-derived skew bound instead.
_now_ns = time.time_ns
_clock_domain = "wall"  # "wall" | "virtual" — exported with the ring


def now_ns() -> int:
    """The ring clock (wall on live nodes, the shared virtual clock
    under simnet) — sibling planes (libs/txtrace, the mempool admit
    stamps) read it so their durations stay domain-consistent with the
    ring rows they sit next to."""
    return _now_ns()


def set_clock(fn, domain: str = "wall"):
    """Swap the ring timestamp source; returns the previous
    ``(fn, domain)`` pair so the caller can restore it."""
    global _now_ns, _clock_domain
    prev = (_now_ns, _clock_domain)
    _now_ns = fn
    _clock_domain = domain
    return prev


def clock_domain() -> str:
    return _clock_domain


# Origin attribution: which NODE a ring row belongs to.  One process
# usually hosts one node (origin = its node-id prefix, registered at
# boot), but the simnet plane and the in-process test nets host N — the
# recording THREAD declares its origin (simnet sets it per scheduler
# event; live nodes set it on the cs-receive and mconn-recv threads
# they own), and the decoder emits it as the row's ``node`` field.  The
# record-path read is one thread-local getattr: allocation- and
# lock-free, covered by the tracemalloc guard.
_ORIGIN_NAMES: list[str] = ["local"]  # id 0 = unattributed/this-process
_ORIGIN_IDS: dict[str, int] = {"local": 0}
_origin_tls = threading.local()


def register_origin(name: str) -> int:
    """Intern an origin name -> id (dedupes, so re-registration across
    node restarts and repeated simnet runs is stable).  Registration is
    a setup-path operation (node boot, peer admit) under ``_mtx``."""
    with _mtx:
        oid = _ORIGIN_IDS.get(name)
        if oid is None:
            oid = len(_ORIGIN_NAMES)
            _ORIGIN_NAMES.append(name)
            _ORIGIN_IDS[name] = oid
        return oid


def origin_name(oid: int) -> str:
    names = _ORIGIN_NAMES
    return names[oid] if 0 <= oid < len(names) else "?"


def set_thread_origin(oid: int) -> None:
    """Declare the node whose events this thread records (0 clears)."""
    _origin_tls.oid = oid


def current_thread_origin() -> int:
    return getattr(_origin_tls, "oid", 0)


# ------------------------------------------------------- flight recorder


class FlightRecorder:
    """Bounded lock-free ring of fixed-width consensus events.

    Storage is six parallel ``array.array('q')`` columns plus a
    per-code last-seen ``array('d')`` vector, all preallocated: the
    record path performs only C-level scalar stores, so steady-state
    recording retains zero allocations.  Concurrent writers reserve
    slots through one GIL-atomic ``itertools.count``; a reader racing a
    writer may see one torn row (skipped by the decoder), never a
    corrupt structure.
    """

    __slots__ = (
        "capacity", "_ts", "_code", "_h", "_r", "_a", "_b", "_o",
        "_seq", "_written", "_last", "_commits",
    )

    def __init__(self, capacity: int = DEFAULT_RING_SIZE):
        self.capacity = max(64, int(capacity))
        zeros = [0] * self.capacity
        self._ts = array("q", zeros)
        self._code = array("q", zeros)
        self._h = array("q", zeros)
        self._r = array("q", zeros)
        self._a = array("q", zeros)
        self._b = array("q", zeros)
        self._o = array("q", zeros)  # recording thread's origin id
        self._seq = itertools.count()
        self._written = array("q", [0])
        # monotonic last-seen per event code (watchdog math)
        self._last = array("d", [0.0] * _N_CODES)
        # commit-row tally: the budget memo's invalidation key — the
        # per-height decomposition only changes when a height closes
        self._commits = array("q", [0])

    def record(
        self, code: int, height: int = 0, round_: int = 0,
        a: int = 0, b: int = 0,
    ) -> None:
        seq = next(self._seq)  # GIL-atomic slot reservation
        i = seq % self.capacity
        self._code[i] = 0  # mark in-progress: readers skip torn rows
        self._ts[i] = _now_ns()
        self._h[i] = height
        self._r[i] = round_
        self._a[i] = a
        self._b[i] = b
        self._o[i] = getattr(_origin_tls, "oid", 0)
        self._code[i] = code  # publish last
        if code == EV_STEP:
            # the one last-seen the stall watchdog consumes; the other
            # codes skip the extra clock read on the hot path
            self._last[EV_STEP] = time.monotonic()
        elif code == EV_COMMIT:
            self._commits[0] = self._commits[0] + 1
        if seq >= self._written[0]:
            self._written[0] = seq + 1

    def last_seen(self, code: int) -> float:
        """Monotonic time the code was last recorded (0.0 = never;
        maintained for EV_STEP only — the stall watchdog's signal)."""
        return self._last[code]

    def _iter_slots(self):
        """(slot index) oldest-first over the currently-filled window."""
        w = self._written[0]
        n = min(w, self.capacity)
        for k in range(w - n, w):
            yield k % self.capacity

    def dump(self) -> list[dict]:
        """Decoded ring contents, oldest first (lock-free snapshot; a
        row being written concurrently is skipped)."""
        out = []
        for i in self._iter_slots():
            code = self._code[i]
            name = _CODE_NAMES.get(code)
            if name is None:
                continue  # empty or torn slot
            rec = {
                "ts": self._ts[i],
                "event": name,
                "height": self._h[i],
                "round": self._r[i],
            }
            # .get with a null default, not [code]: a code registered in
            # _CODE_NAMES but missing its field entry must decode (as
            # raw a/b-less row), never KeyError a scrape/bundle path —
            # the completeness test still flags the gap
            fa, fb = _CODE_FIELDS.get(code, (None, None))
            if fa is not None:
                rec[fa] = self._a[i]
            if fb is not None:
                rec[fb] = self._b[i]
            if code == EV_STEP:
                rec["step_name"] = _STEP_NAMES.get(self._a[i], "?")
            elif code == EV_WATCHDOG:
                rec["watchdog_name"] = _WATCHDOG_NAMES.get(self._a[i], "?")
            elif code == EV_GOSSIP:
                rec["phase_name"] = libnetstats.PHASE_NAMES.get(
                    self._a[i], "?"
                )
                if self._r[i] > 0:
                    # simnet delivery rows park the SENDING node's
                    # origin id in the round column (live rows leave 0)
                    rec["src"] = origin_name(self._r[i])
            elif code == EV_FAULT:
                rec["fault_name"] = _FAULT_NAMES.get(self._a[i], "?")
            elif code == EV_BUDGET:
                # the plane rides the round column (libs/devledger
                # plane codes); heightless rows keep round=plane
                rec["plane"] = libdevledger.PLANES[
                    self._r[i] % len(libdevledger.PLANES)
                ]
            elif code == EV_TX:
                # the stage rides the round column; the key exports as
                # its bounded 16-hex-char prefix, never the raw key
                rec["stage_name"] = TX_STAGES.get(self._r[i], "?")
                rec["key"] = format(self._a[i] % (1 << 64), "016x")
            elif code == EV_LOCK:
                # the registry slot rides the round column; b packs
                # kind (low bit) + interned holder-acquire-site index
                rec["lock"] = liblockprof.slot_name(self._r[i])
                rec["kind_name"] = liblockprof.KIND_NAMES.get(
                    self._b[i] & 1, "?"
                )
                rec["site"] = liblockprof.site_name(self._b[i] >> 1)
            elif code == EV_PROF:
                # the subsystem index rides the round column
                rec["subsystem"] = libprofile.subsystem_name(self._r[i])
            elif code == EV_SPEC:
                rec["outcome_name"] = _SPEC_OUTCOMES.get(self._a[i], "?")
            o = self._o[i]
            if o:
                rec["node"] = origin_name(o)
            out.append(rec)
        return out

    def slis(self) -> dict:
        """SLIs derived from the ring: commit-latency quantiles,
        rounds-per-height, WAL fsync lag, step-progress age."""
        commits: list[float] = []
        rounds: list[int] = []
        fsyncs: list[float] = []
        for i in self._iter_slots():
            code = self._code[i]
            if code == EV_COMMIT:
                commits.append(self._a[i] / 1e9)
                rounds.append(self._r[i] + 1)
            elif code == EV_FSYNC:
                fsyncs.append(self._a[i] / 1e9)
        last_step = self._last[EV_STEP]
        return {
            "commits": len(commits),
            "commit_latency_s": {
                "last": round(commits[-1], 6) if commits else None,
                "p50": _quantile(commits, 0.50),
                "p99": _quantile(commits, 0.99),
            },
            "rounds_per_height": (
                round(sum(rounds) / len(rounds), 3) if rounds else None
            ),
            "wal_fsync_p99_s": _quantile(fsyncs, 0.99),
            "step_age_s": (
                round(time.monotonic() - last_step, 3) if last_step else None
            ),
        }

    def status(self) -> dict:
        return {
            "capacity": self.capacity,
            "recorded": self._written[0],
        }


def _quantile(values: list[float], q: float) -> float | None:
    if not values:
        return None
    vs = sorted(values)
    idx = min(len(vs) - 1, int(q * len(vs)))
    return round(vs[idx], 6)


def histogram_quantile(h, q: float) -> float:
    """Upper-bound quantile estimate from a libs/metrics Histogram's
    cumulative buckets (the promql-style read).  Unlocked GIL-consistent
    snapshot: the scrape path must not contend with observers.  The
    math lives in the shared :func:`libmetrics.quantile_from_buckets`
    estimator (one implementation for health, netstats and the
    device-ledger budget plane)."""
    return libmetrics.quantile_from_buckets(h.buckets, list(h._counts), q)


# -------------------------------------------------- module-level recorder

_mode = _env_mode()
_enabled: bool = _mode == "on"
# reference count of node-lifecycle holders (every booting node acquires
# unless the env knob pins health off) — "always-on" means on for every
# running node with zero opt-in, while bare library use stays free
_acquirers = 0

_REC = FlightRecorder(_ring_size_from_env())

# bundle rate limit + monitor registry + origin interning only (all
# setup/trip paths — never the record path)
_mtx = libsync.Mutex("libs.health._mtx")

# breaker-trip notices from crypto/coalesce (module-level so the hook
# needs no monitor handle; a lost increment under a rare write race
# costs one duplicate-free notice, never a missed episode — the ring
# event is recorded regardless)
_BREAKER_NOTICES = array("q", [0])


def enabled() -> bool:
    """The one check hot paths make before recording."""
    return _enabled


def enable(ring: int | None = None) -> None:
    """Force the recorder on (tests, bench).  ``ring`` rebuilds the
    buffer at a new capacity, dropping prior records."""
    global _enabled, _REC
    if ring is not None and ring != _REC.capacity:
        _REC = FlightRecorder(ring)
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def reset() -> None:
    """Drop all buffered records (tests, bench bursts)."""
    global _REC
    _REC = FlightRecorder(_REC.capacity)


def set_ring_capacity(n: int) -> None:
    """Rebuild the ring at a new capacity WITHOUT touching the enabled
    flag (simnet scenario runs size the ring to hold a whole run's
    gossip-annotated event stream, then restore the prior capacity)."""
    global _REC
    n = max(64, int(n))
    if n != _REC.capacity:
        _REC = FlightRecorder(n)


def export_ring(node: str | None = None) -> dict:
    """The portable flight-ring export: the ``flight.json`` bundle
    artifact, the ``/debug/flight`` pprof body, and the input shape the
    cross-node timeline merge (cometbft_tpu/postmortem) consumes.

    ``domain`` says which clock stamped the rows ("wall" for live
    nodes, "virtual" for simnet rings — where the shared clock makes a
    cross-node merge exact); ``origins`` is the interned origin-name
    table the per-row ``node``/``src`` fields were decoded from."""
    return {
        "schema": 1,
        "node": node,
        "domain": _clock_domain,
        "origins": list(_ORIGIN_NAMES),
        # measured per-peer clock-skew bounds (netstamp round trips):
        # the merge tags this ring's cross-node edges with them
        "skews": libnetstats.skew_table(),
        "ring": _REC.status(),
        "events": _REC.dump(),
    }


# ------------------------------------------------- per-height budget

# The stage vocabulary of the per-height latency budget — the ``stage``
# label of height_budget_seconds and the keys of every budget row.
BUDGET_STAGES = (
    "proposal_wait",  # enter-height -> Prevote step (proposal receipt)
    "gossip",  # vote-gathering wall time net of plane overlays
    "verify_queue",  # consensus-caller coalescer queue wait
    "verify_execute",  # consensus-caller pro-rata verify execute
    "hash",  # FSM-adjacent hash-plane time (merkle/mempool)
    "spec_exec",  # speculative FinalizeBlock time consumed by a hit
    "wal_fsync",  # FSM-blocking WAL fsync durations in the height window
    "apply",  # Commit step -> applied, net of fsync overlay
    "residual",  # whatever the named stages don't explain
)

_STEP_PREVOTE = 4  # RoundStep.PREVOTE in the EV_STEP step column
_STEP_COMMIT = 8  # RoundStep.COMMIT


def budget_from_events(events) -> dict[int, dict]:
    """Decompose each committed height's latency into BUDGET_STAGES.

    Input is a decoded event stream (``FlightRecorder.dump()`` rows, a
    ``flight.json`` export's ``events``, or a merged multi-node
    stream).  Per height: the EARLIEST commit row anchors the window
    ``[commit_ts - latency, commit_ts]``; that node's first Prevote and
    Commit step rows split it into proposal / vote-gathering / apply
    spans; ``plane.budget`` (EV_BUDGET) and ``wal.fsync`` rows are
    window-assigned by timestamp as overlays, and each span reports its
    remainder — so the stages tile the measured latency and
    ``coverage`` (stage sum / latency) sits at ~1.0 on a healthy burst.
    Pure function: deterministic for a given event list (the timeline
    merge reuses it for its per-height budget rows)."""
    commits: dict[int, tuple] = {}
    steps: dict[tuple, dict] = {}
    planes: list[tuple] = []
    fsyncs: list[tuple] = []
    specs: list[tuple] = []
    for ev in events:
        name = ev.get("event")
        if name == "consensus.commit":
            h = ev.get("height", 0)
            if h:
                cur = commits.get(h)
                if cur is None or ev.get("ts", 0) < cur[0]:
                    commits[h] = (
                        ev.get("ts", 0), ev.get("dur_ns", 0),
                        ev.get("node"),
                    )
        elif name == "consensus.step":
            h = ev.get("height", 0)
            if h:
                d = steps.setdefault((h, ev.get("node")), {})
                s = ev.get("step")
                if s not in d:
                    d[s] = ev.get("ts", 0)
        elif name == "plane.budget":
            planes.append((
                ev.get("ts", 0), ev.get("plane"),
                ev.get("wait_ns", 0), ev.get("exec_ns", 0),
            ))
        elif name == "wal.fsync":
            fsyncs.append((
                ev.get("ts", 0), ev.get("dur_ns", 0),
                ev.get("overlapped", 0),
            ))
        elif name == "spec.exec":
            specs.append((
                ev.get("ts", 0), ev.get("outcome", 0),
                ev.get("dur_ns", 0),
            ))
    out: dict[int, dict] = {}
    for h in sorted(commits):
        cts, dur, node = commits[h]
        if dur <= 0:
            continue
        t0 = cts - dur
        sd = steps.get((h, node), {})
        t_pv = sd.get(_STEP_PREVOTE)
        t_cm = sd.get(_STEP_COMMIT)
        have_steps = t_pv is not None
        e1 = min(max(t_pv, t0), cts) if t_pv else cts
        e2 = min(max(t_cm, e1), cts) if t_cm else cts

        def _span(ts: int) -> int:
            if ts <= e1:
                return 0
            return 1 if ts <= e2 else 2

        # per span: [verify_wait, verify_exec, hash, fsync, spec_exec]
        ov = [[0] * 5, [0] * 5, [0] * 5]
        # overlapped credit: work the pipelined commit moved OFF the
        # serial span (flagged fsyncs; a winning speculation's execute
        # time beyond what the span clamp can absorb). Reported beside
        # the stages — never inside them — so the tiling still covers
        # exactly the FSM-blocking latency without double-counting.
        overlapped_fsync = 0
        for ts, plane, w, x in planes:
            if t0 <= ts <= cts:
                k = _span(ts)
                if plane == "verify":
                    ov[k][0] += w
                    ov[k][1] += x
                else:
                    ov[k][2] += w + x
        for ts, d, lap in fsyncs:
            if t0 <= ts <= cts:
                if lap:
                    overlapped_fsync += d
                else:
                    ov[_span(ts)][3] += d
        for ts, outcome, d in specs:
            if t0 <= ts <= cts and outcome == SPEC_HIT:
                ov[_span(ts)][4] += d
        # Clamp each span's overlay total to the span's wall length:
        # FSM-blocking time inside a span cannot exceed the span, but
        # a shared multi-node ring (in-process nets, simnet) assigns
        # every node's plane rows to the one committing node's window,
        # and concurrent-thread callers (CheckTx hashing, the spec-exec
        # worker) overlap the FSM wall — scaling the components
        # pro-rata keeps the stage tiling honest (coverage ~1.0)
        # instead of double-counting.
        spans = (e1 - t0, e2 - e1, cts - e2)
        overlapped_spec = 0
        for k in range(3):
            tot = sum(ov[k])
            if tot > spans[k] > 0:
                scaled_spec = ov[k][4] * spans[k] // tot
                overlapped_spec += ov[k][4] - scaled_spec
                for j in range(5):
                    ov[k][j] = ov[k][j] * spans[k] // tot
            elif tot > 0 and spans[k] <= 0:
                overlapped_spec += ov[k][4]
                ov[k] = [0] * 5
        vq = ov[0][0] + ov[1][0] + ov[2][0]
        vx = ov[0][1] + ov[1][1] + ov[2][1]
        hs = ov[0][2] + ov[1][2] + ov[2][2]
        fs = ov[0][3] + ov[1][3] + ov[2][3]
        sp = ov[0][4] + ov[1][4] + ov[2][4]
        # a height with NO step rows cannot attribute its wall time to
        # a protocol stage — the unexplained remainder goes to
        # `residual`, not `proposal_wait`, so residual is the honest
        # "no data / decomposition gap" signal rather than a stage
        # that silently absorbs everything
        proposal_wait = (
            max(0, (e1 - t0) - sum(ov[0])) if have_steps else 0
        )
        gossip = max(0, (e2 - e1) - sum(ov[1]))
        apply_ = max(0, (cts - e2) - sum(ov[2]))
        named = proposal_wait + gossip + apply_ + vq + vx + hs + fs + sp
        residual = max(0, dur - named)
        stages_ns = {
            "proposal_wait": proposal_wait,
            "gossip": gossip,
            "verify_queue": vq,
            "verify_execute": vx,
            "hash": hs,
            "spec_exec": sp,
            "wal_fsync": fs,
            "apply": apply_,
            "residual": residual,
        }
        hv = {
            "height": h,
            "node": node,
            "latency_s": round(dur / 1e9, 9),
            "stages": {
                s: round(v / 1e9, 9) for s, v in stages_ns.items()
            },
            "coverage": round((named + residual) / dur, 4),
        }
        if overlapped_fsync or overlapped_spec:
            hv["overlapped"] = {
                "wal_fsync": round(overlapped_fsync / 1e9, 9),
                "spec_exec": round(overlapped_spec / 1e9, 9),
            }
        out[h] = hv
    return out


# budget() memo for the live-ring case: [recorder identity, commit
# tally, result]. sample() runs on every metrics scrape (and health
# tests poll it in tight loops); the per-height decomposition only
# changes when a height CLOSES, so keying the memo on the commit-row
# tally makes every between-commits scrape O(1) instead of a full
# 4096+-slot ring decode. (Overlay rows resolved after a commit carry
# post-commit timestamps, outside every closed window — they cannot
# change a cached view.)
_BUDGET_CACHE: list = [None, -1, None]


def budget(events=None) -> dict:
    """The per-height latency-budget view: ``/debug/budget``'s budget
    body, ``budget.json``'s, and the source of the
    ``height_budget_seconds{stage}`` gauges.  ``events`` defaults to
    the live flight ring (memoized on the ring's commit tally — no new
    commit returns the cached view without re-decoding)."""
    if events is None:
        rec = _REC
        cursor = rec._commits[0]
        if _BUDGET_CACHE[0] is rec and _BUDGET_CACHE[1] == cursor:
            return _BUDGET_CACHE[2]
        evs = rec.dump()
    else:
        rec = None
        evs = events
    per = budget_from_events(evs)
    heights = [per[h] for h in sorted(per)]
    agg = {s: 0.0 for s in BUDGET_STAGES}
    tot = 0.0
    for hv in heights:
        for s in BUDGET_STAGES:
            agg[s] += hv["stages"][s]
        tot += hv["latency_s"]
    out = {
        "commits": len(heights),
        "heights": heights,
        "stages_total_s": {s: round(v, 6) for s, v in agg.items()},
        "stage_fractions": (
            {s: round(v / tot, 4) for s, v in agg.items()}
            if tot > 0
            else None
        ),
        "coverage": (
            round(sum(agg.values()) / tot, 4) if tot > 0 else None
        ),
    }
    if events is None:
        # value slot FIRST: a concurrent reader that matches the key
        # slots below must find the new result, never None/stale
        _BUDGET_CACHE[2] = out
        _BUDGET_CACHE[1] = cursor
        _BUDGET_CACHE[0] = rec
    return out


# ---------------------------------------------------- critical path

# budget stages that are device-plane time — the ``plane`` dimension of
# the critical-path verdict groups them back into their planes
_PLANE_STAGES = {
    "verify": ("verify_queue", "verify_execute"),
    "hash": ("hash",),
}


def critical_path_from_events(events) -> dict[int, dict]:
    """Name, per committed height, the resource that gated the commit.

    Joins three views of the same commit window: the per-height budget
    stage tiles (:func:`budget_from_events` — the coalescer queue waits
    already ride in via the EV_BUDGET overlay rows), the EV_LOCK slow
    lock-wait rows (window-assigned by timestamp, exactly like
    EV_FSYNC), and the device-plane share of the stage tiling.  The
    verdict is ``stage × lock × plane × cpu``: the dominant non-residual
    budget stage, the lock with the largest in-window slow-wait total
    (with the blocking holder's acquire site), the dominant device
    plane, and — when the sampling profiler ran — the subsystem with
    the largest in-window on-CPU time (EV_PROF window rows, so a commit
    gated by GIL-bound Python in the FSM says ``cpu:consensus``, not
    just ``stage:verify_execute``) — ``gate`` names whichever dimension
    explains the most time.  Pure function of the decoded event stream
    (the postmortem timeline merge reuses it for its per-height
    ``critical_path`` rows)."""
    budgets = budget_from_events(events)
    if not budgets:
        return {}
    # commit window anchors (earliest commit row per height, the same
    # anchor budget_from_events uses) + the EV_LOCK wait rows + the
    # EV_PROF profiler window rows
    anchors: dict[int, tuple] = {}
    lock_rows: list[tuple] = []
    prof_rows: list[tuple] = []
    for ev in events:
        name = ev.get("event")
        if name == "consensus.commit":
            h = ev.get("height", 0)
            if h:
                cur = anchors.get(h)
                if cur is None or ev.get("ts", 0) < cur[0]:
                    anchors[h] = (ev.get("ts", 0), ev.get("dur_ns", 0))
        elif name == "sync.lock":
            if ev.get("kind_name") == "wait":
                lock_rows.append((
                    ev.get("ts", 0), ev.get("lock", "?"),
                    ev.get("dur_ns", 0), ev.get("site", "?"),
                ))
        elif name == "prof.window":
            # the profiler's own thread never gates a commit
            if ev.get("subsystem") != "sampler":
                prof_rows.append((
                    ev.get("ts", 0), ev.get("subsystem", "?"),
                    ev.get("oncpu_ns", 0),
                ))
    out: dict[int, dict] = {}
    for h, bud in budgets.items():
        cts, dur = anchors.get(h, (0, 0))
        if dur <= 0:
            continue
        t0 = cts - dur
        stages = bud["stages"]
        # dominant non-residual stage tile
        stage, stage_s = None, -1.0
        for s, v in stages.items():
            if s != "residual" and v > stage_s:
                stage, stage_s = s, v
        stage_s = max(0.0, stage_s)
        # dominant device plane (its stages' combined tile)
        plane, plane_s = None, 0.0
        for p, names in _PLANE_STAGES.items():
            v = 0.0
            for s in names:
                v += stages.get(s, 0.0)
            if v > plane_s:
                plane, plane_s = p, v
        # hottest lock: largest slow-wait total inside the window
        waits: dict[str, float] = {}
        sites: dict[str, str] = {}
        for ts, lk, d, site in lock_rows:
            if t0 <= ts <= cts:
                waits[lk] = waits.get(lk, 0.0) + d / 1e9
                sites.setdefault(lk, site)
        lock, lock_wait_s = None, 0.0
        for lk, v in waits.items():
            if v > lock_wait_s:
                lock, lock_wait_s = lk, v
        # hottest on-CPU subsystem: EV_PROF flush windows are stamped
        # at window END, so a row belongs to the commit window when its
        # flush landed inside it (the per-second granularity matches
        # the ~100 ms-to-seconds commit windows this joins against)
        cpus: dict[str, float] = {}
        for ts, subname, oncpu_ns in prof_rows:
            if t0 <= ts <= cts:
                cpus[subname] = cpus.get(subname, 0.0) + oncpu_ns / 1e9
        cpu, cpu_s = None, 0.0
        for subname, v in cpus.items():
            if v > cpu_s:
                cpu, cpu_s = subname, v
        gate, gate_s = f"stage:{stage}", stage_s
        if lock is not None and lock_wait_s > gate_s:
            gate, gate_s = f"lock:{lock}", lock_wait_s
        if plane is not None and plane_s > gate_s:
            gate, gate_s = f"plane:{plane}", plane_s
        if cpu is not None and cpu_s > gate_s:
            gate, gate_s = f"cpu:{cpu}", cpu_s
        out[h] = {
            "height": h,
            "node": bud.get("node"),
            "latency_s": bud["latency_s"],
            "coverage": bud["coverage"],
            "stage": stage,
            "stage_s": round(stage_s, 6),
            "lock": lock,
            "lock_wait_s": round(lock_wait_s, 6),
            "lock_site": sites.get(lock) if lock else None,
            "plane": plane,
            "plane_s": round(plane_s, 6),
            "cpu": cpu,
            "cpu_s": round(cpu_s, 6),
            "gate": gate,
        }
    return out


def critical_path(events=None) -> dict:
    """The per-height critical-path view: the ``/debug/contention``
    and ``contention.json`` verdict body.  ``events`` defaults to the
    live flight ring."""
    per = critical_path_from_events(
        _REC.dump() if events is None else events
    )
    heights = [per[h] for h in sorted(per)]
    gates: dict[str, int] = {}
    cov = 0.0
    for hv in heights:
        gates[hv["gate"]] = gates.get(hv["gate"], 0) + 1
        cov += hv["coverage"]
    return {
        "commits": len(heights),
        "heights": heights,
        "gates": dict(sorted(gates.items(), key=lambda kv: -kv[1])),
        "coverage": round(cov / len(heights), 4) if heights else None,
    }


def acquire() -> None:
    """Reference-counted enable for node lifecycles (the devstats
    pattern): every booting node acquires, so the recorder is on exactly
    while a node runs — unless ``COMETBFT_TPU_HEALTH=0`` pins it off."""
    global _acquirers, _enabled
    if _env_mode() == "off":
        return
    _acquirers += 1
    _enabled = True


def release() -> None:
    global _acquirers, _enabled
    _acquirers = max(0, _acquirers - 1)
    if _acquirers == 0 and _env_mode() != "on":
        _enabled = False


def monitor_enabled() -> bool:
    """Whether a booting node should start a HealthMonitor (watchdogs
    ride the same kill switch as the recorder)."""
    return _env_mode() != "off"


def record(
    code: int, height: int = 0, round_: int = 0, a: int = 0, b: int = 0
) -> None:
    """Record one flight event.  Allocation-free and lock-free; a
    single flag check when the recorder is off."""
    if not _enabled:
        return
    _REC.record(code, height, round_, a, b)


def recorder() -> FlightRecorder:
    return _REC


def slis() -> dict:
    return _REC.slis()


def note_breaker_trip() -> None:
    """crypto/coalesce hook: the half-open breaker tripped (wedged
    verify executor).  Records the ring event and leaves a notice the
    wedged-coalescer watchdog converts into a trip on its next check.
    Takes no lock — the caller may sit close to engine mutexes."""
    _BREAKER_NOTICES[0] = _BREAKER_NOTICES[0] + 1
    record(EV_BREAKER, a=1)


def note_breaker_rearm() -> None:
    """crypto/coalesce hook: a successful half-open probe re-armed
    routing."""
    record(EV_BREAKER, a=0)


# ------------------------------------------------------------- watchdogs

# HealthMonitor._st slot indices (array('d') state vector: the no-trip
# check path must retain nothing, so every mutable scalar lives in
# preallocated storage)
_ST_PROGRESS_BASE = 0  # stall baseline (monotonic)
_ST_STORM_BASE = 1  # recompile count at the storm window start
_ST_STORM_T0 = 2  # storm window start (monotonic)
_ST_BREAKER_SEEN = 3  # breaker notices already converted to trips
_ST_STORM_TRIP_T = 4  # last storm trip (monotonic; drives storm_active)
_ST_LAST_BUNDLE = 5  # last bundle write (monotonic; rate limit)
_ST_STALLED = 6  # 1.0 while the stall detector considers us stalled
# the saturation watchdog's counters live in a separate int vector
# (``_qfull``: [drops already seen, consecutive-fresh-drop streak]) —
# keeping them out of the float ``_st`` array matters: float temporaries
# land on CPython's float free-list, which tracemalloc counts as LIVE
# blocks attributed to the arithmetic line, tripping the pinned
# allocation-free guard whenever an earlier test perturbed the free-list
_QF_SEEN = 0
_QF_STREAK = 1
_ST_DISK_DEGRADED = 7  # 1.0 while the wired WAL reports disk_degraded
# tx-starvation slots: ring commit tally already seen, monotonic of the
# last observed tally advance, inter-commit interval EWMA (seconds),
# and the edge-trigger episode flag
_ST_TX_SEEN = 8
_ST_TX_LAST_T = 9
_ST_TX_INTERVAL = 10
_ST_TX_STARVED = 11


class HealthMonitor(BaseService):
    """Background watchdog thread over the flight recorder.

    One instance per node (node/node.py starts it alongside the
    Prometheus exporter); ``_check()`` is a pure, allocation-free
    evaluation so tests (and the tracemalloc guard) can drive it
    directly without the thread.
    """

    def __init__(
        self,
        metrics=None,
        stall_base_s: float = 4.0,
        stall_mult: float | None = None,
        bundle_dir: str | None = None,
        bundle_rl_s: float | None = None,
        bundle_keep: int = DEFAULT_BUNDLE_KEEP,
        storm_recompiles: int = STORM_RECOMPILES,
        storm_window_s: float = STORM_WINDOW_S,
        saturation_streak: int = SATURATION_STREAK,
        lock_wait_s: float | None = None,
        starve_s: float | None = None,
        starve_share: float = STARVE_LANE_SHARE,
        starve_min_lanes: int = STARVE_MIN_LANES,
        tx_starve_commits: float | None = None,
        interval_s: float | None = None,
        trace_tail: int = 512,
        idle_ok=None,
        disk_degraded_fn=None,
        logger=None,
    ):
        super().__init__("HealthMonitor", logger)
        self.metrics = metrics
        # disk_degraded_fn: zero-arg bool — the slow-disk watchdog's
        # signal, wired by node/node.py to the consensus WAL's fsync
        # EWMA state (consensus/wal.py disk_degraded()). A trip fires
        # on each False->True transition (per-episode, not per-tick);
        # None (bare harnesses, NopWAL nodes) disables the watchdog.
        self._disk_degraded = disk_degraded_fn
        # idle_ok: zero-arg callable consulted when the stall window
        # expires — True means the silence is LEGITIMATE (the node is
        # still block-syncing, or create_empty_blocks=False with an
        # empty mempool leaves the FSM intentionally parked), so the
        # window re-baselines without a trip. node/node.py wires this
        # to its own sync/mempool state; None = every silence is a
        # stall (bare consensus harnesses, tests).
        self._idle_ok = idle_ok
        self.bundle_keep = bundle_keep
        mult = (
            stall_mult
            if stall_mult is not None
            else _env_float(_ENV_STALL_MULT, DEFAULT_STALL_MULT)
        )
        self.stall_after_s = max(0.05, stall_base_s * mult)
        self.bundle_dir = os.environ.get(_ENV_BUNDLE_DIR) or bundle_dir
        self.bundle_rl_s = (
            bundle_rl_s
            if bundle_rl_s is not None
            else _env_float(_ENV_BUNDLE_RL, DEFAULT_BUNDLE_RL_S)
        )
        self.storm_recompiles = storm_recompiles
        self.storm_window_s = storm_window_s
        self.saturation_streak = max(1, saturation_streak)
        self.interval_s = (
            interval_s
            if interval_s is not None
            else max(0.05, min(1.0, self.stall_after_s / 4.0))
        )
        self.trace_tail = trace_tail
        # trip tallies per watchdog (trip paths may allocate)
        self.trips = {name: 0 for name, _ in _WATCHDOGS}
        self.bundles = 0
        self._thread: threading.Thread | None = None
        # tx-starvation config + the txtrace handle (resolved once at
        # construction — the per-tick check must not run the import
        # machinery; health cannot top-import txtrace, which imports
        # this module for the ring clock and EV_TX recording)
        from . import txtrace as libtxtrace

        self._txtrace = libtxtrace
        self.tx_starve_commits = (
            tx_starve_commits
            if tx_starve_commits is not None
            else libtxtrace.starve_commits()
        )
        # preallocated scalar state — see the _ST_* index comments
        self._st = array("d", [0.0] * 12)
        now = time.monotonic()
        self._st[_ST_PROGRESS_BASE] = now
        self._st[_ST_STORM_T0] = now
        self._st[_ST_STORM_BASE] = float(self._recompile_total())
        self._st[_ST_BREAKER_SEEN] = float(_BREAKER_NOTICES[0])
        # commits that predate this monitor must not feed the
        # inter-commit interval estimate (the lane-watermark posture)
        self._st[_ST_TX_SEEN] = float(_REC._commits[0])
        # drops that predate this monitor must not count toward a streak
        self._qfull = array("q", [0, 0])
        self._qfull[_QF_SEEN] = libnetstats.consensus_queue_full_total()
        # -- consensus-starvation state (preallocated, the _qfull
        # posture): [prev consensus lanes, prev total lanes, starved
        # flag]; the windowed queue-wait bucket watermarks allocate
        # lazily on the first window that reaches starve_min_lanes —
        # never on the steady no-traffic path the tracemalloc guard
        # drives. ``starve_s <= 0`` disables the watchdog.
        self.starve_s = (
            starve_s
            if starve_s is not None
            else libdevledger.starve_threshold_s()
        )
        self.starve_share = starve_share
        self.starve_min_lanes = max(1, starve_min_lanes)
        self._sv = array("q", [0, 0, 0])
        cons0, total0 = libdevledger.verify_lanes_split()
        self._sv[0] = cons0  # lanes that predate this monitor don't count
        self._sv[1] = total0
        # -- lock-contention state (preallocated): the lockprof wait-
        # histogram watermark the windowed p99 deltas run against, plus
        # [consecutive-hot-window streak, last hot slot]. The seeding
        # call advances the watermark so contention that predates this
        # monitor cannot replay as a fresh trip (the lane posture).
        # ``lock_wait_s <= 0`` disables the watchdog.
        self.lock_wait_s = (
            lock_wait_s
            if lock_wait_s is not None
            else liblockprof.slow_threshold_s()
        )
        self._lk_hist = array(
            "q", [0] * (liblockprof.N_SLOTS * liblockprof.N_BUCKETS)
        )
        self._lk = array("q", [0, -1])
        liblockprof.worst_windowed_p99(self._lk_hist)
        self._starve_counts: array | None = None
        if self.starve_s > 0:
            try:
                # same watermark posture as the lanes above: queue-wait
                # observations that predate this monitor must not leak
                # into the first judged window's p99 (the delta would
                # otherwise be computed against a zero baseline and
                # replay an old storm as a fresh trip)
                self._consensus_wait_p99()
            except Exception:
                pass  # no metrics yet: first _check seeds the baseline

    # -- lifecycle ---------------------------------------------------------

    def on_start(self) -> None:
        self._st[_ST_PROGRESS_BASE] = time.monotonic()
        t = threading.Thread(
            target=self._run, name="health-monitor", daemon=True
        )
        # the fallible step FIRST: a failed spawn must leak neither the
        # recorder acquire nor a registry entry
        t.start()
        self._thread = t
        acquire()  # the watchdogs need the recorder's step timeline
        with _mtx:
            _MONITORS.append(self)

    def on_stop(self) -> None:
        with _mtx:
            for i in range(len(_MONITORS) - 1, -1, -1):
                if _MONITORS[i] is self:
                    del _MONITORS[i]
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=2)
        release()

    def _run(self) -> None:
        quit_ev = self.quit_event()
        while not quit_ev.is_set():
            try:
                mask = self._check()
                if mask:
                    self._handle_trips(mask)
            except Exception:
                # a watchdog fault must never take the monitor down
                import traceback

                traceback.print_exc()
            quit_ev.wait(self.interval_s)

    # -- evaluation --------------------------------------------------------

    def _recompile_total(self) -> int:
        """Current ``xla_recompile_total`` from the devstats ledger
        (drains staged compiles — a read path, like every scrape)."""
        from . import devstats as libdevstats

        return libdevstats.counters()["recompiles"]

    def _check(self) -> int:
        """One watchdog evaluation; returns a bitmask of FRESH trips.

        Allocation-free on the no-trip path (pinned by the tracemalloc
        guard): all mutable state lives in the preallocated ``_st``
        vector, and the mask is a small int.
        """
        st = self._st
        now = time.monotonic()
        mask = 0
        # -- consensus stall: no step transition within the window
        last_step = _REC._last[EV_STEP]
        base = st[_ST_PROGRESS_BASE]
        progress = last_step if last_step > base else base
        if now - progress > self.stall_after_s:
            # a legitimately idle node (syncing, or intentionally
            # parked waiting for txs) re-baselines without a trip —
            # only consulted at window expiry, never on the hot path
            if self._idle_ok is not None:
                try:
                    idle = bool(self._idle_ok())
                except Exception:
                    idle = False
            else:
                idle = False
            # re-baseline: one evaluation per window, not per tick
            st[_ST_PROGRESS_BASE] = now
            if idle:
                st[_ST_STALLED] = 0.0
            else:
                mask |= 1
                st[_ST_STALLED] = 1.0
        elif last_step > base:
            st[_ST_STALLED] = 0.0  # progress resumed
        # -- wedged coalescer: breaker notices since the last check
        notices = _BREAKER_NOTICES[0]
        if notices > st[_ST_BREAKER_SEEN]:
            st[_ST_BREAKER_SEEN] = float(notices)
            mask |= 2
        # -- recompile storm: ledger delta inside a rolling window
        cur = self._recompile_total()
        if now - st[_ST_STORM_T0] > self.storm_window_s:
            st[_ST_STORM_T0] = now
            st[_ST_STORM_BASE] = float(cur)
        elif cur - st[_ST_STORM_BASE] >= self.storm_recompiles:
            mask |= 4
            st[_ST_STORM_TRIP_T] = now
            st[_ST_STORM_T0] = now
            st[_ST_STORM_BASE] = float(cur)
        # -- saturated consensus send queue: MConnection.send drops on
        # a consensus channel in SATURATION_STREAK consecutive checks —
        # a full queue that stays full is a peer that stopped draining
        # (or a reactor wedged behind it), not a burst (int-only math:
        # see the _qfull vector comment above)
        qf = self._qfull
        qfull = libnetstats.consensus_queue_full_total()
        if qfull > qf[_QF_SEEN]:
            qf[_QF_STREAK] += 1
            if qf[_QF_STREAK] >= self.saturation_streak:
                mask |= 8
                qf[_QF_STREAK] = 0
        else:
            qf[_QF_STREAK] = 0
        qf[_QF_SEEN] = qfull
        # -- slow disk: the wired WAL's fsync-latency EWMA crossed its
        # degradation threshold (consensus/wal.py hysteresis). Trip on
        # the False->True EDGE only — degradation is an episode, and
        # the widened propose timeouts keep the chain live through it;
        # a raising probe fails toward alerting (degraded=True).
        if self._disk_degraded is not None:
            try:
                degraded = bool(self._disk_degraded())
            except Exception:
                degraded = True
            if degraded and st[_ST_DISK_DEGRADED] == 0.0:
                mask |= 16
            st[_ST_DISK_DEGRADED] = 1.0 if degraded else 0.0
        # -- consensus starvation: consensus-caller verify queue-wait
        # p99 (windowed from the device_queue_wait_seconds buckets)
        # above the threshold WHILE other callers dominate the lane
        # share of the same window. Judged only once the ledger saw
        # starve_min_lanes fresh lanes — an idle or lightly-loaded
        # plane is never starved, and the no-traffic check path stays
        # allocation-free. Edge-triggered per episode like slow_disk.
        if self.starve_s > 0:
            sv = self._sv
            cons, total = libdevledger.verify_lanes_split()
            d_total = total - sv[1]
            if d_total >= self.starve_min_lanes:
                d_cons = cons - sv[0]
                sv[0] = cons
                sv[1] = total
                others = d_total - d_cons
                dominate = others >= d_total * self.starve_share
                p99 = self._consensus_wait_p99()
                if dominate and p99 > self.starve_s:
                    if sv[2] == 0:
                        mask |= 32
                    sv[2] = 1
                else:
                    sv[2] = 0
        # -- tx starvation: the oldest admitted-uncommitted tx is older
        # than N measured commit intervals WHILE heights keep
        # committing. The interval EWMA comes from the ring's commit
        # tally (pre-monitor commits excluded at ctor); "keeps
        # committing" = the tally advanced within the starve window
        # itself, so a dead chain stays the stall watchdog's case.
        # Edge-triggered per episode like slow_disk.
        if self.tx_starve_commits > 0:
            cur_c = _REC._commits[0]
            seen_c = st[_ST_TX_SEEN]
            if cur_c > seen_c:
                t_last = st[_ST_TX_LAST_T]
                if t_last > 0:
                    iv = (now - t_last) / (cur_c - seen_c)
                    ew = st[_ST_TX_INTERVAL]
                    st[_ST_TX_INTERVAL] = (
                        iv if ew == 0.0 else 0.75 * ew + 0.25 * iv
                    )
                st[_ST_TX_LAST_T] = now
                st[_ST_TX_SEEN] = float(cur_c)
            interval = st[_ST_TX_INTERVAL]
            if interval > 0:
                window = self.tx_starve_commits * interval
                committing = (
                    st[_ST_TX_LAST_T] > 0
                    and now - st[_ST_TX_LAST_T] <= window
                )
                if (
                    committing
                    and self._txtrace.oldest_admitted_age_s() > window
                ):
                    if st[_ST_TX_STARVED] == 0.0:
                        mask |= 64
                    st[_ST_TX_STARVED] = 1.0
                else:
                    st[_ST_TX_STARVED] = 0.0
        # -- sustained lock contention: the worst registered engine
        # lock's windowed p99 wait (lockprof delta histogram since the
        # last check) at or above the threshold in
        # LOCK_CONTENDED_STREAK consecutive checks. The streak resets
        # on trip, so a wedged lock re-trips once per streak window,
        # not per tick; int-only state (the _qfull posture).
        if self.lock_wait_s > 0:
            lk = self._lk
            slot, p99 = liblockprof.worst_windowed_p99(self._lk_hist)
            if slot >= 0 and p99 >= self.lock_wait_s:
                lk[1] = slot
                lk[0] += 1
                if lk[0] >= LOCK_CONTENDED_STREAK:
                    mask |= 128
                    lk[0] = 0
            else:
                lk[0] = 0
        return mask

    def _consensus_wait_p99(self) -> float:
        """Windowed p99 of the consensus-caller verify queue wait:
        delta of the device_queue_wait_seconds{plane=verify,caller}
        buckets (summed over the consensus caller classes) since the
        last judged window, through the shared
        libmetrics.quantile_from_buckets estimator."""
        m = self.metrics if self.metrics is not None else (
            libmetrics.node_metrics()
        )
        fam = m.device_queue_wait
        nb = len(fam.buckets) + 1
        prev = self._starve_counts
        if prev is None:
            prev = self._starve_counts = array("q", [0] * nb)
        cur = [0] * nb
        for cid in libdevledger.BUDGET_VERIFY_CALLERS:
            child = fam.labels("verify", libdevledger.caller_name(cid))
            cc = child._counts
            for i in range(nb):
                cur[i] += cc[i]
        delta = [0] * nb
        for i in range(nb):
            delta[i] = cur[i] - prev[i]
            prev[i] = cur[i]
        return libmetrics.quantile_from_buckets(fam.buckets, delta, 0.99)

    def starved(self) -> bool:
        """Last-observed consensus-starvation state."""
        return self._sv[2] != 0

    def tx_starved(self) -> bool:
        """Last-observed tx-starvation state (inclusion broken while
        the chain keeps committing)."""
        return self._st[_ST_TX_STARVED] != 0.0

    def hot_lock(self) -> str | None:
        """The registered lock the contention watchdog most recently
        flagged as over-threshold (None until a window crosses it)."""
        slot = self._lk[1]
        return liblockprof.slot_name(slot) if slot >= 0 else None

    def stalled(self) -> bool:
        return self._st[_ST_STALLED] != 0.0

    def disk_degraded(self) -> bool:
        """Last-observed slow-disk state (updated each check tick)."""
        return self._st[_ST_DISK_DEGRADED] != 0.0

    def storm_active(self) -> bool:
        t = self._st[_ST_STORM_TRIP_T]
        return bool(t) and time.monotonic() - t < self.storm_window_s

    # -- trip handling -----------------------------------------------------

    def _handle_trips(self, mask: int) -> None:
        m = self.metrics if self.metrics is not None else (
            libmetrics.node_metrics()
        )
        names = [name for name, bit in _WATCHDOGS if mask & bit]
        for name, bit in _WATCHDOGS:
            if not mask & bit:
                continue
            self.trips[name] += 1
            m.health_watchdog_trips.labels(name).inc()
            record(EV_WATCHDOG, a=bit)
            if self.logger is not None:
                self.logger.error(
                    "health watchdog tripped",
                    watchdog=name,
                    stall_after_s=round(self.stall_after_s, 3),
                )
        path = self._maybe_bundle("-".join(names), m)
        if path is not None and self.logger is not None:
            self.logger.error("black-box bundle written", path=path)

    def _maybe_bundle(self, reason: str, m) -> str | None:
        """Write one black-box bundle unless the rate limit forbids it.
        The check-and-set runs under ``libs.health._mtx``; all file I/O
        happens after release (the mutex stays a blocking-free leaf)."""
        if not self.bundle_dir:
            return None
        now = time.monotonic()
        with _mtx:
            last = self._st[_ST_LAST_BUNDLE]
            if last and now - last < self.bundle_rl_s:
                return None
            self._st[_ST_LAST_BUNDLE] = now
        try:
            path = write_bundle(
                self.bundle_dir, reason,
                metrics=self.metrics, trace_tail=self.trace_tail,
            )
        except Exception:
            import traceback

            traceback.print_exc()
            return None
        prune_bundles(self.bundle_dir, self.bundle_keep)
        self.bundles += 1
        m.health_bundles.inc()
        return path

    def status(self) -> dict:
        return {
            "running": self.is_running(),
            "stall_after_s": round(self.stall_after_s, 3),
            "interval_s": round(self.interval_s, 3),
            "stalled": self.stalled(),
            "storm_active": self.storm_active(),
            "disk_degraded": self.disk_degraded(),
            "consensus_starved": self.starved(),
            "tx_starved": self.tx_starved(),
            "tx_starve_commits": round(self.tx_starve_commits, 2),
            "starve_threshold_s": round(self.starve_s, 4),
            "lock_wait_s": round(self.lock_wait_s, 4),
            "hot_lock": self.hot_lock(),
            "trips": dict(self.trips),
            "bundles": self.bundles,
            "bundle_dir": self.bundle_dir,
            "bundle_rl_s": self.bundle_rl_s,
            "bundle_keep": self.bundle_keep,
        }


# registry of running monitors (stack semantics like libs/metrics'
# node-metrics stack: the most recent running monitor answers
# process-wide queries; pops are by identity)
_MONITORS: list[HealthMonitor] = []


def active_monitor() -> HealthMonitor | None:
    # lock-free read (tuple snapshot, like crypto/coalesce._ACTIVE):
    # the scrape path consults this and must never touch _mtx — only
    # the start/stop writers serialize on it
    mons = tuple(_MONITORS)
    return mons[-1] if mons else None


# --------------------------------------------------------- black-box dump


def write_bundle(
    dir_: str, reason: str, metrics=None, trace_tail: int = 512
) -> str:
    """Write one black-box bundle directory and return its path.

    Contents: ``manifest.json`` (reason + SLI snapshot), ``flight.json``
    (the decoded flight-recorder ring), ``devstats.json`` (the XLA/device
    telemetry snapshot), ``locks.json`` (deadlock-tier status + every
    thread's held lock-order stack), ``threads.txt`` (all thread
    stacks), ``trace.json`` (tracer status + ring tail).
    """
    safe = "".join(c if (c.isalnum() or c in "-_") else "-" for c in reason)
    path = os.path.join(dir_, f"health-{time.time_ns()}-{safe}")
    os.makedirs(path, exist_ok=True)

    def save(name: str, obj) -> None:
        try:
            with open(os.path.join(path, name), "w") as f:
                if isinstance(obj, str):
                    f.write(obj)
                else:
                    json.dump(obj, f, indent=1, default=str)
        except Exception as e:
            try:
                with open(os.path.join(path, name + ".err"), "w") as f:
                    f.write(repr(e))
            except Exception:
                pass

    save(
        "manifest.json",
        {
            "reason": reason,
            "ts_ns": time.time_ns(),
            "slis": _REC.slis(),
            "ring": _REC.status(),
        },
    )
    save("flight.json", export_ring())
    # the device-time ledger + per-height latency budget: who used the
    # device and where each height's wall time went at the failure edge
    try:
        save(
            "budget.json",
            {"ledger": libdevledger.snapshot(), "budget": budget()},
        )
    except Exception as e:
        save("budget.json.err", repr(e))
    # lock-contention plane + per-height critical path: which mutex the
    # engine waited on and what actually gated each commit, with every
    # thread's blocked-on lock at the failure edge
    try:
        save(
            "contention.json",
            {
                "lockprof": liblockprof.snapshot(),
                "critical_path": critical_path(),
            },
        )
    except Exception as e:
        save("contention.json.err", repr(e))
    # merged cross-node timeline + root-cause attribution: peers' rings
    # are pulled over RPC when COMETBFT_TPU_POSTMORTEM_PEERS names them
    # (reachable or not, the local view is always written) — the knob
    # COMETBFT_TPU_POSTMORTEM=0 skips the pass entirely
    if os.environ.get(_ENV_POSTMORTEM, "").lower() not in _OFF_VALUES:
        try:
            from .. import postmortem as _pm

            save("timeline.json", _pm.bundle_timeline())
        except Exception as e:
            save("timeline.json.err", repr(e))
    # tx-lifecycle plane: in-flight + recently-committed sampled txs
    # and the per-mempool oldest-admitted table — a tx_starved bundle
    # names the starved keys (bounded short prefixes) right here
    try:
        from . import txtrace as libtxtrace

        save("tx.json", libtxtrace.snapshot())
    except Exception as e:
        save("tx.json.err", repr(e))
    try:
        from . import devstats as libdevstats

        save("devstats.json", libdevstats.snapshot())
    except Exception as e:
        save("devstats.json.err", repr(e))
    # sampling-profiler plane: the recent-sample ring covering the
    # seconds BEFORE the trip — what every subsystem was doing (and
    # which lock/queue blocked threads were parked on) at the edge
    try:
        save("profile.json", libprofile.bundle_snapshot())
    except Exception as e:
        save("profile.json.err", repr(e))
    save(
        "locks.json",
        {
            "deadlock_detection": libsync.enabled(),
            "lock_order_mode": libsync.lock_order_mode(),
            "held": {
                str(tid): stack
                for tid, stack in libsync.held_locks_snapshot().items()
            },
        },
    )
    try:
        save("net.json", libnetstats.snapshot())
    except Exception as e:
        save("net.json.err", repr(e))
    try:
        from . import pprof as libpprof

        save("threads.txt", libpprof.thread_dump())
    except Exception as e:
        save("threads.txt.err", repr(e))
    save(
        "trace.json",
        {
            "status": libtrace.status(),
            "events": libtrace.ring_dump()[-trace_tail:],
        },
    )
    return path


def prune_bundles(dir_: str, keep: int) -> None:
    """Bound the ``health-*`` bundle directories in ``dir_`` to ``keep``.

    The rate limit floors the write interval; this bounds the TOTAL on
    disk. Retention favors forensics: the OLDEST bundle (the original
    failure edge) is always kept, and the remaining ``keep - 1`` slots
    hold the newest ones (the still-failing state) — the middle of a
    days-long stall is the least interesting part. ``keep <= 0``
    disables pruning. Names embed ``time.time_ns()``, so the
    lexicographic sort is the chronological one."""
    if keep <= 0:
        return
    try:
        names = sorted(
            n for n in os.listdir(dir_) if n.startswith("health-")
        )
    except OSError:
        return
    if len(names) <= keep:
        return
    doomed = names[1:] if keep == 1 else names[1 : -(keep - 1)]
    for n in doomed:
        shutil.rmtree(os.path.join(dir_, n), ignore_errors=True)


# ------------------------------------------------------ SLO/health engine


def sample(metrics=None) -> dict:
    """Pull-time SLI computation: derive the ``health_*`` gauges and the
    composite score into ``metrics`` (the scraped node's NodeMetrics) or
    the process-wide top.  Touches NO flight-recorder lock (there is
    none) and no engine mutex — safe on every scrape path."""
    m = metrics if metrics is not None else libmetrics.node_metrics()
    s = _REC.slis()
    from ..crypto import coalesce as crypto_coalesce

    breaker_open = crypto_coalesce.breaker_open()
    mon = active_monitor()
    stalled = False
    storm = False
    disk_degraded = False
    tx_starved = False
    if mon is not None:
        storm = mon.storm_active()
        disk_degraded = mon.disk_degraded()
        tx_starved = mon.tx_starved()
        age = s["step_age_s"]
        stalled = mon.stalled() or (
            age is not None and age > mon.stall_after_s
        )
    lat = s["commit_latency_s"]
    if lat["p50"] is not None:
        m.health_commit_latency.labels("p50").set(lat["p50"])
        m.health_commit_latency.labels("p99").set(lat["p99"])
        m.health_commit_latency.labels("last").set(lat["last"])
    if s["rounds_per_height"] is not None:
        m.health_rounds_per_height.set(s["rounds_per_height"])
    if s["wal_fsync_p99_s"] is not None:
        m.health_wal_fsync.set(s["wal_fsync_p99_s"])
    wait_p99 = histogram_quantile(m.coalesce_wait_seconds, 0.99)
    m.health_verify_wait_p99.set(wait_p99)
    m.health_breaker_open.set(1.0 if breaker_open else 0.0)
    if s["step_age_s"] is not None:
        m.health_stall_seconds.set(s["step_age_s"])
    gossip_lag = libnetstats.gossip_lag_s()
    m.health_gossip_lag.set(gossip_lag)
    # tx-lifecycle plane bridge: completed sampled txs observe into
    # the tx histograms from per-registry watermarks, and the
    # mempool_oldest_age_seconds gauge reads the live mempools
    # (libs/txtrace.sample — lazy import: txtrace imports this module
    # for the ring clock and EV_TX recording)
    from . import txtrace as libtxtrace

    libtxtrace.sample(m)
    # device-time ledger bridge + the latest height's latency budget
    # (gauges carry the most recent fully-decomposed height; the full
    # per-height table lives on /debug/budget and in budget.json)
    libdevledger.sample(m)
    # lock-contention bridge: per-lock wait/hold/contended counters
    # from per-registry watermarks (libs/lockprof)
    liblockprof.sample(m)
    # sampling-profiler bridge: per-(subsystem, state) sample counters
    # into profile_samples_total from per-registry watermarks
    libprofile.sample(m)
    bud = budget()
    if bud["heights"]:
        last_stages = bud["heights"][-1]["stages"]
        for stage in BUDGET_STAGES:
            m.height_budget.labels(stage).set(last_stages[stage])
    # composite score: 1.0 healthy; a stall zeroes it (liveness lost);
    # an open breaker or an active recompile storm each cost 0.3, a
    # degraded disk or a starved tx 0.2 each (degraded but live — the
    # chain still commits) — documented in docs/observability.md
    if stalled:
        score = 0.0
    else:
        score = 1.0
        if breaker_open:
            score -= 0.3
        if storm:
            score -= 0.3
        if disk_degraded:
            score -= 0.2
        if tx_starved:
            score -= 0.2
        score = max(0.0, score)
    m.health_score.set(score)
    return {
        "score": round(score, 3),
        "stalled": stalled,
        "breaker_open": breaker_open,
        "recompile_storm": storm,
        "disk_degraded": disk_degraded,
        "tx_starved": tx_starved,
        "verify_wait_p99_s": wait_p99,
        "gossip_lag_p99_s": round(gossip_lag, 6),
        **s,
    }


def debug_budget_json() -> str:
    """Body of the pprof server's ``/debug/budget`` route: the
    device-time ledger (per-caller attribution + occupancy +
    reconciliation) and the per-height latency budget."""
    return json.dumps(
        {
            "ledger": libdevledger.snapshot(),
            "budget": budget(),
        },
        default=str,
    )


def debug_contention_json() -> str:
    """Body of the pprof server's ``/debug/contention`` route: the
    per-lock contention ledger (libs/lockprof), the per-height
    critical-path verdicts, and every thread's held/blocked-on lock
    state."""
    mon = active_monitor()
    return json.dumps(
        {
            "lockprof": liblockprof.snapshot(),
            "critical_path": critical_path(),
            "hot_lock": mon.hot_lock() if mon is not None else None,
            "threads": {
                str(tid): info
                for tid, info in libsync.held_locks_snapshot().items()
            },
        },
        default=str,
    )


def debug_health_json(tail: int = 100) -> str:
    """Body of the pprof server's ``/debug/health`` route."""
    mon = active_monitor()
    out = {
        "enabled": _enabled,
        "ring": _REC.status(),
        "health": sample(),
        "watchdogs": mon.status() if mon is not None else None,
        "events": _REC.dump()[-tail:],
    }
    return json.dumps(out, default=str)
