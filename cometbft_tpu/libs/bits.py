"""Thread-safe bit array (reference: libs/bits/bit_array.go).

Used for vote bookkeeping (which validators have voted) and block-part
tracking; gossip messages exchange these to decide what to send a peer.
"""

from __future__ import annotations

import random
from . import sync as libsync


class BitArray:
    def __init__(self, bits: int):
        if bits < 0:
            raise ValueError("negative bit count")
        self.bits = bits
        self._elems = bytearray((bits + 7) // 8)
        self._mtx = libsync.Mutex("libs.bits._mtx")

    @classmethod
    def from_indices(cls, bits: int, indices) -> "BitArray":
        ba = cls(bits)
        for i in indices:
            ba.set_index(i, True)
        return ba

    def size(self) -> int:
        return self.bits

    def to_bytes(self) -> bytes:
        with self._mtx:
            return bytes(self._elems)

    @classmethod
    def from_bytes(cls, bits: int, data: bytes) -> "BitArray":
        ba = cls(bits)
        if len(data) != len(ba._elems):
            raise ValueError(
                f"bit array of {bits} bits needs {len(ba._elems)} bytes, "
                f"got {len(data)}"
            )
        ba._elems[:] = data
        # Zero-tail invariant: every predicate (is_full/__eq__/or_) assumes
        # bits past `bits` are 0.
        rem = bits % 8
        if rem and ba._elems:
            ba._elems[-1] &= (1 << rem) - 1
        return ba

    def get_index(self, i: int) -> bool:
        if i < 0 or i >= self.bits:
            return False
        with self._mtx:
            return bool(self._elems[i // 8] >> (i % 8) & 1)

    def set_index(self, i: int, value: bool) -> bool:
        if i < 0 or i >= self.bits:
            return False
        with self._mtx:
            if value:
                self._elems[i // 8] |= 1 << (i % 8)
            else:
                self._elems[i // 8] &= ~(1 << (i % 8))
            return True

    def copy(self) -> "BitArray":
        ba = BitArray(self.bits)
        with self._mtx:
            ba._elems = bytearray(self._elems)
        return ba

    def or_(self, other: "BitArray") -> "BitArray":
        """Union, sized to the larger operand (bit_array.go Or)."""
        out = BitArray(max(self.bits, other.bits))
        with self._mtx:
            mine = bytes(self._elems)
        with other._mtx:
            theirs = bytes(other._elems)
        for i, b in enumerate(mine):
            out._elems[i] |= b
        for i, b in enumerate(theirs):
            out._elems[i] |= b
        return out

    def and_(self, other: "BitArray") -> "BitArray":
        out = BitArray(min(self.bits, other.bits))
        with self._mtx:
            mine = bytes(self._elems)
        with other._mtx:
            theirs = bytes(other._elems)
        for i in range(len(out._elems)):
            out._elems[i] = mine[i] & theirs[i]
        return out

    def not_(self) -> "BitArray":
        out = BitArray(self.bits)
        with self._mtx:
            for i in range(len(self._elems)):
                out._elems[i] = ~self._elems[i] & 0xFF
        # mask tail bits beyond size
        extra = len(out._elems) * 8 - self.bits
        if extra and out._elems:
            out._elems[-1] &= 0xFF >> extra
        return out

    def sub(self, other: "BitArray") -> "BitArray":
        """Bits set in self but not in other (bit_array.go Sub)."""
        out = self.copy()
        n = min(self.bits, other.bits)
        for i in range(n):
            if other.get_index(i):
                out.set_index(i, False)
        return out

    def is_empty(self) -> bool:
        with self._mtx:
            return not any(self._elems)

    def is_full(self) -> bool:
        if self.bits == 0:
            return True
        with self._mtx:
            whole, rem = divmod(self.bits, 8)
            if any(b != 0xFF for b in self._elems[:whole]):
                return False
            if rem:
                return self._elems[whole] == (1 << rem) - 1
            return True

    def pick_random(self, rng: random.Random | None = None) -> tuple[int, bool]:
        """A uniformly random set bit, or (0, False) if none."""
        trues = self.get_true_indices()
        if not trues:
            return 0, False
        return (rng or random).choice(trues), True

    def get_true_indices(self) -> list[int]:
        with self._mtx:
            return [
                i
                for i in range(self.bits)
                if self._elems[i // 8] >> (i % 8) & 1
            ]

    def __eq__(self, other: object) -> bool:
        return (
            isinstance(other, BitArray)
            and self.bits == other.bits
            and bytes(self._elems) == bytes(other._elems)
        )

    def __str__(self) -> str:
        return "".join(
            "x" if self.get_index(i) else "_" for i in range(self.bits)
        )
