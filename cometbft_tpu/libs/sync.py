"""Deadlock-detecting mutex tier (reference: libs/sync/deadlock.go —
the ``deadlock`` build tag swaps every mutex for sasha-s/go-deadlock).

``Mutex()`` / ``RLock()`` return plain ``threading`` primitives unless
deadlock detection is enabled (env ``COMETBFT_TPU_DEADLOCK=1`` or
:func:`enable`), in which case they return instrumented locks that:

* report when an acquisition waits longer than ``DEADLOCK_TIMEOUT``
  seconds (go-deadlock's Opts.DeadlockTimeout), dumping every thread's
  stack plus the current holder's acquisition stack to stderr;
* detect same-thread double-acquire of a non-reentrant Mutex
  immediately (the classic self-deadlock), raising ``DeadlockError``.

Zero overhead when disabled — the factory hands out raw
``threading.Lock``/``RLock`` objects, so the hot consensus paths pay
nothing in production. Long-running services construct locks through
this module (consensus state, switch, mempool) so the whole engine
flips with one env var — the analog of rebuilding with ``-tags
deadlock``.

Lock-order sanitizer (``COMETBFT_TPU_LOCK_ORDER=record|enforce``):
every instrumented acquisition also maintains a per-thread stack of
held lock *names* and derives acquisition-order edges (outermost held
name → newly acquired name).  ``record`` accumulates the observed
edges (:func:`observed_lock_order`) so tests can validate them as a
subgraph of the static lock-order graph that cometlint's whole-program
pass (``devtools/lint/graph``) emits; ``enforce`` raises
:class:`LockOrderError` the moment a thread takes an edge absent from
the shipped static graph — static analysis and runtime sanitizer
verifying each other.  Same-name edges are skipped: lock names label
*roles* (every ``Peer`` shares ``p2p.peer._data_mtx``), so a same-name
edge is either a reentrant RLock or an instance-ambiguous hierarchy
hop that neither side can order.  Like deadlock detection, the mode is
read at lock *construction* — flip it (env var or
:func:`set_lock_order_mode`) before building the objects under test.

Lockset sanitizer (``COMETBFT_TPU_LOCKSET=record|enforce``): the
runtime counterpart of the guarded-field pass (CLNT011/012).  Shared
classes carry :func:`lockset_note` calls at a handful of accessor
seams; each call samples ``(Class.field, held-lock names)`` from the
same per-thread held stack the lock-order tier maintains.  ``record``
accumulates the samples (:func:`observed_locksets`) so tests can
assert every runtime sample is consistent with the static
``fieldguards.json`` facts (guard held at the seam, or the field is a
documented ``# lockfree:`` plane); ``enforce`` raises
:class:`LocksetError` at the seam the moment the field's inferred
guard is not fully held.  Like the other tiers, the mode is read at
lock construction — flip it (env var or :func:`set_lockset_mode`)
before building the objects under test.

Contention profiler (``COMETBFT_TPU_LOCKPROF``, libs/lockprof): when NO
diagnostic tier is on, the factories hand out ``_ProfiledMutex`` /
``_ProfiledRLock`` — thin ``__slots__`` wrappers that account every
named lock's acquires, contended acquires, wait and hold time into
libs/lockprof's preallocated per-registry-slot columns.  The enabled
record path retains zero allocations and takes no lock (a non-blocking
probe first; only an acquire that actually blocks pays the timed
path); disabled, one flag check stands between the caller and the raw
primitive.  Waits and holds past the slow threshold emit EV_LOCK
flight-ring rows naming the holder's acquire site.  Unlike the
instrumented tier, profiled locks implement the stdlib save/restore
protocol, so :func:`Condition` keeps the wrapper and waiter
re-acquires stay in the contention ledger.  ``COMETBFT_TPU_LOCKPROF=0``
is the kill switch back to raw ``threading`` primitives.  Both tiers
additionally publish each thread's *blocked-on* lock and wait start
into :func:`held_locks_snapshot` for live starvation diagnosis.
"""

from __future__ import annotations

import os
import sys
import threading
import time
import traceback
import faulthandler

from . import lockprof as _lockprof

DEADLOCK_TIMEOUT = float(os.environ.get("COMETBFT_TPU_DEADLOCK_TIMEOUT", "30"))

_enabled = os.environ.get("COMETBFT_TPU_DEADLOCK") == "1"


def enable(timeout: float | None = None) -> None:
    global _enabled, DEADLOCK_TIMEOUT
    _enabled = True
    if timeout is not None:
        DEADLOCK_TIMEOUT = timeout


def disable() -> None:
    global _enabled
    _enabled = False


def enabled() -> bool:
    return _enabled


class DeadlockError(RuntimeError):
    pass


class LockOrderError(RuntimeError):
    """An acquisition-order edge not present in the static lock-order
    graph was taken under ``COMETBFT_TPU_LOCK_ORDER=enforce``."""


class LocksetError(RuntimeError):
    """A guarded field was accessed without its statically inferred
    guard fully held, under ``COMETBFT_TPU_LOCKSET=enforce``."""


# -------------------------------------------------------- lock ordering

_LOCK_ORDER_MODES = ("off", "record", "enforce")
_order_mode = os.environ.get("COMETBFT_TPU_LOCK_ORDER", "off")
if _order_mode not in _LOCK_ORDER_MODES:
    _order_mode = "off"
_order_graph_path = os.environ.get("COMETBFT_TPU_LOCK_ORDER_GRAPH") or None

_tls = threading.local()  # .held: list[str] of instrumented-lock names
# every thread's held stack, keyed by thread id (the SAME list objects
# the TLS slots hold, registered at first use) — lets the health layer's
# black-box bundle snapshot which locks every thread held at a watchdog
# trip without reaching into foreign TLS
_all_held: dict[int, list] = {}
# every thread's blocked-on cell ``[lock name | None, wait-start ns]``
# (the SAME list objects the TLS slots hold, registered at first use —
# in-place stores keep the record path retention-free): set by a
# contended acquire in the sanitizer AND profiled tiers, cleared when
# the wait resolves, so snapshots can say who is parked on what
_all_blocked: dict[int, list] = {}
# observed (from, to) -> first witness "file:line" of the inner acquire
_observed: dict[tuple[str, str], str] = {}
_observed_mtx = threading.Lock()  # tier-internal meta-lock, never exposed
_allowed_edges: frozenset[tuple[str, str]] | None = None


def set_lock_order_mode(mode: str, graph_path: str | None = None) -> None:
    """Programmatic analog of ``COMETBFT_TPU_LOCK_ORDER`` (tests).
    Only affects locks constructed AFTER the call."""
    global _order_mode, _order_graph_path, _allowed_edges
    if mode not in _LOCK_ORDER_MODES:
        raise ValueError(f"lock-order mode must be one of {_LOCK_ORDER_MODES}")
    _order_mode = mode
    if graph_path is not None:
        _order_graph_path = graph_path
        _allowed_edges = None


def lock_order_mode() -> str:
    return _order_mode


def observed_lock_order() -> dict[tuple[str, str], str]:
    """Snapshot of recorded (outer_name, inner_name) -> witness edges."""
    with _observed_mtx:
        return dict(_observed)


def reset_lock_order() -> None:
    with _observed_mtx:
        _observed.clear()


def _static_graph_path() -> str:
    if _order_graph_path:
        return _order_graph_path
    # the artifact cometlint --graph ships inside the package
    return os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "devtools", "lint", "graph", "lockorder.json",
    )


def _load_allowed_edges() -> frozenset[tuple[str, str]]:
    global _allowed_edges
    if _allowed_edges is None:
        import json

        with open(_static_graph_path(), encoding="utf-8") as f:
            data = json.load(f)
        _allowed_edges = frozenset(
            (e["from"], e["to"]) for e in data.get("edges", [])
        )
    return _allowed_edges


# ------------------------------------------------------------- locksets

_LOCKSET_MODES = ("off", "record", "enforce")
_lockset_mode = os.environ.get("COMETBFT_TPU_LOCKSET", "off")
if _lockset_mode not in _LOCKSET_MODES:
    _lockset_mode = "off"
_lockset_fields_path = os.environ.get("COMETBFT_TPU_LOCKSET_FIELDS") or None

# observed ("Class.field", frozenset(held names)) -> first witness
# "file:line" of the seam
_lockset_observed: dict[tuple[str, frozenset], str] = {}
# (guard frozenset, lockfree) per "Class.field", lazy-loaded from the
# fieldguards artifact
_field_guards: dict[str, tuple[frozenset, bool]] | None = None


def set_lockset_mode(mode: str, fields_path: str | None = None) -> None:
    """Programmatic analog of ``COMETBFT_TPU_LOCKSET`` (tests).  Only
    affects locks constructed AFTER the call — seams themselves read
    the mode live, but the held stacks they sample are only maintained
    by instrumented locks."""
    global _lockset_mode, _lockset_fields_path, _field_guards
    if mode not in _LOCKSET_MODES:
        raise ValueError(f"lockset mode must be one of {_LOCKSET_MODES}")
    _lockset_mode = mode
    if fields_path is not None:
        _lockset_fields_path = fields_path
        _field_guards = None


def lockset_mode() -> str:
    return _lockset_mode


def observed_locksets() -> dict[tuple[str, frozenset], str]:
    """Snapshot of recorded (field, held-names) -> witness samples."""
    with _observed_mtx:
        return dict(_lockset_observed)


def reset_locksets() -> None:
    with _observed_mtx:
        _lockset_observed.clear()


def _fieldguards_path() -> str:
    if _lockset_fields_path:
        return _lockset_fields_path
    # the artifact cometlint --fields ships inside the package
    return os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "devtools", "lint", "graph", "fieldguards.json",
    )


def _load_field_guards() -> dict[str, tuple[frozenset, bool]]:
    global _field_guards
    if _field_guards is None:
        import json

        with open(_fieldguards_path(), encoding="utf-8") as f:
            data = json.load(f)
        _field_guards = {
            f"{e['class']}.{e['field']}": (
                frozenset(e.get("guard", ())),
                bool(e.get("lockfree")),
            )
            for e in data.get("fields", [])
        }
    return _field_guards


def lockset_note(field: str) -> None:
    """Accessor seam for the lockset sanitizer: sample (``field``, the
    calling thread's held instrumented-lock names).  Free when the
    sanitizer is off.  Callers place this INSIDE the critical section
    that the static guard of ``Class.field`` names, so record mode
    reproduces the static facts and enforce mode fails the moment a
    refactor (pipelined heights) drops a guard acquisition."""
    if _lockset_mode == "off":
        return
    held = frozenset(_held_stack())
    if _lockset_mode == "enforce":
        info = _load_field_guards().get(field)
        if info is None:
            raise LocksetError(
                f"lockset seam for unknown field {field!r} — regenerate "
                f"the artifact: python -m cometbft_tpu.devtools.lint "
                f"--fields {_fieldguards_path()}"
            )
        guard, lockfree = info
        if not lockfree and not guard <= held:
            raise LocksetError(
                f"field {field!r} accessed with held locks "
                f"{sorted(held)!r} but its static guard is "
                f"{sorted(guard)!r} ({_fieldguards_path()}); take the "
                f"missing lock(s), or re-run the guarded-field pass if "
                f"the discipline legitimately changed."
            )
    key = (field, held)
    with _observed_mtx:
        if key not in _lockset_observed:
            _lockset_observed[key] = _acquire_site()


def _held_stack() -> list:
    stack = getattr(_tls, "held", None)
    if stack is None:
        stack = _tls.held = []
        with _observed_mtx:
            _all_held[threading.get_ident()] = stack
    return stack


def _blocked_cell() -> list:
    """This thread's preallocated blocked-on cell ``[name | None,
    wait-start ns]`` — registered once, mutated in place thereafter
    (the ``_held_stack`` pattern), so setting/clearing the blocked-on
    marker on a contended acquire retains nothing."""
    cell = getattr(_tls, "blocked", None)
    if cell is None:
        cell = _tls.blocked = [None, 0]
        with _observed_mtx:
            _all_blocked[threading.get_ident()] = cell
    return cell


def held_locks_snapshot() -> dict[int, dict]:
    """Per-thread lock forensics (the health layer's ``locks.json``
    bundle surface and the thread-dump annotations): ``held`` — the
    thread's held instrumented-lock names, populated only while a
    sanitizer tier runs (``COMETBFT_TPU_LOCK_ORDER`` /
    ``COMETBFT_TPU_LOCKSET``; plain production locks keep no held
    stacks) — plus ``blocked_on`` / ``blocked_since_ns`` — the lock the
    thread is parked on right now and the ``monotonic_ns`` its wait
    began, maintained by BOTH the sanitizer and the lockprof profiled
    tiers, so live lock starvation is diagnosable in production.  Dead
    threads are pruned."""
    live = set(sys._current_frames())
    with _observed_mtx:
        for reg in (_all_held, _all_blocked):
            for tid in [t for t in reg if t not in live]:
                del reg[tid]
        out: dict[int, dict] = {}
        for tid in set(_all_held) | set(_all_blocked):
            stack = _all_held.get(tid)
            cell = _all_blocked.get(tid)
            blocked = cell[0] if cell is not None else None
            if not stack and blocked is None:
                continue
            out[tid] = {
                "held": list(stack) if stack else [],
                "blocked_on": blocked,
                "blocked_since_ns": (
                    cell[1] if blocked is not None else None
                ),
            }
        return out


def _acquire_site() -> str:
    """file:line of the engine frame performing the acquire (skips the
    sync-tier frames themselves)."""
    f = sys._getframe(1)
    here = os.path.dirname(os.path.abspath(__file__))
    while f is not None:
        fn = f.f_code.co_filename
        if os.path.join(here, "sync.py") not in fn:
            return f"{fn}:{f.f_lineno}"
        f = f.f_back
    return "?"


def _order_check(name: str) -> None:
    """Enforce-mode gate, called BEFORE the raw acquire so a forbidden
    edge fails fast instead of deadlocking on the inversion itself."""
    stack = _held_stack()
    if not stack or stack[-1] == name:
        return
    edge = (stack[-1], name)
    if edge not in _load_allowed_edges():
        raise LockOrderError(
            f"lock-order edge {edge[0]!r} -> {edge[1]!r} is absent from the "
            f"static lock-order graph ({_static_graph_path()}); held: "
            f"{stack!r}. Re-run `python -m cometbft_tpu.devtools.lint "
            f"--graph` after teaching the analysis about this path, or fix "
            f"the acquisition order."
        )


def _order_note_acquired(name: str) -> None:
    stack = _held_stack()
    if stack and stack[-1] != name:
        edge = (stack[-1], name)
        with _observed_mtx:
            if edge not in _observed:
                _observed[edge] = _acquire_site()
    stack.append(name)


def _order_note_released(name: str) -> None:
    stack = _held_stack()
    for i in range(len(stack) - 1, -1, -1):
        if stack[i] == name:
            del stack[i]
            return


def _dump_all_threads(out=None) -> None:
    out = out or sys.stderr
    try:
        faulthandler.dump_traceback(file=out)
    except Exception:
        for tid, frame in sys._current_frames().items():
            out.write(f"\n--- thread {tid} ---\n")
            traceback.print_stack(frame, file=out)


class _InstrumentedMutex:
    """Non-reentrant lock with waiter timeout + self-deadlock detection."""

    _reentrant = False

    def __init__(self, name: str = ""):
        self._name = name or f"mutex@{id(self):x}"
        self._lock = (
            threading.RLock() if self._reentrant else threading.Lock()
        )
        self._holder: int | None = None
        self._holder_stack: str = ""
        self._depth = 0

    # -- context manager ---------------------------------------------------

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()

    # -- lock protocol -----------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1):
        me = threading.get_ident()
        if not self._reentrant and self._holder == me:
            raise DeadlockError(
                f"self-deadlock: thread {me} re-acquiring {self._name}\n"
                f"first acquired at:\n{self._holder_stack}"
            )
        if _order_mode == "enforce":
            _order_check(self._name)
        if not blocking:
            ok = self._lock.acquire(False)
            if ok:
                self._note_acquired(me)
            return ok
        # threading.Lock semantics: timeout < 0 means wait forever,
        # timeout == 0 is an immediate poll
        if timeout == 0:
            ok = self._lock.acquire(False)
            if ok:
                self._note_acquired(me)
            return ok
        if self._lock.acquire(False):
            self._note_acquired(me)
            return True
        budget = timeout if timeout > 0 else None
        waited = 0.0
        next_report = DEADLOCK_TIMEOUT
        step = min(DEADLOCK_TIMEOUT, 5.0)
        cell = _blocked_cell()
        cell[1] = time.monotonic_ns()
        cell[0] = self._name
        try:
            while True:
                slice_ = (
                    step if budget is None else min(step, budget - waited)
                )
                if slice_ <= 0:
                    return False  # caller's timeout wins, report or not
                if self._lock.acquire(True, slice_):
                    self._note_acquired(me)
                    return True
                waited += slice_
                if waited >= next_report:
                    holder = self._holder
                    sys.stderr.write(
                        f"POSSIBLE DEADLOCK: thread {me} waited "
                        f"{waited:.0f}s for {self._name} "
                        f"(held by thread {holder})\n"
                        f"holder acquired at:\n{self._holder_stack}\n"
                    )
                    _dump_all_threads()
                    # report-and-continue, re-reporting each further
                    # interval (go-deadlock keeps flagging a wedged lock)
                    next_report += DEADLOCK_TIMEOUT
        finally:
            cell[0] = None

    def release(self) -> None:
        me = threading.get_ident()
        if self._reentrant and self._depth > 1:
            self._depth -= 1
        else:
            self._holder = None
            self._holder_stack = ""
            self._depth = 0
            if _order_mode != "off" or _lockset_mode != "off":
                _order_note_released(self._name)
        self._lock.release()

    def locked(self) -> bool:
        if self._reentrant:
            return self._holder is not None
        return self._lock.locked()

    def _note_acquired(self, me: int) -> None:
        if self._reentrant and self._holder == me:
            self._depth += 1
            return
        self._holder = me
        self._depth = 1
        self._holder_stack = "".join(traceback.format_stack(limit=12)[:-2])
        if _order_mode != "off" or _lockset_mode != "off":
            _order_note_acquired(self._name)


class _InstrumentedRLock(_InstrumentedMutex):
    _reentrant = True


# ------------------------------------------------- contention profiling

# A Condition re-acquire below this wait is treated as uncontended:
# unlike the ordinary acquire path there is no non-blocking probe
# available inside the stdlib's _acquire_restore protocol, so a small
# floor keeps every notify->wakeup from counting as a contended acquire
_RESTORE_CONTENDED_NS = 20_000


def _profile_wait(slot: int, wait_ns: int, site_code, site_line) -> None:
    """Bank one contended acquire; past the slow threshold, emit the
    EV_LOCK wait row naming the HOLDER's acquire site (a best-effort
    racy read of the wrapper's site slots — forensics, not bookkeeping:
    the blocker is whoever held the lock while we waited)."""
    _lockprof.note_contended(slot, wait_ns)
    if wait_ns >= _lockprof._slow_ns:
        site = (
            f"{site_code.co_filename}:{site_line}" if site_code else "?"
        )
        _lockprof.note_slow(slot, _lockprof.KIND_WAIT, wait_ns, site)


def _profile_hold(slot: int, hold_ns: int, site_code, site_line) -> None:
    """Bank one completed hold; past the slow threshold, emit the
    EV_LOCK hold row naming our own acquire site."""
    if hold_ns > 0:
        _lockprof._hold_ns[slot] += hold_ns
    if hold_ns >= _lockprof._slow_ns:
        site = (
            f"{site_code.co_filename}:{site_line}" if site_code else "?"
        )
        _lockprof.note_slow(slot, _lockprof.KIND_HOLD, hold_ns, site)


class _ProfiledMutex:
    """Contention-profiled non-reentrant lock (the production tier).

    The record path is allocation- and lock-free: preallocated
    libs/lockprof columns take GIL-atomic scalar stores, the holder
    site is kept as a code-object reference plus a line int in
    ``__slots__`` (formatted to a string only on the EV_LOCK slow
    path), and the acquire timestamp lives in a slot whose int is
    simply replaced each acquire.  Disabled, a single flag check
    stands between the caller and the raw primitive.
    """

    __slots__ = (
        "_name", "_slot", "_lock", "_t_acq", "_site_code", "_site_line",
    )

    def __init__(self, name: str = ""):
        self._name = name or f"mutex@{id(self):x}"
        self._slot = _lockprof.slot_for(self._name)
        self._lock = threading.Lock()
        self._t_acq = 0
        self._site_code = None
        self._site_line = 0

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()

    def _stamp(self) -> None:
        # the engine frame performing the acquire: skip this module's
        # own frames (acquire/__enter__) and threading.py's Condition
        # plumbing — identity-cheap co_filename membership checks
        f = sys._getframe(1)
        while f is not None and f.f_code.co_filename in _SKIP_SITE_FILES:
            f = f.f_back
        if f is not None:
            self._site_code = f.f_code
            self._site_line = f.f_lineno
        self._t_acq = time.monotonic_ns()

    def acquire(self, blocking: bool = True, timeout: float = -1):
        lock = self._lock
        if not _lockprof._enabled:
            return lock.acquire(blocking, timeout)
        slot = self._slot
        if lock.acquire(False):  # uncontended fast path: zero wait
            _lockprof._acquires[slot] += 1
            self._stamp()
            return True
        if not blocking or timeout == 0:
            return False
        cell = _blocked_cell()
        t0 = time.monotonic_ns()
        cell[1] = t0
        cell[0] = self._name
        try:
            ok = lock.acquire(True, timeout)
        finally:
            cell[0] = None
        wait = time.monotonic_ns() - t0
        # read the holder's site BEFORE stamping our own: the blocker
        # we waited behind is the one worth naming in the ring
        _profile_wait(slot, wait, self._site_code, self._site_line)
        if ok:
            _lockprof._acquires[slot] += 1
            self._stamp()
        return ok

    def release(self) -> None:
        t0 = self._t_acq
        if t0:
            self._t_acq = 0
            if _lockprof._enabled:
                _profile_hold(
                    self._slot, time.monotonic_ns() - t0,
                    self._site_code, self._site_line,
                )
        self._lock.release()

    def locked(self) -> bool:
        return self._lock.locked()

    def _is_owned(self):
        # Condition's ownership sanity probe — bypasses the ledger (a
        # probe is not an acquire); release/acquire during wait() go
        # through the profiled methods and stay accounted
        if self._lock.acquire(False):
            self._lock.release()
            return False
        return True


class _ProfiledRLock:
    """Contention-profiled reentrant lock.  ``_depth`` (owner-thread
    mutated, so race-free) marks the outermost acquire/release pair:
    hold time spans the whole reentrant session, and reentrant
    re-acquires never count as contention.  Implements the stdlib
    save/restore protocol by delegating to the inner C RLock, so a
    Condition keeps the wrapper and waiter re-acquires stay in the
    ledger."""

    __slots__ = (
        "_name", "_slot", "_lock", "_depth", "_t_acq",
        "_site_code", "_site_line",
    )

    def __init__(self, name: str = ""):
        self._name = name or f"rlock@{id(self):x}"
        self._slot = _lockprof.slot_for(self._name)
        self._lock = threading.RLock()
        self._depth = 0
        self._t_acq = 0
        self._site_code = None
        self._site_line = 0

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()

    def _stamp(self) -> None:
        f = sys._getframe(1)
        while f is not None and f.f_code.co_filename in _SKIP_SITE_FILES:
            f = f.f_back
        if f is not None:
            self._site_code = f.f_code
            self._site_line = f.f_lineno
        self._t_acq = time.monotonic_ns()

    def acquire(self, blocking: bool = True, timeout: float = -1):
        lock = self._lock
        if not _lockprof._enabled or lock._is_owned():
            ok = lock.acquire(blocking, timeout)
            if ok:
                self._depth += 1
            return ok
        slot = self._slot
        if lock.acquire(False):  # uncontended fast path: zero wait
            self._depth += 1
            _lockprof._acquires[slot] += 1
            self._stamp()
            return True
        if not blocking or timeout == 0:
            return False
        cell = _blocked_cell()
        t0 = time.monotonic_ns()
        cell[1] = t0
        cell[0] = self._name
        try:
            ok = lock.acquire(True, timeout)
        finally:
            cell[0] = None
        wait = time.monotonic_ns() - t0
        _profile_wait(slot, wait, self._site_code, self._site_line)
        if ok:
            self._depth += 1
            _lockprof._acquires[slot] += 1
            self._stamp()
        return ok

    def release(self) -> None:
        d = self._depth
        if d <= 1:
            self._depth = 0
            t0 = self._t_acq
            if t0:
                self._t_acq = 0
                if _lockprof._enabled:
                    _profile_hold(
                        self._slot, time.monotonic_ns() - t0,
                        self._site_code, self._site_line,
                    )
        else:
            self._depth = d - 1
        self._lock.release()

    def locked(self) -> bool:
        return self._depth > 0

    # -- stdlib Condition save/restore protocol ---------------------------

    def _is_owned(self):
        return self._lock._is_owned()

    def _release_save(self):
        d = self._depth
        self._depth = 0
        t0 = self._t_acq
        if t0:
            self._t_acq = 0
            if _lockprof._enabled:
                _profile_hold(
                    self._slot, time.monotonic_ns() - t0,
                    self._site_code, self._site_line,
                )
        return (self._lock._release_save(), d)

    def _acquire_restore(self, state):
        inner, d = state
        if not _lockprof._enabled:
            self._lock._acquire_restore(inner)
            self._depth = d
            return
        slot = self._slot
        cell = _blocked_cell()
        t0 = time.monotonic_ns()
        cell[1] = t0
        cell[0] = self._name
        try:
            self._lock._acquire_restore(inner)
        finally:
            cell[0] = None
        wait = time.monotonic_ns() - t0
        _lockprof._acquires[slot] += 1
        if wait >= _RESTORE_CONTENDED_NS:
            _profile_wait(slot, wait, self._site_code, self._site_line)
        self._depth = d
        # keep the pre-wait acquire site: attribution names the frame
        # that entered the critical section, not threading.Condition
        self._t_acq = time.monotonic_ns()


# co_filename values the acquire-site walk skips (this module's frames
# and threading.py's Condition plumbing) — identity-stable strings, so
# the frozenset membership test on the hot stamp path is one hash probe
_SKIP_SITE_FILES = frozenset({
    _ProfiledMutex._stamp.__code__.co_filename,
    threading.Condition.wait.__code__.co_filename,
})


def _profiling_constructed() -> bool:
    """Whether the factories hand out profiled locks right now: no
    diagnostic tier active (those take precedence — their wrappers
    carry the held stacks and self-deadlock checks) and the lockprof
    kill switch not set.  Read at lock CONSTRUCTION, like the
    sanitizer modes."""
    return (
        not _enabled
        and _order_mode == "off"
        and _lockset_mode == "off"
        and _lockprof._env_mode() != "off"
    )


def Mutex(name: str = ""):
    """A non-reentrant lock; instrumented when deadlock detection or a
    sanitizer (lock-order or lockset) is on, contention-profiled
    (libs/lockprof) otherwise unless ``COMETBFT_TPU_LOCKPROF=0``."""
    if _enabled or _order_mode != "off" or _lockset_mode != "off":
        return _InstrumentedMutex(name)
    if _lockprof._env_mode() != "off":
        return _ProfiledMutex(name)
    return threading.Lock()


def RLock(name: str = ""):
    """A reentrant lock; instrumented when deadlock detection or a
    sanitizer (lock-order or lockset) is on, contention-profiled
    (libs/lockprof) otherwise unless ``COMETBFT_TPU_LOCKPROF=0``."""
    if _enabled or _order_mode != "off" or _lockset_mode != "off":
        return _InstrumentedRLock(name)
    if _lockprof._env_mode() != "off":
        return _ProfiledRLock(name)
    return threading.RLock()


def Condition(lock=None, name: str = ""):
    """A condition variable routed through the sync tier.

    Conditions are not instrumented by the DIAGNOSTIC tiers: ``wait()``
    must release and re-acquire the underlying primitive with the
    stdlib's exact save/restore protocol, which the instrumented
    wrappers deliberately don't implement (their non-reentrant
    self-deadlock check would misfire inside ``Condition._is_owned``).
    When handed an instrumented Mutex/RLock the raw lock is unwrapped,
    so waiters remain visible to the deadlock tier through every
    ordinary ``acquire`` on the associated mutex; only the wait/notify
    edge itself is uninstrumented.

    The PROFILED tier does implement the protocol, so a profiled lock
    is kept as-is — and a bare ``Condition(name=...)`` gets a profiled
    RLock under the condition's registry name, putting waiter
    re-acquires in the contention ledger too.
    """
    if isinstance(lock, _InstrumentedMutex):
        lock = lock._lock
    elif lock is None and _profiling_constructed():
        lock = _ProfiledRLock(name)
    return threading.Condition(lock)
