"""Deadlock-detecting mutex tier (reference: libs/sync/deadlock.go —
the ``deadlock`` build tag swaps every mutex for sasha-s/go-deadlock).

``Mutex()`` / ``RLock()`` return plain ``threading`` primitives unless
deadlock detection is enabled (env ``COMETBFT_TPU_DEADLOCK=1`` or
:func:`enable`), in which case they return instrumented locks that:

* report when an acquisition waits longer than ``DEADLOCK_TIMEOUT``
  seconds (go-deadlock's Opts.DeadlockTimeout), dumping every thread's
  stack plus the current holder's acquisition stack to stderr;
* detect same-thread double-acquire of a non-reentrant Mutex
  immediately (the classic self-deadlock), raising ``DeadlockError``.

Zero overhead when disabled — the factory hands out raw
``threading.Lock``/``RLock`` objects, so the hot consensus paths pay
nothing in production. Long-running services construct locks through
this module (consensus state, switch, mempool) so the whole engine
flips with one env var — the analog of rebuilding with ``-tags
deadlock``.

Lock-order sanitizer (``COMETBFT_TPU_LOCK_ORDER=record|enforce``):
every instrumented acquisition also maintains a per-thread stack of
held lock *names* and derives acquisition-order edges (outermost held
name → newly acquired name).  ``record`` accumulates the observed
edges (:func:`observed_lock_order`) so tests can validate them as a
subgraph of the static lock-order graph that cometlint's whole-program
pass (``devtools/lint/graph``) emits; ``enforce`` raises
:class:`LockOrderError` the moment a thread takes an edge absent from
the shipped static graph — static analysis and runtime sanitizer
verifying each other.  Same-name edges are skipped: lock names label
*roles* (every ``Peer`` shares ``p2p.peer._data_mtx``), so a same-name
edge is either a reentrant RLock or an instance-ambiguous hierarchy
hop that neither side can order.  Like deadlock detection, the mode is
read at lock *construction* — flip it (env var or
:func:`set_lock_order_mode`) before building the objects under test.

Lockset sanitizer (``COMETBFT_TPU_LOCKSET=record|enforce``): the
runtime counterpart of the guarded-field pass (CLNT011/012).  Shared
classes carry :func:`lockset_note` calls at a handful of accessor
seams; each call samples ``(Class.field, held-lock names)`` from the
same per-thread held stack the lock-order tier maintains.  ``record``
accumulates the samples (:func:`observed_locksets`) so tests can
assert every runtime sample is consistent with the static
``fieldguards.json`` facts (guard held at the seam, or the field is a
documented ``# lockfree:`` plane); ``enforce`` raises
:class:`LocksetError` at the seam the moment the field's inferred
guard is not fully held.  Like the other tiers, the mode is read at
lock construction — flip it (env var or :func:`set_lockset_mode`)
before building the objects under test.
"""

from __future__ import annotations

import os
import sys
import threading
import traceback
import faulthandler

DEADLOCK_TIMEOUT = float(os.environ.get("COMETBFT_TPU_DEADLOCK_TIMEOUT", "30"))

_enabled = os.environ.get("COMETBFT_TPU_DEADLOCK") == "1"


def enable(timeout: float | None = None) -> None:
    global _enabled, DEADLOCK_TIMEOUT
    _enabled = True
    if timeout is not None:
        DEADLOCK_TIMEOUT = timeout


def disable() -> None:
    global _enabled
    _enabled = False


def enabled() -> bool:
    return _enabled


class DeadlockError(RuntimeError):
    pass


class LockOrderError(RuntimeError):
    """An acquisition-order edge not present in the static lock-order
    graph was taken under ``COMETBFT_TPU_LOCK_ORDER=enforce``."""


class LocksetError(RuntimeError):
    """A guarded field was accessed without its statically inferred
    guard fully held, under ``COMETBFT_TPU_LOCKSET=enforce``."""


# -------------------------------------------------------- lock ordering

_LOCK_ORDER_MODES = ("off", "record", "enforce")
_order_mode = os.environ.get("COMETBFT_TPU_LOCK_ORDER", "off")
if _order_mode not in _LOCK_ORDER_MODES:
    _order_mode = "off"
_order_graph_path = os.environ.get("COMETBFT_TPU_LOCK_ORDER_GRAPH") or None

_tls = threading.local()  # .held: list[str] of instrumented-lock names
# every thread's held stack, keyed by thread id (the SAME list objects
# the TLS slots hold, registered at first use) — lets the health layer's
# black-box bundle snapshot which locks every thread held at a watchdog
# trip without reaching into foreign TLS
_all_held: dict[int, list] = {}
# observed (from, to) -> first witness "file:line" of the inner acquire
_observed: dict[tuple[str, str], str] = {}
_observed_mtx = threading.Lock()  # tier-internal meta-lock, never exposed
_allowed_edges: frozenset[tuple[str, str]] | None = None


def set_lock_order_mode(mode: str, graph_path: str | None = None) -> None:
    """Programmatic analog of ``COMETBFT_TPU_LOCK_ORDER`` (tests).
    Only affects locks constructed AFTER the call."""
    global _order_mode, _order_graph_path, _allowed_edges
    if mode not in _LOCK_ORDER_MODES:
        raise ValueError(f"lock-order mode must be one of {_LOCK_ORDER_MODES}")
    _order_mode = mode
    if graph_path is not None:
        _order_graph_path = graph_path
        _allowed_edges = None


def lock_order_mode() -> str:
    return _order_mode


def observed_lock_order() -> dict[tuple[str, str], str]:
    """Snapshot of recorded (outer_name, inner_name) -> witness edges."""
    with _observed_mtx:
        return dict(_observed)


def reset_lock_order() -> None:
    with _observed_mtx:
        _observed.clear()


def _static_graph_path() -> str:
    if _order_graph_path:
        return _order_graph_path
    # the artifact cometlint --graph ships inside the package
    return os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "devtools", "lint", "graph", "lockorder.json",
    )


def _load_allowed_edges() -> frozenset[tuple[str, str]]:
    global _allowed_edges
    if _allowed_edges is None:
        import json

        with open(_static_graph_path(), encoding="utf-8") as f:
            data = json.load(f)
        _allowed_edges = frozenset(
            (e["from"], e["to"]) for e in data.get("edges", [])
        )
    return _allowed_edges


# ------------------------------------------------------------- locksets

_LOCKSET_MODES = ("off", "record", "enforce")
_lockset_mode = os.environ.get("COMETBFT_TPU_LOCKSET", "off")
if _lockset_mode not in _LOCKSET_MODES:
    _lockset_mode = "off"
_lockset_fields_path = os.environ.get("COMETBFT_TPU_LOCKSET_FIELDS") or None

# observed ("Class.field", frozenset(held names)) -> first witness
# "file:line" of the seam
_lockset_observed: dict[tuple[str, frozenset], str] = {}
# (guard frozenset, lockfree) per "Class.field", lazy-loaded from the
# fieldguards artifact
_field_guards: dict[str, tuple[frozenset, bool]] | None = None


def set_lockset_mode(mode: str, fields_path: str | None = None) -> None:
    """Programmatic analog of ``COMETBFT_TPU_LOCKSET`` (tests).  Only
    affects locks constructed AFTER the call — seams themselves read
    the mode live, but the held stacks they sample are only maintained
    by instrumented locks."""
    global _lockset_mode, _lockset_fields_path, _field_guards
    if mode not in _LOCKSET_MODES:
        raise ValueError(f"lockset mode must be one of {_LOCKSET_MODES}")
    _lockset_mode = mode
    if fields_path is not None:
        _lockset_fields_path = fields_path
        _field_guards = None


def lockset_mode() -> str:
    return _lockset_mode


def observed_locksets() -> dict[tuple[str, frozenset], str]:
    """Snapshot of recorded (field, held-names) -> witness samples."""
    with _observed_mtx:
        return dict(_lockset_observed)


def reset_locksets() -> None:
    with _observed_mtx:
        _lockset_observed.clear()


def _fieldguards_path() -> str:
    if _lockset_fields_path:
        return _lockset_fields_path
    # the artifact cometlint --fields ships inside the package
    return os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "devtools", "lint", "graph", "fieldguards.json",
    )


def _load_field_guards() -> dict[str, tuple[frozenset, bool]]:
    global _field_guards
    if _field_guards is None:
        import json

        with open(_fieldguards_path(), encoding="utf-8") as f:
            data = json.load(f)
        _field_guards = {
            f"{e['class']}.{e['field']}": (
                frozenset(e.get("guard", ())),
                bool(e.get("lockfree")),
            )
            for e in data.get("fields", [])
        }
    return _field_guards


def lockset_note(field: str) -> None:
    """Accessor seam for the lockset sanitizer: sample (``field``, the
    calling thread's held instrumented-lock names).  Free when the
    sanitizer is off.  Callers place this INSIDE the critical section
    that the static guard of ``Class.field`` names, so record mode
    reproduces the static facts and enforce mode fails the moment a
    refactor (pipelined heights) drops a guard acquisition."""
    if _lockset_mode == "off":
        return
    held = frozenset(_held_stack())
    if _lockset_mode == "enforce":
        info = _load_field_guards().get(field)
        if info is None:
            raise LocksetError(
                f"lockset seam for unknown field {field!r} — regenerate "
                f"the artifact: python -m cometbft_tpu.devtools.lint "
                f"--fields {_fieldguards_path()}"
            )
        guard, lockfree = info
        if not lockfree and not guard <= held:
            raise LocksetError(
                f"field {field!r} accessed with held locks "
                f"{sorted(held)!r} but its static guard is "
                f"{sorted(guard)!r} ({_fieldguards_path()}); take the "
                f"missing lock(s), or re-run the guarded-field pass if "
                f"the discipline legitimately changed."
            )
    key = (field, held)
    with _observed_mtx:
        if key not in _lockset_observed:
            _lockset_observed[key] = _acquire_site()


def _held_stack() -> list:
    stack = getattr(_tls, "held", None)
    if stack is None:
        stack = _tls.held = []
        with _observed_mtx:
            _all_held[threading.get_ident()] = stack
    return stack


def held_locks_snapshot() -> dict[int, list[str]]:
    """Per-thread held instrumented-lock names (crash-forensics surface
    for the health layer's black-box bundle).  Only populated while the
    lock-order sanitizer runs (``COMETBFT_TPU_LOCK_ORDER``) — plain
    production locks keep no held stacks.  Dead threads are pruned."""
    live = set(sys._current_frames())
    with _observed_mtx:
        for tid in [t for t in _all_held if t not in live]:
            del _all_held[tid]
        return {
            tid: list(stack) for tid, stack in _all_held.items() if stack
        }


def _acquire_site() -> str:
    """file:line of the engine frame performing the acquire (skips the
    sync-tier frames themselves)."""
    f = sys._getframe(1)
    here = os.path.dirname(os.path.abspath(__file__))
    while f is not None:
        fn = f.f_code.co_filename
        if os.path.join(here, "sync.py") not in fn:
            return f"{fn}:{f.f_lineno}"
        f = f.f_back
    return "?"


def _order_check(name: str) -> None:
    """Enforce-mode gate, called BEFORE the raw acquire so a forbidden
    edge fails fast instead of deadlocking on the inversion itself."""
    stack = _held_stack()
    if not stack or stack[-1] == name:
        return
    edge = (stack[-1], name)
    if edge not in _load_allowed_edges():
        raise LockOrderError(
            f"lock-order edge {edge[0]!r} -> {edge[1]!r} is absent from the "
            f"static lock-order graph ({_static_graph_path()}); held: "
            f"{stack!r}. Re-run `python -m cometbft_tpu.devtools.lint "
            f"--graph` after teaching the analysis about this path, or fix "
            f"the acquisition order."
        )


def _order_note_acquired(name: str) -> None:
    stack = _held_stack()
    if stack and stack[-1] != name:
        edge = (stack[-1], name)
        with _observed_mtx:
            if edge not in _observed:
                _observed[edge] = _acquire_site()
    stack.append(name)


def _order_note_released(name: str) -> None:
    stack = _held_stack()
    for i in range(len(stack) - 1, -1, -1):
        if stack[i] == name:
            del stack[i]
            return


def _dump_all_threads(out=None) -> None:
    out = out or sys.stderr
    try:
        faulthandler.dump_traceback(file=out)
    except Exception:
        for tid, frame in sys._current_frames().items():
            out.write(f"\n--- thread {tid} ---\n")
            traceback.print_stack(frame, file=out)


class _InstrumentedMutex:
    """Non-reentrant lock with waiter timeout + self-deadlock detection."""

    _reentrant = False

    def __init__(self, name: str = ""):
        self._name = name or f"mutex@{id(self):x}"
        self._lock = (
            threading.RLock() if self._reentrant else threading.Lock()
        )
        self._holder: int | None = None
        self._holder_stack: str = ""
        self._depth = 0

    # -- context manager ---------------------------------------------------

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()

    # -- lock protocol -----------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1):
        me = threading.get_ident()
        if not self._reentrant and self._holder == me:
            raise DeadlockError(
                f"self-deadlock: thread {me} re-acquiring {self._name}\n"
                f"first acquired at:\n{self._holder_stack}"
            )
        if _order_mode == "enforce":
            _order_check(self._name)
        if not blocking:
            ok = self._lock.acquire(False)
            if ok:
                self._note_acquired(me)
            return ok
        # threading.Lock semantics: timeout < 0 means wait forever,
        # timeout == 0 is an immediate poll
        if timeout == 0:
            ok = self._lock.acquire(False)
            if ok:
                self._note_acquired(me)
            return ok
        budget = timeout if timeout > 0 else None
        waited = 0.0
        next_report = DEADLOCK_TIMEOUT
        step = min(DEADLOCK_TIMEOUT, 5.0)
        while True:
            slice_ = step if budget is None else min(step, budget - waited)
            if slice_ <= 0:
                return False  # caller's timeout wins, report or not
            if self._lock.acquire(True, slice_):
                self._note_acquired(me)
                return True
            waited += slice_
            if waited >= next_report:
                holder = self._holder
                sys.stderr.write(
                    f"POSSIBLE DEADLOCK: thread {me} waited "
                    f"{waited:.0f}s for {self._name} "
                    f"(held by thread {holder})\n"
                    f"holder acquired at:\n{self._holder_stack}\n"
                )
                _dump_all_threads()
                # report-and-continue, re-reporting each further interval
                # (go-deadlock keeps flagging a wedged lock)
                next_report += DEADLOCK_TIMEOUT

    def release(self) -> None:
        me = threading.get_ident()
        if self._reentrant and self._depth > 1:
            self._depth -= 1
        else:
            self._holder = None
            self._holder_stack = ""
            self._depth = 0
            if _order_mode != "off" or _lockset_mode != "off":
                _order_note_released(self._name)
        self._lock.release()

    def locked(self) -> bool:
        if self._reentrant:
            return self._holder is not None
        return self._lock.locked()

    def _note_acquired(self, me: int) -> None:
        if self._reentrant and self._holder == me:
            self._depth += 1
            return
        self._holder = me
        self._depth = 1
        self._holder_stack = "".join(traceback.format_stack(limit=12)[:-2])
        if _order_mode != "off" or _lockset_mode != "off":
            _order_note_acquired(self._name)


class _InstrumentedRLock(_InstrumentedMutex):
    _reentrant = True


def Mutex(name: str = ""):
    """A non-reentrant lock; instrumented when deadlock detection or a
    sanitizer (lock-order or lockset) is on."""
    if _enabled or _order_mode != "off" or _lockset_mode != "off":
        return _InstrumentedMutex(name)
    return threading.Lock()


def RLock(name: str = ""):
    """A reentrant lock; instrumented when deadlock detection or a
    sanitizer (lock-order or lockset) is on."""
    if _enabled or _order_mode != "off" or _lockset_mode != "off":
        return _InstrumentedRLock(name)
    return threading.RLock()


def Condition(lock=None, name: str = ""):
    """A condition variable routed through the sync tier.

    Conditions are not themselves instrumented: ``wait()`` must release
    and re-acquire the underlying primitive with the stdlib's exact
    save/restore protocol, which the instrumented wrappers deliberately
    don't implement (their non-reentrant self-deadlock check would
    misfire inside ``Condition._is_owned``). When handed an
    instrumented Mutex/RLock the raw lock is unwrapped, so waiters
    remain visible to the deadlock tier through every ordinary
    ``acquire`` on the associated mutex; only the wait/notify edge
    itself is uninstrumented.
    """
    if isinstance(lock, _InstrumentedMutex):
        lock = lock._lock
    return threading.Condition(lock)
