"""Deadlock-detecting mutex tier (reference: libs/sync/deadlock.go —
the ``deadlock`` build tag swaps every mutex for sasha-s/go-deadlock).

``Mutex()`` / ``RLock()`` return plain ``threading`` primitives unless
deadlock detection is enabled (env ``COMETBFT_TPU_DEADLOCK=1`` or
:func:`enable`), in which case they return instrumented locks that:

* report when an acquisition waits longer than ``DEADLOCK_TIMEOUT``
  seconds (go-deadlock's Opts.DeadlockTimeout), dumping every thread's
  stack plus the current holder's acquisition stack to stderr;
* detect same-thread double-acquire of a non-reentrant Mutex
  immediately (the classic self-deadlock), raising ``DeadlockError``.

Zero overhead when disabled — the factory hands out raw
``threading.Lock``/``RLock`` objects, so the hot consensus paths pay
nothing in production. Long-running services construct locks through
this module (consensus state, switch, mempool) so the whole engine
flips with one env var — the analog of rebuilding with ``-tags
deadlock``.
"""

from __future__ import annotations

import os
import sys
import threading
import traceback
import faulthandler

DEADLOCK_TIMEOUT = float(os.environ.get("COMETBFT_TPU_DEADLOCK_TIMEOUT", "30"))

_enabled = os.environ.get("COMETBFT_TPU_DEADLOCK") == "1"


def enable(timeout: float | None = None) -> None:
    global _enabled, DEADLOCK_TIMEOUT
    _enabled = True
    if timeout is not None:
        DEADLOCK_TIMEOUT = timeout


def disable() -> None:
    global _enabled
    _enabled = False


def enabled() -> bool:
    return _enabled


class DeadlockError(RuntimeError):
    pass


def _dump_all_threads(out=None) -> None:
    out = out or sys.stderr
    try:
        faulthandler.dump_traceback(file=out)
    except Exception:
        for tid, frame in sys._current_frames().items():
            out.write(f"\n--- thread {tid} ---\n")
            traceback.print_stack(frame, file=out)


class _InstrumentedMutex:
    """Non-reentrant lock with waiter timeout + self-deadlock detection."""

    _reentrant = False

    def __init__(self, name: str = ""):
        self._name = name or f"mutex@{id(self):x}"
        self._lock = (
            threading.RLock() if self._reentrant else threading.Lock()
        )
        self._holder: int | None = None
        self._holder_stack: str = ""
        self._depth = 0

    # -- context manager ---------------------------------------------------

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()

    # -- lock protocol -----------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1):
        me = threading.get_ident()
        if not self._reentrant and self._holder == me:
            raise DeadlockError(
                f"self-deadlock: thread {me} re-acquiring {self._name}\n"
                f"first acquired at:\n{self._holder_stack}"
            )
        if not blocking:
            ok = self._lock.acquire(False)
            if ok:
                self._note_acquired(me)
            return ok
        # threading.Lock semantics: timeout < 0 means wait forever,
        # timeout == 0 is an immediate poll
        if timeout == 0:
            ok = self._lock.acquire(False)
            if ok:
                self._note_acquired(me)
            return ok
        budget = timeout if timeout > 0 else None
        waited = 0.0
        next_report = DEADLOCK_TIMEOUT
        step = min(DEADLOCK_TIMEOUT, 5.0)
        while True:
            slice_ = step if budget is None else min(step, budget - waited)
            if slice_ <= 0:
                return False  # caller's timeout wins, report or not
            if self._lock.acquire(True, slice_):
                self._note_acquired(me)
                return True
            waited += slice_
            if waited >= next_report:
                holder = self._holder
                sys.stderr.write(
                    f"POSSIBLE DEADLOCK: thread {me} waited "
                    f"{waited:.0f}s for {self._name} "
                    f"(held by thread {holder})\n"
                    f"holder acquired at:\n{self._holder_stack}\n"
                )
                _dump_all_threads()
                # report-and-continue, re-reporting each further interval
                # (go-deadlock keeps flagging a wedged lock)
                next_report += DEADLOCK_TIMEOUT

    def release(self) -> None:
        me = threading.get_ident()
        if self._reentrant and self._depth > 1:
            self._depth -= 1
        else:
            self._holder = None
            self._holder_stack = ""
            self._depth = 0
        self._lock.release()

    def locked(self) -> bool:
        if self._reentrant:
            return self._holder is not None
        return self._lock.locked()

    def _note_acquired(self, me: int) -> None:
        if self._reentrant and self._holder == me:
            self._depth += 1
            return
        self._holder = me
        self._depth = 1
        self._holder_stack = "".join(traceback.format_stack(limit=12)[:-2])


class _InstrumentedRLock(_InstrumentedMutex):
    _reentrant = True


def Mutex(name: str = ""):
    """A non-reentrant lock; instrumented when deadlock detection is on."""
    return _InstrumentedMutex(name) if _enabled else threading.Lock()


def RLock(name: str = ""):
    """A reentrant lock; instrumented when deadlock detection is on."""
    return _InstrumentedRLock(name) if _enabled else threading.RLock()


def Condition(lock=None, name: str = ""):
    """A condition variable routed through the sync tier.

    Conditions are not themselves instrumented: ``wait()`` must release
    and re-acquire the underlying primitive with the stdlib's exact
    save/restore protocol, which the instrumented wrappers deliberately
    don't implement (their non-reentrant self-deadlock check would
    misfire inside ``Condition._is_owned``). When handed an
    instrumented Mutex/RLock the raw lock is unwrapped, so waiters
    remain visible to the deadlock tier through every ordinary
    ``acquire`` on the associated mutex; only the wait/notify edge
    itself is uninstrumented.
    """
    if isinstance(lock, _InstrumentedMutex):
        lock = lock._lock
    return threading.Condition(lock)
