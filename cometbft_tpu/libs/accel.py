"""Process-wide accelerator-backend probe.

One answer to "is jax's default backend an accelerator?", shared by
every auto-mode gate (the verify coalescer's device windows, the node's
coalescer boot decision, the adaptive host/device crossover) so the
gates can never disagree within a process and a new platform string is
added in exactly one place.

``jax.default_backend()`` initializes an XLA backend, which a host-only
node may otherwise never pay for (seconds of import + backend init).
When ``JAX_PLATFORMS`` pins a host-only platform set — every CPU test
run does — the probe answers False without importing jax at all; only
an unpinned environment (where a device may genuinely exist) pays the
probe, once per process.
"""

from __future__ import annotations

import os
import sys

ACCELERATOR_BACKENDS = ("tpu", "axon")

_probe: bool | None = None
_live_peek_warned = False


def _host_only_pinned() -> bool:
    """True when JAX_PLATFORMS pins a platform set with no accelerator
    in it — the one parse both probes share."""
    plats = os.environ.get("JAX_PLATFORMS", "")
    return bool(plats) and not any(
        p.strip().lower() in ACCELERATOR_BACKENDS for p in plats.split(",")
    )


def accelerator_backend() -> bool:
    """True when jax's default backend is an accelerator (cached)."""
    global _probe
    if _probe is None:
        if _host_only_pinned():
            _probe = False
        else:
            try:
                import jax

                _probe = jax.default_backend() in ACCELERATOR_BACKENDS
            except Exception:
                _probe = False
    return _probe


def accelerator_backend_live() -> bool:
    """True when an accelerator backend is ALREADY initialized in this
    process. NEVER triggers backend init, so it is safe on hot paths
    and on hosts with a dead device tunnel (where ``default_backend()``
    would hang in PJRT init). Steady-state gates (the adaptive
    crossover, the coalescer's per-window device check) use this: a
    process that never initialized an accelerator has, by construction,
    no device work to route or calibrate — the node's boot-time
    :func:`accelerator_backend` probe is what brings the backend up on
    accelerator deployments.
    """
    if _host_only_pinned():
        return False
    jax = sys.modules.get("jax")
    if jax is None:
        return False
    try:
        # peek at initialized backends only — xla_bridge populates
        # _backends as platforms come up; reading it never inits one
        backends = getattr(jax._src.xla_bridge, "_backends", None) or {}
        return any(name in ACCELERATOR_BACKENDS for name in backends)
    except Exception:
        # a jax relayout that moves _backends must not SILENTLY retire
        # device windows and the adaptive crossover on accelerator
        # deployments — flag it once, then degrade to host
        global _live_peek_warned
        if not _live_peek_warned:
            _live_peek_warned = True
            import logging

            logging.getLogger(__name__).warning(
                "accelerator liveness peek failed (jax internals moved?);"
                " treating the process as host-only: device verify"
                " windows and adaptive-crossover calibration are disabled",
                exc_info=True,
            )
        return False
