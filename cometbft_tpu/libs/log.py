"""Leveled structured key-value logging (reference: libs/log/).

Mirrors the reference's go-kit style: loggers carry bound fields
(``with_fields``), emit ``tmfmt``-like lines
(``I[2026-07-30|00:00:00.000] message        module=consensus height=5``),
and a per-module level filter (libs/log/filter.go) gates output so one
chatty module can be silenced without losing error visibility.

The default sink is stderr; a node wires a file sink via config. Writes
are mutex-serialized — log lines from 20 threads must not interleave.
"""

from __future__ import annotations

import sys
import threading
from . import sync as libsync
import time

DEBUG, INFO, ERROR, NONE = 0, 1, 2, 3
_LEVEL_CHAR = {DEBUG: "D", INFO: "I", ERROR: "E"}
_LEVEL_BY_NAME = {
    "debug": DEBUG,
    "info": INFO,
    "error": ERROR,
    "none": NONE,
}


def parse_level(name: str) -> int:
    try:
        return _LEVEL_BY_NAME[name.strip().lower()]
    except KeyError:
        raise ValueError(f"unknown log level {name!r}")


class Logger:
    """A sink + bound fields + level filter. Cheap to derive, safe to
    share across threads."""

    def __init__(
        self,
        sink=None,
        level: int = INFO,
        fields: dict | None = None,
        module_levels: dict[str, int] | None = None,
        _lock: threading.Lock | None = None,
    ):
        self._sink = sink if sink is not None else sys.stderr
        self._level = level
        self._fields = dict(fields or {})
        # SHARED (like _lock) so set_module_level on any derived logger
        # affects the whole tree — the 'silence one module' use case
        self._module_levels = (
            module_levels if module_levels is not None else {}
        )
        self._lock = _lock if _lock is not None else libsync.Mutex("libs.log")

    # -- derivation --------------------------------------------------------

    def with_fields(self, **fields) -> "Logger":
        merged = dict(self._fields)
        merged.update(fields)
        return Logger(
            self._sink, self._level, merged, self._module_levels, self._lock
        )

    def with_module(self, module: str) -> "Logger":
        return self.with_fields(module=module)

    def set_module_level(self, module: str, level: int) -> None:
        """Per-module override (libs/log/filter.go AllowLevelWith)."""
        self._module_levels[module] = level

    # -- emission ----------------------------------------------------------

    def _enabled(self, level: int) -> bool:
        module = self._fields.get("module")
        threshold = self._module_levels.get(module, self._level)
        return level >= threshold and level != NONE

    def _emit(self, level: int, msg: str, kv: dict) -> None:
        if not self._enabled(level):
            return
        now = time.time()
        stamp = time.strftime("%Y-%m-%d|%H:%M:%S", time.localtime(now))
        ms = int(now * 1000) % 1000
        fields = dict(self._fields)
        fields.update(kv)
        parts = " ".join(f"{k}={_fmt(v)}" for k, v in fields.items())
        line = (
            f"{_LEVEL_CHAR[level]}[{stamp}.{ms:03d}] "
            f"{msg:<44}{(' ' + parts) if parts else ''}\n"
        )
        with self._lock:
            try:
                self._sink.write(line)
                self._sink.flush()
            except Exception:
                pass  # a dead sink must never take the node down

    def debug(self, msg: str, **kv) -> None:
        self._emit(DEBUG, msg, kv)

    def info(self, msg: str, **kv) -> None:
        self._emit(INFO, msg, kv)

    def error(self, msg: str, **kv) -> None:
        self._emit(ERROR, msg, kv)


def _fmt(v) -> str:
    if isinstance(v, bytes):
        return v.hex()[:16].upper()
    if isinstance(v, float):
        return f"{v:.3f}"
    s = str(v)
    return f'"{s}"' if " " in s else s


class NopLogger(Logger):
    def __init__(self):
        super().__init__(level=NONE)

    def _emit(self, level, msg, kv) -> None:
        pass


_default = Logger()


def default_logger() -> Logger:
    return _default
