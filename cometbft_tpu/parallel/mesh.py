"""Device-mesh sharding for validator-scale signature batches.

The reference engine scales verification with CPU batch verification
(crypto/batch/batch.go:11, types/validation.go:153). The TPU-native analog
has two sharding axes that map onto a 2-D ``jax.sharding.Mesh``:

* ``commit`` — independent commits verified concurrently (light-client
  replay over many heights, blocksync catch-up windows). Embarrassingly
  parallel: no cross-shard traffic at all.
* ``sig``    — signatures *within* one commit (one lane per validator).
  The only cross-shard value is the commit-level verdict, a single bool;
  XLA lowers the ``jnp.all`` over the sharded axis to an ICI all-reduce of
  one byte per commit — the cheapest possible collective.

Everything is expressed as sharding annotations on a single ``jax.jit`` of
the plain batched kernel (ops/curve.py): XLA inserts the collectives; there
is no hand-written communication. This file is the ``pjit``-over-signature-
axis design called for by SURVEY.md §2.9/§5 (long-context analog: shard the
signature axis like a sequence axis, all-gather only the validity bitmap).
"""

from __future__ import annotations

from functools import lru_cache

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops import curve

AXIS_COMMIT = "commit"
AXIS_SIG = "sig"


def make_mesh(devices=None, commit_axis: int = 1) -> Mesh:
    """Build a (commit, sig) mesh over ``devices`` (default: all).

    ``commit_axis`` devices are assigned to the commit axis; the rest to the
    signature axis. With the default 1, the whole slice shards one commit's
    signature batch — the consensus hot-path layout (one commit per round).
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if n % commit_axis != 0:
        raise ValueError(f"{n} devices not divisible by commit_axis={commit_axis}")
    arr = np.asarray(devices).reshape(commit_axis, n // commit_axis)
    return Mesh(arr, (AXIS_COMMIT, AXIS_SIG))


@lru_cache(maxsize=None)
def _sharded_verify(mesh: Mesh):
    """jit of the verify kernel over a (..., C, V) batch sharded on the mesh.

    Batch dims TRAIL (see ops/field.py): y limbs are (20, C, V), parity
    bits (C, V), scalar windows (64, C, V). Returns per-signature validity
    (C, V) sharded like the inputs plus the per-commit verdict (C,) — the
    latter forces the one collective (a commit-local all-reduce over the
    sig axis).
    """
    lead = NamedSharding(mesh, P(None, AXIS_COMMIT, AXIS_SIG))
    flat = NamedSharding(mesh, P(AXIS_COMMIT, AXIS_SIG))
    verdict = NamedSharding(mesh, P(AXIS_COMMIT))

    def step(y_a, sign_a, y_r, sign_r, s_nibs, kneg_nibs):
        ok = curve.verify_kernel(y_a, sign_a, y_r, sign_r, s_nibs, kneg_nibs)
        return ok, jnp.all(ok, axis=-1)

    return jax.jit(
        step,
        in_shardings=(lead, flat, lead, flat, lead, lead),
        out_shardings=(flat, verdict),
    )


# One synchronous pallas-under-shard_map failure retires the path for the
# process (per-mesh compile caches make retrying per call pointless).
_SHARDED_PALLAS_BROKEN = False


@lru_cache(maxsize=None)
def _sharded_verify_pallas(mesh: Mesh):
    """Sharded verify with the PALLAS kernel per shard (accelerators).

    Mosaic custom calls are not SPMD-auto-partitionable, so the kernel
    runs inside ``shard_map``: each device gets its (C_l, V_l) block,
    flattens the commit axis into lanes, pads to the kernel's 512-lane
    block constraint (static shapes — padding targets are computed at
    trace time), and runs the VMEM-resident ladder. The per-commit
    verdict's ``jnp.all`` stays OUTSIDE the shard_map, so XLA still
    lowers it to the one-byte-per-commit ICI all-reduce. ~2.5x the XLA
    program per chip (round-5 A/B) — this is the multi-chip projection
    of that measured single-chip win.
    """
    from ..ops import pallas_verify
    from jax.experimental.shard_map import shard_map

    lead = P(None, AXIS_COMMIT, AXIS_SIG)
    flat = P(AXIS_COMMIT, AXIS_SIG)

    def local(y_a, sign_a, y_r, sign_r, s_nibs, kneg_nibs):
        c_l, v_l = y_a.shape[-2], y_a.shape[-1]
        n = c_l * v_l
        target = n if n <= 512 else pad_to(n, 512)

        def lanes(x):
            x = x.reshape(*x.shape[:-2], n)
            if target != n:
                pad = [(0, 0)] * (x.ndim - 1) + [(0, target - n)]
                x = jnp.pad(x, pad)
            return x

        ok = pallas_verify.verify_kernel(
            lanes(y_a), lanes(sign_a), lanes(y_r), lanes(sign_r),
            lanes(s_nibs), lanes(kneg_nibs), interpret=False,
        )
        return ok[:n].reshape(c_l, v_l)

    sm = shard_map(
        local,
        mesh=mesh,
        in_specs=(lead, flat, lead, flat, lead, lead),
        out_specs=flat,
        check_rep=False,
    )

    def step(y_a, sign_a, y_r, sign_r, s_nibs, kneg_nibs):
        ok = sm(y_a, sign_a, y_r, sign_r, s_nibs, kneg_nibs)
        return ok, jnp.all(ok, axis=-1)

    return jax.jit(step)


def _dispatch_sharded(mesh: Mesh, args, lanes_per_shard: int):
    """Pallas-per-shard on accelerator backends, the portable XLA
    program otherwise (CPU virtual meshes: interpret mode is far too
    slow). Returns MATERIALIZED (ok, verdict) ndarrays: jit dispatch is
    asynchronous, so a Mosaic runtime fault only surfaces at
    np.asarray — materializing inside the try is what lets it retire
    the path and fall back (the multi-chip analog of
    ops/verify._materialize).

    Knob semantics here: COMETBFT_TPU_KERNEL=xla|xla8 disables the
    pallas branch (via _pallas_wanted); a pallas/pallas8 pin or auto
    runs the 4-bit pallas LADDER per shard — the 8-bit-window kernels
    take a different wire layout (s_bytes) than pack_inputs ships
    (s_nibs), so flavor pins to them apply to the single-chip path
    only. The backend gate is explicit: an off-accelerator pallas pin
    must route to XLA, not attempt a Mosaic compile that retires the
    path."""
    global _SHARDED_PALLAS_BROKEN
    from ..ops import verify as ov

    from ..libs.accel import ACCELERATOR_BACKENDS

    try:
        on_accel = jax.default_backend() in ACCELERATOR_BACKENDS
    except Exception:
        on_accel = False
    if (
        on_accel
        and lanes_per_shard >= ov._PALLAS_MIN_LANES
        and ov._pallas_wanted()
        and not _SHARDED_PALLAS_BROKEN
    ):
        try:
            ok, verdict = _sharded_verify_pallas(mesh)(*args)
            # cometlint: disable=CLNT002 -- sanctioned sharded readback:
            # materializing INSIDE the try is what lets a Mosaic runtime
            # fault retire the pallas path and fall through to XLA
            return np.asarray(ok), np.asarray(verdict)
        except Exception as e:
            _SHARDED_PALLAS_BROKEN = True
            from ..libs import log as _log

            _log.default_logger().with_module("parallel.mesh").error(
                "sharded pallas kernel failed; falling back to XLA",
                err=repr(e)[:200],
            )
    ok, verdict = _sharded_verify(mesh)(*args)
    # cometlint: disable=CLNT002 -- sanctioned readback of the XLA
    # sharded launch (single sync point of the multi-chip path)
    return np.asarray(ok), np.asarray(verdict)


def pad_to(n: int, multiple: int) -> int:
    return (n + multiple - 1) // multiple * multiple


@lru_cache(maxsize=None)
def default_mesh() -> Mesh:
    """Process-wide (1, n_devices) mesh: one commit, all chips on the
    signature axis — the consensus hot-path layout. Cached so the
    production dispatch (ops/verify.verify_batch) builds it once."""
    return make_mesh(commit_axis=1)


def verify_sharded(
    arrays: dict,
    host_ok: np.ndarray,
    mesh: Mesh,
    n_commits: int,
    n_sigs: int,
):
    """Run the sharded verifier over host-packed arrays (see ops.verify).

    ``arrays``/``host_ok`` come from ops.verify.pack_inputs with trailing
    batch dim n_commits * n_sigs; arrays are padded so both mesh axes
    divide their dims, reshaped to (..., C, V), and dispatched. Padding
    lanes are sliced off the result. ``host_ok`` must be ANDed in: a lane
    the host rejected (malformed length, non-canonical S) is zeroed in
    ``arrays`` and the all-zero encoding decompresses to a small-order
    point that the cofactored check accepts — without the mask that is a
    consensus-critical false accept.

    Returns ok (n_commits, n_sigs) bool ndarray.
    """
    c_dev, v_dev = mesh.devices.shape
    cp = pad_to(n_commits, c_dev)
    vp = pad_to(n_sigs, v_dev)

    shaped = {}
    for k, v in arrays.items():
        v = v.reshape(*v.shape[:-1], n_commits, n_sigs)
        pad = [(0, 0)] * (v.ndim - 2) + [(0, cp - n_commits), (0, vp - n_sigs)]
        shaped[k] = np.pad(v, pad)
    # pjit with in_shardings requires positional args.
    ok, _ = _dispatch_sharded(
        mesh,
        (
            shaped["y_a"],
            shaped["sign_a"],
            shaped["y_r"],
            shaped["sign_r"],
            shaped["s_nibs"],
            shaped["kneg_nibs"],
        ),
        lanes_per_shard=(cp // c_dev) * (vp // v_dev),
    )
    device_ok = ok[:n_commits, :n_sigs]
    return device_ok & np.asarray(host_ok, bool).reshape(n_commits, n_sigs)
