"""ABCI conformance driver + console (reference: abci/cmd/abci-cli,
abci/tests/server/client.go:114).

``run_conformance(client)`` drives any started ABCI client (socket, gRPC,
or local) through the protocol-level request/response assertions the
reference's ``abci-cli test`` performs against example apps: echo/info
round trips, InitChain, the PrepareProposal -> ProcessProposal ->
FinalizeBlock -> Commit block flow with app-hash stability, CheckTx
accept/reject, Query after commit, and snapshot listing. Failures raise
``ConformanceError`` naming the failed check.

``console(client)`` is the interactive REPL (`abci-cli console`).
"""

from __future__ import annotations

from . import types as abci


class ConformanceError(AssertionError):
    pass


def _check(cond: bool, name: str, detail: str = "") -> None:
    if not cond:
        raise ConformanceError(f"{name}: {detail}" if detail else name)


def run_conformance(client, chain_id: str = "abci-conformance") -> list[str]:
    """Drive the protocol conformance suite; returns passed check names.

    The app behind ``client`` must be kvstore-semantic (key=value txs) —
    the same assumption abci-cli's tests make about the example apps.
    """
    passed: list[str] = []

    def ok(name: str) -> None:
        passed.append(name)

    # echo round trip (client.go TestEcho)
    msg = "conformance-echo"
    _check(client.echo(msg) == msg, "echo", "payload not echoed back")
    ok("echo")
    client.flush()
    ok("flush")

    # info before init (client.go InfoSync)
    info = client.info(abci.RequestInfo(version="conformance"))
    _check(info is not None, "info", "nil response")
    first_height = info.last_block_height
    ok("info")

    # init chain on a fresh app only (a replayed app keeps its state)
    if first_height == 0:
        client.init_chain(
            abci.RequestInitChain(chain_id=chain_id, initial_height=1)
        )
        ok("init_chain")

    # check_tx accept + reject (client.go TestCheckTx-style)
    good = b"conf-key=conf-val"
    res = client.check_tx(abci.RequestCheckTx(tx=good))
    _check(res.code == 0, "check_tx_ok", f"code={res.code}")
    ok("check_tx_ok")
    res_bad = client.check_tx(abci.RequestCheckTx(tx=b"="))
    _check(res_bad.code != 0, "check_tx_reject", "empty kv accepted")
    ok("check_tx_reject")

    # block flow: prepare -> process -> finalize -> commit
    height = max(first_height, 0) + 1
    prep = client.prepare_proposal(
        abci.RequestPrepareProposal(
            max_tx_bytes=1 << 20,
            txs=[good],
            local_last_commit=abci.ExtendedCommitInfo(round=0),
            misbehavior=[],
            height=height,
            time_ns=0,
            next_validators_hash=b"",
            proposer_address=b"",
        )
    )
    txs = list(prep.txs)
    _check(good in txs, "prepare_proposal", "tx dropped")
    ok("prepare_proposal")

    proc = client.process_proposal(
        abci.RequestProcessProposal(
            txs=txs,
            proposed_last_commit=abci.CommitInfo(round=0),
            misbehavior=[],
            hash=b"",
            height=height,
            time_ns=0,
            next_validators_hash=b"",
            proposer_address=b"",
        )
    )
    _check(proc.is_accepted, "process_proposal", f"status={proc.status}")
    ok("process_proposal")

    fin = client.finalize_block(
        abci.RequestFinalizeBlock(
            txs=txs,
            decided_last_commit=abci.CommitInfo(round=0),
            misbehavior=[],
            hash=b"",
            height=height,
            time_ns=0,
            next_validators_hash=b"",
            proposer_address=b"",
        )
    )
    _check(len(fin.tx_results) == len(txs), "finalize_block", "result count")
    _check(
        all(r.code == 0 for r in fin.tx_results),
        "finalize_block_codes",
        "tx failed",
    )
    app_hash = fin.app_hash
    ok("finalize_block")

    client.commit(abci.RequestCommit())
    ok("commit")

    # deterministic app hash: replaying the same block on a fresh height
    # must NOT change state retroactively — info reflects the commit
    info2 = client.info(abci.RequestInfo(version="conformance"))
    _check(
        info2.last_block_height == height,
        "info_height_advanced",
        f"{info2.last_block_height} != {height}",
    )
    _check(
        info2.last_block_app_hash == app_hash,
        "info_app_hash",
        "hash mismatch after commit",
    )
    ok("info_after_commit")

    # query returns the committed value (client.go TestKV semantics)
    q = client.query(abci.RequestQuery(data=b"conf-key", path="/key"))
    _check(q.value == b"conf-val", "query_committed", f"value={q.value!r}")
    ok("query_committed")

    # snapshots surface (may be empty below the snapshot interval)
    snaps = client.list_snapshots(abci.RequestListSnapshots())
    _check(snaps is not None, "list_snapshots", "nil response")
    ok("list_snapshots")

    return passed


# ------------------------------------------------------------------ console


_CONSOLE_HELP = """\
commands (abci-cli console surface):
  echo <text>            info
  check_tx <key=value>   deliver <key=value>   (finalize+commit one block)
  query <key>            commit
  help                   quit
"""


def console(client, inp=None, out=None) -> None:
    """Interactive ABCI console (abci-cli.go console command)."""
    import sys

    inp = inp if inp is not None else sys.stdin
    out = out if out is not None else sys.stdout
    height = client.info(abci.RequestInfo()).last_block_height

    def w(s: str) -> None:
        out.write(s + "\n")
        out.flush()

    w(_CONSOLE_HELP)
    for line in inp:
        parts = line.strip().split(None, 1)
        if not parts:
            continue
        cmd, arg = parts[0], (parts[1] if len(parts) > 1 else "")
        try:
            if cmd == "quit":
                return
            elif cmd == "help":
                w(_CONSOLE_HELP)
            elif cmd == "echo":
                w(f"-> {client.echo(arg)}")
            elif cmd == "info":
                r = client.info(abci.RequestInfo())
                w(
                    f"-> height={r.last_block_height} "
                    f"app_hash={r.last_block_app_hash.hex()}"
                )
            elif cmd == "check_tx":
                r = client.check_tx(abci.RequestCheckTx(tx=arg.encode()))
                w(f"-> code={r.code} log={r.log}")
            elif cmd == "deliver":
                height += 1
                fin = client.finalize_block(
                    abci.RequestFinalizeBlock(
                        txs=[arg.encode()],
                        decided_last_commit=abci.CommitInfo(round=0),
                        misbehavior=[],
                        hash=b"",
                        height=height,
                        time_ns=0,
                        next_validators_hash=b"",
                        proposer_address=b"",
                    )
                )
                client.commit(abci.RequestCommit())
                w(
                    f"-> height={height} "
                    f"codes={[r.code for r in fin.tx_results]} "
                    f"app_hash={fin.app_hash.hex()}"
                )
            elif cmd == "query":
                r = client.query(
                    abci.RequestQuery(data=arg.encode(), path="/key")
                )
                w(f"-> code={r.code} value={r.value!r}")
            elif cmd == "commit":
                client.commit(abci.RequestCommit())
                w("-> committed")
            else:
                w(f"unknown command {cmd!r} (try help)")
        except Exception as e:
            w(f"error: {e!r}")
