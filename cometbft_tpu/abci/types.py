"""ABCI 2.0 request/response types (reference: abci/types/types.pb.go,
proto/tendermint/abci/types.proto).

Dataclass mirrors of the protobuf messages the 14-method ``Application``
interface exchanges. Field names follow the proto definitions; enums keep
the proto numeric values so a wire codec can round-trip them.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from enum import IntEnum

OK = 0  # response code for success (abci/types/result.go)


class CheckTxType(IntEnum):
    NEW = 0
    RECHECK = 1


class ProcessProposalStatus(IntEnum):
    UNKNOWN = 0
    ACCEPT = 1
    REJECT = 2


class VerifyVoteExtensionStatus(IntEnum):
    UNKNOWN = 0
    ACCEPT = 1
    REJECT = 2


class OfferSnapshotResult(IntEnum):
    UNKNOWN = 0
    ACCEPT = 1
    ABORT = 2
    REJECT = 3
    REJECT_FORMAT = 4
    REJECT_SENDER = 5


class ApplySnapshotChunkResult(IntEnum):
    UNKNOWN = 0
    ACCEPT = 1
    ABORT = 2
    RETRY = 3
    RETRY_SNAPSHOT = 4
    REJECT_SNAPSHOT = 5


class MisbehaviorType(IntEnum):
    UNKNOWN = 0
    DUPLICATE_VOTE = 1
    LIGHT_CLIENT_ATTACK = 2


# -- shared sub-messages ---------------------------------------------------


@dataclass
class EventAttribute:
    key: str
    value: str
    index: bool = False


@dataclass
class Event:
    type: str
    attributes: list[EventAttribute] = field(default_factory=list)


@dataclass
class ExecTxResult:
    code: int = OK
    data: bytes = b""
    log: str = ""
    info: str = ""
    gas_wanted: int = 0
    gas_used: int = 0
    events: list[Event] = field(default_factory=list)
    codespace: str = ""

    @property
    def is_ok(self) -> bool:
        return self.code == OK


@dataclass
class ValidatorUpdate:
    pub_key_type: str
    pub_key_bytes: bytes
    power: int


@dataclass
class Validator:
    address: bytes
    power: int


@dataclass
class VoteInfo:
    validator: Validator
    block_id_flag: int  # types.BlockIDFlag numeric value


@dataclass
class ExtendedVoteInfo:
    validator: Validator
    vote_extension: bytes
    extension_signature: bytes
    block_id_flag: int


@dataclass
class CommitInfo:
    round: int
    votes: list[VoteInfo] = field(default_factory=list)


@dataclass
class ExtendedCommitInfo:
    round: int
    votes: list[ExtendedVoteInfo] = field(default_factory=list)


@dataclass
class Misbehavior:
    type: MisbehaviorType
    validator: Validator
    height: int
    time_ns: int
    total_voting_power: int


@dataclass
class Snapshot:
    height: int
    format: int
    chunks: int
    hash: bytes
    metadata: bytes = b""


# -- requests / responses --------------------------------------------------


@dataclass
class RequestInfo:
    version: str = ""
    block_version: int = 0
    p2p_version: int = 0
    abci_version: str = ""


@dataclass
class ResponseInfo:
    data: str = ""
    version: str = ""
    app_version: int = 0
    last_block_height: int = 0
    last_block_app_hash: bytes = b""


@dataclass
class RequestInitChain:
    time_ns: int = 0
    chain_id: str = ""
    consensus_params: object | None = None  # types.ConsensusParams
    validators: list[ValidatorUpdate] = field(default_factory=list)
    app_state_bytes: bytes = b""
    initial_height: int = 1


@dataclass
class ResponseInitChain:
    consensus_params: object | None = None
    validators: list[ValidatorUpdate] = field(default_factory=list)
    app_hash: bytes = b""


@dataclass
class RequestQuery:
    data: bytes = b""
    path: str = ""
    height: int = 0
    prove: bool = False


@dataclass
class ResponseQuery:
    code: int = OK
    log: str = ""
    info: str = ""
    index: int = 0
    key: bytes = b""
    value: bytes = b""
    proof_ops: list | None = None
    height: int = 0
    codespace: str = ""


@dataclass
class RequestCheckTx:
    tx: bytes
    type: CheckTxType = CheckTxType.NEW


@dataclass
class ResponseCheckTx:
    code: int = OK
    data: bytes = b""
    log: str = ""
    info: str = ""
    gas_wanted: int = 0
    gas_used: int = 0
    events: list[Event] = field(default_factory=list)
    codespace: str = ""

    @property
    def is_ok(self) -> bool:
        return self.code == OK


@dataclass
class RequestPrepareProposal:
    max_tx_bytes: int
    txs: list[bytes]
    local_last_commit: ExtendedCommitInfo
    misbehavior: list[Misbehavior]
    height: int
    time_ns: int
    next_validators_hash: bytes
    proposer_address: bytes


@dataclass
class ResponsePrepareProposal:
    txs: list[bytes] = field(default_factory=list)


@dataclass
class RequestProcessProposal:
    txs: list[bytes]
    proposed_last_commit: CommitInfo
    misbehavior: list[Misbehavior]
    hash: bytes
    height: int
    time_ns: int
    next_validators_hash: bytes
    proposer_address: bytes


@dataclass
class ResponseProcessProposal:
    status: ProcessProposalStatus = ProcessProposalStatus.UNKNOWN

    @property
    def is_accepted(self) -> bool:
        return self.status == ProcessProposalStatus.ACCEPT


@dataclass
class RequestExtendVote:
    hash: bytes
    height: int
    time_ns: int = 0
    txs: list[bytes] = field(default_factory=list)
    proposed_last_commit: CommitInfo | None = None
    misbehavior: list[Misbehavior] = field(default_factory=list)
    next_validators_hash: bytes = b""
    proposer_address: bytes = b""


@dataclass
class ResponseExtendVote:
    vote_extension: bytes = b""


@dataclass
class RequestVerifyVoteExtension:
    hash: bytes
    validator_address: bytes
    height: int
    vote_extension: bytes


@dataclass
class ResponseVerifyVoteExtension:
    status: VerifyVoteExtensionStatus = VerifyVoteExtensionStatus.UNKNOWN

    @property
    def is_accepted(self) -> bool:
        return self.status == VerifyVoteExtensionStatus.ACCEPT


@dataclass
class RequestFinalizeBlock:
    txs: list[bytes]
    decided_last_commit: CommitInfo
    misbehavior: list[Misbehavior]
    hash: bytes
    height: int
    time_ns: int
    next_validators_hash: bytes
    proposer_address: bytes


@dataclass
class ResponseFinalizeBlock:
    events: list[Event] = field(default_factory=list)
    tx_results: list[ExecTxResult] = field(default_factory=list)
    validator_updates: list[ValidatorUpdate] = field(default_factory=list)
    consensus_param_updates: object | None = None
    app_hash: bytes = b""


@dataclass
class RequestCommit:
    pass


@dataclass
class ResponseCommit:
    retain_height: int = 0


@dataclass
class RequestListSnapshots:
    pass


@dataclass
class ResponseListSnapshots:
    snapshots: list[Snapshot] = field(default_factory=list)


@dataclass
class RequestOfferSnapshot:
    snapshot: Snapshot
    app_hash: bytes


@dataclass
class ResponseOfferSnapshot:
    result: OfferSnapshotResult = OfferSnapshotResult.UNKNOWN


@dataclass
class RequestLoadSnapshotChunk:
    height: int
    format: int
    chunk: int


@dataclass
class ResponseLoadSnapshotChunk:
    chunk: bytes = b""


@dataclass
class RequestApplySnapshotChunk:
    index: int
    chunk: bytes
    sender: str = ""


@dataclass
class ResponseApplySnapshotChunk:
    result: ApplySnapshotChunkResult = ApplySnapshotChunkResult.UNKNOWN
    refetch_chunks: list[int] = field(default_factory=list)
    reject_senders: list[str] = field(default_factory=list)
