"""The 14-method Application interface (reference:
abci/types/application.go:9-35) and a no-op base implementation
(``BaseApplication``, abci/types/application.go:43+) that concrete apps
override selectively.
"""

from __future__ import annotations

from . import types as abci


class Application:
    """ABCI 2.0: Info/Query, mempool, consensus, and snapshot groups."""

    # Info/Query connection
    def info(self, req: abci.RequestInfo) -> abci.ResponseInfo:
        raise NotImplementedError

    def query(self, req: abci.RequestQuery) -> abci.ResponseQuery:
        raise NotImplementedError

    # Mempool connection
    def check_tx(self, req: abci.RequestCheckTx) -> abci.ResponseCheckTx:
        raise NotImplementedError

    # Consensus connection
    def init_chain(self, req: abci.RequestInitChain) -> abci.ResponseInitChain:
        raise NotImplementedError

    def prepare_proposal(
        self, req: abci.RequestPrepareProposal
    ) -> abci.ResponsePrepareProposal:
        raise NotImplementedError

    def process_proposal(
        self, req: abci.RequestProcessProposal
    ) -> abci.ResponseProcessProposal:
        raise NotImplementedError

    def finalize_block(
        self, req: abci.RequestFinalizeBlock
    ) -> abci.ResponseFinalizeBlock:
        raise NotImplementedError

    def extend_vote(self, req: abci.RequestExtendVote) -> abci.ResponseExtendVote:
        raise NotImplementedError

    def verify_vote_extension(
        self, req: abci.RequestVerifyVoteExtension
    ) -> abci.ResponseVerifyVoteExtension:
        raise NotImplementedError

    def commit(self, req: abci.RequestCommit) -> abci.ResponseCommit:
        raise NotImplementedError

    # State-sync connection
    def list_snapshots(
        self, req: abci.RequestListSnapshots
    ) -> abci.ResponseListSnapshots:
        raise NotImplementedError

    def offer_snapshot(
        self, req: abci.RequestOfferSnapshot
    ) -> abci.ResponseOfferSnapshot:
        raise NotImplementedError

    def load_snapshot_chunk(
        self, req: abci.RequestLoadSnapshotChunk
    ) -> abci.ResponseLoadSnapshotChunk:
        raise NotImplementedError

    def apply_snapshot_chunk(
        self, req: abci.RequestApplySnapshotChunk
    ) -> abci.ResponseApplySnapshotChunk:
        raise NotImplementedError

    # -- optional speculation extension ------------------------------------
    #
    # Apps that want optimistic block execution (consensus/pipeline.py)
    # implement BOTH of these; a local client then runs FinalizeBlock in
    # a snapshot/finalize/restore sandwich so a speculation that never
    # commits leaves no trace. The token is opaque to the engine. An app
    # must only advertise the pair if a restore really reverts EVERY
    # side effect its finalize_block has (in particular: no durable
    # writes inside finalize — persistence belongs in Commit). There is
    # deliberately NO default implementation: a no-op inherited pair on
    # a stateful subclass would silently corrupt it.
    #
    # def snapshot_spec_state(self): ...
    # def restore_spec_state(self, token): ...


class BaseApplication(Application):
    """Accept-everything defaults; concrete apps override what they need."""

    def info(self, req):
        return abci.ResponseInfo()

    def query(self, req):
        return abci.ResponseQuery(code=abci.OK)

    def check_tx(self, req):
        return abci.ResponseCheckTx(code=abci.OK)

    def init_chain(self, req):
        return abci.ResponseInitChain()

    def prepare_proposal(self, req):
        # Default: include txs up to the byte budget (application.go defaults)
        txs, total = [], 0
        for tx in req.txs:
            if req.max_tx_bytes >= 0 and total + len(tx) > req.max_tx_bytes:
                break
            txs.append(tx)
            total += len(tx)
        return abci.ResponsePrepareProposal(txs=txs)

    def process_proposal(self, req):
        return abci.ResponseProcessProposal(
            status=abci.ProcessProposalStatus.ACCEPT
        )

    def finalize_block(self, req):
        return abci.ResponseFinalizeBlock(
            tx_results=[abci.ExecTxResult() for _ in req.txs]
        )

    def extend_vote(self, req):
        return abci.ResponseExtendVote()

    def verify_vote_extension(self, req):
        return abci.ResponseVerifyVoteExtension(
            status=abci.VerifyVoteExtensionStatus.ACCEPT
        )

    def commit(self, req):
        return abci.ResponseCommit()

    def list_snapshots(self, req):
        return abci.ResponseListSnapshots()

    def offer_snapshot(self, req):
        return abci.ResponseOfferSnapshot()

    def load_snapshot_chunk(self, req):
        return abci.ResponseLoadSnapshotChunk()

    def apply_snapshot_chunk(self, req):
        return abci.ResponseApplySnapshotChunk(
            result=abci.ApplySnapshotChunkResult.ACCEPT
        )
