"""Wire codec for the ABCI socket protocol.

The reference frames length-prefixed protobuf ``Request``/``Response``
oneofs over a unix/tcp socket (abci/client/socket_client.go,
abci/server/socket_server.go). Here the framing is identical (uvarint
length prefix, libs/protoio) but the payload is self-describing JSON:
dataclasses carry a ``__t`` type tag, bytes are hex-tagged. The codec is
an internal boundary between this framework's node and app processes —
swapping in a protobuf payload for Go-app interop only touches this module.
"""

from __future__ import annotations

import dataclasses
import json
from enum import IntEnum

from ..types import proto
from . import types as abci

# Registry of every dataclass the protocol can carry, by class name.
# RequestInitChain/ResponseFinalizeBlock embed the consensus-params types.
from ..types import params as _params  # noqa: E402

_TYPES = {
    name: obj
    for mod in (abci, _params)
    for name, obj in vars(mod).items()
    if dataclasses.is_dataclass(obj)
}


def _to_jsonable(v):
    """Tagged-JSON encoding of registered dataclasses. Shared by every
    process-boundary codec (ABCI socket/gRPC, privval socket)."""
    if dataclasses.is_dataclass(v) and not isinstance(v, type):
        d = {"__t": type(v).__name__}
        for f in dataclasses.fields(v):
            d[f.name] = _to_jsonable(getattr(v, f.name))
        return d
    if isinstance(v, bytes):
        return {"__b": v.hex()}
    if isinstance(v, IntEnum):
        return int(v)
    if isinstance(v, (list, tuple)):
        return [_to_jsonable(x) for x in v]
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    raise TypeError(f"cannot encode {type(v).__name__} over ABCI socket")


def _from_jsonable(v, types=None):
    """Inverse of :func:`_to_jsonable` against a type registry
    (defaults to the ABCI message set)."""
    if types is None:
        types = _TYPES
    if isinstance(v, dict):
        if "__b" in v:
            return bytes.fromhex(v["__b"])
        if "__t" in v:
            cls = types[v["__t"]]
            kwargs = {
                k: _from_jsonable(x, types) for k, x in v.items() if k != "__t"
            }
            obj = cls(**kwargs)
            # Restore enum types declared on the dataclass.
            for f in dataclasses.fields(cls):
                cur = getattr(obj, f.name)
                if isinstance(f.type, str) and isinstance(cur, int):
                    enum_cls = getattr(abci, f.type, None)
                    if isinstance(enum_cls, type) and issubclass(
                        enum_cls, IntEnum
                    ):
                        setattr(obj, f.name, enum_cls(cur))
            return obj
        raise ValueError(f"unknown tagged value {v.keys()}")
    if isinstance(v, list):
        return [_from_jsonable(x, types) for x in v]
    return v


def encode_frame(method: str, msg) -> bytes:
    """One protocol frame: uvarint length + JSON {method, msg}."""
    payload = json.dumps(
        {"method": method, "msg": _to_jsonable(msg)}, separators=(",", ":")
    ).encode()
    return proto.delimited(payload)


# Frames beyond this are protocol corruption or abuse, not real traffic
# (the reference caps reads the same way — libs/protoio reader limit).
MAX_FRAME_BYTES = 64 * 1024 * 1024


def read_frame(sock_file) -> tuple[str, object] | None:
    """Read one frame from a file-like socket; None on clean EOF."""
    first = sock_file.read(1)
    if not first:
        return None  # clean EOF between frames
    buffered = [first]

    def read_exact(n: int) -> bytes:
        out = buffered.pop() if (buffered and n) else b""
        while len(out) < n:
            chunk = sock_file.read(n - len(out))
            if not chunk:
                raise EOFError("truncated ABCI frame")
            out += chunk
        return out

    payload = proto.read_delimited(read_exact, MAX_FRAME_BYTES)
    obj = json.loads(payload)
    return obj["method"], _from_jsonable(obj["msg"])
