"""gRPC ABCI transport (reference: abci/client/grpc_client.go:247,
abci/server/grpc_server.go:76).

A real gRPC (HTTP/2) service carrying the same 16 unary methods as the
socket transport. The payload serializer is the framework's canonical
self-describing JSON (abci/codec.py) registered through gRPC's generic
method handlers — the one codec family used at every process boundary.
Interop with a Go ABCI app would swap the (de)serializers for protobuf
encoding of proto/tendermint/abci; like the socket codec, that's a
boundary-module-only change.

Unlike the socket protocol there is no FIFO pipelining contract: gRPC
gives each call its own stream, so CheckTxAsync maps to a channel future
(the reference's grpc client does the same with per-call goroutines).
"""

from __future__ import annotations

import concurrent.futures
import json

import grpc

from ..libs.service import BaseService
from . import codec
from .application import Application
from .client import Client, ReqRes

_SERVICE = "cometbft.abci.ABCI"

# method name -> (request attr on Application). Echo/Flush are transport
# no-ops kept for protocol parity (abci/types/application.go).
_METHODS = (
    "echo",
    "flush",
    "info",
    "query",
    "check_tx",
    "init_chain",
    "prepare_proposal",
    "process_proposal",
    "finalize_block",
    "extend_vote",
    "verify_vote_extension",
    "commit",
    "list_snapshots",
    "offer_snapshot",
    "load_snapshot_chunk",
    "apply_snapshot_chunk",
)


def _serialize(msg) -> bytes:
    return json.dumps(codec._to_jsonable(msg), separators=(",", ":")).encode()


def _deserialize(data: bytes):
    return codec._from_jsonable(json.loads(data))


class GrpcServer(BaseService):
    """Serves one Application over gRPC (abci/server/grpc_server.go)."""

    def __init__(self, addr: str, app: Application, max_workers: int = 10):
        super().__init__("abci-grpc-server")
        for scheme in ("grpc://", "tcp://"):
            if addr.startswith(scheme):
                addr = addr[len(scheme) :]
        self.addr = addr
        self.app = app
        self._max_workers = max_workers
        self._server = None

    def _handle(self, method: str):
        app = self.app

        def unary(request, context):
            if method == "echo":
                return request  # echo carries its payload back (a str)
            if method == "flush":
                return ""  # acknowledgement only
            return getattr(app, method)(request)

        return grpc.unary_unary_rpc_method_handler(
            unary,
            request_deserializer=_deserialize,
            response_serializer=_serialize,
        )

    def on_start(self) -> None:
        self._server = grpc.server(
            concurrent.futures.ThreadPoolExecutor(
                max_workers=self._max_workers,
                thread_name_prefix="abci-grpc",
            )
        )
        handlers = {m: self._handle(m) for m in _METHODS}
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(_SERVICE, handlers),)
        )
        bound = self._server.add_insecure_port(self.addr)
        if bound == 0:
            raise OSError(f"cannot bind gRPC ABCI server at {self.addr}")
        self.bound_port = bound
        self._server.start()

    def on_stop(self) -> None:
        if self._server is not None:
            self._server.stop(grace=1.0).wait(2.0)


class GrpcClient(Client):
    """ABCI client over gRPC (abci/client/grpc_client.go).

    Sync methods issue blocking unary calls; ``check_tx_async`` uses the
    channel's future API and completes the ReqRes from a callback thread
    (the reference launches a goroutine per async call, :247).
    """

    def __init__(self, addr: str, timeout: float = 10.0):
        super().__init__("abci-grpc-client")
        # accept grpc:// and tcp:// prefixes — gRPC targets are bare
        # host:port (the CLI's default --addr carries a tcp:// scheme)
        for scheme in ("grpc://", "tcp://"):
            if addr.startswith(scheme):
                addr = addr[len(scheme) :]
        self.addr = addr
        self.timeout = timeout
        self._channel = None
        self._calls = {}

    def on_start(self) -> None:
        self._channel = grpc.insecure_channel(self.addr)
        grpc.channel_ready_future(self._channel).result(timeout=self.timeout)
        for m in _METHODS:
            self._calls[m] = self._channel.unary_unary(
                f"/{_SERVICE}/{m}",
                request_serializer=_serialize,
                response_deserializer=_deserialize,
            )

    def on_stop(self) -> None:
        if self._channel is not None:
            self._channel.close()

    def _call(self, method: str, req):
        try:
            return self._calls[method](req, timeout=self.timeout)
        except grpc.RpcError as e:
            err = ConnectionError(f"ABCI gRPC {method}: {e.code().name}")
            self._err = self._err or err
            if self._on_error is not None:
                self._on_error(err)
            raise err from e

    # -- sync surface ------------------------------------------------------

    def echo(self, msg: str) -> str:
        return self._call("echo", msg)

    def flush(self) -> None:
        self._call("flush", "")

    def info(self, req):
        return self._call("info", req)

    def query(self, req):
        return self._call("query", req)

    def check_tx(self, req):
        return self._call("check_tx", req)

    def init_chain(self, req):
        return self._call("init_chain", req)

    def prepare_proposal(self, req):
        return self._call("prepare_proposal", req)

    def process_proposal(self, req):
        return self._call("process_proposal", req)

    def finalize_block(self, req):
        return self._call("finalize_block", req)

    def extend_vote(self, req):
        return self._call("extend_vote", req)

    def verify_vote_extension(self, req):
        return self._call("verify_vote_extension", req)

    def commit(self, req=None):
        # Client contract: the executor calls commit() bare
        # (abci/client.py:125; Commit carries no fields)
        from . import types as abci

        return self._call("commit", req if req is not None else abci.RequestCommit())

    def list_snapshots(self, req):
        return self._call("list_snapshots", req)

    def offer_snapshot(self, req):
        return self._call("offer_snapshot", req)

    def load_snapshot_chunk(self, req):
        return self._call("load_snapshot_chunk", req)

    def apply_snapshot_chunk(self, req):
        return self._call("apply_snapshot_chunk", req)

    # -- async surface -----------------------------------------------------

    def check_tx_async(self, req) -> ReqRes:
        rr = ReqRes("check_tx", req)
        fut = self._calls["check_tx"].future(req, timeout=self.timeout)

        def done(f):
            try:
                resp = f.result()
            except grpc.RpcError as e:
                err = ConnectionError(
                    f"ABCI gRPC check_tx: {e.code().name}"
                )
                rr._complete_error(err)
                # same client-level bookkeeping as the sync path: the
                # proxy layer fail-stops the node through this callback
                self._err = self._err or err
                if self._on_error is not None:
                    self._on_error(err)
                return
            rr._complete(resp)
            if self._global_cb is not None:
                self._global_cb(rr.request, resp)

        fut.add_done_callback(done)
        return rr
