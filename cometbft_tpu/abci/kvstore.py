"""The canonical in-process test application (reference:
abci/example/kvstore/kvstore.go:552).

Transactions are ``key=value`` byte strings; ``val:<pubkey_hex>!<power>``
transactions update the validator set (the mechanism consensus tests use
to exercise validator-set changes). App hash commits to the total tx
count, matching the reference's size-based hash, so two nodes diverge the
moment they disagree on history. State persists to a KV db under a
dedicated prefix — restart + handshake-replay tests depend on it.
"""

from __future__ import annotations

import json
import struct
from ..libs import sync as libsync

from ..libs import db as dbm
from . import types as abci
from .application import BaseApplication

_STATE_KEY = b"kvstore:state"
_KV_PREFIX = b"kvstore:k:"
VALIDATOR_TX_PREFIX = b"val:"


class KVStoreApplication(BaseApplication):
    def __init__(self, db: dbm.DB | None = None, snapshot_interval: int = 5):
        self.db = db if db is not None else dbm.MemDB()
        self._mtx = libsync.Mutex("abci.kvstore._mtx")
        self._staged: dict[bytes, bytes] = {}
        self._val_updates: list[abci.ValidatorUpdate] = []
        self._validators: dict[str, int] = {}  # pubkey hex -> power
        # Point-in-time snapshots taken at commit every snapshot_interval
        # heights (reference: test/e2e/app snapshots). A LIVE dump would
        # race block production: the chunk served later must match the
        # app hash advertised for that height exactly.
        self.snapshot_interval = snapshot_interval
        self._snapshots: dict[int, tuple[bytes, bytes]] = {}  # h -> (hash, blob)
        self._restore_target = None  # accepted OfferSnapshot, if any
        raw = self.db.get(_STATE_KEY)
        if raw:
            st = json.loads(raw)
            self.height = st["height"]
            self.size = st["size"]
            self.app_hash = bytes.fromhex(st["app_hash"])
            self._validators = st.get("validators", {})
        else:
            self.height = 0
            self.size = 0
            self.app_hash = b""

    # -- helpers -----------------------------------------------------------

    @staticmethod
    def _parse_tx(tx: bytes) -> tuple[bytes, bytes] | None:
        if b"=" not in tx:
            return None
        k, _, v = tx.partition(b"=")
        if not k:
            return None
        return k, v

    @staticmethod
    def _parse_validator_tx(tx: bytes) -> abci.ValidatorUpdate | None:
        # val:<pubkey_hex>!<power>
        body = tx[len(VALIDATOR_TX_PREFIX) :]
        if b"!" not in body:
            return None
        pk_hex, _, power = body.partition(b"!")
        try:
            pk = bytes.fromhex(pk_hex.decode())
            return abci.ValidatorUpdate("ed25519", pk, int(power))
        except ValueError:
            return None

    def _compute_app_hash(self) -> bytes:
        return struct.pack(">Q", self.size)

    # -- Info/Query --------------------------------------------------------

    def info(self, req):
        with self._mtx:
            return abci.ResponseInfo(
                data=json.dumps({"size": self.size}),
                version="kvstore-tpu/1",
                app_version=1,
                last_block_height=self.height,
                last_block_app_hash=self.app_hash,
            )

    def query(self, req):
        with self._mtx:
            value = self.db.get(_KV_PREFIX + req.data)
            return abci.ResponseQuery(
                code=abci.OK,
                key=req.data,
                value=value or b"",
                log="exists" if value is not None else "does not exist",
                height=self.height,
            )

    # -- Mempool -----------------------------------------------------------

    def check_tx(self, req):
        tx = req.tx
        if tx.startswith(VALIDATOR_TX_PREFIX):
            ok = self._parse_validator_tx(tx) is not None
        else:
            ok = self._parse_tx(tx) is not None
        if ok:
            return abci.ResponseCheckTx(code=abci.OK, gas_wanted=1)
        return abci.ResponseCheckTx(code=1, log="invalid tx format")

    # -- Consensus ---------------------------------------------------------

    def init_chain(self, req):
        with self._mtx:
            for vu in req.validators:
                self._validators[vu.pub_key_bytes.hex()] = vu.power
        return abci.ResponseInitChain(app_hash=self._compute_app_hash())

    def process_proposal(self, req):
        for tx in req.txs:
            bad_val = tx.startswith(VALIDATOR_TX_PREFIX) and (
                self._parse_validator_tx(tx) is None
            )
            if bad_val or (
                not tx.startswith(VALIDATOR_TX_PREFIX)
                and self._parse_tx(tx) is None
            ):
                return abci.ResponseProcessProposal(
                    status=abci.ProcessProposalStatus.REJECT
                )
        return abci.ResponseProcessProposal(
            status=abci.ProcessProposalStatus.ACCEPT
        )

    def finalize_block(self, req):
        with self._mtx:
            self._staged = {}
            self._val_updates = []
            results = []
            for tx in req.txs:
                if tx.startswith(VALIDATOR_TX_PREFIX):
                    vu = self._parse_validator_tx(tx)
                    if vu is None:
                        results.append(
                            abci.ExecTxResult(code=1, log="bad val tx")
                        )
                        continue
                    self._val_updates.append(vu)
                    self._validators[vu.pub_key_bytes.hex()] = vu.power
                    results.append(abci.ExecTxResult(code=abci.OK))
                    continue
                parsed = self._parse_tx(tx)
                if parsed is None:
                    results.append(abci.ExecTxResult(code=1, log="bad tx"))
                    continue
                k, v = parsed
                self._staged[k] = v
                self.size += 1
                results.append(
                    abci.ExecTxResult(
                        code=abci.OK,
                        events=[
                            abci.Event(
                                "app",
                                [
                                    abci.EventAttribute(
                                        "key", k.decode(errors="replace"), True
                                    ),
                                    abci.EventAttribute("creator", "kvstore"),
                                ],
                            )
                        ],
                    )
                )
            self.height = req.height
            self.app_hash = self._compute_app_hash()
            return abci.ResponseFinalizeBlock(
                tx_results=results,
                validator_updates=list(self._val_updates),
                app_hash=self.app_hash,
            )

    # -- speculation extension (consensus/pipeline.py) ---------------------
    #
    # finalize_block mutates exactly these fields and touches no storage
    # (persistence happens in commit), so a snapshot/restore pair over
    # them makes speculative execution state-neutral: speculate →
    # restore(pre) leaves the app bit-identical, and a winning
    # speculation replays as restore(post) + commit.

    def snapshot_spec_state(self) -> dict:
        with self._mtx:
            return {
                "staged": dict(self._staged),
                "val_updates": list(self._val_updates),
                "validators": dict(self._validators),
                "height": self.height,
                "size": self.size,
                "app_hash": self.app_hash,
            }

    def restore_spec_state(self, token: dict) -> None:
        with self._mtx:
            self._staged = dict(token["staged"])
            self._val_updates = list(token["val_updates"])
            self._validators = dict(token["validators"])
            self.height = token["height"]
            self.size = token["size"]
            self.app_hash = token["app_hash"]

    def _stage_state(self, batch) -> None:
        batch.set(
            _STATE_KEY,
            json.dumps(
                {
                    "height": self.height,
                    "size": self.size,
                    "app_hash": self.app_hash.hex(),
                    "validators": self._validators,
                }
            ).encode(),
        )

    def commit(self, req=None):
        with self._mtx:  # cometlint: disable=CLNT009 -- Commit persists the app state; the app mutex is its atomicity boundary
            batch = self.db.new_batch()
            for k, v in self._staged.items():
                batch.set(_KV_PREFIX + k, v)
            self._stage_state(batch)
            batch.write()
            self._staged = {}
            if (
                self.snapshot_interval > 0
                and self.height % self.snapshot_interval == 0
            ):
                self._snapshots[self.height] = (
                    self.app_hash,
                    self._dump_state_blob(),
                )
                for h in sorted(self._snapshots)[:-2]:
                    del self._snapshots[h]  # keep the 2 most recent
            retain = self.height - 500 if self.height > 500 else 0
            return abci.ResponseCommit(retain_height=max(retain, 0))

    # -- Snapshots (whole state in one chunk) ------------------------------

    def _dump_state_blob(self) -> bytes:
        kvs = {
            k[len(_KV_PREFIX) :].hex(): v.hex()
            for k, v in self.db.iterator(
                _KV_PREFIX, dbm.prefix_end(_KV_PREFIX)
            )
        }
        return json.dumps(
            {
                "height": self.height,
                "size": self.size,
                "validators": self._validators,
                "kvs": kvs,
            }
        ).encode()

    def list_snapshots(self, req):
        with self._mtx:
            return abci.ResponseListSnapshots(
                snapshots=[
                    abci.Snapshot(
                        height=h, format=1, chunks=1, hash=hash_
                    )
                    for h, (hash_, _) in sorted(self._snapshots.items())
                ]
            )

    def load_snapshot_chunk(self, req):
        with self._mtx:
            snap = self._snapshots.get(req.height)
            if snap is None:
                return abci.ResponseLoadSnapshotChunk(chunk=b"")
            return abci.ResponseLoadSnapshotChunk(chunk=snap[1])

    def offer_snapshot(self, req):
        if req.snapshot.format != 1:
            return abci.ResponseOfferSnapshot(
                result=abci.OfferSnapshotResult.REJECT_FORMAT
            )
        # Wrong chunk count is THIS snapshot's defect, not the format's: a
        # bogus advertisement must not poison every valid format-1 snapshot
        # via the pool's reject_format.
        if req.snapshot.chunks != 1:
            return abci.ResponseOfferSnapshot(
                result=abci.OfferSnapshotResult.REJECT
            )
        # This app's snapshot hash IS its app hash: verify against the
        # light-client-trusted value the engine passes us (the app-side
        # check the ABCI contract prescribes).
        if req.app_hash and req.snapshot.hash != req.app_hash:
            return abci.ResponseOfferSnapshot(
                result=abci.OfferSnapshotResult.REJECT
            )
        self._restore_target = req.snapshot
        return abci.ResponseOfferSnapshot(
            result=abci.OfferSnapshotResult.ACCEPT
        )

    def apply_snapshot_chunk(self, req):
        # Chunks come from untrusted peers: validate EVERYTHING before any
        # mutation — a half-applied parse failure would leave the app
        # inconsistent and poison a later blocksync-from-genesis.
        try:
            st = json.loads(req.chunk)
            height = int(st["height"])
            size = int(st["size"])
            validators = dict(st["validators"])
            kvs = {
                bytes.fromhex(k): bytes.fromhex(v)
                for k, v in st["kvs"].items()
            }
        except (ValueError, KeyError, TypeError, AttributeError):
            return abci.ResponseApplySnapshotChunk(
                result=abci.ApplySnapshotChunkResult.REJECT_SNAPSHOT
            )
        if (
            self._restore_target is not None
            and height != self._restore_target.height
        ):
            return abci.ResponseApplySnapshotChunk(
                result=abci.ApplySnapshotChunkResult.REJECT_SNAPSHOT
            )
        st = {
            "height": height,
            "size": size,
            "validators": validators,
            "kvs": {k.hex(): v.hex() for k, v in kvs.items()},
        }
        with self._mtx:  # cometlint: disable=CLNT009 -- snapshot-chunk restore writes the app DB; atomic under the app mutex
            batch = self.db.new_batch()
            for k_hex, v_hex in st["kvs"].items():
                batch.set(_KV_PREFIX + bytes.fromhex(k_hex), bytes.fromhex(v_hex))
            self.height = st["height"]
            self.size = st["size"]
            self._validators = st["validators"]
            self.app_hash = self._compute_app_hash()
            self._stage_state(batch)
            batch.write()
        return abci.ResponseApplySnapshotChunk(
            result=abci.ApplySnapshotChunkResult.ACCEPT
        )
