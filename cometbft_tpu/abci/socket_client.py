"""ABCI socket client (reference: abci/client/socket_client.go:515).

A send thread drains a request queue; a recv thread matches responses to
in-flight ``ReqRes`` entries in FIFO order (the protocol guarantee).
Sync methods enqueue + wait. A transport error completes all in-flight
requests with an error and stops the client — the proxy layer then kills
the node (proxy/multi_app_conn.go:129 semantics).
"""

from __future__ import annotations

import queue
import socket
import threading
from ..libs import sync as libsync

from ..libs import log as _log
from . import codec
from . import types as abci
from .client import Client, ReqRes
from .server import _parse_addr


class SocketClientError(Exception):
    pass


class SocketClient(Client):
    def __init__(self, addr: str, timeout: float = 10.0):
        super().__init__("abci-socket-client")
        self.addr = addr
        self.timeout = timeout
        self._sock: socket.socket | None = None
        self._wfile = None
        self._rfile = None
        self._send_q: queue.Queue[ReqRes | None] = queue.Queue()
        self._inflight: queue.Queue[ReqRes] = queue.Queue()
        # Guards the (_inflight, _send_q) enqueue pair: both queues must see
        # requests in the same order or FIFO response matching breaks.
        self._queue_mtx = libsync.Mutex("abci.socket_client._queue_mtx")

    def on_start(self) -> None:
        family, target = _parse_addr(self.addr)
        if family == "unix":
            self._sock = socket.socket(socket.AF_UNIX)
        else:
            self._sock = socket.socket(socket.AF_INET)
            self._sock.setsockopt(
                socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
            )
        self._sock.connect(target)
        self._wfile = self._sock.makefile("wb")
        self._rfile = self._sock.makefile("rb")
        threading.Thread(
            target=self._send_loop, name="abci-send", daemon=True
        ).start()
        threading.Thread(
            target=self._recv_loop, name="abci-recv", daemon=True
        ).start()

    def on_stop(self) -> None:
        self._send_q.put(None)
        if self._sock:
            try:
                self._sock.close()
            except OSError:
                pass

    # -- io loops ----------------------------------------------------------

    def _send_loop(self) -> None:
        while True:
            rr = self._send_q.get()
            if rr is None:
                return
            try:
                self._wfile.write(codec.encode_frame(rr.method, rr.request))
                self._wfile.flush()
            except Exception as e:  # incl. codec errors — fail loudly
                self._fail(e)
                return

    def _recv_loop(self) -> None:
        while True:
            try:
                frame = codec.read_frame(self._rfile)
            except (OSError, EOFError, ValueError) as e:
                self._fail(e)
                return
            if frame is None:
                if not self.quit_event().is_set():
                    self._fail(EOFError("server closed ABCI connection"))
                return
            method, res = frame
            if method == "exception":
                # Application-level failure: fatal, like the reference's
                # ResponseException handling (socket_client.go).
                err = SocketClientError(str(res))
                try:
                    self._inflight.get_nowait()._complete_error(err)
                except queue.Empty:
                    pass
                self._fail(err)
                return
            try:
                rr = self._inflight.get_nowait()
            except queue.Empty:
                self._fail(SocketClientError(f"unsolicited {method} response"))
                return
            if rr.method != method:
                self._fail(
                    SocketClientError(
                        f"response order mismatch: want {rr.method}, got {method}"
                    )
                )
                return
            rr._complete(res)
            if self._global_cb and rr.method == "check_tx":
                self._global_cb(rr.request, res)

    def _fail(self, err: Exception) -> None:
        # During an orderly stop the dying socket raises in the io loops;
        # that is not a transport failure — don't fail-stop the node.
        closing = self.quit_event().is_set()
        with self._queue_mtx:
            self._err = err
            pending = []
            while True:
                try:
                    pending.append(self._inflight.get_nowait())
                except queue.Empty:
                    break
        for rr in pending:
            rr._complete_error(err)
        if closing:
            return
        if self.is_running():
            try:
                self.stop()
            except Exception as e:  # CLNT006: teardown is best-effort,
                # but a stop() failure during error handling is worth a
                # line — it usually means a wedged reader thread
                _log.default_logger().with_module("abci.socket_client").debug(
                    "stop during error teardown failed", err=repr(e)[:120]
                )
        if self._on_error is not None:
            self._on_error(err)

    # -- request plumbing --------------------------------------------------

    def _queue(self, method: str, req) -> ReqRes:
        rr = ReqRes(method, req)
        with self._queue_mtx:
            if self._err is not None:
                raise SocketClientError(f"client in error state: {self._err}")
            self._inflight.put(rr)  # cometlint: disable=CLNT009 -- unbounded queue: put cannot block
            self._send_q.put(rr)  # cometlint: disable=CLNT009 -- unbounded queue: put cannot block
        return rr

    def _sync(self, method: str, req):
        return self._queue(method, req).wait(self.timeout)

    # -- API ---------------------------------------------------------------

    def echo(self, msg: str) -> str:
        return self._sync("echo", msg)

    def flush(self) -> None:
        self._sync("flush", None)

    def info(self, req):
        return self._sync("info", req)

    def query(self, req):
        return self._sync("query", req)

    def check_tx(self, req):
        return self._sync("check_tx", req)

    def check_tx_async(self, req) -> ReqRes:
        return self._queue("check_tx", req)

    def init_chain(self, req):
        return self._sync("init_chain", req)

    def prepare_proposal(self, req):
        return self._sync("prepare_proposal", req)

    def process_proposal(self, req):
        return self._sync("process_proposal", req)

    def finalize_block(self, req):
        return self._sync("finalize_block", req)

    def extend_vote(self, req):
        return self._sync("extend_vote", req)

    def verify_vote_extension(self, req):
        return self._sync("verify_vote_extension", req)

    def commit(self, req=None):
        return self._sync("commit", req or abci.RequestCommit())

    def list_snapshots(self, req):
        return self._sync("list_snapshots", req)

    def offer_snapshot(self, req):
        return self._sync("offer_snapshot", req)

    def load_snapshot_chunk(self, req):
        return self._sync("load_snapshot_chunk", req)

    def apply_snapshot_chunk(self, req):
        return self._sync("apply_snapshot_chunk", req)
