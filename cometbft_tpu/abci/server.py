"""ABCI socket server (reference: abci/server/socket_server.go:335).

Serves one ``Application`` to any number of node connections over TCP or
unix sockets. Per-connection reader thread handles requests strictly in
order (the ABCI protocol is FIFO; responses are matched positionally by
the client) and writes each response immediately — ``flush`` is a no-op
acknowledgement frame retained for protocol compatibility.
"""

from __future__ import annotations

import os
import socket
import threading
from ..libs import sync as libsync

from ..libs.service import BaseService
from . import codec
from .application import Application


def _parse_addr(addr: str) -> tuple[str, object]:
    """'tcp://host:port' or 'unix:///path' → (family, bind target)."""
    if addr.startswith("unix://"):
        return "unix", addr[len("unix://") :]
    if addr.startswith("tcp://"):
        host, _, port = addr[len("tcp://") :].rpartition(":")
        return "tcp", (host or "127.0.0.1", int(port))
    raise ValueError(f"unsupported ABCI address {addr!r}")


class SocketServer(BaseService):
    def __init__(self, addr: str, app: Application):
        super().__init__("abci-socket-server")
        self.addr = addr
        self.app = app
        self._app_mtx = libsync.Mutex("abci.server._app_mtx")
        self._listener: socket.socket | None = None
        self._conns: list[socket.socket] = []
        self._threads: list[threading.Thread] = []

    def on_start(self) -> None:
        family, target = _parse_addr(self.addr)
        if family == "unix":
            if os.path.exists(target):
                os.unlink(target)  # stale socket from a previous run
            self._listener = socket.socket(socket.AF_UNIX)
        else:
            self._listener = socket.socket(socket.AF_INET)
            self._listener.setsockopt(
                socket.SOL_SOCKET, socket.SO_REUSEADDR, 1
            )
        self._listener.bind(target)
        self._listener.listen(8)
        t = threading.Thread(
            target=self._accept_loop, name="abci-accept", daemon=True
        )
        t.start()
        self._threads.append(t)

    @property
    def bound_addr(self) -> str:
        """Actual address after bind (useful with tcp port 0 in tests)."""
        family, _ = _parse_addr(self.addr)
        if family == "unix":
            return self.addr
        host, port = self._listener.getsockname()
        return f"tcp://{host}:{port}"

    def _accept_loop(self) -> None:
        while not self.quit_event().is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            self._conns.append(conn)
            t = threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            )
            t.start()
            self._threads.append(t)

    # The 14 ABCI methods + protocol control frames; nothing else is
    # reachable over the wire (socket_server.go handleRequest's oneof).
    _METHODS = frozenset(
        {
            "info", "query", "check_tx", "init_chain", "prepare_proposal",
            "process_proposal", "finalize_block", "extend_vote",
            "verify_vote_extension", "commit", "list_snapshots",
            "offer_snapshot", "load_snapshot_chunk", "apply_snapshot_chunk",
        }
    )

    def _serve_conn(self, conn: socket.socket) -> None:
        rfile = conn.makefile("rb")
        wfile = conn.makefile("wb")
        try:
            while True:
                frame = codec.read_frame(rfile)
                if frame is None:
                    return
                method, req = frame
                if method == "echo":
                    method_out, res = method, req
                elif method == "flush":
                    method_out, res = method, None
                elif method not in self._METHODS:
                    method_out, res = "exception", f"unknown method {method!r}"
                else:
                    try:
                        with self._app_mtx:  # cometlint: disable=CLNT009 -- the server app mutex serializes ABCI calls (socket server contract); app persistence happens inside them
                            res = getattr(self.app, method)(req)
                        method_out = method
                    except Exception as e:  # app bug: report, keep serving
                        method_out, res = "exception", f"{method}: {e!r}"
                wfile.write(codec.encode_frame(method_out, res))
                wfile.flush()
        except (EOFError, OSError, ValueError, BrokenPipeError):
            return
        finally:
            conn.close()

    def on_stop(self) -> None:
        if self._listener:
            self._listener.close()
        family, target = _parse_addr(self.addr)
        if family == "unix" and os.path.exists(target):
            try:
                os.unlink(target)
            except OSError:
                pass
        for c in self._conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
