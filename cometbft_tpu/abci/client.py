"""ABCI client abstraction (reference: abci/client/client.go:24,
abci/client/local_client.go:186).

``Client`` = Service + the Application method set + an async CheckTx path
with callbacks (the only method the reference calls asynchronously —
mempool ingress). ``ReqRes`` carries one in-flight request; its callback
fires when the response lands. ``LocalClient`` runs an in-process app
behind one mutex — the default for a single-binary node.
"""

from __future__ import annotations

import threading
from ..libs import sync as libsync
from typing import Callable

from ..libs.service import BaseService
from . import types as abci
from .application import Application


class ReqRes:
    """One request/response pair; ``wait()`` blocks until the response."""

    def __init__(self, method: str, request):
        self.method = method
        self.request = request
        self.response = None
        self.error: Exception | None = None
        self._done = threading.Event()
        self._cb: Callable | None = None
        self._mtx = libsync.Mutex("abci.client._mtx")

    def set_callback(self, cb: Callable) -> None:
        """Fires on successful completion only; error completions surface
        through ``wait()`` / the client's error callback instead."""
        with self._mtx:
            if self._done.is_set():
                done = self.error is None
            else:
                self._cb = cb
                done = False
        if done:
            cb(self.response)

    def _complete(self, response) -> None:
        with self._mtx:
            self.response = response
            cb = self._cb
            self._done.set()
        if cb:
            cb(response)

    def _complete_error(self, err: Exception) -> None:
        with self._mtx:
            self.error = err
            self._done.set()

    def wait(self, timeout: float | None = None):
        if not self._done.wait(timeout):
            raise TimeoutError(f"ABCI {self.method} timed out")
        if self.error is not None:
            raise self.error
        return self.response


class SpeculationUnsupported(Exception):
    """The client/app pair cannot run speculative finalization.

    Raised by ``Client.speculate_finalize`` when the transport is remote
    (socket/grpc — no way to sandbox the app) or the application does
    not implement the optional ``snapshot_spec_state`` /
    ``restore_spec_state`` extension. Callers fall back to the serial
    FinalizeBlock path; the error carries no app-state consequences.
    """


class Client(BaseService):
    """Service + Application surface + async CheckTx + global callback."""

    def __init__(self, name: str = "abci-client"):
        super().__init__(name)
        self._global_cb: Callable | None = None
        self._err: Exception | None = None
        self._on_error: Callable[[Exception], None] | None = None

    def set_response_callback(self, cb: Callable) -> None:
        """Global callback fired for every async response (mempool uses
        this to learn CheckTx results — clist_mempool.go:373)."""
        self._global_cb = cb

    def set_error_callback(self, cb: Callable[[Exception], None]) -> None:
        """Fired once on unrecoverable transport failure; the proxy layer
        uses it to fail-stop the node (multi_app_conn.go:129)."""
        self._on_error = cb

    def error(self) -> Exception | None:
        return self._err

    # sync surface (consensus/query/snapshot connections)
    def echo(self, msg: str) -> str:
        raise NotImplementedError

    def flush(self) -> None:
        raise NotImplementedError

    def info(self, req: abci.RequestInfo) -> abci.ResponseInfo:
        raise NotImplementedError

    def query(self, req: abci.RequestQuery) -> abci.ResponseQuery:
        raise NotImplementedError

    def check_tx(self, req: abci.RequestCheckTx) -> abci.ResponseCheckTx:
        raise NotImplementedError

    def check_tx_async(self, req: abci.RequestCheckTx) -> ReqRes:
        raise NotImplementedError

    def init_chain(self, req) -> abci.ResponseInitChain:
        raise NotImplementedError

    def prepare_proposal(self, req) -> abci.ResponsePrepareProposal:
        raise NotImplementedError

    def process_proposal(self, req) -> abci.ResponseProcessProposal:
        raise NotImplementedError

    def finalize_block(self, req) -> abci.ResponseFinalizeBlock:
        raise NotImplementedError

    def extend_vote(self, req) -> abci.ResponseExtendVote:
        raise NotImplementedError

    def verify_vote_extension(self, req) -> abci.ResponseVerifyVoteExtension:
        raise NotImplementedError

    def commit(self, req=None) -> abci.ResponseCommit:
        raise NotImplementedError

    def list_snapshots(self, req) -> abci.ResponseListSnapshots:
        raise NotImplementedError

    def offer_snapshot(self, req) -> abci.ResponseOfferSnapshot:
        raise NotImplementedError

    def load_snapshot_chunk(self, req) -> abci.ResponseLoadSnapshotChunk:
        raise NotImplementedError

    def apply_snapshot_chunk(self, req) -> abci.ResponseApplySnapshotChunk:
        raise NotImplementedError

    # -- optional speculation extension (consensus/pipeline.py) ------------

    def supports_speculation(self) -> bool:
        """Whether speculate_finalize can work at all for this
        client/app pair (node boot keys COMETBFT_TPU_SPEC_EXEC=auto
        off this)."""
        return False

    def speculate_finalize(self, req) -> tuple:
        """Run FinalizeBlock speculatively and leave the app UNCHANGED.

        Returns ``(response, post_token)`` where ``post_token`` is an
        opaque snapshot of the post-finalize app state; a later
        ``apply_speculation(post_token)`` makes the speculative result
        real without re-executing. Only local clients over apps that
        implement the snapshot/restore extension support this — remote
        transports cannot sandbox the app, so the base client refuses.
        """
        raise SpeculationUnsupported(f"{self.name}: remote ABCI transport")

    def apply_speculation(self, post_token) -> None:
        raise SpeculationUnsupported(f"{self.name}: remote ABCI transport")


class LocalClient(Client):
    """In-process app behind one mutex (local_client.go:186). The mutex may
    be shared across the 4 proxy connections so consensus/mempool/query
    calls serialize exactly like the reference's ``NewLocalClientCreator``.
    """

    def __init__(self, app: Application, mtx: threading.RLock | None = None):
        super().__init__("local-abci-client")
        self.app = app
        self.mtx = mtx or libsync.RLock("abci.client")

    def echo(self, msg: str) -> str:
        return msg

    def flush(self) -> None:
        pass

    def _call(self, method: str, req):
        with self.mtx:  # cometlint: disable=CLNT009 -- the local-client mutex serializes the app exactly like NewLocalClientCreator; app-side persistence is the call's purpose
            return getattr(self.app, method)(req)

    def info(self, req):
        return self._call("info", req)

    def query(self, req):
        return self._call("query", req)

    def check_tx(self, req):
        return self._call("check_tx", req)

    def check_tx_async(self, req) -> ReqRes:
        rr = ReqRes("check_tx", req)
        res = self._call("check_tx", req)
        rr._complete(res)
        if self._global_cb:
            self._global_cb(req, res)
        return rr

    def init_chain(self, req):
        return self._call("init_chain", req)

    def prepare_proposal(self, req):
        return self._call("prepare_proposal", req)

    def process_proposal(self, req):
        return self._call("process_proposal", req)

    def finalize_block(self, req):
        return self._call("finalize_block", req)

    def extend_vote(self, req):
        return self._call("extend_vote", req)

    def verify_vote_extension(self, req):
        return self._call("verify_vote_extension", req)

    def commit(self, req=None):
        return self._call("commit", req or abci.RequestCommit())

    def list_snapshots(self, req):
        return self._call("list_snapshots", req)

    def offer_snapshot(self, req):
        return self._call("offer_snapshot", req)

    def load_snapshot_chunk(self, req):
        return self._call("load_snapshot_chunk", req)

    def apply_snapshot_chunk(self, req):
        return self._call("apply_snapshot_chunk", req)

    # -- speculation (consensus/pipeline.py's cs-spec-exec worker) ---------

    def supports_speculation(self) -> bool:
        return callable(
            getattr(self.app, "snapshot_spec_state", None)
        ) and callable(getattr(self.app, "restore_spec_state", None))

    def speculate_finalize(self, req) -> tuple:
        """FinalizeBlock inside a snapshot/restore sandwich, atomic under
        the shared proxy mutex: snapshot pre → finalize → snapshot post →
        restore pre. The app comes out exactly as it went in, so a
        speculation that never wins (different block, round change, node
        restart) needs no cleanup, and concurrent connections never see
        half-speculated state."""
        with self.mtx:  # cometlint: disable=CLNT009 -- the snapshot/finalize/restore sandwich must be atomic against the other proxy connections
            if not self.supports_speculation():
                raise SpeculationUnsupported(
                    f"{type(self.app).__name__} lacks snapshot_spec_state/"
                    "restore_spec_state"
                )
            pre = self.app.snapshot_spec_state()
            try:
                resp = self.app.finalize_block(req)
                post = self.app.snapshot_spec_state()
            finally:
                self.app.restore_spec_state(pre)
            return resp, post

    def apply_speculation(self, post_token) -> None:
        """Make a speculative finalize real: restore the memoized
        post-finalize state so the following Commit persists it."""
        with self.mtx:  # cometlint: disable=CLNT009 -- restoring the memoized post-state must serialize against the other proxy connections
            if not self.supports_speculation():
                raise SpeculationUnsupported(
                    f"{type(self.app).__name__} lacks restore_spec_state"
                )
            self.app.restore_spec_state(post_token)
