"""Native (C++) runtime components, consumed via ctypes."""
