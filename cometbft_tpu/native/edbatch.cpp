// Native edwards25519 multiscalar multiplication: the host tier of the
// framework's ed25519 batch verification (reference analog: the
// curve25519-voi batch verify behind crypto/ed25519/ed25519.go:196-228 —
// random-linear-combination over the cofactored equation, one MSM).
//
// Role in the framework:
//   * the MEASURED baseline bench.py compares the TPU kernel against
//     (replacing the former "OpenSSL single x 2.0" guess), and
//   * the host fast path for batches below the device crossover —
//     sub-threshold commits (150-validator Cosmos-Hub-sized) verify here
//     at multiscalar speed instead of one-at-a-time OpenSSL.
//
// Split of labor (crypto/host_batch.py drives this via ctypes): Python
// computes the SHA-512 challenges, draws the random 128-bit RLC
// coefficients z_i, enforces S_i < L, and reduces the per-point
// coefficients mod L with CPython bigints (microseconds per batch).
// This file does only what needs native speed: ZIP-215 point
// decompression and the Pippenger bucket MSM over 2N+1 points, checking
//   [8]( [b]B - sum_i [z_i k_i]A_i - sum_i [z_i]R_i ) == O.
//
// Field arithmetic: 5x51-bit limbs on unsigned __int128 accumulators
// (the standard radix-51 schedule for 64-bit targets). Point formulas:
// the same complete a=-1 extended-Edwards formulas as ops/curve.py (see
// its docstring for the ZIP-215 completeness argument). Every add/sub
// output is carried, so limbs stay below 2^52 and every product column
// fits u128 with a wide margin.

#include <atomic>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <vector>

typedef unsigned __int128 u128;
typedef uint64_t u64;

namespace {

// ------------------------------------------------------------- field

struct fe {
    u64 v[5];
};

const u64 MASK51 = ((u64)1 << 51) - 1;
// 2p per limb: subtraction bias (operands are always carried, < 2^52)
const u64 TWO_P0 = 0xFFFFFFFFFFFDAULL;   // 2*(2^51 - 19)
const u64 TWO_P1234 = 0xFFFFFFFFFFFFEULL;  // 2*(2^51 - 1)

inline fe fe_zero() { return fe{{0, 0, 0, 0, 0}}; }
inline fe fe_one() { return fe{{1, 0, 0, 0, 0}}; }

inline void fe_carry_inline(fe& r) {
    u64 c;
    c = r.v[0] >> 51; r.v[0] &= MASK51; r.v[1] += c;
    c = r.v[1] >> 51; r.v[1] &= MASK51; r.v[2] += c;
    c = r.v[2] >> 51; r.v[2] &= MASK51; r.v[3] += c;
    c = r.v[3] >> 51; r.v[3] &= MASK51; r.v[4] += c;
    c = r.v[4] >> 51; r.v[4] &= MASK51; r.v[0] += 19 * c;
    c = r.v[0] >> 51; r.v[0] &= MASK51; r.v[1] += c;
}

inline fe fe_add(const fe& a, const fe& b) {
    fe r;
    for (int i = 0; i < 5; i++) r.v[i] = a.v[i] + b.v[i];
    fe_carry_inline(r);
    return r;
}

inline fe fe_sub(const fe& a, const fe& b) {
    fe r;
    r.v[0] = a.v[0] + TWO_P0 - b.v[0];
    r.v[1] = a.v[1] + TWO_P1234 - b.v[1];
    r.v[2] = a.v[2] + TWO_P1234 - b.v[2];
    r.v[3] = a.v[3] + TWO_P1234 - b.v[3];
    r.v[4] = a.v[4] + TWO_P1234 - b.v[4];
    fe_carry_inline(r);
    return r;
}

inline fe fe_neg(const fe& a) { return fe_sub(fe_zero(), a); }

inline void fe_carry_wide(fe& r, u128 t0, u128 t1, u128 t2, u128 t3,
                          u128 t4) {
    u64 c;
    c = (u64)(t0 >> 51); t0 &= MASK51; t1 += c;
    c = (u64)(t1 >> 51); t1 &= MASK51; t2 += c;
    c = (u64)(t2 >> 51); t2 &= MASK51; t3 += c;
    c = (u64)(t3 >> 51); t3 &= MASK51; t4 += c;
    c = (u64)(t4 >> 51); t4 &= MASK51; t0 += (u128)c * 19;
    c = (u64)(t0 >> 51); t0 &= MASK51; t1 += c;
    r.v[0] = (u64)t0; r.v[1] = (u64)t1; r.v[2] = (u64)t2;
    r.v[3] = (u64)t3; r.v[4] = (u64)t4;
}

fe fe_mul(const fe& a, const fe& b) {
    const u64 *x = a.v, *y = b.v;
    u64 y1_19 = 19 * y[1], y2_19 = 19 * y[2], y3_19 = 19 * y[3],
        y4_19 = 19 * y[4];
    u128 t0 = (u128)x[0] * y[0] + (u128)x[1] * y4_19 + (u128)x[2] * y3_19 +
              (u128)x[3] * y2_19 + (u128)x[4] * y1_19;
    u128 t1 = (u128)x[0] * y[1] + (u128)x[1] * y[0] + (u128)x[2] * y4_19 +
              (u128)x[3] * y3_19 + (u128)x[4] * y2_19;
    u128 t2 = (u128)x[0] * y[2] + (u128)x[1] * y[1] + (u128)x[2] * y[0] +
              (u128)x[3] * y4_19 + (u128)x[4] * y3_19;
    u128 t3 = (u128)x[0] * y[3] + (u128)x[1] * y[2] + (u128)x[2] * y[1] +
              (u128)x[3] * y[0] + (u128)x[4] * y4_19;
    u128 t4 = (u128)x[0] * y[4] + (u128)x[1] * y[3] + (u128)x[2] * y[2] +
              (u128)x[3] * y[1] + (u128)x[4] * y[0];
    fe r;
    fe_carry_wide(r, t0, t1, t2, t3, t4);
    return r;
}

inline fe fe_sq(const fe& a) { return fe_mul(a, a); }

// Fully reduce to the canonical representative in [0, p).
void fe_canon(fe& a) {
    fe_carry_inline(a);
    fe_carry_inline(a);
    // conditional subtract p: q = 1 iff a >= p
    u64 q = (a.v[0] + 19) >> 51;
    q = (a.v[1] + q) >> 51;
    q = (a.v[2] + q) >> 51;
    q = (a.v[3] + q) >> 51;
    q = (a.v[4] + q) >> 51;
    a.v[0] += 19 * q;
    u64 c = 0;
    for (int i = 0; i < 5; i++) {
        u64 t = a.v[i] + c;
        a.v[i] = t & MASK51;
        c = t >> 51;
    }
    // c is the dropped 2^255 bit when a >= p was folded
}

bool fe_is_zero(fe a) {
    fe_canon(a);
    return (a.v[0] | a.v[1] | a.v[2] | a.v[3] | a.v[4]) == 0;
}

bool fe_eq(const fe& a, const fe& b) { return fe_is_zero(fe_sub(a, b)); }

fe fe_frombytes(const uint8_t s[32]) {
    u64 w0, w1, w2, w3;
    memcpy(&w0, s, 8);
    memcpy(&w1, s + 8, 8);
    memcpy(&w2, s + 16, 8);
    memcpy(&w3, s + 24, 8);
    fe r;
    r.v[0] = w0 & MASK51;
    r.v[1] = ((w0 >> 51) | (w1 << 13)) & MASK51;
    r.v[2] = ((w1 >> 38) | (w2 << 26)) & MASK51;
    r.v[3] = ((w2 >> 25) | (w3 << 39)) & MASK51;
    r.v[4] = (w3 >> 12) & MASK51;  // bits 204..254 (sign bit cleared)
    return r;
}

void fe_tobytes(fe a, uint8_t out[32]) {
    fe_canon(a);
    u64 w0 = a.v[0] | (a.v[1] << 51);
    u64 w1 = (a.v[1] >> 13) | (a.v[2] << 38);
    u64 w2 = (a.v[2] >> 26) | (a.v[3] << 25);
    u64 w3 = (a.v[3] >> 39) | (a.v[4] << 12);
    memcpy(out, &w0, 8);
    memcpy(out + 8, &w1, 8);
    memcpy(out + 16, &w2, 8);
    memcpy(out + 24, &w3, 8);
}

fe fe_pow_2_252_m3(const fe& z) {
    // the classic curve25519 addition chain (ops/field.pow_2_252_m3)
    fe z2 = fe_sq(z);
    fe z8 = fe_sq(fe_sq(z2));
    fe z9 = fe_mul(z, z8);
    fe z11 = fe_mul(z2, z9);
    fe z22 = fe_sq(z11);
    fe z_5_0 = fe_mul(z9, z22);
    fe t = z_5_0;
    for (int i = 0; i < 5; i++) t = fe_sq(t);
    fe z_10_0 = fe_mul(t, z_5_0);
    t = z_10_0;
    for (int i = 0; i < 10; i++) t = fe_sq(t);
    fe z_20_0 = fe_mul(t, z_10_0);
    t = z_20_0;
    for (int i = 0; i < 20; i++) t = fe_sq(t);
    fe z_40_0 = fe_mul(t, z_20_0);
    t = z_40_0;
    for (int i = 0; i < 10; i++) t = fe_sq(t);
    fe z_50_0 = fe_mul(t, z_10_0);
    t = z_50_0;
    for (int i = 0; i < 50; i++) t = fe_sq(t);
    fe z_100_0 = fe_mul(t, z_50_0);
    t = z_100_0;
    for (int i = 0; i < 100; i++) t = fe_sq(t);
    fe z_200_0 = fe_mul(t, z_100_0);
    t = z_200_0;
    for (int i = 0; i < 50; i++) t = fe_sq(t);
    fe z_250_0 = fe_mul(t, z_50_0);
    t = fe_sq(fe_sq(z_250_0));
    return fe_mul(t, z);
}

// d and sqrt(-1), canonical little-endian byte encodings.
const uint8_t D_BYTES[32] = {
    0xa3, 0x78, 0x59, 0x13, 0xca, 0x4d, 0xeb, 0x75, 0xab, 0xd8, 0x41,
    0x41, 0x4d, 0x0a, 0x70, 0x00, 0x98, 0xe8, 0x79, 0x77, 0x79, 0x40,
    0xc7, 0x8c, 0x73, 0xfe, 0x6f, 0x2b, 0xee, 0x6c, 0x03, 0x52};
const uint8_t SQRTM1_BYTES[32] = {
    0xb0, 0xa0, 0x0e, 0x4a, 0x27, 0x1b, 0xee, 0xc4, 0x78, 0xe4, 0x2f,
    0xad, 0x06, 0x18, 0x43, 0x2f, 0xa7, 0xd7, 0xfb, 0x3d, 0x99, 0x00,
    0x4d, 0x2b, 0x0b, 0xdf, 0xc1, 0x4f, 0x80, 0x24, 0x83, 0x2b};

fe FE_D, FE_D2, FE_SQRTM1;

// --------------------------------------------------------------- point

struct pt {
    fe x, y, z, t;  // extended coordinates, a = -1
};

pt pt_identity() { return pt{fe_zero(), fe_one(), fe_one(), fe_zero()}; }

pt pt_add(const pt& p, const pt& q) {
    fe a = fe_mul(fe_sub(p.y, p.x), fe_sub(q.y, q.x));
    fe b = fe_mul(fe_add(p.y, p.x), fe_add(q.y, q.x));
    fe c = fe_mul(fe_mul(p.t, FE_D2), q.t);
    fe zz = fe_mul(p.z, q.z);
    fe d = fe_add(zz, zz);
    fe e = fe_sub(b, a);
    fe f = fe_sub(d, c);
    fe g = fe_add(d, c);
    fe h = fe_add(b, a);
    return pt{fe_mul(e, f), fe_mul(g, h), fe_mul(f, g), fe_mul(e, h)};
}

// Input point in affine-Niels form (y+x, y-x, 2d*x*y; Z == 1): the MSM
// scatter phase adds DECOMPRESSED (affine) input points into buckets
// ~64x per point, so precomputing the Niels triple once per point turns
// each bucket add from 9 into 7 field muls (~20% of total MSM muls).
struct niels {
    fe yplusx, yminusx, t2d;
};

niels to_niels(const pt& p) {  // requires z == 1
    return niels{fe_add(p.y, p.x), fe_sub(p.y, p.x), fe_mul(p.t, FE_D2)};
}

pt pt_add_niels(const pt& p, const niels& q) {
    fe a = fe_mul(fe_sub(p.y, p.x), q.yminusx);
    fe b = fe_mul(fe_add(p.y, p.x), q.yplusx);
    fe c = fe_mul(p.t, q.t2d);
    fe d = fe_add(p.z, p.z);
    fe e = fe_sub(b, a);
    fe f = fe_sub(d, c);
    fe g = fe_add(d, c);
    fe h = fe_add(b, a);
    return pt{fe_mul(e, f), fe_mul(g, h), fe_mul(f, g), fe_mul(e, h)};
}

pt pt_double(const pt& p) {
    fe a = fe_sq(p.x);
    fe b = fe_sq(p.y);
    fe zz = fe_sq(p.z);
    fe c = fe_add(zz, zz);
    fe h = fe_add(a, b);
    fe e = fe_sub(h, fe_sq(fe_add(p.x, p.y)));
    fe g = fe_sub(a, b);
    fe f = fe_add(c, g);
    return pt{fe_mul(e, f), fe_mul(g, h), fe_mul(f, g), fe_mul(e, h)};
}

bool pt_is_identity(const pt& p) {
    return fe_is_zero(p.x) && fe_eq(p.y, p.z);
}

// ZIP-215 decompression: y >= p folds mod p in limb arithmetic (exactly
// the ZIP-215 acceptance), "negative zero" x accepted.
bool pt_decompress(const uint8_t enc[32], pt& out) {
    int sign = enc[31] >> 7;
    uint8_t yb[32];
    memcpy(yb, enc, 32);
    yb[31] &= 0x7F;
    fe y = fe_frombytes(yb);
    fe yy = fe_sq(y);
    fe u = fe_sub(yy, fe_one());
    fe v = fe_add(fe_mul(FE_D, yy), fe_one());
    fe v3 = fe_mul(fe_sq(v), v);
    fe v7 = fe_mul(fe_sq(v3), v);
    fe x = fe_mul(fe_mul(u, v3), fe_pow_2_252_m3(fe_mul(u, v7)));
    fe vxx = fe_mul(v, fe_sq(x));
    if (!fe_eq(vxx, u)) {
        if (!fe_eq(vxx, fe_neg(u))) return false;
        x = fe_mul(x, FE_SQRTM1);
    }
    fe xc = x;
    fe_canon(xc);
    if ((int)(xc.v[0] & 1) != sign)
        x = fe_neg(xc);
    else
        x = xc;
    out.x = x;
    out.y = y;
    out.z = fe_one();
    out.t = fe_mul(x, y);
    return true;
}

// --------------------------------------------------------------- MSM
// Pippenger, 8-bit unsigned windows: scalars are 32-byte little-endian
// values < L supplied pre-reduced by the caller; window w is byte w.

pt msm(const std::vector<pt>& points, const uint8_t* coeffs, size_t m) {
    const int NWIN = 32, NBUCKET = 255;
    pt acc = pt_identity();
    std::vector<pt> buckets(NBUCKET);
    std::vector<uint8_t> used(NBUCKET);
    // inputs are affine (z == 1, straight from decompression): hoist
    // their Niels form out of the 32-window scatter loop
    std::vector<niels> npts(m);
    for (size_t i = 0; i < m; i++) npts[i] = to_niels(points[i]);
    for (int w = NWIN - 1; w >= 0; w--) {
        if (w != NWIN - 1)
            for (int i = 0; i < 8; i++) acc = pt_double(acc);
        memset(used.data(), 0, NBUCKET);
        for (size_t i = 0; i < m; i++) {
            int d = coeffs[32 * i + w];
            if (!d) continue;
            if (used[d - 1])
                buckets[d - 1] = pt_add_niels(buckets[d - 1], npts[i]);
            else {
                buckets[d - 1] = points[i];
                used[d - 1] = 1;
            }
        }
        pt running = pt_identity(), sum = pt_identity();
        bool have_running = false;
        for (int b = NBUCKET - 1; b >= 0; b--) {
            if (used[b]) {
                running = have_running ? pt_add(running, buckets[b])
                                       : buckets[b];
                have_running = true;
            }
            if (have_running) sum = pt_add(sum, running);
        }
        acc = pt_add(acc, sum);
    }
    return acc;
}

// ------------------------------------------------------ base-point mult
// Fixed-base scalar multiplication for the SIGNING path (sr25519 nonce
// and public points ride this; verification stays on the MSM above).
// 4-bit fixed windows MSB-first with a CONSTANT-TIME table select:
// signing scalars are secrets, so the lookup touches all 16 entries
// with arithmetic masks — no secret-indexed loads, no secret branches
// (fe ops themselves are u64/u128 arithmetic, constant-time on this
// target).

fe fe_invert(const fe& z) {
    // z^(p-2), p-2 = 8*(2^252 - 3) + 3
    fe a = fe_pow_2_252_m3(z);
    a = fe_sq(fe_sq(fe_sq(a)));
    return fe_mul(a, fe_mul(fe_sq(z), z));
}

// canonical encoding of the ed25519 base point (y = 4/5, even x)
const uint8_t B_BYTES[32] = {
    0x58, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66,
    0x66, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66,
    0x66, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66};

niels G_TABLE[16];  // [v]B in Niels form, v = 0..15 ([0]B = identity)

inline void fe_cmov(fe& r, const fe& a, u64 mask) {
    for (int i = 0; i < 5; i++) r.v[i] ^= mask & (r.v[i] ^ a.v[i]);
}

niels ct_select16(const niels table[16], unsigned v) {
    niels r = table[0];
    for (unsigned i = 1; i < 16; i++) {
        // mask = all-ones iff i == v: diff-1 underflows to 2^64-1 only
        // when diff == 0, so its top bit is the equality predicate
        u64 diff = (u64)(i ^ v);
        u64 mask = (u64)(((int64_t)(diff - 1)) >> 63);
        fe_cmov(r.yplusx, table[i].yplusx, mask);
        fe_cmov(r.yminusx, table[i].yminusx, mask);
        fe_cmov(r.t2d, table[i].t2d, mask);
    }
    return r;
}

pt scalar_base_mult(const uint8_t scalar[32]) {
    pt acc = pt_identity();
    for (int w = 63; w >= 0; w--) {
        if (w != 63)
            for (int i = 0; i < 4; i++) acc = pt_double(acc);
        unsigned byte = scalar[w / 2];
        unsigned v = (w & 1) ? (byte >> 4) : (byte & 0x0F);
        acc = pt_add_niels(acc, ct_select16(G_TABLE, v));
    }
    return acc;
}

// ------------------------------------------------- host packing engine
// The per-lane host work of ops/verify.pack_bytes — the SHA-512
// challenge k = H(R||A||M), its reduction mod L, kneg = (L - k) mod L,
// and the S < L canonicality check — moved to C: the Python loop was
// ~9 us/lane (~36 ms of a 4096-lane pack), a material share of the
// device round trip's host side.
//
// SHA-512 round/init constants are NOT hardcoded: Python computes them
// from the FIPS definition (frac bits of cube/square roots of primes,
// exact integer arithmetic) and installs them once via
// edb_sha512_set_constants; parity with hashlib is pinned by tests.

u64 SHA_K[80];
u64 SHA_H0[8];
std::atomic<bool> g_sha_ready{false};

inline u64 rotr64(u64 x, int n) { return (x >> n) | (x << (64 - n)); }

struct Sha512Ctx {
    u64 h[8];
    uint8_t block[128];
    size_t fill;
    u64 total;
};

void sha_init_ctx(Sha512Ctx& c) {
    memcpy(c.h, SHA_H0, sizeof c.h);
    c.fill = 0;
    c.total = 0;
}

void sha_compress(u64 h[8], const uint8_t* p) {
    u64 w[80];
    for (int i = 0; i < 16; i++) {
        u64 v = 0;
        for (int j = 0; j < 8; j++) v = (v << 8) | p[8 * i + j];
        w[i] = v;
    }
    for (int i = 16; i < 80; i++) {
        u64 s0 = rotr64(w[i - 15], 1) ^ rotr64(w[i - 15], 8) ^
                 (w[i - 15] >> 7);
        u64 s1 = rotr64(w[i - 2], 19) ^ rotr64(w[i - 2], 61) ^
                 (w[i - 2] >> 6);
        w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    u64 a = h[0], b = h[1], c = h[2], d = h[3];
    u64 e = h[4], f = h[5], g = h[6], hh = h[7];
    for (int i = 0; i < 80; i++) {
        u64 S1 = rotr64(e, 14) ^ rotr64(e, 18) ^ rotr64(e, 41);
        u64 ch = (e & f) ^ ((~e) & g);
        u64 t1 = hh + S1 + ch + SHA_K[i] + w[i];
        u64 S0 = rotr64(a, 28) ^ rotr64(a, 34) ^ rotr64(a, 39);
        u64 maj = (a & b) ^ (a & c) ^ (b & c);
        u64 t2 = S0 + maj;
        hh = g; g = f; f = e; e = d + t1;
        d = c; c = b; b = a; a = t1 + t2;
    }
    h[0] += a; h[1] += b; h[2] += c; h[3] += d;
    h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
}

void sha_update(Sha512Ctx& c, const uint8_t* data, size_t len) {
    c.total += len;
    while (len) {
        size_t take = 128 - c.fill;
        if (take > len) take = len;
        memcpy(c.block + c.fill, data, take);
        c.fill += take;
        data += take;
        len -= take;
        if (c.fill == 128) {
            sha_compress(c.h, c.block);
            c.fill = 0;
        }
    }
}

void sha_final(Sha512Ctx& c, uint8_t out[64]) {
    u64 bits = c.total * 8;
    uint8_t pad = 0x80;
    sha_update(c, &pad, 1);
    uint8_t zero = 0;
    while (c.fill != 112) sha_update(c, &zero, 1);
    uint8_t lenb[16] = {0};
    for (int i = 0; i < 8; i++) lenb[15 - i] = (uint8_t)(bits >> (8 * i));
    sha_update(c, lenb, 16);
    for (int i = 0; i < 8; i++)
        for (int j = 0; j < 8; j++)
            out[8 * i + j] = (uint8_t)(c.h[i] >> (56 - 8 * j));
}

// 4-limb (u64 LE) scalar arithmetic mod L = 2^252 + c.
const u64 L_LIMBS[4] = {0x5812631a5cf5d3edULL, 0x14def9dea2f79cd6ULL,
                        0ULL, 0x1000000000000000ULL};
// c = L - 2^252, two limbs
const u64 C_LIMBS[2] = {0x5812631a5cf5d3edULL, 0x14def9dea2f79cd6ULL};
u64 POW64_MOD_L[4][4];  // 2^(64k) mod L for k = 4..7

bool sc_geq(const u64 a[4], const u64 b[4]) {
    for (int i = 3; i >= 0; i--) {
        if (a[i] != b[i]) return a[i] > b[i];
    }
    return true;
}

void sc_sub_inplace(u64 a[4], const u64 b[4]) {
    u64 borrow = 0;
    for (int i = 0; i < 4; i++) {
        u128 t = (u128)a[i] - b[i] - borrow;
        a[i] = (u64)t;
        borrow = (u64)(t >> 64) ? 1 : 0;  // wraps to all-ones on underflow
    }
}

void sc_init_pow64() {
    u64 x[4] = {1, 0, 0, 0};
    int idx = 0;
    for (int bit = 1; bit <= 448; bit++) {
        u64 carry = 0;
        for (int i = 0; i < 4; i++) {
            u64 nv = (x[i] << 1) | carry;
            carry = x[i] >> 63;
            x[i] = nv;
        }
        if (sc_geq(x, L_LIMBS)) sc_sub_inplace(x, L_LIMBS);
        if (bit % 64 == 0 && bit >= 256)
            memcpy(POW64_MOD_L[idx++], x, 32);
    }
}

// x (64 bytes LE) mod L -> out 4 limbs canonical
void sc_reduce512(const uint8_t in[64], u64 out[4]) {
    u64 x[8];
    memcpy(x, in, 64);
    // fold limbs 7..4: acc = x[0..3] + sum x[k] * (2^(64k) mod L)
    u128 a0 = x[0], a1 = x[1], a2 = x[2], a3 = x[3], a4 = 0;
    for (int k = 4; k < 8; k++) {
        const u64* m = POW64_MOD_L[k - 4];
        u128 p0 = (u128)x[k] * m[0];
        u128 p1 = (u128)x[k] * m[1];
        u128 p2 = (u128)x[k] * m[2];
        u128 p3 = (u128)x[k] * m[3];
        // add carries and lows SEPARATELY: u64 + u64 wraps before the
        // u128 accumulator would widen it
        a0 += (u64)p0;
        a1 += (p0 >> 64);
        a1 += (u64)p1;
        a2 += (p1 >> 64);
        a2 += (u64)p2;
        a3 += (p2 >> 64);
        a3 += (u64)p3;
        a4 += (p3 >> 64);
    }
    // carry-normalize into 5 limbs (value < 2^320)
    u64 y[5];
    u128 c = a0;
    y[0] = (u64)c; c = (c >> 64) + a1;
    y[1] = (u64)c; c = (c >> 64) + a2;
    y[2] = (u64)c; c = (c >> 64) + a3;
    y[3] = (u64)c; c = (c >> 64) + a4;
    y[4] = (u64)c;
    // x = hi*2^252 + lo, 2^252 = -c (mod L)  =>  x = lo - hi*c (mod L)
    u64 hi[2];  // < 2^68
    hi[0] = (y[3] >> 60) | (y[4] << 4);
    hi[1] = y[4] >> 60;
    u64 lo[4] = {y[0], y[1], y[2], y[3] & 0x0FFFFFFFFFFFFFFFULL};
    // d = hi * c  (< 2^(68+125) = 2^193, 4 limbs)
    u128 q0 = (u128)hi[0] * C_LIMBS[0];
    u128 q1 = (u128)hi[0] * C_LIMBS[1];
    u128 q2 = (u128)hi[1] * C_LIMBS[0];
    u128 q3 = (u128)hi[1] * C_LIMBS[1];
    u64 d[4];
    c = (u64)q0;
    d[0] = (u64)c; c = (c >> 64) + (u64)(q0 >> 64) + (u64)q1 + (u64)q2;
    d[1] = (u64)c;
    c = (c >> 64) + (u64)(q1 >> 64) + (u64)(q2 >> 64) + (u64)q3;
    d[2] = (u64)c; c = (c >> 64) + (u64)(q3 >> 64);
    d[3] = (u64)c;
    // r = lo - d, + L on underflow (d < 2^193 << L so one add suffices)
    u64 r[4];
    u64 borrow = 0;
    for (int i = 0; i < 4; i++) {
        u64 di = d[i] + borrow;
        u64 nb = (di < borrow) || (lo[i] < di) ? 1 : 0;
        r[i] = lo[i] - di;
        borrow = nb;
    }
    if (borrow) {
        u128 cc = 0;
        for (int i = 0; i < 4; i++) {
            cc += (u128)r[i] + L_LIMBS[i];
            r[i] = (u64)cc;
            cc >>= 64;
        }
    }
    while (sc_geq(r, L_LIMBS)) sc_sub_inplace(r, L_LIMBS);
    memcpy(out, r, 32);
}

// (z * x) mod L for a 128-bit z and canonical 4-limb x: the product is
// < 2^381, so padding it to 512 bits reuses sc_reduce512.
void sc_mul_z_mod_L(const u64 z[2], const u64 x[4], u64 out[4]) {
    u128 acc[6] = {0, 0, 0, 0, 0, 0};
    for (int zi = 0; zi < 2; zi++)
        for (int xi = 0; xi < 4; xi++) {
            u128 p = (u128)z[zi] * x[xi];
            acc[zi + xi] += (u64)p;
            acc[zi + xi + 1] += (u64)(p >> 64);
        }
    u64 pl[8] = {0};
    u128 carry = 0;
    for (int w = 0; w < 6; w++) {
        carry += acc[w];
        pl[w] = (u64)carry;
        carry >>= 64;
    }
    pl[6] = (u64)carry;
    uint8_t prod[64];
    memcpy(prod, pl, 64);
    sc_reduce512(prod, out);
}

// Decompress-all + cofactored-MSM verdict shared by the two batch
// entries: 1 identity, 0 not, -(2+i) when point i fails to decode.
long msm_verdict(const uint8_t* points_enc, const uint8_t* coeffs,
                 size_t m) {
    std::vector<pt> pts(m);
    for (size_t i = 0; i < m; i++)
        if (!pt_decompress(points_enc + 32 * i, pts[i]))
            return -(long)(2 + i);
    pt res = msm(pts, coeffs, m);
    res = pt_double(pt_double(pt_double(res)));
    return pt_is_identity(res) ? 1 : 0;
}

// ctypes releases the GIL during calls, so first-use init can race
// across threads (consensus verify vs RPC verify): call_once makes the
// table/constant build happen exactly once with a proper barrier.
std::once_flag g_init_once;

void init_tables() {
    FE_D = fe_frombytes(D_BYTES);
    FE_D2 = fe_add(FE_D, FE_D);
    FE_SQRTM1 = fe_frombytes(SQRTM1_BYTES);
    sc_init_pow64();
    pt g;
    pt_decompress(B_BYTES, g);
    pt acc = pt_identity();
    for (int v = 0; v < 16; v++) {
        // to_niels requires z == 1: normalize each multiple
        fe zi = fe_invert(acc.z);
        pt aff;
        aff.x = fe_mul(acc.x, zi);
        aff.y = fe_mul(acc.y, zi);
        aff.z = fe_one();
        aff.t = fe_mul(aff.x, aff.y);
        G_TABLE[v] = to_niels(aff);
        acc = pt_add(acc, g);
    }
}

void ensure_init() { std::call_once(g_init_once, init_tables); }

}  // namespace

extern "C" {

// points_enc: m x 32-byte compressed edwards points (ZIP-215 decoding);
// coeffs: m x 32-byte little-endian scalars, already reduced mod L by
// the caller. Computes [8](sum_i [coeff_i]P_i) and returns 1 if it is
// the identity, 0 if not, -(2 + i) if point i fails to decompress.
long edb_msm_is_identity_x8(const uint8_t* points_enc,
                            const uint8_t* coeffs, size_t m) {
    ensure_init();
    return msm_verdict(points_enc, coeffs, m);
}

// keccak-f[1600] permutation over a 200-byte little-endian-lane state.
// The merlin/STROBE transcript layer (crypto/sr25519.py) permutes ~6x
// per signature and per verification-challenge; the pure-Python
// permutation was ~1 ms — the whole remaining signing cost once the
// scalar mult went native.
void edb_keccak_f1600(uint8_t state[200]) {
    static const u64 RC[24] = {
        0x0000000000000001ULL, 0x0000000000008082ULL, 0x800000000000808AULL,
        0x8000000080008000ULL, 0x000000000000808BULL, 0x0000000080000001ULL,
        0x8000000080008081ULL, 0x8000000000008009ULL, 0x000000000000008AULL,
        0x0000000000000088ULL, 0x0000000080008009ULL, 0x000000008000000AULL,
        0x000000008000808BULL, 0x800000000000008BULL, 0x8000000000008089ULL,
        0x8000000000008003ULL, 0x8000000000008002ULL, 0x8000000000000080ULL,
        0x000000000000800AULL, 0x800000008000000AULL, 0x8000000080008081ULL,
        0x8000000000008080ULL, 0x0000000080000001ULL, 0x8000000080008008ULL};
    static const int ROTC[5][5] = {{0, 36, 3, 41, 18},
                                   {1, 44, 10, 45, 2},
                                   {62, 6, 43, 15, 61},
                                   {28, 55, 25, 21, 56},
                                   {27, 20, 39, 8, 14}};
    u64 a[25];
    memcpy(a, state, 200);
    for (int round = 0; round < 24; round++) {
        u64 c[5], d[5], b[25];
        for (int x = 0; x < 5; x++)
            c[x] = a[x] ^ a[x + 5] ^ a[x + 10] ^ a[x + 15] ^ a[x + 20];
        for (int x = 0; x < 5; x++) {
            u64 t = c[(x + 1) % 5];
            d[x] = c[(x + 4) % 5] ^ ((t << 1) | (t >> 63));
        }
        for (int x = 0; x < 5; x++)
            for (int y = 0; y < 5; y++) a[x + 5 * y] ^= d[x];
        for (int x = 0; x < 5; x++)
            for (int y = 0; y < 5; y++) {
                int r = ROTC[x][y];
                u64 v = a[x + 5 * y];
                b[y + 5 * ((2 * x + 3 * y) % 5)] =
                    r ? ((v << r) | (v >> (64 - r))) : v;
            }
        for (int x = 0; x < 5; x++)
            for (int y = 0; y < 5; y++)
                a[x + 5 * y] =
                    b[x + 5 * y] ^ ((~b[(x + 1) % 5 + 5 * y]) &
                                    b[(x + 2) % 5 + 5 * y]);
        a[0] ^= RC[round];
    }
    memcpy(state, a, 200);
}

// [s]B for a 32-byte little-endian scalar (caller reduces mod L), out =
// affine x || y, 64 bytes little-endian. Constant-time window select:
// this is the SIGNING primitive (sr25519 public/nonce points) — the
// scalar is secret.
void edb_scalar_base_mult_xy(const uint8_t scalar[32], uint8_t out[64]) {
    ensure_init();
    pt p = scalar_base_mult(scalar);
    fe zi = fe_invert(p.z);
    fe x = fe_mul(p.x, zi);
    fe y = fe_mul(p.y, zi);
    fe_tobytes(x, out);
    fe_tobytes(y, out + 32);
}

// Install SHA-512 constants (80 round + 8 init words, big-endian u64
// values) computed by the Python side from the FIPS definition.
void edb_sha512_set_constants(const uint64_t* k80, const uint64_t* h8) {
    memcpy(SHA_K, k80, sizeof SHA_K);
    memcpy(SHA_H0, h8, sizeof SHA_H0);
    g_sha_ready = true;
}

// Batched challenge packing: per lane i, recs holds A(32) | R(32) | S(32)
// and msgs[offs[i]:offs[i+1]] the sign bytes. Computes
// k = SHA512(R || A || M) mod L, writes (L - k) mod L little-endian to
// out_kneg, and out_ok[i] = (S < L). Returns 0, or -1 if constants were
// never installed.
long edb_pack_challenges(const uint8_t* recs, const uint8_t* msgs,
                         const uint64_t* offs, size_t n,
                         uint8_t* out_kneg, uint8_t* out_ok) {
    if (!g_sha_ready) return -1;
    ensure_init();
    for (size_t i = 0; i < n; i++) {
        const uint8_t* a = recs + 96 * i;
        const uint8_t* r = a + 32;
        const uint8_t* s = a + 64;
        Sha512Ctx c;
        sha_init_ctx(c);
        sha_update(c, r, 32);
        sha_update(c, a, 32);
        sha_update(c, msgs + offs[i], (size_t)(offs[i + 1] - offs[i]));
        uint8_t digest[64];
        sha_final(c, digest);
        u64 k[4];
        sc_reduce512(digest, k);
        // kneg = (L - k) mod L
        u64 kneg[4] = {0, 0, 0, 0};
        if (k[0] | k[1] | k[2] | k[3]) {
            memcpy(kneg, L_LIMBS, 32);
            sc_sub_inplace(kneg, k);
        }
        memcpy(out_kneg + 32 * i, kneg, 32);
        u64 sv[4];
        memcpy(sv, s, 32);
        out_ok[i] = sc_geq(sv, L_LIMBS) ? 0 : 1;
    }
    return 0;
}

// Fused happy-path batch verification: per lane i, recs holds
// A(32) | R(32) | S(32), msgs[offs[i]:offs[i+1]] the sign bytes, and
// zs 16 random bytes (the RLC coefficient, drawn by the caller from a
// CSPRNG). Computes k_i = SHA512(R||A||M) mod L, the coefficients
// -(z_i*k_i) mod L for A_i and +z_i for -R_i, the basepoint scalar
// b = sum z_i*s_i mod L, and runs the cofactored MSM — the entire
// per-lane preparation that used to be Python bigints. Returns the MSM
// verdict (1 valid, 0 fail, -(2+i) decode failure at MSM point i), or
// -1 if SHA constants were never installed. Rejecting S >= L stays the
// CALLER's job (it filters those lanes out before building recs).
long edb_verify_batch(const uint8_t* recs, const uint8_t* msgs,
                      const uint64_t* offs, const uint8_t* zs, size_t n) {
    if (!g_sha_ready) return -1;
    ensure_init();
    std::vector<uint8_t> points(32 * (2 * n + 1));
    std::vector<uint8_t> coeffs(32 * (2 * n + 1));
    u64 b[4] = {0, 0, 0, 0};
    for (size_t i = 0; i < n; i++) {
        const uint8_t* a = recs + 96 * i;
        const uint8_t* r = a + 32;
        const uint8_t* s = a + 64;
        Sha512Ctx c;
        sha_init_ctx(c);
        sha_update(c, r, 32);
        sha_update(c, a, 32);
        sha_update(c, msgs + offs[i], (size_t)(offs[i + 1] - offs[i]));
        uint8_t digest[64];
        sha_final(c, digest);
        u64 k[4];
        sc_reduce512(digest, k);
        u64 z[2];
        memcpy(z, zs + 16 * i, 16);
        u64 zk[4];
        sc_mul_z_mod_L(z, k, zk);
        // coeff for A_i: (L - zk) mod L
        u64 czk[4] = {0, 0, 0, 0};
        if (zk[0] | zk[1] | zk[2] | zk[3]) {
            memcpy(czk, L_LIMBS, 32);
            sc_sub_inplace(czk, zk);
        }
        memcpy(&points[32 * (2 * i)], a, 32);
        memcpy(&coeffs[32 * (2 * i)], czk, 32);
        // -R_i with coefficient +z (sign-bit flip; short coeff keeps
        // half the Pippenger windows idle — same trick as the caller)
        memcpy(&points[32 * (2 * i + 1)], r, 32);
        points[32 * (2 * i + 1) + 31] ^= 0x80;
        memcpy(&coeffs[32 * (2 * i + 1)], z, 16);
        memset(&coeffs[32 * (2 * i + 1)] + 16, 0, 16);
        // b += (z * s) mod L
        u64 sv[4];
        memcpy(sv, s, 32);
        u64 zsv[4];
        sc_mul_z_mod_L(z, sv, zsv);
        u128 cc = 0;
        for (int w = 0; w < 4; w++) {
            cc += (u128)b[w] + zsv[w];
            b[w] = (u64)cc;
            cc >>= 64;
        }
        // b < 2L after the add (both operands canonical): one subtract
        if (cc || sc_geq(b, L_LIMBS)) sc_sub_inplace(b, L_LIMBS);
    }
    memcpy(&points[32 * 2 * n], B_BYTES, 32);
    memcpy(&coeffs[32 * 2 * n], b, 32);
    return msm_verdict(points.data(), coeffs.data(), 2 * n + 1);
}

// ---------------------------------------------------------------------
// STROBE-128 / merlin — the schnorrkel transcript layer.
//
// Mirrors crypto/sr25519.py's Strobe128/Transcript subset byte-for-byte
// (parity pinned by tests against the Python state machine, which is
// itself pinned to merlin's published protocol vector). Verify-side
// challenges are the sr25519 batch hot path (reference:
// crypto/sr25519/batch.go:14-46): each lane permutes the sponge ~6
// times, and before this the absorb/squeeze byte pushing ran in Python.
// ---------------------------------------------------------------------

namespace {

constexpr int STROBE_R = 166;  // security level 128 -> rate 166

struct Strobe {
    uint8_t st[200];
    uint8_t pos, pos_begin, flags;
};

void strobe_f(Strobe& s) {
    s.st[s.pos] ^= s.pos_begin;
    s.st[s.pos + 1] ^= 0x04;
    s.st[STROBE_R + 1] ^= 0x80;
    edb_keccak_f1600(s.st);
    s.pos = 0;
    s.pos_begin = 0;
}

void strobe_absorb(Strobe& s, const uint8_t* d, size_t n) {
    for (size_t i = 0; i < n; i++) {
        s.st[s.pos++] ^= d[i];
        if (s.pos == STROBE_R) strobe_f(s);
    }
}

void strobe_begin(Strobe& s, uint8_t flags) {
    // header absorbs the OLD pos_begin, then records the new one
    uint8_t hdr[2] = {s.pos_begin, flags};
    s.pos_begin = (uint8_t)(s.pos + 1);
    s.flags = flags;
    strobe_absorb(s, hdr, 2);
    if ((flags & 0x24) && s.pos != 0) strobe_f(s);  // C|K force a round
}

void strobe_meta_ad(Strobe& s, const uint8_t* d, size_t n) {
    strobe_begin(s, 0x12);  // M|A
    strobe_absorb(s, d, n);
}

void strobe_ad(Strobe& s, const uint8_t* d, size_t n) {
    strobe_begin(s, 0x02);  // A
    strobe_absorb(s, d, n);
}

void strobe_prf(Strobe& s, uint8_t* out, size_t n) {
    strobe_begin(s, 0x07);  // I|A|C
    for (size_t i = 0; i < n; i++) {
        out[i] = s.st[s.pos];
        s.st[s.pos++] = 0;
        if (s.pos == STROBE_R) strobe_f(s);
    }
}

// ---- ristretto255 (RFC 9496) decode -> compressed edwards ----
// sr25519 feeds the SAME curve machinery as ed25519 (host MSM and TPU
// kernel both take compressed edwards points); this is the per-lane
// ristretto_decode + edwards compression that was 4 Python modexps.

bool fe_isneg(const fe& a) {
    uint8_t b[32];
    fe_tobytes(a, b);
    return b[0] & 1;
}

fe fe_abs(const fe& a) { return fe_isneg(a) ? fe_neg(a) : a; }

// sqrt_ratio_m1 specialized to u == 1 (RFC 9496 §4.2): out = 1/sqrt(v)
// (or 1/sqrt(i*v)); returns was_square.
bool fe_invsqrt(const fe& v, fe& out) {
    fe v3 = fe_mul(fe_sq(v), v);
    fe v7 = fe_mul(fe_sq(v3), v);
    fe r = fe_mul(v3, fe_pow_2_252_m3(v7));
    fe check = fe_mul(v, fe_sq(r));
    fe one = fe_one();
    bool correct = fe_eq(check, one);
    bool flipped = fe_eq(check, fe_neg(one));
    bool flipped_i = fe_eq(check, fe_neg(FE_SQRTM1));
    if (flipped || flipped_i) r = fe_mul(r, FE_SQRTM1);
    out = fe_abs(r);
    return correct || flipped;
}

// RFC 9496 §4.3.1 decode; writes the compressed edwards encoding of
// the decoded (affine) point. False for non-canonical/negative/invalid.
bool ristretto_to_edwards(const uint8_t enc[32], uint8_t out[32]) {
    fe s = fe_frombytes(enc);
    uint8_t canon[32];
    fe_tobytes(s, canon);
    if (memcmp(canon, enc, 32) != 0) return false;  // s >= P
    if (enc[0] & 1) return false;                   // s negative
    fe ss = fe_sq(s);
    fe u1 = fe_sub(fe_one(), ss);
    fe u2 = fe_add(fe_one(), ss);
    fe u2s = fe_sq(u2);
    fe v = fe_sub(fe_neg(fe_mul(FE_D, fe_sq(u1))), u2s);
    fe invsqrt;
    bool ws = fe_invsqrt(fe_mul(v, u2s), invsqrt);
    fe den_x = fe_mul(invsqrt, u2);
    fe den_y = fe_mul(fe_mul(invsqrt, den_x), v);
    fe x = fe_abs(fe_mul(fe_add(s, s), den_x));
    fe y = fe_mul(u1, den_y);
    fe t = fe_mul(x, y);
    if (!ws || fe_isneg(t) || fe_is_zero(y)) return false;
    uint8_t xb[32];
    fe_tobytes(x, xb);
    fe_tobytes(y, out);
    out[31] |= (uint8_t)((xb[0] & 1) << 7);
    return true;
}

// merlin append_message: meta_AD(label || LE32(len)); AD(message)
void merlin_append(Strobe& s, const char* label, size_t label_len,
                   const uint8_t* msg, size_t msg_len) {
    uint8_t hdr[20];
    memcpy(hdr, label, label_len);
    hdr[label_len + 0] = (uint8_t)(msg_len);
    hdr[label_len + 1] = (uint8_t)(msg_len >> 8);
    hdr[label_len + 2] = (uint8_t)(msg_len >> 16);
    hdr[label_len + 3] = (uint8_t)(msg_len >> 24);
    strobe_meta_ad(s, hdr, label_len + 4);
    strobe_ad(s, msg, msg_len);
}

}  // namespace

// Batched schnorrkel verification challenges. ``ctx`` is the 203-byte
// serialized STROBE state (200-byte sponge || pos || pos_begin ||
// cur_flags) of a merlin transcript already carrying
// Transcript("SigningContext") + append_message("", signing_context) —
// a pure function of the signing context, built once by the caller and
// cached. Per lane i, recs holds pk(32) | R(32) and
// msgs[offs[i]:offs[i+1]] the sign bytes; writes
// k_i = PRF64("sign:c") mod L (32 bytes little-endian) to out_k.
long edb_sr_challenge_batch(const uint8_t* ctx, const uint8_t* recs,
                            const uint8_t* msgs, const uint64_t* offs,
                            size_t n, uint8_t* out_k) {
    ensure_init();  // sc_reduce512 needs POW64_MOD_L
    Strobe base;
    memcpy(base.st, ctx, 200);
    base.pos = ctx[200];
    base.pos_begin = ctx[201];
    base.flags = ctx[202];
    for (size_t i = 0; i < n; i++) {
        Strobe s = base;
        merlin_append(s, "sign-bytes", 10, msgs + offs[i],
                      (size_t)(offs[i + 1] - offs[i]));
        merlin_append(s, "proto-name", 10,
                      (const uint8_t*)"Schnorr-sig", 11);
        merlin_append(s, "sign:pk", 7, recs + 64 * i, 32);
        merlin_append(s, "sign:R", 6, recs + 64 * i + 32, 32);
        // challenge_bytes("sign:c", 64): meta_AD(label||LE32(64)); PRF
        static const uint8_t clbl[10] = {'s', 'i', 'g', 'n', ':', 'c',
                                         64,  0,   0,   0};
        strobe_meta_ad(s, clbl, 10);
        uint8_t prf[64];
        strobe_prf(s, prf, 64);
        u64 k[4];
        sc_reduce512(prf, k);
        memcpy(out_k + 32 * i, k, 32);
    }
    return 0;
}

// Batched ristretto255 -> compressed-edwards conversion (RFC 9496
// decode + edwards compression): out_enc[i] gets the 32-byte edwards
// encoding, out_ok[i] = 1 iff encs[i] is a valid canonical ristretto
// encoding. Feeds both sr25519 batch paths (host MSM and TPU kernel
// take compressed edwards points).
void edb_ristretto_to_edwards(const uint8_t* encs, size_t m,
                              uint8_t* out_enc, uint8_t* out_ok) {
    ensure_init();
    for (size_t i = 0; i < m; i++)
        out_ok[i] =
            ristretto_to_edwards(encs + 32 * i, out_enc + 32 * i) ? 1 : 0;
}

// Batched decompress-only check (ZIP-215): out[i] = 1 if points_enc[i]
// decodes. Used for fast per-lane attribution of decode failures.
void edb_decompress_ok(const uint8_t* points_enc, size_t m, uint8_t* out) {
    ensure_init();
    pt tmp;
    for (size_t i = 0; i < m; i++)
        out[i] = pt_decompress(points_enc + 32 * i, tmp) ? 1 : 0;
}

}  // extern "C"
