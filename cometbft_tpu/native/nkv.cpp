// Native log-structured KV storage engine (the framework's analog of the
// reference's cgo storage backends — cleveldb/rocksdb slots in
// cometbft-db, config/config.go:256). Same semantics as libs/db.py's
// FileDB: ordered index, append-only CRC-framed log, atomic batches,
// torn-tail tolerance, live-set compaction — implemented in C++ for the
// node's disk hot path and exposed through a minimal C ABI consumed via
// ctypes (no pybind11 in the image).
//
// File framing: 5-byte magic "NKV1\n", then records:
//   [u8 op][u32 klen][u32 vlen][key][value][u32 crc]
//   op: 1=SET 2=DEL 3=BATCH (value = concatenated sub-records, no crc)
//   crc: CRC32 over op|klen|vlen|key|value
// A torn/corrupt tail record terminates replay (crash mid-append loses
// at most the final record; a BATCH is one record, hence atomic).
// A non-empty file whose head is not the magic is a FOREIGN format
// (e.g. Python FileDB, magic "FKV1\n") — open refuses (-1) rather than
// parsing zero records and truncating someone else's database to zero.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <cstdlib>
#include <map>
#include <string>
#include <vector>

#include <unistd.h>  // fsync, ftruncate, fileno

extern "C" {

struct NKV;

}  // extern "C"

namespace {

uint32_t crc_table[256];
bool crc_init_done = false;

void crc_init() {
    if (crc_init_done) return;
    for (uint32_t i = 0; i < 256; i++) {
        uint32_t c = i;
        for (int k = 0; k < 8; k++)
            c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
        crc_table[i] = c;
    }
    crc_init_done = true;
}

uint32_t crc32(uint32_t crc, const uint8_t* buf, size_t len) {
    crc = crc ^ 0xFFFFFFFFu;
    for (size_t i = 0; i < len; i++)
        crc = crc_table[(crc ^ buf[i]) & 0xFF] ^ (crc >> 8);
    return crc ^ 0xFFFFFFFFu;
}

void put_u32(std::string& out, uint32_t v) {
    out.push_back((char)(v & 0xFF));
    out.push_back((char)((v >> 8) & 0xFF));
    out.push_back((char)((v >> 16) & 0xFF));
    out.push_back((char)((v >> 24) & 0xFF));
}

uint32_t get_u32(const uint8_t* p) {
    return (uint32_t)p[0] | ((uint32_t)p[1] << 8) | ((uint32_t)p[2] << 16) |
           ((uint32_t)p[3] << 24);
}

std::string frame(uint8_t op, const std::string& k, const std::string& v,
                  bool with_crc) {
    std::string rec;
    rec.push_back((char)op);
    put_u32(rec, (uint32_t)k.size());
    put_u32(rec, (uint32_t)v.size());
    rec += k;
    rec += v;
    if (with_crc) {
        uint32_t c = crc32(0, (const uint8_t*)rec.data(), rec.size());
        put_u32(rec, c);
    }
    return rec;
}

// Parse one record at buf[pos..len). Returns false on truncation/corruption.
bool parse_record(const uint8_t* buf, size_t len, size_t& pos, bool with_crc,
                  uint8_t& op, std::string& k, std::string& v) {
    if (pos + 9 > len) return false;
    op = buf[pos];
    uint32_t klen = get_u32(buf + pos + 1);
    uint32_t vlen = get_u32(buf + pos + 5);
    size_t body = 9 + (size_t)klen + vlen;
    size_t total = body + (with_crc ? 4 : 0);
    if (pos + total > len) return false;
    if (with_crc) {
        uint32_t want = get_u32(buf + pos + body);
        uint32_t got = crc32(0, buf + pos, body);
        if (want != got) return false;
    }
    k.assign((const char*)buf + pos + 9, klen);
    v.assign((const char*)buf + pos + 9 + klen, vlen);
    pos += total;
    return true;
}

}  // namespace

extern "C" {

struct NKV {
    std::string path;
    std::map<std::string, std::string> data;
    FILE* log = nullptr;
    size_t records = 0;       // total records appended since open/compact
    int compact_factor = 4;   // compact when records > factor * live
};

static void nkv_apply(NKV* h, uint8_t op, const std::string& k,
                      const std::string& v) {
    if (op == 1) {
        h->data[k] = v;
    } else if (op == 2) {
        h->data.erase(k);
    } else if (op == 3) {
        size_t pos = 0;
        const uint8_t* buf = (const uint8_t*)v.data();
        uint8_t sop;
        std::string sk, sv;
        while (pos < v.size() &&
               parse_record(buf, v.size(), pos, /*crc=*/false, sop, sk, sv))
            nkv_apply(h, sop, sk, sv);
    }
}

static const char kMagic[5] = {'N', 'K', 'V', '1', '\n'};

NKV* nkv_open(const char* path, int compact_factor) {
    crc_init();
    NKV* h = new NKV();
    h->path = path;
    h->compact_factor = compact_factor > 0 ? compact_factor : 4;
    bool need_magic = true;
    // replay existing log
    FILE* f = fopen(path, "rb");
    if (f) {
        fseek(f, 0, SEEK_END);
        long sz = ftell(f);
        fseek(f, 0, SEEK_SET);
        std::vector<uint8_t> buf((size_t)(sz > 0 ? sz : 0));
        if (sz > 0 && fread(buf.data(), 1, (size_t)sz, f) != (size_t)sz) {
            fclose(f);
            delete h;
            return nullptr;
        }
        fclose(f);
        if (!buf.empty() && buf.size() < sizeof(kMagic) &&
            memcmp(buf.data(), kMagic, buf.size()) == 0) {
            // Crash between creation and the magic becoming durable: a
            // strict prefix of the magic is a torn tail of an EMPTY
            // database — reset to empty, not a foreign-format refusal.
            FILE* t = fopen(path, "rb+");
            if (t) {
                if (ftruncate(fileno(t), 0) != 0) { /* best effort */ }
                fclose(t);
            }
            buf.clear();
        }
        if (!buf.empty()) {
            // Foreign on-disk format (FileDB or anything else): refuse —
            // truncating an unparseable file would erase it.
            if (buf.size() < sizeof(kMagic) ||
                memcmp(buf.data(), kMagic, sizeof(kMagic)) != 0) {
                delete h;
                return nullptr;
            }
            need_magic = false;
            size_t pos = sizeof(kMagic);
            uint8_t op;
            std::string k, v;
            while (pos < buf.size() &&
                   parse_record(buf.data(), buf.size(), pos, true, op, k, v)) {
                nkv_apply(h, op, k, v);
                h->records++;
            }
            // truncate any torn tail so future appends start clean
            if (pos < buf.size()) {
                FILE* t = fopen(path, "rb+");
                if (t) {
                    if (ftruncate(fileno(t), (off_t)pos) != 0) { /* best effort */ }
                    fclose(t);
                }
            }
        }
    }
    h->log = fopen(path, "ab");
    if (!h->log) {
        delete h;
        return nullptr;
    }
    if (need_magic &&
        (fwrite(kMagic, 1, sizeof(kMagic), h->log) != sizeof(kMagic) ||
         fflush(h->log) != 0)) {
        fclose(h->log);
        delete h;
        return nullptr;
    }
    return h;
}

static int nkv_append(NKV* h, uint8_t op, const std::string& k,
                      const std::string& v, int sync) {
    if (!h->log) return -1;  // a failed compaction reopen: fail cleanly
    std::string rec = frame(op, k, v, true);
    if (fwrite(rec.data(), 1, rec.size(), h->log) != rec.size()) return -1;
    if (fflush(h->log) != 0) return -1;
    if (sync && fsync(fileno(h->log)) != 0) return -1;
    h->records++;
    return 0;
}

static void nkv_maybe_compact(NKV* h);

int nkv_set(NKV* h, const uint8_t* k, size_t klen, const uint8_t* v,
            size_t vlen, int sync) {
    std::string key((const char*)k, klen), val((const char*)v, vlen);
    if (nkv_append(h, 1, key, val, sync) != 0) return -1;
    h->data[key] = val;
    nkv_maybe_compact(h);
    return 0;
}

int nkv_delete(NKV* h, const uint8_t* k, size_t klen, int sync) {
    std::string key((const char*)k, klen);
    if (h->data.find(key) == h->data.end()) return 0;
    if (nkv_append(h, 2, key, "", sync) != 0) return -1;
    h->data.erase(key);
    nkv_maybe_compact(h);
    return 0;
}

int nkv_get(NKV* h, const uint8_t* k, size_t klen, uint8_t** out,
            size_t* outlen) {
    auto it = h->data.find(std::string((const char*)k, klen));
    if (it == h->data.end()) return 1;  // not found
    *out = (uint8_t*)malloc(it->second.size());
    memcpy(*out, it->second.data(), it->second.size());
    *outlen = it->second.size();
    return 0;
}

// ops buffer: concatenated crc-less records (op|klen|vlen|key|value)*
int nkv_batch(NKV* h, const uint8_t* ops, size_t len, int sync) {
    std::string blob((const char*)ops, len);
    if (nkv_append(h, 3, "", blob, sync) != 0) return -1;
    nkv_apply(h, 3, "", blob);
    nkv_maybe_compact(h);
    return 0;
}

// Range [start, end) in order (rev=1: reversed). NULL start/end = open.
// Returns a malloc'd buffer of (u32 klen|key|u32 vlen|value)*.
int nkv_range(NKV* h, const uint8_t* start, size_t slen, const uint8_t* end,
              size_t elen, int rev, uint8_t** out, size_t* outlen) {
    // An inverted range (start ordered at/after end) is empty — matching
    // the Python backends; iterating lo..hi with lo past hi would walk
    // off the map (UB).
    if (start && end &&
        std::string((const char*)start, slen) >=
            std::string((const char*)end, elen)) {
        *out = (uint8_t*)malloc(1);
        *outlen = 0;
        return 0;
    }
    auto lo = start ? h->data.lower_bound(std::string((const char*)start, slen))
                    : h->data.begin();
    auto hi = end ? h->data.lower_bound(std::string((const char*)end, elen))
                  : h->data.end();
    std::string buf;
    if (!rev) {
        for (auto it = lo; it != hi; ++it) {
            put_u32(buf, (uint32_t)it->first.size());
            buf += it->first;
            put_u32(buf, (uint32_t)it->second.size());
            buf += it->second;
        }
    } else {
        for (auto it = hi; it != lo;) {
            --it;
            put_u32(buf, (uint32_t)it->first.size());
            buf += it->first;
            put_u32(buf, (uint32_t)it->second.size());
            buf += it->second;
        }
    }
    *out = (uint8_t*)malloc(buf.size() ? buf.size() : 1);
    memcpy(*out, buf.data(), buf.size());
    *outlen = buf.size();
    return 0;
}

void nkv_free(uint8_t* p) { free(p); }

int nkv_compact(NKV* h) {
    std::string tmp = h->path + ".compact";
    FILE* f = fopen(tmp.c_str(), "wb");
    if (!f) return -1;
    if (fwrite(kMagic, 1, sizeof(kMagic), f) != sizeof(kMagic)) {
        fclose(f);
        remove(tmp.c_str());
        return -1;
    }
    for (auto& kv : h->data) {
        std::string rec = frame(1, kv.first, kv.second, true);
        if (fwrite(rec.data(), 1, rec.size(), f) != rec.size()) {
            fclose(f);
            remove(tmp.c_str());
            return -1;
        }
    }
    if (fflush(f) != 0 || fsync(fileno(f)) != 0) {
        fclose(f);
        remove(tmp.c_str());
        return -1;
    }
    fclose(f);
    fclose(h->log);
    h->log = nullptr;
    if (rename(tmp.c_str(), h->path.c_str()) != 0) {
        h->log = fopen(h->path.c_str(), "ab");
        return -1;
    }
    h->log = fopen(h->path.c_str(), "ab");
    if (!h->log)  // retry once; appends return -1 while it stays null
        h->log = fopen(h->path.c_str(), "ab");
    h->records = h->data.size();
    return h->log ? 0 : -1;
}

static void nkv_maybe_compact(NKV* h) {
    if (h->records > 64 &&
        h->records > (size_t)h->compact_factor * (h->data.size() + 1))
        nkv_compact(h);
}

size_t nkv_count(NKV* h) { return h->data.size(); }

int nkv_sync(NKV* h) {
    if (!h->log) return -1;  // failed compaction reopen, same as nkv_append
    return fsync(fileno(h->log)) == 0 ? 0 : -1;
}

void nkv_close(NKV* h) {
    if (h->log) fclose(h->log);
    delete h;
}

}  // extern "C"
