"""VoteSet: 2/3-majority tracking per (height, round, type).

Reference: types/vote_set.go. Key behaviors preserved:

* one "primary" vote per validator (by index); a conflicting vote for a
  different block is only admitted if some peer claimed a 2/3 majority for
  that block (set_peer_maj23) — otherwise it surfaces as
  ConflictingVoteError carrying both votes (evidence input);
* per-block tallies; ``maj23`` latches the first block to cross 2/3;
* signature verification happens BEFORE admission. Beyond the reference,
  ``add_votes_batch`` admits a whole micro-batch through the device
  verifier in one launch (the SURVEY §7(d) vote-ingest design; single
  ``add_vote`` keeps the reference's per-vote path);
* an internal mutex (vote_set.go:60 ``mtx``): admission runs on the
  consensus receive thread, but per-peer gossip routines concurrently
  read bit arrays / tallies and blocksync builds commits — multi-field
  state (votes, bit array, sum, maj23) must never tear across readers
  (exercised by tests/test_stress_concurrency.py, the ``-race`` tier).
"""

from __future__ import annotations

from dataclasses import dataclass

from ..crypto import batch as crypto_batch
from ..libs import sync as libsync
from ..libs.bits import BitArray
from . import canonical
from .block import (
    BLOCK_ID_FLAG_COMMIT,
    BlockID,
    Commit,
)
from .validator_set import ValidatorSet
from .vote import Vote, VoteError


class VoteSetError(Exception):
    pass


@dataclass
class ConflictingVoteError(VoteSetError):
    existing: Vote
    new: Vote

    def __str__(self) -> str:
        return (
            f"conflicting votes from validator "
            f"{self.new.validator_address.hex()}"
        )


class _BlockVotes:
    __slots__ = ("peer_maj23", "bit_array", "votes", "sum")

    def __init__(self, peer_maj23: bool, num_validators: int):
        self.peer_maj23 = peer_maj23
        self.bit_array = BitArray(num_validators)
        self.votes: list[Vote | None] = [None] * num_validators
        self.sum = 0

    def add_verified_vote(self, vote: Vote, voting_power: int) -> None:
        idx = vote.validator_index
        if self.votes[idx] is None:
            self.bit_array.set_index(idx, True)
            self.votes[idx] = vote
            self.sum += voting_power

    def get_by_index(self, idx: int) -> Vote | None:
        return self.votes[idx]


class VoteSet:
    def __init__(
        self,
        chain_id: str,
        height: int,
        round_: int,
        signed_msg_type: int,
        val_set: ValidatorSet,
        extensions_enabled: bool = False,
        sig_memo: dict | None = None,
    ):
        if height == 0:
            raise VoteSetError("cannot make VoteSet for height 0")
        if extensions_enabled and signed_msg_type != canonical.PRECOMMIT_TYPE:
            raise VoteSetError("extensions require precommit vote set")
        # Optional shared memo of batch-preverified signatures:
        # (pubkey bytes, sign bytes, signature) -> bool. Filled by the
        # consensus receive loop's micro-batch launch so per-vote admission
        # skips the signature check (SURVEY §7(d)); entries are popped on
        # use to bound memory.
        self.sig_memo = sig_memo
        self._mtx = libsync.RLock("vote_set")
        self.chain_id = chain_id
        self.height = height
        self.round = round_
        self.signed_msg_type = signed_msg_type
        self.val_set = val_set
        self.extensions_enabled = extensions_enabled
        self.votes_bit_array = BitArray(len(val_set))
        self.votes: list[Vote | None] = [None] * len(val_set)
        self.sum = 0
        self.maj23: BlockID | None = None
        self.votes_by_block: dict[bytes, _BlockVotes] = {}
        self.peer_maj23s: dict[str, BlockID] = {}

    # --- queries -------------------------------------------------------------

    def size(self) -> int:
        return len(self.val_set)

    def get_by_index(self, idx: int) -> Vote | None:
        # queries take the (reentrant) mutex like the reference
        # (vote_set.go guards every accessor): the gossip routines read
        # while the FSM thread's add_vote writes
        with self._mtx:
            return self.votes[idx]

    def get_by_address(self, address: bytes) -> Vote | None:
        with self._mtx:
            idx, _ = self.val_set.get_by_address(address)
            return self.votes[idx] if idx >= 0 else None

    def two_thirds_majority(self) -> BlockID | None:
        with self._mtx:
            return self.maj23

    def has_two_thirds_majority(self) -> bool:
        with self._mtx:
            return self.maj23 is not None

    def has_two_thirds_any(self) -> bool:
        # Integer math: float division diverges from the reference's int64
        # arithmetic once total power exceeds 2^53 (vote_set.go:340).
        with self._mtx:
            return 3 * self.sum > 2 * self.val_set.total_voting_power()

    def has_all(self) -> bool:
        with self._mtx:
            return self.sum == self.val_set.total_voting_power()

    def bit_array(self) -> BitArray:
        with self._mtx:
            return self.votes_bit_array.copy()

    def bit_array_by_block_id(self, block_id: BlockID) -> BitArray | None:
        with self._mtx:
            bv = self.votes_by_block.get(block_id.key())
            return bv.bit_array.copy() if bv is not None else None

    # --- vote admission ------------------------------------------------------

    def add_vote(self, vote: Vote) -> bool:
        """Validate + verify + admit one vote (vote_set.go:157-266).

        Returns True if the vote was newly added; raises on invalid votes.
        """
        with self._mtx:
            libsync.lockset_note("VoteSet.votes")
            self._check_vote(vote)
            val = self.val_set.get_by_index(vote.validator_index)
            self._verify_vote_signature(vote, val.pub_key)
            return self._admit(vote, val)

    def add_votes_batch(
        self, votes: list[Vote]
    ) -> tuple[list[bool], list[Exception | None]]:
        """Admit many votes with ONE device verification launch.

        TPU-native vote ingest: validates and pre-screens each vote, streams
        all (pubkey, sign-bytes, sig) triples (plus extension signatures
        when enabled) to the batch verifier, then admits the valid ones.
        Per-vote errors don't abort the batch; returns ``(added, errors)``
        where ``added[i]`` marks newly admitted votes and ``errors[i]``
        carries the per-vote failure (ConflictingVoteError for equivocation
        — the caller's duplicate-vote-evidence input — or VoteError for a
        bad signature / malformed vote) so the batched path surfaces the
        same signals as single ``add_vote``.
        """
        with self._mtx:
            return self._add_votes_batch_locked(votes)

    def _add_votes_batch_locked(self, votes):
        n = len(votes)
        added = [False] * n
        errors: list[Exception | None] = [None] * n

        screened: list[tuple[Vote, object]] = []
        for i, vote in enumerate(votes):
            try:
                self._check_vote(vote)
            except (VoteError, VoteSetError) as e:
                errors[i] = e
                screened.append((vote, None))
                continue
            val = self.val_set.get_by_index(vote.validator_index)
            screened.append((vote, val))

        # Keyed off the SET, not the proposer: a heterogeneous
        # ed25519+sr25519 valset gets MixedBatchVerifier (one launch)
        # instead of a TypeError from add() on the first foreign key. A
        # set with a type no backend supports (e.g. secp256k1) verifies
        # per-vote instead of crashing reconstruction.
        try:
            verifier = crypto_batch.create_commit_batch_verifier(
                self.val_set
            )
        except ValueError:
            verifier = None

        def finish(i, vote, val, ok: bool) -> None:
            """Shared verdict->admission tail for both verify paths."""
            if not ok:
                errors[i] = VoteError(
                    f"invalid signature from validator "
                    f"{vote.validator_address.hex()}"
                )
                return
            try:
                added[i] = self._admit(vote, val)
            except ConflictingVoteError as e:
                errors[i] = e

        lanes: list[int] = []
        for i, (vote, val) in enumerate(screened):
            if val is None:
                continue
            if verifier is not None:
                verifier.add(
                    val.pub_key, vote.sign_bytes(self.chain_id),
                    vote.signature,
                )
                lanes.append(i)
                if self._needs_extension(vote):
                    verifier.add(
                        val.pub_key,
                        vote.extension_sign_bytes(self.chain_id),
                        vote.extension_signature,
                    )
                    lanes.append(i)  # second lane for the same vote
                continue
            # per-vote fallback path
            ok = val.pub_key.verify_signature(
                vote.sign_bytes(self.chain_id), vote.signature
            )
            if ok and self._needs_extension(vote):
                ok = val.pub_key.verify_signature(
                    vote.extension_sign_bytes(self.chain_id),
                    vote.extension_signature,
                )
            finish(i, vote, val, ok)

        if lanes:
            _, bits = verifier.verify()
            vote_ok: dict[int, bool] = {}
            for lane, ok in zip(lanes, bits):
                vote_ok[lane] = vote_ok.get(lane, True) and bool(ok)
            for i, ok in vote_ok.items():
                vote, val = screened[i]
                finish(i, vote, val, bool(ok))
        return added, errors

    def set_peer_maj23(self, peer_id: str, block_id: BlockID) -> None:
        """Record a peer's claim of 2/3 for a block (vote_set.go:335-378):
        future conflicting votes for that block become admissible."""
        with self._mtx:
            self._set_peer_maj23_locked(peer_id, block_id)

    def _set_peer_maj23_locked(self, peer_id: str, block_id: BlockID) -> None:
        existing = self.peer_maj23s.get(peer_id)
        if existing is not None:
            if existing == block_id:
                return
            raise VoteSetError(
                f"setPeerMaj23: conflicting claims from {peer_id}"
            )
        self.peer_maj23s[peer_id] = block_id
        key = block_id.key()
        if key not in self.votes_by_block:
            self.votes_by_block[key] = _BlockVotes(True, len(self.val_set))
        else:
            self.votes_by_block[key].peer_maj23 = True

    # --- internals -----------------------------------------------------------

    def _needs_extension(self, vote: Vote) -> bool:
        return (
            self.extensions_enabled
            and vote.msg_type == canonical.PRECOMMIT_TYPE
            and not vote.block_id.is_nil()
        )

    def _check_vote(self, vote: Vote) -> None:
        vote.validate_basic()
        if (
            vote.height != self.height
            or vote.round != self.round
            or vote.msg_type != self.signed_msg_type
        ):
            raise VoteSetError(
                f"vote H/R/T {vote.height}/{vote.round}/{vote.msg_type} "
                f"does not match set "
                f"{self.height}/{self.round}/{self.signed_msg_type}"
            )
        val = self.val_set.get_by_index(vote.validator_index)
        if val is None:
            raise VoteSetError(
                f"validator index {vote.validator_index} out of range"
            )
        if val.address != vote.validator_address:
            raise VoteSetError("validator address does not match index")
        if self._needs_extension(vote):
            if not vote.extension_signature:
                raise VoteError("missing required extension signature")
        elif self.extensions_enabled is False and (
            vote.extension or vote.extension_signature
        ):
            if vote.msg_type == canonical.PRECOMMIT_TYPE:
                raise VoteError("unexpected vote extension data")
        existing = self.votes[vote.validator_index]
        if existing is not None:
            if existing.block_id == vote.block_id:
                if existing.signature != vote.signature:
                    raise VoteSetError("same block, different signature")
                # exact duplicate: handled by _admit returning False
                return
            # conflicting: only admissible if peer claimed maj23 for it
            bv = self.votes_by_block.get(vote.block_id.key())
            if bv is None or not bv.peer_maj23:
                raise ConflictingVoteError(existing=existing, new=vote)

    def _verify_vote_signature(self, vote: Vote, pub_key) -> None:
        if self.sig_memo is None:
            # No memo: the reference per-vote path, untouched.
            if self._needs_extension(vote):
                vote.verify_vote_and_extension(self.chain_id, pub_key)
            else:
                vote.verify(self.chain_id, pub_key)
            return
        # The memo only certifies SIGNATURES; the address binding is not
        # part of the sign bytes and must be enforced here exactly like
        # vote.verify (types/vote.go:210-232) — a memo hit must never admit
        # an address-spoofed relay of a validly signed vote.
        if bytes(pub_key.address()) != vote.validator_address:
            raise VoteError("invalid validator address")
        ok = self.sig_memo.pop(
            (pub_key.bytes(), vote.sign_bytes(self.chain_id), vote.signature),
            None,
        )
        if ok is False:
            raise VoteError(
                f"invalid signature from validator "
                f"{vote.validator_address.hex()}"
            )
        if self._needs_extension(vote):
            ext_ok = self.sig_memo.pop(
                (
                    pub_key.bytes(),
                    vote.extension_sign_bytes(self.chain_id),
                    vote.extension_signature,
                ),
                None,
            )
            if ext_ok is False:
                raise VoteError(
                    f"invalid extension signature from validator "
                    f"{vote.validator_address.hex()}"
                )
            if ok and ext_ok:
                return
            vote.verify_vote_and_extension(self.chain_id, pub_key)
        else:
            if ok:
                return
            vote.verify(self.chain_id, pub_key)

    def _admit(self, vote: Vote, val) -> bool:
        idx = vote.validator_index
        existing = self.votes[idx]
        key = vote.block_id.key()
        if existing is not None:
            if existing.block_id == vote.block_id:
                return False  # duplicate
            # conflicting but peer-claimed: record in block votes only
            bv = self.votes_by_block.get(key)
            if bv is None or not bv.peer_maj23:
                raise ConflictingVoteError(existing=existing, new=vote)
            bv.add_verified_vote(vote, val.voting_power)
            self._maybe_latch_maj23(key, vote)
            return True

        self.votes[idx] = vote
        self.votes_bit_array.set_index(idx, True)
        self.sum += val.voting_power
        bv = self.votes_by_block.get(key)
        if bv is None:
            bv = _BlockVotes(False, len(self.val_set))
            self.votes_by_block[key] = bv
        bv.add_verified_vote(vote, val.voting_power)
        self._maybe_latch_maj23(key, vote)
        return True

    def _maybe_latch_maj23(self, key: bytes, vote: Vote) -> None:
        bv = self.votes_by_block[key]
        quorum = self.val_set.total_voting_power() * 2 // 3 + 1
        if bv.sum >= quorum and self.maj23 is None:
            self.maj23 = vote.block_id
            # promote block votes into primary slots (vote_set.go:257-263)
            for i, v in enumerate(bv.votes):
                if v is not None and self.votes[i] is not v:
                    if self.votes[i] is None:
                        self.votes_bit_array.set_index(i, True)
                        self.sum += self.val_set.get_by_index(i).voting_power
                    self.votes[i] = v

    # --- commit construction -------------------------------------------------

    def make_commit(self) -> Commit:
        """Build a Commit from the 2/3 majority (vote_set.go MakeCommit)."""
        with self._mtx:
            return self._make_commit_locked()

    def _make_commit_locked(self) -> Commit:
        if self.signed_msg_type != canonical.PRECOMMIT_TYPE:
            raise VoteSetError("cannot MakeCommit from non-precommit set")
        if self.maj23 is None:
            raise VoteSetError("cannot MakeCommit: no 2/3 majority")
        from .block import CommitSig

        sigs = []
        for i, vote in enumerate(self.votes):
            if (
                vote is not None
                and vote.block_id == self.maj23
                and vote.block_id.is_complete()
            ):
                sigs.append(vote.commit_sig())
            elif vote is not None and vote.block_id.is_nil():
                sigs.append(vote.commit_sig())
            else:
                sigs.append(CommitSig.absent())
        return Commit(
            height=self.height,
            round=self.round,
            block_id=self.maj23,
            signatures=sigs,
        )

    def make_extended_commit(self, require_extensions: bool = False):
        """Commit + vote extensions (vote_set.go MakeExtendedCommit:636)."""
        from .block import ExtendedCommit, ExtendedCommitSig

        with self._mtx:
            return self._make_extended_commit_locked(
                require_extensions, ExtendedCommit, ExtendedCommitSig
            )

    def _make_extended_commit_locked(
        self, require_extensions, ExtendedCommit, ExtendedCommitSig
    ):
        commit = self._make_commit_locked()
        ext_sigs = []
        for i, cs in enumerate(commit.signatures):
            vote = self.votes[i]
            # Only COMMIT-flag sigs may carry extension data
            # (types/block.go EnsureExtensions / issue #8487).
            if vote is not None and cs.block_id_flag == BLOCK_ID_FLAG_COMMIT:
                ext_sigs.append(
                    ExtendedCommitSig(
                        commit_sig=cs,
                        extension=vote.extension,
                        extension_signature=vote.extension_signature,
                    )
                )
            else:
                ext_sigs.append(ExtendedCommitSig(commit_sig=cs))
        ec = ExtendedCommit(
            height=commit.height,
            round=commit.round,
            block_id=commit.block_id,
            extended_signatures=ext_sigs,
        )
        ec.ensure_extensions(require_extensions)
        return ec
