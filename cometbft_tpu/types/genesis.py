"""Genesis document (reference: types/genesis.go)."""

from __future__ import annotations

import json
import time
from dataclasses import dataclass, field as dc_field

from ..crypto import keys, tmhash
from .params import ConsensusParams
from .validator_set import Validator

MAX_CHAIN_ID_LEN = 50


@dataclass(slots=True)
class GenesisValidator:
    pub_key: object
    power: int
    name: str = ""
    address: bytes = b""

    def __post_init__(self) -> None:
        if not self.address:
            self.address = bytes(self.pub_key.address())


@dataclass(slots=True)
class GenesisDoc:
    chain_id: str
    genesis_time_ns: int = 0
    initial_height: int = 1
    consensus_params: ConsensusParams = dc_field(
        default_factory=ConsensusParams
    )
    validators: list[GenesisValidator] = dc_field(default_factory=list)
    app_hash: bytes = b""
    app_state: dict = dc_field(default_factory=dict)

    def validate_and_complete(self) -> None:
        """types/genesis.go ValidateAndComplete."""
        if not self.chain_id:
            raise ValueError("genesis doc must include non-empty chain_id")
        if len(self.chain_id) > MAX_CHAIN_ID_LEN:
            raise ValueError("chain_id too long")
        if self.initial_height < 0:
            raise ValueError("initial_height cannot be negative")
        if self.initial_height == 0:
            self.initial_height = 1
        self.consensus_params.validate_basic()
        # The validator-set hash proto-encodes every key through the
        # tendermint.crypto.PublicKey oneof, which carries ONLY ed25519
        # and secp256k1 (keys.proto; the reference's PubKeyToProto
        # errors identically, crypto/encoding/codec.go:20-38). Reject
        # here with a clear message instead of crashing the consensus
        # FSM at enter-new-round.
        from .validator_set import pubkey_proto_encode

        for v in self.validators:
            if v.power == 0:
                raise ValueError("genesis validator cannot have power 0")
            try:
                pubkey_proto_encode(v.pub_key)
            except ValueError as e:
                raise ValueError(
                    f"genesis validator key not wire-encodable: {e} "
                    "(tendermint.crypto.PublicKey supports ed25519 and "
                    "secp256k1 only)"
                ) from e
        if self.genesis_time_ns == 0:
            self.genesis_time_ns = time.time_ns()

    def validator_set(self):
        from .validator_set import ValidatorSet

        return ValidatorSet(
            [
                Validator(pub_key=v.pub_key, voting_power=v.power)
                for v in self.validators
            ]
        )

    # --- JSON persistence ----------------------------------------------------

    def to_json(self) -> str:
        return json.dumps(
            {
                "genesis_time_ns": self.genesis_time_ns,
                "chain_id": self.chain_id,
                "initial_height": self.initial_height,
                "app_hash": self.app_hash.hex(),
                "app_state": self.app_state,
                "validators": [
                    {
                        "pub_key": {
                            "type": v.pub_key.type,
                            "value": v.pub_key.bytes().hex(),
                        },
                        "power": v.power,
                        "name": v.name,
                    }
                    for v in self.validators
                ],
            },
            indent=2,
        )

    @classmethod
    def from_json(cls, data: str) -> "GenesisDoc":
        d = json.loads(data)
        validators = [
            GenesisValidator(
                pub_key=keys.pubkey_from_type_and_bytes(
                    gv["pub_key"]["type"], bytes.fromhex(gv["pub_key"]["value"])
                ),
                power=int(gv["power"]),
                name=gv.get("name", ""),
            )
            for gv in d.get("validators", [])
        ]
        doc = cls(
            chain_id=d["chain_id"],
            genesis_time_ns=int(d.get("genesis_time_ns", 0)),
            initial_height=int(d.get("initial_height", 1)),
            validators=validators,
            app_hash=bytes.fromhex(d.get("app_hash", "")),
            app_state=d.get("app_state", {}),
        )
        doc.validate_and_complete()
        return doc

    def hash(self) -> bytes:
        return tmhash.sum(self.to_json().encode())
