"""Validator and ValidatorSet with proposer-priority rotation.

Reference: types/validator.go, types/validator_set.go:
* validators ordered by voting power desc, ties by address asc
  (ValidatorsByVotingPower, validator_set.go:752-767);
* IncrementProposerPriority: rescale to a 2*total window, shift by avg,
  then `times` rounds of (everyone += power; max -= total)
  (validator_set.go:116-178);
* set hash = merkle root of SimpleValidator proto encodings
  (validator.go:117-133);
* updates: changed/added vals merged, added vals start at
  -1.125*new-total priority (validator_set.go:477-495).

Clipping arithmetic (safeAddClip/safeSubClip) saturates at int64 bounds.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..crypto import merkle
from . import proto

INT64_MAX = (1 << 63) - 1
INT64_MIN = -(1 << 63)
MAX_TOTAL_VOTING_POWER = INT64_MAX // 8
PRIORITY_WINDOW_SIZE_FACTOR = 2


def _clip(v: int) -> int:
    return max(INT64_MIN, min(INT64_MAX, v))


def pubkey_proto_encode(pub_key) -> bytes:
    """tendermint.crypto.PublicKey oneof body (keys.proto: ed25519=1,
    secp256k1=2)."""
    if pub_key.type == "ed25519":
        return proto.field_bytes(1, pub_key.bytes())
    if pub_key.type == "secp256k1":
        return proto.field_bytes(2, pub_key.bytes())
    raise ValueError(f"unsupported key type {pub_key.type}")


@dataclass(slots=True)
class Validator:
    pub_key: object
    voting_power: int
    proposer_priority: int = 0
    address: bytes = b""

    def __post_init__(self) -> None:
        if not self.address:
            self.address = bytes(self.pub_key.address())

    def copy(self) -> "Validator":
        return Validator(
            pub_key=self.pub_key,
            voting_power=self.voting_power,
            proposer_priority=self.proposer_priority,
            address=self.address,
        )

    def bytes(self) -> bytes:
        """SimpleValidator proto encoding (validator.go:117-133)."""
        return proto.field_message(
            1, pubkey_proto_encode(self.pub_key)
        ) + proto.field_varint(2, self.voting_power)

    def compare_proposer_priority(self, other: "Validator") -> "Validator":
        if self.proposer_priority > other.proposer_priority:
            return self
        if self.proposer_priority < other.proposer_priority:
            return other
        if self.address < other.address:
            return self
        if self.address > other.address:
            return other
        raise ValueError("cannot compare identical validators")

    def validate_basic(self) -> None:
        if self.pub_key is None:
            raise ValueError("validator has nil pubkey")
        if self.voting_power < 0:
            raise ValueError("negative voting power")
        if len(self.address) != 20:
            raise ValueError("address must be 20 bytes")


def _sort_key(v: Validator):
    # power desc, then address asc.
    return (-v.voting_power, v.address)


class ValidatorSet:
    def __init__(self, validators: list[Validator]):
        self.validators: list[Validator] = sorted(
            (v.copy() for v in validators), key=_sort_key
        )
        self.proposer: Validator | None = None
        self._total: int | None = None
        if self.validators:
            self.increment_proposer_priority(1)

    # --- queries -------------------------------------------------------------

    def __len__(self) -> int:
        return len(self.validators)

    def is_nil_or_empty(self) -> bool:
        return not self.validators

    def total_voting_power(self) -> int:
        if self._total is None:
            total = sum(v.voting_power for v in self.validators)
            if total > MAX_TOTAL_VOTING_POWER:
                raise ValueError(
                    f"total voting power {total} exceeds max "
                    f"{MAX_TOTAL_VOTING_POWER}"
                )
            self._total = total
        return self._total

    def get_by_address(self, address: bytes) -> tuple[int, Validator | None]:
        for i, v in enumerate(self.validators):
            if v.address == address:
                return i, v
        return -1, None

    def get_by_index(self, index: int) -> Validator | None:
        if 0 <= index < len(self.validators):
            return self.validators[index]
        return None

    def has_address(self, address: bytes) -> bool:
        return self.get_by_address(address)[0] >= 0

    def hash(self) -> bytes:
        return merkle.hash_from_byte_slices(
            [v.bytes() for v in self.validators]
        )

    def copy(self) -> "ValidatorSet":
        cp = ValidatorSet.__new__(ValidatorSet)
        cp.validators = [v.copy() for v in self.validators]
        cp.proposer = None
        cp._total = self._total
        if self.proposer is not None:
            idx, _ = cp.get_by_address(self.proposer.address)
            cp.proposer = cp.validators[idx] if idx >= 0 else self.proposer.copy()
        return cp

    # --- proposer rotation ---------------------------------------------------

    def increment_proposer_priority(self, times: int) -> None:
        if not self.validators:
            raise ValueError("empty validator set")
        if times <= 0:
            raise ValueError("times must be positive")
        diff_max = PRIORITY_WINDOW_SIZE_FACTOR * self.total_voting_power()
        self.rescale_priorities(diff_max)
        self._shift_by_avg_proposer_priority()
        proposer = None
        for _ in range(times):
            proposer = self._increment_proposer_priority()
        self.proposer = proposer

    def copy_increment_proposer_priority(self, times: int) -> "ValidatorSet":
        cp = self.copy()
        cp.increment_proposer_priority(times)
        return cp

    def rescale_priorities(self, diff_max: int) -> None:
        if diff_max <= 0:
            return
        prios = [v.proposer_priority for v in self.validators]
        diff = abs(max(prios) - min(prios))
        if diff > diff_max:
            ratio = (diff + diff_max - 1) // diff_max
            for v in self.validators:
                # Go int64 division truncates toward zero.
                q = abs(v.proposer_priority) // ratio
                v.proposer_priority = q if v.proposer_priority >= 0 else -q

    def _shift_by_avg_proposer_priority(self) -> None:
        n = len(self.validators)
        total = sum(v.proposer_priority for v in self.validators)
        # Go big.Int Div floors (Euclidean for positive divisor).
        avg = total // n
        for v in self.validators:
            v.proposer_priority = _clip(v.proposer_priority - avg)

    def _increment_proposer_priority(self) -> Validator:
        for v in self.validators:
            v.proposer_priority = _clip(
                v.proposer_priority + v.voting_power
            )
        mostest = self.validators[0]
        for v in self.validators[1:]:
            mostest = mostest.compare_proposer_priority(v)
        mostest.proposer_priority = _clip(
            mostest.proposer_priority - self.total_voting_power()
        )
        return mostest

    def get_proposer(self) -> Validator:
        if self.proposer is None:
            self.proposer = self._find_proposer()
        return self.proposer

    def _find_proposer(self) -> Validator:
        mostest = self.validators[0]
        for v in self.validators[1:]:
            mostest = mostest.compare_proposer_priority(v)
        return mostest

    # --- updates -------------------------------------------------------------

    def update_with_change_set(self, changes: list[Validator]) -> None:
        """Apply ABCI validator updates (power 0 = removal).

        Reference semantics (validator_set.go:477-650): dedup/sort changes
        by address, verify removals exist, compute new total, added vals get
        priority -(new_total + new_total >> 3), then merge, re-sort by
        power, rescale + center priorities.
        """
        if not changes:
            return
        by_addr: dict[bytes, Validator] = {}
        for c in sorted(changes, key=lambda v: v.address):
            if c.address in by_addr:
                raise ValueError(f"duplicate update for {c.address.hex()}")
            if c.voting_power < 0:
                raise ValueError("negative voting power in update")
            by_addr[c.address] = c

        removals = {a for a, c in by_addr.items() if c.voting_power == 0}
        for addr in removals:
            if not self.has_address(addr):
                raise ValueError(
                    f"cannot remove unknown validator {addr.hex()}"
                )

        new_total = 0
        for v in self.validators:
            upd = by_addr.get(v.address)
            new_total += v.voting_power if upd is None else upd.voting_power
        for addr, c in by_addr.items():
            if not self.has_address(addr):
                new_total += c.voting_power
        if new_total > MAX_TOTAL_VOTING_POWER:
            raise ValueError("updates exceed max total voting power")
        if new_total == 0:
            raise ValueError("updates would remove all validators")

        merged: dict[bytes, Validator] = {
            v.address: v for v in self.validators
        }
        for addr, c in by_addr.items():
            if addr in removals:
                merged.pop(addr, None)
                continue
            existing = merged.get(addr)
            nv = c.copy()
            if existing is None:
                nv.proposer_priority = -(new_total + (new_total >> 3))
            else:
                nv.proposer_priority = existing.proposer_priority
            merged[addr] = nv

        self.validators = sorted(merged.values(), key=_sort_key)
        self._total = None
        self.rescale_priorities(
            PRIORITY_WINDOW_SIZE_FACTOR * self.total_voting_power()
        )
        self._shift_by_avg_proposer_priority()
        self.proposer = None

    def validate_basic(self) -> None:
        if not self.validators:
            raise ValueError("empty validator set")
        for v in self.validators:
            v.validate_basic()
        if self.proposer is not None:
            self.proposer.validate_basic()

    # --- commit verification façades (validator_set.go:660-678) -------------

    def verify_commit(self, chain_id, block_id, height, commit):
        from . import validation

        validation.verify_commit(chain_id, self, block_id, height, commit)

    def verify_commit_light(self, chain_id, block_id, height, commit):
        from . import validation

        validation.verify_commit_light(
            chain_id, self, block_id, height, commit
        )

    def verify_commit_light_trusting(self, chain_id, commit, trust_level):
        from . import validation

        validation.verify_commit_light_trusting(
            chain_id, self, commit, trust_level
        )
