"""PrivValidator interface + in-memory mock (reference: types/priv_validator.go).

The production FilePV (double-sign protection, key files) lives in
``cometbft_tpu.privval``; MockPV is the deterministic test signer used by
consensus fixtures (common_test.go's validatorStub).
"""

from __future__ import annotations

from ..crypto.keys import Ed25519PrivKey
from . import canonical
from .vote import Proposal, Vote


class PrivValidator:
    """SignVote/SignProposal contract (types/priv_validator.go:18-27)."""

    def get_pub_key(self):
        raise NotImplementedError

    def sign_vote(
        self, chain_id: str, vote: Vote, sign_extension: bool
    ) -> None:
        raise NotImplementedError

    def sign_proposal(self, chain_id: str, proposal: Proposal) -> None:
        raise NotImplementedError


class MockPV(PrivValidator):
    """Deterministic in-memory signer (types/priv_validator.go:73-135)."""

    def __init__(
        self,
        priv_key: Ed25519PrivKey | None = None,
        break_proposal_sigs: bool = False,
        break_vote_sigs: bool = False,
    ):
        self.priv_key = priv_key or Ed25519PrivKey.generate()
        self.break_proposal_sigs = break_proposal_sigs
        self.break_vote_sigs = break_vote_sigs

    def get_pub_key(self):
        return self.priv_key.pub_key()

    def sign_vote(
        self, chain_id: str, vote: Vote, sign_extension: bool = True
    ) -> None:
        use_chain_id = "incorrect-chain-id" if self.break_vote_sigs else chain_id
        vote.signature = self.priv_key.sign(vote.sign_bytes(use_chain_id))
        if (
            sign_extension
            and vote.msg_type == canonical.PRECOMMIT_TYPE
            and not vote.block_id.is_nil()
        ):
            vote.extension_signature = self.priv_key.sign(
                vote.extension_sign_bytes(use_chain_id)
            )

    def sign_proposal(self, chain_id: str, proposal: Proposal) -> None:
        use_chain_id = (
            "incorrect-chain-id" if self.break_proposal_sigs else chain_id
        )
        proposal.signature = self.priv_key.sign(
            proposal.sign_bytes(use_chain_id)
        )


class ErroringMockPV(MockPV):
    """Always refuses to sign (types/priv_validator.go:139-158)."""

    def sign_vote(self, chain_id, vote, sign_extension=True):
        raise RuntimeError("erroring mock private validator")

    def sign_proposal(self, chain_id, proposal):
        raise RuntimeError("erroring mock private validator")
