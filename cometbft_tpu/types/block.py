"""Block, Header, Commit, CommitSig, BlockID (reference: types/block.go).

Hashing follows the reference exactly: Header.Hash is the RFC-6962 merkle
root of the proto-encoded fields (types/block.go:439-474), where scalar
fields are wrapped in gogotypes value wrappers (types/encoding_helper.go's
cdcEncode) and time is a google.protobuf.Timestamp.

Time is represented as integer nanoseconds since the Unix epoch throughout
the framework (Go's time.Time has ns precision; Python datetime does not).
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field

from ..crypto import merkle, tmhash
from . import canonical, proto

MAX_HEADER_BYTES = 626
BLOCK_PART_SIZE_BYTES = 65536  # types/part_set.go part size

# BlockIDFlag (types/block.go:574-583)
BLOCK_ID_FLAG_ABSENT = 1
BLOCK_ID_FLAG_COMMIT = 2
BLOCK_ID_FLAG_NIL = 3


def cdc_encode_string(s: str) -> bytes:
    """gogotypes.StringValue wrapper (types/encoding_helper.go)."""
    return proto.field_string(1, s) if s else b""


def cdc_encode_int64(v: int) -> bytes:
    return proto.field_varint(1, v) if v else b""


def cdc_encode_bytes(b: bytes) -> bytes:
    return proto.field_bytes(1, b) if b else b""


@dataclass(frozen=True, slots=True)
class PartSetHeader:
    total: int = 0
    hash: bytes = b""

    def is_zero(self) -> bool:
        return self.total == 0 and not self.hash

    def encode(self) -> bytes:
        return proto.field_varint(1, self.total) + proto.field_bytes(
            2, self.hash
        )

    def validate_basic(self) -> None:
        if self.total < 0:
            raise ValueError("negative part-set total")
        if self.hash and len(self.hash) != tmhash.SIZE:
            raise ValueError("part-set hash must be 32 bytes")


@dataclass(frozen=True, slots=True)
class BlockID:
    hash: bytes = b""
    part_set_header: PartSetHeader = dc_field(default_factory=PartSetHeader)

    def is_nil(self) -> bool:
        return not self.hash and self.part_set_header.is_zero()

    def is_complete(self) -> bool:
        return (
            len(self.hash) == tmhash.SIZE
            and self.part_set_header.total > 0
            and len(self.part_set_header.hash) == tmhash.SIZE
        )

    def encode(self) -> bytes:
        """BlockID proto body; part_set_header is nullable=false."""
        return proto.field_bytes(1, self.hash) + proto.field_message(
            2, self.part_set_header.encode(), always=True
        )

    def validate_basic(self) -> None:
        if self.hash and len(self.hash) != tmhash.SIZE:
            raise ValueError("block-id hash must be 32 bytes")
        self.part_set_header.validate_basic()

    def key(self) -> bytes:
        return self.hash + self.part_set_header.hash + bytes(
            [self.part_set_header.total & 0xFF]
        )


NIL_BLOCK_ID = BlockID()


@dataclass(frozen=True, slots=True)
class Version:
    """Consensus version (proto/tendermint/version/types.proto)."""

    block: int = 11
    app: int = 0

    def encode(self) -> bytes:
        return proto.field_varint(1, self.block) + proto.field_varint(
            2, self.app
        )


@dataclass(frozen=True, slots=True)
class Header:
    version: Version
    chain_id: str
    height: int
    time_ns: int
    last_block_id: BlockID
    last_commit_hash: bytes
    data_hash: bytes
    validators_hash: bytes
    next_validators_hash: bytes
    consensus_hash: bytes
    app_hash: bytes
    last_results_hash: bytes
    evidence_hash: bytes
    proposer_address: bytes

    def hash(self) -> bytes | None:
        """Merkle root over proto-encoded fields (types/block.go:439-474)."""
        if not self.validators_hash:
            return None
        return merkle.hash_from_byte_slices(
            [
                self.version.encode(),
                cdc_encode_string(self.chain_id),
                cdc_encode_int64(self.height),
                proto.timestamp(self.time_ns),
                self.last_block_id.encode(),
                cdc_encode_bytes(self.last_commit_hash),
                cdc_encode_bytes(self.data_hash),
                cdc_encode_bytes(self.validators_hash),
                cdc_encode_bytes(self.next_validators_hash),
                cdc_encode_bytes(self.consensus_hash),
                cdc_encode_bytes(self.app_hash),
                cdc_encode_bytes(self.last_results_hash),
                cdc_encode_bytes(self.evidence_hash),
                cdc_encode_bytes(self.proposer_address),
            ]
        )

    def validate_basic(self) -> None:
        if len(self.chain_id) > 50:
            raise ValueError("chain id too long")
        if self.height < 0:
            raise ValueError("negative height")
        self.last_block_id.validate_basic()
        for name in (
            "last_commit_hash",
            "data_hash",
            "validators_hash",
            "next_validators_hash",
            "consensus_hash",
            "last_results_hash",
            "evidence_hash",
        ):
            v = getattr(self, name)
            if v and len(v) != tmhash.SIZE:
                raise ValueError(f"{name} must be 32 bytes")
        if len(self.proposer_address) != tmhash.TRUNCATED_SIZE:
            raise ValueError("proposer address must be 20 bytes")


@dataclass(frozen=True, slots=True)
class CommitSig:
    """One validator's slot in a commit (types/block.go:592-606)."""

    block_id_flag: int = BLOCK_ID_FLAG_ABSENT
    validator_address: bytes = b""
    timestamp_ns: int = proto.ZERO_TIME_NS
    signature: bytes = b""

    @classmethod
    def absent(cls) -> "CommitSig":
        return cls()

    def for_block(self) -> bool:
        return self.block_id_flag == BLOCK_ID_FLAG_COMMIT

    def block_id(self, commit_block_id: BlockID) -> BlockID:
        """The BlockID this sig voted for (types/block.go:632-644)."""
        if self.block_id_flag == BLOCK_ID_FLAG_ABSENT:
            return NIL_BLOCK_ID
        if self.block_id_flag == BLOCK_ID_FLAG_COMMIT:
            return commit_block_id
        if self.block_id_flag == BLOCK_ID_FLAG_NIL:
            return NIL_BLOCK_ID
        raise ValueError(f"unknown BlockIDFlag {self.block_id_flag}")

    def encode(self) -> bytes:
        return (
            proto.field_varint(1, self.block_id_flag)
            + proto.field_bytes(2, self.validator_address)
            + proto.field_message(
                3, proto.timestamp(self.timestamp_ns), always=True
            )
            + proto.field_bytes(4, self.signature)
        )

    def validate_basic(self) -> None:
        if self.block_id_flag not in (
            BLOCK_ID_FLAG_ABSENT,
            BLOCK_ID_FLAG_COMMIT,
            BLOCK_ID_FLAG_NIL,
        ):
            raise ValueError("unknown block-id flag")
        if self.block_id_flag == BLOCK_ID_FLAG_ABSENT:
            if self.validator_address or self.signature:
                raise ValueError("absent commit sig must be empty")
        else:
            if len(self.validator_address) != tmhash.TRUNCATED_SIZE:
                raise ValueError("validator address must be 20 bytes")
            if not self.signature or len(self.signature) > 64:
                raise ValueError("bad signature length")


@dataclass(slots=True)
class Commit:
    """+2/3 precommits for a block (types/block.go:715+)."""

    height: int
    round: int
    block_id: BlockID
    signatures: list[CommitSig]

    _hash: bytes | None = dc_field(default=None, compare=False, repr=False)

    def size(self) -> int:
        return len(self.signatures)

    def vote_sign_bytes(self, chain_id: str, val_idx: int) -> bytes:
        """Sign bytes of validator ``val_idx``'s precommit in this commit
        (types/block.go:871-883 — only the timestamp differs per validator).
        """
        cs = self.signatures[val_idx]
        return canonical.vote_sign_bytes(
            chain_id,
            canonical.PRECOMMIT_TYPE,
            self.height,
            self.round,
            cs.block_id(self.block_id),
            cs.timestamp_ns,
        )

    def hash(self) -> bytes:
        if self._hash is None:
            self._hash = merkle.hash_from_byte_slices(
                [cs.encode() for cs in self.signatures]
            )
        return self._hash

    def validate_basic(self) -> None:
        if self.height < 0:
            raise ValueError("negative height")
        if self.round < 0:
            raise ValueError("negative round")
        if self.height >= 1:
            if self.block_id.is_nil():
                raise ValueError("commit cannot be for nil block")
            if not self.signatures:
                raise ValueError("no signatures in commit")
            for cs in self.signatures:
                cs.validate_basic()


@dataclass(frozen=True, slots=True)
class ExtendedCommitSig:
    """CommitSig + the vote extension it carried (types/block.go:646+)."""

    commit_sig: CommitSig
    extension: bytes = b""
    extension_signature: bytes = b""

    def validate_basic(self) -> None:
        self.commit_sig.validate_basic()
        if self.commit_sig.block_id_flag != BLOCK_ID_FLAG_COMMIT and (
            self.extension or self.extension_signature
        ):
            raise ValueError("non-commit sig cannot carry an extension")

    def ensure_extension(self) -> None:
        if (
            self.commit_sig.block_id_flag == BLOCK_ID_FLAG_COMMIT
            and not self.extension_signature
        ):
            raise ValueError("commit sig missing required vote extension")


@dataclass(slots=True)
class ExtendedCommit:
    """Commit carrying vote extensions, persisted so a restarting proposer
    can re-inject them into PrepareProposal (types/block.go:736+)."""

    height: int
    round: int
    block_id: BlockID
    extended_signatures: list[ExtendedCommitSig]

    def to_commit(self) -> Commit:
        return Commit(
            height=self.height,
            round=self.round,
            block_id=self.block_id,
            signatures=[es.commit_sig for es in self.extended_signatures],
        )

    def size(self) -> int:
        return len(self.extended_signatures)

    def ensure_extensions(self, required: bool) -> None:
        if required:
            for es in self.extended_signatures:
                es.ensure_extension()

    def validate_basic(self) -> None:
        self.to_commit().validate_basic()
        for es in self.extended_signatures:
            es.validate_basic()


@dataclass(slots=True)
class Data:
    """Block transactions; hash is the merkle root of tx hashes."""

    txs: list[bytes] = dc_field(default_factory=list)

    def hash(self) -> bytes:
        # the per-tx pre-hash is one flat batch over up to max_tx_bytes
        # messages — the exact shape the device hash plane wins on; the
        # merkle root over the 32-byte keys then routes level-by-level
        # through the same plane (crypto/merkle._compute_levels)
        from ..crypto import hashplane

        return merkle.hash_from_byte_slices(hashplane.hash_many(self.txs))


@dataclass(slots=True)
class Block:
    header: Header
    data: Data
    evidence: list = dc_field(default_factory=list)
    last_commit: Commit | None = None

    def hash(self) -> bytes | None:
        return self.header.hash()

    def validate_basic(self) -> None:
        self.header.validate_basic()
        if self.header.height > 1:
            if self.last_commit is None:
                raise ValueError("block above height 1 needs last commit")
            self.last_commit.validate_basic()
            if self.header.last_commit_hash != self.last_commit.hash():
                raise ValueError("last commit hash mismatch")
        if self.header.data_hash != self.data.hash():
            raise ValueError("data hash mismatch")
        for ev in self.evidence:
            ev.validate_basic()
        # Cross-check the evidence section against the committed header
        # hash (types/block.go:98) — without this, a relay could strip or
        # alter evidence while the header still content-verifies.
        from .evidence import evidence_list_hash

        if self.header.evidence_hash != evidence_list_hash(self.evidence):
            raise ValueError("evidence hash mismatch")


@dataclass(slots=True)
class BlockMeta:
    """Block summary stored per height (types/block_meta.go)."""

    block_id: BlockID
    block_size: int
    header: Header
    num_txs: int


def make_block(
    height: int,
    txs: list[bytes],
    last_commit: Commit | None,
    evidence: list,
    header_fields: dict,
) -> Block:
    """Assemble a block and fill derived hashes (types/block.go MakeBlock +
    fillHeader)."""
    from .evidence import evidence_list_hash

    data = Data(txs=list(txs))
    header = Header(
        height=height,
        data_hash=data.hash(),
        last_commit_hash=(
            last_commit.hash()
            if last_commit is not None
            else merkle.hash_from_byte_slices([])
        ),
        evidence_hash=evidence_list_hash(evidence),
        **header_fields,
    )
    return Block(
        header=header, data=data, evidence=evidence, last_commit=last_commit
    )
