"""Evidence of Byzantine behavior (reference: types/evidence.go).

* DuplicateVoteEvidence — two signed votes from one validator for the same
  height/round/type but different blocks (from VoteSet's
  ConflictingVoteError).
* LightClientAttackEvidence — a conflicting light block + the common
  height, with the byzantine validator subset.
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field

from ..crypto import tmhash
from . import proto
from .vote import Vote


class EvidenceError(Exception):
    pass


class Evidence:
    def hash(self) -> bytes:
        raise NotImplementedError

    def height(self) -> int:
        raise NotImplementedError

    def time_ns(self) -> int:
        raise NotImplementedError

    def validate_basic(self) -> None:
        raise NotImplementedError


def _vote_encode(v: Vote) -> bytes:
    """Deterministic vote encoding for evidence hashing."""
    return (
        proto.field_varint(1, v.msg_type)
        + proto.field_sfixed64(2, v.height)
        + proto.field_sfixed64(3, v.round)
        + proto.field_bytes(4, v.block_id.encode())
        + proto.field_message(5, proto.timestamp(v.timestamp_ns), always=True)
        + proto.field_bytes(6, v.validator_address)
        + proto.field_varint(7, v.validator_index, emit_zero=True)
        + proto.field_bytes(8, v.signature)
    )


@dataclass(slots=True)
class DuplicateVoteEvidence(Evidence):
    vote_a: Vote
    vote_b: Vote
    total_voting_power: int = 0
    validator_power: int = 0
    timestamp_ns: int = 0

    @classmethod
    def from_conflicting_votes(
        cls, vote1: Vote, vote2: Vote, block_time_ns: int, val_set
    ) -> "DuplicateVoteEvidence":
        """types/evidence.go NewDuplicateVoteEvidence — orders votes by
        BlockID key and fills power info from the validator set."""
        _, val = val_set.get_by_address(vote1.validator_address)
        if val is None:
            raise EvidenceError("validator not in set")
        a, b = sorted(
            (vote1, vote2), key=lambda v: v.block_id.key()
        )
        return cls(
            vote_a=a,
            vote_b=b,
            total_voting_power=val_set.total_voting_power(),
            validator_power=val.voting_power,
            timestamp_ns=block_time_ns,
        )

    def bytes(self) -> bytes:
        return (
            proto.field_bytes(1, _vote_encode(self.vote_a))
            + proto.field_bytes(2, _vote_encode(self.vote_b))
            + proto.field_varint(3, self.total_voting_power)
            + proto.field_varint(4, self.validator_power)
            + proto.field_message(
                5, proto.timestamp(self.timestamp_ns), always=True
            )
        )

    def hash(self) -> bytes:
        return tmhash.sum(self.bytes())

    def height(self) -> int:
        return self.vote_a.height

    def time_ns(self) -> int:
        return self.timestamp_ns

    def validate_basic(self) -> None:
        if self.vote_a is None or self.vote_b is None:
            raise EvidenceError("missing vote")
        if self.vote_a.block_id.key() >= self.vote_b.block_id.key():
            raise EvidenceError("votes must be ordered by block id")
        va, vb = self.vote_a, self.vote_b
        if (va.height, va.round, va.msg_type) != (
            vb.height,
            vb.round,
            vb.msg_type,
        ):
            raise EvidenceError("votes are not for the same H/R/T")
        if va.validator_address != vb.validator_address:
            raise EvidenceError("votes are from different validators")
        if va.block_id == vb.block_id:
            raise EvidenceError("votes are for the same block")
        va.validate_basic()
        vb.validate_basic()


@dataclass(slots=True)
class LightClientAttackEvidence(Evidence):
    """types/evidence.go:266+ — conflicting header forged for light clients."""

    conflicting_block: object  # light block (signed header + val set)
    common_height: int
    byzantine_validators: list = dc_field(default_factory=list)
    total_voting_power: int = 0
    timestamp_ns: int = 0

    def bytes(self) -> bytes:
        sh = self.conflicting_block.signed_header
        return (
            proto.field_bytes(1, sh.header.hash() or b"")
            + proto.field_sfixed64(2, self.common_height)
            + proto.field_varint(3, self.total_voting_power)
            + proto.field_message(
                4, proto.timestamp(self.timestamp_ns), always=True
            )
        )

    def hash(self) -> bytes:
        return tmhash.sum(self.bytes())

    def height(self) -> int:
        return self.common_height

    def time_ns(self) -> int:
        return self.timestamp_ns

    def conflicting_header_is_invalid(self, trusted_header) -> bool:
        """Whether this was a lunatic attack (invalid header fields) vs an
        equivocation/amnesia attack (valid header, double signing)."""
        sh = self.conflicting_block.signed_header
        h = sh.header
        return (
            h.validators_hash != trusted_header.validators_hash
            or h.next_validators_hash != trusted_header.next_validators_hash
            or h.consensus_hash != trusted_header.consensus_hash
            or h.app_hash != trusted_header.app_hash
            or h.last_results_hash != trusted_header.last_results_hash
        )

    def validate_basic(self) -> None:
        if self.conflicting_block is None:
            raise EvidenceError("conflicting block is nil")
        if self.common_height <= 0:
            raise EvidenceError("non-positive common height")
        if self.total_voting_power <= 0:
            raise EvidenceError("non-positive total voting power")


def evidence_list_hash(evidence: list[Evidence]) -> bytes:
    from ..crypto import merkle

    return merkle.hash_from_byte_slices([ev.hash() for ev in evidence])
