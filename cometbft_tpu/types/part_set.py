"""PartSet: block bytes split into 64KB parts with merkle proofs.

Reference: types/part_set.go. Blocks gossip as parts so a proposal can
stream from many peers concurrently; each part carries an inclusion proof
against the PartSetHeader hash in the proposal.

Hashing rides the device hash plane when one is routed: ``from_data``'s
leaf/proof construction goes through the batched merkle backend
(crypto/merkle._compute_levels -> crypto/hashplane), and ``add_part``'s
proof verification hashes its 64 KiB leaf through the cross-caller
coalescer — concurrent part gossip from many peers packs into shared
device windows. Digests are bit-identical either way.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..crypto import merkle
from ..libs import sync as libsync
from ..libs.bits import BitArray
from .block import BLOCK_PART_SIZE_BYTES, PartSetHeader


class PartSetError(Exception):
    pass


@dataclass(slots=True)
class Part:
    index: int
    bytes_: bytes
    proof: merkle.Proof

    def validate_basic(self) -> None:
        if self.index < 0:
            raise PartSetError("negative part index")
        if len(self.bytes_) > BLOCK_PART_SIZE_BYTES:
            raise PartSetError("part too big")


class PartSet:
    @classmethod
    def from_data(
        cls, data: bytes, part_size: int = BLOCK_PART_SIZE_BYTES
    ) -> "PartSet":
        """Split ``data`` into parts + proofs (part_set.go NewPartSetFromData)."""
        chunks = [
            data[i : i + part_size] for i in range(0, len(data), part_size)
        ] or [b""]
        root, proofs = merkle.proofs_from_byte_slices(chunks)
        ps = cls(PartSetHeader(total=len(chunks), hash=root))
        for i, chunk in enumerate(chunks):
            # proofs we JUST computed need no re-verification: skipping
            # it saves total*(1 + log total) hashes per self-built block
            # (the dominant cost of from_data after the leaf hashing
            # itself); gossip ingress still takes the verifying
            # add_part path
            ps._add_trusted_part(Part(index=i, bytes_=chunk, proof=proofs[i]))
        return ps

    def __init__(self, header: PartSetHeader):
        self.header = header
        # lockfree: single writer per instance — only the owning routine (FSM receive or blocksync pool) adds parts; gossip readers tolerate a stale snapshot and retry, and slot/count stores are GIL-atomic
        self.parts: list[Part | None] = [None] * header.total
        self.parts_bit_array = BitArray(header.total)
        # lockfree: single writer per instance (see parts above)
        self.count = 0
        # lockfree: single writer per instance (see parts above)
        self.byte_size = 0

    def has_header(self, header: PartSetHeader) -> bool:
        return self.header == header

    def add_part(self, part: Part) -> bool:
        """Verify proof + store (part_set.go AddPart). False if duplicate."""
        part.validate_basic()
        if part.index >= self.header.total:
            raise PartSetError("part index out of range")
        if self.parts[part.index] is not None:
            return False
        if part.proof.index != part.index or part.proof.total != self.header.total:
            raise PartSetError("part proof index/total mismatch")
        try:
            part.proof.verify(self.header.hash, part.bytes_)
        except ValueError as e:
            # a bad proof is a protocol-level rejection, not an internal
            # error: callers catch PartSetError to drop bad peer parts
            # (consensus addProposalBlockPart; a cross-round or byzantine
            # part must not escape that guard)
            raise PartSetError(f"invalid part proof: {e}")
        return self._store(part)

    def _add_trusted_part(self, part: Part) -> bool:
        """Store a part whose proof this process just computed
        (from_data) without the redundant proof walk; never for parts
        from the wire."""
        part.validate_basic()
        if part.index >= self.header.total:
            raise PartSetError("part index out of range")
        if self.parts[part.index] is not None:
            return False
        return self._store(part)

    def _store(self, part: Part) -> bool:
        self.parts[part.index] = part
        self.parts_bit_array.set_index(part.index, True)
        self.count += 1
        self.byte_size += len(part.bytes_)
        # exercises the sanitizer's lockfree path: a documented
        # lock-free plane records its (empty) lockset without tripping
        # enforce mode
        libsync.lockset_note("PartSet.count")
        return True

    def get_part(self, index: int) -> Part | None:
        return self.parts[index]

    def is_complete(self) -> bool:
        return self.count == self.header.total

    def assemble(self) -> bytes:
        if not self.is_complete():
            raise PartSetError("incomplete part set")
        return b"".join(p.bytes_ for p in self.parts)
