"""SignedHeader + LightBlock — the light client's data model.

Reference: types/light.go (LightBlock, SignedHeader) — the pair every
light-client verification step consumes: a header, the commit that signed
it, and the validator set the commit is checked against.
"""

from __future__ import annotations

from dataclasses import dataclass

from .block import Commit, Header
from .validator_set import ValidatorSet


class LightBlockError(Exception):
    pass


@dataclass(frozen=True, slots=True)
class SignedHeader:
    """Header plus the commit that finalized it (types/light.go:118)."""

    header: Header
    commit: Commit

    @property
    def height(self) -> int:
        return self.header.height

    @property
    def chain_id(self) -> str:
        return self.header.chain_id

    @property
    def time_ns(self) -> int:
        return self.header.time_ns

    def hash(self) -> bytes | None:
        return self.header.hash()

    def validate_basic(self, chain_id: str) -> None:
        """types/light.go SignedHeader.ValidateBasic: header/commit present,
        matching chain id and height, commit signs THIS header."""
        if self.header is None:
            raise LightBlockError("missing header")
        if self.commit is None:
            raise LightBlockError("missing commit")
        self.header.validate_basic()
        self.commit.validate_basic()
        if self.header.chain_id != chain_id:
            raise LightBlockError(
                f"header chain id {self.header.chain_id!r} != {chain_id!r}"
            )
        if self.commit.height != self.header.height:
            raise LightBlockError(
                f"commit height {self.commit.height} != header height "
                f"{self.header.height}"
            )
        if self.commit.block_id.hash != self.header.hash():
            raise LightBlockError(
                "commit signs a different header "
                f"({self.commit.block_id.hash.hex()} != "
                f"{(self.header.hash() or b'').hex()})"
            )


@dataclass(frozen=True, slots=True)
class LightBlock:
    """SignedHeader + the validator set of that height (types/light.go:28)."""

    signed_header: SignedHeader
    validator_set: ValidatorSet

    @property
    def height(self) -> int:
        return self.signed_header.height

    @property
    def time_ns(self) -> int:
        return self.signed_header.time_ns

    def hash(self) -> bytes | None:
        return self.signed_header.hash()

    def validate_basic(self, chain_id: str) -> None:
        if self.signed_header is None:
            raise LightBlockError("missing signed header")
        if self.validator_set is None:
            raise LightBlockError("missing validator set")
        self.signed_header.validate_basic(chain_id)
        vals_hash = self.validator_set.hash()
        if self.signed_header.header.validators_hash != vals_hash:
            raise LightBlockError(
                "validator set does not match header validators_hash"
            )
