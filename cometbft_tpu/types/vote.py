"""Vote and Proposal (reference: types/vote.go, types/proposal.go).

A Vote is one validator's signed prevote/precommit for a block (or nil).
Sign bytes are the canonical length-delimited protobuf of CanonicalVote
(types/vote.go:139-161); extensions sign a separate CanonicalVoteExtension
(precommits for non-nil blocks only).
"""

from __future__ import annotations

from dataclasses import dataclass

from . import canonical
from .block import (
    BLOCK_ID_FLAG_ABSENT,
    BLOCK_ID_FLAG_COMMIT,
    BLOCK_ID_FLAG_NIL,
    BlockID,
    CommitSig,
)

MAX_VOTE_EXTENSION_SIZE = 1024 * 1024  # types/params.go default cap


class VoteError(Exception):
    pass


@dataclass(slots=True)
class Vote:
    msg_type: int  # PREVOTE_TYPE | PRECOMMIT_TYPE
    height: int
    round: int
    block_id: BlockID  # nil BlockID = vote for nil
    timestamp_ns: int
    validator_address: bytes
    validator_index: int
    signature: bytes = b""
    extension: bytes = b""
    extension_signature: bytes = b""

    def is_nil(self) -> bool:
        return self.block_id.is_nil()

    def sign_bytes(self, chain_id: str) -> bytes:
        return canonical.vote_sign_bytes(
            chain_id,
            self.msg_type,
            self.height,
            self.round,
            self.block_id,
            self.timestamp_ns,
        )

    def extension_sign_bytes(self, chain_id: str) -> bytes:
        return canonical.vote_extension_sign_bytes(
            chain_id, self.height, self.round, self.extension
        )

    def verify(self, chain_id: str, pub_key) -> None:
        """Signature + address check (types/vote.go:210-232).

        The signature check routes through the cross-caller verify
        coalescer when one is active (crypto/coalesce): identical
        verdicts — the coalescer runs the same kernels/host verifiers
        — but concurrent per-vote callers share one device launch.
        Unrouted (no coalescer, foreign key type, routing failure) it
        is exactly ``pub_key.verify_signature``.
        """
        from ..crypto import coalesce
        from ..libs import devledger

        if bytes(pub_key.address()) != self.validator_address:
            raise VoteError("invalid validator address")
        # ledger attribution default: an untagged vote verify is the
        # steady-state consensus path; outer tenants (the evidence
        # verifier, the light service) already declared and win
        with devledger.caller_class("consensus-vote"):
            ok = coalesce.verify_signature(
                pub_key, self.sign_bytes(chain_id), self.signature
            )
        if not ok:
            raise VoteError("invalid signature")

    def verify_vote_and_extension(self, chain_id: str, pub_key) -> None:
        """Verify vote + extension signature (types/vote.go:233-252)."""
        self.verify(chain_id, pub_key)
        if (
            self.msg_type == canonical.PRECOMMIT_TYPE
            and not self.block_id.is_nil()
        ):
            self.verify_extension(chain_id, pub_key)

    def verify_extension(self, chain_id: str, pub_key) -> None:
        """Extension signature only (types/vote.go:254-270); coalesced
        like :meth:`verify`."""
        from ..crypto import coalesce
        from ..libs import devledger

        if self.msg_type != canonical.PRECOMMIT_TYPE or self.block_id.is_nil():
            return
        with devledger.caller_class("consensus-vote"):
            ok = coalesce.verify_signature(
                pub_key, self.extension_sign_bytes(chain_id),
                self.extension_signature,
            )
        if not ok:
            raise VoteError("invalid extension signature")

    def commit_sig(self) -> CommitSig:
        """Convert to a commit slot (types/vote.go CommitSig)."""
        if self.block_id.is_complete():
            flag = BLOCK_ID_FLAG_COMMIT
        elif self.block_id.is_nil():
            flag = BLOCK_ID_FLAG_NIL
        else:
            raise VoteError(f"invalid block id {self.block_id} for conversion")
        return CommitSig(
            block_id_flag=flag,
            validator_address=self.validator_address,
            timestamp_ns=self.timestamp_ns,
            signature=self.signature,
        )

    def validate_basic(self) -> None:
        if self.msg_type not in (
            canonical.PREVOTE_TYPE,
            canonical.PRECOMMIT_TYPE,
        ):
            raise VoteError("invalid vote type")
        if self.height < 0:
            raise VoteError("negative height")
        if self.round < 0:
            raise VoteError("negative round")
        self.block_id.validate_basic()
        if not self.block_id.is_nil() and not self.block_id.is_complete():
            raise VoteError(f"block id must be nil or complete: {self.block_id}")
        if len(self.validator_address) != 20:
            raise VoteError("validator address must be 20 bytes")
        if self.validator_index < 0:
            raise VoteError("negative validator index")
        if not self.signature:
            raise VoteError("missing signature")
        if len(self.signature) > 64:
            raise VoteError("signature too long")
        if self.msg_type == canonical.PREVOTE_TYPE and self.extension:
            raise VoteError("prevotes cannot carry extensions")
        if self.is_nil() and (self.extension or self.extension_signature):
            # issue #8487: nil precommits must not carry extension data
            raise VoteError("nil votes cannot carry extensions")
        if len(self.extension) > MAX_VOTE_EXTENSION_SIZE:
            raise VoteError("extension too large")


@dataclass(slots=True)
class Proposal:
    """Block proposal (types/proposal.go)."""

    height: int
    round: int
    pol_round: int  # -1 if no proof-of-lock
    block_id: BlockID
    timestamp_ns: int
    signature: bytes = b""

    def sign_bytes(self, chain_id: str) -> bytes:
        return canonical.proposal_sign_bytes(
            chain_id,
            self.height,
            self.round,
            self.pol_round,
            self.block_id,
            self.timestamp_ns,
        )

    def validate_basic(self) -> None:
        if self.height < 0:
            raise VoteError("negative height")
        if self.round < 0:
            raise VoteError("negative round")
        if self.pol_round < -1 or self.pol_round >= self.round:
            raise VoteError("invalid pol round")
        self.block_id.validate_basic()
        if not self.block_id.is_complete():
            raise VoteError("proposal block id must be complete")
        if not self.signature or len(self.signature) > 64:
            raise VoteError("bad proposal signature")


__all__ = [
    "Vote",
    "Proposal",
    "VoteError",
    "BLOCK_ID_FLAG_ABSENT",
    "BLOCK_ID_FLAG_COMMIT",
    "BLOCK_ID_FLAG_NIL",
]
