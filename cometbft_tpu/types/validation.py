"""Commit verification — the engine-wide hot path (types/validation.go).

All three façades tally voting power while streaming (pubkey, sign-bytes,
signature) triples into one device batch:

* verify_commit          — full check, every signature (consensus apply path)
* verify_commit_light    — stop at +2/3, commit-flag sigs only (light/blocksync)
* verify_commit_light_trusting — trust-level fraction over a *different*
  validator set, lookup by address (light-client bisection)

Semantics follow types/validation.go:26-257 exactly, including the
batch-vs-single fallback threshold and the find-first-invalid error.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..crypto import batch as crypto_batch
from .block import (
    BLOCK_ID_FLAG_ABSENT,
    BLOCK_ID_FLAG_COMMIT,
    BlockID,
    Commit,
)
from .validator_set import ValidatorSet

BATCH_VERIFY_THRESHOLD = 2  # types/validation.go:13-17


class VerificationError(Exception):
    pass


@dataclass
class NotEnoughVotingPowerError(VerificationError):
    got: int
    needed: int

    def __str__(self) -> str:
        return (
            f"invalid commit -- insufficient voting power: got {self.got}, "
            f"needed more than {self.needed}"
        )


@dataclass(frozen=True)
class Fraction:
    numerator: int
    denominator: int


DEFAULT_TRUST_LEVEL = Fraction(1, 3)


def _should_batch_verify(vals: ValidatorSet, commit: Commit) -> bool:
    # Unlike the reference (which keys off one type and bails to single
    # verifies when a mixed set trips Add, types/validation.go:170-176),
    # a heterogeneous set batches too: every key type just needs a
    # backend (crypto_batch.MixedBatchVerifier — one device launch).
    return (
        len(commit.signatures) >= BATCH_VERIFY_THRESHOLD
        and crypto_batch.supports_commit_batch(vals)
    )


def _verify_basic(vals, commit, height, block_id) -> None:
    if vals is None:
        raise VerificationError("nil validator set")
    if commit is None:
        raise VerificationError("nil commit")
    if len(vals) != len(commit.signatures):
        raise VerificationError(
            f"validator set size {len(vals)} != commit size "
            f"{len(commit.signatures)}"
        )
    if height != commit.height:
        raise VerificationError(
            f"invalid commit height {commit.height}, expected {height}"
        )
    if block_id != commit.block_id:
        raise VerificationError(
            f"invalid commit block id {commit.block_id}, expected {block_id}"
        )


def verify_commit(
    chain_id: str,
    vals: ValidatorSet,
    block_id: BlockID,
    height: int,
    commit: Commit,
) -> None:
    """+2/3 check over ALL signatures (incl. nil votes) — consensus path."""
    _verify_basic(vals, commit, height, block_id)
    needed = vals.total_voting_power() * 2 // 3
    ignore = lambda cs: cs.block_id_flag == BLOCK_ID_FLAG_ABSENT  # noqa: E731
    count = lambda cs: cs.block_id_flag == BLOCK_ID_FLAG_COMMIT  # noqa: E731
    _verify(
        chain_id, vals, commit, needed, ignore, count,
        count_all=True, by_index=True,
    )


def verify_commit_light(
    chain_id: str,
    vals: ValidatorSet,
    block_id: BlockID,
    height: int,
    commit: Commit,
) -> None:
    """+2/3 check, commit-flag signatures only, stops when reached."""
    _verify_basic(vals, commit, height, block_id)
    needed = vals.total_voting_power() * 2 // 3
    ignore = lambda cs: cs.block_id_flag != BLOCK_ID_FLAG_COMMIT  # noqa: E731
    count = lambda cs: True  # noqa: E731
    _verify(
        chain_id, vals, commit, needed, ignore, count,
        count_all=False, by_index=True,
    )


def verify_commit_light_trusting(
    chain_id: str,
    vals: ValidatorSet,
    commit: Commit,
    trust_level: Fraction = DEFAULT_TRUST_LEVEL,
) -> None:
    """trust-level fraction of a (possibly different) validator set."""
    if vals is None:
        raise VerificationError("nil validator set")
    if commit is None:
        raise VerificationError("nil commit")
    if trust_level.denominator == 0:
        raise VerificationError("trust level has zero denominator")
    needed = (
        vals.total_voting_power() * trust_level.numerator
    ) // trust_level.denominator
    ignore = lambda cs: cs.block_id_flag != BLOCK_ID_FLAG_COMMIT  # noqa: E731
    count = lambda cs: True  # noqa: E731
    _verify(
        chain_id, vals, commit, needed, ignore, count,
        count_all=False, by_index=False,
    )


def _verify(
    chain_id, vals, commit, needed, ignore, count, count_all, by_index
) -> None:
    from ..libs import devledger

    # ledger attribution default: an untagged commit verification is
    # the consensus apply path; outer tenants (the light service, the
    # blocksync reactor, statesync restores) declared first and win
    with devledger.caller_class("commit-verify"):
        if _should_batch_verify(vals, commit):
            _verify_batch(
                chain_id, vals, commit, needed, ignore, count, count_all,
                by_index,
            )
        else:
            _verify_single(
                chain_id, vals, commit, needed, ignore, count, count_all,
                by_index,
            )


def _verify_batch(
    chain_id, vals, commit, needed, ignore, count, count_all, by_index
) -> None:
    """Mirror of verifyCommitBatch (types/validation.go:153-257)."""
    bv = crypto_batch.create_commit_batch_verifier(vals)
    seen: dict[int, int] = {}
    batch_sig_idxs: list[int] = []
    tallied = 0
    for idx, cs in enumerate(commit.signatures):
        if ignore(cs):
            continue
        if by_index:
            val = vals.validators[idx]
        else:
            val_idx, val = vals.get_by_address(cs.validator_address)
            if val is None:
                continue
            if val_idx in seen:
                raise VerificationError(
                    f"double vote from validator {val_idx} "
                    f"({seen[val_idx]} and {idx})"
                )
            seen[val_idx] = idx
        sign_bytes = commit.vote_sign_bytes(chain_id, idx)
        bv.add(val.pub_key, sign_bytes, cs.signature)
        batch_sig_idxs.append(idx)
        if count(cs):
            tallied += val.voting_power
        if not count_all and tallied > needed:
            break
    if tallied <= needed:
        raise NotEnoughVotingPowerError(got=tallied, needed=needed)
    ok, valid_sigs = bv.verify()
    if ok:
        return
    for i, sig_ok in enumerate(valid_sigs):
        if not sig_ok:
            idx = batch_sig_idxs[i]
            raise VerificationError(
                f"wrong signature (#{idx}): "
                f"{commit.signatures[idx].signature.hex()}"
            )
    raise VerificationError(
        "BUG: batch verification failed with no invalid signatures"
    )


def _verify_single(
    chain_id, vals, commit, needed, ignore, count, count_all, by_index
) -> None:
    """Mirror of verifyCommitSingle (types/validation.go:266-330).

    With a cross-caller coalescer routed (crypto/coalesce), the
    eligible per-signature verifies of one commit are deferred and
    submitted as a group — concurrent single-verify commit checks
    (light bisection, evidence) then share device micro-batches — with
    the same tally walk, the same early stop, and the same
    first-invalid error by index. Ineligible key types verify inline
    exactly as before.
    """
    from ..crypto import coalesce

    co = coalesce.active()
    seen: dict[int, int] = {}
    tallied = 0
    deferred: list[tuple] = []  # (idx, pubkey_data, sign_bytes, sig)
    stopped_early = False
    # Any raise inside the walk is HELD, not thrown: deferred ed25519
    # lanes collected earlier in the walk are still unverified, and the
    # unrouted walk raises at the earliest failing index — an invalid
    # deferred lane must surface before a later double-vote /
    # sign-bytes / wrong-signature error. All deferred lanes precede
    # the break point by construction, so resolving them first and
    # then re-raising preserves the reference error identity.
    walk_exc: BaseException | None = None
    for idx, cs in enumerate(commit.signatures):
        if ignore(cs):
            continue
        if by_index:
            val = vals.validators[idx]
        else:
            val_idx, val = vals.get_by_address(cs.validator_address)
            if val is None:
                continue
            if val_idx in seen:
                walk_exc = VerificationError(
                    f"double vote from validator {val_idx} "
                    f"({seen[val_idx]} and {idx})"
                )
                break
            seen[val_idx] = idx
        try:
            sign_bytes = commit.vote_sign_bytes(chain_id, idx)
        except Exception as e:
            walk_exc = e
            break
        if co is not None and coalesce.eligible(val.pub_key):
            deferred.append(
                (idx, val.pub_key, sign_bytes, cs.signature)
            )
        elif not val.pub_key.verify_signature(sign_bytes, cs.signature):
            walk_exc = VerificationError(f"wrong signature (#{idx})")
            break
        if count(cs):
            tallied += val.voting_power
        if not count_all and tallied > needed:
            stopped_early = True
            break
    if deferred:
        bits = coalesce.verify_bytes(
            [pk.data for _, pk, _, _ in deferred],
            [sb for _, _, sb, _ in deferred],
            [sig for _, _, _, sig in deferred],
        )
        if bits is None:  # coalescer went away mid-walk: host verify
            bits = [
                pk.verify_signature(sb, sig)
                for _, pk, sb, sig in deferred
            ]
        for (idx, _, _, _), ok in zip(deferred, bits):
            if not ok:
                raise VerificationError(f"wrong signature (#{idx})")
    if walk_exc is not None:
        raise walk_exc
    if stopped_early:
        return
    if tallied <= needed:
        raise NotEnoughVotingPowerError(got=tallied, needed=needed)
