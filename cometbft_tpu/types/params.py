"""Consensus parameters (reference: types/params.go).

Hashed into Header.ConsensusHash; updatable by the ABCI app per block.
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field, replace

from ..crypto import tmhash
from . import proto

MAX_BLOCK_SIZE_BYTES = 104857600  # 100MB
MAX_BLOCK_PARTS = 1601
MAX_EVIDENCE_BYTES_DENOM = 3


@dataclass(frozen=True, slots=True)
class BlockParams:
    max_bytes: int = 22020096  # 21MB
    max_gas: int = -1


@dataclass(frozen=True, slots=True)
class EvidenceParams:
    max_age_num_blocks: int = 100000
    max_age_duration_ns: int = 48 * 3600 * 1_000_000_000
    max_bytes: int = 1048576


@dataclass(frozen=True, slots=True)
class ValidatorParams:
    pub_key_types: tuple[str, ...] = ("ed25519",)


@dataclass(frozen=True, slots=True)
class VersionParams:
    app: int = 0


@dataclass(frozen=True, slots=True)
class ABCIParams:
    vote_extensions_enable_height: int = 0


@dataclass(frozen=True, slots=True)
class ConsensusParams:
    block: BlockParams = dc_field(default_factory=BlockParams)
    evidence: EvidenceParams = dc_field(default_factory=EvidenceParams)
    validator: ValidatorParams = dc_field(default_factory=ValidatorParams)
    version: VersionParams = dc_field(default_factory=VersionParams)
    abci: ABCIParams = dc_field(default_factory=ABCIParams)

    def vote_extensions_enabled(self, height: int) -> bool:
        h = self.abci.vote_extensions_enable_height
        return h != 0 and height >= h

    def hash(self) -> bytes:
        """SHA-256 of the HashedParams subset (types/params.go Hash —
        only block max_bytes/max_gas feed the hash, by protocol spec)."""
        body = proto.field_varint(1, self.block.max_bytes) + proto.field_varint(
            2, self.block.max_gas & 0xFFFFFFFFFFFFFFFF
            if self.block.max_gas < 0
            else self.block.max_gas,
        )
        return tmhash.sum(body)

    def validate_basic(self) -> None:
        if self.block.max_bytes == 0 or self.block.max_bytes < -1:
            raise ValueError("block.max_bytes must be -1 or positive")
        if self.block.max_bytes > MAX_BLOCK_SIZE_BYTES:
            raise ValueError("block.max_bytes too large")
        if self.block.max_gas < -1:
            raise ValueError("block.max_gas must be >= -1")
        if self.evidence.max_age_num_blocks <= 0:
            raise ValueError("evidence.max_age_num_blocks must be positive")
        if self.evidence.max_bytes < 0:
            raise ValueError("evidence.max_bytes must be non-negative")
        if not self.validator.pub_key_types:
            raise ValueError("validator.pub_key_types cannot be empty")
        if self.abci.vote_extensions_enable_height < 0:
            raise ValueError("abci.vote_extensions_enable_height negative")

    def update(self, updates) -> "ConsensusParams":
        """Apply an ABCI ConsensusParams update (partial)."""
        if updates is None:
            return self
        out = self
        for section in ("block", "evidence", "validator", "version", "abci"):
            upd = getattr(updates, section, None)
            if upd is not None:
                out = replace(out, **{section: upd})
        return out
