"""Canonical sign-bytes encodings (consensus-critical, byte-exact).

Reference: types/canonical.go, proto/tendermint/types/canonical.proto,
types/vote.go:139-161 (VoteSignBytes / VoteExtensionSignBytes),
types/proposal.go:102-116 (ProposalSignBytes). All sign bytes are uvarint
length-delimited protobuf (protoio.MarshalDelimited).

Message types: prevote=1, precommit=2, proposal=32
(proto/tendermint/types/types.proto:17-23).
"""

from __future__ import annotations

from . import proto

PREVOTE_TYPE = 1
PRECOMMIT_TYPE = 2
PROPOSAL_TYPE = 32


def is_vote_type(msg_type: int) -> bool:
    return msg_type in (PREVOTE_TYPE, PRECOMMIT_TYPE)


def canonical_part_set_header(total: int, hash_: bytes) -> bytes:
    return proto.field_varint(1, total) + proto.field_bytes(2, hash_)


def canonical_block_id(block_id) -> bytes:
    """CanonicalBlockID body; b'' when the block id is nil (field omitted).

    The nested part-set header is gogoproto nullable=false: always emitted.
    """
    if block_id is None or block_id.is_nil():
        return b""
    psh = block_id.part_set_header
    return proto.field_bytes(1, block_id.hash) + proto.field_message(
        2, canonical_part_set_header(psh.total, psh.hash), always=True
    )


# One consensus round encodes O(validators) CanonicalVotes that differ
# ONLY in the timestamp field: the constant prefix (type|height|round|
# block-id) and suffix (chain-id) are cached per round context so the
# batch-ingest hot path (types/vote_set.add_votes_batch) re-encodes just
# the timestamp. Tiny working set (a handful of contexts per height);
# cleared wholesale when it grows past the bound. Byte-equality with the
# uncached encoding is pinned by tests.
_SIGN_TEMPLATE_CACHE: dict = {}
_SIGN_TEMPLATE_BOUND = 64


def vote_sign_bytes(
    chain_id: str,
    msg_type: int,
    height: int,
    round_: int,
    block_id,
    timestamp_ns: int,
) -> bytes:
    """CanonicalVote sign bytes (types/vote.go:139, canonical.proto:30-37)."""
    bid_key = (
        None
        if block_id is None or block_id.is_nil()
        else (
            bytes(block_id.hash),
            block_id.part_set_header.total,
            bytes(block_id.part_set_header.hash),
        )
    )
    key = (chain_id, msg_type, height, round_, bid_key)
    tpl = _SIGN_TEMPLATE_CACHE.get(key)
    if tpl is None:
        cbid = canonical_block_id(block_id)
        tpl = (
            proto.field_varint(1, msg_type)
            + proto.field_sfixed64(2, height)
            + proto.field_sfixed64(3, round_)
            + proto.field_message(4, cbid),
            proto.field_string(6, chain_id),
        )
        if len(_SIGN_TEMPLATE_CACHE) >= _SIGN_TEMPLATE_BOUND:
            _SIGN_TEMPLATE_CACHE.clear()
        _SIGN_TEMPLATE_CACHE[key] = tpl
    prefix, suffix = tpl
    body = (
        prefix
        + proto.field_message(5, proto.timestamp(timestamp_ns), always=True)
        + suffix
    )
    return proto.delimited(body)


def proposal_sign_bytes(
    chain_id: str,
    height: int,
    round_: int,
    pol_round: int,
    block_id,
    timestamp_ns: int,
) -> bytes:
    """CanonicalProposal sign bytes (types/proposal.go:110)."""
    cbid = canonical_block_id(block_id)
    body = (
        proto.field_varint(1, PROPOSAL_TYPE)
        + proto.field_sfixed64(2, height)
        + proto.field_sfixed64(3, round_)
        + proto.field_varint(4, pol_round)
        + proto.field_message(5, cbid)
        + proto.field_message(6, proto.timestamp(timestamp_ns), always=True)
        + proto.field_string(7, chain_id)
    )
    return proto.delimited(body)


def vote_extension_sign_bytes(
    chain_id: str, height: int, round_: int, extension: bytes
) -> bytes:
    """CanonicalVoteExtension sign bytes (canonical.proto:41-46)."""
    body = (
        proto.field_bytes(1, extension)
        + proto.field_sfixed64(2, height)
        + proto.field_sfixed64(3, round_)
        + proto.field_string(4, chain_id)
    )
    return proto.delimited(body)
