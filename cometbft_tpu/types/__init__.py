"""L2 wire types & data model (reference: types/, proto/tendermint)."""

from .block import (  # noqa: F401
    BLOCK_ID_FLAG_ABSENT,
    BLOCK_ID_FLAG_COMMIT,
    BLOCK_ID_FLAG_NIL,
    Block,
    BlockID,
    BlockMeta,
    Commit,
    CommitSig,
    Data,
    ExtendedCommit,
    ExtendedCommitSig,
    Header,
    NIL_BLOCK_ID,
    PartSetHeader,
    Version,
    make_block,
)
from .canonical import (  # noqa: F401
    PRECOMMIT_TYPE,
    PREVOTE_TYPE,
    PROPOSAL_TYPE,
)
from .evidence import (  # noqa: F401
    DuplicateVoteEvidence,
    Evidence,
    LightClientAttackEvidence,
)
from .genesis import GenesisDoc, GenesisValidator  # noqa: F401
from .params import ConsensusParams  # noqa: F401
from .part_set import Part, PartSet  # noqa: F401
from .priv_validator import ErroringMockPV, MockPV, PrivValidator  # noqa: F401
from .validation import (  # noqa: F401
    Fraction,
    NotEnoughVotingPowerError,
    VerificationError,
    verify_commit,
    verify_commit_light,
    verify_commit_light_trusting,
)
from .validator_set import Validator, ValidatorSet  # noqa: F401
from .vote import Proposal, Vote, VoteError  # noqa: F401
from .vote_set import ConflictingVoteError, VoteSet  # noqa: F401
from .light_block import LightBlock, SignedHeader  # noqa: E402,F401
