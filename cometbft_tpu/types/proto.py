"""Minimal deterministic protobuf wire-format writer/reader.

The consensus-critical byte strings (vote/proposal sign bytes, canonical
block IDs) are protobuf messages whose encoding must be byte-exact
(reference: types/canonical.go + gogoproto marshaling). Rather than depend
on a codegen toolchain, the handful of message shapes involved are encoded
directly with these primitives, following proto3 + gogoproto rules:

* fields appear in ascending field-number order;
* scalar fields equal to their zero value are omitted;
* non-nullable embedded messages (gogoproto.nullable=false) are ALWAYS
  emitted, even when empty;
* sfixed64 for canonical height/round (fixed-width: canonicalization
  requires size-independent encoding — proto/tendermint/types/canonical.proto).

Also the uvarint length-delimited framing of protoio.MarshalDelimited
(libs/protoio/writer.go) used for all sign bytes.
"""

from __future__ import annotations

# Wire types
VARINT = 0
FIXED64 = 1
BYTES = 2
FIXED32 = 5

# Unix-epoch offset of time.Time's zero value (year 1, UTC) in seconds;
# gogoproto stdtime encodes Go's zero time as this many seconds.
ZERO_TIME_SECONDS = -62135596800
ZERO_TIME_NS = ZERO_TIME_SECONDS * 1_000_000_000


def uvarint(n: int) -> bytes:
    if n < 0:
        raise ValueError("uvarint requires n >= 0")
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def varint(n: int) -> bytes:
    """Signed int64 as protobuf varint (two's complement, 10 bytes if <0)."""
    return uvarint(n & 0xFFFFFFFFFFFFFFFF)


def tag(field: int, wire: int) -> bytes:
    return uvarint(field << 3 | wire)


def field_varint(field: int, value: int, emit_zero: bool = False) -> bytes:
    if value == 0 and not emit_zero:
        return b""
    return tag(field, VARINT) + varint(value)


def field_sfixed64(field: int, value: int, emit_zero: bool = False) -> bytes:
    if value == 0 and not emit_zero:
        return b""
    return tag(field, FIXED64) + (value & 0xFFFFFFFFFFFFFFFF).to_bytes(
        8, "little"
    )


def field_bytes(field: int, value: bytes, emit_empty: bool = False) -> bytes:
    if not value and not emit_empty:
        return b""
    return tag(field, BYTES) + uvarint(len(value)) + value


def field_string(field: int, value: str, emit_empty: bool = False) -> bytes:
    return field_bytes(field, value.encode(), emit_empty)


def field_message(field: int, encoded: bytes, always: bool = False) -> bytes:
    """Embedded message; ``always=True`` = gogoproto nullable=false."""
    if not encoded and not always:
        return b""
    return tag(field, BYTES) + uvarint(len(encoded)) + encoded


def timestamp(ns: int) -> bytes:
    """google.protobuf.Timestamp message body from ns since Unix epoch."""
    seconds, nanos = divmod(ns, 1_000_000_000)
    return field_varint(1, seconds) + field_varint(2, nanos)


def delimited(msg: bytes) -> bytes:
    """protoio.MarshalDelimited framing: uvarint byte-length prefix."""
    return uvarint(len(msg)) + msg


def read_delimited(read_exact, max_bytes: int) -> bytes:
    """Read one uvarint-length-prefixed frame via ``read_exact(n)``.

    ``read_exact`` must return exactly n bytes or raise (EOFError on a
    closed stream). Shared by every process-boundary codec (ABCI socket,
    privval socket) — protoio.Reader semantics with a hard size cap.
    """
    length = 0
    shift = 0
    while True:
        b = read_exact(1)
        length |= (b[0] & 0x7F) << shift
        if not b[0] & 0x80:
            break
        shift += 7
        if shift > 35:
            raise ValueError("frame length uvarint overflow")
    if length > max_bytes:
        raise ValueError(f"frame of {length} bytes exceeds limit")
    return read_exact(length)


# --- Reader (for WAL / wire decode) -----------------------------------------


def read_uvarint(buf: bytes, pos: int) -> tuple[int, int]:
    shift = 0
    value = 0
    while True:
        if pos >= len(buf):
            raise ValueError("truncated uvarint")
        b = buf[pos]
        pos += 1
        value |= (b & 0x7F) << shift
        if not b & 0x80:
            return value, pos
        shift += 7
        if shift > 63:
            raise ValueError("uvarint overflow")


def read_svarint(buf: bytes, pos: int) -> tuple[int, int]:
    v, pos = read_uvarint(buf, pos)
    if v >= 1 << 63:
        v -= 1 << 64
    return v, pos


def read_fields(buf: bytes) -> list[tuple[int, int, object]]:
    """Decode a message body into (field, wire, value) triples."""
    out = []
    pos = 0
    while pos < len(buf):
        key, pos = read_uvarint(buf, pos)
        field, wire = key >> 3, key & 7
        if wire == VARINT:
            value, pos = read_uvarint(buf, pos)
        elif wire == FIXED64:
            value = int.from_bytes(buf[pos : pos + 8], "little")
            pos += 8
        elif wire == FIXED32:
            value = int.from_bytes(buf[pos : pos + 4], "little")
            pos += 4
        elif wire == BYTES:
            ln, pos = read_uvarint(buf, pos)
            value = buf[pos : pos + ln]
            if len(value) != ln:
                raise ValueError("truncated bytes field")
            pos += ln
        else:
            raise ValueError(f"unsupported wire type {wire}")
        out.append((field, wire, value))
    return out
