"""Typed event bus over pubsub (reference: types/event_bus.go:33,
types/events.go).

Every consensus step, block, and tx publishes here; RPC subscriptions and
the tx/block indexers consume. Event data carries the publishing type's
object plus the ABCI events flattened into composite-keyed attributes
(``{event_type}.{attr_key}`` → values) for query matching.
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field
from typing import Any

from ..libs import pubsub
from ..libs.service import BaseService

# tm.event values (types/events.go)
EVENT_NEW_BLOCK = "NewBlock"
EVENT_NEW_BLOCK_HEADER = "NewBlockHeader"
EVENT_NEW_BLOCK_EVENTS = "NewBlockEvents"
EVENT_NEW_EVIDENCE = "NewEvidence"
EVENT_TX = "Tx"
EVENT_VALIDATOR_SET_UPDATES = "ValidatorSetUpdates"
EVENT_NEW_ROUND_STEP = "NewRoundStep"
EVENT_NEW_ROUND = "NewRound"
EVENT_COMPLETE_PROPOSAL = "CompleteProposal"
EVENT_VOTE = "Vote"
EVENT_POLKA = "Polka"
EVENT_RELOCK = "Relock"
EVENT_LOCK = "Lock"
EVENT_TIMEOUT_PROPOSE = "TimeoutPropose"
EVENT_TIMEOUT_WAIT = "TimeoutWait"
EVENT_PROPOSAL_BLOCK_PART = "ProposalBlockPart"

EVENT_TYPE_KEY = "tm.event"
TX_HASH_KEY = "tx.hash"
TX_HEIGHT_KEY = "tx.height"
BLOCK_HEIGHT_KEY = "block.height"


def query_for_event(event: str) -> pubsub.Query:
    return pubsub.Query.parse(f"{EVENT_TYPE_KEY} = '{event}'")


QUERY_NEW_BLOCK = query_for_event(EVENT_NEW_BLOCK)
QUERY_TX = query_for_event(EVENT_TX)


def flatten_abci_events(events, base: dict[str, list[str]]) -> dict:
    """composite ``{type}.{key}`` → [values] (pubsub indexing convention)."""
    out = dict(base)
    for ev in events or ():
        for attr in ev.attributes:
            out.setdefault(f"{ev.type}.{attr.key}", []).append(attr.value)
    return out


@dataclass(slots=True)
class EventDataNewBlock:
    block: Any
    block_id: Any
    result_finalize_block: Any = None


@dataclass(slots=True)
class EventDataNewBlockHeader:
    header: Any


@dataclass(slots=True)
class EventDataNewBlockEvents:
    height: int
    events: list = dc_field(default_factory=list)
    num_txs: int = 0


@dataclass(slots=True)
class EventDataTx:
    height: int
    index: int
    tx: bytes
    result: Any  # ExecTxResult


@dataclass(slots=True)
class EventDataRoundState:
    height: int
    round: int
    step: str


@dataclass(slots=True)
class EventDataNewRound:
    height: int
    round: int
    step: str
    proposer_address: bytes = b""


@dataclass(slots=True)
class EventDataCompleteProposal:
    height: int
    round: int
    step: str
    block_id: Any = None


@dataclass(slots=True)
class EventDataVote:
    vote: Any


@dataclass(slots=True)
class EventDataValidatorSetUpdates:
    validator_updates: list


@dataclass(slots=True)
class EventDataNewEvidence:
    height: int
    evidence: Any


class EventBus(BaseService):
    def __init__(self):
        super().__init__("event-bus")
        self.server = pubsub.Server()

    def on_stop(self) -> None:
        self.server.stop()

    # -- subscription façade ----------------------------------------------

    def subscribe(self, subscriber: str, query, capacity: int | None = 100):
        return self.server.subscribe(subscriber, query, capacity)

    def unsubscribe(self, subscriber: str, query) -> None:
        self.server.unsubscribe(subscriber, query)

    def unsubscribe_all(self, subscriber: str) -> None:
        self.server.unsubscribe_all(subscriber)

    def num_clients(self) -> int:
        return self.server.num_clients()

    # -- typed publishers --------------------------------------------------

    def _publish(self, event: str, data, extra: dict | None = None) -> None:
        events = {EVENT_TYPE_KEY: [event]}
        if extra:
            for k, v in extra.items():
                events.setdefault(k, []).extend(v)
        self.server.publish(data, events)

    def publish_new_block(self, data: EventDataNewBlock) -> None:
        extra = flatten_abci_events(
            getattr(data.result_finalize_block, "events", None),
            {BLOCK_HEIGHT_KEY: [str(data.block.header.height)]},
        )
        self._publish(EVENT_NEW_BLOCK, data, extra)

    def publish_new_block_header(self, data: EventDataNewBlockHeader) -> None:
        self._publish(
            EVENT_NEW_BLOCK_HEADER,
            data,
            {BLOCK_HEIGHT_KEY: [str(data.header.height)]},
        )

    def publish_new_block_events(self, data: EventDataNewBlockEvents) -> None:
        extra = flatten_abci_events(
            data.events, {BLOCK_HEIGHT_KEY: [str(data.height)]}
        )
        self._publish(EVENT_NEW_BLOCK_EVENTS, data, extra)

    def publish_tx(self, data: EventDataTx) -> None:
        from ..crypto import tmhash

        extra = flatten_abci_events(
            getattr(data.result, "events", None),
            {
                TX_HEIGHT_KEY: [str(data.height)],
                TX_HASH_KEY: [tmhash.sum(data.tx).hex().upper()],
            },
        )
        self._publish(EVENT_TX, data, extra)

    def publish_validator_set_updates(
        self, data: EventDataValidatorSetUpdates
    ) -> None:
        self._publish(EVENT_VALIDATOR_SET_UPDATES, data)

    def publish_new_evidence(self, data: EventDataNewEvidence) -> None:
        self._publish(EVENT_NEW_EVIDENCE, data)

    # consensus step events (consumed by the consensus reactor + RPC)
    def publish_new_round_step(self, data: EventDataRoundState) -> None:
        self._publish(EVENT_NEW_ROUND_STEP, data)

    def publish_new_round(self, data: EventDataNewRound) -> None:
        self._publish(EVENT_NEW_ROUND, data)

    def publish_complete_proposal(self, data: EventDataCompleteProposal) -> None:
        self._publish(EVENT_COMPLETE_PROPOSAL, data)

    def publish_vote(self, data: EventDataVote) -> None:
        self._publish(EVENT_VOTE, data)

    def publish_polka(self, data: EventDataRoundState) -> None:
        self._publish(EVENT_POLKA, data)

    def publish_lock(self, data: EventDataRoundState) -> None:
        self._publish(EVENT_LOCK, data)

    def publish_relock(self, data: EventDataRoundState) -> None:
        self._publish(EVENT_RELOCK, data)

    def publish_timeout_propose(self, data: EventDataRoundState) -> None:
        self._publish(EVENT_TIMEOUT_PROPOSE, data)

    def publish_timeout_wait(self, data: EventDataRoundState) -> None:
        self._publish(EVENT_TIMEOUT_WAIT, data)


class NopEventBus:
    """Publishes nowhere (used by tools that don't need events)."""

    def __getattr__(self, name):
        if name.startswith("publish_"):
            return lambda *a, **k: None
        raise AttributeError(name)
