"""Storage/wire codec for the data model: one shared ``Codec`` with every
persistable type registered (reference analog: proto/tendermint marshaling
used by store/store.go and state/store.go).

``ValidatorSet`` restores exactly (validator order, proposer, priorities) —
its constructor rotates priorities, so decode bypasses it.
"""

from __future__ import annotations

from ..crypto import keys
from ..crypto.merkle import Proof
from ..libs.jsoncodec import Codec
from . import evidence as ev
from .block import (
    Block,
    BlockID,
    BlockMeta,
    Commit,
    CommitSig,
    Data,
    ExtendedCommit,
    ExtendedCommitSig,
    Header,
    PartSetHeader,
    Version,
)
from .params import (
    ABCIParams,
    BlockParams,
    ConsensusParams,
    EvidenceParams,
    ValidatorParams,
    VersionParams,
)
from .light_block import LightBlock, SignedHeader
from .part_set import Part
from .validator_set import Validator, ValidatorSet
from .vote import Proposal, Vote

codec = Codec()

codec.register(
    Proof,
    PartSetHeader,
    BlockID,
    Version,
    Header,
    CommitSig,
    Commit,
    Data,
    Block,
    BlockMeta,
    ExtendedCommitSig,
    ExtendedCommit,
    Part,
    Vote,
    Proposal,
    Validator,
    BlockParams,
    EvidenceParams,
    ValidatorParams,
    VersionParams,
    ABCIParams,
    ConsensusParams,
    ev.DuplicateVoteEvidence,
    ev.LightClientAttackEvidence,
    SignedHeader,
    LightBlock,
)

from ..abci.types import Event, EventAttribute, ExecTxResult  # noqa: E402

codec.register(Event, EventAttribute, ExecTxResult)

codec.register_adapter(
    keys.Ed25519PubKey,
    "ed25519.pub",
    lambda pk: pk.bytes(),
    lambda raw: keys.Ed25519PubKey(raw),
)

# Every supported validator key type must round-trip through the codec:
# validator sets carrying them appear in consensus WAL messages, state
# snapshots, genesis docs, and light blocks (a mixed ed25519+sr25519 set
# is a first-class consensus citizen here — crypto/batch.MixedBatchVerifier).
from ..crypto.secp256k1 import Secp256k1PubKey  # noqa: E402
from ..crypto.sr25519 import Sr25519PubKey  # noqa: E402

codec.register_adapter(
    Sr25519PubKey,
    "sr25519.pub",
    lambda pk: pk.bytes(),
    lambda raw: Sr25519PubKey(raw),
)
codec.register_adapter(
    Secp256k1PubKey,
    "secp256k1.pub",
    lambda pk: pk.bytes(),
    lambda raw: Secp256k1PubKey(raw),
)


def _valset_enc(vs: ValidatorSet) -> dict:
    return {
        "validators": list(vs.validators),
        "proposer_address": vs.proposer.address if vs.proposer else b"",
    }


def _valset_dec(payload: dict) -> ValidatorSet:
    vs = ValidatorSet.__new__(ValidatorSet)
    vs.validators = list(payload["validators"])
    vs._total = None
    vs.proposer = None
    addr = payload["proposer_address"]
    if addr:
        for v in vs.validators:
            if v.address == addr:
                vs.proposer = v
                break
    return vs


codec.register_adapter(ValidatorSet, "valset", _valset_enc, _valset_dec)

dumps = codec.dumps
loads = codec.loads
