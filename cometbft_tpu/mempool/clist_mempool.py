"""CList mempool (reference: mempool/clist_mempool.go:26-671).

Tx pool on a concurrent list so per-peer broadcast routines can tail it.
``check_tx`` pushes through the async ABCI mempool connection; the global
response callback admits valid txs (``resCbFirstTime:373``). After every
block commit, ``update`` removes committed txs and re-checks the remainder
(``resCbRecheck:438``). Consensus gets ``TxsAvailable`` edge signals.
"""

from __future__ import annotations

import threading

from ..libs import health as libhealth
from ..libs import metrics as libmetrics
from ..libs import sync as libsync
from ..libs import trace as libtrace
from ..libs import txtrace as libtxtrace
from dataclasses import dataclass, field

from ..abci import types as abci
from ..config import MempoolConfig
from ..crypto import hashplane
from ..libs.clist import CList
from .cache import LRUTxCache, NopTxCache


def TxKey(tx: bytes) -> bytes:
    # routed through the device hash plane when one is up: concurrent
    # CheckTx threads' key hashes coalesce into shared SHA-256 windows
    # (large txs only — small keys stay on the host hash; digests are
    # identical either way); ledger-attributed to the mempool tenant
    from ..libs import devledger

    with devledger.caller_class("mempool"):
        return hashplane.hash_bytes(tx)


class MempoolError(Exception):
    pass


class TxInCacheError(MempoolError):
    pass


class MempoolFullError(MempoolError):
    pass


@dataclass(slots=True)
class MempoolTx:
    tx: bytes
    height: int  # height when validated
    gas_wanted: int = 0
    senders: set = field(default_factory=set)  # peer ids that sent it
    # the tx key, computed ONCE at CheckTx ingress and threaded through
    # every later cache/map touch — a 1 MB tx must never pay a second
    # SHA-256 on the remove/recheck paths
    key: bytes = b""
    # admission stamp (libs/health ring clock, so the age is
    # virtual-domain-consistent under simnet): the clist is FIFO, so
    # the front element's stamp is the pool's oldest — the
    # mempool_oldest_age_seconds gauge and the tx_starved watchdog
    time_ns: int = 0


class CListMempool:
    def __init__(
        self,
        config: MempoolConfig,
        proxy_app,  # mempool-connection ABCI client
        height: int = 0,
        pre_check=None,
        post_check=None,
    ):
        self.config = config
        self.proxy_app = proxy_app
        self.height = height
        self.pre_check = pre_check
        self.post_check = post_check
        self.txs = CList()
        self.tx_map: dict[bytes, object] = {}  # TxKey -> CElement
        self.cache = (
            LRUTxCache(config.cache_size)
            if config.cache_size > 0
            else NopTxCache()
        )
        # Consensus lock: held across Commit so no CheckTx races app state
        self._update_mtx = libsync.RLock("mempool.update")
        self._size_bytes = 0
        self._recheck_cursor = None  # next element expecting a recheck result
        self._recheck_end = None
        self._txs_available: threading.Event | None = None
        self._notified_txs_available = False
        self._pending_senders: dict[bytes, str] = {}
        # tx bytes -> key for in-flight CheckTx requests: the async
        # response callback only receives the tx back, and re-deriving
        # the key there would re-hash up to max_tx_bytes per response
        # (the call-count test in tests/test_hashplane.py pins ONE
        # TxKey per CheckTx). Entries live exactly as long as a
        # _pending_senders entry would.
        self._pending_tx_keys: dict[bytes, bytes] = {}
        proxy_app.set_response_callback(self._global_cb)

    # -- config hooks ------------------------------------------------------

    def enable_txs_available(self) -> None:
        self._txs_available = threading.Event()

    def txs_available(self) -> threading.Event:
        return self._txs_available

    # -- sizes -------------------------------------------------------------

    def size(self) -> int:
        return len(self.txs)

    def size_bytes(self) -> int:
        with self._update_mtx:
            return self._size_bytes

    def oldest_age_s(self) -> float:
        """Age of the oldest admitted-uncommitted tx (0.0 = empty).
        Lock-free racy read of the clist front — the tx_starved
        watchdog polls this from its check tick, which must not
        contend with the update lock."""
        el = self.txs.front()
        if el is None:
            return 0.0
        t = el.value.time_ns
        if not t:
            return 0.0
        age = (libhealth.now_ns() - t) / 1e9
        return age if age > 0 else 0.0

    def oldest_entries(self, n: int = 8) -> list[tuple[bytes, float]]:
        """The ``n`` oldest pending txs as ``(key, age_s)`` — the
        starved keys a tx_starved black-box bundle names."""
        now = libhealth.now_ns()
        out: list[tuple[bytes, float]] = []
        for el in self.txs:
            memtx = el.value
            age = (now - memtx.time_ns) / 1e9 if memtx.time_ns else 0.0
            out.append((memtx.key, age if age > 0 else 0.0))
            if len(out) >= n:
                break
        return out

    def is_full(self, tx_len: int) -> MempoolFullError | None:
        if (
            self.size() >= self.config.size
            or tx_len + self.size_bytes() > self.config.max_txs_bytes
        ):
            return MempoolFullError(
                f"mempool full: {self.size()} txs, {self.size_bytes()}B"
            )
        return None

    # -- CheckTx ingress (clist_mempool.go:247) ----------------------------

    def check_tx(self, tx: bytes, cb=None, sender: str = "") -> None:
        # Size gate and tx hash OUTSIDE the update lock (cometlint
        # CLNT009 discipline): TxKey is SHA-256 over up to max_tx_bytes
        # (1 MB) of peer-controlled bytes — pure compute that must not
        # serialize concurrent CheckTx against commit's Update window.
        if len(tx) > self.config.max_tx_bytes:
            raise MempoolError(
                f"tx too large: {len(tx)} > {self.config.max_tx_bytes}"
            )
        key = TxKey(tx)
        if libtrace.enabled():  # before the lock: pure ring append
            libtrace.event(
                "mempool.checktx", bytes=len(tx), sender=sender
            )
        with self._update_mtx:  # cometlint: disable=CLNT009 -- async CheckTx dispatch under the update lock is the reference behavior (clist_mempool.go:247); the dispatch union overapproximates which app method runs
            if self.pre_check is not None:
                self.pre_check(tx)
            err = self.is_full(len(tx))
            if err is not None:
                raise err
            if not self.cache.push(key):
                # Seen before: record the extra sender for gossip dedup.
                el = self.tx_map.get(key)
                if el is not None and sender:
                    el.value.senders.add(sender)
                raise TxInCacheError(key.hex())
            # first-seen only (mempool/metrics.go TxSizeBytes): duplicates
            # and rejected-before-cache txs must not shift the histogram
            libmetrics.node_metrics().mempool_tx_size.observe(len(tx))
            if sender and libtxtrace.enabled():
                # first receipt FROM a peer — stamped AFTER the cache
                # dedup, so re-gossip of an already-seen/committed tx
                # cannot re-create a ghost lifecycle row that never
                # closes; the netstamp wall hint is still parked (the
                # recv routine dispatches reactors synchronously on
                # this thread, and the stamp stores are cheap array
                # writes, safe under the update lock)
                from ..libs import netstats as libnetstats

                stamp = libnetstats.current_stamp()
                libtxtrace.note_gossip_recv(
                    key, stamp[2] if stamp is not None else 0
                )
            if sender:
                self._pending_senders[key] = sender
            self._pending_tx_keys[tx] = key
            try:
                reqres = self.proxy_app.check_tx_async(
                    abci.RequestCheckTx(tx=tx, type=abci.CheckTxType.NEW)
                )
            except BaseException:
                # a failed dispatch means no response callback will
                # ever pop these — each leaked tx-key entry pins up to
                # max_tx_bytes of tx bytes, so clean up at the failure
                # site (the cache entry stays, matching the reference's
                # seen-tx semantics)
                self._pending_tx_keys.pop(tx, None)
                self._pending_senders.pop(key, None)
                raise
            if cb is not None:
                reqres.set_callback(cb)

    def _global_cb(self, req, res) -> None:
        """proxy_app's global callback. Routed by the REQUEST type, not by
        whether a recheck is in flight — a NEW response racing a recheck
        window must not consume the recheck cursor."""
        if req.type == abci.CheckTxType.RECHECK:
            self._res_cb_recheck(req, res)
        else:
            self._res_cb_first_time(req, res)

    def _res_cb_first_time(self, req, res) -> None:
        tx = req.tx
        with self._update_mtx:
            # the key was computed at CheckTx ingress; a socket client
            # round-trips the tx bytes so the map lookup is by value (a
            # dict hash, not another SHA-256). The TxKey fallback only
            # fires for responses whose ingress predates this process
            # (never in practice — the map is cleared with the pool).
            # The pop itself must happen under the update lock: every
            # other _pending_tx_keys access (check_tx insert, flush
            # clear) is guarded, and a socket client delivers this
            # callback from its recv thread — popping lock-free races a
            # concurrent flush() and can resurrect a just-cleared entry.
            key = self._pending_tx_keys.pop(tx, None)
            libsync.lockset_note("CListMempool._pending_tx_keys")
            if key is None:
                key = TxKey(tx)
            post_ok = True
            if self.post_check is not None:
                try:
                    self.post_check(tx, res)
                except Exception:
                    post_ok = False
            if res.code == abci.OK and post_ok:
                if self.is_full(len(tx)) is not None:
                    self.cache.remove(key)
                    self._pending_senders.pop(key, None)
                    return
                sender = self._pending_senders.pop(key, "")
                # tx-lifecycle admission stamp (+ the mempool depth
                # the tx saw — txs queued ahead of it at admit);
                # self-gated: the disabled path is one flag check
                libtxtrace.note_admit(key, len(self.txs))
                memtx = MempoolTx(
                    tx=tx,
                    height=self.height,
                    gas_wanted=res.gas_wanted,
                    key=key,
                    time_ns=libhealth.now_ns(),
                )
                if sender:
                    memtx.senders.add(sender)
                el = self.txs.push_back(memtx)
                self.tx_map[key] = el
                self._size_bytes += len(tx)
                if libtrace.enabled():
                    libtrace.event(
                        "mempool.admit", bytes=len(tx), code=res.code
                    )
                self._notify_txs_available()
            else:
                if libtrace.enabled():
                    libtrace.event(
                        "mempool.reject", bytes=len(tx), code=res.code
                    )
                libmetrics.node_metrics().mempool_failed_txs.inc()
                self._pending_senders.pop(key, None)
                if not self.config.keep_invalid_txs_in_cache:
                    self.cache.remove(key)

    def _res_cb_recheck(self, req, res) -> None:
        libmetrics.node_metrics().mempool_rechecks.inc()
        with self._update_mtx:
            el = self._recheck_cursor
            if el is None:
                return
            # responses come back in recheck submission order
            if el.value.tx != req.tx:
                # out-of-sync; drop cursor to stop recheck gracefully
                self._recheck_cursor = None
                return
            if res.code != abci.OK:
                key = el.value.key  # == TxKey(req.tx): el.value.tx matched
                self._remove_tx_el(el)
                if not self.config.keep_invalid_txs_in_cache:
                    self.cache.remove(key)
            if el is self._recheck_end:
                self._recheck_cursor = None
                if self.size() > 0:
                    self._notify_txs_available()
            else:
                self._recheck_cursor = el.next()

    # -- reap (clist_mempool.go ReapMaxBytesMaxGas) ------------------------

    def reap_max_bytes_max_gas(self, max_bytes: int, max_gas: int) -> list[bytes]:
        with self._update_mtx:
            out, total_bytes, total_gas = [], 0, 0
            for el in self.txs:
                memtx = el.value
                if max_bytes > -1 and total_bytes + len(memtx.tx) > max_bytes:
                    break
                if max_gas > -1 and total_gas + memtx.gas_wanted > max_gas:
                    break
                out.append(memtx.tx)
                total_bytes += len(memtx.tx)
                total_gas += memtx.gas_wanted
            return out

    def reap_max_txs(self, n: int) -> list[bytes]:
        with self._update_mtx:
            out = []
            for el in self.txs:
                if 0 <= n <= len(out):
                    break
                out.append(el.value.tx)
            return out

    # -- consensus integration ---------------------------------------------

    def lock(self) -> None:
        self._update_mtx.acquire()

    def unlock(self) -> None:
        self._update_mtx.release()

    def flush(self) -> None:
        with self._update_mtx:
            for el in list(self.txs):
                self.txs.remove(el)
            self.tx_map.clear()
            self._size_bytes = 0
            self.cache.reset()
            self._recheck_cursor = None
            self._pending_tx_keys.clear()

    def _remove_tx_el(self, el) -> None:
        self.txs.remove(el)
        # admitted txs always carry their ingress key; the TxKey
        # fallback guards hand-constructed entries in tests
        self.tx_map.pop(el.value.key or TxKey(el.value.tx), None)
        self._size_bytes -= len(el.value.tx)

    def remove_tx_by_key(self, key: bytes) -> None:
        with self._update_mtx:
            el = self.tx_map.get(key)
            if el is not None:
                self._remove_tx_el(el)

    def update(
        self,
        height: int,
        txs: list[bytes],
        tx_results: list,
        pre_check=None,
        post_check=None,
    ) -> None:
        """Called with the lock HELD, inside BlockExecutor.Commit
        (clist_mempool.go Update:584)."""
        self.height = height
        self._notified_txs_available = False
        if pre_check is not None:
            self.pre_check = pre_check
        if post_check is not None:
            self.post_check = post_check
        # committed txs arrive keyless from the block — derive all
        # their keys as ONE batch (hash_many routes to the device
        # plane only when that wins, and per-tx routed tickets inside
        # the commit critical section would pay a round trip each)
        from ..libs import devledger

        with devledger.caller_class("mempool"):
            keys = hashplane.hash_many(txs)
        # the commit stage closes each sampled tx's lifecycle row —
        # ONE batched call for the whole block (keys just derived
        # above, no extra hashing; self-gated, so the disabled cost
        # is one flag check per block)
        libtxtrace.note_commit_many(keys, height)
        for tx, key, res in zip(txs, keys, tx_results):
            if res.code == abci.OK:
                self.cache.push(key)  # committed: never re-admit
            elif not self.config.keep_invalid_txs_in_cache:
                self.cache.remove(key)
            self.remove_tx_by_key(key)
        if self.size() > 0:
            if self.config.recheck:
                self._recheck_txs()
            else:
                self._notify_txs_available()

    def _recheck_txs(self) -> None:
        # No sync flush here: we hold _update_mtx and the socket client's
        # recv thread needs it to process the recheck responses — a
        # synchronous flush would deadlock (the reference uses FlushAsync,
        # clist_mempool.go:476). Requests are written eagerly.
        self._recheck_cursor = self.txs.front()
        self._recheck_end = self.txs.back()
        for el in self.txs:
            self.proxy_app.check_tx_async(
                abci.RequestCheckTx(
                    tx=el.value.tx, type=abci.CheckTxType.RECHECK
                )
            )

    def _notify_txs_available(self) -> None:
        if self._txs_available is not None and not self._notified_txs_available:
            self._notified_txs_available = True
            self._txs_available.set()
