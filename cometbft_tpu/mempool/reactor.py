"""Mempool gossip reactor (reference: mempool/reactor.go:138-210).

Channel ``0x30``. One broadcast thread per peer walks the mempool clist
and sends each tx, skipping peers that already sent it to us
(``isSender``, reactor.go:212) and peers that are still syncing.
"""

from __future__ import annotations

import threading

from ..libs import netstats as libnetstats
from ..libs import txtrace as libtxtrace
from ..p2p.base_reactor import ChannelDescriptor, Reactor
from .clist_mempool import CListMempool, MempoolError

MEMPOOL_CHANNEL = 0x30


class MempoolReactor(Reactor):
    def __init__(self, config, mempool: CListMempool):
        super().__init__("mempool-reactor")
        self.config = config
        self.mempool = mempool

    def get_channels(self):
        return [
            ChannelDescriptor(
                id=MEMPOOL_CHANNEL, priority=5, send_queue_capacity=128
            )
        ]

    def add_peer(self, peer) -> None:
        if not self.config.broadcast:
            return
        threading.Thread(
            target=self._broadcast_tx_routine,
            args=(peer,),
            name=f"mempool-bcast-{peer.id[:8]}",
            daemon=True,
        ).start()

    def receive(self, ch_id: int, peer, msg_bytes: bytes) -> None:
        # tx gossip rides the stamped mempool channel: one-hop lag is
        # attributed under phase="tx" (raw tx payloads are safe to
        # stamp because stamping is negotiated, never sniffed)
        libnetstats.observe_propagation("tx")
        try:
            self.mempool.check_tx(msg_bytes, sender=peer.id)
        except MempoolError:
            pass  # dup/full/invalid — normal gossip noise

    def _broadcast_tx_routine(self, peer) -> None:
        """reactor.go:138 — tail the clist, skip the tx's senders."""
        el = None
        while peer.is_running() and self.is_running():
            if el is None:
                el = self.mempool.txs.front_wait(timeout=0.2)
                if el is None:
                    continue
            memtx = el.value
            if peer.id not in memtx.senders and not el.removed:
                if not peer.send(MEMPOOL_CHANNEL, memtx.tx):
                    continue  # retry same element
                # tx-lifecycle: first gossip send of a sampled tx
                # toward ANY peer (set-once inside the plane; the
                # admitted element carries its ingress key)
                libtxtrace.note_gossip_send(memtx.key)
            nxt = el.next_wait(timeout=0.2)
            if nxt is not None:
                el = nxt
            elif el.removed:
                el = None  # restart from the front
