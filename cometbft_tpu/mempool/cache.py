"""Seen-tx dedup cache (reference: mempool/cache.go:120)."""

from __future__ import annotations

from ..libs import sync as libsync
from collections import OrderedDict


class LRUTxCache:
    def __init__(self, size: int):
        self._size = size
        self._mtx = libsync.Mutex("mempool.cache._mtx")
        self._map: OrderedDict[bytes, None] = OrderedDict()

    def push(self, tx_key: bytes) -> bool:
        """True if newly added; False if already present (moves to front)."""
        with self._mtx:
            if tx_key in self._map:
                self._map.move_to_end(tx_key)
                return False
            if len(self._map) >= self._size:
                self._map.popitem(last=False)
            self._map[tx_key] = None
            return True

    def remove(self, tx_key: bytes) -> None:
        with self._mtx:
            self._map.pop(tx_key, None)

    def has(self, tx_key: bytes) -> bool:
        with self._mtx:
            return tx_key in self._map

    def reset(self) -> None:
        with self._mtx:
            self._map.clear()


class NopTxCache:
    def push(self, tx_key: bytes) -> bool:
        return True

    def remove(self, tx_key: bytes) -> None:
        pass

    def has(self, tx_key: bytes) -> bool:
        return False

    def reset(self) -> None:
        pass
