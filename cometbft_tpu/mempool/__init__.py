"""L6 mempool (reference: mempool/)."""

from .cache import LRUTxCache, NopTxCache  # noqa: F401
from .clist_mempool import CListMempool, MempoolError, TxKey  # noqa: F401
