"""Light-client RPC proxy (reference: light/proxy/proxy.go +
light/rpc/client.go — ``cometbft light`` command).

Serves the standard RPC surface on a local address while routing data
through the light client's verification:

* ``commit`` / ``validators`` / ``header`` answer FROM the verified
  light-block store — the strongest guarantee, no primary data at all;
* ``block`` fetches the full block from the primary and accepts it only
  if (a) the header hash equals the light-verified header's hash and
  (b) the transactions re-hash to the verified header's ``data_hash``
  (light/rpc/client.go Block: untrusted data is cross-checked against
  the trusted header before being returned);
* tx submission, ``status``, ``health``, ``tx``, ``abci_query`` pass
  through to the primary (abci_query proof verification requires
  app-side proof ops — documented passthrough, as in the reference's
  default ``DefaultMerkleKeyPathFn``-less mode).
"""

from __future__ import annotations

import base64
import time

from ..crypto import merkle, tmhash
from ..libs.service import BaseService
from ..rpc import encoding as enc
from ..rpc.client import HTTPClient
from ..rpc.jsonrpc.server import RPCServer
from .client import Client
from .errors import LightClientError


class LightProxy(BaseService):
    """RPC server whose read routes are light-verified."""

    def __init__(
        self,
        light_client: Client,
        primary_addr: str,
        laddr: str,
        logger=None,
        update_interval: float = 8.0,
    ):
        super().__init__("light-proxy", logger)
        self.light_client = light_client
        self.primary = HTTPClient(primary_addr)
        # Background head-tracking (light/proxy keeps the trusted store
        # near the chain tip so request-time verification is one hop,
        # and the trusting period never lapses while the proxy idles).
        self.update_interval = update_interval
        self._update_thread = None
        self._server = RPCServer(
            env=None, laddr=laddr, logger=logger, routes=self._routes()
        )

    @property
    def bound_addr(self) -> str:
        return self._server.bound_addr

    def on_start(self) -> None:
        self._server.start()
        if self.update_interval > 0:
            import threading

            self._update_thread = threading.Thread(
                target=self._update_loop, name="light-update", daemon=True
            )
            self._update_thread.start()

    def _update_loop(self) -> None:
        while not self.quit_event().wait(self.update_interval):
            try:
                self.light_client.update(time.time_ns())
            except Exception:
                pass  # primary hiccup: try again next tick

    def on_stop(self) -> None:
        self._server.stop()

    # -- route table -------------------------------------------------------

    def _verified(self, height) -> "LightBlock":  # noqa: F821
        if height is None:
            raise LightClientError("height is required on a light proxy")
        return self.light_client.verify_light_block_at_height(
            int(height), time.time_ns()
        )

    def _routes(self) -> dict:
        lp = self

        def health(env):
            return lp.primary.call("health")

        def status(env):
            st = lp.primary.call("status")
            latest = lp.light_client.trusted_light_block(0)
            st["light_client_info"] = {
                "trusted_height": latest.height,
                "trusted_hash": (latest.hash() or b"").hex().upper(),
            }
            return st

        def commit(env, height=None):
            lb = lp._verified(height)
            return {
                "signed_header": {
                    "header": enc.enc_header(lb.signed_header.header),
                    "commit": enc.enc_commit(lb.signed_header.commit),
                },
                "canonical": True,
            }

        def header(env, height=None):
            lb = lp._verified(height)
            return {"header": enc.enc_header(lb.signed_header.header)}

        def validators(env, height=None):
            lb = lp._verified(height)
            vs = lb.validator_set
            return {
                "block_height": lb.height,
                "validators": [enc.enc_validator(v) for v in vs.validators],
                "count": len(vs.validators),
                "total": len(vs.validators),
            }

        def block(env, height=None):
            lb = lp._verified(height)
            raw = lp.primary.call("block", height=int(height))
            verified_hash = (lb.hash() or b"").hex().upper()
            got_hash = raw["block_id"]["hash"].upper()
            if got_hash != verified_hash:
                raise LightClientError(
                    f"primary returned block {got_hash}, light client "
                    f"verified {verified_hash} at height {height}"
                )
            txs = [
                base64.b64decode(t)
                for t in (raw["block"]["data"]["txs"] or [])
            ]
            # data_hash = merkle root of tx HASHES (types.Data.hash)
            data_hash = merkle.hash_from_byte_slices(
                [tmhash.sum(tx) for tx in txs]
            )
            want = lb.signed_header.header.data_hash
            if data_hash != want:
                raise LightClientError(
                    "primary block transactions do not hash to the "
                    "verified data_hash"
                )
            return raw

        def passthrough(method):
            def fn(env, **params):
                return lp.primary.call(method, **params)

            return fn

        routes = {
            "health": health,
            "status": status,
            "commit": commit,
            "header": header,
            "validators": validators,
            "block": block,
        }
        for m in (
            "broadcast_tx_sync",
            "broadcast_tx_async",
            "broadcast_tx_commit",
            "tx",
            "abci_query",
            "abci_info",
            "net_info",
            "unconfirmed_txs",
            "num_unconfirmed_txs",
        ):
            routes[m] = passthrough(m)
        return routes
