"""Light-client RPC proxy (reference: light/proxy/proxy.go +
light/rpc/client.go — ``cometbft light`` command).

Serves the standard RPC surface on a local address while routing data
through the light client's verification:

* ``commit`` / ``validators`` / ``header`` answer FROM the verified
  light-block store — the strongest guarantee, no primary data at all;
* ``block`` fetches the full block from the primary, decodes it, and
  accepts it only if the block hash RECOMPUTED FROM CONTENT (after
  ValidateBasic, which re-hashes txs against ``data_hash`` and the last
  commit against ``last_commit_hash``) equals the light-verified hash
  (light/rpc/client.go:319-340 recomputes ``res.Block.Hash()``). The
  response is a RE-ENCODING of the verified decoded block — nothing
  from the primary's raw JSON (claimed block_id, injected evidence,
  extra keys) is ever relayed;
* tx submission, ``status``, ``health``, ``tx``, ``abci_query`` pass
  through to the primary (abci_query proof verification requires
  app-side proof ops — documented passthrough, as in the reference's
  default ``DefaultMerkleKeyPathFn``-less mode).
"""

from __future__ import annotations

import time

from ..libs.service import BaseService
from ..rpc import encoding as enc
from ..rpc.client import HTTPClient
from ..rpc.jsonrpc.server import RPCServer
from .client import Client
from .errors import LightClientError


class LightProxy(BaseService):
    """RPC server whose read routes are light-verified."""

    def __init__(
        self,
        light_client: Client,
        primary_addr: str,
        laddr: str,
        logger=None,
        update_interval: float = 8.0,
    ):
        super().__init__("light-proxy", logger)
        self.light_client = light_client
        self.primary = HTTPClient(primary_addr)
        # Background head-tracking (light/proxy keeps the trusted store
        # near the chain tip so request-time verification is one hop,
        # and the trusting period never lapses while the proxy idles).
        self.update_interval = update_interval
        self._update_thread = None
        self._server = RPCServer(
            env=None, laddr=laddr, logger=logger, routes=self._routes()
        )

    @property
    def bound_addr(self) -> str:
        return self._server.bound_addr

    def on_start(self) -> None:
        self._server.start()
        if self.update_interval > 0:
            import threading

            self._update_thread = threading.Thread(
                target=self._update_loop, name="light-update", daemon=True
            )
            self._update_thread.start()

    def _update_loop(self) -> None:
        while not self.quit_event().wait(self.update_interval):
            try:
                self.light_client.update(time.time_ns())
            except Exception:
                pass  # primary hiccup: try again next tick

    def on_stop(self) -> None:
        self._server.stop()

    # -- route table -------------------------------------------------------

    def _verified(self, height) -> "LightBlock":  # noqa: F821
        if height is None:
            raise LightClientError("height is required on a light proxy")
        return self.light_client.verify_light_block_at_height(
            int(height), time.time_ns()
        )

    @staticmethod
    def _verified_block_id(lb, content_hash: bytes):
        """The BlockID to return for a verified block: the one the
        validators signed (the light block's own commit), sanity-checked
        against the recomputed content hash."""
        bid = lb.signed_header.commit.block_id
        if bid.hash != content_hash:
            raise LightClientError(
                "light block commit id does not match the verified header"
            )
        return bid

    def _routes(self) -> dict:
        lp = self

        def health(env):
            return lp.primary.call("health")

        def status(env):
            st = lp.primary.call("status")
            latest = lp.light_client.trusted_light_block(0)
            st["light_client_info"] = {
                "trusted_height": latest.height,
                "trusted_hash": (latest.hash() or b"").hex().upper(),
            }
            return st

        def commit(env, height=None):
            lb = lp._verified(height)
            return {
                "signed_header": {
                    "header": enc.enc_header(lb.signed_header.header),
                    "commit": enc.enc_commit(lb.signed_header.commit),
                },
                "canonical": True,
            }

        def header(env, height=None):
            lb = lp._verified(height)
            return {"header": enc.enc_header(lb.signed_header.header)}

        def validators(env, height=None):
            lb = lp._verified(height)
            vs = lb.validator_set
            return {
                "block_height": lb.height,
                "validators": [enc.enc_validator(v) for v in vs.validators],
                "count": len(vs.validators),
                "total": len(vs.validators),
            }

        def block(env, height=None):
            # Verify from CONTENT, never from the primary's claimed
            # block_id: decode the returned block, ValidateBasic it
            # (which re-hashes txs against data_hash and the last commit
            # against last_commit_hash), then recompute the header hash
            # and compare against the light-verified hash
            # (light/rpc/client.go:319-340 recomputes res.Block.Hash()).
            lb = lp._verified(height)
            raw = lp.primary.call("block", height=int(height))
            try:
                blk = enc.dec_block(raw["block"])
                blk.validate_basic()
            except Exception as e:
                raise LightClientError(
                    f"primary returned an invalid block at height "
                    f"{height}: {e}"
                )
            if blk.header.height == 1 and blk.last_commit is not None:
                # Block 1 carries an EMPTY last commit; ValidateBasic only
                # cross-checks last_commit_hash above height 1, so signed
                # commit data injected here would relay unverified.
                raise LightClientError(
                    "primary returned a signed last_commit on block 1"
                )
            verified_hash = lb.hash() or b""
            content_hash = blk.hash() or b""
            if content_hash != verified_hash:
                raise LightClientError(
                    f"primary returned block {content_hash.hex().upper()} "
                    f"(recomputed from content), light client verified "
                    f"{verified_hash.hex().upper()} at height {height}"
                )
            # Never relay the primary's raw JSON: anything outside the
            # decode/re-hash surface (claimed block_id, injected
            # evidence, unknown extra keys) would pass through
            # unverified. The response is a RE-ENCODING of the verified
            # decoded block, with the block_id taken from the
            # light-verified commit (hash + part-set header both signed).
            return {
                "block_id": enc.enc_block_id(
                    lp._verified_block_id(lb, content_hash)
                ),
                "block": enc.enc_block(blk),
            }

        def passthrough(method):
            def fn(env, **params):
                return lp.primary.call(method, **params)

            return fn

        routes = {
            "health": health,
            "status": status,
            "commit": commit,
            "header": header,
            "validators": validators,
            "block": block,
        }
        for m in (
            "broadcast_tx_sync",
            "broadcast_tx_async",
            "broadcast_tx_commit",
            "tx",
            "abci_query",
            "abci_info",
            "net_info",
            "unconfirmed_txs",
            "num_unconfirmed_txs",
        ):
            routes[m] = passthrough(m)
        return routes
